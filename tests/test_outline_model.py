"""The outline model must behave identically over both engines, nest and
delete subtrees with reference semantics, and converge under concurrent
editing (models/outline.py)."""
import pytest

from crdt_graph_tpu.models.outline import OutlineDoc


@pytest.fixture(params=["tpu", "oracle"])
def eng(request):
    return request.param


def test_nesting_and_render(eng):
    d = OutlineDoc(1, engine=eng)
    plan = d.add_section("plan")
    first = d.add_item("write tests", parent=plan)
    d.add_item("ship", parent=plan, after=first)
    d.add_item("later", after=plan)
    assert [(dep, t) for dep, t, _ in d.items()] == [
        (1, "plan"), (2, "write tests"), (2, "ship"), (1, "later")]
    assert d.render() == "plan\n  write tests\n  ship\nlater"


def test_delete_kills_subtree(eng):
    d = OutlineDoc(1, engine=eng)
    sec = d.add_section("sec")
    d.add_item("child", parent=sec)
    keep = d.add_item("keep", after=sec)
    d.delete_item(sec)
    assert [t for _, t, _ in d.items()] == ["keep"]
    assert d.items()[0][2] == keep


def test_concurrent_merge_converges(eng):
    a = OutlineDoc(1, engine=eng)
    b = OutlineDoc(2, engine=eng)
    sec = a.add_section("agenda")
    b.apply(a.operations_since(0))
    # both replicas add under the same section concurrently
    a.add_item("from-a", parent=sec)
    b.add_item("from-b", parent=sec)
    a.sync_from(b)
    b.sync_from(a)
    assert a.items() == b.items()
    # RGA rule: higher timestamp (replica 2) sits nearer the branch head
    assert [t for _, t, _ in a.items()] == ["agenda", "from-b", "from-a"]


def test_engines_agree_on_session():
    """Same scripted session through both engines → identical documents."""
    def script(doc):
        s1 = doc.add_section("one")
        i = doc.add_item("a", parent=s1)
        doc.add_item("b", parent=s1, after=i)
        s2 = doc.add_section("two", after=s1)
        doc.add_item("c", parent=s2)
        doc.delete_item(i)
        return doc

    t = script(OutlineDoc(5, engine="tpu"))
    o = script(OutlineDoc(5, engine="oracle"))
    assert [(d, v) for d, v, _ in t.items()] == \
        [(d, v) for d, v, _ in o.items()]
    assert t.render() == o.render() == "one\n  b\ntwo\n  c"


def test_absorbed_add_returns_none(eng):
    """Adding under a deleted section (a concurrent delete won) is a
    success-no-op: add_item returns None instead of crashing (the
    reference's AlreadyApplied -> Ok contract, CRDTree.elm:318-319)."""
    d = OutlineDoc(1, engine=eng)
    sec = d.add_section("sec")
    d.delete_item(sec)
    assert d.add_item("child", parent=sec) is None
    assert len(d) == 0


def test_wire_interop_with_text_engine():
    """Outline deltas ride the same JSON wire as everything else."""
    from crdt_graph_tpu.codec import json_codec
    a = OutlineDoc(1)
    a.add_section("s")
    wire = json_codec.dumps(a.operations_since(0))
    b = OutlineDoc(2)
    b.apply(json_codec.loads(wire))
    assert b.items() == a.items()
