"""Disaggregated merge tier (mergetier/; docs/MERGETIER.md): the wire
codec's digests, the linger batcher's epochs, and — the acceptance pin
— bit-identity between the local merge path and the remote worker path
over the in-process transport: equal state fingerprints, byte-identical
``/ops`` windows, identical ``last_applied_mask`` attribution, dup
re-sends included.

The failure half: worker death mid-round and a netchaos cut on the
merge link both fall back to the local merge with zero acked loss (the
dedicated ``mid-remote-merge`` crash leg recovers a durable front-end
that died with verified frames in hand and nothing committed);
``GRAFT_MERGETIER=0`` is the A/B kill switch that leaves the engine —
and its scrape — byte-identical to a local-only build.
"""
import threading
import time

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.codec import json_codec                 # noqa: E402
from crdt_graph_tpu.codec import packed as packed_mod       # noqa: E402
from crdt_graph_tpu.core.operation import Add, Batch        # noqa: E402
from crdt_graph_tpu.mergetier import client as client_mod   # noqa: E402
from crdt_graph_tpu.mergetier import wire                   # noqa: E402
from crdt_graph_tpu.mergetier import MergeWorker            # noqa: E402
from crdt_graph_tpu.mergetier.worker import MergeWorkerServer  # noqa: E402
from crdt_graph_tpu.obs import flight as flight_mod         # noqa: E402
from crdt_graph_tpu.obs import prom as prom_mod             # noqa: E402
from crdt_graph_tpu.parallel import mesh as mesh_mod        # noqa: E402
from crdt_graph_tpu.serve import ServingEngine, SchedulerStopped  # noqa: E402

OFFSET = 2**32
N = 1200   # above the kernel crossover, coalescible in one chunk


def chain_ops(rid, n, counter0=0, anchor=0):
    ops, prev = [], anchor
    for i in range(n):
        ts = rid * OFFSET + counter0 + i + 1
        ops.append(Add(ts, (prev,), (counter0 + i) & 0xFF))
        prev = ts
    return ops


def submit_async(engine, doc_id, body):
    box = {}

    def go():
        try:
            box["result"] = engine.submit(doc_id, body)
        except BaseException as e:          # noqa: BLE001 — test capture
            box["error"] = e

    th = threading.Thread(target=go, daemon=True)
    th.start()
    return th, box


def wait_queue_depth(engine, doc_id, depth, timeout=10.0):
    doc = engine.get(doc_id)
    deadline = time.monotonic() + timeout
    while len(doc.queue) < depth:
        assert time.monotonic() < deadline, \
            f"queue never reached depth {depth} (at {len(doc.queue)})"
        time.sleep(0.002)


def _push_staged(engine, doc_bodies):
    """Stage one delta per doc with the scheduler stopped, run one
    scheduling round synchronously, resolve all."""
    pairs = []
    for doc_id, body in doc_bodies:
        engine.get(doc_id)
        pairs.append(submit_async(engine, doc_id, body))
    for doc_id, _ in doc_bodies:
        wait_queue_depth(engine, doc_id, 1)
    assert engine.scheduler.step() == len(doc_bodies)
    for th, box in pairs:
        th.join(30)
        assert box["result"][0], "staged push rejected"


# -- wire codec ------------------------------------------------------------


def test_wire_request_roundtrip_and_tamper():
    """encode_request → decode_request is lossless (capacity restored,
    meta intact, digest bound); truncation and bit-flips are detected,
    never mis-decoded."""
    p = packed_mod.pack(chain_ops(1, 64))
    body = wire.encode_request("docA", p, 64)
    p2, meta = wire.decode_request(body)
    assert meta["doc_id"] == "docA" and meta["num_new"] == 64
    assert meta["num_ops"] == p.num_ops
    assert p2.num_ops == p.num_ops and p2.capacity == p.capacity
    assert p2.values == p.values
    a1, a2 = p.arrays(), p2.arrays()
    assert set(a1) == set(a2)
    for k in a1:
        assert np.array_equal(np.asarray(a1[k]), np.asarray(a2[k])), k
    # truncated body
    with pytest.raises(wire.MergeWireError):
        wire.decode_request(body[:len(body) // 2])
    # bit-flip mid-payload: either the container or the digest trips
    flipped = bytearray(body)
    flipped[(6 * len(body)) // 10] ^= 0x40
    with pytest.raises(wire.MergeWireError):
        wire.decode_request(bytes(flipped))
    # num_new outside the row count is rejected even when well-formed
    with pytest.raises(wire.MergeWireError):
        wire.decode_request(wire.encode_request("docA", p, p.num_ops + 1))


def test_wire_response_roundtrip_and_tamper():
    """A real worker answer decodes (frame digest recomputed, digest
    echoed); a corrupted or truncated frame raises, and a corrupt
    REQUEST answers 400 without touching the batcher."""
    w = MergeWorker(linger_ms=0.0, name="wire-w")
    try:
        p = packed_mod.pack(chain_ops(1, 64))
        req = wire.encode_request("docA", p, 64)
        status, resp, headers = w.handle_merge(req)
        assert status == 200
        assert headers["Content-Type"] == "application/octet-stream"
        table, meta = wire.decode_response(resp)
        assert meta["input_digest"] == wire.request_digest(p)
        assert meta["width"] == 1
        assert int(table.ts.shape[0]) == meta["shared_capacity"] + 2
        assert 0 < int(table.num_nodes) <= int(table.ts.shape[0])
        # tampered / truncated responses must not decode
        with pytest.raises(wire.MergeWireError):
            wire.decode_response(resp[:len(resp) - 40])
        flipped = bytearray(resp)
        flipped[(6 * len(resp)) // 10] ^= 0x10
        with pytest.raises(wire.MergeWireError):
            wire.decode_response(bytes(flipped))
        # a corrupt request is a 400 + wire_errors, nothing merged
        status, _, _ = w.handle_merge(req[:32])
        assert status == 400
        st = w.stats()
        assert st["wire_errors"] == 1 and st["merged_docs"] == 1
    finally:
        w.close()


# -- the linger batcher ----------------------------------------------------


def test_linger_batcher_epochs_widths_and_close():
    """Concurrent submitters meet in one epoch (each gets exactly its
    own result), the width cap launches early, a failed launch fails
    every rider with the same error, and close() severs submitters."""
    launched = []

    def launch(items):
        launched.append(list(items))
        if "boom" in items:
            raise ValueError("epoch failed")
        return [x * 10 for x in items]

    b = mesh_mod.LingerBatcher(launch, linger_s=0.2, max_width=4)
    results, errs = {}, {}

    def run(i):
        try:
            results[i] = b.submit(i)
        except Exception as e:       # noqa: BLE001 — test capture
            errs[i] = e

    ths = [threading.Thread(target=run, args=(i,)) for i in range(3)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    assert results == {0: 0, 1: 10, 2: 20}
    assert len(launched) == 1 and sorted(launched[0]) == [0, 1, 2]
    st = b.stats()
    assert st["launches"] == 1 and st["items_in"] == 3
    assert st["linger_waits"] == 1 and st["full_launches"] == 0
    # width cap: 4 submitters launch immediately, no linger
    ths = [threading.Thread(target=run, args=(i,)) for i in range(10, 14)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    assert all(results[i] == i * 10 for i in range(10, 14))
    assert b.stats()["full_launches"] == 1
    # a failed epoch fails EVERY rider with the launch's error
    ths = [threading.Thread(target=run, args=(x,))
           for x in ("boom", "rider")]
    for t in ths:
        t.start()
    for t in ths:
        t.join(10)
    assert isinstance(errs["boom"], ValueError)
    assert isinstance(errs["rider"], ValueError)
    b.close()
    with pytest.raises(RuntimeError):
        b.submit(99)


# -- the acceptance pin: remote ≡ local ------------------------------------


def _assert_docs_equiv(remote, local, doc_ids, mids=()):
    for d in doc_ids:
        rd, ld = remote.get(d), local.get(d)
        assert rd.snapshot() == ld.snapshot(), d
        assert rd.clock() == ld.clock(), d
        assert rd.read_view().state_fingerprint() == \
            ld.read_view().state_fingerprint(), d
        # byte-identical /ops windows at every tier seam
        assert rd.dumps_since_bytes(0) == ld.dumps_since_bytes(0), d
        for since in mids:
            assert rd.dumps_since_bytes(since) == \
                ld.dumps_since_bytes(since), (d, since)
        # identical per-row attribution of the LAST commit
        m1, m2 = rd.tree.last_applied_mask, ld.tree.last_applied_mask
        assert m1 is not None and m2 is not None, d
        assert np.array_equal(np.asarray(m1), np.asarray(m2)), d


def test_remote_local_bit_identity_inproc():
    """The tier-1 equivalence pin: the same op stream through a
    remote-merge engine (in-process transport, one pooled worker) and
    a local-only engine — fresh coalesced waves, a second wave on the
    committed state, a half-duplicate re-send, and a giant single that
    takes the min-ops route — is bit-identical at every seam."""
    worker = MergeWorker(linger_ms=200.0, name="pin-w")
    mt = client_mod.MergeTierClient([worker], src="pin-fe")
    remote = ServingEngine(start=False, cross_doc=True, mergetier=mt,
                           flight=flight_mod.FlightRecorder())
    local = ServingEngine(start=False, cross_doc=True)
    docs = [f"x{i}" for i in range(3)]

    def bodies(counter0, anchor_c):
        return [(f"x{i}", json_codec.dumps(Batch(tuple(chain_ops(
            i + 2, N, counter0=counter0,
            anchor=((i + 2) * OFFSET + anchor_c) if anchor_c else 0)))))
            for i in range(3)]

    try:
        # wave 1: fresh 3-doc coalesced round → one remote width-3 epoch
        _push_staged(remote, bodies(0, 0))
        _push_staged(local, bodies(0, 0))
        _assert_docs_equiv(remote, local, docs)
        mst = mt.stats()
        assert mst["remote_docs"] == 3 and not mst["fallbacks"]
        assert mst["width"]["max"] == 3
        assert worker.stats()["batcher"]["launches"] == 1
        # wave 2 lands on the REMOTE-committed state (n0 > 0)
        _push_staged(remote, bodies(N, N))
        _push_staged(local, bodies(N, N))
        mids = [4 * OFFSET + N // 2]   # a mid-chain window seam
        _assert_docs_equiv(remote, local, docs, mids=mids)
        # wave 3: half-duplicate re-send — attribution must mark the
        # same rows dup/applied on both paths
        _push_staged(remote, bodies(N + N // 2, N + N // 2))
        _push_staged(local, bodies(N + N // 2, N + N // 2))
        _assert_docs_equiv(remote, local, docs, mids=mids)
        mask = np.asarray(remote.get("x0").tree.last_applied_mask)
        assert mask.sum() == N // 2    # second half fresh, first dup
        assert mt.stats()["remote_docs"] == 9
        # the giant single route: >= GRAFT_MERGETIER_MIN_OPS (default
        # 4096) fused ops ship remote even without co-travellers
        giant = [("x0", json_codec.dumps(Batch(tuple(chain_ops(
            2, 4200, counter0=2 * N + N // 2,
            anchor=2 * OFFSET + 2 * N + N // 2)))))]
        _push_staged(remote, giant)
        _push_staged(local, giant)
        _assert_docs_equiv(remote, local, ["x0"], mids=mids)
        mst = mt.stats()
        assert mst["remote_docs"] == 10 and not mst["fallbacks"]
        assert remote.counters.get("mergetier_fallbacks") == 0
        # the flight/chainaudit surface carries the achieved width
        widths = [r.batch_width for r in remote.flight.records()
                  if r.outcome == "committed"]
        assert 3 in widths and 1 in widths
        assert remote.scheduler_metrics()["mergetier"] is not None
    finally:
        remote.close()
        local.close()
        worker.close()


def test_remote_over_http_single_roundtrip():
    """One giant write through a REAL worker server (HTTP transport):
    the verified frame commits and matches the local engine."""
    srv = MergeWorkerServer(MergeWorker(linger_ms=1.0, name="http-w"))
    mt = client_mod.MergeTierClient([srv.addr], src="http-fe")
    remote = ServingEngine(start=False, mergetier=mt)
    local = ServingEngine(start=False)
    try:
        body = json_codec.dumps(Batch(tuple(chain_ops(3, 4200))))
        _push_staged(remote, [("h0", body)])
        _push_staged(local, [("h0", body)])
        _assert_docs_equiv(remote, local, ["h0"])
        mst = mt.stats()
        assert mst["remote_docs"] == 1 and not mst["fallbacks"]
        assert mst["workers"][0]["inproc"] is False
        assert srv.worker.stats()["merged_docs"] == 1
    finally:
        remote.close()
        local.close()
        srv.stop()


# -- the fallback ladder ---------------------------------------------------


def test_dead_worker_falls_back_local_zero_loss():
    """Every request to a dead worker falls back to the bit-identical
    local merge: all writes ack, documents match a local-only engine,
    and the ladder counts the rung."""
    worker = MergeWorker(linger_ms=1.0, name="dead-w")
    worker.crash()                    # answers 503 from the first byte
    mt = client_mod.MergeTierClient([worker], src="dead-fe")
    remote = ServingEngine(start=False, cross_doc=True, mergetier=mt)
    local = ServingEngine(start=False, cross_doc=True)
    docs = [f"d{i}" for i in range(3)]
    bodies = [(f"d{i}", json_codec.dumps(Batch(tuple(
        chain_ops(i + 2, N))))) for i in range(3)]
    try:
        _push_staged(remote, bodies)
        _push_staged(local, bodies)
        _assert_docs_equiv(remote, local, docs)
        mst = mt.stats()
        assert mst["fallbacks"] == {"http_status": 3}
        assert mst["remote_docs"] == 0
        assert remote.counters.get("mergetier_fallbacks") == 3
    finally:
        remote.close()
        local.close()


def test_digest_mismatch_falls_back(monkeypatch):
    """A well-formed frame bound to a DIFFERENT request (echoed
    input_digest mismatch) must never commit — counted fallback, local
    merge instead."""
    worker = MergeWorker(linger_ms=1.0, name="digest-w")
    real = worker.handle_merge

    def forged(body):
        status, resp, headers = real(body)
        if status != 200:
            return status, resp, headers
        table, meta = wire.decode_response(resp)
        return 200, wire.encode_response(
            table, meta["shared_capacity"], meta["width"],
            "0badc0ffee0badc0"), headers

    monkeypatch.setattr(worker, "handle_merge", forged)
    mt = client_mod.MergeTierClient([worker], src="digest-fe")
    remote = ServingEngine(start=False, mergetier=mt)
    local = ServingEngine(start=False)
    try:
        body = json_codec.dumps(Batch(tuple(chain_ops(5, 4200))))
        _push_staged(remote, [("g0", body)])
        _push_staged(local, [("g0", body)])
        _assert_docs_equiv(remote, local, ["g0"])
        assert mt.stats()["fallbacks"] == {"digest": 1}
    finally:
        remote.close()
        local.close()
        worker.close()


def test_breaker_opens_and_probes_recovered_worker():
    """Repeated failures open the worker's breaker (later rounds skip
    it outright, one cooldown probe excepted) and a recovered worker
    closes it again through the probe."""
    worker = MergeWorker(linger_ms=1.0, name="flaky-w")
    mt = client_mod.MergeTierClient(
        [worker], src="brk-fe", breaker_threshold=2,
        breaker_cooldown_s=0.05)
    p = packed_mod.pack(chain_ops(1, 2048))
    worker._dead = True                   # fail without closing batcher
    for _ in range(2):
        with pytest.raises(client_mod.MergeFallback):
            mt.merge_one("b0", p, p.num_ops)
    ws = mt.stats()["workers"][0]
    assert ws["breaker_open"] and ws["breaker_opens"] == 1
    # breaker open + cooldown not elapsed → no request reaches the worker
    with pytest.raises(client_mod.MergeFallback) as ei:
        mt.merge_one("b0", p, p.num_ops)
    assert ei.value.reason == "breaker_open"
    # after the cooldown the probe goes through; a healthy worker
    # closes the breaker with one success
    worker._dead = False
    time.sleep(0.06)
    table, shared, width, sub = mt.merge_one("b0", p, p.num_ops)
    assert width == 1 and shared >= p.capacity and sub is None
    ws = mt.stats()["workers"][0]
    assert not ws["breaker_open"] and ws["ok"] == 1
    worker.close()
    mt.close()


def test_kill_switch_and_env_arming(monkeypatch):
    """GRAFT_MERGETIER=0 disarms the tier even over an explicit worker
    list; GRAFT_MERGETIER=1 arms from GRAFT_MERGETIER_WORKERS but
    degrades to local-only when no worker is named."""
    worker = MergeWorker(linger_ms=1.0, name="kill-w")
    monkeypatch.setenv("GRAFT_MERGETIER", "0")
    eng = ServingEngine(start=False, mergetier=[worker])
    try:
        assert eng.mergetier is None
        assert eng.scheduler_metrics()["mergetier"] is None
    finally:
        eng.close()
    # armed-but-empty env: stays local rather than arming a client
    # that can only fall back
    monkeypatch.setenv("GRAFT_MERGETIER", "1")
    monkeypatch.delenv("GRAFT_MERGETIER_WORKERS", raising=False)
    eng = ServingEngine(start=False)
    try:
        assert eng.mergetier is None
    finally:
        eng.close()
    # env-named workers arm the client
    monkeypatch.setenv("GRAFT_MERGETIER_WORKERS", "127.0.0.1:9,127.0.0.1:10")
    eng = ServingEngine(start=False)
    try:
        assert eng.mergetier is not None
        assert len(eng.mergetier.workers) == 2
    finally:
        eng.close()
    worker.close()


# -- worker death mid-round (crash site mid-remote-merge) ------------------


def test_crash_mid_remote_merge_zero_acked_loss(tmp_path, monkeypatch):
    """A durable front-end dies at ``mid-remote-merge`` — verified
    frames in hand, nothing committed, nothing acked: recovery serves
    every previously acked write, the doomed delta is simply absent
    (never acked), and the recovered doc accepts writes at once."""
    monkeypatch.setenv("GRAFT_MERGETIER_MIN_OPS", "1024")
    worker = MergeWorker(linger_ms=1.0, name="crash-w")
    ddir = tmp_path / "dur"
    eng = ServingEngine(
        durable_dir=str(ddir), wal_sync="batch", submit_timeout_s=2.0,
        flight=flight_mod.FlightRecorder(),
        mergetier=client_mod.MergeTierClient([worker], src="crash-fe"))
    acked = []
    ops = chain_ops(1, 15)
    for i in range(0, 15, 5):
        ok, _ = eng.submit("doc", json_codec.dumps(
            Batch(tuple(ops[i:i + 5]))))
        assert ok
        acked.extend(ops[i:i + 5])
    assert eng.flush(30)
    monkeypatch.setenv("GRAFT_CRASH_POINT", "mid-remote-merge")
    doomed_ops = chain_ops(1, 1100, counter0=15, anchor=OFFSET + 15)
    crashed = {}

    def doomed():
        try:
            crashed["ack"] = eng.submit("doc", json_codec.dumps(
                Batch(tuple(doomed_ops))))
        except SchedulerStopped:
            crashed["ack"] = None

    th = threading.Thread(target=doomed, daemon=True)
    th.start()
    eng.scheduler.join(30)
    assert not eng.scheduler.is_alive(), "mid-remote-merge never fired"
    th.join(10)
    # the site sits between the worker's answer and the commit: the
    # remote merge HAPPENED, the ack never did
    assert crashed.get("ack") is None, "a write acked after the crash"
    assert worker.stats()["merged_docs"] == 1
    monkeypatch.delenv("GRAFT_CRASH_POINT")
    worker.close()
    # recover from disk (the wounded engine is abandoned, un-closed)
    eng2 = ServingEngine(durable_dir=str(ddir), wal_sync="batch")
    try:
        doc2 = eng2.get("doc", create=False)
        assert doc2 is not None and doc2.epoch == 2
        assert doc2.snapshot() == [op.value for op in acked]
        # serving-ready: an independent chain lands immediately
        ok, _ = eng2.submit("doc", json_codec.dumps(
            Batch(tuple(chain_ops(9, 3)))))
        assert ok
    finally:
        eng2.close()


def test_netchaos_cut_on_merge_link_falls_back(monkeypatch):
    """A deterministic netchaos cut on the front-end→worker link
    severs every remote merge mid-response: the production ladder
    falls back locally, every write acks, zero loss — and the fired
    counters prove the faults actually hit the merge path."""
    from crdt_graph_tpu.cluster import netchaos as netchaos_mod
    srv = MergeWorkerServer(MergeWorker(linger_ms=200.0, name="cut-w"))
    chaos = netchaos_mod.NetChaos(seed=7, spec="cut=1.0")
    mt = client_mod.MergeTierClient([srv.addr], src="cut-fe",
                                    chaos=chaos)
    remote = ServingEngine(start=False, cross_doc=True, mergetier=mt)
    local = ServingEngine(start=False, cross_doc=True)
    docs = [f"c{i}" for i in range(3)]
    bodies = [(f"c{i}", json_codec.dumps(Batch(tuple(
        chain_ops(i + 2, N))))) for i in range(3)]
    try:
        _push_staged(remote, bodies)
        _push_staged(local, bodies)
        _assert_docs_equiv(remote, local, docs)
        mst = mt.stats()
        assert mst["remote_docs"] == 0
        assert sum(mst["fallbacks"].values()) == 3
        assert set(mst["fallbacks"]) <= {"transport", "breaker_open",
                                         "timeout"}
        assert chaos.counters["cuts"] >= 1
        assert remote.counters.get("mergetier_fallbacks") == 3
    finally:
        remote.close()
        local.close()
        srv.stop()


# -- telemetry: present when on, ABSENT when off ---------------------------


def test_prom_families_present_when_armed_absent_when_off():
    worker = MergeWorker(linger_ms=200.0, name="prom-w")
    mt = client_mod.MergeTierClient([worker], src="prom-fe")
    on = ServingEngine(start=False, cross_doc=True, mergetier=mt)
    off = ServingEngine(start=False, cross_doc=True)
    try:
        bodies = [(f"p{i}", json_codec.dumps(Batch(tuple(
            chain_ops(i + 2, N))))) for i in range(3)]
        _push_staged(on, bodies)
        fams = prom_mod.parse_text(on.render_prom())   # strict parse
        for fam in ("crdt_mergetier_workers",
                    "crdt_mergetier_workers_open",
                    "crdt_mergetier_breaker_opens_total",
                    "crdt_mergetier_rounds_total",
                    "crdt_mergetier_remote_docs_total",
                    "crdt_mergetier_remote_ops_total",
                    "crdt_mergetier_fallbacks_total",
                    "crdt_mergetier_batch_width",
                    "crdt_mergetier_remote_ms"):
            assert fam in fams, fam
        assert fams["crdt_mergetier_remote_docs_total"][
            "samples"][0][2] == 3.0
        assert fams["crdt_mergetier_workers"]["samples"][0][2] == 1.0
        # the worker-side scrape (its own /metrics/prom) parses too,
        # linger occupancy and width distribution included
        wfams = prom_mod.parse_text(worker.render_prom())
        for fam in ("crdt_mergetier_worker_up",
                    "crdt_mergetier_worker_requests_total",
                    "crdt_mergetier_worker_launches_total",
                    "crdt_mergetier_worker_linger_occupancy",
                    "crdt_mergetier_worker_batch_width"):
            assert fam in wfams, fam
        assert wfams["crdt_mergetier_worker_up"]["samples"][0][2] == 1.0
        # tier off: every crdt_mergetier_* family is ABSENT (the A/B
        # scrape contract)
        off_fams = prom_mod.parse_text(off.render_prom())
        assert not [f for f in off_fams
                    if f.startswith("crdt_mergetier_")]
    finally:
        on.close()
        off.close()
        worker.close()


# -- worker pool registration over the coordination KV ---------------------


def test_mergepool_register_expire_and_keeper():
    from crdt_graph_tpu.cluster import mergepool
    from crdt_graph_tpu.cluster.kv import MemoryKV
    kv = MemoryKV()
    now = [1000.0]
    clock = lambda: now[0]                         # noqa: E731
    mergepool.register(kv, "w1", "127.0.0.1:9101", ttl_s=5.0,
                       clock=clock)
    mergepool.register(kv, "w0", "127.0.0.1:9100", ttl_s=5.0,
                       clock=clock)
    workers = mergepool.list_workers(kv, clock=clock)
    assert [w["name"] for w in workers] == ["w0", "w1"]   # name-sorted
    # re-registration refreshes (CAS over the old incarnation)
    mergepool.register(kv, "w1", "127.0.0.1:9201", ttl_s=5.0,
                       clock=clock)
    workers = mergepool.list_workers(kv, clock=clock)
    assert workers[1]["addr"] == "127.0.0.1:9201"
    # a worker that stops renewing ages out at its TTL
    now[0] += 6.0
    assert mergepool.list_workers(kv, clock=clock) == []
    # the keeper renews under real time; stop deregisters
    keeper = mergepool.MergePoolKeeper(kv, "w2", "127.0.0.1:9102",
                                       ttl_s=5.0).start()
    assert [w["name"] for w in mergepool.list_workers(kv)] == ["w2"]
    keeper.stop()
    assert mergepool.list_workers(kv) == []
    # from_env(kv=...) builds the client off the registry
    mergepool.register(kv, "w3", "127.0.0.1:9103", ttl_s=60.0)
    mt = client_mod.MergeTierClient.from_env(src="kv-fe", kv=kv)
    assert mt is not None
    assert mt.workers[0].endpoint == "127.0.0.1:9103"
    mt.close()


# -- the closed-loop oracle leg --------------------------------------------


def test_loadgen_with_mergetier_zero_violations(monkeypatch):
    """A full closed-loop loadgen run with the tier armed: zero oracle
    violations, the giant racer routed remote, the report carries the
    mergetier block and the remote_merge ack stage."""
    from crdt_graph_tpu.bench import loadgen
    monkeypatch.setenv("GRAFT_MERGETIER_MIN_OPS", "1024")
    worker = MergeWorker(linger_ms=1.0, name="load-w")
    engine = ServingEngine(
        flight=flight_mod.FlightRecorder(capacity=4096),
        max_queue_requests=64,
        mergetier=client_mod.MergeTierClient([worker], src="load-fe"))
    cfg = loadgen.LoadgenConfig(
        n_sessions=8, n_docs=2, writes_per_session=4, delta_size=8,
        max_queue_requests=64, giant_ops=2000, stage_first_round=True,
        seed=3)
    try:
        rep = loadgen.run(cfg, engine=engine)
    finally:
        engine.close()
        worker.close()
    assert not rep["errors"], rep["errors"]
    assert rep["oracle"]["violations_total"] == 0
    assert rep["violations"] == []
    assert rep["writes_acked"] == 8 * 4 + 1          # + the giant
    mst = rep["mergetier"]
    assert mst is not None and mst["remote_docs"] >= 1
    assert not mst["fallbacks"]
    assert rep["ack_breakdown_ms"]["remote_merge"] is not None


@pytest.mark.slow
def test_bench_mergetier_headline_full(tmp_path):
    """The committed-artifact run (BENCH_MERGETIER_r01_cpu.json
    shape): interleaved coalesced / per-replica / local legs, mean
    cross-fleet width ≥ 2× the per-replica baseline, zero fallbacks on
    the tiered legs, zero violations everywhere.  Slow-marked — the
    tier-1 gate runs the loadgen smoke above instead."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "_bench_mergetier_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_mergetier_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_MERGETIER_test.json"))
    assert out["gate"]["pass"]
    assert out["violations_total"] == 0 and out["errors_total"] == 0
    assert out["legs"]["coalesced"]["best"]["mean_width"] >= \
        2 * out["legs"]["perreplica"]["best"]["mean_width"]
    assert out["legs"]["local"]["best"]["writes_per_sec"] > 0
