"""The pallas bounded-span row gather must equal the lax reference
(ops/fused_resolve.py), and the full merge must be bit-identical with
the pallas resolution path green — across every sweep shape, including
the three adversarial configs (ISSUE 2 satellite: fused-resolution
coverage).  Runs the Mosaic kernel in interpreter mode on CPU; the
real-TPU path is staged for the next grant window
(scripts/tpu_next_grant.sh)."""
import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from crdt_graph_tpu.bench import workloads  # noqa: E402
from crdt_graph_tpu.codec import packed  # noqa: E402
from crdt_graph_tpu.ops import fused_resolve, merge, view  # noqa: E402

FIELDS = ["ts", "parent", "depth", "value_ref", "paths", "exists",
          "tombstone", "dead", "visible", "doc_index", "order",
          "visible_order", "num_nodes", "num_visible", "status"]


def _bounded_span_idx(rng, t, r, spread):
    """Indices that wander but stay within ``spread`` of a moving base
    (bounded span per tile when spread is small)."""
    base = np.minimum(np.arange(t, dtype=np.int64) * max(r - 1, 1) // max(t, 1),
                      r - 1)
    jitter = rng.integers(-spread, spread + 1, t)
    return np.clip(base + jitter, 0, r - 1).astype(np.int32)


@pytest.mark.parametrize("t,r,c", [(7, 5, 1), (700, 700, 3),
                                   (1024, 4096, 5), (2050, 2050, 9)])
def test_interpret_matches_lax(t, r, c):
    rng = np.random.default_rng(t * 31 + r)
    # full int64 range including >= 2^48 values (timestamps): the
    # 16-bit-limb one-hot contraction must be exact everywhere
    plane = rng.integers(0, 2**62, (r, c), dtype=np.int64)
    plane[rng.random((r, c)) < 0.2] = 2**62 - 1
    idx = _bounded_span_idx(rng, t, r, spread=40)
    want = np.asarray(fused_resolve._lax_rows(jnp.asarray(plane),
                                              jnp.asarray(idx)))
    got = np.asarray(fused_resolve.plane_rows(
        jnp.asarray(plane), jnp.asarray(idx), interpret=True))
    np.testing.assert_array_equal(got, want)


def test_constant_and_identity_idx():
    plane = jnp.asarray(
        np.arange(40, dtype=np.int64).reshape(8, 5) * 3**30)
    for idx in (np.zeros(1300, np.int32),
                np.arange(8, dtype=np.int32)):
        got = np.asarray(fused_resolve.plane_rows(
            plane, jnp.asarray(idx), interpret=True))
        np.testing.assert_array_equal(
            got, np.asarray(plane)[np.asarray(idx)])


def test_span_violation_falls_back_identically():
    """A shuffled index (no bounded span) must take the in-trace lax
    fallback and still be exactly right."""
    rng = np.random.default_rng(0)
    r, t, c = 8192, 2048, 4
    plane = rng.integers(0, 2**62, (r, c), dtype=np.int64)
    idx = rng.permutation(r)[:t].astype(np.int32)   # spans ~all rows
    got = np.asarray(fused_resolve.plane_rows(
        jnp.asarray(plane), jnp.asarray(idx), interpret=True))
    np.testing.assert_array_equal(got, np.asarray(plane)[idx])


def test_auto_falls_back_on_cpu():
    rng = np.random.default_rng(1)
    plane = jnp.asarray(rng.integers(0, 2**40, (300, 3), dtype=np.int64))
    idx = jnp.asarray(_bounded_span_idx(rng, 200, 300, 10))
    got = np.asarray(fused_resolve.plane_rows(plane, idx))
    np.testing.assert_array_equal(got, np.asarray(plane)[np.asarray(idx)])


# --- the 2-hop resolution superop (round 7) ---------------------------

def _hop_plane(rng, r, c, hop_col, hop_spread, neg_frac=0.2):
    """A plane whose ``hop_col`` holds a locally-bounded row index
    (or -1 with probability ``neg_frac``) and full-range int64 payload
    elsewhere."""
    plane = rng.integers(0, 2**62, (r, c), dtype=np.int64)
    hops = np.clip(np.arange(r) +
                   rng.integers(-hop_spread, hop_spread + 1, r), 0, r - 1)
    hops[rng.random(r) < neg_frac] = -1
    plane[:, hop_col] = hops
    return plane


@pytest.mark.parametrize("t,r,c,hop_col", [
    (700, 700, 3, 1), (1024, 4096, 5, 2), (2050, 2050, 6, 4)])
def test_plane_rows2_interpret_matches_lax(t, r, c, hop_col):
    rng = np.random.default_rng(t * 13 + r)
    plane = _hop_plane(rng, r, c, hop_col, hop_spread=40)
    idx = _bounded_span_idx(rng, t, r, spread=40)
    want = fused_resolve._lax_rows2(jnp.asarray(plane),
                                    jnp.asarray(idx), hop_col)
    got = fused_resolve.plane_rows2(jnp.asarray(plane),
                                    jnp.asarray(idx), hop_col,
                                    interpret=True)
    for gw, ww, tag in ((got[0], want[0], "hop1"),
                        (got[1], want[1], "hop2")):
        np.testing.assert_array_equal(np.asarray(gw), np.asarray(ww),
                                      err_msg=tag)


def test_plane_rows2_span_violation_falls_back():
    """A shuffled FIRST-hop index routes the whole sweep through the
    fallback branch — still exactly right."""
    rng = np.random.default_rng(2)
    r, t, c = 8192, 2048, 4
    plane = _hop_plane(rng, r, c, 2, hop_spread=30)
    idx = rng.permutation(r)[:t].astype(np.int32)
    want = fused_resolve._lax_rows2(jnp.asarray(plane),
                                    jnp.asarray(idx), 2)
    got = fused_resolve.plane_rows2(jnp.asarray(plane),
                                    jnp.asarray(idx), 2, interpret=True)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))


def test_plane_rows2_hop_violation_falls_back():
    """A far-jumping SECOND hop (|hop - row| > HOP_J) keeps the first
    hop on the single-hop pallas sweep and takes the lax second gather
    — still exactly right."""
    rng = np.random.default_rng(3)
    r, t, c = 8192, 2048, 4
    plane = _hop_plane(rng, r, c, 2, hop_spread=30)
    hops = np.asarray(plane[:, 2]).copy()
    hops[100] = r - 1                      # one violating far hop
    plane[:, 2] = hops
    idx = _bounded_span_idx(rng, t, r, spread=40)
    want = fused_resolve._lax_rows2(jnp.asarray(plane),
                                    jnp.asarray(idx), 2)
    got = fused_resolve.plane_rows2(jnp.asarray(plane),
                                    jnp.asarray(idx), 2, interpret=True)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))


# --- full-merge parity: every sweep shape, pallas resolution green ----

def _small_configs():
    """Small instances of all 8 sweep shapes (BASELINE configs 1-5 +
    the three adversarial extensions), as packed column dicts."""
    return {
        1: packed.pack(workloads.editor_replay(400)).arrays(),
        2: packed.pack(workloads.two_replica_interleaved(800)).arrays(),
        3: packed.pack(workloads.nested_tree(1500, 4)).arrays(),
        4: packed.pack(
            workloads.tombstone_heavy(600, 8)).arrays(),
        5: workloads.chain_workload(4, 2048),
        6: workloads.descending_chains(64, 2048),
        7: workloads.comb_pairs(2048),
        8: workloads.deep_paths(8, 2048),
    }


@pytest.mark.parametrize("cid", sorted(_small_configs()))
def test_full_merge_pallas_interpret_bit_identity(cid, monkeypatch):
    """merge with use_pallas=True (interpreted Mosaic: mono_gather AND
    the fused_resolve plane sweep) == the lax path, every NodeTable
    field, production exhaustive mode."""
    monkeypatch.setenv("GRAFT_PALLAS_INTERPRET", "1")
    arrs = _small_configs()[cid]
    t_lax = view.to_host(merge.materialize(arrs, use_pallas=False,
                                           hints="exhaustive"))
    t_pal = view.to_host(merge.materialize(arrs, use_pallas=True,
                                           hints="exhaustive"))
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(t_pal, f)), np.asarray(getattr(t_lax, f)),
            err_msg=f"config {cid} field {f}")


@pytest.mark.parametrize("flags_on", [True, False])
def test_fallback_path_order_exact_config5(flags_on, monkeypatch, request):
    """ISSUE 3 acceptance: the config-5 closed-form order must hold on
    the lax path both with the round-7 fusions on (their lax fallbacks)
    and with every GRAFT_FUSED_* kill-switch thrown (the round-6
    trace)."""
    for f in ("GRAFT_FUSED_RESOLVE", "GRAFT_FUSED_TAIL",
              "GRAFT_FUSED_SCAN", "GRAFT_FUSED_SUPEROP"):
        if flags_on:
            monkeypatch.delenv(f, raising=False)
        else:
            monkeypatch.setenv(f, "0")
    # the flags are read at TRACE time under identical shapes/static
    # args, so a cached trace from the other parametrization (or from
    # earlier tests) would silently shadow this leg's flag state — and
    # this leg's trace would poison later tests the same way
    jax.clear_caches()
    request.addfinalizer(jax.clear_caches)
    n = 65_536
    arrs = workloads.chain_workload(64, n)
    t = view.to_host(merge.materialize(arrs, use_pallas=False,
                                       hints="exhaustive"))
    exp = workloads.chain_expected_ts(64, n)
    seq = np.asarray(t.ts)[np.asarray(t.visible_order)]
    seq = seq[:int(t.num_visible)]
    assert int(t.num_visible) == n
    np.testing.assert_array_equal(seq, exp)


def test_full_merge_pallas_interpret_auto_mode(monkeypatch):
    """The verified auto mode rides the same pallas plane sweep."""
    monkeypatch.setenv("GRAFT_PALLAS_INTERPRET", "1")
    arrs = _small_configs()[5]
    t_lax = view.to_host(merge.materialize(arrs, use_pallas=False))
    t_pal = view.to_host(merge.materialize(arrs, use_pallas=True))
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(t_pal, f)), np.asarray(getattr(t_lax, f)),
            err_msg=f"auto field {f}")
