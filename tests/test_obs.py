"""Observability subsystem (ISSUE 5): end-to-end commit tracing, the
flight recorder, and the unified telemetry exposition.

Acceptance pins:

- a soak drives concurrent pushes through ``ServingEngine`` and every
  commit record in the flight recorder carries ≥1 trace_id minted at
  admission (and the union of records covers every submitted id);
- a deliberately slow (SLO-breaching) commit and an injected audit
  failure each produce a JSONL dump containing the full stage
  breakdown;
- ``/metrics/prom`` parses with consistent counter/histogram naming
  (strict parser: ``crdt_`` namespace, counters end ``_total``,
  cumulative ``le`` buckets ending ``+Inf``).

Plus the satellite pins: multi-threaded observe/snapshot races on the
serve metrics, histogram bucket-bound exposition, and ring-buffer
wraparound/dump-trigger behavior.
"""
import json
import os
import threading
import time

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.codec import json_codec                  # noqa: E402
from crdt_graph_tpu.core.operation import Add, Batch         # noqa: E402
from crdt_graph_tpu.obs import flight as flight_mod          # noqa: E402
from crdt_graph_tpu.obs import prom as prom_mod              # noqa: E402
from crdt_graph_tpu.obs.trace import ensure_trace_id, \
    mint_trace_id                                            # noqa: E402
from crdt_graph_tpu.serve import SchedulerError, ServingEngine  # noqa: E402
from crdt_graph_tpu.serve.metrics import Counters, Histogram  # noqa: E402

OFFSET = 2**32


def chain_ops(rid, n, counter0=0, anchor=0):
    ops, prev = [], anchor
    for i in range(n):
        ts = rid * OFFSET + counter0 + i + 1
        ops.append(Add(ts, (prev,), (counter0 + i) & 0xFF))
        prev = ts
    return ops


def mk_recorder(tmp_path, **kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("slo_ms", 60_000.0)
    kw.setdefault("audit_every", 0)
    kw.setdefault("dump_dir", str(tmp_path))
    kw.setdefault("min_dump_interval_s", 0.0)
    return flight_mod.FlightRecorder(**kw)


def base_rec(**over):
    """Minimal record-field dict for direct FlightRecorder.record."""
    rec = {
        "doc_id": "d", "trace_ids": ("t" * 16,), "outcome": "committed",
        "num_ops": 1, "applied_ops": 1, "dup_ops": 0,
        "coalesce_width": 1, "chunk_count": 1,
        "queue_depth_admission": 0,
        "stages_ms": {"parse": 0.1, "merge": 0.2, "publish": 0.1},
        "total_ms": 0.5, "staleness_s": 0.01, "snapshot_seq": 1,
        "fingerprint": "abcd", "audit": None, "error": None,
    }
    rec.update(over)
    return rec


# -- trace ids -------------------------------------------------------------


def test_trace_id_mint_and_adopt():
    a, b = mint_trace_id(), mint_trace_id()
    assert a != b and len(a) == 16
    # well-formed client ids are adopted verbatim
    assert ensure_trace_id("client-trace-42") == "client-trace-42"
    # malformed / missing ids are re-minted (they land in filenames
    # and label values)
    assert ensure_trace_id(None) != ensure_trace_id(None)
    assert ensure_trace_id("short") != "short"
    assert ensure_trace_id("x" * 65) != "x" * 65
    assert ensure_trace_id('bad"quote__') != 'bad"quote__'


# -- serve metrics under concurrency (satellite) ---------------------------


def test_histogram_concurrent_observe_snapshot_race():
    """8 observer threads race snapshot/export readers; no exception,
    no lost updates: the final exported count/sum account for every
    observation."""
    h = Histogram((1, 2, 4, 8))
    n_threads, per_thread = 8, 2000
    stop = threading.Event()
    errors = []

    def observer():
        for i in range(per_thread):
            h.observe(float(i % 10))

    def reader():
        while not stop.is_set():
            snap = h.snapshot()
            exp = h.export()
            try:
                assert sum(exp["counts"]) == exp["count"]
                if snap["count"]:
                    assert snap["sum"] >= 0
            except AssertionError as e:    # noqa: PERF203
                errors.append(str(e))
                return

    readers = [threading.Thread(target=reader) for _ in range(2)]
    observers = [threading.Thread(target=observer)
                 for _ in range(n_threads)]
    for t in readers + observers:
        t.start()
    for t in observers:
        t.join(30)
    stop.set()
    for t in readers:
        t.join(10)
    assert not errors, errors[:3]
    exp = h.export()
    assert exp["count"] == n_threads * per_thread
    assert sum(exp["counts"]) == exp["count"]
    assert exp["sum"] == pytest.approx(
        n_threads * sum(i % 10 for i in range(per_thread)))


def test_counters_concurrent_add():
    c = Counters()
    threads = [threading.Thread(
        target=lambda: [c.add("x") for _ in range(5000)])
        for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert c.get("x") == 30000


def test_histogram_export_exposes_bucket_bounds():
    """The exposition carries the BOUNDS and per-bucket counts — not
    just the quantile summary — and they round-trip through the prom
    renderer's cumulative le series."""
    h = Histogram((1, 5, 10))
    for v in (0.5, 0.7, 3, 7, 20, 30):
        h.observe(v)
    exp = h.export()
    assert exp["bounds"] == [1, 5, 10]
    assert exp["counts"] == [2, 1, 1, 2]     # last = overflow
    assert exp["count"] == 6 and exp["max"] == 30
    # cumulative rendering ends at the exact count
    w = prom_mod._Writer()
    w.histogram("crdt_x_ms", "t", exp["bounds"], exp["counts"],
                exp["count"], exp["sum"], {"doc": "d"})
    fams = prom_mod.parse_text(w.render())
    buckets = [(lbl["le"], v) for name, lbl, v in
               fams["crdt_x_ms"]["samples"] if name.endswith("_bucket")]
    assert buckets == [("1", 2.0), ("5", 3.0), ("10", 4.0),
                       ("+Inf", 6.0)]


def test_prom_label_values_round_trip_through_escaping():
    """Label values with backslashes, quotes, and newlines must come
    back from parse_text exactly as they went into the writer — a
    label-keyed consumer joining parsed labels to doc ids must not see
    the escaped text."""
    w = prom_mod._Writer()
    for raw in ('a"b', "a\\b", "a\nb", 'tricky\\"mix\n'):
        w = prom_mod._Writer()
        w.counter("crdt_t_total", "t", 1, {"doc": raw})
        fams = prom_mod.parse_text(w.render())
        (_, lbl, v), = fams["crdt_t_total"]["samples"]
        assert lbl["doc"] == raw, (raw, lbl["doc"])
        assert v == 1.0


def test_prom_parser_rejects_inconsistent_exposition():
    with pytest.raises(prom_mod.PromParseError):
        prom_mod.parse_text("# HELP crdt_a b\n# TYPE crdt_a counter\n"
                            "crdt_a 1\n")        # counter sans _total
    with pytest.raises(prom_mod.PromParseError):
        prom_mod.parse_text(
            "# HELP crdt_h t\n# TYPE crdt_h histogram\n"
            'crdt_h_bucket{le="1"} 5\ncrdt_h_bucket{le="+Inf"} 3\n'
            "crdt_h_sum 1\ncrdt_h_count 3\n")     # not cumulative
    with pytest.raises(prom_mod.PromParseError):
        prom_mod.parse_text("# HELP other_x t\n# TYPE other_x gauge\n"
                            "other_x 1\n")        # outside namespace
    with pytest.raises(prom_mod.PromParseError):
        prom_mod.parse_text("# HELP crdt_h t\n# TYPE crdt_h histogram\n"
                            'crdt_h_bucket{le="1"} 1\n'
                            "crdt_h_sum 1\ncrdt_h_count 1\n")  # no +Inf


# -- flight recorder core (satellite) --------------------------------------


def test_flight_ring_wraparound(tmp_path):
    rec = mk_recorder(tmp_path, capacity=8)
    for i in range(20):
        rec.record(base_rec(num_ops=i))
    records = rec.records()
    assert len(records) == 8                       # bounded
    assert [r.num_ops for r in records] == list(range(12, 20))
    assert records[-1].seq == 20                   # seq keeps counting
    st = rec.stats()
    assert st["records_total"] == 20 and st["records"] == 8
    # a manual dump after wraparound carries exactly the ring
    path = rec.dump()
    lines = [json.loads(ln) for ln in
             open(path).read().splitlines()]
    assert lines[0]["flight_dump"] and lines[0]["records"] == 8
    assert [ln["num_ops"] for ln in lines[1:]] == list(range(12, 20))


def test_flight_dump_triggers_and_rate_limit(tmp_path):
    rec = mk_recorder(tmp_path, slo_ms=100.0, min_dump_interval_s=60.0)
    assert rec.record(base_rec()) is None          # under SLO: no dump
    p1 = rec.record(base_rec(total_ms=250.0))      # breach → dump
    assert p1 and os.path.exists(p1) and p1.endswith("_slo.jsonl")
    # second breach inside the rate-limit window is suppressed
    assert rec.record(base_rec(total_ms=300.0)) is None
    st = rec.stats()
    assert st["slo_breaches"] == 2
    assert st["dumps"] == {"slo": 1, "suppressed": 1}
    # audit failure and error outcomes are independent triggers
    rec2 = mk_recorder(tmp_path)
    pa = rec2.record(base_rec(audit={"ok": False, "fast_path": 99}))
    pe = rec2.record(base_rec(outcome="error", error="boom"))
    assert pa.endswith("_audit.jsonl") and pe.endswith("_error.jsonl")
    # a sample_error without a verdict is NOT an audit failure
    assert rec2.record(base_rec(audit={"sample_error": "x"})) is None
    st2 = rec2.stats()
    assert st2["audit_failures"] == 1 and st2["errors"] == 1


# -- the acceptance soak ---------------------------------------------------


def test_soak_every_commit_record_carries_admission_trace_ids(tmp_path):
    """Concurrent pushes across documents: every flight record carries
    ≥1 trace_id, and the records' union covers every id minted at
    admission — a coalesced batch is attributable to ALL its
    requests."""
    rec = mk_recorder(tmp_path, capacity=4096)
    engine = ServingEngine(flight=rec)
    n_docs, writers_per_doc, deltas = 3, 3, 4
    submitted_ids = set()
    ids_lock = threading.Lock()
    errors = []

    def writer(doc_id, rid, widx):
        counter, anchor = 0, 0
        for d in range(deltas):
            ops = chain_ops(rid, 8, counter0=counter, anchor=anchor)
            counter += 8
            anchor = rid * OFFSET + counter
            tid = f"soak-{doc_id}-w{widx}-{d:02d}"
            with ids_lock:
                submitted_ids.add(tid)
            try:
                acc, _ = engine.submit(doc_id, json_codec.dumps(
                    Batch(tuple(ops))), trace_id=tid)
                if not acc:
                    errors.append(f"{tid} rejected")
            except Exception as e:      # noqa: BLE001 — test capture
                errors.append(f"{tid}: {e!r}")

    threads = [threading.Thread(target=writer,
                                args=(f"doc{i}", 1 + w, w), daemon=True)
               for i in range(n_docs) for w in range(writers_per_doc)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    # the flush barrier replaces close()-as-barrier: records are
    # guaranteed recorded, and the engine KEEPS serving afterwards.
    # close() before asserting — a failure must not leak a live
    # scheduler thread into the rest of the test session
    try:
        flushed = engine.flush(timeout=60)
    finally:
        engine.close()
    assert flushed
    assert not errors, errors[:5]

    records = rec.records()
    assert records, "no commit records"
    seen_ids = set()
    for r in records:
        assert len(r.trace_ids) >= 1, f"record {r.seq} has no trace_id"
        assert r.outcome in ("committed", "partial", "noop")
        assert r.coalesce_width >= 1
        assert set(r.stages_ms) >= {"parse", "fuse"}
        assert r.fingerprint and r.snapshot_seq >= 1
        seen_ids.update(r.trace_ids)
    assert seen_ids == submitted_ids, \
        f"missing: {sorted(submitted_ids - seen_ids)[:5]}"
    # coalescing happened at least once under 3 concurrent writers, or
    # every commit was width-1 — either way the widths sum to the
    # request count
    assert sum(r.coalesce_width for r in records) == len(submitted_ids)


def test_slo_breach_dumps_full_stage_breakdown(tmp_path):
    """A deliberately slow commit (over the recorder's SLO) triggers a
    JSONL dump whose record carries the full parse/fuse/merge/publish
    breakdown and the admission trace id."""
    rec = mk_recorder(tmp_path, slo_ms=120.0)
    engine = ServingEngine(flight=rec)
    try:
        engine.submit("slo", json_codec.dumps(
            Batch(tuple(chain_ops(1, 8)))), trace_id="slo-fast-commit")
        doc = engine.get("slo")
        real = doc.tree.apply_packed_chunked

        def slow(*a, **k):
            time.sleep(0.3)
            return real(*a, **k)

        doc.tree.apply_packed_chunked = slow
        engine.submit("slo", json_codec.dumps(
            Batch(tuple(chain_ops(1, 8, counter0=8,
                                  anchor=OFFSET + 8)))),
            trace_id="slo-slow-commit")
    finally:
        engine.close()
    st = rec.stats()
    assert st["slo_breaches"] == 1
    path = st["last_dump_path"]
    assert path and path.endswith("_slo.jsonl")
    lines = [json.loads(ln) for ln in open(path).read().splitlines()]
    assert lines[0]["reason"] == "slo"
    slow_recs = [ln for ln in lines[1:]
                 if "slo-slow-commit" in ln.get("trace_ids", ())]
    assert len(slow_recs) == 1
    r = slow_recs[0]
    assert r["total_ms"] > 120.0
    assert set(r["stages_ms"]) >= {"parse", "fuse", "merge", "publish"}
    assert r["stages_ms"]["merge"] > 250.0       # the injected sleep
    assert r["outcome"] == "committed" and r["fingerprint"]


def test_audit_failure_is_a_dump_trigger_through_the_engine(tmp_path):
    """The sampled chain audit as a production tripwire: a batch whose
    trace exceeds the CI budget produces an audit record with
    ``ok: false`` and a JSONL dump.  (Sampling a small batch with
    ``audit_min_ops=0`` IS the genuine failure mode — the compacted
    tiers dominate a tiny threshold, exactly what the min-width gate
    exists to exclude in production.)"""
    rec = mk_recorder(tmp_path, audit_every=1, audit_min_ops=0)
    engine = ServingEngine(flight=rec)
    try:
        engine.submit("au", json_codec.dumps(
            Batch(tuple(chain_ops(1, 40)))), trace_id="audit-fail-trace")
    finally:
        engine.close()
    st = rec.stats()
    assert st["audit_failures"] == 1
    records = rec.records()
    audited = [r for r in records if r.audit is not None]
    assert len(audited) == 1
    a = audited[0].audit
    assert a["ok"] is False and a["fast_path"] > a["budget"]
    path = st["last_dump_path"]
    assert path and path.endswith("_audit.jsonl")
    lines = [json.loads(ln) for ln in open(path).read().splitlines()]
    assert lines[0]["reason"] == "audit"
    assert any("audit-fail-trace" in ln.get("trace_ids", ())
               for ln in lines[1:])


def test_engine_exception_records_error_and_dumps(tmp_path):
    """An engine exception resolves the handler with 500 AND leaves an
    error record + dump behind (the crash-post-mortem path)."""
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(flight=rec)
    try:
        engine.submit("err", json_codec.dumps(
            Batch(tuple(chain_ops(1, 5)))))
        doc = engine.get("err")

        def boom(*a, **k):
            raise RuntimeError("injected launch failure")

        doc.tree.apply_packed_chunked = boom
        with pytest.raises(SchedulerError):
            engine.submit("err", json_codec.dumps(
                Batch(tuple(chain_ops(1, 5, counter0=5,
                                      anchor=OFFSET + 5)))),
                trace_id="err-trace-0001")
    finally:
        engine.close()
    st = rec.stats()
    assert st["errors"] == 1 and st["dumps"].get("error") == 1
    err_recs = [r for r in rec.records() if r.outcome == "error"]
    assert len(err_recs) == 1
    assert err_recs[0].trace_ids == ("err-trace-0001",)
    assert "injected launch failure" in err_recs[0].error


def test_flight_record_staleness_and_queue_depth(tmp_path):
    """Snapshot staleness at publish and admission queue depth land on
    the record: a staged multi-delta round (scheduler paused) reports
    the depth its members saw."""
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(flight=rec)
    try:
        engine.submit("sq", json_codec.dumps(
            Batch(tuple(chain_ops(1, 4)))))
        time.sleep(0.15)    # age the published snapshot measurably
        engine.scheduler.pause()
        boxes = []
        for k in range(3):
            body = json_codec.dumps(Batch(tuple(
                chain_ops(2 + k, 4))))
            th = threading.Thread(
                target=lambda b=body: engine.submit("sq", b),
                daemon=True)
            th.start()
            boxes.append(th)
            deadline = time.monotonic() + 10
            while len(engine.get("sq").queue) < k + 1:
                assert time.monotonic() < deadline
                time.sleep(0.002)
        engine.scheduler.resume()
        for th in boxes:
            th.join(30)
    finally:
        engine.close()
    records = rec.records()
    assert len(records) == 2
    fused = records[-1]
    assert fused.coalesce_width == 3
    assert fused.queue_depth_admission == 2     # deepest member saw 2
    assert fused.staleness_s >= 0.14            # the aged snapshot
    assert records[0].staleness_s < 10          # sanity: both stamped


# -- the exposition surface over HTTP --------------------------------------


def test_http_prom_and_flight_endpoints(server, req):
    """/metrics/prom parses under the strict naming contract; /debug/
    flight carries the commit records; POST echoes X-Trace-Id."""
    import http.client
    port = server.server_port
    body = json_codec.dumps(Batch(tuple(chain_ops(1, 12))))
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/docs/obs/ops", body=body,
                 headers={"X-Trace-Id": "client-chose-this-id"})
    resp = conn.getresponse()
    payload = json.loads(resp.read())
    assert resp.status == 200
    assert resp.getheader("X-Trace-Id") == "client-chose-this-id"
    assert payload["trace_id"] == "client-chose-this-id"

    # malformed client id: re-minted, echoed
    conn.request("POST", "/docs/obs/ops", body=json_codec.dumps(
        Batch(tuple(chain_ops(1, 6, counter0=12, anchor=OFFSET + 12)))),
        headers={"X-Trace-Id": "bad id!"})
    resp = conn.getresponse()
    payload2 = json.loads(resp.read())
    minted = resp.getheader("X-Trace-Id")
    assert minted != "bad id!" and payload2["trace_id"] == minted

    # unified prom exposition parses strictly
    conn.request("GET", "/metrics/prom")
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/plain")
    text = resp.read().decode()
    conn.close()
    fams = prom_mod.parse_text(text)
    for family in ("crdt_doc_ops_merged_total",
                   "crdt_doc_commit_latency_ms",
                   "crdt_doc_coalesce_width", "crdt_span_ms_total",
                   "crdt_flight_records_total"):
        assert family in fams, family
    assert fams["crdt_doc_commit_latency_ms"]["type"] == "histogram"
    merged = [v for n, lbl, v in
              fams["crdt_doc_ops_merged_total"]["samples"]
              if lbl.get("doc") == "obs"]
    assert merged == [18.0]
    spans = {lbl["span"] for _, lbl, _ in
             fams["crdt_span_ms_total"]["samples"]}
    assert {"serve.parse", "serve.merge", "serve.publish"} <= spans

    # flight debug endpoint: both commits, trace ids attached.  Records
    # land asynchronously after the POST returns — the flush barrier
    # (not a records_total poll) makes the one-shot scrape safe.
    assert server.store.flush(timeout=30)
    st, flight = req(server, "GET", "/debug/flight")
    assert st == 200
    recs = flight["records"]
    assert len(recs) == 2
    assert recs[0]["trace_ids"] == ["client-chose-this-id"]
    assert recs[1]["trace_ids"] == [minted]
    for r in recs:
        assert set(r["stages_ms"]) >= {"parse", "merge", "publish"}
        assert r["fingerprint"]


def test_autouse_fixture_resets_spans_and_default_recorder():
    """Span registry and default flight recorder start empty for every
    test (the autouse conftest fixture) — span assertions no longer
    depend on which serving test ran first."""
    from crdt_graph_tpu.utils import profiling
    assert profiling.span_stats("serve.") == {}
    assert flight_mod.get_default_recorder().stats()["records_total"] \
        == 0
