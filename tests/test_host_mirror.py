"""The engine's two apply paths — host mirror (small deltas) and batched
kernel (large deltas) — must be indistinguishable: same tree, same log,
same atomicity, same view semantics, all pinned against the oracle.
"""
import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import engine
from crdt_graph_tpu.core import operation as op_mod
from crdt_graph_tpu.host_tree import HostTree

from test_merge_kernel import _random_session


def snapshot(e):
    return (e.visible_values(), e.visible_paths(), e.log_length,
            len(e), e.timestamp, e.cursor)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_host_vs_kernel_vs_oracle(seed):
    """One big-batch apply (kernel), per-op applies (host), and the oracle
    all converge to the same tree on a >threshold random session."""
    merged, ops = _random_session(seed, n_replicas=4, steps=400)
    assert len(ops) > engine.DELTA_THRESHOLD, "session too small to force"

    big = engine.init(42)
    big.apply(crdt.Batch(tuple(ops)))           # kernel path

    small = engine.init(42)
    for op in ops:
        small.apply(op)                          # host path, op by op

    oracle_vis = merged.visible_values()
    assert big.visible_values() == oracle_vis
    assert small.visible_values() == oracle_vis
    assert big.visible_paths() == small.visible_paths()
    assert big.log_length == small.log_length == len(ops)


def test_threshold_boundary_equal():
    """Batches of exactly DELTA_THRESHOLD and DELTA_THRESHOLD+1 leaves land
    on different paths but must produce identical state."""
    rid = 3
    for count in (engine.DELTA_THRESHOLD, engine.DELTA_THRESHOLD + 1):
        ops, prev = [], 0
        for i in range(1, count + 1):
            ts = rid * 2**32 + i
            ops.append(crdt.Add(ts, (prev,), i))
            prev = ts
        e = engine.init(1)
        e.apply(crdt.Batch(tuple(ops)))
        assert e.visible_values() == list(range(1, count + 1))
        assert e.log_length == count


@pytest.mark.parametrize("big", [False, True])
def test_failing_batch_leaves_replica_untouched(big):
    """Atomicity on BOTH paths: a NotFound mid-batch raises and rolls back
    everything (host: undo journal; kernel: materialise-then-commit)."""
    e = engine.init(1)
    e.add("a").add("b")
    before = snapshot(e)
    rid = 7
    count = engine.DELTA_THRESHOLD + 1 if big else 10
    ops, prev = [], 0
    for i in range(1, count + 1):
        ts = rid * 2**32 + i
        ops.append(crdt.Add(ts, (prev,), i))
        prev = ts
    # poison an op mid-batch: anchored at a timestamp nobody has
    ops[count // 2] = crdt.Add(rid * 2**32 + count + 5, (999999,), "x")
    with pytest.raises(crdt.OperationFailedError):
        e.apply(crdt.Batch(tuple(ops)))
    assert snapshot(e) == before


def test_interleaved_host_and_kernel_applies():
    """Alternating small and large applies stays oracle-exact."""
    merged, ops = _random_session(21, n_replicas=3, steps=200)
    e = engine.init(42)
    o = crdt.init(42)
    i = 0
    chunk_sizes = [1, 3, engine.DELTA_THRESHOLD + 1, 2, 50]
    k = 0
    while i < len(ops):
        n = chunk_sizes[k % len(chunk_sizes)]
        k += 1
        chunk = crdt.Batch(tuple(ops[i:i + n]))
        e.apply(chunk)
        o = o.apply(chunk)
        i += n
    assert e.visible_values() == o.visible_values()
    assert e.log_length == len(op_mod.to_list(o.operations_since(0)))


def test_absorbed_duplicates_on_host_path():
    """Redelivering a whole delta through the host path is absorbed: log
    stable, no error, last_operation empty-ish (CRDTree.elm:318-319)."""
    e = engine.init(1)
    e.add("a").add("b")
    delta = e.operations_since(0)
    n0 = e.log_length
    e.apply(delta)
    assert e.log_length == n0
    assert list(op_mod.to_list(e.last_operation)) == []


def test_mirror_rebuild_after_kernel_matches_replay():
    """HostTree.from_table (vectorised rebuild) must equal a sequential
    replay of the same log — links, paths, visibility, everything."""
    merged, ops = _random_session(31, n_replicas=4, steps=400)
    e = engine.init(9)
    e.apply(crdt.Batch(tuple(ops)))             # kernel path
    rebuilt = e._ensure_mirror()                # from_table
    replayed = HostTree(e._max_depth)
    for op in ops:
        if isinstance(op, crdt.Add):
            replayed.apply_add(op.ts, tuple(op.path), op.value)
        else:
            replayed.apply_delete(tuple(op.path))
    a = [(rebuilt.path_of(s), rebuilt.values[int(rebuilt.value_ref[s])])
         for s in rebuilt.iter_visible()]
    b = [(replayed.path_of(s), replayed.values[int(replayed.value_ref[s])])
         for s in replayed.iter_visible()]
    assert a == b


def test_local_batch_rollback_on_host_path():
    """A failing local batch() rolls the mirror back in place; outstanding
    views stay valid (no slot reassignment happened)."""
    e = engine.init(1)
    e.add("a").add("b")
    n = e.get(e.visible_paths()[0])
    before = snapshot(e)

    def boom(t):
        raise RuntimeError("nope")

    with pytest.raises(RuntimeError):
        e.batch([lambda t: t.add("c"), lambda t: t.add("d"), boom])
    assert snapshot(e) == before
    assert n.value == "a"                       # view survived the rollback
