"""Wire-codec conformance suite.

Port of the reference's tests/JsonTest.elm plus golden byte-level fixtures —
this file is the wire-format spec between reference clients and the TPU
service, so the encoded JSON shapes are asserted literally, not just
round-tripped.
"""
import json

import pytest

from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch, Delete

OFFSET = 2**32


# -- round-trips (JsonTest.elm:16-64) -------------------------------------

def test_add_round_trip():
    op = Add(3, (1, 2), "a")
    assert json_codec.decode(json_codec.encode(op)) == op


def test_delete_round_trip():
    op = Delete((1, 2))
    assert json_codec.decode(json_codec.encode(op)) == op


def test_batch_round_trip():
    op = Batch((Add(3, (1, 2), "a"), Add(4, (1, 3), "b"), Delete((1, 2))))
    assert json_codec.decode(json_codec.encode(op)) == op


# -- golden encoded shapes (CRDTree/Operation.elm:109-130) ----------------

def test_add_golden_shape():
    assert json_codec.encode(Add(3, (1, 2), "a")) == {
        "op": "add", "path": [1, 2], "ts": 3, "val": "a"}


def test_delete_golden_shape():
    assert json_codec.encode(Delete((1, 2))) == {
        "op": "del", "path": [1, 2]}


def test_batch_golden_shape():
    assert json_codec.encode(Batch((Delete((1,)),))) == {
        "op": "batch", "ops": [{"op": "del", "path": [1]}]}


def test_string_round_trip_with_large_timestamps():
    op = Add(7 * OFFSET + 12, (OFFSET + 1, 7 * OFFSET + 11), "x")
    assert json_codec.loads(json_codec.dumps(op)) == op


# -- forward compatibility (CRDTree/Operation.elm:158-159) ----------------

def test_unknown_op_decodes_to_empty_batch():
    assert json_codec.decode({"op": "frobnicate", "x": 1}) == Batch(())


def test_malformed_raises():
    with pytest.raises(json_codec.DecodeError):
        json_codec.decode({"no": "tag"})
    with pytest.raises(json_codec.DecodeError):
        json_codec.decode({"op": "add", "path": [1]})  # missing ts/val


def test_malformed_batch_ops_field():
    for bad in (None, 5, "x", {}):
        with pytest.raises(json_codec.DecodeError):
            json_codec.decode({"op": "batch", "ops": bad})


def test_strict_types_match_reference_decoder():
    # Decode.int / Decode.list Decode.int reject these; so must we.
    with pytest.raises(json_codec.DecodeError):
        json_codec.decode({"op": "del", "path": "12"})  # string, not list
    with pytest.raises(json_codec.DecodeError):
        json_codec.decode({"op": "add", "path": [0], "ts": 3.7, "val": "a"})
    with pytest.raises(json_codec.DecodeError):
        json_codec.decode({"op": "del", "path": [True]})


# -- custom value codecs --------------------------------------------------

def test_value_codec_hooks():
    op = Add(1, (0,), {"rich": [1, 2]})
    text = json_codec.dumps(op, value_encoder=lambda v: json.dumps(v))
    back = json_codec.loads(text, value_decoder=lambda v: json.loads(v))
    assert back == op


def test_wire_integer_domain_bounded_identically_to_native():
    """Timestamps and path elements are bounded to [0, 2^62) at DECODE
    in both ingest paths: the merge kernel's int32 bit-half sort keys
    assume ts < 2^62 (merge._split_ts), so a well-formed wire op past
    the bound would silently corrupt bulk merges while the host path
    absorbed it (and a Python int past 2^63 crashes the int64 columns
    with OverflowError) — and the two parsers must reject IDENTICALLY
    or the same payload converges differently by body size.  Values are
    NOT bounded (caller-defined payloads)."""
    from crdt_graph_tpu import native

    mod = native.load()
    cases = [
        (2 ** 62 - 1, True),
        (2 ** 62, False),
        (2 ** 63 - 1, False),
        (2 ** 63, False),            # pre-fix: OverflowError deep inside
        (10 ** 25, False),
        (-1, False),                 # constructive domain is non-negative
        (0, True),                   # the sentinel anchor
    ]
    for v, want_ok in cases:
        text = '{"op":"add","ts":%d,"path":[%d],"val":1}' % (v, max(v, 0))
        try:
            json_codec.loads(text)
            py_ok = True
        except json_codec.DecodeError:
            py_ok = False
        assert py_ok == want_ok, (v, py_ok)
        if mod is not None:
            try:
                mod.parse_pack(text.encode(), 16)
                nat_ok = True
            except ValueError:
                nat_ok = False
            assert nat_ok == want_ok, (v, nat_ok)
    # JSON "-0" parses to integer 0 on both paths (json.loads yields 0;
    # the native parser special-cases the negative-zero token)
    neg_zero = '{"op":"add","ts":-0,"path":[-0],"val":1}'
    assert json_codec.loads(neg_zero).ts == 0
    if mod is not None:
        mod.parse_pack(neg_zero.encode(), 16)

    # huge VALUE payloads stay legal on BOTH paths — only ts/path are
    # domain-bounded (values ride a separate number grammar natively)
    huge_val = '{"op":"add","ts":7,"path":[0],"val":%d}' % (10 ** 30)
    op = json_codec.loads(huge_val)
    assert op.value == 10 ** 30
    if mod is not None:
        cols = mod.parse_pack(huge_val.encode(), 16)
        assert cols["values"][0] == 10 ** 30
