"""Explicitly partitioned merge (parallel/shard.py) vs the whole-array
kernel: bit-identical tables on large mixed batches, adversarial shapes,
and hostile hints, on the simulated 8-device CPU mesh (VERDICT r3
missing-2 "done" criteria)."""
import os

import numpy as np
import pytest

import jax

import crdt_graph_tpu as crdt
from crdt_graph_tpu.bench import workloads
from crdt_graph_tpu.codec import packed
from crdt_graph_tpu.ops import merge, view
from crdt_graph_tpu.parallel import mesh as mesh_mod
from crdt_graph_tpu.parallel import shard
from crdt_graph_tpu.utils import jaxcompat

# the 256k bit-identity suite runs with the packed multi-column layout
# pinned ON (the round-6 default; a hard set so neither an exported
# B-leg override nor a future default change can silently weaken what
# this file proves)
os.environ["GRAFT_PACK_GATHER"] = "1"

FIELDS = ("ts", "parent", "depth", "value_ref", "paths", "exists",
          "tombstone", "dead", "visible", "doc_index", "order",
          "visible_order", "num_nodes", "num_visible", "status")


@pytest.fixture(scope="module")
def ops_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    return mesh_mod.make_mesh(n_docs=1, n_ops=8)


def assert_identical(arrs, mesh, hints="auto"):
    """Pad once, run both paths on the SAME padded arrays, compare every
    table field bitwise."""
    n = mesh_mod.round_up(arrs["kind"].shape[0], mesh.shape["ops"])
    padded = mesh_mod._pad_ops_to(arrs, n)
    want = merge.materialize(padded)
    got = shard.shard_materialize(padded, mesh, hints=hints)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, f)), np.asarray(getattr(got, f)), f)
    return got


def test_large_mixed_batch_identity(ops_mesh):
    """≥256k ops with deletes through the explicit schedule — the r3
    verdict's 'done' bar for genuinely partitioned merges."""
    arrs = workloads.chain_with_deletes(229_376, 8)
    assert arrs["kind"].shape[0] >= 256_000
    assert (arrs["kind"] == packed.KIND_DELETE).sum() > 10_000
    assert_identical(arrs, ops_mesh)


def test_adversarial_shapes_identity(ops_mesh):
    """The bench's adversarial generators (descending chains, comb
    pairs, deep paths) at 16k ops: worst-case sibling contention and
    fragmentation through the partitioned resolve.  (Shrunk from 64k —
    the generators' adversarial structure is size-independent and the
    ≥256k scale bar lives in test_large_mixed_batch_identity; ISSUE 12
    tier-1 budget.)"""
    for arrs in (workloads.chain_workload(64, 16_384),
                 workloads.descending_chains(256, 16_384),
                 workloads.comb_pairs(16_384),
                 workloads.deep_paths(64, 16_384, max_depth=16)):
        assert_identical(arrs, ops_mesh)


def test_chain_closed_form_through_shard_map(ops_mesh):
    """Not just self-consistency: the partitioned result matches the
    closed-form expected visible sequence for the 64-chain interleave."""
    arrs = workloads.chain_workload(64, 65_536)
    got = assert_identical(arrs, ops_mesh)
    want_seq = workloads.chain_expected_ts(64, 65_536)
    seq = np.asarray(got.ts)[np.asarray(got.visible_order)][
        :int(got.num_visible)]
    np.testing.assert_array_equal(seq, want_seq)


def test_exhaustive_mode_identity(ops_mesh):
    """Vouched (pack-produced) hints through the cond-free mode."""
    from test_merge_kernel import _random_session
    _, ops = _random_session(97, n_replicas=4, steps=400)
    p = packed.pack(ops)
    assert p.hints_vouched
    assert_identical(p.arrays(), ops_mesh, hints="exhaustive")


def test_hostile_hints_fall_back_identically(ops_mesh):
    """Corrupted ranks/links trip the distributed verification; the
    gathered batch takes the shared sorted+join fallback and the result
    still matches the stock kernel byte for byte."""
    from test_merge_kernel import _random_session
    _, ops = _random_session(98, n_replicas=3, steps=300)
    p = packed.pack(ops)
    arrs = dict(p.arrays())
    rng = np.random.default_rng(3)
    r = arrs["ts_rank"].copy()
    adds = np.nonzero(r >= 0)[0]
    r[adds] = rng.permutation(r[adds])
    arrs["ts_rank"] = r
    bad = arrs["anchor_pos"].copy()
    bad[bad >= 0] = 0
    arrs["anchor_pos"] = bad
    assert_identical(arrs, ops_mesh)


def test_missing_hint_columns_rejected(ops_mesh):
    arrs = {k: v for k, v in
            packed.pack([crdt.Add(1, (0,), "a")]).arrays().items()
            if k != "ts_rank"}
    with pytest.raises(ValueError, match="hint columns"):
        shard.shard_materialize(arrs, ops_mesh)


def test_collective_volume_explicit_vs_auto(ops_mesh):
    """The measurable claim behind the module: the explicit schedule's
    collective traffic is accounted from compiled HLO and compared with
    XLA's auto-partitioning of the whole-array kernel on the same
    sharded inputs (VERDICT r3 asked for exactly this comparison)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    arrs = workloads.chain_workload(64, 65_536)
    mesh = ops_mesh
    padded = mesh_mod._pad_ops_to(
        arrs, mesh_mod.round_up(arrs["kind"].shape[0], 8))
    with jaxcompat.enable_x64(True):
        dev = {k: jax.device_put(
            v, NamedSharding(mesh, P("ops") if v.ndim == 1
                             else P("ops", None)))
            for k, v in padded.items()}

        explicit = shard.collective_stats(
            shard._shard_materialize_jit
            .lower(dev, mesh, "auto", False, True).compile().as_text())

        auto = shard.collective_stats(
            jax.jit(lambda o: merge._materialize.__wrapped__(
                o, False, None, True))
            .lower(dev).compile().as_text())

    print(f"\ncollectives explicit={explicit}\ncollectives auto={auto}")
    # both paths genuinely communicate, and the explicit schedule's
    # traffic must stay within the same order as auto-partitioning
    assert explicit["count"] > 0 and explicit["total_bytes"] > 0
    assert auto["count"] > 0
    assert explicit["total_bytes"] <= 2 * max(auto["total_bytes"], 1)
