"""Fleet-wide causal tracing + write-to-visibility ledger + canary
probing (crdt_graph_tpu/obs/fleettrace.py, ledger.py, canary.py;
ISSUE 20): cross-process trace propagation on every inter-node path,
the ``/debug/trace/{id}`` federated span tree, the per-stage
visibility-lag ledger, the ``crdt_fleettrace_*`` / ``crdt_visibility_*``
/ ``crdt_canary_*`` exposition under the strict prom naming contract,
the ``GRAFT_FLEETTRACE=0`` wire-revert, and the netchaos leg proving
the canary's numbers honestly reflect an injected link delay.
"""
import json
import threading
import time
from http.client import HTTPConnection

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.cluster import FleetServer, MemoryKV
from crdt_graph_tpu.cluster import netchaos as netchaos_mod
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.codec import packed as packed_mod
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.mergetier import wire
from crdt_graph_tpu.obs import canary as canary_mod
from crdt_graph_tpu.obs import fleettrace as fleettrace_mod
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.obs.trace import (SPAN_CTX_HEADER,
                                      TRACE_FRONTIER_HEADER,
                                      TRACE_HEADER)
from crdt_graph_tpu.serve import ServingEngine


def ts(r, c):
    return r * 2**32 + c


def req(port, method, path, body=None, headers=None, timeout=60):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw, dict(resp.getheaders())
    finally:
        conn.close()


def _spawn_fleet(kv, names, engine_factory=None, **kw):
    """Deterministic fleet (test_cluster.py's shape): huge TTL,
    dormant anti-entropy daemon — tests drive ``sync_now``."""
    fleet = {}
    for n in names:
        eng = engine_factory(n) if engine_factory is not None else None
        fleet[n] = FleetServer(n, kv, engine=eng, ttl_s=600.0,
                               ae_interval_s=3600.0, **kw)
    for fs in fleet.values():
        fs.node.refresh_ring()
    return fleet


def _stop_fleet(fleet):
    for fs in fleet.values():
        try:
            fs.stop()
        except Exception:  # noqa: BLE001 — teardown boundary
            pass


def _doc_owned_by(ring, owner, prefix="doc"):
    for i in range(500):
        d = f"{prefix}{i}"
        if ring.primary(d) == owner:
            return d
    pytest.fail(f"no doc routed to {owner}")


def _chain(rid, n, start=1, prev=0):
    ops = []
    for c in range(start, start + n):
        ops.append(Add(ts(rid, c), (prev,), f"r{rid}:{c}"))
        prev = ts(rid, c)
    return json_codec.dumps(Batch(tuple(ops)))


def chain_ops(rid, n):
    prev = 0
    ops = []
    for c in range(1, n + 1):
        ops.append(Add(ts(rid, c), (prev,), f"r{rid}:{c}"))
        prev = ts(rid, c)
    return Batch(tuple(ops))


# -- unit: span ring, wire helpers -------------------------------------------


def test_span_ring_fifo_bounded():
    """Both rings are FIFO-bounded: old traces evict past max_traces,
    old spans drop past max_spans — span state never grows with
    commit count (the tentpole's memory contract)."""
    ft = fleettrace_mod.FleetTrace("n0", max_traces=4, max_spans=3)
    for i in range(6):
        ft.record(f"t{i:08x}", "admission", seq=i)
    assert ft.trace_count() == 4
    assert ft.stats()["evicted_traces"] == 2
    assert ft.spans("t00000000") == []          # FIFO-evicted
    for j in range(5):
        ft.record("t00000005", "publish", seq=j)
    spans = ft.spans("t00000005")
    assert len(spans) == 3                      # span ring bounded
    # oldest spans dropped: the admission span and the first publishes
    assert [s["seq"] for s in spans] == [2, 3, 4]


def test_span_ctx_and_frontier_wire_helpers():
    ctx = fleettrace_mod.encode_span_ctx("n0", "forward",
                                         send_ts_ms=12345)
    assert fleettrace_mod.parse_span_ctx(ctx) == ("n0", "forward",
                                                  12345)
    # garbage tolerated, never raised — tracing cannot fail a write
    for bad in (None, "", "a;b", "a;b;c;d", ";;9", "a;b;NaNish"):
        assert fleettrace_mod.parse_span_ctx(bad) is None
    fr = fleettrace_mod.encode_frontier(999, ["ta", "tb"])
    assert fleettrace_mod.parse_frontier(fr) == (999, ["ta", "tb"])
    for bad in (None, "", "no-semicolon", "xx;ta"):
        assert fleettrace_mod.parse_frontier(bad) is None


def test_merge_wire_trace_meta_byte_identity():
    """The merge request/response bytes with trace context omitted are
    IDENTICAL to the PR-19 wire — the GRAFT_FLEETTRACE=0 revert is
    byte-exact on the merge-tier leg by construction."""
    p = packed_mod.pack(chain_ops(1, 64))
    base = wire.encode_request("d0", p, p.num_ops)
    assert wire.encode_request("d0", p, p.num_ops,
                               trace_meta=None) == base
    traced = wire.encode_request(
        "d0", p, p.num_ops,
        trace_meta={"trace_ids": ["t1"], "span_ctx": "n0;remote_merge;1"})
    assert traced != base
    _, meta = wire.decode_request(traced)
    assert meta["trace"]["trace_ids"] == ["t1"]


# -- satellite 1: forward-path trace propagation (the bugfix pin) ------------


def test_forward_propagates_minted_trace_id_two_nodes():
    """A client write WITHOUT an X-Trace-Id entering through a
    non-primary: the forwarding node mints the id, the relay rides
    under it, the primary commits under it, and the ack echoes it —
    the forwarder's hop and the committing node's record agree on ONE
    id (the bug: the relay used to forward without an id, so the
    primary minted its own and the hop was unattributable)."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n1")
        st, raw, hdr = req(fleet["n0"].port, "POST",
                           f"/docs/{doc}/ops", body=_chain(7, 4))
        assert st == 200, raw
        payload = json.loads(raw)
        tid = payload["trace_id"]
        assert hdr["X-Trace-Id"] == tid
        assert payload["served_by"]["name"] == "n1"
        # the forwarding node attributed its hop under the SAME id
        fwd_spans = fleet["n0"].node.fleettrace.spans(tid)
        assert any(s["kind"] == "forward" and s["peer"] == "n1"
                   for s in fwd_spans)
        # the primary spliced the sender's X-Span-Ctx AND committed
        # under the same id (admission + publish spans)
        prim = fleet["n1"].node.fleettrace.spans(tid)
        kinds = [s["kind"] for s in prim]
        assert "forward" in kinds       # the received hop (dir=in)
        assert "admission" in kinds and "publish" in kinds
    finally:
        _stop_fleet(fleet)


# -- satellite 4 + tentpole acceptance: the five-hop federated tree ----------


def test_debug_trace_stitches_five_hop_kinds_across_nodes(tmp_path):
    """One forwarded, watched, anti-entropy-replicated write on a
    durable 2-node fleet: ``GET /debug/trace/{id}`` on EITHER node
    assembles the full cross-node causal tree with all five hop kinds
    — admission, fsync, publish, ae_apply, watch_delivery — plus the
    forward hop itself (the tentpole's acceptance bar)."""
    kv = MemoryKV()
    fleet = _spawn_fleet(
        kv, ("n0", "n1"),
        engine_factory=lambda n: ServingEngine(
            durable_dir=str(tmp_path / n), wal_sync="batch"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n1")
        # forwarded write (no client tid) — commits durably on n1
        st, raw, hdr = req(fleet["n0"].port, "POST",
                           f"/docs/{doc}/ops", body=_chain(9, 6))
        assert st == 200, raw
        tid = json.loads(raw)["trace_id"]
        # watch delivery on the primary (an immediate resume delivery
        # — the window already has ops — rides the one shared header
        # builder, which stamps the ledger + watch_delivery span)
        st, _, whdr = req(fleet["n1"].port, "GET",
                          f"/docs/{doc}/watch?since=0&timeout=0.5")
        assert st == 200
        # anti-entropy: n0 pulls the window; its X-Trace-Frontier
        # carries the commit's trace id + n1's send timestamp
        assert fleet["n0"].node.antientropy.sync_now()["n1"] is True

        for port in (fleet["n0"].port, fleet["n1"].port):
            st, raw, _ = req(port, "GET", f"/debug/trace/{tid}")
            assert st == 200
            tree = json.loads(raw)
            assert set(tree["kinds"]) >= {
                "admission", "fsync", "publish", "ae_apply",
                "watch_delivery", "forward"}, tree["kinds"]
            nodes = {s["node"] for s in tree["tree"]}
            assert nodes == {"n0", "n1"}
            assert "skew_note" in tree
        # ?federate=0 answers locally only (the recursion stopper)
        st, raw, _ = req(fleet["n0"].port, "GET",
                         f"/debug/trace/{tid}?federate=0")
        local = json.loads(raw)
        assert "tree" not in local
        assert all(s["node"] == "n0" for s in local["spans"])

        # the visibility ledger's debug tail: the primary holds the
        # commit entry (durable + publish + watch stamped); the
        # replica holds the frontier apply as a BOUND
        st, raw, _ = req(fleet["n1"].port, "GET",
                         f"/debug/visibility/{doc}")
        tail = json.loads(raw)
        assert tail["entries"], tail
        ent = tail["entries"][-1]
        assert ent["trace_ids"] == [tid]
        assert ent["durable_ms"] is not None
        assert ent["watch_ms"] is not None
        st, raw, _ = req(fleet["n0"].port, "GET",
                         f"/docs/{doc}/ops?since=0&limit=64")
        assert st == 200
        st, raw, _ = req(fleet["n0"].port, "GET",
                         f"/debug/visibility/{doc}")
        rtail = json.loads(raw)
        assert any(r["peer"] == "n1" and tid in r["trace_ids"]
                   and r["bound_s"] >= 0.0
                   for r in rtail["remote_applies"]), rtail
        assert "bounds" in rtail["skew_note"]
    finally:
        _stop_fleet(fleet)


def test_ops_window_carries_trace_frontier_header():
    """A windowed /ops response on a node that committed traced writes
    carries X-Trace-Frontier (send_ts;tids) — and a full-log /ops
    (no limit) does not grow new headers."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0",))
    try:
        st, raw, _ = req(fleet["n0"].port, "POST", "/docs/fd0/ops",
                         body=_chain(3, 4))
        assert st == 200
        tid = json.loads(raw)["trace_id"]
        st, _, hdr = req(fleet["n0"].port, "GET",
                         "/docs/fd0/ops?since=0&limit=32")
        assert st == 200
        parsed = fleettrace_mod.parse_frontier(
            hdr.get(TRACE_FRONTIER_HEADER))
        assert parsed is not None and tid in parsed[1]
    finally:
        _stop_fleet(fleet)


# -- satellite 3: prom round-trip + absence off the fleet --------------------


def test_prom_families_roundtrip_and_absent_off_fleet(tmp_path):
    """The new families survive the strict parser on a fleet node
    (histogram bucket invariants included) and are ABSENT from a
    non-fleet engine's scrape — same disabled-tier contract as the
    netchaos families."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n1")
        st, raw, _ = req(fleet["n0"].port, "POST",
                         f"/docs/{doc}/ops", body=_chain(5, 4))
        assert st == 200
        # a watch delivery so the visibility histogram has samples
        st, _, _ = req(fleet["n1"].port, "GET",
                       f"/docs/{doc}/watch?since=0&timeout=0.5")
        assert st == 200
        for name, fs in fleet.items():
            st, raw, _ = req(fs.port, "GET", "/metrics/prom")
            assert st == 200
            fams = prom_mod.parse_text(raw.decode())
            assert "crdt_fleettrace_spans_total" in fams
            assert "crdt_fleettrace_traces" in fams
            assert "crdt_canary_probes_total" in fams
        # the committing node's ledger rendered labeled histograms
        st, raw, _ = req(fleet["n1"].port, "GET", "/metrics/prom")
        fams = prom_mod.parse_text(raw.decode())
        vis = fams["crdt_visibility_lag_seconds"]
        assert vis["type"] == "histogram"
        stages = {lbl["stage"] for _, lbl, _ in vis["samples"]
                  if "stage" in lbl}
        assert {"publish", "watch"} <= stages
        spans = fams["crdt_fleettrace_spans_total"]
        kinds = {lbl["kind"] for _, lbl, _ in spans["samples"]}
        assert {"admission", "publish", "watch_delivery"} <= kinds
    finally:
        _stop_fleet(fleet)
    # non-fleet engine: none of the fleet families exist
    eng = ServingEngine(start=False)
    try:
        fams = prom_mod.parse_text(prom_mod.render_engine(eng))
        assert not [f for f in fams
                    if f.startswith(("crdt_fleettrace_",
                                     "crdt_visibility_",
                                     "crdt_canary_"))]
    finally:
        eng.close()


def test_canary_honest_under_injected_delay():
    """Netchaos leg: with a deterministic 250 ms delay on every
    inter-node link, the canary's peer-visibility lag must report at
    least that much — the canary measures the links real traffic
    rides, so an injected delay is REQUIRED to show up (a canary that
    hid it would be lying)."""
    chaos = netchaos_mod.NetChaos(20, "delay=250-250@1")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos,
                         breaker_threshold=50)
    try:
        prober = fleet["n0"].node.canary
        assert prober is not None     # default-on for fleet nodes
        done = threading.Event()
        rec = {}

        def run_probe():
            rec.update(prober.probe())
            done.set()

        t = threading.Thread(target=run_probe, daemon=True)
        t.start()
        # the probe confirms on n1 only after anti-entropy hands the
        # canary doc over — drive pulls (over the delayed links) until
        # the probe resolves
        deadline = time.monotonic() + 30
        while not done.is_set() and time.monotonic() < deadline:
            fleet["n1"].node.antientropy.sync_now()
            done.wait(0.1)
        t.join(10)
        assert rec.get("ok") is True, rec
        assert rec["stages_s"]["peer_first"] >= 0.25, rec
        assert rec["peers_s"]["n1"] >= 0.25
        # the injected delay actually fired on the probed links
        assert chaos.stats()["counters"]["delays"] > 0
    finally:
        _stop_fleet(fleet)


def test_canary_default_on_and_periodic(monkeypatch):
    """Canary default-ON acceptance: with a short interval the prober
    arms at node start, fires through the REAL admission path, and the
    crdt_canary_visibility_seconds histogram is non-empty after one
    interval; GRAFT_CANARY=0 disarms."""
    monkeypatch.setenv("GRAFT_CANARY_INTERVAL_S", "0.2")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("solo",))
    try:
        prober = fleet["solo"].node.canary
        assert prober is not None
        # probes increments at probe START — wait for a finished
        # record (it carries trace_id) so we don't race the first
        # probe's JAX compile
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            cst = prober.stats()
            if cst["last_probe"] and "trace_id" in cst["last_probe"]:
                break
            time.sleep(0.05)
        assert cst["probes"] >= 1
        assert cst["last_probe"]["ok"] is True, cst["last_probe"]
        assert cst["e2e"]["count"] >= 1
        st, raw, _ = req(fleet["solo"].port, "GET", "/metrics/prom")
        fams = prom_mod.parse_text(raw.decode())
        assert "crdt_canary_visibility_seconds" in fams
        assert fams["crdt_canary_visibility_seconds"]["samples"]
        # the canary rode the real admission path under its own tid
        tid = cst["last_probe"]["trace_id"]
        spans = fleet["solo"].node.fleettrace.spans(tid)
        assert any(s["kind"] == "admission" for s in spans)
    finally:
        _stop_fleet(fleet)
    monkeypatch.setenv("GRAFT_CANARY", "0")
    kv2 = MemoryKV()
    fleet2 = _spawn_fleet(kv2, ("off",))
    try:
        assert fleet2["off"].node.canary is None
    finally:
        _stop_fleet(fleet2)


@pytest.mark.slow
def test_bench_visibility_headline_full(tmp_path):
    """The committed-artifact run (BENCH_VISIBILITY_r01_cpu.json
    shape): 3-node oracle-checked loadgen leg with sub-second canary
    ticks — per-stage visibility lag p50/p99 present, canary overhead
    under 1% of acked throughput, zero violations.  Slow-marked; the
    tier-1 gates are the fast tests above."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "_bench_visibility_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_visibility_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_VISIBILITY_test.json"))
    assert out["gate"]["pass"], out["gate"]
    assert out["violations_total"] == 0
    for stage in ("publish", "replica"):
        lag = out["visibility_lag_s"][stage]
        assert lag["count"] > 0 and lag["p99"] is not None
    assert out["canary"]["probes"] >= 1
    assert out["canary"]["overhead_pct_of_acked"] < 1.0


# -- GRAFT_FLEETTRACE=0: the byte-identical wire revert ----------------------


def test_fleettrace_disabled_reverts_wire(monkeypatch):
    """With GRAFT_FLEETTRACE=0 every new wire surface disappears: no
    X-Span-Ctx on the relay, no X-Trace-Frontier on /ops windows, no
    spans recorded anywhere, fleet families absent from the scrape —
    the PR-19 baseline, byte for byte."""
    monkeypatch.setenv("GRAFT_FLEETTRACE", "0")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n1")
        st, raw, hdr = req(fleet["n0"].port, "POST",
                           f"/docs/{doc}/ops", body=_chain(6, 4))
        assert st == 200
        # trace id still propagates (the satellite-1 bugfix is NOT
        # gated — attribution is baseline behavior)
        tid = json.loads(raw)["trace_id"]
        assert hdr["X-Trace-Id"] == tid
        # ...but no span state accrued anywhere
        assert fleet["n0"].node.fleettrace.trace_count() == 0
        assert fleet["n1"].node.fleettrace.trace_count() == 0
        st, _, ohdr = req(fleet["n1"].port, "GET",
                          f"/docs/{doc}/ops?since=0&limit=32")
        assert st == 200
        assert TRACE_FRONTIER_HEADER not in ohdr
        st, raw, _ = req(fleet["n1"].port, "GET", "/metrics/prom")
        fams = prom_mod.parse_text(raw.decode())
        assert not [f for f in fams
                    if f.startswith(("crdt_fleettrace_",
                                     "crdt_visibility_"))]
        # the ledger stayed empty too (no commit stamping)
        assert fleet["n1"].node.ledger.stats()["commits"] == 0
    finally:
        _stop_fleet(fleet)
