"""Pin what `apply` guarantees under NON-causal batch orders (VERDICT r1
weak-5 / next-9).

Two deliberate regimes, split by DELTA_THRESHOLD:

- **Host path (small deltas): SEQUENCE semantics, reference-exact.**  Ops
  apply in batch order; an op whose anchor hasn't arrived yet fails the
  whole batch exactly like the oracle/reference (CRDTree.elm:224-232), no
  matter the permutation, and a failed batch never half-commits.
- **Kernel path (large deltas): SET semantics.**  Bulk anti-entropy must
  absorb any arrival order of a valid op set — that is the CRDT promise —
  so the batched join resolves anchors against the whole set and order
  inside the batch does not matter for adds.  (Deletes targeting an add
  placed LATER in the batch still fail: ops/merge.py d_target_later.)

The converged TREE is identical wherever both paths accept.
"""
import itertools

import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import engine

R = 5 * 2**32


def _nested_ops():
    """5 causally-chained ops: branch, child, sibling, delete, grandchild."""
    return (
        crdt.Add(R + 1, (0,), "branch"),
        crdt.Add(R + 2, (R + 1, 0), "child"),
        crdt.Add(R + 3, (R + 1,), "sibling"),
        crdt.Delete((R + 1, R + 2)),
        crdt.Add(R + 4, (R + 3, 0), "grandchild"),
    )


def test_host_path_every_permutation_matches_oracle():
    """All 120 permutations: the engine's small-batch apply raises exactly
    when the oracle raises, with the same error type, and never commits a
    half batch; accepted permutations converge identically."""
    ops = _nested_ops()
    outcomes = set()
    for perm in itertools.permutations(ops):
        e = engine.init(50)
        o = crdt.init(50)
        e_err = o_err = None
        try:
            e.apply(crdt.Batch(perm))
        except crdt.CRDTError as ex:
            e_err = type(ex)
        try:
            o = o.apply(crdt.Batch(perm))
        except crdt.CRDTError as ex:
            o_err = type(ex)
        assert e_err is o_err, perm
        if e_err is None:
            assert e.visible_values() == o.visible_values(), perm
            outcomes.add(tuple(e.visible_values()))
        else:
            # atomicity: nothing committed
            assert e.log_length == 0 and len(e) == 0, perm
    # every accepted order converged to the same document
    assert outcomes == {("branch", "sibling", "grandchild")}


def _chain(count, rid=6):
    ops, prev = [], 0
    for i in range(1, count + 1):
        ts = rid * 2**32 + i
        ops.append(crdt.Add(ts, (prev,), i))
        prev = ts
    return ops


def test_kernel_path_accepts_any_order_of_a_valid_set():
    """A >threshold batch delivered fully REVERSED (every anchor arrives
    after its dependant) still converges: the batched join is a set
    semilattice, not a fold."""
    n = engine.DELTA_THRESHOLD + 10
    ops = _chain(n)
    e = engine.init(1)
    e.apply(crdt.Batch(tuple(reversed(ops))))
    assert e.visible_values() == list(range(1, n + 1))
    assert e.log_length == n


def test_host_path_rejects_non_causal_order_like_the_reference():
    """The SAME reversed chain, small enough for the host path, fails like
    the oracle does (anchor not yet present ⇒ NotFound, batch atomic)."""
    ops = _chain(10)
    e = engine.init(1)
    with pytest.raises(crdt.OperationFailedError):
        e.apply(crdt.Batch(tuple(reversed(ops))))
    assert e.log_length == 0 and len(e) == 0
    o = crdt.init(1)
    with pytest.raises(crdt.OperationFailedError):
        o.apply(crdt.Batch(tuple(reversed(ops))))


def test_delete_before_its_add_fails_on_both_paths():
    """d_target_later: a delete positioned before its target's add fails
    the batch on the kernel path too — deletes are order-sensitive even
    under set semantics (first-arrival tombstoning needs the node)."""
    for count in (10, engine.DELTA_THRESHOLD + 10):
        ops = _chain(count)
        first_ts = 6 * 2**32 + 1
        batch = [crdt.Delete((first_ts,))] + ops
        e = engine.init(1)
        with pytest.raises(crdt.OperationFailedError):
            e.apply(crdt.Batch(tuple(batch)))
        assert e.log_length == 0, count


def test_apply_packed_keeps_the_set_semantics_contract(monkeypatch):
    """The column ingest entry (engine.apply_packed, the POST /ops fast
    path) is the same kernel SET regime: a fully reversed valid chain
    converges, and a delete placed before its target's add still fails
    the batch (d_target_later), exactly like apply()."""
    from crdt_graph_tpu.codec import packed

    n = engine.DELTA_THRESHOLD + 10
    ops = _chain(n)
    monkeypatch.setattr(engine, "DELTA_THRESHOLD", 0)
    e = engine.init(1)
    e.apply_packed(packed.pack(list(reversed(ops))))
    assert e.visible_values() == list(range(1, n + 1))
    assert e.log_length == n

    bad = [crdt.Delete((R + 1,))] + ops      # delete precedes its add
    e2 = engine.init(1)
    with pytest.raises(crdt.CRDTError):
        e2.apply_packed(packed.pack(bad))
    assert e2.log_length == 0 and e2.visible_values() == []
