"""Ops-axis sharded merge (parallel/opsaxis.py, ISSUE 13): bit-identity
vs the single-device kernel across the sweep shapes, the mesh-size edge
cases (1-device no-op, non-divisible padded tail, halo-straddling
fallback), and the serving path with the GRAFT_OPSAXIS route on/off
(fingerprints + byte-identical sync windows + unchanged
last_applied_mask attribution)."""
import os

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from jax import lax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from crdt_graph_tpu.bench import workloads  # noqa: E402
from crdt_graph_tpu.codec import packed  # noqa: E402
from crdt_graph_tpu.ops import merge, tour_scan  # noqa: E402
from crdt_graph_tpu.parallel import opsaxis  # noqa: E402
from crdt_graph_tpu.utils import jaxcompat  # noqa: E402

# the bit-identity suite pins the packed layout like test_shard_map
os.environ["GRAFT_PACK_GATHER"] = "1"

FIELDS = ("ts", "parent", "depth", "value_ref", "paths", "exists",
          "tombstone", "dead", "visible", "doc_index", "order",
          "visible_order", "num_nodes", "num_visible", "status")

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")


def assert_identical(arrs, hints="exhaustive", k=8):
    """Pad once, run the stock kernel and the sharded path on the SAME
    padded arrays, compare every table field bitwise."""
    n = arrs["kind"].shape[0]
    n_pad = -(-n // k) * k
    padded = packed.pad_arrays(arrs, n_pad) if n_pad != n else arrs
    want = merge.materialize(padded, hints=hints)
    got = opsaxis.materialize(arrs, k=k, hints=hints)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(want, f)), np.asarray(getattr(got, f)),
            f)
    return got


# -- the 8 sweep shapes (CONFIGS 1-8, reduced sizes: the generators'
#    structure is size-independent; the 1M-scale budget gate lives in
#    test_chain_audit.py) ---------------------------------------------------

def test_sweep_configs_1_to_4_bit_identical():
    for name, ops in (
            ("editor", workloads.editor_replay(1000)),
            ("tworep", workloads.two_replica_interleaved(2000)),
            ("nested", workloads.nested_tree(6000)),
            ("tombstone", workloads.tombstone_heavy(4000))):
        arrs = packed.pack(ops).arrays()
        hints = "exhaustive" if not np.any(
            arrs["kind"] == packed.KIND_DELETE) else "auto"
        assert_identical(arrs, hints=hints)


def test_sweep_configs_5_to_8_bit_identical():
    for name, arrs in (
            ("chain", workloads.chain_workload(16, 8192)),
            ("descending", workloads.descending_chains(128, 8192)),
            ("comb", workloads.comb_pairs(8192)),
            ("deep", workloads.deep_paths(16, 8192, max_depth=16))):
        assert_identical(arrs)


def test_mixed_deletes_bit_identical():
    arrs = workloads.chain_with_deletes(8192, 8)
    assert (arrs["kind"] == packed.KIND_DELETE).sum() > 500
    assert_identical(arrs, hints="auto")


def test_chain_closed_form_through_opsaxis():
    """Not just self-consistency: the sharded result matches the
    closed-form expected visible sequence."""
    arrs = workloads.chain_workload(16, 8192)
    got = assert_identical(arrs)
    want_seq = workloads.chain_expected_ts(16, 8192)
    seq = np.asarray(got.ts)[np.asarray(got.visible_order)][
        :int(got.num_visible)]
    np.testing.assert_array_equal(seq, want_seq)


# -- mesh-size edge cases --------------------------------------------------

def test_one_device_mesh_is_noop_identical():
    """k=1: the sharded path degenerates to the stock kernel (windows
    cover everything, carries are identities, all-gathers are the
    identity) — pinned bit-identical."""
    arrs = workloads.chain_workload(8, 4096)
    assert_identical(arrs, k=1)


def test_non_divisible_ops_pad_tail_shard():
    """An op count the mesh width does not divide pads to the next
    multiple (the tail shard carries the padding) — identical to the
    stock kernel on the same padded arrays."""
    arrs = workloads.chain_workload(3, 3 * 667)     # 2001 rows
    assert arrs["kind"].shape[0] % 8 != 0
    assert_identical(arrs)


def test_shard_edge_straddling_span_takes_halo_fallback():
    """deep_paths parents all resolve to one skeleton slot, so every
    shard but the first sees parent rows far outside its halo window —
    the replicated window check fails and the plane sweep falls back
    to the single-device gather, bit-identically."""
    arrs = workloads.deep_paths(16, 4096, max_depth=16)
    # the straddle really exists: parent slots concentrate on the
    # skeleton while the windowed check only accepts near-diagonal
    # rows (or ROOT/NULL) — shard 7's window cannot contain slot ~15
    w = -(-(4096 + 2) // 8)
    assert 15 < 7 * w - opsaxis.HALO
    assert_identical(arrs)


def test_windowed_plane_rows_unit_fallback():
    """OpsAxisPart.plane_rows directly: a near-diagonal index takes
    the windowed leg, a straddling index the fallback — both
    bit-identical to ``plane[idx]``."""
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:8]), (opsaxis.AXIS,))
    r = 4096
    plane = jnp.arange(r * 3, dtype=jnp.int64).reshape(r, 3)
    near = jnp.clip(jnp.arange(r, dtype=jnp.int32) - 1, 0, r - 1)
    rng = np.random.default_rng(7)
    far = jnp.asarray(rng.integers(0, r, r).astype(np.int32))

    def body(_):
        part = opsaxis.OpsAxisPart(8)
        return (part.plane_rows(plane, near),
                part.plane_rows(plane, far))

    fn = jaxcompat.shard_map(
        body, mesh=mesh, in_specs=(P(opsaxis.AXIS),),
        out_specs=(P(), P()), check_vma=False)
    g_near, g_far = jax.jit(fn)(jnp.zeros(8, jnp.int32))
    np.testing.assert_array_equal(np.asarray(g_near),
                                  np.asarray(plane[near]))
    np.testing.assert_array_equal(np.asarray(g_far),
                                  np.asarray(plane[far]))


def test_sharded_prefix_sums_bit_identical():
    """The ring-carry scan core, across chunk-alignment edge cases."""
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:8]), (opsaxis.AXIS,))
    rng = np.random.default_rng(0)
    for m in (16, 100, 1000, 4097):
        t = 2 * m
        b = rng.integers(0, 2, t).astype(np.int32)
        w = rng.integers(0, 2, (2, m)).astype(np.int32)

        def body(_):
            return tour_scan.sharded_prefix_sums(
                jnp.asarray(b), jnp.asarray(w), axis=opsaxis.AXIS, k=8)

        fn = jaxcompat.shard_map(
            body, mesh=mesh, in_specs=(P(opsaxis.AXIS),),
            out_specs=(P(), P()), check_vma=False)
        ob, ow = jax.jit(fn)(jnp.zeros(8, jnp.int32))
        np.testing.assert_array_equal(np.asarray(ob), np.cumsum(b))
        np.testing.assert_array_equal(np.asarray(ow),
                                      np.cumsum(w, axis=1))


# -- crowding pre-pass hints (ISSUE 13 satellite) --------------------------

def test_crowding_hinted_leg_bit_identical_to_counted(monkeypatch):
    """The host-derived crowd columns must reproduce the device
    counting trio exactly — both legs pinned on a crowded shape (16
    chain heads under the root) and a contested interleave.  GRAFT_S_CAP
    is forced below M so the compacted sibling branch — the ONLY place
    the crowd columns are live (merge.crowding_hinted gate) — actually
    compiles at these test sizes (at the default 64k cap both legs
    would trace identically and the comparison would be vacuous)."""
    monkeypatch.setenv("GRAFT_S_CAP", "512")
    for arrs in (workloads.chain_workload(16, 8192),
                 packed.pack(
                     workloads.two_replica_interleaved(2000)).arrays()):
        assert "crowd_slot" in arrs
        no_del = not np.any(arrs["kind"] == packed.KIND_DELETE)
        try:
            jax.clear_caches()
            assert merge.crowding_hinted(arrs, "exhaustive", no_del)
            want = merge.materialize(arrs, hints="exhaustive")
            monkeypatch.setenv("GRAFT_CROWD_HINTS", "0")
            jax.clear_caches()
            base = merge.materialize(arrs, hints="exhaustive")
        finally:
            monkeypatch.delenv("GRAFT_CROWD_HINTS", raising=False)
            jax.clear_caches()
        for f in FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(want, f)),
                np.asarray(getattr(base, f)), f)


def test_crowding_hints_not_emitted_for_deletes_or_non_causal():
    """Verification, not trust: deletes and non-causal anchors must
    suppress the columns (the counting leg keeps running there)."""
    mixed = workloads.chain_with_deletes(2048, 8)
    assert "crowd_slot" not in mixed
    desc = workloads.descending_chains(64, 2048)
    # descending chains anchor at LARGER timestamps — not causal
    assert "crowd_slot" not in desc


# -- serving path (GRAFT_OPSAXIS on/off) -----------------------------------

def _serve_leg(tmp_path, tag, opsaxis_on, monkeypatch):
    from crdt_graph_tpu.codec import json_codec
    from crdt_graph_tpu.core.operation import Add, Batch
    from crdt_graph_tpu.obs import flight as flight_mod
    from crdt_graph_tpu.serve import ServingEngine
    monkeypatch.setenv("GRAFT_OPSAXIS", "1" if opsaxis_on else "0")
    monkeypatch.setenv("GRAFT_OPSAXIS_MIN_OPS", "1")
    before = opsaxis.stats()["merges"]
    eng = ServingEngine(durable_dir=str(tmp_path / tag),
                        wal_sync="batch", oplog_hot_ops=512,
                        flight=flight_mod.FlightRecorder())
    off = 2 ** 32
    prev = 0
    masks = []
    for start in (1, 1501):
        ops = []
        for c in range(start, start + 1500):
            ops.append(Add(off + c, (prev,), f"v{c}"))
            prev = off + c
        ok, _ = eng.submit("doc", json_codec.dumps(Batch(tuple(ops))))
        assert ok
        masks.append(eng.get("doc").tree.last_applied_mask.copy())
    assert eng.flush(60)
    doc = eng.get("doc")
    sv = doc.snapshot_view()
    windows = {s: sv.ops_since_bytes(s)
               for s in (0, off + 1, off + 1400, off + 2900)}
    routed = opsaxis.stats()["merges"] - before
    out = {"fp": sv.fingerprint(), "sfp": sv.state_fingerprint(),
           "seq": sv.seq, "log_length": sv.log_length,
           "masks": masks, "windows": windows, "routed": routed}
    eng.close()
    return out


def test_serving_fingerprints_and_windows_flag_on_off(tmp_path,
                                                      monkeypatch):
    """The acceptance contract: the same write sequence through the
    routed and unrouted engines publishes bit-identical fingerprints,
    byte-identical sync windows, and the same per-op applied-mask
    attribution — and the on-leg really routed through the sharded
    kernel."""
    on = _serve_leg(tmp_path, "on", True, monkeypatch)
    off = _serve_leg(tmp_path, "off", False, monkeypatch)
    assert on["fp"] == off["fp"]
    assert on["sfp"] == off["sfp"]
    assert on["seq"] == off["seq"]
    assert on["log_length"] == off["log_length"]
    for (a, b) in zip(on["masks"], off["masks"]):
        np.testing.assert_array_equal(a, b)
    assert on["windows"] == off["windows"]
    assert on["routed"] >= 1
    assert off["routed"] == 0


def test_route_gates(monkeypatch):
    monkeypatch.setenv("GRAFT_OPSAXIS", "0")
    assert not opsaxis.enabled_for(1 << 20)
    monkeypatch.setenv("GRAFT_OPSAXIS", "1")
    monkeypatch.setenv("GRAFT_OPSAXIS_MIN_OPS", "1024")
    assert not opsaxis.enabled_for(512)          # below threshold
    assert not opsaxis.enabled_for(1025)         # not divisible
    assert opsaxis.enabled_for(2048)
    k = opsaxis.mesh_devices()
    assert k >= 2 and 2048 % k == 0


def test_prom_families_strict_parse(monkeypatch):
    """crdt_opsaxis_* families ride the unified scrape and survive the
    strict parser."""
    from crdt_graph_tpu.obs import flight as flight_mod
    from crdt_graph_tpu.obs import prom as prom_mod
    from crdt_graph_tpu.serve import ServingEngine
    eng = ServingEngine(flight=flight_mod.FlightRecorder())
    try:
        fams = prom_mod.parse_text(eng.render_prom())
        for fam in ("crdt_opsaxis_enabled", "crdt_opsaxis_devices",
                    "crdt_opsaxis_min_ops", "crdt_opsaxis_halo_rows",
                    "crdt_opsaxis_merges_total",
                    "crdt_opsaxis_routed_ops_total"):
            assert fam in fams, fam
        sm = eng.scheduler_metrics()
        assert "opsaxis" in sm and "devices" in sm["opsaxis"]
    finally:
        eng.close()


@pytest.mark.slow
def test_bench_opsaxis_headline_reduced(tmp_path):
    """The committed-artifact run (BENCH_OPSAXIS_r01_cpu.json shape,
    reduced): fingerprint-equal legs, audit gates green, and the
    broken-path tripwire (a hang / wholesale fallback / widened shard
    reads as red; CPU-mesh slowness per se does not — SHARD_TAIL §7)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_opsaxis_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_opsaxis_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(n_ops=65_536, repeats=1,
                  out_path=str(tmp_path / "BENCH_OPSAXIS_test.json"))
    assert out["bit_identical"]
    assert out["opsaxis_audit"]["ok"]
    assert out["opsaxis_audit"]["leg"] == "hinted"
    assert out["tripwire"]["ok"], out["p50_ms"]


# -- staged pallas ring-carry kernel ---------------------------------------

def test_pallas_ring_carry_interpret():
    """The make_async_remote_copy ring variant of the carry exchange,
    interpret-mode: validated where the installed pallas can interpret
    remote DMAs under shard_map; skipped (with the on-chip probe
    staged in scripts/tpu_next_grant.sh) where it cannot."""
    if not tour_scan.HAVE_PALLAS:
        pytest.skip("pallas unavailable")
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:8]), (opsaxis.AXIS,))
    vals = np.arange(8, dtype=np.int32) + 1

    def body(v):
        return tour_scan.ring_exclusive_pallas(v, 8, interpret=True)

    fn = jaxcompat.shard_map(
        body, mesh=mesh, in_specs=(P(opsaxis.AXIS),),
        out_specs=P(opsaxis.AXIS), check_vma=False)
    try:
        got = np.asarray(jax.jit(fn)(jnp.asarray(vals)))
    except Exception as e:  # noqa: BLE001 — interpret-mode remote DMA
        pytest.skip(f"installed pallas cannot interpret remote DMA "
                    f"under shard_map: {type(e).__name__}")
    want = np.concatenate([[0], np.cumsum(vals)[:-1]])
    np.testing.assert_array_equal(got, want)
