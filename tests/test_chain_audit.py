"""The chain-length budget, CI-pinned (ISSUE 2 tentpole c; ISSUE 3
lowered it 16 → ≤10 with a width-weighted cost model).

The on-chip cost model (docs/TPU_PROFILE.md §3-4, §7): every M-wide
memory op costs ~6 ms at 1M on v5e (T = 2M-wide passes bill double
under the round-7 width weighting), so <100 ms needs the production
trace's chain ≤ ~10 such ops with modeled ms ≤ 70.
utils/chainaudit.py counts and prices them at TRACE time; this suite
turns the budget into a regression gate — any future kernel change
that re-adds an M-wide pass to the config-5 production trace fails
tier-1 instead of surfacing in the next grant window's profile.

Two traces are pinned: the DEVICE trace (use_pallas=True — the pallas
superops with their in-trace fallback conds, what runs on TPU) at
``FAST_PATH_BUDGET``, and the lax/CPU trace (what the CPU fallback
bench runs) at ``FAST_PATH_BUDGET_LAX``.
"""
import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.bench import workloads  # noqa: E402
from crdt_graph_tpu.utils import chainaudit  # noqa: E402

BUDGET = chainaudit.FAST_PATH_BUDGET            # device trace, ≤10
BUDGET_LAX = chainaudit.FAST_PATH_BUDGET_LAX    # lax/CPU trace
MODELED_MS_CAP = chainaudit.MODELED_MS_CAP      # width-weighted, ≤70


def _audit(arrs, hints="exhaustive", use_pallas=False):
    no_del = not bool(np.any(arrs["kind"] == 1))
    return chainaudit.audit_materialize(arrs, hints, no_del,
                                        use_pallas=use_pallas)


def test_config5_production_trace_within_budget(monkeypatch):
    """The headline DEVICE trace (1M ops, exhaustive, no deletes,
    pack-gather default ON, slot hints attached, pallas superops with
    their in-trace fallbacks) must fit the CI budget, in count AND in
    width-weighted modeled ms."""
    monkeypatch.delenv("GRAFT_PACK_GATHER", raising=False)
    arrs = workloads.chain_workload(64, 1_000_000)
    audit = _audit(arrs, use_pallas=True)
    assert audit.fast_path <= BUDGET, "\n" + audit.table()
    assert audit.modeled_ms_fast <= MODELED_MS_CAP, "\n" + audit.table()
    assert audit.summary()["ok"]


def test_config5_lax_trace_within_budget(monkeypatch):
    """The lax/CPU fallback trace (what the round-end CPU bench runs)
    keeps the sibling machinery and split scans the pallas kernels
    fuse — its own, slightly higher, budget is pinned so CPU-visible
    regressions fail here too."""
    monkeypatch.delenv("GRAFT_PACK_GATHER", raising=False)
    arrs = workloads.chain_workload(64, 1_000_000)
    audit = _audit(arrs, use_pallas=False)
    assert audit.fast_path <= BUDGET_LAX, "\n" + audit.table()


@pytest.mark.parametrize("cid", [6, 7, 8])
def test_adversarial_shapes_share_the_fast_path_budget(cid, monkeypatch):
    """The adversarial generators are still causal logs: their FAST
    path must match the budget too (their extra cost lives in the cond
    fallbacks and loop trips the auditor prices as ``static``)."""
    monkeypatch.delenv("GRAFT_PACK_GATHER", raising=False)
    _, gen = workloads.CONFIGS[cid]
    audit = _audit(gen(), use_pallas=True)
    assert audit.fast_path <= BUDGET, f"config {cid}\n" + audit.table()
    assert audit.static >= audit.fast_path


def test_pack_gather_flag_is_load_bearing(monkeypatch):
    """GRAFT_PACK_GATHER=0 (the A/B's B leg) must cost extra M-wide
    ops — pinning that the default-ON packing is what buys the budget,
    not a counting artifact."""
    arrs = workloads.chain_workload(8, 65_536)
    monkeypatch.setenv("GRAFT_PACK_GATHER", "1")
    on = _audit(arrs)
    monkeypatch.setenv("GRAFT_PACK_GATHER", "0")
    off = _audit(arrs)
    # (the ≤10 budget itself is a headline-SCALE property — at 64k the
    # S_CAP/R_CAP-compacted stages sit above the relative threshold —
    # so only the flag's relative effect is pinned here)
    assert off.fast_path > on.fast_path


def test_slot_hints_are_load_bearing():
    """Dropping the derived slot-hint columns must re-add the
    resolution gathers (the trace falls back to the gather-based
    exhaustive path) — pinning that the host-side derivation is what
    removed them."""
    arrs = dict(workloads.chain_workload(8, 65_536))
    fused = _audit(arrs)
    from crdt_graph_tpu.codec.packed import SLOT_HINT_COLS
    for k in SLOT_HINT_COLS:
        arrs.pop(k)
    unfused = _audit(arrs)
    assert unfused.fast_path > fused.fast_path


def test_fused_kill_switches_restore_the_r6_trace(monkeypatch):
    """GRAFT_FUSED_*=0 (the A/B's B leg, scripts/probe_fusedab.py) must
    re-add the round-6 passes: the winner scatter-min, the parent-row
    gather, the T-wide run-start scatter, the visible-order scatter —
    pinning that the round-7 cuts are the flags' doing, not a counting
    artifact.  (Flags are read at trace time; merge._materialize is
    re-traced via __wrapped__ on every audit, so no cache clearing is
    needed.)"""
    arrs = workloads.chain_workload(8, 65_536)
    for flag in ("GRAFT_FUSED_RESOLVE", "GRAFT_FUSED_TAIL",
                 "GRAFT_FUSED_SCAN"):
        monkeypatch.delenv(flag, raising=False)
    on = _audit(arrs)
    for flag in ("GRAFT_FUSED_RESOLVE", "GRAFT_FUSED_TAIL",
                 "GRAFT_FUSED_SCAN"):
        monkeypatch.setenv(flag, "0")
    off = _audit(arrs)
    assert off.fast_path > on.fast_path, (
        f"on={on.fast_path}\n{on.table()}\n\noff={off.fast_path}\n"
        f"{off.table()}")


def test_opsaxis_shard_width_budget_config5_1M():
    """ISSUE 13 CI gate: the ops-axis sharded trace at the 1M config-5
    headline bills NO fast-path memory op wider than ceil(M/k) + HALO
    per shard, and its collective traffic stays within the documented
    bound — a regression that silently widens a shard (or re-adds an
    M-wide pass inside the body) fails tier-1 the way the 9-op chain
    budget does."""
    from crdt_graph_tpu.parallel import opsaxis
    if len(__import__("jax").devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    arrs = workloads.chain_workload(64, 1_000_000)
    st = opsaxis.audit_opsaxis(arrs)
    m = 1_000_000 + 2
    assert st["devices"] == 8
    assert st["shard_budget"] == -(-m // 8) + opsaxis.HALO
    assert st["shard_width"] <= st["shard_budget"], st
    assert st["ok"]
    assert 0 < st["collective_bytes"] <= \
        opsaxis.COLLECTIVE_BYTES_CAP_1M, st
    # the production config-5 batch is host-verified all-valid causal,
    # so the crowding pre-pass leg must be the hinted one
    assert st["leg"] == "hinted"


def test_crowding_hints_are_load_bearing(monkeypatch):
    """Dropping the crowd columns (or killing GRAFT_CROWD_HINTS) must
    re-add the scatter-add + gather + cumsum trio to the lax trace —
    pinning that the host pre-pass is what removed it — and the audit
    summary must record which leg compiled."""
    monkeypatch.delenv("GRAFT_PACK_GATHER", raising=False)
    arrs = dict(workloads.chain_workload(8, 65_536))
    assert "crowd_slot" in arrs
    hinted = _audit(arrs)
    assert hinted.crowding_leg == "hinted"
    assert hinted.summary()["crowding_leg"] == "hinted"
    stripped = {k: v for k, v in arrs.items()
                if k not in ("crowd_slot", "crowd_cpos")}
    counted = _audit(stripped)
    assert counted.crowding_leg == "counted"
    # exactly the trio returns
    assert counted.fast_path == hinted.fast_path + 3, (
        f"hinted\n{hinted.table()}\n\ncounted\n{counted.table()}")
    monkeypatch.setenv("GRAFT_CROWD_HINTS", "0")
    killed = _audit(arrs)
    assert killed.fast_path == counted.fast_path
    assert killed.crowding_leg == "counted"


def test_counter_basics():
    """The counter itself: gathers/scatters/sorts/scans count at or
    above threshold; elementwise chains, reductions and slices do not;
    cond takes the cheapest branch on the fast path; width-weighted
    costs scale with width above the reference."""
    import jax.numpy as jnp
    from jax import lax

    n = 1024
    x = jax.ShapeDtypeStruct((n,), np.int32)
    i = jax.ShapeDtypeStruct((n,), np.int32)

    def memops(a, idx):
        g = a[jnp.clip(idx, 0, n - 1)]
        s = jnp.zeros_like(a).at[jnp.clip(idx, 0, n - 1)].add(g)
        return lax.cumsum(s) + lax.sort(a)

    audit = chainaudit.count_mwide(memops, x, i, threshold=n)
    assert audit.fast_path == 4, audit.table()
    # all four ops run at the reference width: modeled = 4 x 6 ms
    assert audit.modeled_ms_fast == pytest.approx(
        4 * chainaudit.MODELED_MS_PER_OP)

    def cheap(a, idx):
        for _ in range(5):
            a = (a * 3) ^ (a + 1)
        return jnp.sum(a) + a[:16].sum() + jnp.max(idx)

    assert chainaudit.count_mwide(cheap, x, i,
                                  threshold=n).fast_path == 0

    def with_cond(a, idx):
        return lax.cond(jnp.sum(a) > 0,
                        lambda _: a[jnp.clip(idx, 0, n - 1)] +
                        lax.cumsum(a),
                        lambda _: a * 2, None)

    audit = chainaudit.count_mwide(with_cond, x, i, threshold=n)
    assert audit.fast_path == 0, audit.table()   # cheap branch
    assert audit.static == 2                      # expensive branch

    def wide(a, idx):
        # a 2n-wide scan must bill 2x the per-op cost
        return lax.cumsum(jnp.concatenate([a, a]))

    audit = chainaudit.count_mwide(wide, x, i, threshold=n)
    assert audit.modeled_ms_fast == pytest.approx(
        2 * chainaudit.MODELED_MS_PER_OP)


def test_compact_risk_bucket():
    """Sub-threshold compacted ops land in the disclosed conservative
    fixed-cost bucket, not the budget count."""
    import jax.numpy as jnp

    n = 4096
    x = jax.ShapeDtypeStruct((n,), np.int32)

    def compacted(a):
        small = a[:n // 8]
        return jnp.sum(small[jnp.clip(small, 0, n // 8 - 1)]) + a[0]

    audit = chainaudit.count_mwide(compacted, x, threshold=n)
    assert audit.fast_path == 0
    assert audit.compact_fast == 1
    assert audit.compact_risk_ms == pytest.approx(
        chainaudit.MODELED_MS_PER_OP)
