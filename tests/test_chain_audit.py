"""The chain-length budget, CI-pinned (ISSUE 2 tentpole c).

The on-chip cost model (docs/TPU_PROFILE.md §3-4): every M-wide memory
op costs ~6 ms at 1M on v5e, so <100 ms needs the production trace's
chain ≤ ~16 such ops.  utils/chainaudit.py counts them at TRACE time;
this suite turns "≤16" from a projection into a regression gate — any
future kernel change that re-adds an M-wide pass to the config-5
production trace fails tier-1 instead of surfacing in the next grant
window's profile.
"""
import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.bench import workloads  # noqa: E402
from crdt_graph_tpu.utils import chainaudit  # noqa: E402

BUDGET = 16          # M-wide memory ops, production fast path
MODELED_MS_CAP = 120  # acceptance: count x ~6 ms/op lands under this


def _audit(arrs, hints="exhaustive"):
    no_del = not bool(np.any(arrs["kind"] == 1))
    return chainaudit.audit_materialize(arrs, hints, no_del)


def test_config5_production_trace_within_budget(monkeypatch):
    """The headline trace (1M ops, exhaustive, no deletes, pack-gather
    default ON, slot hints attached) must fit the CI budget."""
    monkeypatch.delenv("GRAFT_PACK_GATHER", raising=False)
    arrs = workloads.chain_workload(64, 1_000_000)
    audit = _audit(arrs)
    assert audit.fast_path <= BUDGET, "\n" + audit.table()
    assert audit.fast_path * chainaudit.MODELED_MS_PER_OP < MODELED_MS_CAP


@pytest.mark.parametrize("cid", [6, 7, 8])
def test_adversarial_shapes_share_the_fast_path_budget(cid, monkeypatch):
    """The adversarial generators are still causal logs: their FAST
    path must match the budget too (their extra cost lives in the cond
    fallbacks and loop trips the auditor prices as ``static``)."""
    monkeypatch.delenv("GRAFT_PACK_GATHER", raising=False)
    _, gen = workloads.CONFIGS[cid]
    audit = _audit(gen())
    assert audit.fast_path <= BUDGET, f"config {cid}\n" + audit.table()
    assert audit.static >= audit.fast_path


def test_pack_gather_flag_is_load_bearing(monkeypatch):
    """GRAFT_PACK_GATHER=0 (the A/B's B leg) must cost extra M-wide
    ops — pinning that the default-ON packing is what buys the budget,
    not a counting artifact."""
    arrs = workloads.chain_workload(8, 65_536)
    monkeypatch.setenv("GRAFT_PACK_GATHER", "1")
    on = _audit(arrs)
    monkeypatch.setenv("GRAFT_PACK_GATHER", "0")
    off = _audit(arrs)
    # (the ≤16 budget itself is a headline-SCALE property — at 64k the
    # S_CAP/R_CAP-compacted stages sit above the relative threshold —
    # so only the flag's relative effect is pinned here)
    assert off.fast_path > on.fast_path


def test_slot_hints_are_load_bearing():
    """Dropping the derived slot-hint columns must re-add the
    resolution gathers (the trace falls back to the gather-based
    exhaustive path) — pinning that the host-side derivation is what
    removed them."""
    arrs = dict(workloads.chain_workload(8, 65_536))
    fused = _audit(arrs)
    from crdt_graph_tpu.codec.packed import SLOT_HINT_COLS
    for k in SLOT_HINT_COLS:
        arrs.pop(k)
    unfused = _audit(arrs)
    assert unfused.fast_path > fused.fast_path


def test_counter_basics():
    """The counter itself: gathers/scatters/sorts/scans count at or
    above threshold; elementwise chains, reductions and slices do not;
    cond takes the cheapest branch on the fast path."""
    import jax.numpy as jnp
    from jax import lax

    n = 1024
    x = jax.ShapeDtypeStruct((n,), np.int32)
    i = jax.ShapeDtypeStruct((n,), np.int32)

    def memops(a, idx):
        g = a[jnp.clip(idx, 0, n - 1)]
        s = jnp.zeros_like(a).at[jnp.clip(idx, 0, n - 1)].add(g)
        return lax.cumsum(s) + lax.sort(a)

    audit = chainaudit.count_mwide(memops, x, i, threshold=n)
    assert audit.fast_path == 4, audit.table()

    def cheap(a, idx):
        for _ in range(5):
            a = (a * 3) ^ (a + 1)
        return jnp.sum(a) + a[:16].sum() + jnp.max(idx)

    assert chainaudit.count_mwide(cheap, x, i,
                                  threshold=n).fast_path == 0

    def with_cond(a, idx):
        return lax.cond(jnp.sum(a) > 0,
                        lambda _: a[jnp.clip(idx, 0, n - 1)] +
                        lax.cumsum(a),
                        lambda _: a * 2, None)

    audit = chainaudit.count_mwide(with_cond, x, i, threshold=n)
    assert audit.fast_path == 0, audit.table()   # cheap branch
    assert audit.static == 2                      # expensive branch
