"""Sync-backend identity guard (ISSUE 17; docs/DURABILITY.md §Sync
backends): the completion-driven fsync lanes are a PERFORMANCE fork,
never a semantics fork.  Same workload, same WAL stream bytes, same
recovery results, and the same in-process crash-matrix outcomes across
``GRAFT_WAL_SYNC_BACKEND=single|workers|uring`` — the uring leg
auto-skips (counted, not silent) where the kernel lacks io_uring.
"""
import os
import threading

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import wal as wal_mod
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.obs import flight as flight_mod
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.serve import SchedulerStopped, ServingEngine
from crdt_graph_tpu.utils import uring as uring_mod

OFF = 2**32

BACKENDS = ("single", "workers", "uring")


def _skip_unless_available(backend):
    if backend == "uring" and not uring_mod.available():
        pytest.skip("kernel lacks io_uring fsync support")


def ts(r, c):
    return r * OFF + c


def chain_ops(r, n, start=1):
    out = []
    prev = ts(r, start - 1) if start > 1 else 0
    for c in range(start, start + n):
        out.append(Add(ts(r, c), (prev,), f"v{r}.{c}"))
        prev = ts(r, c)
    return out


def _submit(eng, doc, ops):
    return eng.submit(doc, json_codec.dumps(Batch(tuple(ops))))


def _engine(ddir, backend, **kw):
    # wal_sync="batch" + the pipelined scheduler is the ONLY shape
    # that runs the group-commit fan-out (engine.py constructs the
    # WalSyncWorker exactly there) — "commit" fsyncs inline on the
    # scheduler and would silently ignore the backend under test
    kw.setdefault("oplog_hot_ops", 8)
    kw.setdefault("flight", flight_mod.FlightRecorder())
    kw.setdefault("pipeline", True)
    return ServingEngine(durable_dir=str(ddir), wal_sync="batch",
                         wal_sync_backend=backend, **kw)


def _wal_streams(ddir):
    """doc-relative WAL path -> file bytes, for every stream on disk."""
    out = {}
    for root, _dirs, files in os.walk(ddir):
        for f in files:
            if f.endswith(".log"):
                p = os.path.join(root, f)
                with open(p, "rb") as fh:
                    out[os.path.relpath(p, ddir)] = fh.read()
    return out


def _run_workload(ddir, backend):
    """Serial acked submits (one commit per round — deterministic
    record boundaries) across two docs; returns (wal streams,
    recovered values per doc, recovered state fingerprints)."""
    eng = _engine(ddir, backend)
    for i in range(0, 20, 5):
        ok, _ = _submit(eng, "docA", chain_ops(1, 5, start=i + 1))
        assert ok
        ok, _ = _submit(eng, "docB", chain_ops(2, 5, start=i + 1))
        assert ok
    assert eng.flush(30)
    eng.close()
    streams = _wal_streams(ddir)
    eng2 = _engine(ddir, "single")
    state = {}
    for d in ("docA", "docB"):
        doc = eng2.get(d, create=False)
        assert doc is not None and doc.recovered
        snap = doc.read_view()
        state[d] = (tuple(doc.snapshot()), snap.state_fingerprint())
    eng2.close()
    return streams, state


def test_wal_stream_bytes_and_recovery_identical_across_backends(
        tmp_path):
    """The byte-level guard: every backend lands the IDENTICAL WAL
    stream for the same acked workload (fan-out reorders fsyncs, never
    appends), and recovery reproduces identical values + replica-
    independent state fingerprints."""
    results = {}
    for backend in BACKENDS:
        if backend == "uring" and not uring_mod.available():
            continue
        results[backend] = _run_workload(tmp_path / backend, backend)
    assert "single" in results and "workers" in results
    base_streams, base_state = results["single"]
    assert base_streams, "workload produced no WAL streams"
    for backend, (streams, state) in results.items():
        assert streams == base_streams, \
            f"backend {backend}: WAL stream bytes diverged"
        assert state == base_state, \
            f"backend {backend}: recovered state diverged"
    if "uring" not in results:
        pytest.skip("identity held for single|workers; "
                    "kernel lacks io_uring — uring leg not run")


def _crash_once(ddir, backend, site, monkeypatch):
    """Arm one in-process crash site under ``backend``, recover, and
    return the recovered doc's (values, state fingerprint, epoch)."""
    monkeypatch.setenv("GRAFT_OPLOG_GC_SEGS", "1")
    monkeypatch.setenv("GRAFT_MATZ_TAIL_OPS", "8")
    eng = _engine(ddir, backend, submit_timeout_s=2.0)
    ops = chain_ops(1, 60)
    acked = []
    for i in range(0, 15, 5):
        ok, _ = _submit(eng, "doc", ops[i:i + 5])
        assert ok
        acked.extend(ops[i:i + 5])
    assert eng.flush(30)
    monkeypatch.setenv("GRAFT_CRASH_POINT", site)
    crashed = {}

    def doomed():
        try:
            crashed["ack"] = _submit(eng, "doc", ops[15:35])
        except SchedulerStopped:
            crashed["ack"] = None

    th = threading.Thread(target=doomed, daemon=True)
    th.start()
    eng.scheduler.join(30)
    assert not eng.scheduler.is_alive(), \
        f"{backend}/{site}: site never fired"
    th.join(10)
    monkeypatch.delenv("GRAFT_CRASH_POINT")
    eng2 = _engine(ddir, "single")
    doc2 = eng2.get("doc", create=False)
    assert doc2 is not None and doc2.epoch == 2
    vals = set(doc2.snapshot())
    missing = [op.value for op in acked if op.value not in vals]
    assert not missing, \
        f"{backend}/{site} lost acked writes: {missing}"
    acked_vals = {op.value for op in acked}
    out = (tuple(sorted(v for v in vals if v in acked_vals)),
           doc2.epoch)
    eng2.close()
    return out


@pytest.mark.parametrize("site", [
    s for s in wal_mod.CRASH_SITES if s != "mid-matz-write"])
def test_crash_matrix_identical_across_backends(tmp_path, site,
                                                monkeypatch):
    """Each crash site, run under every available backend: zero acked
    loss everywhere, and the recovered ACKED state is identical across
    backends (post-crash-point residue may legitimately differ — a
    faster lane can have fsynced the doomed round before the site
    fired — but nothing acked may diverge).  mid-matz-write is
    excluded here only because its firing depends on a refresh cadence
    the per-backend timing legitimately shifts; the per-backend matrix
    in test_wal.py still covers it."""
    outcomes = {}
    for backend in BACKENDS:
        if backend == "uring" and not uring_mod.available():
            continue
        outcomes[backend] = _crash_once(tmp_path / backend, backend,
                                        site, monkeypatch)
    assert "single" in outcomes and "workers" in outcomes
    base = outcomes["single"]
    for backend, got in outcomes.items():
        assert got == base, (f"site {site}: backend {backend} "
                             f"recovered different acked state")
    if "uring" not in outcomes:
        pytest.skip(f"site {site} held for single|workers; "
                    "kernel lacks io_uring — uring leg not run")


@pytest.mark.parametrize("backend", BACKENDS)
def test_prom_sync_backend_families_strict_parse(tmp_path, backend):
    """`crdt_wal_sync_backend` + `crdt_wal_sync_inflight` render under
    the strict parser with the ACTIVE backend labeled, and are ABSENT
    (same gating as crdt_wal_*) on a non-durable engine."""
    _skip_unless_available(backend)
    eng = _engine(tmp_path / "dur", backend)
    ok, _ = _submit(eng, "doc", chain_ops(1, 5))
    assert ok
    fams = prom_mod.parse_text(eng.render_prom())
    assert "crdt_wal_sync_backend" in fams
    assert "crdt_wal_sync_inflight" in fams
    samples = fams["crdt_wal_sync_backend"]["samples"]
    active = eng.sync_worker.stats()["backend"]
    assert any(lb.get("backend") == active
               for _n, lb, _v in samples), samples
    eng.close()
    eng2 = ServingEngine(oplog_hot_ops=8)
    fams2 = prom_mod.parse_text(eng2.render_prom())
    assert "crdt_wal_sync_backend" not in fams2
    assert "crdt_wal_sync_inflight" not in fams2
    eng2.close()
