"""Crash/recovery worker for tests/test_failure_recovery.py.

Phase "crash": join the service, make 10 local edits, push only the
first half, checkpoint the FULL local state (the WAL role of
checkpoint_packed), then die hard mid-session (os._exit) — the unpushed
tail exists only in the checkpoint.

Phase "recover": restore the checkpoint (own replica id rides in it),
pull the server's log (anti-entropy; everything already pushed comes
back as duplicates and absorbs), re-push the whole local log
(idempotent — the server absorbs the first half again), and verify both
sides converged on all 10 edits.

Usage: python tests/_crash_worker.py PHASE PORT CHECKPOINT_PATH
"""
import json
import os
import sys

PHASE, PORT, CKPT = sys.argv[1], int(sys.argv[2]), sys.argv[3]

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from http.client import HTTPConnection  # noqa: E402

from crdt_graph_tpu import engine  # noqa: E402
from crdt_graph_tpu.codec import json_codec  # noqa: E402


def req(method, path, body=None):
    conn = HTTPConnection("127.0.0.1", PORT, timeout=30)
    conn.request(method, path, body=body)
    resp = conn.getresponse()
    payload = json.loads(resp.read().decode())
    conn.close()
    return resp.status, payload


def crash() -> None:
    _, r = req("POST", "/docs/wal/replicas")
    t = engine.init(r["replica"])
    for i in range(10):
        t.add(f"edit-{i}")
    # push only the first half...
    half_ts = t.operations_since(0).ops[4].ts
    first_half = json_codec.dumps(
        engine.Batch(t.operations_since(0).ops[:5]))
    st, out = req("POST", "/docs/wal/ops", first_half)
    assert st == 200 and out["accepted"], out
    # ...checkpoint everything (the local WAL), then die mid-session
    t.checkpoint_packed(CKPT)
    print(f"crashing with {half_ts} pushed", flush=True)
    os._exit(3)


def recover() -> None:
    t = engine.TpuTree.restore_packed(CKPT)
    assert t.log_length == 10, t.log_length
    # anti-entropy pull: server ops re-apply; the overlap absorbs
    _, ops = req("GET", "/docs/wal/ops?since=0")
    t.apply(json_codec.decode(ops))
    assert t.log_length == 10, t.log_length   # nothing new, all dups
    # idempotent re-push of the whole local log: the server absorbs the
    # five it has and applies the five that died with the first worker
    st, out = req("POST", "/docs/wal/ops",
                  json_codec.dumps(t.operations_since(0)))
    assert st == 200 and out["accepted"], out
    _, snap = req("GET", "/docs/wal")
    assert snap["values"] == t.visible_values() == \
        [f"edit-{i}" for i in range(10)], snap
    print("recovered: OK", flush=True)


if __name__ == "__main__":
    {"crash": crash, "recover": recover}[PHASE]()
