"""Text-buffer model: index edits, replication, engine equivalence, and the
canonical two-user editing scenarios of the companion app the reference was
built for (README.md:3)."""
import random

import pytest

from crdt_graph_tpu.models import TextBuffer


@pytest.fixture(params=["oracle", "tpu"])
def eng(request):
    return request.param


def test_insert_and_read(eng):
    doc = TextBuffer(1, engine=eng)
    doc.insert(0, "hello")
    assert doc.text() == "hello"
    doc.insert(5, " world")
    assert doc.text() == "hello world"
    doc.insert(5, ",")
    assert doc.text() == "hello, world"


def test_delete_range(eng):
    doc = TextBuffer(1, engine=eng)
    doc.insert(0, "abcdef")
    doc.delete(1, 3)
    assert doc.text() == "aef"
    doc.delete(0)
    assert doc.text() == "ef"


def test_out_of_range_rejected(eng):
    doc = TextBuffer(1, engine=eng)
    doc.insert(0, "ab")
    with pytest.raises(IndexError):
        doc.insert(5, "x")
    with pytest.raises(IndexError):
        doc.delete(1, 5)
    assert doc.text() == "ab"


def test_two_replica_convergence(eng):
    a = TextBuffer(1, engine=eng)
    b = TextBuffer(2, engine=eng)
    a.insert(0, "shared base ")
    b.sync_from(a)
    assert b.text() == "shared base "
    # concurrent edits at both ends
    da = a.insert(0, "A:")
    db = b.insert(len(b), "B!")
    a.apply(db)
    b.apply(da)
    assert a.text() == b.text()
    assert "A:" in a.text() and "B!" in a.text()


def test_concurrent_same_point_inserts_converge(eng):
    a = TextBuffer(1, engine=eng)
    b = TextBuffer(2, engine=eng)
    a.insert(0, "xy")
    b.sync_from(a)
    da = a.insert(1, "AAA")
    db = b.insert(1, "BBB")
    a.apply(db)
    b.apply(da)
    assert a.text() == b.text()
    # chunks do not interleave character-by-character: each chunk is an
    # insertion chain anchored at its own previous character
    assert "AAA" in a.text() and "BBB" in a.text()


def test_duplicate_delta_absorbed(eng):
    a = TextBuffer(1, engine=eng)
    b = TextBuffer(2, engine=eng)
    a.insert(0, "dup")
    delta = a.operations_since(0)
    b.apply(delta)
    b.apply(delta)
    b.sync_from(a)
    assert b.text() == "dup"


def test_engines_equivalent_random_session():
    rng = random.Random(13)
    docs = {e: TextBuffer(1, engine=e) for e in ("oracle", "tpu")}
    for _ in range(60):
        n = len(docs["oracle"])
        roll = rng.random()
        if roll < 0.6 or n == 0:
            i = rng.randrange(n + 1)
            s = "".join(rng.choice("abcdef")
                        for _ in range(rng.randrange(1, 4)))
            for d in docs.values():
                d.insert(i, s)
        else:
            i = rng.randrange(n)
            c = rng.randrange(1, min(3, n - i) + 1)
            for d in docs.values():
                d.delete(i, c)
        assert docs["oracle"].text() == docs["tpu"].text()


def test_low_id_editor_insert_between_high_ts_chars():
    """Regression (r2 review): the path cache must not assume index
    placement.  A LOWER-id editor inserting between higher-ts characters
    gets skip-scanned right by the RGA rule; text() and index edits must
    track the REAL order (and agree with the oracle)."""
    from crdt_graph_tpu.models.text import TextBuffer

    low = TextBuffer(1, engine="tpu")
    low.insert(0, "a")
    high = TextBuffer(9, engine="oracle")
    high.sync_from(low)
    high.insert(1, "b")                  # higher replica id ⇒ higher ts
    low.sync_from(high)
    assert low.text() == "ab"
    low.insert(1, "c")                   # RGA sends 'c' past 'b'
    oracle = TextBuffer(5, engine="oracle")
    oracle.sync_from(low)
    assert low.text() == oracle.text() == "abc"
    # index edits keep operating on the displayed order
    low.delete(1)                        # deletes 'b', the char at index 1
    oracle.sync_from(low)
    assert low.text() == oracle.text() == "ac"


def test_children_and_views_in_deleted_branch():
    """Regression (r2 review): node views held into a branch that then gets
    deleted must report is_deleted, value None, no children, no siblings —
    the subtree left the document."""
    import crdt_graph_tpu as crdt
    from crdt_graph_tpu import engine

    e = engine.init(1)
    e.add_branch("p").add("child")
    parent_path = e.visible_paths()[0]
    child_path = e.visible_paths()[1]
    pn = e.get(parent_path)
    cn = e.get(child_path)
    e.delete(parent_path)
    assert pn.is_deleted and pn.children() == []
    assert cn.is_deleted and cn.value is None
    assert e.next(cn) is None and e.prev(cn) is None
    assert e.walk(lambda n, a: ("take", a + [n.path]), [], start=cn) == []
    assert e.get(child_path) is None and e.get_value(child_path) is None
    assert e.visible_values() == []
