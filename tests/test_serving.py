"""Serving engine: snapshot-isolated reads, coalescing scheduler,
admission control, chunked merges, cross-document batched launches.

The acceptance pins (ISSUE 1): readers see complete, monotonically
advancing snapshots with sub-10ms latency while a bulk merge commits;
chunked and single-shot merges are bit-identical; a full queue answers
429 with Retry-After; fused batches attribute per-request outcomes
exactly like sequential application.
"""
import json
import threading
import time

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")
# subset runs must not depend on another test module's global enable
jax.config.update("jax_enable_x64", True)

import crdt_graph_tpu as crdt                          # noqa: E402
from crdt_graph_tpu import engine as engine_mod        # noqa: E402
from crdt_graph_tpu.codec import json_codec            # noqa: E402
from crdt_graph_tpu.codec import packed as packed_mod  # noqa: E402
from crdt_graph_tpu.core import operation as op_mod    # noqa: E402
from crdt_graph_tpu.core.operation import Add, Batch   # noqa: E402
from crdt_graph_tpu.serve import (QueueFull, SchedulerError,  # noqa: E402
                                  SchedulerStopped, ServingEngine)
from crdt_graph_tpu.service.store import Document      # noqa: E402

OFFSET = 2**32


def chain_ops(rid, n, counter0=0, anchor=0):
    """n causally ordered adds from replica ``rid``, chained after
    ``anchor``."""
    ops, prev = [], anchor
    for i in range(n):
        ts = rid * OFFSET + counter0 + i + 1
        ops.append(Add(ts, (prev,), (counter0 + i) & 0xFF))
        prev = ts
    return ops


def submit_async(engine, doc_id, body):
    """Fire a submit from a worker thread; returns (thread, result box)."""
    box = {}

    def go():
        try:
            box["result"] = engine.submit(doc_id, body)
        except BaseException as e:          # noqa: BLE001 — test capture
            box["error"] = e

    th = threading.Thread(target=go, daemon=True)
    th.start()
    return th, box


def wait_queue_depth(engine, doc_id, depth, timeout=10.0):
    doc = engine.get(doc_id)
    deadline = time.monotonic() + timeout
    while len(doc.queue) < depth:
        assert time.monotonic() < deadline, \
            f"queue never reached depth {depth} (at {len(doc.queue)})"
        time.sleep(0.002)


# -- snapshot isolation ----------------------------------------------------


def _reader_soak(n_merge_ops, reader_seconds_after=0.0):
    """N reader threads assert every observed snapshot is complete and
    monotone while a bulk chain merge commits; returns reader latencies
    (ms) observed STRICTLY while the merge was in flight."""
    engine = ServingEngine()
    try:
        engine.submit("soak", json_codec.dumps(
            Batch(tuple(chain_ops(1, 8)))))
        doc = engine.get("soak")
        stop = threading.Event()
        merging = threading.Event()
        failures = []
        lat_ms = []
        lock = threading.Lock()

        def reader():
            last_seq = -1
            local = []
            while not stop.is_set():
                t0 = time.perf_counter()
                snap = doc.snapshot_view()
                n_vals = len(snap.values)
                seq = snap.seq
                dt = (time.perf_counter() - t0) * 1e3
                if merging.is_set():
                    local.append(dt)
                if seq < last_seq:
                    failures.append(f"seq regressed {last_seq}->{seq}")
                    break
                last_seq = seq
                # chain workload: every committed snapshot has exactly
                # as many visible values as applied ops — a torn or
                # half-merged view cannot satisfy this
                if n_vals != snap.log_length:
                    failures.append(
                        f"incomplete snapshot: {n_vals} values for "
                        f"{snap.log_length} ops (seq {seq})")
                    break
            with lock:
                lat_ms.extend(local)

        readers = [threading.Thread(target=reader, daemon=True)
                   for _ in range(4)]
        for r in readers:
            r.start()
        big = Batch(tuple(chain_ops(2, n_merge_ops)))
        merging.set()
        t0 = time.perf_counter()
        accepted, _ = engine.submit("soak", json_codec.dumps(big))
        merge_s = time.perf_counter() - t0
        merging.clear()
        assert accepted
        if reader_seconds_after:
            time.sleep(reader_seconds_after)
        stop.set()
        for r in readers:
            r.join(10)
        assert not failures, failures[:3]
        snap = doc.snapshot_view()
        assert snap.log_length == n_merge_ops + 8
        assert len(snap.values) == n_merge_ops + 8
        return lat_ms, merge_s
    finally:
        engine.close()


def test_concurrent_readers_during_merge_soak():
    """Readers never block on (or observe) an in-flight merge: while a
    multi-chunk catch-up merge commits, every read returns a complete,
    monotonically advancing snapshot, p99 under 10 ms.  (140k ops = 2
    chunks — the smallest shape that still exercises mid-chunk reads;
    the slow 1M variant below holds the acceptance scale, ISSUE 12
    tier-1 budget.)"""
    lat_ms, _ = _reader_soak(140_000)
    assert lat_ms, "no reads observed during the merge window"
    lat_ms.sort()
    p99 = lat_ms[(99 * len(lat_ms)) // 100 - 1] if len(lat_ms) >= 100 \
        else lat_ms[-1]
    assert p99 < 10.0, f"reader p99 {p99:.3f} ms during merge"


@pytest.mark.slow
def test_concurrent_readers_during_million_op_merge():
    """The acceptance-scale soak: a 1M-op merge commits while readers
    stay sub-10ms."""
    lat_ms, merge_s = _reader_soak(1_000_000)
    lat_ms.sort()
    p99 = lat_ms[(99 * len(lat_ms)) // 100 - 1]
    assert p99 < 10.0, f"reader p99 {p99:.3f} ms during 1M merge"


def test_snapshot_isolated_reads_are_frozen():
    """A held snapshot keeps answering consistently after later commits
    (readers resolve against the value they loaded, not the live doc)."""
    engine = ServingEngine()
    try:
        engine.submit("frozen", json_codec.dumps(
            Batch(tuple(chain_ops(1, 10)))))
        doc = engine.get("frozen")
        held = doc.snapshot_view()
        vals0 = held.visible_values()
        clock0 = held.clock_wire()
        since0 = held.ops_since_bytes(0)
        engine.submit("frozen", json_codec.dumps(
            Batch(tuple(chain_ops(1, 10, counter0=10,
                                  anchor=1 * OFFSET + 10)))))
        assert doc.snapshot_view().log_length == 20
        # the held snapshot is untouched by the commit
        assert held.visible_values() == vals0
        assert held.clock_wire() == clock0
        assert held.ops_since_bytes(0) == since0
        assert held.log_length == 10
    finally:
        engine.close()


# -- chunked merges --------------------------------------------------------


def _tree_fingerprint(t):
    """Everything the merge result determines, as comparable values."""
    p = t.packed_state()
    n = p.num_ops
    cols = {k: np.asarray(v)[:n] for k, v in p.arrays().items()}
    return (t.visible_values(), t.timestamp, dict(t._replicas),
            t.log_length, {k: v.tobytes() for k, v in cols.items()})


def test_chunked_merge_bit_identical_to_single_shot():
    """apply_packed_chunked == apply_packed, bit for bit: same column
    bytes, same clocks, same visible sequence — only the segment split
    differs."""
    ops = chain_ops(2, 9000) + chain_ops(3, 9000)
    p = packed_mod.pack(ops)
    one = engine_mod.init(0)
    one.apply_packed(p)
    chunked = engine_mod.init(0)
    chunked.apply_packed_chunked(p, 2048)
    f1, f2 = _tree_fingerprint(one), _tree_fingerprint(chunked)
    assert f1[0] == f2[0] and f1[1] == f2[1] and f1[2] == f2[2] \
        and f1[3] == f2[3]
    assert f1[4] == f2[4], "column bytes diverged"
    assert np.array_equal(one.last_applied_mask, chunked.last_applied_mask)


def test_chunked_merge_atomic_rollback():
    """A failing chunk leaves the tree exactly as before the call, and
    the error matches the single-shot error."""
    good = chain_ops(2, 3000)
    bad = good[:2500] + [Add(9 * OFFSET + 1, (123456789,), "orphan")]
    p = packed_mod.pack(bad)
    t = engine_mod.init(0)
    t.apply_packed(packed_mod.pack(chain_ops(1, 50)))
    before = _tree_fingerprint(t)
    with pytest.raises(crdt.OperationFailedError):
        t.apply_packed_chunked(p, 512)
    assert _tree_fingerprint(t) == before


def test_serving_engine_chunked_equivalence():
    """The same push through a tiny-chunk engine and a single-shot
    engine publishes identical snapshots."""
    body = json_codec.dumps(Batch(tuple(chain_ops(2, 6000))))
    small = ServingEngine(chunk_ops=1024)
    big = ServingEngine(chunk_ops=1 << 30)
    try:
        small.submit("d", body)
        big.submit("d", body)
        s1 = small.get("d").snapshot_view()
        s2 = big.get("d").snapshot_view()
        assert s1.values == s2.values
        assert s1.clock == s2.clock
        assert small.get("d").chunks_launched >= 6
        assert big.get("d").chunks_launched == 1
    finally:
        small.close()
        big.close()


# -- admission control -----------------------------------------------------


def test_queue_full_raises_and_shutdown_unblocks():
    engine = ServingEngine(start=False, max_queue_requests=2)
    body = json_codec.dumps(Batch(tuple(chain_ops(1, 3))))
    th1, b1 = submit_async(engine, "q", body)
    th2, b2 = submit_async(engine, "q", body)
    wait_queue_depth(engine, "q", 2)
    with pytest.raises(QueueFull) as ei:
        engine.submit("q", body)
    assert ei.value.retry_after_s >= 1
    assert engine.get("q").admission_rejected == 1
    # shutdown resolves the blocked submitters instead of hanging them
    engine.close()
    th1.join(10)
    th2.join(10)
    assert isinstance(b1.get("error"), SchedulerStopped)
    assert isinstance(b2.get("error"), SchedulerStopped)


def test_queue_full_http_429_with_retry_after():
    """The wire face of backpressure: 429, Retry-After header, JSON
    error body — without touching the document tree."""
    from http.client import HTTPConnection
    from crdt_graph_tpu.service import make_server

    engine = ServingEngine(start=False, max_queue_requests=0)
    srv = make_server(port=0, store=engine)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = HTTPConnection("127.0.0.1", srv.server_port, timeout=30)
        conn.request("POST", "/docs/busy/ops",
                     body='{"op":"add","path":[0],"ts":4294967297,'
                          '"val":"a"}')
        resp = conn.getresponse()
        body = json.loads(resp.read().decode())
        assert resp.status == 429
        assert int(resp.getheader("Retry-After")) >= 1
        assert "retry_after_s" in body
        conn.close()
    finally:
        srv.shutdown()
        srv.server_close()
        engine.close()


# -- coalescing ------------------------------------------------------------


def test_coalesced_pushes_match_sequential_document():
    """Five concurrent deltas (with cross-delta duplicates) fused into
    one commit produce the same document and counters as sequential
    application, and each request gets its own applied/dup attribution."""
    r2, r3 = chain_ops(2, 40), chain_ops(3, 40)
    deltas = [
        Batch(tuple(r2)),
        Batch(tuple(r3)),
        Batch(tuple(r2[:10])),                       # pure duplicate
        Batch(tuple(chain_ops(4, 25))),
        Batch(()),                                   # empty delta
    ]
    bodies = [json_codec.dumps(d) for d in deltas]

    engine = ServingEngine()
    try:
        engine.get("co")
        engine.scheduler.pause()
        # enqueue ORDER is load-bearing here (first-arrival-wins dedup
        # attributes the cross-delta duplicates to whichever request is
        # earlier in the queue), so serialize the submits — five racing
        # threads reach the queue in nondeterministic order and the
        # expected counts below assume list order
        pairs = []
        for i, b in enumerate(bodies):
            pairs.append(submit_async(engine, "co", b))
            wait_queue_depth(engine, "co", i + 1)
        engine.scheduler.resume()
        for th, _ in pairs:
            th.join(30)
        results = [b["result"] for _, b in pairs]
        assert all(acc for acc, _ in results)
        counts = [op_mod.count(applied) if applied is not None else 0
                  for _, applied in results]
        assert counts == [40, 40, 0, 25, 0]
        doc = engine.get("co")
        assert engine.counters.get("fused_batches") >= 1
        assert doc.ops_merged == 105 and doc.dup_absorbed == 10

        ref = Document("ref")
        for b in bodies:
            ref.apply_body(b)
        assert doc.snapshot() == ref.tree.visible_values()
        assert doc.clock() == {str(k): v
                               for k, v in ref.tree._replicas.items()}
    finally:
        engine.close()


def test_fused_rejection_attributes_only_guilty_request():
    """A causality-gap delta co-batched with valid deltas 409s alone:
    the valid ones commit (sequential fallback), only the orphan is
    rejected."""
    good1 = json_codec.dumps(Batch(tuple(chain_ops(2, 30))))
    orphan = json_codec.dumps(crdt.Add(7 * OFFSET + 1, (987654321,), "x"))
    good2 = json_codec.dumps(Batch(tuple(chain_ops(3, 30))))

    engine = ServingEngine()
    try:
        engine.get("fr")
        engine.scheduler.pause()
        pairs = [submit_async(engine, "fr", b)
                 for b in (good1, orphan, good2)]
        wait_queue_depth(engine, "fr", 3)
        engine.scheduler.resume()
        for th, _ in pairs:
            th.join(30)
        accs = [b["result"][0] for _, b in pairs]
        assert accs == [True, False, True]
        doc = engine.get("fr")
        assert doc.batches_rejected == 1
        assert doc.ops_merged == 60
        assert engine.counters.get("sequential_fallbacks") >= 1
        assert len(doc.snapshot()) == 60
    finally:
        engine.close()


# -- cross-document batched launch ----------------------------------------


def _push_staged(engine, doc_bodies):
    """Stage one delta per doc with the scheduler stopped, run one
    scheduling round synchronously, resolve all."""
    pairs = []
    for doc_id, body in doc_bodies:
        engine.get(doc_id)
        pairs.append(submit_async(engine, doc_id, body))
    for doc_id, _ in doc_bodies:
        wait_queue_depth(engine, doc_id, 1)
    assert engine.scheduler.step() == len(doc_bodies)
    for th, box in pairs:
        th.join(30)
        assert box["result"][0], "staged push rejected"


def test_cross_doc_batched_launch_matches_per_doc():
    """Three documents' kernel merges in one vmapped launch produce the
    same documents as per-doc launches, and later merges on top of the
    batched commit keep working."""
    n = 1500   # above the kernel crossover (4 * DELTA_THRESHOLD)
    bodies1 = [(f"x{i}", json_codec.dumps(
        Batch(tuple(chain_ops(i + 2, n))))) for i in range(3)]
    bodies2 = [(f"x{i}", json_codec.dumps(
        Batch(tuple(chain_ops(i + 2, n, counter0=n,
                              anchor=(i + 2) * OFFSET + n)))))
               for i in range(3)]

    batched = ServingEngine(start=False, cross_doc=True)
    plain = ServingEngine(start=False, cross_doc=False)
    try:
        _push_staged(batched, bodies1)
        assert batched.counters.get("cross_doc_batches") == 1
        assert batched.counters.get("cross_doc_docs") == 3
        _push_staged(plain, bodies1)
        assert plain.counters.get("cross_doc_batches") == 0
        for doc_id, _ in bodies1:
            assert batched.get(doc_id).snapshot() == \
                plain.get(doc_id).snapshot()
            assert batched.get(doc_id).clock() == \
                plain.get(doc_id).clock()
        # second wave lands on the batched-committed state (n0 > 0)
        _push_staged(batched, bodies2)
        _push_staged(plain, bodies2)
        for doc_id, _ in bodies2:
            assert batched.get(doc_id).snapshot() == \
                plain.get(doc_id).snapshot()
            assert len(batched.get(doc_id).snapshot()) == 2 * n
    finally:
        batched.close()
        plain.close()


# -- snapshot wire formats -------------------------------------------------


def test_snapshot_checkpoint_bytes_bootstrap():
    """A serving snapshot's /snapshot bytes restore under a new replica
    id, and its /ops bytes match the engine's own egress encoder."""
    import io

    engine = ServingEngine()
    try:
        engine.submit("boot", json_codec.dumps(
            Batch(tuple(chain_ops(1, 500)))))
        doc = engine.get("boot")
        blob = doc.snapshot_packed()
        t = engine_mod.TpuTree.restore_packed(io.BytesIO(blob), replica=9)
        assert t.visible_values() == doc.snapshot()
        assert t.replica_id == 9
        # /ops parity with the live-tree encoder
        ref = engine_mod.init(0)
        ref.apply(json_codec.loads(json_codec.dumps(
            Batch(tuple(chain_ops(1, 500))))))
        assert doc.dumps_since_bytes(0) == ref.dumps_since_bytes(0)
        mid = OFFSET + 250
        assert doc.dumps_since_bytes(mid) == ref.dumps_since_bytes(mid)
    finally:
        engine.close()


def test_scheduler_infrastructure_error_surfaces_as_scheduler_error():
    """A non-CRDT failure inside the scheduler resolves the waiting
    request with SchedulerError (the handler's 500) — never a hang,
    never a client-error class — and the scheduler survives for the
    next request."""
    engine = ServingEngine()
    try:
        engine.submit("err", json_codec.dumps(
            Batch(tuple(chain_ops(1, 5)))))
        doc = engine.get("err")
        real = doc.tree.apply_packed_chunked

        def boom(*a, **k):
            raise RuntimeError("injected launch failure")

        doc.tree.apply_packed_chunked = boom
        with pytest.raises(SchedulerError) as ei:
            engine.submit("err", json_codec.dumps(
                Batch(tuple(chain_ops(1, 5, counter0=5,
                                      anchor=OFFSET + 5)))))
        assert isinstance(ei.value.__cause__, RuntimeError)
        assert engine.counters.get("scheduler_errors") == 1
        # scheduler survived: the next submit merges normally
        doc.tree.apply_packed_chunked = real
        accepted, _ = engine.submit("err", json_codec.dumps(
            Batch(tuple(chain_ops(1, 5, counter0=5,
                                  anchor=OFFSET + 5)))))
        assert accepted and len(doc.snapshot()) == 10
    finally:
        engine.close()


def test_scheduler_metrics_surface():
    engine = ServingEngine()
    try:
        engine.submit("m", json_codec.dumps(
            Batch(tuple(chain_ops(1, 20)))))
        m = engine.get("m").metrics()
        for key in ("ops_merged", "queue_depth", "queue_leaves",
                    "admission_rejected", "snapshot_seq",
                    "snapshot_age_s", "chunks_launched",
                    "commit_latency_ms", "coalesce_width"):
            assert key in m, key
        assert m["snapshot_seq"] >= 1
        assert m["commit_latency_ms"]["count"] >= 1
        sm = engine.scheduler_metrics()
        assert "spans" in sm and "queue_depth_total" in sm
        assert any(k.startswith("serve.") for k in sm["spans"])
    finally:
        engine.close()
