"""Convergence property suite — the framework's race-detection strategy
(SURVEY §5): the merge is a semilattice join, so it must be invariant
under delivery order, duplication, and partitioning.  Each property is
checked over randomized causally-valid multi-replica logs."""
import random

import numpy as np
import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import engine
from crdt_graph_tpu.codec import packed
from crdt_graph_tpu.ops import merge, view

from test_merge_kernel import _random_session

SEEDS = [41, 42, 43]


def table_fingerprint(t):
    """Everything order-dependent about a converged table."""
    order = np.asarray(t.order)[:int(t.num_nodes)]
    return (
        [int(x) for x in np.asarray(t.ts)[order]],
        [bool(b) for b in np.asarray(t.tombstone)[order]],
        [bool(b) for b in np.asarray(t.dead)[order]],
    )


@pytest.mark.parametrize("seed", SEEDS)
def test_permutation_invariance(seed):
    _, ops = _random_session(seed, n_replicas=4, steps=90)
    p0 = packed.pack(ops)
    want = table_fingerprint(view.to_host(merge.materialize(p0.arrays())))
    rng = random.Random(seed * 7)
    for _ in range(3):
        perm = ops[:]
        rng.shuffle(perm)
        p = packed.pack(perm)
        got = table_fingerprint(view.to_host(merge.materialize(p.arrays())))
        assert got == want


@pytest.mark.parametrize("seed", SEEDS)
def test_duplication_invariance(seed):
    """log ++ log materialises identically to log (idempotent join)."""
    _, ops = _random_session(seed, n_replicas=3, steps=60)
    p1 = packed.pack(ops)
    p2 = packed.concat(packed.pack(ops), packed.pack(ops))
    f1 = table_fingerprint(view.to_host(merge.materialize(p1.arrays())))
    f2 = table_fingerprint(view.to_host(merge.materialize(p2.arrays())))
    assert f1 == f2


@pytest.mark.parametrize("seed", SEEDS)
def test_partition_tree_merge(seed):
    """Splitting the log into k parts and joining them pairwise in any
    tree shape equals materialising the whole (associativity)."""
    _, ops = _random_session(seed, n_replicas=3, steps=75)
    want = table_fingerprint(
        view.to_host(merge.materialize(packed.pack(ops).arrays())))
    rng = random.Random(seed)
    k = 4
    cuts = sorted(rng.sample(range(1, len(ops)), k - 1))
    parts = [packed.pack(ops[a:b])
             for a, b in zip([0] + cuts, cuts + [len(ops)])]
    while len(parts) > 1:
        i = rng.randrange(len(parts) - 1)
        parts[i:i + 2] = [packed.concat(parts[i], parts[i + 1])]
    got = table_fingerprint(view.to_host(merge.materialize(
        parts[0].arrays())))
    assert got == want


@pytest.mark.parametrize("seed", [51, 52])
def test_gossip_with_loss_and_redelivery_converges(seed):
    """Engine-level network simulation: replicas gossip deltas over a lossy
    channel that drops, duplicates, and reorders messages; pull-based
    anti-entropy repairs the gaps; all replicas converge."""
    rng = random.Random(seed)
    n = 4
    trees = [engine.init(r + 1) for r in range(n)]
    inflight = []   # (dest, delta)
    for step in range(150):
        r = rng.randrange(n)
        t = trees[r]
        roll = rng.random()
        try:
            if roll < 0.55:
                t.add(f"{r}:{step}")
                # broadcast the delta — unreliably
                for d in range(n):
                    if d != r and rng.random() < 0.7:
                        inflight.append((d, t.last_operation))
                        if rng.random() < 0.3:   # duplicate delivery
                            inflight.append((d, t.last_operation))
            elif roll < 0.75 and inflight:
                i = rng.randrange(len(inflight))   # arbitrary reordering
                dest, delta = inflight.pop(i)
                try:
                    trees[dest].apply(delta)
                except crdt.CRDTError:
                    pass                            # causality gap: dropped
            else:
                # anti-entropy pull from a random peer
                peer = rng.randrange(n)
                if peer != r:
                    since = t.last_replica_timestamp(peer + 1)
                    t.apply(trees[peer].operations_since(since))
        except crdt.CRDTError:
            pass
    # final repair: full mesh sync twice (second pass covers transitive ops)
    for _ in range(2):
        for i in range(n):
            for j in range(n):
                if i != j:
                    trees[i].apply(trees[j].operations_since(0))
    views = [t.visible_values() for t in trees]
    assert all(v == views[0] for v in views[1:])
    assert views[0]   # something actually happened


def test_checkpoint_packed_roundtrip(tmp_path):
    _, ops = _random_session(44, n_replicas=3, steps=50)
    t = engine.init(5)
    t.apply(crdt.Batch(tuple(ops)))
    path = str(tmp_path / "snap.npz")
    t.checkpoint_packed(path)
    back = engine.TpuTree.restore_packed(path)
    assert back.visible_values() == t.visible_values()
    assert back.timestamp == t.timestamp
    assert back.log_length == t.log_length
    # the restored replica keeps replicating
    back.add("after-restore")
    assert "after-restore" in back.visible_values()


def test_table_stats():
    from crdt_graph_tpu.utils import table_stats
    ops = [crdt.Add(1, (0,), "a"), crdt.Add(2, (1, 0), "b"),
           crdt.Add(3, (1,), "c"), crdt.Delete((3,))]
    p = packed.pack(ops)
    st = table_stats(view.to_host(merge.materialize(p.arrays())))
    assert st["nodes"] == 3 and st["visible"] == 2
    assert st["tombstones"] == 1 and st["max_depth"] == 2


def test_timed_harness():
    from crdt_graph_tpu.utils import timed
    p = packed.pack([crdt.Add(1, (0,), "a")])
    stats, result = timed(lambda: merge.materialize(p.arrays()).ts,
                          repeats=2)
    # stats is pure floats (JSON-safe); the device result rides separately
    assert stats["p50_ms"] > 0 and result is not None
    assert all(isinstance(v, float) for v in stats.values())


def test_trace_kill_switch_and_stop_timeout(monkeypatch, tmp_path):
    """GRAFT_NO_JAX_TRACE parses like every other GRAFT kill-switch
    (hostenv.flag_on: "0"/"off"/"" keep tracing ON) and a hung
    stop_trace is bounded by GRAFT_TRACE_STOP_TIMEOUT_S."""
    import threading
    import time

    import jax

    from crdt_graph_tpu.utils import profiling

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    monkeypatch.setenv("GRAFT_NO_JAX_TRACE", "1")
    with profiling.trace(str(tmp_path)):
        pass
    assert calls == []                     # no-op: profiler untouched
    for off in ("0", "off", ""):
        calls.clear()
        monkeypatch.setenv("GRAFT_NO_JAX_TRACE", off)
        with profiling.trace(str(tmp_path)):
            pass
        assert calls == [("start", str(tmp_path)), ("stop",)], off
    # a wedged stop_trace (the axon hang) must not wedge the caller,
    # and must latch tracing OFF for the rest of the process — the
    # profiler session is still active, so another start_trace would
    # raise mid-run
    monkeypatch.setenv("GRAFT_NO_JAX_TRACE", "0")
    monkeypatch.setenv("GRAFT_TRACE_STOP_TIMEOUT_S", "0.2")
    monkeypatch.setattr(profiling, "_trace_wedged", False)
    hang = threading.Event()
    monkeypatch.setattr(jax.profiler, "stop_trace", hang.wait)
    t0 = time.perf_counter()
    with profiling.trace(str(tmp_path)):
        pass
    assert time.perf_counter() - t0 < 5.0
    assert profiling._trace_wedged
    calls.clear()
    with profiling.trace(str(tmp_path)):   # no-op now, must not raise
        pass
    assert calls == []
    hang.set()     # release the abandoned daemon stop thread


def test_distributed_single_host_mesh():
    from crdt_graph_tpu.parallel import distributed
    distributed.initialize(num_processes=1)   # no-op
    m = distributed.global_device_mesh(n_ops=2)
    assert m.shape["ops"] == 2
    assert m.shape["docs"] * 2 == len(__import__("jax").devices())

def test_checkpoint_packed_exact_path_and_last_operation(tmp_path):
    # exact path (no .npz suffix appended) and last_operation preserved
    t = engine.init(4).add("x")
    path = str(tmp_path / "snapshot.bin")
    t.checkpoint_packed(path)
    import os
    assert os.path.exists(path)
    back = engine.TpuTree.restore_packed(path)
    assert back.last_operation == t.last_operation
    assert back.last_operation != crdt.Batch(())


def test_distributed_explicit_cluster_failure_raises():
    from crdt_graph_tpu.parallel import distributed
    with pytest.raises(Exception):
        distributed.initialize("256.0.0.1:1", num_processes=2, process_id=5)


def test_restore_repairs_stale_vouch(tmp_path):
    """ADVICE r3: hints_vouched rides in the same npz as the hint columns
    it vouches for, so restore_packed re-verifies on host.  A tampered
    checkpoint (mislinked parent_pos under a True vouch) must not reach
    the cond-free exhaustive mode with the corrupt columns — the restore
    audit catches it and REBUILDS the hints (keeping them corrupt would
    cost the sort+join fallback on every later merge), so the restored
    tree is both correct and back on the fast path."""
    _, ops = _random_session(45, n_replicas=3, steps=40)
    t = engine.init(6)
    t.apply(crdt.Batch(tuple(ops)))
    path = str(tmp_path / "snap.npz")
    t.checkpoint_packed(path)

    def tamper(mutate):
        z = dict(np.load(path))
        mutate(z)
        with open(path, "wb") as f:
            np.savez_compressed(f, **z)
        back = engine.TpuTree.restore_packed(path)
        assert back._packed.hints_vouched
        assert packed.verify_hints(back._packed)   # repaired, not trusted
        assert back.visible_values() == t.visible_values()

    def mislink(z):
        z["parent_pos"][z["parent_pos"] >= 0] = 0

    def rank_swap(z):
        # two rows with DISTINCT ranks (duplicate deliveries share a
        # rank, and swapping equal ranks would be a no-op tamper)
        adds = np.nonzero(z["ts_rank"] >= 0)[0]
        r = z["ts_rank"][adds]
        j = int(np.nonzero(r != r[0])[0][0])
        a, b = adds[0], adds[j]
        z["ts_rank"][a], z["ts_rank"][b] = z["ts_rank"][b], z["ts_rank"][a]

    tamper(mislink)
    t.checkpoint_packed(path)
    tamper(rank_swap)


def test_restore_reads_old_format_checkpoints(tmp_path):
    """r3-format checkpoints carried FULL-CAPACITY columns, an encoded
    last_operation blob, and no ts_rank file; restore must still read
    them (pad/span/rank branches all have a legacy side)."""
    import json as _json
    from crdt_graph_tpu.codec import json_codec

    t = engine.init(8)
    for i in range(5):
        t.add(f"w{i}")
    p = t._ensure_packed()
    meta = {
        "replica": 8, "timestamp": t.timestamp,
        "cursor": list(t.cursor),
        "replicas": {str(k): v for k, v in t._replicas.items()},
        "max_depth": 16, "num_ops": p.num_ops,
        "last_operation": json_codec.encode(t.last_operation),
        "hints_vouched": True,
    }
    path = str(tmp_path / "old.npz")
    with open(path, "wb") as f:
        np.savez_compressed(                      # full capacity, no rank
            f, kind=p.kind, ts=p.ts, parent_ts=p.parent_ts,
            anchor_ts=p.anchor_ts, depth=p.depth, paths=p.paths,
            value_ref=p.value_ref, pos=p.pos,
            parent_pos=p.parent_pos, anchor_pos=p.anchor_pos,
            target_pos=p.target_pos,
            values=np.frombuffer(_json.dumps(p.values).encode(), np.uint8),
            meta=np.frombuffer(_json.dumps(meta).encode(), np.uint8))
    back = engine.TpuTree.restore_packed(path)
    assert back.visible_values() == t.visible_values()
    assert back.last_operation == t.last_operation
    assert back.timestamp == t.timestamp
    back.add("after")
    assert "after" in back.visible_values()
