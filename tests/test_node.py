"""RGA node-kernel conformance suite.

Port of the reference's tests/NodeTest.elm (185 LoC): drives the node kernel
directly, pinning down the CRDT convergence rule (concurrent inserts after
the same anchor converge regardless of arrival order, higher timestamp
closer to the anchor) and the traversal combinators with tombstone skipping.
"""
import pytest

from crdt_graph_tpu.core import node as N
from crdt_graph_tpu.core.errors import AlreadyApplied, InvalidPath, NotFound


def values(root):
    return N.node_map(lambda n: n.get_value(), root)


# -- add order: the canonical convergence fixtures (NodeTest.elm:23-60) ---

def test_append_smaller_first():
    root = N.add_after(N.Node.root(), [0], 1, "a")
    root = N.add_after(root, [0], 2, "b")
    assert values(root) == ["b", "a"]


def test_append_bigger_first():
    root = N.add_after(N.Node.root(), [0], 2, "b")
    root = N.add_after(root, [0], 1, "a")
    assert values(root) == ["b", "a"]


def _insert_in_order(order):
    """Six inserts: 1 after sentinel; 2 after 1; 3 after 2; then
    {4,5,6} after 1 in the given arrival order (NodeTest.elm:150-167)."""
    root = N.add_after(N.Node.root(), [0], 1, 1)
    root = N.add_after(root, [1], 2, 2)
    root = N.add_after(root, [2], 3, 3)
    for ts in order:
        root = N.add_after(root, [1], ts, ts)
    return root


@pytest.mark.parametrize("order", [(6, 5, 4), (4, 6, 5), (4, 5, 6),
                                   (5, 4, 6), (5, 6, 4), (6, 4, 5)])
def test_insert_converges_any_order(order):
    assert values(_insert_in_order(order)) == [1, 6, 5, 4, 2, 3]


# -- fixtures for traversal (NodeTest.elm:170-185) ------------------------

@pytest.fixture
def flat_example():
    root = N.add_after(N.Node.root(), [0], 1, "a")
    root = N.add_after(root, [1], 2, "b")
    root = N.add_after(root, [2], 3, "x")
    root = N.add_after(root, [3], 4, "c")
    root = N.add_after(root, [4], 5, "d")
    return N.delete(root, [3])


@pytest.fixture
def nested_example():
    root = N.add_after(N.Node.root(), [0], 1, "a")
    root = N.add_after(root, [1, 0], 2, "b")
    root = N.add_after(root, [1, 2, 0], 3, "c")
    root = N.add_after(root, [1, 2, 3, 0], 4, "d")
    return root


def test_find(flat_example):
    found = N.find(lambda n: n.get_value() == "c", flat_example)
    assert found is not None and found.get_value() == "c"


def test_descendant(nested_example):
    node = N.descendant(nested_example, [1, 2, 3, 4])
    assert node is not None and node.get_value() == "d"


def test_path(nested_example):
    node = N.descendant(nested_example, [1, 2, 3, 4])
    assert node.path == (1, 2, 3, 4)


def test_timestamp(nested_example):
    node = N.descendant(nested_example, [1, 2, 3, 4])
    assert node.timestamp == 4


def test_map_skips_tombstones(flat_example):
    assert values(flat_example) == ["a", "b", "c", "d"]


def test_filter_map(flat_example):
    assert N.filter_map(lambda n: n.get_value(), flat_example) == \
        ["a", "b", "c", "d"]


def test_foldl(flat_example):
    out = N.foldl(lambda n, acc: acc + [n.get_value()], [], flat_example)
    assert out == ["a", "b", "c", "d"]


def test_foldr(flat_example):
    out = N.foldr(lambda n, acc: [n.get_value()] + acc, [], flat_example)
    assert out == ["a", "b", "c", "d"]


def test_head(flat_example):
    assert N.head(flat_example).get_value() == "a"


def test_last(flat_example):
    assert N.last(flat_example).get_value() == "d"


# -- kernel error semantics (Internal/Node.elm:63-65,112-122,138-163) -----

def test_duplicate_add_raises_already_applied(flat_example):
    with pytest.raises(AlreadyApplied):
        N.add_after(flat_example, [1], 1, "dup")


def test_missing_anchor_raises_not_found(flat_example):
    with pytest.raises(NotFound):
        N.add_after(flat_example, [99], 7, "zz")


def test_empty_path_raises_invalid_path(flat_example):
    with pytest.raises(InvalidPath):
        N.add_after(flat_example, [], 7, "zz")


def test_missing_intermediate_raises_invalid_path(flat_example):
    with pytest.raises(InvalidPath):
        N.add_after(flat_example, [42, 0], 7, "zz")


def test_delete_tombstone_raises_already_applied(flat_example):
    with pytest.raises(AlreadyApplied):
        N.delete(flat_example, [3])


def test_delete_missing_raises_not_found(flat_example):
    with pytest.raises(NotFound):
        N.delete(flat_example, [99])


def test_add_under_tombstone_raises_already_applied(flat_example):
    with pytest.raises(AlreadyApplied):
        N.add_after(flat_example, [3, 0], 7, "zz")


# -- tombstone-interleaved inserts (beyond the reference suite; see the
#    divergence note in crdt_graph_tpu/core/node.py) ----------------------

def test_insert_anchored_at_tombstone(flat_example):
    # anchor at the tombstone ts=3: lands right after it, before "c"(4)
    root = N.add_after(flat_example, [3], 6, "y")
    assert values(root) == ["a", "b", "y", "c", "d"]


def test_insert_before_tombstone(flat_example):
    # anchored at "b"(2) with ts larger than the tombstone(3): stops
    # immediately and lands between "b" and the tombstone.
    root = N.add_after(flat_example, [2], 6, "y")
    assert values(root) == ["a", "b", "y", "c", "d"]


def test_insert_skips_past_tombstone():
    # a tombstone with a larger timestamp is skipped like a live sibling:
    # chain 0→10(a)→30(b†); inserting 20 after 10 must pass the tombstone.
    root = N.add_after(N.Node.root(), [0], 10, "a")
    root = N.add_after(root, [10], 30, "b")
    root = N.delete(root, [30])
    root = N.add_after(root, [10], 20, "c")
    assert values(root) == ["a", "c"]
    # and the tombstone still holds its position: ordering key intact
    assert [n.timestamp for n in N.iter_chain(root)] == [10, 30, 20]


def test_delete_after_tombstone_interleave(flat_example):
    # regression for the reference findInsertion divergence: insert with a
    # tombstone between anchor and successor, then delete the successor —
    # the delete must still take effect.
    root = N.add_after(flat_example, [2], 35, "y")  # lands before tombstone 3
    root2 = N.delete(root, [4])  # delete "c"
    assert values(root2) == ["a", "b", "y", "d"]


def test_loop_early_exit_and_children():
    """`loop` folds visible children until "done"; `children` lists them
    (CRDTree/Node.elm:94-98, 136-160)."""
    from crdt_graph_tpu.core import node as node_mod
    root = node_mod.Node.root()
    root = node_mod.add_after(root, (0,), 1, "a")
    root = node_mod.add_after(root, (1,), 2, "b")
    root = node_mod.add_after(root, (2,), 3, "c")
    root = node_mod.delete(root, (2,))

    kids = node_mod.children(root)
    assert [n.value for n in kids] == ["a", "c"]

    seen = node_mod.loop(
        lambda n, acc: ("take", acc + [n.value]), [], root)
    assert seen == ["a", "c"]
    first = node_mod.loop(
        lambda n, acc: ("done", n.value), None, root)
    assert first == "a"
