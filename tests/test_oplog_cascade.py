"""The cascade op-log (crdt_graph_tpu/oplog.py rebuild, ISSUE 8):
tiered hot-tail → packed-npz cold segments → checkpoint base, with
reference-stable ``operationsSince`` windows and watermark-gated GC.

The contract under test: the tiers are PHYSICAL only.  Every read —
object iteration, ``operations_since`` suffixes, bounded anti-entropy
windows (bytes AND ``X-Since-*`` meta), fingerprints, checkpoints —
must be indistinguishable from the untiered log across every tier
seam, while resident memory stays O(hot window) and a concurrent
spill/compaction/GC can never disturb an in-flight window chain.
"""
import io
import json
import os
import threading
import time
from http.client import HTTPConnection

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import engine
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.codec import packed as packed_mod
from crdt_graph_tpu.core import operation as op_mod
from crdt_graph_tpu.core.errors import CheckpointError
from crdt_graph_tpu.core.operation import Add, Batch, Delete
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.oplog import OpLog
from crdt_graph_tpu.serve import snapshot as snapshot_mod


def ts(r, c):
    return r * 2**32 + c


def chain_ops(r, n, start=1):
    out = []
    prev = ts(r, start - 1) if start > 1 else 0
    for c in range(start, start + n):
        out.append(Add(ts(r, c), (prev,), f"v{r}.{c}"))
        prev = ts(r, c)
    return out


def mixed_ops(n_per=40):
    """Two interleaved replica chains + scattered deletes + an
    ALL-DELETE TAIL — the window-rule torture shape (trim-to-Add,
    all-delete extension, inclusive terminator, delete-tail rule)."""
    a, b = chain_ops(1, n_per), chain_ops(2, n_per)
    ops = [op for pair in zip(a, b) for op in pair]
    # a delete burst mid-log (longer than small window limits)
    ops[n_per:n_per] = [Delete((ts(1, c),)) for c in range(3, 9)]
    # all-delete tail
    ops.extend(Delete((ts(2, c),)) for c in range(n_per - 4, n_per + 1))
    return ops


def applied_log_tree(ops):
    """Apply ``ops`` one-by-one through a reference tree so the log is
    a genuine applied history (deletes validated)."""
    t = engine.init(0)
    for op in ops:
        t.apply(op)
    return t


def tiered_copy(log_ops, tmp_path, name, **kw):
    """An OpLog holding ``log_ops`` with tiering armed and fully
    spilled under the given hot budget (folding disabled by default so
    tests see a multi-segment cold tier; pass gc_min_segs to allow
    compaction)."""
    kw.setdefault("hot_ops", 16)
    kw.setdefault("gc_min_segs", 99)
    log = OpLog(log_ops)
    log.enable_tiering(str(tmp_path / name), **kw)
    log.maybe_spill()
    return log


# -- logical equivalence across tiers ---------------------------------------


def test_tiered_log_matches_untiered_object_contract(tmp_path):
    ops = mixed_ops(30)
    t = applied_log_tree(ops)
    applied = list(t._log)
    flat = OpLog(applied)
    log = tiered_copy(applied, tmp_path, "eq")
    assert log.spills >= 1 and log.telemetry()["segments"]["cold"] >= 1
    assert len(log) == len(flat)
    assert list(log) == applied
    assert log[5] == applied[5]
    assert log[-1] == applied[-1]
    assert log[3:17] == applied[3:17]
    for op in applied:
        if isinstance(op, Add):
            assert log.index_of_add(op.ts) == flat.index_of_add(op.ts)
    assert log.index_of_add(ts(9, 9)) is None
    # full-column reassembly equals the untiered export
    a = log.to_packed(max_depth=4)
    b = flat.to_packed(max_depth=4)
    assert a.num_ops == b.num_ops
    assert packed_mod.unpack(a) == packed_mod.unpack(b)
    assert packed_mod.verify_hints(a)


def test_window_bytes_and_meta_identical_at_every_seam(tmp_path):
    """`packed_since_window` equivalence: for EVERY Add terminator and
    a spread of limits, the tiered view's window must be byte- and
    meta-identical to the untiered implementation — including windows
    that end exactly on a tier seam, all-delete windows that extend
    across a seam, and the all-delete-tail rule."""
    ops = mixed_ops(30)
    t = applied_log_tree(ops)
    applied = list(t._log)
    p = packed_mod.pack(applied, max_depth=4)
    # hot_ops=16 → several cold segments; limits chosen to land
    # windows exactly on 16-aligned seams as well as off them
    log = tiered_copy(applied, tmp_path, "seams", hot_ops=16)
    tele = log.telemetry()
    assert tele["segments"]["cold"] >= 3
    view = log.view(max_depth=4)
    adds = [op.ts for op in applied if isinstance(op, Add)]
    # every 2nd Add terminator (plus 0) × limits spanning sub-seam,
    # seam-exact (16 = the spill segment size), and cross-seam sizes —
    # tier-1-sized without losing any seam class
    boundaries = [0] + adds[::2] + adds[-2:]
    for since in boundaries:
        for limit in (0, 1, 3, 5, 8, 16, 1000):
            want = engine.packed_since_window(p, since, limit)
            got = view.window(since, limit)
            assert got[0] == want[0], (since, limit)
            assert got[1] == want[1], (since, limit)
    # unknown terminator: found=0, not a silent full pull
    _, meta = view.window(ts(7, 1), 4)
    assert not meta["found"]
    # unbounded suffix bytes match too
    for since in boundaries:
        assert view.since_bytes(since) == \
            engine.packed_since_bytes(p, since), since


def test_operations_since_equivalent_on_tiered_tree(tmp_path):
    ops = mixed_ops(25)
    plain = applied_log_tree(ops)
    tiered = applied_log_tree(ops)
    tiered.enable_log_tiering(str(tmp_path / "t"), hot_ops=16,
                              gc_min_segs=2)
    tiered._log.maybe_spill()
    assert tiered._log.spills >= 1
    applied_adds = [op.ts for op in list(plain._log)
                    if isinstance(op, Add)]
    for boundary in [0] + applied_adds[::3] + applied_adds[-2:]:
        assert tiered.operations_since(boundary) == \
            plain.operations_since(boundary), boundary
    # and the tree still merges correctly after the spill (cold tiers
    # reassemble into the kernel's candidate set)
    more = chain_ops(3, 1200)
    tiered.apply_packed(packed_mod.pack(more, max_depth=4))
    plain.apply(op_mod.from_list(more))
    assert tiered.visible_values() == plain.visible_values()
    assert tiered._replicas == plain._replicas


def test_window_chain_stable_across_concurrent_spill_and_gc(tmp_path):
    """A spill/compaction/GC landing BETWEEN the pulls of an in-flight
    anti-entropy chain must not shift, re-serve, or lose a window: the
    chain keeps reading from its pinned reference-stable view, and GC
    defers deleting any file that view still needs."""
    ops = mixed_ops(30)
    t = applied_log_tree(ops)
    applied = list(t._log)
    p = packed_mod.pack(applied, max_depth=4)
    log = tiered_copy(applied, tmp_path, "race", hot_ops=16,
                      gc_min_segs=2)
    view = log.view(max_depth=4)    # the chain's pinned view

    # expected chain against the untiered packing, precomputed
    def pull_chain(windows_fn):
        since, out = 0, []
        for _ in range(80):
            body, meta = windows_fn(since)
            out.append((body, tuple(sorted(meta.items()))))
            if meta["next_since"] is not None:
                since = meta["next_since"]
            if not meta["more"]:
                return out
        raise AssertionError("chain did not terminate")

    want = pull_chain(lambda s: engine.packed_since_window(p, s, 7))

    got = []
    since = 0
    step = 0
    while True:
        body, meta = view.window(since, 7)
        got.append((body, tuple(sorted(meta.items()))))
        # chaos between pulls: new writes + spill + watermark GC
        log.extend(chain_ops(5, 3, start=1 + 3 * step))
        log.maybe_spill()
        log.run_gc()
        step += 1
        if meta["next_since"] is not None:
            since = meta["next_since"]
        if not meta["more"]:
            break
    assert got == want
    # a FRESH view serves the grown log (old ops + the chaos writes)
    n_new = len(log)
    assert n_new == len(applied) + 3 * step
    fresh = log.view(max_depth=4)
    assert fresh.length == n_new
    # dropping the pinned view lets deferred GC collect its files
    del view, fresh
    log.run_gc()
    assert log.telemetry()["gc_deferred"] == 0


def test_gc_gated_by_stability_watermark(tmp_path):
    """Checkpoint advancement consumes ONLY watermark-cleared
    segments: with the mark mid-log the base stops there; clearing the
    mark lets the fold finish and the folded files disappear."""
    applied = list(applied_log_tree(mixed_ops(30))._log)
    log = tiered_copy(applied, tmp_path, "wm", hot_ops=8,
                      gc_min_segs=2, auto_stable=False)
    tele = log.telemetry()
    assert tele["segments"]["cold"] >= 4 and tele["base_ops"] == 0
    files_before = set(os.listdir(tmp_path / "wm"))
    # nothing stable yet → nothing folds
    log.run_gc()
    assert log.telemetry()["base_ops"] == 0
    # mid-log watermark → base advances AT MOST to the mark
    mark = len(log) // 2
    log.set_stable_mark(mark)
    log.run_gc()
    tele = log.telemetry()
    assert 0 < tele["base_ops"] <= mark
    assert tele["compactions"] == 1
    # full watermark → everything cold folds; old segment files GC'd
    log.set_stable_mark(len(log))
    log.run_gc()
    tele = log.telemetry()
    assert tele["base_ops"] == len(log) - tele["hot_ops"]
    assert tele["segments"]["cold"] == 0
    files_after = set(os.listdir(tmp_path / "wm"))
    assert not (files_before & files_after), \
        "folded segment files must be collected"
    # reads still logically identical after base advancement
    assert list(log) == applied


# -- truncate / restore ------------------------------------------------------


def test_truncate_into_cold_tier(tmp_path):
    applied = list(applied_log_tree(mixed_ops(20))._log)
    flat = OpLog(applied)
    log = tiered_copy(applied, tmp_path, "trunc", hot_ops=8)
    assert log.telemetry()["segments"]["cold"] >= 2
    cut = 11        # inside the cold range
    log.truncate(cut)
    flat.truncate(cut)
    assert len(log) == cut
    assert list(log) == applied[:cut]
    for op in applied[:cut]:
        if isinstance(op, Add):
            assert log.index_of_add(op.ts) == flat.index_of_add(op.ts)
    for op in applied[cut:]:
        if isinstance(op, Add) and flat.index_of_add(op.ts) is None:
            assert log.index_of_add(op.ts) is None
    # the log keeps working: append + re-spill + windows
    log.extend([Delete((ts(1, 1),))])
    assert list(log) == applied[:cut] + [Delete((ts(1, 1),))]
    log.maybe_spill()
    assert list(log) == applied[:cut] + [Delete((ts(1, 1),))]


def test_restore_checkpoint_plus_tail_bit_identical(tmp_path):
    """A tiered checkpoint restore must be fingerprint-equal —
    bit-identical merge state — to the full-replay tree: same log,
    same clocks, same visible sequence, same replica-independent
    state fingerprint, and a follow-up merge converges identically."""
    big = chain_ops(1, 1500)            # kernel-path bulk
    small = chain_ops(2, 30)            # host-path edits
    t = engine.init(0)
    t.enable_log_tiering(str(tmp_path / "ckpt"), hot_ops=256,
                         gc_min_segs=2)
    t.apply_packed(packed_mod.pack(big, max_depth=4))
    for op in small:
        t.apply(op)
    t.apply(Delete((ts(2, 30),)))
    assert t._log.spills >= 1
    t.checkpoint_tiered(str(tmp_path / "ckpt"))

    r = engine.TpuTree.restore_tiered(str(tmp_path / "ckpt"))
    replay = engine.init(0)
    replay.apply(op_mod.from_list(big + small + [Delete((ts(2, 30),))]))
    assert list(r._log) == list(t._log) == list(replay._log)
    assert r._replicas == t._replicas == replay._replicas
    assert r.visible_values() == replay.visible_values()
    snap_r = snapshot_mod.derive("d", 0, r)
    snap_t = snapshot_mod.derive("d", 7, t)
    snap_o = snapshot_mod.derive("d", 3, replay)
    assert snap_r.state_fingerprint() == snap_t.state_fingerprint() \
        == snap_o.state_fingerprint()
    # restored tree keeps merging bit-identically to the replay oracle
    more = chain_ops(3, 1100)
    r.apply_packed(packed_mod.pack(more, max_depth=4))
    replay.apply(op_mod.from_list(more))
    assert r.visible_values() == replay.visible_values()
    assert snapshot_mod.derive("d", 0, r).state_fingerprint() == \
        snapshot_mod.derive("d", 0, replay).state_fingerprint()


def test_missing_or_corrupt_segment_is_typed_checkpoint_error(tmp_path):
    t = engine.init(0)
    t.enable_log_tiering(str(tmp_path / "bad"), hot_ops=64)
    t.apply_packed(packed_mod.pack(chain_ops(1, 600), max_depth=4))
    assert t._log.spills >= 1
    t.checkpoint_tiered(str(tmp_path / "bad"))
    seg_files = [f for f in os.listdir(tmp_path / "bad")
                 if f.startswith("seg-")]
    assert seg_files
    victim = tmp_path / "bad" / seg_files[0]

    # corrupt: truncated bytes → typed error at restore (the light
    # open reads the file), never a silent partial log
    blob = victim.read_bytes()
    victim.write_bytes(blob[: len(blob) // 2])
    with pytest.raises(CheckpointError):
        engine.TpuTree.restore_tiered(str(tmp_path / "bad"))

    # missing entirely → typed error too
    victim.unlink()
    with pytest.raises(CheckpointError):
        engine.TpuTree.restore_tiered(str(tmp_path / "bad"))

    # and a LIVE log whose spilled file vanishes behind its back
    # surfaces the same typed error when a cold read needs it
    live = OpLog(chain_ops(1, 60))
    live.enable_tiering(str(tmp_path / "live"), hot_ops=8,
                        cache_segments=1)
    live.maybe_spill()
    for f in os.listdir(tmp_path / "live"):
        os.remove(tmp_path / "live" / f)
    with pytest.raises(CheckpointError):
        live.materialize(0, 10)
    with pytest.raises(CheckpointError):
        live.view(4).window(ts(1, 1), 4)


def test_corrupt_manifest_is_typed(tmp_path):
    t = engine.init(0)
    t.enable_log_tiering(str(tmp_path / "m"), hot_ops=64)
    t.apply_packed(packed_mod.pack(chain_ops(1, 300), max_depth=4))
    t.checkpoint_tiered(str(tmp_path / "m"))
    (tmp_path / "m" / "manifest.json").write_text("{not json")
    with pytest.raises(CheckpointError):
        engine.TpuTree.restore_tiered(str(tmp_path / "m"))
    with pytest.raises(CheckpointError):
        engine.TpuTree.restore_tiered(str(tmp_path / "nowhere"))


# -- fingerprints across tier layouts ---------------------------------------


def test_state_fingerprint_layout_independent(tmp_path):
    """Converged replicas with DIFFERENT tier layouts (one untiered,
    one spilled, one spilled+compacted) must agree on the replica-
    independent fingerprint: it hashes the logical op extent, never
    the physical segmentation."""
    ops = chain_ops(1, 900) + chain_ops(2, 50)
    flat = engine.init(0)
    flat.apply_packed(packed_mod.pack(ops, max_depth=4))
    spilled = engine.init(0)
    spilled.enable_log_tiering(str(tmp_path / "s"), hot_ops=128,
                               gc_min_segs=99)      # spill, no fold
    spilled.apply_packed(packed_mod.pack(ops, max_depth=4))
    folded = engine.init(0)
    folded.enable_log_tiering(str(tmp_path / "f"), hot_ops=64,
                              gc_min_segs=2)        # spill AND fold
    folded.apply_packed(packed_mod.pack(ops[:700], max_depth=4))
    folded.apply_packed(packed_mod.pack(ops, max_depth=4))
    assert spilled._log.spills >= 1 and folded._log.compactions >= 1
    snaps = [snapshot_mod.derive("doc", i, t)
             for i, t in enumerate((flat, spilled, folded))]
    assert snaps[0].log_length == snaps[1].log_length \
        == snaps[2].log_length == len(ops)
    fps = {s.state_fingerprint() for s in snaps}
    assert len(fps) == 1, "tier layout leaked into the fingerprint"
    # while the physical layouts genuinely differ
    assert len({s.log_segments for s in snaps}) >= 2


# -- memory bound ------------------------------------------------------------


def test_memory_bound_500k_resident_is_o_hot_window(tmp_path):
    """The headline memory claim, tier-1-sized: a 500k-op document's
    resident log bytes after spill stay O(hot window) — ≤10% of the
    untiered log measured by the SAME estimator, with the hot tier at
    its budget and the rest on disk."""
    from crdt_graph_tpu.bench import workloads
    n = 500_000
    hot = 8192
    arrs = workloads.chain_workload(n_replicas=8, n_ops=n)
    p = packed_mod.PackedOps(
        kind=arrs["kind"], ts=arrs["ts"],
        parent_ts=arrs["parent_ts"], anchor_ts=arrs["anchor_ts"],
        depth=arrs["depth"], paths=arrs["paths"],
        value_ref=arrs["value_ref"], pos=arrs["pos"],
        values=[f"v{i}" for i in range(n)], num_ops=n,
        parent_pos=arrs["parent_pos"], anchor_pos=arrs["anchor_pos"],
        target_pos=arrs["target_pos"], ts_rank=arrs["ts_rank"],
        hints_vouched=True)

    untiered = OpLog()
    untiered.extend_packed(p)
    # the untiered serving shape builds the ts index on its first
    # since-pull — include that honestly on the untiered side
    untiered.view(1).since_bytes(int(arrs["ts"][n - 10]))
    untiered_bytes = untiered.resident_bytes()

    log = OpLog()
    log.extend_packed(p)
    # folding disabled: the cold tier stays segment-granular, so a
    # cold catch-up read loads ONE bounded segment, not the backlog
    log.enable_tiering(str(tmp_path / "mem"), hot_ops=hot,
                       gc_min_segs=10_000)
    log.maybe_spill()
    tele = log.telemetry()
    # spill hysteresis keeps at most hot + hot/4 resident
    assert tele["hot_ops"] <= hot + hot // 4
    assert tele["cold_ops"] + tele["base_ops"] == n - tele["hot_ops"]
    resident = tele["resident_bytes"]
    assert resident <= 0.10 * untiered_bytes, \
        (resident, untiered_bytes)
    # and the log still answers: a steady-state window off the hot
    # tail touches no cold segment
    loads0 = tele["segment_loads"]
    view = log.view(1)
    body, meta = view.window(int(arrs["ts"][n - 100]), 64)
    assert meta["found"] and meta["count"] >= 1
    assert log.telemetry()["segment_loads"] == loads0
    # a cold window loads exactly what it serves (bounded by the LRU)
    body, meta = view.window(int(arrs["ts"][100]), 64)
    assert meta["found"] and meta["more"]
    assert log.telemetry()["segment_loads"] >= loads0 + 1
    assert log.telemetry()["cache_bytes"] <= 0.15 * untiered_bytes


# -- chunked checkpoint base (ISSUE 11) --------------------------------------


def test_base_chunks_window_identity_at_chunk_seams(tmp_path):
    """With the base split into bounded chunks, every window —
    including ones whose terminator sits exactly on a chunk seam or
    whose body spans several chunks — stays byte- and meta-identical
    to the untiered ``packed_since_window``."""
    ops = mixed_ops(30)
    t = applied_log_tree(ops)
    applied = list(t._log)
    p = packed_mod.pack(applied, max_depth=4)
    log = tiered_copy(applied, tmp_path, "bc", hot_ops=8,
                      gc_min_segs=1, base_chunk_ops=16)
    log.set_stable_mark(len(log))
    log.run_gc()
    tele = log.telemetry()
    assert tele["segments"]["base"] >= 3, tele
    assert tele["base_ops"] == len(log) - tele["hot_ops"]
    view = log.view(max_depth=4)
    adds = [op.ts for op in applied if isinstance(op, Add)]
    for since in [0] + adds + adds[-2:]:
        for limit in (0, 1, 3, 8, 16, 17, 1000):
            want = engine.packed_since_window(p, since, limit)
            got = view.window(since, limit)
            assert got[0] == want[0], (since, limit)
            assert got[1] == want[1], (since, limit)
    # incremental folds keep appending chunks, identically
    log.extend(chain_ops(7, 40))
    log.maybe_spill()
    log.set_stable_mark(len(log))
    log.run_gc()
    assert log.telemetry()["segments"]["base"] >= \
        tele["segments"]["base"]
    full = log.view(max_depth=4)
    p2 = full.to_packed()
    for since in (0, adds[5], adds[-1]):
        want = engine.packed_since_window(p2, since, 9)
        got = log.view(max_depth=4).window(since, 9)
        assert got[0] == want[0] and got[1] == want[1], since


def test_mid_history_window_opens_only_covering_chunks(tmp_path):
    """The resident-bytes bound (acceptance): a mid-history catch-up
    window over a fully-folded log loads ONLY its covering base
    chunks — the cache holds O(covering chunks), never the whole
    base, and the byte-denominated LRU (GRAFT_OPLOG_CACHE_MB) counts
    its evictions."""
    from crdt_graph_tpu.bench import workloads
    from crdt_graph_tpu.oplog import OpLog as _OpLog, _packed_resident
    n = 60_000
    arrs = workloads.chain_workload(n_replicas=8, n_ops=n)
    p = packed_mod.PackedOps(
        kind=arrs["kind"], ts=arrs["ts"],
        parent_ts=arrs["parent_ts"], anchor_ts=arrs["anchor_ts"],
        depth=arrs["depth"], paths=arrs["paths"],
        value_ref=arrs["value_ref"], pos=arrs["pos"],
        values=[f"v{i}" for i in range(n)], num_ops=n,
        parent_pos=arrs["parent_pos"], anchor_pos=arrs["anchor_pos"],
        target_pos=arrs["target_pos"], ts_rank=arrs["ts_rank"],
        hints_vouched=True)
    chunk = 8192
    log = _OpLog()
    log.extend_packed(p)
    log.enable_tiering(str(tmp_path / "cw"), hot_ops=2048,
                       gc_min_segs=1, base_chunk_ops=chunk)
    log.maybe_spill()
    log.set_stable_mark(len(log))
    log.run_gc()
    tele = log.telemetry()
    assert tele["segments"]["base"] >= 6, tele
    whole_base_resident = _packed_resident(p)  # upper-ruler: full log
    view = log.view(1)
    loads0 = tele["segment_loads"]
    # one bounded mid-history window → at most the 1-2 chunks that
    # cover it load; the cache stays O(chunk), not O(base)
    body, meta = view.window(int(arrs["ts"][n // 2]), 256)
    assert meta["found"] and meta["count"] >= 256
    tele = log.telemetry()
    assert 1 <= tele["segment_loads"] - loads0 <= 2, tele
    per_chunk = whole_base_resident * (chunk / n)
    assert tele["cache_bytes"] <= 2.5 * per_chunk, \
        (tele["cache_bytes"], per_chunk, whole_base_resident)
    assert tele["cache_bytes"] < 0.2 * whole_base_resident
    # a sweep across the whole history stays byte-bounded by the LRU
    # knob and counts evictions (the shared-sizing satellite)
    small = _OpLog()
    small.extend_packed(p)
    small.enable_tiering(str(tmp_path / "cw2"), hot_ops=2048,
                         gc_min_segs=1, base_chunk_ops=chunk,
                         cache_mb=1)
    small.maybe_spill()
    small.set_stable_mark(len(small))
    small.run_gc()
    sview = small.view(1)
    for i in range(4, n, n // 9):
        body, meta = sview.window(int(arrs["ts"][i]), 128)
        assert meta["found"], i
    stele = small.telemetry()
    assert stele["cache_evictions"] >= 1, stele
    assert stele["cache_bytes"] <= 2 * (1 << 20), stele


def test_fold_rewrites_only_trailing_partial_chunk(tmp_path):
    """Write-amplification bound: an incremental fold may rewrite the
    trailing PARTIAL chunk but never a full one — earlier full chunks
    keep their exact files across later folds."""
    applied = list(applied_log_tree(mixed_ops(40))._log)
    log = tiered_copy(applied, tmp_path, "wa", hot_ops=8,
                      gc_min_segs=1, base_chunk_ops=16)
    log.set_stable_mark(len(log))
    log.run_gc()
    full_before = {cs.path for cs in log._bases
                   if cs.length == 16}
    assert full_before
    log.extend(chain_ops(8, 60))
    log.maybe_spill()
    log.set_stable_mark(len(log))
    log.run_gc()
    after = {cs.path for cs in log._bases}
    assert full_before <= after, \
        "a fold rewrote full base chunks (unbounded write amp)"
    assert list(log) == applied + chain_ops(8, 60)


# -- serving integration + exposition ----------------------------------------


def test_serving_engine_tiers_by_default_and_prom_round_trips():
    """A served document spills under sustained writes with the
    default-on cascade, keeps serving byte-correct windows, exports
    the ``crdt_oplog_*`` families under the strict naming contract,
    and reports tier state in /metrics."""
    from crdt_graph_tpu.serve import ServingEngine
    eng = ServingEngine(oplog_hot_ops=512)
    try:
        doc_id = "casc"
        for k in range(4):
            ops = chain_ops(1, 600, start=1 + 600 * k)
            eng.get(doc_id).apply_body(
                json_codec.dumps(Batch(tuple(ops))))
        assert eng.flush(timeout=60)
        doc = eng.get(doc_id, create=False)
        tele = doc.tree._log.telemetry()
        assert tele["tiered"] and tele["spills"] >= 1
        assert tele["hot_ops"] < 2400
        # windows off the published snapshot match the untiered ruler
        p = packed_mod.pack(chain_ops(1, 2400), max_depth=16)
        for since in (0, ts(1, 1), ts(1, 600), ts(1, 2399)):
            want = engine.packed_since_window(p, since, 100)
            got = doc.ops_since_window(since, 100)
            assert got[0] == want[0], since
            # the served window adds the body validator (ISSUE 16) on
            # top of the ruler's meta
            got_meta = {k: v for k, v in got[1].items() if k != "etag"}
            assert got_meta == want[1], since
        # /metrics carries the tier state
        assert doc.metrics()["oplog"]["spills"] >= 1
        # strict prom round trip with the new families present
        fams = prom_mod.parse_text(eng.render_prom())
        for fam in ("crdt_oplog_spills_total",
                    "crdt_oplog_compactions_total",
                    "crdt_oplog_segments_gc_total",
                    "crdt_oplog_segment_loads_total",
                    "crdt_oplog_resident_bytes",
                    "crdt_oplog_stable_mark",
                    "crdt_oplog_tier_ops", "crdt_oplog_tier_bytes",
                    "crdt_oplog_segment_load_ms"):
            assert fam in fams, fam
        tiers = {lbl["tier"] for _, lbl, _ in
                 fams["crdt_oplog_tier_ops"]["samples"]}
        assert tiers == {"hot", "cold", "base"}
        spills = [v for _, lbl, v in
                  fams["crdt_oplog_spills_total"]["samples"]
                  if lbl["doc"] == doc_id]
        assert spills and spills[0] >= 1
        # the spill scratch tier dies with the engine
        spill_dir = eng.oplog_dir
        assert os.path.isdir(spill_dir)
    finally:
        eng.close()
    assert not os.path.exists(spill_dir)


def test_snapshot_pins_view_across_spill_and_bootstrap_roundtrip():
    """A published snapshot keeps serving its exact generation while
    the live log spills underneath it, and its /snapshot bootstrap
    bytes restore to the same state."""
    from crdt_graph_tpu.serve import ServingEngine
    eng = ServingEngine(oplog_hot_ops=256)
    try:
        doc = eng.get("pin")
        doc.apply_body(json_codec.dumps(Batch(tuple(chain_ops(1, 400)))))
        assert eng.flush(timeout=60)
        snap = doc.snapshot_view()
        want_bytes = snap.ops_since_bytes(0)
        # push more → spill moves the first batch to disk
        doc.apply_body(json_codec.dumps(Batch(tuple(chain_ops(2, 700)))))
        assert eng.flush(timeout=60)
        assert doc.tree._log.spills >= 1
        # the OLD snapshot still serves its own generation, unchanged
        assert snap.log_length == 400
        assert snap.ops_since_bytes(0) == want_bytes
        # the NEW snapshot's binary bootstrap restores bit-identically
        new = doc.snapshot_view()
        assert new.log_length == 1100
        r = engine.TpuTree.restore_packed(
            io.BytesIO(new.checkpoint_bytes()), replica=7)
        assert r.log_length == 1100
        assert snapshot_mod.derive("pin", 0, r).state_fingerprint() \
            == new.state_fingerprint()
    finally:
        eng.close()


def test_checkpoint_tiered_to_foreign_dir_survives_engine(tmp_path):
    """A served document tiers into EPHEMERAL engine scratch;
    ``checkpoint_tiered(dir)`` must honor the requested dir (copying
    the segment files) so the checkpoint survives the engine that
    wrote it — checkpointing into the scratch dir would be silently
    destroyed by ``engine.close()``."""
    from crdt_graph_tpu.serve import ServingEngine
    eng = ServingEngine(oplog_hot_ops=256)
    target = str(tmp_path / "backup")
    try:
        doc = eng.get("ckpt")
        doc.apply_body(json_codec.dumps(Batch(tuple(chain_ops(1, 900)))))
        assert eng.flush(timeout=60)
        assert doc.tree._log.spills >= 1
        want_fp = doc.snapshot_view().state_fingerprint()
        path = doc.tree.checkpoint_tiered(target)
        assert path.startswith(target)
    finally:
        eng.close()
    # the scratch tier is gone with the engine; the checkpoint is not
    r = engine.TpuTree.restore_tiered(target)
    assert r.log_length == 900
    assert snapshot_mod.derive("ckpt", 0, r).state_fingerprint() \
        == want_fp


def test_hot_bytes_budget_spills_by_bytes(tmp_path):
    """GRAFT_OPLOG_HOT_BYTES semantics: with large per-op values the
    BYTE budget triggers the spill long before the op budget would,
    and its hysteresis is byte-denominated."""
    ops = [Add(ts(1, c), ((ts(1, c - 1) if c > 1 else 0),), "x" * 2000)
           for c in range(1, 201)]
    log = OpLog()
    log.extend_packed(packed_mod.pack(ops, max_depth=4))
    budget = 100_000
    log.enable_tiering(str(tmp_path / "hb"), hot_ops=100_000,
                       hot_bytes=budget, gc_min_segs=99)
    assert log.maybe_spill()
    tele = log.telemetry()
    assert tele["spills"] >= 1
    assert tele["hot_ops"] < 200
    assert tele["hot_bytes"] <= 2 * budget, tele


# -- headline artifact (slow wrapper) ----------------------------------------


@pytest.mark.slow
def test_bench_oplog_headline_full(tmp_path):
    """The committed-artifact run (BENCH_OPLOG_r01_cpu.json shape):
    1M-op config-5 document, default cascade knobs — resident log
    bytes ≤10% of untiered, checkpoint+tail restore ≥5× faster than
    full replay, bit-identical merge fingerprints throughout."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_oplog_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_oplog_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_OPLOG_test.json"))
    assert out["fingerprints_equal"]
    assert out["resident"]["ratio"] <= 0.10, out["resident"]
    assert out["restore"]["speedup_serving_ready"] >= 5.0, \
        out["restore"]
    assert out["tiers"]["spills"] >= 1
    assert out["windows"]["hot_p50_ms"] is not None


# -- deterministic fleet round: GC mid-sync ---------------------------------


def _req(port, method, path, body=None, headers=None, timeout=60):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw, dict(resp.getheaders())
    finally:
        conn.close()


def test_fleet_gc_mid_sync_converges_with_oracle(monkeypatch):
    """Tier-1 fleet determinism: a 2-node fleet with tiny hot budgets
    syncs a spilled document in bounded window chains; checkpoint
    advancement + segment GC run MID-CHAIN (watermark at the puller's
    half-way mark), the chain resumes across the fold, and the session
    oracle reports fingerprint-equal convergence with 0 violations."""
    from crdt_graph_tpu.cluster import FleetServer, MemoryKV
    from crdt_graph_tpu.obs.oracle import SessionOracle
    monkeypatch.setenv("GRAFT_OPLOG_HOT_OPS", "96")
    monkeypatch.setenv("GRAFT_OPLOG_GC_SEGS", "2")
    kv = MemoryKV()
    fleet = {}
    for n in ("n0", "n1"):
        fleet[n] = FleetServer(n, kv, ttl_s=600.0,
                               ae_interval_s=3600.0, delta_cap=300)
    try:
        for fs in fleet.values():
            fs.node.refresh_ring()
        ring = fleet["n0"].node.ring()
        doc = next(f"doc{i}" for i in range(500)
                   if ring.primary(f"doc{i}") == "n0")
        # 1200 ops through the primary → several cold segments
        ops = chain_ops(3, 1200)
        st, raw, _ = _req(fleet["n0"].port, "POST", f"/docs/{doc}/ops",
                          body=json_codec.dumps(Batch(tuple(ops))))
        assert st == 200 and json.loads(raw)["accepted"]
        assert fleet["n0"].node.engine.flush(timeout=60)
        log0 = fleet["n0"].node.engine.get(doc).tree._log
        assert log0.spills >= 1
        segs_before = log0.telemetry()["segments"]["cold"]
        assert segs_before >= 2
        # fleet logs must NOT auto-stabilize: no peer pulled yet
        log0.run_gc()
        assert log0.telemetry()["base_ops"] == 0

        # n1 pulls a PARTIAL chain (2 bounded windows), then stops —
        # mid-sync by construction
        ae1 = fleet["n1"].node.antientropy
        ae1.max_windows_per_doc = 2
        ae1.sync_now()              # partial: chain cut after 2 windows
        marks = fleet["n0"].node._peer_marks.get(doc, {})
        assert "n1" in marks and marks["n1"] > 0
        # the primary folds what n1 provably consumed — and ONLY that
        fleet["n0"].node.update_stability()
        tele = log0.telemetry()
        mark_pos = log0.index_of_add(marks["n1"])
        assert tele["stable_mark"] == mark_pos
        assert tele["base_ops"] <= mark_pos
        gc_ran = tele["compactions"] >= 1
        assert gc_ran, "GC must advance the base mid-sync"
        # unauthenticated X-Ae-Peer values must not accumulate: marks
        # from non-members are pruned on every stability round
        fleet["n0"].node.note_peer_mark(doc, "not-a-member", 12345)
        fleet["n0"].node.update_stability()
        assert "not-a-member" not in \
            fleet["n0"].node._peer_marks.get(doc, {})

        # the chain RESUMES across the fold and completes
        ae1.max_windows_per_doc = 10_000
        assert ae1.sync_now() == {"n0": True}
        assert fleet["n1"].node.engine.flush(timeout=60)
        fleet["n0"].node.update_stability()

        # oracle-verified fingerprint-equal convergence
        oracle = SessionOracle()
        fps = {}
        for name, fs in fleet.items():
            st, raw, hdr = _req(fs.port, "GET", f"/docs/{doc}")
            assert st == 200
            fps[name] = hdr["X-State-Fingerprint"]
            oracle.observe_replica_state(
                doc, f"{name}.1", hdr["X-State-Fingerprint"])
        assert fps["n0"] == fps["n1"], fps
        violations = oracle.finalize()
        assert violations == [], violations
        assert oracle.stats()["violations_total"] == 0
    finally:
        for fs in fleet.values():
            try:
                fs.stop()
            except Exception:   # noqa: BLE001 — teardown boundary
                pass
