"""Wire-service end-to-end: two clients collaborating on one document over
real HTTP, speaking the reference-compatible JSON codec."""
import json

import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.models import TextBuffer

# ``server`` and ``req`` fixtures come from tests/conftest.py (shared
# with test_elm_interop.py)


def test_collaboration_roundtrip(server, req):
    # two clients join and get distinct replica ids
    _, r1 = req(server, "POST", "/docs/novel/replicas")
    _, r2 = req(server, "POST", "/docs/novel/replicas")
    assert r1["replica"] != r2["replica"]

    a = TextBuffer(r1["replica"])
    b = TextBuffer(r2["replica"])
    a.insert(0, "hello")
    delta = json_codec.dumps(a.operations_since(0))
    st, out = req(server, "POST", "/docs/novel/ops", delta)
    assert st == 200 and out["accepted"]

    # b pulls everything, edits, pushes
    _, ops = req(server, "GET", "/docs/novel/ops?since=0")
    b.apply(json_codec.decode(ops))
    assert b.text() == "hello"
    b.insert(5, " world")
    since = b.last_replica_timestamp(b.replica_id)
    st, _ = req(server, "POST", "/docs/novel/ops",
                json_codec.dumps(b.last_operation))
    assert st == 200

    # server snapshot reflects the merge; a converges by pulling
    _, snap = req(server, "GET", "/docs/novel")
    assert "".join(snap["values"]) == "hello world"
    _, ops = req(server, "GET", "/docs/novel/ops?since=0")
    a.apply(json_codec.decode(ops))
    assert a.text() == "hello world"


def test_three_client_randomized_convergence(server, req):
    """Race coverage at the service level: three clients interleave local
    edits, pushes, and pulls in random order over real HTTP; everyone
    (and the server snapshot) must converge to one document."""
    import random
    rng = random.Random(13)
    clients = []
    for _ in range(3):
        _, r = req(server, "POST", "/docs/race/replicas")
        clients.append(TextBuffer(r["replica"]))
    def push(i):
        c = clients[i]
        delta = c.last_operation
        body = json_codec.dumps(delta)
        st, _ = req(server, "POST", "/docs/race/ops", body)
        assert st in (200, 409)

    def pull(i):
        # full replay every pull: duplicate delivery is normal and must
        # be absorbed (the idempotence contract under test)
        _, ops = req(server, "GET", "/docs/race/ops?since=0")
        clients[i].apply(json_codec.decode(ops))

    for step in range(60):
        i = rng.randrange(3)
        roll = rng.random()
        c = clients[i]
        if roll < 0.5:
            n = len(c)
            if n and rng.random() < 0.3:
                c.delete(rng.randrange(n))
            else:
                c.insert(rng.randrange(n + 1), chr(97 + step % 26))
            push(i)
        else:
            pull(i)
    for i in range(3):
        pull(i)
    _, snap = req(server, "GET", "/docs/race")
    server_text = "".join(str(v) for v in snap["values"])
    assert clients[0].text() == clients[1].text() == clients[2].text() \
        == server_text
    assert server_text            # non-trivial document


def test_duplicate_push_absorbed(server, req):
    a = TextBuffer(1)
    a.insert(0, "x")
    delta = json_codec.dumps(a.operations_since(0))
    req(server, "POST", "/docs/d/ops", delta)
    req(server, "POST", "/docs/d/ops", delta)
    _, metrics = req(server, "GET", "/docs/d/metrics")
    assert metrics["ops_merged"] == 1
    assert metrics["dup_absorbed"] == 1
    assert metrics["num_visible"] == 1


def test_causality_gap_rejected_and_recoverable(server, req):
    # op anchored at a node the server has never seen → 409, doc untouched
    orphan = json_codec.dumps(crdt.Add(5 * 2**32 + 1, (999,), "z"))
    st, out = req(server, "POST", "/docs/g/ops", orphan)
    assert st == 409 and not out["accepted"]
    _, metrics = req(server, "GET", "/docs/g/metrics")
    assert metrics["batches_rejected"] == 1
    assert metrics["num_visible"] == 0
    # after syncing the missing context, the same edit applies
    base = json_codec.dumps(crdt.Add(999, (0,), "base"))
    st, _ = req(server, "POST", "/docs/g/ops", base)
    assert st == 200
    st, _ = req(server, "POST", "/docs/g/ops", orphan)
    assert st == 200


def test_malformed_payload_400(server, req):
    st, _ = req(server, "POST", "/docs/m/ops", '{"op": "add"}')
    assert st == 400
    st, _ = req(server, "POST", "/docs/m/ops", "not json at all")
    assert st == 400


def test_unknown_doc_404(server, req):
    st, _ = req(server, "GET", "/docs/nope")
    assert st == 404
    st, _ = req(server, "GET", "/bogus")
    assert st == 404


def test_global_metrics_lists_docs(server, req):
    req(server, "POST", "/docs/one/replicas")
    req(server, "POST", "/docs/two/replicas")
    _, m = req(server, "GET", "/metrics")
    assert set(m) == {"one", "two"}


def test_ops_endpoint_serves_native_encoded_batch(server, req):
    a = TextBuffer(1)
    a.insert(0, "fast")
    st, out = req(server, "POST", "/docs/fast/ops",
                  json_codec.dumps(a.operations_since(0)))
    assert st == 200 and out["accepted"]
    _, ops = req(server, "GET", "/docs/fast/ops?since=0")
    b = TextBuffer(2)
    b.apply(json_codec.decode(ops))
    assert b.text() == "fast"


def test_snapshot_bootstrap_roundtrip(server, req):
    """GET /snapshot returns the binary packed checkpoint; a client
    restores it under its OWN replica id (from POST /replicas) in one
    transfer and keeps replicating — the bootstrap alternative to
    replaying the JSON log.  Without the id adoption every snapshot-
    bootstrapped client would inherit the server's replica 0 and mint
    colliding timestamps."""
    import io
    from http.client import HTTPConnection
    from crdt_graph_tpu import engine

    a = TextBuffer(1)
    a.insert(0, "snapshot me")
    req(server, "POST", "/docs/snap/ops",
        json_codec.dumps(a.operations_since(0)))

    def fetch_snapshot():
        conn = HTTPConnection("127.0.0.1", server.server_port, timeout=30)
        conn.request("GET", "/docs/snap/snapshot")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "application/octet-stream"
        blob = resp.read()
        conn.close()
        return blob

    blob = fetch_snapshot()
    # two clients bootstrap from the SAME snapshot bytes under distinct
    # assigned ids; their concurrent edits must not collide
    _, r1 = req(server, "POST", "/docs/snap/replicas")
    _, r2 = req(server, "POST", "/docs/snap/replicas")
    b = engine.TpuTree.restore_packed(io.BytesIO(blob),
                                      replica=r1["replica"])
    c = engine.TpuTree.restore_packed(io.BytesIO(blob),
                                      replica=r2["replica"])
    assert "".join(b.visible_values()) == "snapshot me"
    assert b.replica_id == r1["replica"] != c.replica_id

    b.add("B")
    c.add("C")
    assert b.last_replica_timestamp(b.replica_id) != \
        c.last_replica_timestamp(c.replica_id)
    for t in (b, c):
        st, out = req(server, "POST", "/docs/snap/ops",
                      json_codec.dumps(t.last_operation))
        assert st == 200 and out["accepted"]
    _, snap = req(server, "GET", "/docs/snap")
    assert sorted(v for v in snap["values"] if v in "BC") == ["B", "C"]

    # the original replica converges by pulling
    _, ops = req(server, "GET", "/docs/snap/ops?since=0")
    b.apply(json_codec.decode(ops))
    assert [v for v in b.visible_values() if v in "BC"] == \
        [v for v in snap["values"] if v in "BC"]


def test_oversized_body_413():
    """POST bodies above max_body are rejected before being read
    (VERDICT r3 weak-6: request-size cap on /ops)."""
    import threading
    from http.client import HTTPConnection
    from crdt_graph_tpu.service import make_server

    srv = make_server(port=0, max_body=1024)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = HTTPConnection("127.0.0.1", srv.server_port, timeout=30)
        conn.request("POST", "/docs/big/ops", body=b"x" * 4096)
        resp = conn.getresponse()
        assert resp.status == 413
        conn.close()
        # small bodies still work on a fresh connection
        conn = HTTPConnection("127.0.0.1", srv.server_port, timeout=30)
        conn.request("POST", "/docs/big/ops",
                     body='{"op":"add","path":[0],"ts":1,"val":"a"}')
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_malformed_content_length_400():
    """A non-numeric Content-Length answers 400 instead of aborting the
    connection with an uncaught ValueError (ADVICE r4)."""
    import socket
    import threading
    from crdt_graph_tpu.service import make_server

    srv = make_server(port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        s = socket.create_connection(("127.0.0.1", srv.server_port),
                                     timeout=30)
        s.sendall(b"POST /docs/cl/ops HTTP/1.1\r\n"
                  b"Host: x\r\nContent-Length: abc\r\n\r\n")
        data = s.recv(4096)
        assert b"400" in data.split(b"\r\n", 1)[0]
        s.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_wire_fast_path_matches_object_path(monkeypatch):
    """POST bodies route by size: >WIRE_FAST_BYTES takes the column
    ingest (engine.apply_packed), smaller ones the object path.  Both
    must produce identical documents, counters, and rejection behavior
    — pinned by forcing the threshold to 0 and replaying the same
    session through both."""
    from crdt_graph_tpu.service.store import Document

    ops1 = json_codec.dumps(crdt.Batch(tuple(
        crdt.Add(2**32 + i + 1, (2**32 + i if i else 0,), f"v{i}")
        for i in range(1200))))
    # overlap + fresh tail, exercises dup absorption on the fast path
    ops2 = json_codec.dumps(crdt.Batch(tuple(
        crdt.Add(2**32 + i + 1, (2**32 + i if i else 0,), f"v{i}")
        for i in range(800, 2400))))
    orphan = json_codec.dumps(crdt.Batch(
        tuple(crdt.Add(7 * 2**32 + i + 1, (999999 + i,), "x")
              for i in range(1100))))

    def run(fast):
        doc = Document("d")
        if fast:
            monkeypatch.setattr(Document, "WIRE_FAST_BYTES", 0)
        else:
            monkeypatch.setattr(Document, "WIRE_FAST_BYTES", 1 << 60)
        ok1, _ = doc.apply_body(ops1)
        ok2, _ = doc.apply_body(ops2)
        ok3, _ = doc.apply_body(orphan)       # causality gap -> reject
        assert (ok1, ok2, ok3) == (True, True, False)
        return doc.tree.visible_values(), doc.metrics()

    vals_fast, m_fast = run(True)
    vals_obj, m_obj = run(False)
    assert vals_fast == vals_obj
    assert m_fast == m_obj
    assert m_fast["dup_absorbed"] == 400
    assert m_fast["batches_rejected"] == 1


def test_clock_endpoint_enables_minimal_sync(server, req):
    """GET /clock exposes the server's vector clock; a client pulls
    exactly its missing suffix instead of replaying from 0."""
    a = TextBuffer(3)
    a.insert(0, "abc")
    req(server, "POST", "/docs/ck/ops", json_codec.dumps(a.operations_since(0)))
    st, out = req(server, "GET", "/docs/ck/clock")
    assert st == 200
    last = out["replicas"]["3"]
    assert last == a.last_replica_timestamp(3)
    # nothing new since the clock value: empty suffix, not a full replay
    _, ops = req(server, "GET", f"/docs/ck/ops?since={last}")
    assert len(ops["ops"]) == 1          # the inclusive terminator only
    a.insert(3, "d")
    req(server, "POST", "/docs/ck/ops", json_codec.dumps(a.last_operation))
    _, ops = req(server, "GET", f"/docs/ck/ops?since={last}")
    assert len(ops["ops"]) == 2          # terminator + the new edit
