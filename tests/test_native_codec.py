"""Native codec differential suite: _fastcodec.parse_pack must agree with
the pure-Python json_codec.loads → pack path on every input — same columns,
same values, same rejections."""
import json
import math
import random

import numpy as np
import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import native
from crdt_graph_tpu.codec import json_codec, packed
from crdt_graph_tpu.core import operation as op_mod

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def assert_same(payload, max_depth=16):
    want = packed.pack(json_codec.loads(payload), max_depth=max_depth)
    got = native.parse_pack(payload, max_depth=max_depth)
    assert got.num_ops == want.num_ops
    for f in ("kind", "ts", "parent_ts", "anchor_ts", "depth", "paths",
              "value_ref", "pos", "parent_pos", "anchor_pos", "target_pos"):
        np.testing.assert_array_equal(getattr(got, f), getattr(want, f), f)
    assert got.values == want.values
    return got


def test_golden_fixtures():
    # the JsonTest.elm shapes: add, del, batch
    assert_same('{"op":"add","path":[0,1],"ts":2,"val":"a"}')
    assert_same('{"op":"del","path":[1,2,3]}')
    assert_same('{"op":"batch","ops":['
                '{"op":"add","path":[0],"ts":1,"val":"x"},'
                '{"op":"del","path":[1]}]}')


def test_nested_batches_flatten_in_order():
    assert_same('{"op":"batch","ops":[{"op":"batch","ops":['
                '{"op":"add","path":[0],"ts":1,"val":1}]},'
                '{"op":"add","path":[1],"ts":2,"val":2},'
                '{"op":"batch","ops":[]}]}')


def test_unknown_tag_is_noop():
    got = assert_same('{"op":"mystery","path":[1]}')
    assert got.num_ops == 0
    # a NON-STRING tag is also just unknown (fuzz find, r4): Python
    # compares obj["op"] to the known tags and falls through
    for doc in ('{"op":[42949672967297]}', '{"op":null}', '{"op":7}',
                '{"op":{"x":1},"path":[0]}'):
        assert assert_same(doc).num_ops == 0
    assert_same('{"op":"batch","ops":[{"op":"future","x":[{"y":1}]},'
                '{"op":"add","path":[0],"ts":5,"val":null}]}')


def test_value_payload_types():
    vals = ["str", "", "unié中😀", 0, -5, 2**40, 1.5,
            -0.25, 1e10, True, False, None, [1, [2, "x"]],
            {"k": {"n": None, "l": [1.0]}}, "esc\"\\\n\t/"]
    ops = crdt.Batch(tuple(crdt.Add(i + 1, (i,), v)
                           for i, v in enumerate(vals)))
    payload = json_codec.dumps(ops)
    got = assert_same(payload)
    assert got.values == list(vals)


def test_whitespace_tolerated():
    assert_same('  {  "op" : "add" , "path" : [ 0 , 1 ] , "ts" : 2 , '
                '"val" : { "a" : [ 1 , 2 ] } }  ')


def test_random_session_payloads():
    from test_merge_kernel import _random_session
    for seed in (31, 32):
        merged, ops = _random_session(seed, n_replicas=3, steps=80)
        payload = json_codec.dumps(op_mod.from_list(ops))
        assert_same(payload)


@pytest.mark.parametrize("bad", [
    '{"op":"add","path":[0]}',                   # missing ts/val
    '{"op":"add","ts":1,"val":1}',               # missing path
    '{"op":"add","path":[0],"ts":1.5,"val":1}',  # float ts
    '{"op":"add","path":[0.5],"ts":1,"val":1}',  # float path elem
    '{"op":"add","path":[0],"ts":true,"val":1}',  # bool ts
    '{"op":"del"}',                              # missing path
    '{"op":"batch"}',                            # missing ops
    '{"op":"batch","ops":{}}',                   # ops not a list
    '{"path":[0],"ts":1,"val":1}',               # missing tag
    '{"op":"add","path":[0],"ts":4611686018427387905,"val":1}',  # >= 2^62
    'noise',
    '{"op":"add","path":[0],"ts":1,"val":1} trailing',
])
def test_rejections_match_python(bad):
    with pytest.raises(ValueError):
        native.parse_pack(bad)
    with pytest.raises(ValueError):
        packed.pack(json_codec.loads(bad))


def test_merge_from_native_pack_matches_oracle():
    from crdt_graph_tpu.ops import merge, view
    from test_merge_kernel import _random_session
    merged, ops = _random_session(33, n_replicas=4, steps=100)
    payload = json_codec.dumps(op_mod.from_list(ops))
    p = packed.pack_json(payload)
    t = view.to_host(merge.materialize(p.arrays()))
    assert view.visible_values(t, p.values) == merged.visible_values()


def test_big_int_and_unicode_roundtrip():
    # int64 extremes inside protocol range and astral-plane text
    ts = (2**30 - 1) * 2**32 + 7
    payload = json.dumps({"op": "add", "path": [0], "ts": ts,
                          "val": "\U0001F680 ß"})
    got = assert_same(payload)
    assert got.ts[0] == ts


def test_duplicate_keys_last_wins_like_python():
    # duplicate "ops": only the last list contributes (json.loads semantics)
    assert_same('{"op":"batch","ops":['
                '{"op":"add","path":[0],"ts":1,"val":"A"}],'
                '"ops":[{"op":"add","path":[0],"ts":2,"val":"B"}]}')
    # duplicate "ts"/"val": last wins
    assert_same('{"op":"add","path":[0],"ts":1,"ts":2,'
                '"val":"x","val":"y"}')
    # tag flips after fields: final tag governs
    assert_same('{"op":"del","path":[3],"op":"add","ts":9,"val":1}'
                .replace('"op":"add","ts"', '"op":"add","path":[0],"ts"'))


@pytest.mark.parametrize("bad", [
    '{"op":"del","path":[01]}',                     # leading zero
    '{"op":"add","path":[0],"ts":1,"val":1.}',      # trailing dot
    '{"op":"add","path":[0],"ts":1,"val":.5}',      # leading dot
    '{"op":"add","path":[0],"ts":1,"val":1.0e}',    # empty exponent
    '{"op":"add","path":[0],"ts":1,"val":00}',      # leading zero int
    '{"op":"add","path":[0],"ts":01,"val":1}',      # leading zero ts
])
def test_number_grammar_rejections_match_python(bad):
    with pytest.raises(ValueError):
        native.parse_pack(bad)
    with pytest.raises(ValueError):
        packed.pack(json_codec.loads(bad))


def test_error_offsets_are_real():
    with pytest.raises(ValueError, match="offset (?!0\\b)"):
        native.parse_pack('{"op":"add","path":[0],"ts":1,"val":1} x')


def test_semantic_checks_wait_for_final_tag():
    # unknown tags tolerate arbitrary field contents (json.loads parses,
    # decode ignores) — native must accept these too
    got = assert_same('{"op":"mystery","ts":1.5,"path":{"x":1}}')
    assert got.num_ops == 0
    # del ignores a float ts field entirely
    assert_same('{"op":"del","path":[0],"ts":2.5}'
                .replace('"path":[0]', '"path":[7]')
                .replace('[7]', '[0]'))


def test_python_json_extensions_accepted():
    # json.loads accepts NaN/Infinity/-Infinity and lone surrogates in
    # value payloads; parity demands the native parser does too
    got = assert_same('{"op":"add","path":[0],"ts":1,'
                      '"val":[Infinity,-Infinity]}')
    assert got.values == [[float("inf"), float("-inf")]]
    got = native.parse_pack('{"op":"add","path":[0],"ts":1,"val":NaN}')
    assert math.isnan(got.values[0])
    assert_same('{"op":"add","path":[0],"ts":1,"val":"\\ud800"}')
    assert_same('{"op":"add","path":[0],"ts":1,"val":"\\ud800\\udc00x"}')


def test_deep_nesting_rejected_not_segfault():
    """Untrusted wire input with pathological nesting must fail the parse
    cleanly (Python's json raises RecursionError; the native parser raises
    ValueError) — never overflow the C stack.  Guards both the value_py
    payload path and the skip_value unknown-field path."""
    deep = "[" * 100_000 + "]" * 100_000
    for doc in ('{"op":"add","path":[0],"ts":1,"val":' + deep + "}",
                '{"op":"add","path":[0],"ts":1,"val":1,"x":' + deep + "}"):
        with pytest.raises(ValueError, match="nesting too deep"):
            native.parse_pack(doc)
    # sane nesting (well under the 512 cap) still parses
    ok = ('{"op":"add","path":[0],"ts":1,"val":'
          + "[" * 100 + "1" + "]" * 100 + "}")
    assert native.parse_pack(ok).num_ops == 1


# ===== egress: encode_pack (the parse_pack mirror) =======================

def _pyside_dumps(ops):
    return json_codec.dumps(op_mod.from_list(tuple(ops)))


def encode_both(ops, max_depth=16):
    p = packed.pack(ops, max_depth=max_depth)
    return native.encode_pack(p).decode(), _pyside_dumps(ops)


def test_encode_golden_fixtures_byte_exact():
    ops = [crdt.Add(2, (0, 1), "a"), crdt.Delete((1, 2, 3)),
           crdt.Add(1, (0,), "x")]
    got, want = encode_both(ops)
    assert got == want


def test_encode_value_payload_types_byte_exact():
    vals = ["str", "", "unié中😀", 0, -5, 2**40, 2**80, -2**90, 1.5,
            -0.25, -0.0, 1e10, 1e-12, float("inf"), float("-inf"),
            float("nan"), True, False, None, [1, [2, "x"], (3, 4)],
            {"k": {"n": None, "l": [1.0]}, "é": "☃"},
            {1: "a", 2.5: "b", True: "c", None: "d"},
            "esc\"\\\n\t/control\x01\x1f"]
    ops = [crdt.Add(i + 1, (i,), v) for i, v in enumerate(vals)]
    got, want = encode_both(ops)
    assert got == want
    # NaN breaks == on reparse; compare through repr of parsed trees
    assert repr(json.loads(got)) == repr(json.loads(want))


def test_encode_lone_surrogates_round_trip():
    # the parser admits lone surrogates (like json.loads); the encoder
    # must re-emit their \uD8xx escapes exactly like json.dumps
    payload = '{"op":"add","path":[0],"ts":1,"val":"hi\\ud800there"}'
    p = native.parse_pack(payload)
    assert native.encode_pack(p).decode() == \
        _pyside_dumps([json_codec.loads(payload)])


def test_encode_random_sessions_byte_exact():
    rng = random.Random(7)
    for seed in range(3):
        ops = []
        t = 1
        anchors = [0]
        for _ in range(300):
            if ops and rng.random() < 0.2:
                ops.append(crdt.Delete((rng.choice(anchors[1:] or [1]),)))
            else:
                a = rng.choice(anchors)
                ops.append(crdt.Add(t, (a,), rng.choice(
                    ["v%d" % t, t * 1.5, None, {"n": t}, ["l", t]])))
                anchors.append(t)
                t += 1
        got, want = encode_both(ops)
        assert got == want


def test_encode_start_slices_suffix():
    ops = [crdt.Add(i + 1, (i,), "v%d" % i) for i in range(10)]
    p = packed.pack(ops)
    got = native.encode_pack(p, start=6).decode()
    assert got == _pyside_dumps(ops[6:])


def test_encode_skips_padding_rows():
    ops = [crdt.Add(1, (0,), "a"), crdt.Add(2, (1,), "b")]
    p = packed.pack(ops, capacity=16)      # padded to 16 rows
    # num_ops bounds the scan, but even a raw full-capacity call must
    # skip KIND_PAD rows
    got = native.encode_pack(p).decode()
    assert got == _pyside_dumps(ops)


def test_encode_rejects_unencodable_value():
    class Opaque:
        pass
    p = packed.pack([crdt.Add(1, (0,), Opaque())])
    with pytest.raises(ValueError):
        native.encode_pack(p)


def test_parse_encode_round_trip_is_identity():
    payload = ('{"op":"batch","ops":['
               '{"op":"add","path":[0],"ts":1,"val":{"rich":[1,2.5,null]}},'
               '{"op":"add","path":[1],"ts":2,"val":"x"},'
               '{"op":"del","path":[1]}]}')
    p = native.parse_pack(payload)
    assert native.encode_pack(p).decode() == payload
