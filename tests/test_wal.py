"""Crash-durable acked writes (ISSUE 9): the group-commit WAL under
the cascade op-log, recovery-to-serving, and the crash-point matrix.

The contract under test: an acknowledged write survives a kill at ANY
point — the WAL fsyncs before the ack is released, spill/fold
manifests keep the tiers reopenable, recovery replays the WAL tail
through the ordinary apply path, and the recovered log's windows stay
byte-identical to the untiered ``packed_since_window`` contract.
Corruption is typed: a torn final record is tolerated and counted, a
mid-log checksum flip raises ``WalError``, and a full disk sheds
writes as honest 503s while the server keeps serving.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import engine
from crdt_graph_tpu import wal as wal_mod
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.codec import packed as packed_mod
from crdt_graph_tpu.core.operation import Add, Batch, Delete
from crdt_graph_tpu.obs import flight as flight_mod
from crdt_graph_tpu.obs import oracle as oracle_mod
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.serve import (SchedulerStopped, ServingEngine,
                                  WalUnavailable)

OFF = 2**32


def ts(r, c):
    return r * OFF + c


def chain_ops(r, n, start=1):
    out = []
    prev = ts(r, start - 1) if start > 1 else 0
    for c in range(start, start + n):
        out.append(Add(ts(r, c), (prev,), f"v{r}.{c}"))
        prev = ts(r, c)
    return out


def _submit(eng, doc, ops):
    return eng.submit(doc, json_codec.dumps(Batch(tuple(ops))))


def _windows_match_untiered(tree, sinces=(0,), limits=(0, 7)):
    """The recovered log's window answers vs engine.packed_since_window
    over its own full packing — the tiered/untiered byte contract."""
    view = tree.log_view()
    full = view.to_packed()
    for since in sinces:
        for limit in limits:
            if limit:
                b1, m1 = view.window(since, limit)
                b2, m2 = engine.packed_since_window(full, since, limit)
                assert b1 == b2 and m1 == m2, (since, limit)
            else:
                assert view.since_bytes(since) == \
                    engine.packed_since_bytes(full, since), since


# -- raw WAL format + corruption taxonomy ---------------------------------


def _raw_wal(tmp_path, n_records=3):
    w = wal_mod.Wal(str(tmp_path / "wal.log"))
    pos = 0
    for k in range(n_records):
        ops = chain_ops(1, 5, start=1 + 5 * k)
        pos += 5
        w.append(packed_mod.pack(ops), pos)
    w.sync()
    w.close()
    return w


def test_wal_scan_roundtrip_and_truncate_below(tmp_path):
    w = _raw_wal(tmp_path, n_records=3)
    records, torn, good = wal_mod.scan(w.path)
    assert [r[1] for r in records] == [5, 10, 15] and torn == 0
    # payloads decode back to the exact ops appended
    _, p = wal_mod._decode_payload(records[1][2])
    assert packed_mod.unpack_rows(p, 0, p.num_ops) == \
        chain_ops(1, 5, start=6)
    # truncation drops fully-covered records, keeps straddlers
    w2 = wal_mod.Wal(w.path)
    assert w2.truncate_below(10) == 2
    records, torn, _ = wal_mod.scan(w.path)
    assert [r[1] for r in records] == [15] and torn == 0
    # idempotent; nothing below the watermark left
    assert w2.truncate_below(10) == 0
    w2.close()


def test_wal_torn_final_record_tolerated_and_counted(tmp_path):
    w = _raw_wal(tmp_path, n_records=2)
    data = open(w.path, "rb").read()
    for cut in (7, 1, len(data) - wal_mod.scan(w.path)[0][1][0] - 3):
        torn_path = str(tmp_path / f"torn{cut}.log")
        with open(torn_path, "wb") as f:
            f.write(data[:-cut])
        records, torn, good = wal_mod.scan(torn_path)
        assert torn == 1 and len(records) == 1, cut
    # a crc flip on the FINAL record is a torn tail too (partial
    # payload write), not mid-log corruption
    flipped = bytearray(data)
    flipped[-3] ^= 0xFF
    flip_path = str(tmp_path / "flip-last.log")
    with open(flip_path, "wb") as f:
        f.write(bytes(flipped))
    records, torn, _ = wal_mod.scan(flip_path)
    assert torn == 1 and len(records) == 1


def test_wal_midlog_corruption_raises_typed(tmp_path):
    w = _raw_wal(tmp_path, n_records=3)
    data = bytearray(open(w.path, "rb").read())
    records, _, _ = wal_mod.scan(w.path)
    # flip a byte INSIDE the first record's payload: valid records
    # continue past it, so this must be WalError, never a partial scan
    data[records[0][0] + 12] ^= 0xFF
    with open(w.path, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(wal_mod.WalError):
        wal_mod.scan(w.path)
    # and recovery through replay_into refuses too
    t = engine.init(0)
    with pytest.raises(wal_mod.WalError):
        wal_mod.Wal(w.path).replay_into(t)
    # bad magic is typed as well
    with open(w.path, "wb") as f:
        f.write(b"NOTAWAL!" + bytes(16))
    with pytest.raises(wal_mod.WalError):
        wal_mod.scan(w.path)


def test_wal_duplicate_replay_idempotent_after_crash_mid_truncate(
        tmp_path):
    """A crash between the spill's manifest write and the WAL truncate
    leaves records the tiers already cover — replay must absorb them
    through apply dedup, bit-identically."""
    w = _raw_wal(tmp_path, n_records=3)
    ref = engine.init(0)
    ref.apply(Batch(tuple(chain_ops(1, 15))))

    # replay everything into a fresh tree, then replay the SAME file
    # again (the mid-truncate shape: every record is a duplicate)
    t = engine.init(0)
    stats = wal_mod.Wal(w.path).replay_into(t)
    assert stats["ops"] == 15 and t.log_length == 15
    again = wal_mod.Wal(w.path).replay_into(t)
    assert again["applied"] == 0, "duplicate replay must absorb"
    assert t.log_length == 15
    assert t.visible_values() == ref.visible_values()
    _windows_match_untiered(t, sinces=(0, ts(1, 1), ts(1, 9)))


def test_wal_records_deletes_and_replays_them(tmp_path):
    """Deletes ride WAL records like adds (the all-delete tail is the
    PR-6 window bug class — it must survive a crash too)."""
    ops = chain_ops(1, 8) + [Delete((ts(1, c),)) for c in (2, 5, 8)]
    ref = engine.init(0)
    ref.apply(Batch(tuple(ops)))
    w = wal_mod.Wal(str(tmp_path / "wal.log"))
    w.append(packed_mod.pack(ops), len(ops))
    w.sync()
    w.close()
    t = engine.init(0)
    wal_mod.Wal(w.path).replay_into(t)
    assert t.visible_values() == ref.visible_values()
    assert t.log_length == ref.log_length
    _windows_match_untiered(t, sinces=(0, ts(1, 3)), limits=(0, 4))


def test_wal_failed_append_repairs_to_record_boundary(tmp_path):
    """A failed append can leave partial bytes on disk (large records
    flush incrementally before the OSError).  The repair truncates
    back to the last good record boundary, so a LATER successful
    append never buries the garbage mid-log — a torn tail must stay a
    torn tail, never become fatal mid-log corruption at recovery."""
    w = _raw_wal(tmp_path, n_records=2)
    good = os.path.getsize(w.path)
    # the partially-flushed failed append's residue
    with open(w.path, "ab") as f:
        f.write(b"\x99" * 11)
    w2 = wal_mod.Wal(w.path)
    w2._size = good                  # what Wal tracked pre-failure
    w2._repair_locked(good)
    assert w2.repairs == 1
    w2.append(packed_mod.pack(chain_ops(1, 3, start=11)), 13)
    w2.sync()
    w2.close()
    records, torn, _ = wal_mod.scan(w.path)
    assert torn == 0 and len(records) == 3
    # and the appended record decodes
    _, p = wal_mod._decode_payload(records[-1][2])
    assert packed_mod.unpack_rows(p, 0, p.num_ops) == \
        chain_ops(1, 3, start=11)


# -- the serving path: durability, group commit, shedding -----------------


def _durable_engine(ddir, wal_sync="batch", **kw):
    kw.setdefault("oplog_hot_ops", 8)
    kw.setdefault("flight", flight_mod.FlightRecorder())
    return ServingEngine(durable_dir=str(ddir), wal_sync=wal_sync, **kw)


def test_recovery_to_serving_windows_epoch_and_metrics(tmp_path):
    eng = _durable_engine(tmp_path / "dur")
    ops = chain_ops(1, 30)
    for i in range(0, 30, 5):
        ok, _ = _submit(eng, "docA", ops[i:i + 5])
        assert ok
    doc = eng.get("docA")
    vals = doc.snapshot()
    m = doc.metrics()
    assert m["durable"] and m["epoch"] == 1 and m["wal"]["fsyncs"] >= 1
    assert eng.flush(20)
    # abandon WITHOUT close: everything written is on disk/page cache,
    # exactly what a kill leaves behind
    eng2 = _durable_engine(tmp_path / "dur")
    doc2 = eng2.get("docA", create=False)
    assert doc2 is not None, "recovery scan must reopen the doc"
    assert doc2.recovered and doc2.epoch == 2
    assert doc2.snapshot() == vals
    # recovered hot tail came through the WAL, tiers through the
    # manifest; windows stay byte-identical to untiered at the seams
    _windows_match_untiered(doc2.tree,
                            sinces=(0, ts(1, 1), ts(1, 17), ts(1, 28)),
                            limits=(0, 6))
    # steady-state WAL stayed O(hot tail): spills truncated it
    assert doc2.wal.telemetry()["size_bytes"] < 16384
    eng2.close()
    eng.close()


def test_group_commit_one_fsync_covers_coalesced_tickets(tmp_path):
    """batch mode: N tickets fused into one commit share ONE WAL
    record and ONE fsync — the group-commit amortization."""
    eng = _durable_engine(tmp_path / "dur", oplog_hot_ops=4096)
    eng.scheduler.pause()
    n = 6
    results = []

    def writer(rid):
        ops = [Add(ts(rid, 1), (0,), f"w{rid}")]
        results.append(_submit(eng, "gdoc", ops))

    threads = [threading.Thread(target=writer, args=(rid,),
                                daemon=True) for rid in range(2, 2 + n)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        d = eng.get("gdoc", create=False)
        if d is not None and len(d.queue) == n:
            break
        time.sleep(0.005)
    eng.scheduler.resume()
    for t in threads:
        t.join(30)
    assert len(results) == n and all(ok for ok, _ in results)
    doc = eng.get("gdoc")
    w = doc.wal.telemetry()
    assert w["appends"] == 1, w
    assert w["fsyncs"] == 1, w
    # the fsync is billed into the commit's flight stages
    rec = [r for r in eng.flight.records()
           if r.doc_id == "gdoc" and r.outcome == "committed"][-1]
    assert rec.coalesce_width == n
    assert "wal_fsync" in rec.stages_ms and "wal_append" in rec.stages_ms
    eng.close()


def test_commit_mode_fsyncs_every_commit(tmp_path):
    eng = _durable_engine(tmp_path / "dur", wal_sync="commit")
    for i in range(3):
        ok, _ = _submit(eng, "cdoc", chain_ops(1, 4, start=1 + 4 * i))
        assert ok
    w = eng.get("cdoc").wal.telemetry()
    assert w["fsyncs"] >= 3 and w["appends"] == 3
    eng.close()


def test_disk_full_sheds_503_and_server_stays_up(tmp_path):
    """ENOSPC on the WAL path: the write is shed with the typed 503
    mapping, the merged ops stay un-acked, reads keep serving, and the
    disk recovering restores the write path."""
    eng = _durable_engine(tmp_path / "dur")
    ok, _ = _submit(eng, "ddoc", chain_ops(1, 5))
    assert ok
    doc = eng.get("ddoc")
    vals_before = doc.snapshot()
    real_sync = doc.wal.sync
    real_sync_begin = doc.wal.sync_begin

    def enospc(*_a, **_k):
        raise OSError(28, "No space left on device")

    # fail both WAL durability seams: sync() for the single/threaded
    # lanes, sync_begin() for completion-driven backends
    doc.wal.sync = enospc
    doc.wal.sync_begin = enospc
    try:
        with pytest.raises(WalUnavailable):
            _submit(eng, "ddoc", chain_ops(1, 5, start=6))
    finally:
        doc.wal.sync = real_sync
        doc.wal.sync_begin = real_sync_begin
    # server alive: reads serve the last PUBLISHED snapshot, the
    # scheduler thread survived, the shed is counted, and the merge
    # was ROLLED BACK (the log must never hold ops in neither the
    # tiers nor the WAL)
    assert doc.snapshot() == vals_before
    assert doc.tree.log_length == 5
    assert eng.scheduler.is_alive()
    assert eng.counters.snapshot().get("wal_shed_commits", 0) >= 1
    # WalUnavailable maps through the SchedulerStopped → 503 contract
    assert issubclass(WalUnavailable, SchedulerStopped)
    # disk back: writes ack again (the shed delta's ops were merged
    # un-acked; the retry's duplicates absorb)
    ok, _ = _submit(eng, "ddoc", chain_ops(1, 5, start=6))
    assert ok
    assert len(eng.get("ddoc").snapshot()) == 10
    eng.close()


# -- the crash-point matrix (deterministic, in-process) --------------------


# sites where the kill legitimately races the ack: the work that
# crashes runs AFTER ticket resolution.  Serialized: only the matz
# export runs post-ack.  Pipelined: spill/fold/manifest/matz all
# moved to the background maintenance worker, which by construction
# only ever touches fsync-durable (hence ack-resolved) rows.
_POST_ACK_SITES = {
    False: {"mid-matz-write"},
    True: {"mid-matz-write", "mid-spill", "mid-fold",
           "mid-manifest-write", "mid-bg-fold"},
}


def _matrix_params():
    """The full site × {perdoc, shared} × {serial, pipelined} matrix,
    with the ISSUE-12 pipelined expansion's duplicate coverage
    slow-marked (ISSUE 13 tier-1 wall-time satellite): tier-1 keeps
    every SERIAL combo (the pre-expansion coverage), one pipelined
    representative per PIPELINE-ONLY site (the sites that exist
    nowhere else), and one {shared}×{pipelined} representative — the
    remaining pipelined duplicates (same site already proven serial,
    same code path already proven perdoc) run in the slow lane."""
    tier1_pipelined = {(s, False) for s in wal_mod.PIPELINE_ONLY_SITES}
    tier1_pipelined.add((wal_mod.CRASH_SITES[0], True))   # shared rep
    out = []
    for site in wal_mod.CRASH_SITES:
        for shared in (False, True):
            for pipeline in (False, True):
                marks = ()
                if pipeline and (site, shared) not in tier1_pipelined:
                    marks = (pytest.mark.slow,)
                out.append(pytest.param(
                    site, shared, pipeline, marks=marks,
                    id=f"{site}-{'shared' if shared else 'perdoc'}-"
                       f"{'pipelined' if pipeline else 'serial'}"))
    return out


@pytest.mark.parametrize("site,shared,pipeline", _matrix_params())
def test_crash_point_matrix_zero_acked_loss(tmp_path, site, shared,
                                            pipeline, monkeypatch):
    """One kill site per run — × {per-doc, shared} WAL streams × the
    {serialized, pipelined} commit paths: acked writes survive, the
    recovered doc serves immediately at a bumped epoch, windows stay
    byte-identical, and the oracle's convergence check reports zero
    violations over the recovered serving surface.  In-process kill:
    the CrashPoint BaseException stops the thread that hit the site
    exactly there (nothing after it runs — no fsync, no publish, no
    ack on that path), the other pipeline threads die at their next
    check, and everything already ``write()``-en survives in the page
    cache, which is precisely the post-SIGKILL disk state."""
    if not pipeline and site in wal_mod.PIPELINE_ONLY_SITES:
        pytest.skip("site only exists on the pipelined commit path")
    monkeypatch.setenv("GRAFT_OPLOG_GC_SEGS", "1")
    # a tiny materialization cadence so the armed commit also crosses
    # the matz refresh (the mid-matz-write site must actually fire,
    # and every OTHER site now runs with artifact writes in play too)
    monkeypatch.setenv("GRAFT_MATZ_TAIL_OPS", "8")
    ddir = tmp_path / "dur"
    eng = _durable_engine(ddir, submit_timeout_s=2.0,
                          wal_shared=shared, pipeline=pipeline)
    acked = []
    ops = chain_ops(1, 80)
    for i in range(0, 15, 5):
        ok, _ = _submit(eng, "doc", ops[i:i + 5])
        assert ok
        acked.extend(ops[i:i + 5])
    # barrier over the pipeline lanes so the setup writes' background
    # spills/exports are done BEFORE the site arms (the doomed write
    # below must be the one that trips it)
    assert eng.flush(30)
    monkeypatch.setenv("GRAFT_CRASH_POINT", site)
    # a 20-leaf commit from a 15-op log with hot_ops=8 forces spill →
    # fold (gc_min_segs=1) → manifest in the armed commit, so every
    # site fires on this one write
    crashed = {}

    def doomed():
        try:
            crashed["ack"] = _submit(eng, "doc", ops[15:35])
        except SchedulerStopped:
            crashed["ack"] = None

    th = threading.Thread(target=doomed, daemon=True)
    th.start()
    eng.scheduler.join(30)
    assert not eng.scheduler.is_alive(), \
        f"site {site} never fired (scheduler survived)"
    th.join(10)
    if site in _POST_ACK_SITES[pipeline]:
        # post-ack work: the doomed commit's ack legitimately races
        # the crash — and if it landed, it is already fsynced and
        # must survive recovery like any other acked write
        if crashed.get("ack") and crashed["ack"][0]:
            acked.extend(ops[15:35])
    else:
        assert crashed.get("ack") is None, \
            f"site {site}: a write acked AFTER the crash point"
    monkeypatch.delenv("GRAFT_CRASH_POINT")
    # recover from disk (the wounded engine is abandoned, un-closed)
    eng2 = _durable_engine(ddir, wal_shared=shared)
    doc2 = eng2.get("doc", create=False)
    assert doc2 is not None and doc2.epoch == 2
    vals = set(doc2.snapshot())
    missing = [op.value for op in acked if op.value not in vals]
    assert not missing, f"site {site} lost acked writes: {missing}"
    _windows_match_untiered(doc2.tree,
                            sinces=(0, ts(1, 3), ts(1, 13)),
                            limits=(0, 6))
    # oracle contract over the recovered serving surface: two
    # sessions' final reads of the SAME published snapshot converge
    oracle = oracle_mod.SessionOracle()
    snap = doc2.read_view()
    for sess in ("s-a", "s-b"):
        oracle.observe_final_read(sess, "doc", snap.seq,
                                  snap.fingerprint())
        oracle.observe_replica_state("doc", f"n0.{doc2.epoch}",
                                     snap.state_fingerprint())
    assert oracle.finalize() == []
    assert oracle.stats()["violations_total"] == 0
    # serving-ready: the recovered doc accepts writes at once (an
    # independent chain — the doomed batch was never acked, so a
    # write anchored on it would be a legitimate 409)
    ok, _ = _submit(eng2, "doc", chain_ops(9, 3))
    assert ok
    eng2.close()


# -- persisted materialization (ISSUE 11) -----------------------------------


def test_matz_corruption_taxonomy(tmp_path):
    """The artifact's failure modes, each the SAFE way: a stale
    artifact dup-absorbs through tail replay, a crc-flipped or
    truncated or missing artifact falls back to the full first merge
    with a typed MatzWarning and a counted fallback — never wrong
    data, never an exception to the reader."""
    import glob
    import warnings

    from crdt_graph_tpu.serve import snapshot as snapshot_mod

    def fresh(dirname, n=900):
        t = engine.init(0)
        t.enable_log_tiering(str(tmp_path / dirname), hot_ops=64,
                             gc_min_segs=2)
        t.apply_packed(packed_mod.pack(chain_ops(1, n), max_depth=4))
        return t

    ref = engine.init(0)
    ref.apply(Batch(tuple(chain_ops(1, 900))))
    want_vals = ref.visible_values()
    want_fp = snapshot_mod.derive("d", 0, ref).state_fingerprint()

    # (a) healthy: first read comes off the artifact, zero fallbacks
    t = fresh("ok")
    t.checkpoint_tiered(str(tmp_path / "ok"))
    r = engine.TpuTree.restore_tiered(str(tmp_path / "ok"))
    assert r.visible_values() == want_vals
    assert r.matz_stats == {"writes": 0, "loads": 1, "fallbacks": 0,
                            "tail_replayed": 0}
    assert snapshot_mod.derive("d", 0, r).state_fingerprint() == want_fp

    # (b) stale: ops landed after the artifact — tail replay absorbs
    t = fresh("stale", n=700)
    assert t.write_matz()
    t.apply(Batch(tuple(chain_ops(1, 200, start=701))))
    t.checkpoint_tiered(str(tmp_path / "stale"), write_matz=False)
    r = engine.TpuTree.restore_tiered(str(tmp_path / "stale"))
    assert r.visible_values() == want_vals
    assert r.matz_stats["loads"] == 1
    assert r.matz_stats["tail_replayed"] == 200
    assert snapshot_mod.derive("d", 0, r).state_fingerprint() == want_fp

    # (c) crc-flip / truncation / missing: typed fallback, right data
    for mode in ("flip", "trunc", "missing"):
        d = f"bad-{mode}"
        t = fresh(d)
        t.checkpoint_tiered(str(tmp_path / d))
        victim = glob.glob(str(tmp_path / d / "matz-*.npz"))[0]
        blob = open(victim, "rb").read()
        if mode == "flip":
            flipped = bytearray(blob)
            flipped[len(flipped) // 2] ^= 0xFF
            open(victim, "wb").write(bytes(flipped))
        elif mode == "trunc":
            open(victim, "wb").write(blob[: len(blob) // 3])
        else:
            os.remove(victim)
        r = engine.TpuTree.restore_tiered(str(tmp_path / d))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            vals = r.visible_values()
        assert any(issubclass(x.category, engine.MatzWarning)
                   for x in w), (mode, [x.category for x in w])
        assert r.matz_stats["fallbacks"] == 1, mode
        assert vals == want_vals, mode
        assert snapshot_mod.derive("d", 0, r).state_fingerprint() \
            == want_fp, mode


def test_matz_overcovering_entry_degrades_never_bricks(tmp_path):
    """A rollback truncate can shrink the TIERED extent below a matz
    artifact's coverage while the entry legitimately survives (the cut
    was above it).  The mid-flight manifest then carries
    matz.len > length — restore must treat that as the lazy fallback
    case (stale-or-unusable artifact, typed warning at worst), never a
    CheckpointError that bricks the document."""
    import warnings

    d = str(tmp_path / "oc")
    t = engine.init(0)
    t.enable_log_tiering(d, hot_ops=32, gc_min_segs=1, durable=True,
                         base_chunk_ops=64)
    t._log.set_durable_hooks(t.manifest_meta, None)
    t.apply_packed(packed_mod.pack(chain_ops(1, 150), max_depth=4))
    assert t.write_matz()
    assert t._log.matz_entry["len"] == 150
    # enough new ops that the next fold MERGES the trailing partial
    # chunk ([128,150)) with fresh segments into one chunk straddling
    # the artifact's coverage boundary
    t.apply_packed(packed_mod.pack(chain_ops(1, 70, start=151),
                                   max_depth=4))
    t._log.maybe_spill()
    assert any(cs.start < 150 < cs.start + cs.length
               for cs in t._log._bases), \
        [(cs.start, cs.length) for cs in t._log._bases]
    # rollback-shaped cut ABOVE the artifact's coverage: the entry
    # survives, but the straddling chunk's prefix re-hots and the
    # durable manifest's length drops below matz.len
    t._log.truncate(160)
    assert t._log.matz_entry is not None
    extent = t._log.tiered_extent
    # the brick only reproduces when the manifest length undercuts the
    # coverage; the chunk layout guarantees it here
    assert extent < 150, extent
    r = engine.TpuTree.restore_tiered(d)     # must not raise
    assert r.log_length == extent
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        vals = r.visible_values()
    ref = engine.init(0)
    ref.apply(Batch(tuple(chain_ops(1, extent))))
    assert vals == ref.visible_values()


def test_recovered_doc_first_read_from_matz_flight_and_prom(
        tmp_path, monkeypatch):
    """Serving-side cold-path collapse: a durable doc refreshes its
    artifact at the GRAFT_MATZ_TAIL_OPS cadence; a restarted engine's
    first read loads it (no full merge), commits stamp ``matz_hit``
    into their flight records, and the crdt_matz_* families render
    under the strict prom contract."""
    monkeypatch.setenv("GRAFT_MATZ_TAIL_OPS", "16")
    ddir = tmp_path / "dur"
    eng = _durable_engine(ddir)
    for i in range(4):
        ok, _ = _submit(eng, "mdoc", chain_ops(1, 12, start=1 + 12 * i))
        assert ok
    doc = eng.get("mdoc")
    vals = doc.snapshot()
    # pipelined: the artifact export rides the maintenance worker —
    # flush() barriers over it (due pickups included) by contract
    assert eng.flush(30)
    assert doc.tree.matz_stats["writes"] >= 1
    assert doc.tree._log.matz_entry is not None
    # abandon un-closed; recover
    eng2 = _durable_engine(ddir)
    doc2 = eng2.get("mdoc", create=False)
    assert doc2 is not None and doc2.recovered
    assert doc2.snapshot() == vals
    assert doc2.tree.matz_stats["loads"] == 1, doc2.tree.matz_stats
    assert doc2.tree.matz_stats["fallbacks"] == 0
    m = doc2.metrics()["matz"]
    assert m["loads"] == 1 and m["len"] > 0
    # a post-recovery commit's flight record stamps the hit
    ok, _ = _submit(eng2, "mdoc", chain_ops(1, 3, start=49))
    assert ok
    rec = [r for r in eng2.flight.records()
           if r.doc_id == "mdoc" and r.outcome == "committed"][-1]
    assert rec.matz_hit is True
    fams = prom_mod.parse_text(eng2.render_prom())
    for fam in ("crdt_matz_writes_total", "crdt_matz_loads_total",
                "crdt_matz_fallbacks_total",
                "crdt_matz_tail_replayed_total",
                "crdt_matz_covered_ops",
                "crdt_oplog_cache_evictions_total"):
        assert fam in fams, fam
    loads = [v for _, lbl, v in
             fams["crdt_matz_loads_total"]["samples"]
             if lbl["doc"] == "mdoc"]
    assert loads == [1.0]
    eng2.close()
    eng.close()


# -- shared WAL stream (ISSUE 11) --------------------------------------------


def test_shared_wal_one_fsync_covers_whole_round(tmp_path):
    """The amortization headline, deterministically: N documents'
    writes staged under a paused scheduler resolve in ONE round with
    ONE shared fsync covering all of them (per-doc mode pays N), at
    the same fsync-before-ack point."""
    n_docs = 6
    eng = _durable_engine(tmp_path / "dur", wal_shared=True,
                          oplog_hot_ops=4096)
    assert eng.shared_wal is not None
    eng.scheduler.pause()
    results = []

    def writer(k):
        ops = [Add(ts(2 + k, 1), (0,), f"w{k}")]
        results.append(_submit(eng, f"sdoc{k}", ops))

    threads = [threading.Thread(target=writer, args=(k,), daemon=True)
               for k in range(n_docs)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        docs = [eng.get(f"sdoc{k}", create=False)
                for k in range(n_docs)]
        if all(d is not None and len(d.queue) == 1 for d in docs):
            break
        time.sleep(0.005)
    fsyncs0 = eng.shared_wal.telemetry()["fsyncs"]
    eng.scheduler.resume()
    for t in threads:
        t.join(30)
    assert len(results) == n_docs and all(ok for ok, _ in results)
    st = eng.shared_wal.telemetry()
    assert st["fsyncs"] - fsyncs0 == 1, st
    assert st["appends"] >= n_docs
    # the covered-docs histogram saw the whole round at once
    cov = st["covered_docs"]
    assert cov is not None and cov["count"] >= 1
    assert eng.counters.snapshot().get("wal_shared_covered_docs", 0) \
        >= n_docs
    # every doc's commit billed the one fsync into its stages
    for k in range(n_docs):
        rec = [r for r in eng.flight.records()
               if r.doc_id == f"sdoc{k}" and r.outcome == "committed"]
        assert rec and "wal_fsync" in rec[-1].stages_ms
    fams = prom_mod.parse_text(eng.render_prom())
    for fam in ("crdt_wal_shared_fsyncs_total",
                "crdt_wal_shared_appends_total",
                "crdt_wal_shared_covered_docs",
                "crdt_wal_shared_fsync_ms",
                "crdt_wal_shared_size_bytes"):
        assert fam in fams, fam
    eng.close()


def test_shared_wal_failed_sync_after_repair_reopen_drops_tail(
        tmp_path):
    """A repair mid-round closes the handle; the reopen must NOT
    promote the still-unsynced earlier record to durable — when the
    round's fsync then fails, the whole unsynced tail (whose commits
    are all being shed) must drop, or recovery would resurrect a
    write its client was told failed."""
    sh = wal_mod.SharedWal(str(tmp_path / "s.log"))
    sh.append("A", packed_mod.pack(chain_ops(1, 3)), 3)   # unsynced
    # doc B's append dies mid-write: repair truncates the partial
    # bytes and closes the handle
    sh._repair_locked(sh._size)
    # doc C's append reopens the file (A's record still unsynced)
    sh.append("C", packed_mod.pack(chain_ops(2, 3)), 3)
    real = os.fsync

    def eio(fd):
        raise OSError(5, "Input/output error")

    os.fsync = eio
    try:
        with pytest.raises(OSError):
            sh.sync(covered_docs=2)
    finally:
        os.fsync = real
    sh.close()
    # every record in the failed round was shed: none may survive
    records, torn, _ = wal_mod.scan_shared(str(tmp_path / "s.log"))
    assert [(r[1], r[2]) for r in records] == [], records


def test_wal_mode_flip_across_restart_refuses_loudly(tmp_path):
    """Restarting a durable dir under the OTHER WAL format must fail
    with a typed WalError, not silently drop the previous format's
    fsync-acked tail (only the writing format can replay it)."""
    ddir = tmp_path / "dur"
    # per-doc incarnation leaves an un-truncated wal.log tail
    eng = _durable_engine(ddir, oplog_hot_ops=4096)
    ok, _ = _submit(eng, "flip", chain_ops(1, 6))
    assert ok
    assert eng.flush(20)
    with pytest.raises(wal_mod.WalError, match="per-doc WAL"):
        _durable_engine(ddir, wal_shared=True)
    # the honest restart (same mode) still recovers fine
    eng2 = _durable_engine(ddir)
    assert eng2.get("flip", create=False).snapshot() \
        == eng.get("flip").snapshot()
    eng2.close()
    eng.close()
    # and the reverse: a shared incarnation's stream blocks a per-doc
    # restart
    ddir2 = tmp_path / "dur2"
    eng3 = _durable_engine(ddir2, wal_shared=True, oplog_hot_ops=4096)
    ok, _ = _submit(eng3, "flip2", chain_ops(1, 6))
    assert ok
    assert eng3.flush(20)
    with pytest.raises(wal_mod.WalError, match="shared WAL stream"):
        _durable_engine(ddir2)
    eng4 = _durable_engine(ddir2, wal_shared=True)
    assert eng4.get("flip2", create=False).snapshot() \
        == eng3.get("flip2").snapshot()
    eng4.close()
    eng3.close()


def test_shared_wal_disk_full_sheds_all_covered_commits(tmp_path):
    """A failed SHARED fsync sheds and rolls back EVERY commit it
    covered (their records share the dropped unsynced tail) — and the
    disk recovering restores the write path for all of them."""
    eng = _durable_engine(tmp_path / "dur", wal_shared=True)
    for k in range(2):
        ok, _ = _submit(eng, f"fdoc{k}", chain_ops(1, 4))
        assert ok
    real_sync = eng.shared_wal.sync

    def enospc(covered_docs=1):
        raise OSError(28, "No space left on device")

    eng.shared_wal.sync = enospc
    try:
        with pytest.raises(WalUnavailable):
            _submit(eng, "fdoc0", chain_ops(1, 4, start=5))
    finally:
        eng.shared_wal.sync = real_sync
    doc = eng.get("fdoc0")
    assert doc.tree.log_length == 4      # rolled back
    assert eng.scheduler.is_alive()
    ok, _ = _submit(eng, "fdoc0", chain_ops(1, 4, start=5))
    assert ok
    assert doc.tree.log_length == 8
    eng.close()


# -- satellites ------------------------------------------------------------


def test_restore_tiered_preserves_last_operation(tmp_path):
    """ISSUE 9 satellite: checkpoint_tiered/restore_tiered used to
    drop ``last_operation`` silently; the manifest now carries the
    span (or blob), and a restored node reports the same provenance."""
    t = engine.init(0)
    t.apply(Batch(tuple(chain_ops(1, 30))))
    last = t.last_operation
    assert len(last.ops) == 30
    t.checkpoint_tiered(str(tmp_path / "ck"))
    r = engine.TpuTree.restore_tiered(str(tmp_path / "ck"))
    assert r.last_operation == last
    assert len(r.last_operation.ops) == 30

    # bare single-op shape survives too (the reference's bare echo)
    t2 = engine.init(0)
    t2.apply(Batch(tuple(chain_ops(2, 6))))
    bare = Add(ts(2, 7), (ts(2, 6),), "bare")
    t2.apply(bare)
    assert isinstance(t2.last_operation, Add)
    t2.checkpoint_tiered(str(tmp_path / "ck2"))
    r2 = engine.TpuTree.restore_tiered(str(tmp_path / "ck2"))
    assert isinstance(r2.last_operation, Add)
    assert r2.last_operation == bare

    # empty-batch sentinel: a fresh restore-of-restore keeps it
    r2.checkpoint_tiered(str(tmp_path / "ck3"))
    r3 = engine.TpuTree.restore_tiered(str(tmp_path / "ck3"))
    assert r3.last_operation == r2.last_operation


def test_catchup_503_with_priority_pull():
    """ISSUE 9 satellite: a fleet node that doesn't hold a document a
    peer HAS answers 503 + Retry-After + X-Catchup-Remaining (not
    404) and triggers a priority anti-entropy pull that lands without
    waiting out the (dormant) sync interval."""
    from http.client import HTTPConnection

    from crdt_graph_tpu.cluster import FleetServer, MemoryKV

    kv = MemoryKV()
    a = FleetServer("n0", kv, ttl_s=600, ae_interval_s=3600)
    b = FleetServer("n1", kv, ttl_s=600, ae_interval_s=3600)
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if all(len(fs.node.refresh_ring()) == 2 for fs in (a, b)):
                break
            time.sleep(0.02)
        # a doc primaried on n0, written through n0
        doc = next(d for d in (f"cd{i}" for i in range(64))
                   if a.node.primary_for(d) == "n0")
        conn = HTTPConnection("127.0.0.1", a.port, timeout=30)
        conn.request("POST", f"/docs/{doc}/ops",
                     body=json_codec.dumps(Batch(tuple(chain_ops(1, 6)))))
        assert conn.getresponse().status == 200
        conn.close()
        # n1 knows the doc exists (peer listing) but hasn't pulled it:
        # exactly the restart / new-owner catch-up window
        st = b.node.antientropy._peer_state("n0", a.addr)
        st.known_docs = frozenset({doc})
        conn = HTTPConnection("127.0.0.1", b.port, timeout=30)
        conn.request("GET", f"/docs/{doc}")
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 503, (resp.status, body)
        assert resp.getheader("Retry-After") is not None
        assert resp.getheader("X-Catchup-Remaining") == "1"
        conn.close()
        assert b.node.antientropy.priority_pulls >= 1
        # the priority wake pulls the doc despite the 3600 s interval
        deadline = time.monotonic() + 20
        got = None
        while time.monotonic() < deadline:
            conn = HTTPConnection("127.0.0.1", b.port, timeout=30)
            conn.request("GET", f"/docs/{doc}")
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            if resp.status == 200:
                got = json.loads(body)["values"]
                break
            time.sleep(0.05)
        assert got is not None, "priority pull never landed"
        assert got == [f"v1.{c}" for c in range(1, 7)]
        # an unknown doc is still an honest 404
        conn = HTTPConnection("127.0.0.1", b.port, timeout=30)
        conn.request("GET", "/docs/nosuchdoc")
        assert conn.getresponse().status == 404
        conn.close()
    finally:
        a.stop()
        b.stop()


def test_prom_wal_families_strict_parse(tmp_path):
    eng = _durable_engine(tmp_path / "dur")
    ok, _ = _submit(eng, "pdoc", chain_ops(1, 12))
    assert ok
    text = eng.render_prom()
    fams = prom_mod.parse_text(text)
    for fam in ("crdt_wal_appends_total", "crdt_wal_fsyncs_total",
                "crdt_wal_appended_bytes_total",
                "crdt_wal_truncations_total", "crdt_wal_errors_total",
                "crdt_wal_size_bytes", "crdt_wal_epoch",
                "crdt_wal_fsync_ms"):
        assert fam in fams, fam
    assert fams["crdt_wal_fsync_ms"]["type"] == "histogram"
    # non-durable engines keep their scrape unchanged
    eng2 = ServingEngine(flight=flight_mod.FlightRecorder())
    assert not any(f.startswith("crdt_wal_")
                   for f in prom_mod.parse_text(eng2.render_prom()))
    eng2.close()
    eng.close()


# -- process-level matrix + fleet soak + headline (slow) -------------------


def _proc_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    return env


@pytest.mark.slow
@pytest.mark.parametrize(
    "site,shared",
    [(s, False) for s in wal_mod.CRASH_SITES]
    + [("ack-pre-fsync", True), ("post-fsync-pre-publish", True),
       ("mid-matz-write", True), ("pre-queue-fsync", True)])
def test_wal_crash_point_process_matrix(tmp_path, site, shared):
    """The real thing: a server process dies by os._exit(137) at the
    armed site mid-HTTP-traffic; a fresh engine recovers the durable
    dir with zero acked-write loss — per-doc WAL at every site, plus
    the shared stream at its own durability boundaries."""
    ddir = str(tmp_path / "dur")
    ack_log = str(tmp_path / "acked.txt")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__),
                      "_wal_crash_worker.py"),
         site, ddir, ack_log] + (["shared"] if shared else []),
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        env=_proc_env(), capture_output=True, text=True, timeout=300)
    assert proc.returncode == 137, \
        (site, proc.returncode, proc.stdout[-800:], proc.stderr[-800:])
    acked = [ln for ln in open(ack_log).read().splitlines() if ln]
    assert acked, "worker crashed before anything was acked"
    eng = ServingEngine(durable_dir=ddir, wal_sync="batch",
                        wal_shared=shared,
                        flight=flight_mod.FlightRecorder())
    doc = eng.get("crash", create=False)
    assert doc is not None
    vals = set(doc.snapshot())
    missing = [v for v in acked if v not in vals]
    assert not missing, f"site {site} lost acked writes: {missing}"
    assert doc.epoch == 2
    _windows_match_untiered(doc.tree, sinces=(0,), limits=(0, 6))
    eng.close()


@pytest.mark.slow
def test_wal_sigkill_fleet_soak(tmp_path):
    """SIGKILL matrix over the fleet, WAL on: a durable node dies hard
    mid-traffic with acked writes only in its WAL (anti-entropy
    dormant), restarts under its old name, recovers its docs from
    disk, and the fleet converges with every acked value present."""
    import signal

    spool = str(tmp_path / "kv")
    durdirs = {n: str(tmp_path / f"dur-{n}") for n in ("n0", "n1")}
    procs, infos = {}, {}

    def spawn(name, ae_interval):
        proc = subprocess.Popen(
            [sys.executable, "-m", "crdt_graph_tpu.cluster", "--cpu",
             "--name", name, "--kv-dir", spool, "--port", "0",
             "--ttl", "2.0", "--ae-interval", str(ae_interval),
             "--durable-dir", durdirs[name], "--wal-sync", "batch"],
            cwd=os.path.join(os.path.dirname(__file__), ".."),
            env=_proc_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        line = proc.stdout.readline()
        assert line.startswith("READY "), line
        return proc, json.loads(line[len("READY "):])

    def req(port, method, path, body=None, timeout=60):
        from http.client import HTTPConnection
        conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
        try:
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            return resp.status, resp.read(), dict(resp.getheaders())
        finally:
            conn.close()

    try:
        # a LONG anti-entropy interval: the victim's acked writes must
        # survive through its WAL, not through replication
        for n in durdirs:
            procs[n], infos[n] = spawn(n, ae_interval=30.0)
        ports = {n: int(i["addr"].rsplit(":", 1)[1])
                 for n, i in infos.items()}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            views = {n: json.loads(req(p, "GET", "/cluster")[1])
                     for n, p in ports.items()}
            if all(len(v["members"]) == 2 for v in views.values()):
                break
            time.sleep(0.1)
        # find a doc primaried on n0 and push acked writes to it
        doc = None
        for cand in (f"soak{i}" for i in range(64)):
            st, raw, _ = req(ports["n0"], "POST",
                             f"/docs/{cand}/ops",
                             body=json_codec.dumps(
                                 Batch(tuple(chain_ops(1, 5)))))
            assert st == 200
            if json.loads(raw)["served_by"]["name"] == "n0":
                doc = cand
                break
        assert doc is not None
        acked = [f"v1.{c}" for c in range(1, 6)]
        for k in range(5):
            ops = chain_ops(1, 5, start=6 + 5 * k)
            st, raw, _ = req(ports["n0"], "POST", f"/docs/{doc}/ops",
                             body=json_codec.dumps(Batch(tuple(ops))))
            out = json.loads(raw)
            assert st == 200 and out["accepted"], out
            if out["served_by"]["name"] == "n0":
                acked += [op.value for op in ops]
        # SIGKILL the primary: its acked hot tail exists ONLY in its
        # durable dir (anti-entropy hasn't run)
        procs["n0"].send_signal(signal.SIGKILL)
        procs["n0"].wait(30)
        procs.pop("n0").stdout.close()
        # restart under the old name: recovery-to-serving from disk
        p_new, info_new = spawn("n0", ae_interval=0.2)
        procs["n0"] = p_new
        assert info_new["epoch"] >= 2
        assert doc in info_new["recovered_docs"], info_new
        ports["n0"] = int(info_new["addr"].rsplit(":", 1)[1])
        # the recovered node serves the doc IMMEDIATELY (no 404/503)
        st, raw, hdr = req(ports["n0"], "GET", f"/docs/{doc}")
        assert st == 200
        vals = set(json.loads(raw)["values"])
        missing = [v for v in acked if v not in vals]
        assert not missing, f"SIGKILL lost acked writes: {missing}"
        # and the fleet converges to fingerprint-equal state
        deadline = time.monotonic() + 120
        fps = {}
        while time.monotonic() < deadline:
            fps = {}
            for n, p in ports.items():
                st, raw, hdr = req(p, "GET", f"/docs/{doc}")
                if st == 200:
                    fps[n] = hdr.get("X-State-Fingerprint")
            if len(fps) == 2 and len(set(fps.values())) == 1:
                break
            time.sleep(0.5)
        assert len(set(fps.values())) == 1, fps
    finally:
        for p in procs.values():
            try:
                p.terminate()
            except OSError:
                pass
        for p in procs.values():
            try:
                p.wait(20)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_bench_coldpath_headline_full(tmp_path):
    """The committed-artifact run (BENCH_COLDPATH_r01_cpu.json shape,
    reduced): restore-to-first-read off the materialization artifact
    beats the full-first-merge path ≥5× with bit-identical
    fingerprints, the chunked base bounds a mid-history window's
    resident bytes, and the shared WAL collapses fsyncs/round on the
    many-doc fleet shape with zero oracle violations."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_coldpath_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_coldpath_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_COLDPATH_test.json"),
                  n_ops=400_000, restore_rounds=1,
                  fleet_docs=24, fleet_sessions=24, fleet_writes=3,
                  fleet_rounds=1)
    assert out["fingerprints_equal"]
    assert out["restore"]["speedup_to_first_read"] >= 5.0, \
        out["restore"]
    best = out["restore"]["best"]
    assert best["matz"]["matz_stats"]["loads"] == 1
    assert best["matz"]["matz_stats"]["fallbacks"] == 0
    # the chunked base keeps a cold window's resident footprint to its
    # covering chunks (400k ops → ≥3 chunks; monolith holds all)
    cat = out["catchup"]
    assert cat["chunked"]["base_chunks"] >= 3
    assert cat["monolith"]["base_chunks"] == 1
    assert cat["resident_ratio"] <= 0.6, cat
    # shared stream amortizes fsyncs on a multi-doc round (the full
    # 64-doc committed artifact holds the ≥8x headline; the reduced
    # tier-shape gate is looser against 1-core scheduling noise)
    fl = out["fleet"]
    assert fl["best"]["shared"]["violations"] == 0
    assert fl["best"]["perdoc"]["violations"] == 0
    assert fl["fsyncs_per_round_reduction"] >= 2.0, fl
    assert fl["shared_vs_perdoc_throughput"] >= 0.8, fl


@pytest.mark.slow
def test_bench_wal_headline_full(tmp_path):
    """The committed-artifact run (BENCH_WAL_r01_cpu.json shape):
    off/batch/commit legs of the loadgen serving shape, oracle-clean,
    batch fsyncs amortized below commit's, and the batch-vs-off
    acked-throughput regression inside a noise-tolerant bound (the
    committed artifact holds the honest ≤ 25% number; the CPU driver
    box is ±40% run-to-run, so the gate here is looser)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_wal_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_wal_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_WAL_test.json"),
                  n_sessions=12, writes_per_session=6, rounds=2)
    best = out["best"]
    for mode in ("off", "batch", "commit"):
        assert best[mode]["violations"] == 0
        assert best[mode]["writes_acked"] >= 72
        assert best[mode]["ack_p50_ms"] is not None
    assert best["off"]["wal"]["fsyncs"] == 0
    assert best["batch"]["wal"]["fsyncs"] >= 1
    # group commit amortizes within commits: one record and one fsync
    # per COMMIT, never per ticket (cross-mode fsync counts are not
    # comparable — they track commit counts, which vary with how much
    # coalescing each run's timing produced)
    for mode in ("batch", "commit"):
        w = best[mode]["wal"]
        assert w["fsyncs"] <= best[mode]["writes_acked"], (mode, w)
        assert w["appends"] == w["fsyncs"], (mode, w)
    assert out["batch_vs_off_regression"] <= 0.5
