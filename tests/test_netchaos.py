"""Partition & bit-rot chaos, network half (cluster/netchaos.py):
deterministic fault injection on every inter-node link, the peer
health / circuit-breaker degradation layer, the bounded-staleness read
contract, and the forward-deadline budget.

Every chaos test prints its ``NetChaos.describe()`` replay line first,
so a red run's captured stdout carries the exact seed + schedule to
reproduce it verbatim.
"""
import json
import threading
import time
from http.client import HTTPConnection

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.cluster import (FleetServer, MemoryKV, NetChaos,
                                    NetChaosSpecError)
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch


def ts(r, c):
    return r * 2**32 + c


def req(port, method, path, body=None, headers=None, timeout=60):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw, dict(resp.getheaders())
    finally:
        conn.close()


def _chain(rid, n, start=1, prev=0):
    ops = []
    for c in range(start, start + n):
        ops.append(Add(ts(rid, c), (prev,), f"r{rid}:{c}"))
        prev = ts(rid, c)
    return json_codec.dumps(Batch(tuple(ops)))


def _spawn_fleet(kv, names, **kw):
    """Deterministic fleet: huge TTL, dormant daemon (tests drive
    ``sync_now``)."""
    fleet = {}
    for n in names:
        fleet[n] = FleetServer(n, kv, ttl_s=600.0,
                               ae_interval_s=3600.0, **kw)
    for fs in fleet.values():
        fs.node.refresh_ring()
    return fleet


def _stop_fleet(fleet):
    for fs in fleet.values():
        try:
            fs.stop()
        except Exception:  # noqa: BLE001 — teardown boundary
            pass


def _doc_owned_by(ring, owner, prefix="doc"):
    for i in range(500):
        d = f"{prefix}{i}"
        if ring.primary(d) == owner:
            return d
    pytest.fail(f"no doc routed to {owner}")


def _post_retry(port, doc, body, deadline_s=30):
    """Client write with 429/503/connection retry — chaos on the
    forward path legally sheds; an acked-loss check only counts 200s."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            st, raw, _ = req(port, "POST", f"/docs/{doc}/ops",
                             body=body, timeout=30)
        except OSError:
            time.sleep(0.05)
            continue
        if st == 200 and json.loads(raw).get("accepted"):
            return True
        if st in (429, 503):
            time.sleep(0.05)
            continue
        pytest.fail(f"write -> {st}: {raw[:200]!r}")
    return False


def _sync_all(fleet, docs, deadline_s=60, require=None):
    """Drive sync rounds until the named (or all) nodes agree on every
    doc's replica-independent fingerprint.  Returns the converged
    fingerprints."""
    names = sorted(require or fleet)
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for n in names:
            fleet[n].node.antientropy.sync_now()
        fps = {}
        ok = True
        for doc in docs:
            seen = set()
            for n in names:
                st, _, hdr = req(fleet[n].port, "GET", f"/docs/{doc}")
                if st != 200:
                    ok = False
                    continue
                seen.add(hdr["X-State-Fingerprint"])
            fps[doc] = seen
            ok = ok and len(seen) == 1
        if ok:
            return {d: next(iter(s)) for d, s in fps.items()}
        time.sleep(0.02)
    pytest.fail(f"no convergence within {deadline_s}s: {fps}")


def _values(fleet_server, doc):
    st, raw, _ = req(fleet_server.port, "GET", f"/docs/{doc}")
    assert st == 200, raw
    return json.loads(raw)["values"]


# -- spec grammar + determinism ----------------------------------------------


def test_spec_parse_roundtrip_and_errors():
    c = NetChaos(7, "drop=0.25;delay=1-5@0.5;throttle=65536;cut=0.1;"
                    "dup=0.2;part=n0|n1+n2@3-9;oneway=a>b@0-4;"
                    "flap=x|y@10/3")
    assert c.drop_p == 0.25
    assert c.delay == (0.001, 0.005, 0.5)
    assert c.throttle_bps == 65536
    assert c.cut_p == 0.1 and c.dup_p == 0.2
    assert len(c.partitions) == 3
    assert c.describe().startswith("GRAFT_NETCHAOS=7:drop=0.25")
    for bad in ("frob=1", "part=a|b", "flap=a|b@0/0", "drop=x",
                "part=|b@0-4"):
        with pytest.raises(NetChaosSpecError):
            NetChaos(1, bad)
    # env parsing (the multi-process soak's entry)
    import os
    from crdt_graph_tpu.cluster import netchaos as nc_mod
    os.environ["GRAFT_NETCHAOS"] = "3:drop=0.5"
    try:
        nc_mod.reset_env_chaos()
        env = nc_mod.env_chaos()
        assert env is not None and env.seed == 3 and env.drop_p == 0.5
    finally:
        del os.environ["GRAFT_NETCHAOS"]
        nc_mod.reset_env_chaos()
    assert NetChaos.from_env() is None


def _fates(chaos, src, dst, n):
    """The link's next n request fates as a refusal bitmask string."""
    out = []
    for _ in range(n):
        try:
            chaos.decide(src, dst)
            out.append(".")
        except ConnectionRefusedError:
            out.append("X")
    return "".join(out)


def test_partition_schedules_on_link_request_index():
    c = NetChaos(0, "part=a|b@2-4")
    assert _fates(c, "a", "b", 6) == "..XX.."      # [2,4) blocked
    assert _fates(c, "b", "a", 6) == "..XX.."      # symmetric
    assert _fates(c, "a", "c", 3) == "..."         # unrelated link
    c = NetChaos(0, "oneway=a>b@0-2")
    assert _fates(c, "a", "b", 3) == "XX."
    assert _fates(c, "b", "a", 3) == "..."         # asymmetric
    c = NetChaos(0, "flap=a|b@4/2")
    assert _fates(c, "a", "b", 8) == "XX..XX.."    # flapping
    c = NetChaos(0, "part=a|*@0-2")
    assert _fates(c, "a", "anything", 3) == "XX."  # wildcard group


def test_seeded_decisions_are_reproducible():
    spec = "drop=0.4;delay=0-1@0.5;cut=0.2;dup=0.3"
    a = _fates(NetChaos(42, spec), "n0", "n1", 64)
    b = _fates(NetChaos(42, spec), "n0", "n1", 64)
    assert a == b and "X" in a and "." in a
    # a different seed gives a different stream; a different link of
    # the SAME plan draws independently
    assert _fates(NetChaos(43, spec), "n0", "n1", 64) != a
    c = NetChaos(42, spec)
    assert _fates(c, "n0", "n1", 64) == a
    assert _fates(c, "n1", "n0", 64) != a


# -- the acceptance matrix: partition/heal × {sym, asym, flapping} -----------


def test_partition_matrix_converges_zero_acked_loss():
    """The seeded partition/heal matrix (ISSUE 14 acceptance):
    symmetric isolation, an asymmetric one-way cut healed around
    transitively, and a flapping link — over a lossy/slow link plan —
    each phase ends in fingerprint-equal convergence with every acked
    value present on every replica.  Reproducible from the printed
    replay line."""
    chaos = NetChaos(1337, "drop=0.1;delay=1-4@0.3")
    print("REPLAY:", chaos.describe())
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1", "n2"), netchaos=chaos,
                         breaker_threshold=50)
    acked = {}                      # doc -> [values]
    try:
        ring = fleet["n0"].node.ring()
        doc_a = _doc_owned_by(ring, "n0", prefix="pm")
        doc_c = _doc_owned_by(ring, "n2", prefix="pm")
        docs = [doc_a, doc_c]

        def write(port, doc, rid, n, start, prev=0):
            assert _post_retry(port, doc, _chain(rid, n, start=start,
                                                 prev=prev))
            acked.setdefault(doc, []).extend(
                f"r{rid}:{c}" for c in range(start, start + n))

        # phase 0: baseline through every node, converge
        write(fleet["n0"].port, doc_a, 10, 4, 1)
        write(fleet["n2"].port, doc_c, 30, 4, 1)
        _sync_all(fleet, docs)

        # phase 1: SYMMETRIC — n2 cut off from both peers
        chaos.block_groups({"n2"}, {"n0", "n1"})
        write(fleet["n0"].port, doc_a, 11, 4, 1)
        # n2 keeps acking writes to ITS doc while isolated (local
        # apply — availability under partition)
        write(fleet["n2"].port, doc_c, 31, 4, 1)
        fps = _sync_all(fleet, [doc_a], require=("n0", "n1"))
        st, _, hdr = req(fleet["n2"].port, "GET", f"/docs/{doc_a}")
        assert hdr["X-State-Fingerprint"] != fps[doc_a], \
            "n2 cannot have n1's state through a full partition"
        assert float(hdr["X-Ae-Lag-Seconds"]) > 0.0
        chaos.heal()
        _sync_all(fleet, docs)

        # phase 2: ASYMMETRIC — n1 cannot pull from n0, but the write
        # still reaches n1 transitively through n2 (pull-based
        # anti-entropy routes around one-way cuts)
        chaos.block("n1", "n0", oneway=True)
        write(fleet["n0"].port, doc_a, 12, 4, 1)
        _sync_all(fleet, [doc_a], deadline_s=90)
        assert "r12:4" in _values(fleet["n1"], doc_a)
        chaos.heal()

        # phase 3: FLAPPING — the n0↔n1 link cuts and heals repeatedly
        # while writes keep landing; convergence after the last heal
        for k in range(4):
            chaos.block("n0", "n1")
            write(fleet["n0"].port, doc_a, 13 + k, 2, 1)
            for n in fleet:
                fleet[n].node.antientropy.sync_now()
            chaos.heal()
            for n in fleet:
                fleet[n].node.antientropy.sync_now()
        _sync_all(fleet, docs)

        # ZERO ACKED LOSS: every value ever acked is on every replica
        for doc in docs:
            for n, fs in fleet.items():
                got = set(_values(fs, doc))
                missing = [v for v in acked[doc] if v not in got]
                assert not missing, \
                    (f"{n} lost acked values {missing[:4]} "
                     f"({chaos.describe()})")
        # the fault plan actually fired (this was not a clean run)
        stats = chaos.stats()["counters"]
        assert stats["partition_blocks"] > 0
        assert stats["drops"] + stats["delays"] > 0
    finally:
        print("REPLAY:", chaos.describe(),
              "counters:", chaos.stats()["counters"])
        _stop_fleet(fleet)


# -- cut / dup faults through the real anti-entropy wire ---------------------


def test_cut_mid_response_is_a_peer_failure_then_heals():
    chaos = NetChaos(5, "cut=1")
    print("REPLAY:", chaos.describe())
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 5))
        ae = fleet["n1"].node.antientropy
        # every response dies mid-body: a counted peer failure, never
        # an escaped exception or a half-applied window
        assert ae.sync_now() == {"n0": False}
        st = ae.stats()["peers"]["n0"]
        assert st["failures"] >= 1 and st["health"] < 1.0
        assert chaos.stats()["counters"]["cuts"] >= 1
        chaos.cut_p = 0.0           # the link heals
        assert ae.sync_now() == {"n0": True}
        assert _values(fleet["n1"], doc) == [f"r1:{c}"
                                             for c in range(1, 6)]
    finally:
        _stop_fleet(fleet)


def test_chaos_over_pooled_connections_poisons_and_reopens():
    """ISSUE 15 acceptance: all inter-node traffic now rides the
    per-node connection pool THROUGH netchaos.connect — an injected
    cut poisons exactly the pooled connection it hit (evicted, never
    reused), the next round reopens fresh, and a healthy steady state
    reuses connections across rounds with zero acked loss."""
    chaos = NetChaos(11, "")
    print("REPLAY:", chaos.describe())
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 5))
        ae = fleet["n1"].node.antientropy
        pool = fleet["n1"].node.pool

        # clean rounds: round 1 opens, round 2+ REUSE the pooled link
        assert ae.sync_now() == {"n0": True}
        opens_clean = pool.stats()["opens"]
        assert opens_clean >= 1
        assert ae.sync_now() == {"n0": True}
        st = pool.stats()
        assert st["opens"] == opens_clean       # no new connection
        assert st["reuses"] >= 1

        # arm cut=1: the response dies mid-body; the failure poisons
        # the pooled connection (never returned to the idle set)
        chaos.cut_p = 1.0
        assert ae.sync_now() == {"n0": False}
        st = pool.stats()
        assert st["poisoned"] >= 1, st

        # heal: the next round must OPEN a fresh connection (the
        # poisoned one is gone) and fully converge — zero acked loss
        chaos.cut_p = 0.0
        assert ae.sync_now() == {"n0": True}
        st2 = pool.stats()
        assert st2["opens"] > opens_clean, (st, st2)
        assert _values(fleet["n1"], doc) == [f"r1:{c}"
                                             for c in range(1, 6)]
        # partition blocks poison too (a drop fires before bytes move,
        # but the caller cannot know — conservative eviction)
        chaos.block("n1", "n0")
        assert ae.sync_now() == {"n0": False}
        assert pool.stats()["poisoned"] > st2["poisoned"]
        chaos.heal()
        assert ae.sync_now() == {"n0": True}
    finally:
        print("REPLAY:", chaos.describe(),
              "pool:", fleet["n1"].node.pool.stats())
        _stop_fleet(fleet)


def test_dup_reordered_window_deliveries_absorb():
    """dup=1: every pull re-serves the link's previous response — the
    puller applies stale windows and its mark regresses, and the CRDT
    absorbs all of it (idempotence is the contract under reordering)."""
    chaos = NetChaos(9, "dup=1")
    print("REPLAY:", chaos.describe())
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 6))
        ae = fleet["n1"].node.antientropy
        for _ in range(6):
            ae.sync_now()
        assert chaos.stats()["counters"]["dups"] >= 1
        assert _values(fleet["n1"], doc) == [f"r1:{c}"
                                             for c in range(1, 7)]
        st, _, h0 = req(fleet["n0"].port, "GET", f"/docs/{doc}")
        st, _, h1 = req(fleet["n1"].port, "GET", f"/docs/{doc}")
        assert h0["X-State-Fingerprint"] == h1["X-State-Fingerprint"]
    finally:
        _stop_fleet(fleet)


# -- peer health, circuit breaker, probe pulls (satellite pins) --------------


def test_backoff_hygiene_first_success_fully_resets():
    """Satellite pin: a peer's fail_streak/backoff_until reset
    completely on the first successful round — no residual penalty."""
    chaos = NetChaos(2, "")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 3))
        ae = fleet["n1"].node.antientropy
        chaos.block("n1", "n0")
        for _ in range(2):
            assert ae.sync_now() == {"n0": False}
        st = ae.stats()["peers"]["n0"]
        assert st["fail_streak"] == 2 and st["backoff_s"] > 0
        assert st["health"] < 1.0
        chaos.heal()
        assert ae.sync_now() == {"n0": True}
        st = ae.stats()["peers"]["n0"]
        assert st["fail_streak"] == 0
        assert st["backoff_s"] == 0.0
        assert not st["breaker_open"]
        h1 = st["health"]
        assert ae.sync_now() == {"n0": True}
        assert ae.stats()["peers"]["n0"]["health"] > h1  # recovering
    finally:
        _stop_fleet(fleet)


def test_breaker_opens_probe_pull_closes():
    """Satellite pin: past the threshold the breaker opens; a priority
    wake then performs EXACTLY ONE probe pull (listing + one window of
    one doc) rather than a full unthrottled round; the probe's success
    closes the breaker and the next round is full again."""
    chaos = NetChaos(4, "")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos,
                         breaker_threshold=3)
    try:
        ring = fleet["n0"].node.ring()
        owned = []
        for i in range(500):
            if ring.primary(f"bk{i}") == "n0":
                owned.append(f"bk{i}")
            if len(owned) == 3:
                break
        for k, d in enumerate(owned):
            assert _post_retry(fleet["n0"].port, d, _chain(5 + k, 3))
        ae = fleet["n1"].node.antientropy
        assert ae.sync_now() == {"n0": True}     # marks for all 3 docs
        chaos.block("n1", "n0")
        for _ in range(3):
            assert ae.sync_now() == {"n0": False}
        st = ae.stats()["peers"]["n0"]
        assert st["breaker_open"] and st["breaker_opens"] == 1
        pulls_before = st["pulls"]

        # new writes the probe round must NOT fully pull
        for k, d in enumerate(owned):
            assert _post_retry(fleet["n0"].port, d,
                               _chain(5 + k, 2, start=4,
                                      prev=ts(5 + k, 3)))
        chaos.heal()
        # priority wake while the breaker is open: exactly one probe
        lag_before_probe = ae.lag_seconds()
        ae.request_priority(owned[0])
        assert ae.sync_now(respect_backoff=False) == {"n0": True}
        st = ae.stats()["peers"]["n0"]
        assert st["probes"] == 1
        assert st["pulls"] == pulls_before + 1, \
            "probe must pull exactly ONE window of ONE doc"
        assert not st["breaker_open"]            # success closed it
        assert st["fail_streak"] == 0
        # a probe proves reachability, NOT sync: the lag clock (the
        # bounded-staleness 503 input) must not reset until the next
        # FULL round has actually pulled everything
        assert ae.lag_seconds() >= lag_before_probe
        # the NEXT round is a full sync again: every doc catches up
        assert ae.sync_now() == {"n0": True}
        assert ae.lag_seconds() < lag_before_probe  # genuinely fresh
        st = ae.stats()["peers"]["n0"]
        assert st["pulls"] >= pulls_before + 1 + len(owned)
        for k, d in enumerate(owned):
            assert f"r{5 + k}:5" in _values(fleet["n1"], d)
        assert ae.stats()["probe_pulls"] == 1
    finally:
        _stop_fleet(fleet)


def test_breaker_open_skips_full_rounds_on_backoff():
    """While open (and not priority-woken), rounds respect the capped
    backoff and never run a full sync against the dead peer."""
    chaos = NetChaos(6, "")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos,
                         breaker_threshold=2)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 3))
        ae = fleet["n1"].node.antientropy
        assert ae.sync_now() == {"n0": True}
        chaos.block("n1", "n0")
        for _ in range(2):
            ae.sync_now()
        st = ae.stats()["peers"]["n0"]
        assert st["breaker_open"]
        probes0 = st["probes"]
        # a backoff-respecting round inside the backoff window does
        # NOTHING against the peer — no pull, no probe
        res = ae.sync_now(respect_backoff=True)
        assert "n0" not in res
        assert ae.stats()["peers"]["n0"]["probes"] == probes0
        # a backoff-ignoring round (priority shape) probes, and the
        # probe itself fails against the still-cut link — the failure
        # is counted, the breaker stays open
        res = ae.sync_now(respect_backoff=False)
        assert res == {"n0": False}
        st = ae.stats()["peers"]["n0"]
        assert st["probes"] == probes0 + 1 and st["breaker_open"]
    finally:
        _stop_fleet(fleet)


# -- bounded-staleness reads (tentpole piece 2) ------------------------------


def test_bounded_staleness_read_contract():
    chaos = NetChaos(8, "")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 3))
        ae = fleet["n1"].node.antientropy
        assert ae.sync_now() == {"n0": True}
        # fresh replica: bounded read serves, lag stamped
        st, _, hdr = req(fleet["n1"].port, "GET", f"/docs/{doc}",
                         headers={"X-Max-Staleness": "5"})
        assert st == 200
        assert float(hdr["X-Ae-Lag-Seconds"]) < 5.0
        # partition the replica; its lag grows past a tight bound
        chaos.block("n1", "n0")
        ae.sync_now()
        time.sleep(0.15)
        st, raw, hdr = req(fleet["n1"].port, "GET", f"/docs/{doc}",
                           headers={"X-Max-Staleness": "0.05"})
        assert st == 503, raw
        assert "Retry-After" in hdr
        body = json.loads(raw)
        assert body["ae_lag_s"] > 0.05
        assert float(hdr["X-Ae-Lag-Seconds"]) > 0.05
        # snapshots honor the same bound; unbounded reads still serve
        st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}/snapshot",
                       headers={"X-Max-Staleness": "0.05"})
        assert st == 503
        st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}")
        assert st == 200
        # malformed bounds (bogus/nan/-inf) fall back to the (unset)
        # server default — nan would otherwise 503 forever (lag <= nan
        # is always False) — and +inf is honored as explicitly
        # unbounded; all serve here
        for bad in ("bogus", "nan", "inf", "-inf"):
            st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}",
                           headers={"X-Max-Staleness": bad})
            assert st == 200, bad
        assert fleet["n1"].node.counters["staleness_503"] >= 2
        # heal: one successful round resets the lag; bounded serves
        chaos.heal()
        assert ae.sync_now() == {"n0": True}
        st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}",
                       headers={"X-Max-Staleness": "5"})
        assert st == 200
    finally:
        _stop_fleet(fleet)


def test_server_default_staleness_bound():
    """GRAFT_MAX_STALENESS_S as a server-wide default (here via the
    ctor knob it feeds): unbounded requests inherit it."""
    chaos = NetChaos(12, "")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos,
                         max_staleness_s=0.05)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 3))
        ae = fleet["n1"].node.antientropy
        assert ae.sync_now() == {"n0": True}
        chaos.block("n1", "n0")
        ae.sync_now()
        time.sleep(0.15)
        st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}")
        assert st == 503            # no header needed — server default
        # a LOOSER per-request bound overrides the strict default
        st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}",
                       headers={"X-Max-Staleness": "60"})
        assert st == 200
        # +inf is an EXPLICIT unbounded request — it overrides even a
        # strict server default; nan stays malformed and inherits it
        st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}",
                       headers={"X-Max-Staleness": "inf"})
        assert st == 200
        st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}",
                       headers={"X-Max-Staleness": "nan"})
        assert st == 503
    finally:
        _stop_fleet(fleet)


def test_never_synced_replica_reports_unbounded_lag():
    """A replica that has never completed a full round since daemon
    start cannot bound how stale its (possibly recovered) state is:
    lag is inf — a bounded read refuses, an unbounded read stamps the
    honest ``inf`` — until the first full sync lands.  A start-relative
    near-zero here would be exactly the silent-stale lie the 503
    exists to prevent (a node restarted after an hour of downtime
    would serve hour-old data as fresh)."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"))
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 3))
        assert fleet["n0"].node.ae_lag_seconds() == float("inf")

        def strict_loads(raw):
            # RFC 8259 has no Infinity/NaN literals — the wire must
            # serialize unbounded lag as null, never lean on Python's
            # lenient json.loads
            return json.loads(
                raw, parse_constant=lambda c: pytest.fail(
                    f"non-RFC JSON literal {c!r} on the wire"))

        st, raw, hdr = req(fleet["n0"].port, "GET", f"/docs/{doc}",
                           headers={"X-Max-Staleness": "60"})
        assert st == 503
        assert strict_loads(raw)["ae_lag_s"] is None
        assert hdr["X-Ae-Lag-Seconds"] == "inf"
        st, raw, _ = req(fleet["n0"].port, "GET", "/cluster")
        assert strict_loads(raw)["ae_lag_s"] is None
        st, _, hdr = req(fleet["n0"].port, "GET", f"/docs/{doc}")
        assert st == 200
        assert float(hdr["X-Ae-Lag-Seconds"]) == float("inf")
        # first full round: the bound becomes enforceable and serves
        assert fleet["n0"].node.antientropy.sync_now() == {"n1": True}
        st, _, hdr = req(fleet["n0"].port, "GET", f"/docs/{doc}",
                         headers={"X-Max-Staleness": "60"})
        assert st == 200
        assert float(hdr["X-Ae-Lag-Seconds"]) < 60.0
    finally:
        _stop_fleet(fleet)


# -- forward-deadline budget (satellite) -------------------------------------


def test_forward_budget_caps_handler_pin_time():
    """Satellite pin: an unreachable primary can pin a forwarding
    handler only up to the end-to-end budget, then the client gets an
    honest 503 + Retry-After."""
    chaos = NetChaos(3, "")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"), netchaos=chaos,
                         forward_budget_s=0.6, forward_retries=50)
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n1")
        chaos.block("n0", "n1", oneway=True)   # forward path only
        t0 = time.monotonic()
        st, raw, hdr = req(fleet["n0"].port, "POST",
                           f"/docs/{doc}/ops", body=_chain(1, 3),
                           timeout=30)
        elapsed = time.monotonic() - t0
        assert st == 503, raw
        assert "Retry-After" in hdr
        assert elapsed < 5.0, \
            f"handler pinned {elapsed:.1f}s past the 0.6s budget"
        assert fleet["n0"].node.counters["forward_budget_exhausted"] \
            >= 1
        assert fleet["n0"].node.counters["forwarded_err"] >= 1
        # heal: the same write forwards and acks
        chaos.heal()
        assert _post_retry(fleet["n0"].port, doc, _chain(1, 3))
    finally:
        _stop_fleet(fleet)


# -- oracle-checked chaos load (run_fleet netchaos leg) ----------------------


def test_fleet_loadgen_under_netchaos_zero_violations():
    """The session-guarantee oracle stays clean while the fleet's
    inter-node links run delayed + duplicated/reordered deliveries —
    the acceptance matrix's oracle leg."""
    from crdt_graph_tpu.bench import loadgen
    cfg = loadgen.LoadgenConfig(
        n_servers=3, n_sessions=6, n_docs=2, writes_per_session=4,
        delta_size=6, giant_ops=0, kill_mid_run=False,
        lag_probe_every=2, lease_ttl_s=3.0, ae_interval_s=0.1,
        seed=21, netchaos_spec="delay=1-10@0.5;dup=0.3")
    rep = loadgen.run_fleet(cfg)
    print("REPLAY:", rep["netchaos_replay"])
    assert rep["errors"] == [], (rep["errors"], rep["netchaos_replay"])
    assert rep["violations"] == [], rep["netchaos_replay"]
    assert rep["oracle"]["violations_total"] == 0
    assert len(rep["converged"]) == 2
    nc = rep["netchaos"]["counters"]
    assert nc["delays"] > 0                  # the plan actually fired
    assert nc["requests"] > 0


def test_fleet_loadgen_client_links_under_chaos():
    """netchaos_clients=True runs the SESSION links through the plan
    too (delay-only: duplicated RESPONSES to a client would corrupt
    the oracle's own observation channel, not the server — reordering
    coverage lives on the inter-node links above and in the dup
    anti-entropy test)."""
    from crdt_graph_tpu.bench import loadgen
    cfg = loadgen.LoadgenConfig(
        n_servers=3, n_sessions=6, n_docs=2, writes_per_session=3,
        delta_size=5, giant_ops=0, kill_mid_run=False,
        lag_probe_every=2, lease_ttl_s=3.0, ae_interval_s=0.1,
        seed=23, netchaos_spec="delay=1-8@0.6",
        netchaos_clients=True)
    rep = loadgen.run_fleet(cfg)
    print("REPLAY:", rep["netchaos_replay"])
    assert rep["errors"] == [], (rep["errors"], rep["netchaos_replay"])
    assert rep["violations"] == [], rep["netchaos_replay"]
    # client links really rode the plan (session-named links exist)
    assert rep["netchaos"]["links"] > 2


# -- the slow multi-process soak ---------------------------------------------


def _proc_env(extra=None):
    import os
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    env.update(extra or {})
    return env


@pytest.mark.slow
def test_fleet_soak_processes_under_netchaos(tmp_path):
    """3 real node processes over a shared FileKV spool, every
    process armed with the SAME GRAFT_NETCHAOS plan (lossy, slow,
    briefly partitioned links): the fleet still converges to
    fingerprint-equal snapshots holding every acked value."""
    import os
    import subprocess
    import sys
    netchaos = "77:drop=0.1;delay=2-20@0.5;part=n2|n0+n1@20-60"
    print("REPLAY: GRAFT_NETCHAOS=" + netchaos)
    spool = str(tmp_path / "fleet-kv")
    procs, ports = {}, {}
    try:
        for n in ("n0", "n1", "n2"):
            procs[n] = subprocess.Popen(
                [sys.executable, "-m", "crdt_graph_tpu.cluster",
                 "--cpu", "--name", n, "--kv-dir", spool,
                 "--port", "0", "--ttl", "2.0",
                 "--ae-interval", "0.2"],
                cwd=os.path.join(os.path.dirname(__file__), ".."),
                env=_proc_env({"GRAFT_NETCHAOS": netchaos}),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            line = procs[n].stdout.readline()
            assert line.startswith("READY "), line
            info = json.loads(line[len("READY "):])
            ports[n] = int(info["addr"].rsplit(":", 1)[1])
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            views = {n: json.loads(req(p, "GET", "/cluster")[1])
                     for n, p in ports.items()}
            if all(len(v["members"]) == 3 for v in views.values()):
                break
            time.sleep(0.1)
        else:
            pytest.fail("fleet membership never stabilized")

        acked = []
        for k in range(8):
            rid = 20 + k
            entry = ports[f"n{k % 3}"]
            assert _post_retry(entry, "soak0", _chain(rid, 40),
                               deadline_s=120)
            acked.extend(f"r{rid}:{c}" for c in range(1, 41))
        # convergence: equal replica-independent fingerprints + every
        # acked value everywhere, THROUGH the lossy plan
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            fps = {}
            for n, p in ports.items():
                try:
                    st, raw, hdr = req(p, "GET", "/docs/soak0")
                except OSError:
                    break
                if st != 200:
                    break
                fps[n] = hdr["X-State-Fingerprint"]
            if len(fps) == 3 and len(set(fps.values())) == 1:
                break
            time.sleep(0.5)
        assert len(set(fps.values())) == 1, (fps, netchaos)
        st, raw, _ = req(ports["n2"], "GET", "/docs/soak0")
        got = set(json.loads(raw)["values"])
        missing = [v for v in acked if v not in got]
        assert not missing, (missing[:5], netchaos)
    finally:
        import signal
        for p in procs.values():
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for p in procs.values():
            try:
                p.wait(20)
            except subprocess.TimeoutExpired:
                p.kill()
