"""The benchmark workload generators must produce causally valid logs whose
kernel merge matches the sequential oracle — otherwise the benchmarks would
time garbage."""
import numpy as np
import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu.bench import workloads
from crdt_graph_tpu.codec import packed
from crdt_graph_tpu.core import operation as op_mod
from crdt_graph_tpu.ops import merge, view


def oracle_merge(ops):
    tree = crdt.init(99)
    for op in ops:
        tree = tree.apply(op)
    return tree


@pytest.mark.parametrize("gen", [
    lambda: workloads.editor_replay(300),
    lambda: workloads.two_replica_interleaved(400, rounds=10),
    lambda: workloads.nested_tree(500, n_replicas=4),
    lambda: workloads.tombstone_heavy(320, n_replicas=8),
])
def test_generator_oracle_parity(gen):
    ops = gen()
    want = oracle_merge(ops).visible_values()
    p = packed.pack(ops)
    t = view.to_host(merge.materialize(p.arrays()))
    assert view.visible_values(t, p.values) == want
    # every op must actually apply (valid by construction)
    st = view.statuses(t, p.num_ops)
    assert set(st) <= {"applied"}, set(st)


def test_chain_workload_matches_op_form():
    arrays = workloads.chain_workload(4, 64)
    ops = [crdt.Add(int(arrays["ts"][i]), (int(arrays["anchor_ts"][i]),), i)
           for i in np.argsort(arrays["pos"])]
    want = oracle_merge(ops).visible_values()
    t = view.to_host(merge.materialize(arrays))
    assert view.visible_values(t, list(range(64))) == want
    assert int(t.num_visible) == 64


def test_tombstone_heavy_is_tombstone_heavy():
    ops = workloads.tombstone_heavy(320, n_replicas=8)
    dels = sum(1 for o in ops if isinstance(o, crdt.Delete))
    adds = sum(1 for o in ops if isinstance(o, crdt.Add))
    assert dels / adds == pytest.approx(0.9, abs=0.02)


def test_nested_tree_reaches_depth():
    ops = workloads.nested_tree(500, n_replicas=4, depth=8)
    deepest = max(len(op.path) for op in ops)
    assert deepest >= 8


@pytest.mark.parametrize("gen,n", [
    (lambda: workloads.descending_chains(16, 128), 128),
    (lambda: workloads.comb_pairs(200), 200),
    (lambda: workloads.deep_paths(4, 403), 403),
])
def test_adversarial_generator_oracle_parity(gen, n):
    arrays = gen()
    ops = workloads.unpack_ops(arrays)
    assert len(ops) == n
    want = oracle_merge(ops).visible_values()
    t = view.to_host(merge.materialize(
        {k: np.asarray(v) for k, v in arrays.items()}))
    vals = list(range(len(ops)))
    assert view.visible_values(t, vals) == want
    st = view.statuses(t, len(ops))
    assert set(st) <= {"applied"}, set(st)


def test_deep_paths_reaches_max_depth():
    arrays = workloads.deep_paths(4, 403, max_depth=16)
    assert int(arrays["depth"].max()) == 16


def test_chain_expected_ts_matches_oracle():
    arrays = workloads.chain_workload(4, 64)
    ops = workloads.unpack_ops(arrays)
    tree = oracle_merge(ops)
    got = [n for n in _visible_ts(tree)]
    assert got == list(workloads.chain_expected_ts(4, 64))


def _visible_ts(tree):
    out = []
    tree.walk(lambda n, acc: (crdt.TAKE, acc.append(n.timestamp) or acc),
              out)
    return out


@pytest.mark.parametrize("gen,exp", [
    (lambda: workloads.descending_chains(16, 128),
     lambda: workloads.descending_expected_ts(16, 128)),
    (lambda: workloads.comb_pairs(200),
     lambda: workloads.comb_expected_ts(200)),
    (lambda: workloads.deep_paths(4, 403),
     lambda: workloads.deep_expected_ts(4, 403)),
])
def test_adversarial_closed_forms_match_oracle(gen, exp):
    """The closed-form visible sequences the full-scale sweep asserts
    against must themselves match the oracle at small scale."""
    ops = workloads.unpack_ops(gen())
    tree = oracle_merge(ops)
    assert _visible_ts(tree) == list(exp())


def test_runner_smoke():
    from crdt_graph_tpu.bench import runner
    rows = runner.run([1], repeats=1)
    assert rows and rows[0]["n_ops"] == 1000
    assert 0 < rows[0]["num_visible"] <= rows[0]["num_nodes"]
    assert rows[0]["ops_per_sec"] > 0
    assert rows[0]["order_check"] == "exact"
    assert rows[0]["audit"]["ok"]


def test_operations_since_roundtrip_on_workload():
    """The generated logs survive the anti-entropy path: replaying
    operations_since(0) from a merged oracle reproduces the tree."""
    ops = workloads.editor_replay(200)
    tree = oracle_merge(ops)
    replay = crdt.init(7).apply(tree.operations_since(0))
    assert replay.visible_values() == tree.visible_values()
