"""Test harness configuration.

JAX tests run on a simulated 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware (the driver separately dry-runs the multi-chip
path; benches run on the real chip).

The environment force-registers a TPU PJRT plugin at interpreter start
(sitecustomize) and pins ``JAX_PLATFORMS`` to it; plugin registration may
also rewrite the platform list.  Tests must never touch the TPU tunnel —
a concurrently running bench would deadlock on the device grant — so we both
scrub the env and override the jax config explicitly before any backend
initialises.
"""
import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
