"""Test harness configuration.

JAX tests run on a simulated 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware (the driver separately dry-runs the multi-chip
path; benches run on the real chip).

The environment force-registers a TPU PJRT plugin at interpreter start
(sitecustomize) and pins ``JAX_PLATFORMS`` to it; plugin registration may
also rewrite the platform list.  Tests must never touch the TPU tunnel —
a concurrently running bench would deadlock on the device grant — so we both
scrub the env and override the jax config explicitly before any backend
initialises.  The scrub logic lives in crdt_graph_tpu.utils.hostenv (shared
with __graft_entry__); it is loaded here by file path so nothing else of the
package imports before the env is clean.
"""
import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "_hostenv",
    os.path.join(os.path.dirname(__file__), "..", "crdt_graph_tpu",
                 "utils", "hostenv.py"))
_hostenv = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_hostenv)
_hostenv.scrub_tpu_env(8)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Arm the engine's vouch tripwire for the whole suite: every batch that
# reaches the kernel's cond-free exhaustive mode is re-audited on host
# first (engine._mode), so a producer bug breaking the hint-completeness
# invariant fails a test loudly instead of silently mis-resolving.
os.environ.setdefault("GRAFT_DEBUG_VOUCH", "1")


# -- shared HTTP-service fixtures (test_service, test_elm_interop) --------

import json as _json                   # noqa: E402
import threading as _threading         # noqa: E402
from http.client import HTTPConnection as _HTTPConnection  # noqa: E402

import pytest as _pytest               # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow'); run ad hoc "
        "for acceptance-scale workloads (e.g. the 1M-op serving soak)")


@_pytest.fixture(autouse=True)
def _reset_process_wide_observability():
    """The span registry and the default flight recorder are
    process-wide (by design — they are the production post-mortem
    surface), which made span assertions depend on test ORDER: whichever
    test touched a ServingEngine first left ``serve.*`` spans behind for
    every later assertion.  Reset both after every test so each test
    observes only its own telemetry (ISSUE 5 satellite)."""
    yield
    from crdt_graph_tpu.obs import flight as _flight
    from crdt_graph_tpu.utils import profiling as _profiling
    _profiling.reset_spans()
    _flight.reset_default_recorder()


@_pytest.fixture()
def server():
    from crdt_graph_tpu.service import make_server
    srv = make_server(port=0)
    thread = _threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield srv
    srv.shutdown()
    srv.server_close()


@_pytest.fixture()
def req():
    def _req(srv, method, path, body=None):
        conn = _HTTPConnection("127.0.0.1", srv.server_port, timeout=30)
        conn.request(method, path, body=body)
        resp = conn.getresponse()
        payload = _json.loads(resp.read().decode())
        conn.close()
        return resp.status, payload
    return _req
