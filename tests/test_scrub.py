"""Partition & bit-rot chaos, disk half (ISSUE 14): the checksum
scrub on the maintenance lane, quarantine-on-corruption (atomic
manifest rewrite, typed refusals — never silent wrong data), and
scrub-with-peer-repair over the ordinary ``packed_since_window``
machinery, converging fingerprint-equal.

Corruption taxonomy pinned here (satellite): an injected crc flip in a
cold segment, a base chunk, and a matz artifact must each surface as
quarantine + repair (fleet) or typed error + warned fallback (single
node) — reads never observe the corrupt bytes either way.
"""
import glob
import json
import os
import time
from http.client import HTTPConnection

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.cluster import FleetServer, MemoryKV
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.codec import packed as packed_mod
from crdt_graph_tpu.core.errors import CheckpointError
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.obs import flight as flight_mod
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.serve import ServingEngine


def ts(r, c):
    return r * 2**32 + c


def _chain(rid, n, start=1, prev=0):
    ops = []
    for c in range(start, start + n):
        ops.append(Add(ts(rid, c), (prev,), f"r{rid}:{c}"))
        prev = ts(rid, c)
    return json_codec.dumps(Batch(tuple(ops)))


def _flip_byte(path):
    """One bit-rot event: flip a byte inside the DATA of the largest
    zip member (a flip in a zip local header is benign — zipfile reads
    sizes from the central directory — and legitimately not flagged;
    a data flip must always fail the member CRC)."""
    import struct
    import zipfile
    with zipfile.ZipFile(path) as z:
        info = max(z.infolist(), key=lambda i: i.compress_size)
    with open(path, "r+b") as f:
        f.seek(info.header_offset + 26)
        fn_len, extra_len = struct.unpack("<HH", f.read(4))
        off = (info.header_offset + 30 + fn_len + extra_len
               + info.compress_size // 2)
        f.seek(off)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))


def req(port, method, path, body=None, headers=None, timeout=120):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw, dict(resp.getheaders())
    finally:
        conn.close()


def _window_chain(port, doc, limit=50):
    out, since = [], 0
    for _ in range(1000):
        st, raw, hdr = req(port, "GET",
                           f"/docs/{doc}/ops?since={since}"
                           f"&limit={limit}")
        assert st == 200, (st, raw[:200])
        out.append(raw)
        if hdr.get("X-Since-More") != "1":
            return out
        since = int(hdr["X-Since-Next"])
    pytest.fail("window chain never terminated")


# -- verify_packed_npz (the scrub's checksum primitive) ----------------------


def test_verify_packed_npz_catches_flips(tmp_path):
    from crdt_graph_tpu import engine as engine_mod
    p = packed_mod.pack([Add(ts(1, c), (0,), c) for c in range(1, 9)])
    path = str(tmp_path / "seg.npz")
    engine_mod.write_packed_npz(path, p, {"num_ops": p.num_ops})
    assert packed_mod.verify_packed_npz(path) is None
    assert packed_mod.verify_packed_npz(
        path, expect_ops=p.num_ops) is None
    assert packed_mod.verify_packed_npz(path, expect_ops=99)
    _flip_byte(path)
    assert packed_mod.verify_packed_npz(path) is not None
    assert packed_mod.verify_packed_npz(
        str(tmp_path / "missing.npz")) is not None


# -- single node: quarantine + typed errors + warned matz fallback -----------


@pytest.fixture
def _small_tiers(monkeypatch):
    monkeypatch.setenv("GRAFT_OPLOG_GC_SEGS", "2")
    monkeypatch.setenv("GRAFT_MATZ_TAIL_OPS", "64")


def _fill_doc(eng, doc_id, rid, n_chains=6, per=100):
    prev = 0
    for k in range(n_chains):
        body = _chain(rid, per, start=k * per + 1, prev=prev)
        prev = ts(rid, (k + 1) * per)
        accepted, _ = eng.submit(doc_id, body)
        assert accepted
    assert eng.flush(timeout=120)


def test_single_node_corruption_taxonomy(tmp_path, _small_tiers):
    ddir = str(tmp_path / "srv")
    eng = ServingEngine(durable_dir=ddir, oplog_hot_ops=64,
                        flight=flight_mod.FlightRecorder())
    try:
        _fill_doc(eng, "tax", 3)
        doc = eng.get("tax")
        docdir = os.path.join(ddir, "doc-tax")
        # flip LIVE tier files (glob would also match folded tombs a
        # pinned view is still deferring — those are not scrubbed)
        log = doc.tree._log
        segs = [s.path for s in log._cold]
        bases = [s.path for s in log._bases]
        entry = log.matz_entry
        matz = [os.path.join(docdir, entry["file"])] if entry else []
        assert segs and bases and matz, (segs, bases, matz)

        # a clean scrub finds nothing
        doc.run_scrub()
        assert doc.scrub_stats["corrupt"] == 0
        assert doc.scrub_stats["checked"] > 0

        # flip one cold segment, one base chunk, and the matz artifact
        _flip_byte(segs[-1])
        _flip_byte(bases[0])
        _flip_byte(matz[-1])
        doc.run_scrub()
        st = doc.scrub_stats
        assert st["corrupt"] == 2            # the two TIER files
        assert st["matz_dropped"] == 1       # matz: dropped, re-derived
        # single node: no peer to heal from — quarantine stands; no
        # repair was ATTEMPTED, so repair_failed stays 0 (the standing
        # condition is the quarantined gauge, not a failure counter)
        assert st["repaired"] == 0 and st["repair_failed"] == 0
        tele = doc.tree._log.telemetry()
        assert tele["quarantined"] == 2
        assert tele["quarantines"] == 2
        assert doc.tree._log.matz_entry is None

        # typed refusal on touch: a window over the quarantined range
        # raises CheckpointError — never the corrupt bytes
        view = doc.tree._log.view()
        with pytest.raises(CheckpointError, match="quarantined"):
            view.window(0, 50)
        # published-snapshot reads (values) keep serving
        assert len(doc.snapshot()) == 600
        # and the LIVE node still ACKS writes (its mirror is resident;
        # only a restart would need the quarantined rows)
        accepted, _ = eng.submit("tax", _chain(
            3, 5, start=601, prev=ts(3, 600)))
        assert accepted
        # the scrub is idempotent: already-quarantined files are not
        # re-counted
        doc.run_scrub()
        assert doc.scrub_stats["corrupt"] == 2
    finally:
        eng.close()


def test_quarantine_survives_restart_manifest_roundtrip(
        tmp_path, _small_tiers):
    ddir = str(tmp_path / "srv")
    eng = ServingEngine(durable_dir=ddir, oplog_hot_ops=64,
                        flight=flight_mod.FlightRecorder())
    _fill_doc(eng, "rb", 4)
    docdir = os.path.join(ddir, "doc-rb")
    # corrupt the EARLIEST live tier file (inside matz coverage) and
    # scrub so the quarantine lands in the manifest
    doc = eng.get("rb")
    live = doc.tree._log._bases + doc.tree._log._cold
    _flip_byte(live[0].path)
    doc.run_scrub()
    assert doc.tree._log.telemetry()["quarantined"] == 1
    manifest = json.load(open(os.path.join(docdir, "manifest.json")))
    assert any(e.get("quarantined")
               for e in manifest["base_chunks"] + manifest["segments"])
    eng.close()

    # restart: recovery inherits the quarantine instead of bricking —
    # the matz artifact covers state materialization, values serve,
    # and the hole stays a typed refusal until a peer repairs it
    eng2 = ServingEngine(durable_dir=ddir, oplog_hot_ops=64,
                         flight=flight_mod.FlightRecorder())
    try:
        doc2 = eng2.get("rb")
        assert doc2 is not None and doc2.recovered
        tele = doc2.tree._log.telemetry()
        assert tele["quarantined"] == 1
        assert len(doc2.snapshot()) == 600   # matz-backed state
        with pytest.raises(CheckpointError, match="quarantined"):
            doc2.tree._log.view().window(0, 50)
        # scrub on the restored node: still no peer — stands
        doc2.run_scrub()
        assert doc2.tree._log.telemetry()["quarantined"] == 1
        assert doc2.scrub_stats["repair_failed"] == 0
    finally:
        eng2.close()


def test_restart_inherited_quarantine_keeps_add_index(
        tmp_path, _small_tiers):
    """The quarantine manifest entry persists the PRE-corruption add
    index (ISSUE 16 satellite): a restart-inherited quarantine still
    holds the row fingerprint of the healthy bytes, so it can refuse a
    diverged peer's repair instead of trusting whatever it is handed."""
    import numpy as np
    ddir = str(tmp_path / "srv")
    eng = ServingEngine(durable_dir=ddir, oplog_hot_ops=64,
                        flight=flight_mod.FlightRecorder())
    _fill_doc(eng, "qi", 5)
    doc = eng.get("qi")
    live = doc.tree._log._bases + doc.tree._log._cold
    victim = live[0]
    want_ts = np.array(victim.add_ts, copy=True)
    want_pos = np.array(victim.add_pos, copy=True)
    _flip_byte(victim.path)
    doc.run_scrub()
    assert doc.tree._log.telemetry()["quarantined"] == 1
    manifest = json.load(open(
        os.path.join(ddir, "doc-qi", "manifest.json")))
    entry = next(e for e in
                 manifest["base_chunks"] + manifest["segments"]
                 if e.get("quarantined"))
    # the descriptor carries the healthy-bytes index verbatim
    assert "add_index" in entry
    eng.close()

    eng2 = ServingEngine(durable_dir=ddir, oplog_hot_ops=64,
                         flight=flight_mod.FlightRecorder())
    try:
        d2 = eng2.get("qi")
        quarantined = d2.tree._log.quarantined_segments()
        assert len(quarantined) == 1
        seg = quarantined[0]
        # the restart INHERITED the index rather than zeroing it
        assert seg.index_ok
        assert np.array_equal(seg.add_ts, want_ts)
        assert np.array_equal(seg.add_pos, want_pos)
        # so a diverged peer is still refused post-restart
        bogus = packed_mod.pack(
            [Add(ts(99, c + 1), (0,), "x")
             for c in range(seg.length)])
        assert not d2.tree._log.repair_segment(seg, bogus)
        assert d2.tree._log.telemetry()["quarantined"] == 1
    finally:
        eng2.close()


# -- fleet: scrub-with-peer-repair -------------------------------------------


def _spawn_durable_fleet(tmp_path, names, **node_kw):
    kv = MemoryKV()
    fleet = {}
    for n in names:
        eng = ServingEngine(
            durable_dir=os.path.join(str(tmp_path), n),
            oplog_hot_ops=64, flight=flight_mod.FlightRecorder())
        fleet[n] = FleetServer(n, kv, engine=eng, ttl_s=600.0,
                               ae_interval_s=3600.0, **node_kw)
    for fs in fleet.values():
        fs.node.refresh_ring()
    return fleet


def _stop_fleet(fleet):
    for fs in fleet.values():
        try:
            fs.stop()
        except Exception:  # noqa: BLE001 — teardown boundary
            pass


def _doc_owned_by(ring, owner, prefix="doc"):
    for i in range(500):
        d = f"{prefix}{i}"
        if ring.primary(d) == owner:
            return d
    pytest.fail(f"no doc routed to {owner}")


def test_fleet_scrub_repairs_from_peer_windows_byte_identical(
        tmp_path, _small_tiers):
    """The acceptance scenario: a corrupt cold file on one replica is
    detected by scrub, quarantined, re-fetched from a peer through the
    ordinary window machinery, and the doc's full window chain stays
    byte-identical to the uncorrupted replica — reads that touch the
    hole meanwhile get typed 503s, never the corrupt bytes."""
    fleet = _spawn_durable_fleet(tmp_path, ("n0", "n1"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n0", prefix="rep")
        prev = 0
        for k in range(6):
            st, raw, _ = req(fleet["n0"].port, "POST",
                             f"/docs/{doc}/ops",
                             body=_chain(5, 100, start=k * 100 + 1,
                                         prev=prev))
            prev = ts(5, (k + 1) * 100)
            assert st == 200, raw
        assert fleet["n1"].node.antientropy.sync_now() == {"n0": True}
        for fs in fleet.values():
            fs.node.engine.flush(timeout=120)

        chain0 = _window_chain(fleet["n0"].port, doc)
        assert chain0 == _window_chain(fleet["n1"].port, doc)

        docdir1 = os.path.join(str(tmp_path), "n1", f"doc-{doc}")
        segs = sorted(glob.glob(os.path.join(docdir1, "seg-*.npz")))
        assert len(segs) >= 3
        d1 = fleet["n1"].node.engine.get(doc)

        # FIRST file (the since=0 fetch path) and a MIDDLE file (the
        # terminator-anchored path), one after the other
        def _chain_hits_503():
            """Walk the window chain; True when it reaches the
            quarantined hole and gets the typed refusal (windows
            BEFORE the hole legitimately keep serving)."""
            since = 0
            for _ in range(1000):
                st, raw, hdr = req(
                    fleet["n1"].port, "GET",
                    f"/docs/{doc}/ops?since={since}&limit=50")
                if st == 503:
                    assert "Retry-After" in hdr
                    return True
                assert st == 200, raw[:200]
                if hdr.get("X-Since-More") != "1":
                    return False
                since = int(hdr["X-Since-Next"])
            pytest.fail("window chain never terminated")

        for victim in (segs[0], segs[len(segs) // 2]):
            _flip_byte(victim)
            rep = d1.tree._log.scrub()       # quarantine only
            print("scrub report:", rep)
            # pre-repair: the chain refuses (typed 503) at the hole —
            # the corrupt bytes are never served
            assert _chain_hits_503()
            # values (published snapshot) keep serving
            st, _, _ = req(fleet["n1"].port, "GET", f"/docs/{doc}")
            assert st == 200
            # the scrub pass heals from the peer
            d1.run_scrub()
            assert d1.tree._log.telemetry()["quarantined"] == 0
            assert _window_chain(fleet["n1"].port, doc) == chain0, \
                "post-repair windows must be byte-identical"

        st = d1.scrub_stats
        assert st["repaired"] == 2
        # the corruption was counted by the direct log scrubs above
        # (run_scrub skips already-quarantined files)
        assert d1.tree._log.telemetry()["quarantines"] == 2
        assert fleet["n1"].node.counters["repair_fetches"] == 2
        # fingerprints equal across the fleet throughout
        _, _, h0 = req(fleet["n0"].port, "GET", f"/docs/{doc}")
        _, _, h1 = req(fleet["n1"].port, "GET", f"/docs/{doc}")
        assert h0["X-State-Fingerprint"] == h1["X-State-Fingerprint"]

        # the crdt_scrub_* families ride the strict scrape contract
        st_, raw, _ = req(fleet["n1"].port, "GET", "/metrics/prom")
        fams = prom_mod.parse_text(raw.decode())
        for fam in ("crdt_scrub_runs_total",
                    "crdt_scrub_files_checked_total",
                    "crdt_scrub_corrupt_total",
                    "crdt_scrub_repaired_total",
                    "crdt_scrub_repair_failed_total",
                    "crdt_scrub_matz_dropped_total",
                    "crdt_scrub_quarantined_segments",
                    "crdt_peer_health",
                    "crdt_cluster_repair_fetches_total"):
            assert fam in fams, fam
    finally:
        _stop_fleet(fleet)


def test_fleet_repair_refuses_diverged_peer_rows(tmp_path,
                                                 _small_tiers):
    """A peer whose rows do not match the quarantined segment's
    resident add index must be REFUSED — the quarantine stands rather
    than poisoning the log with diverged history."""
    fleet = _spawn_durable_fleet(tmp_path, ("n0", "n1"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n0", prefix="div")
        prev = 0
        for k in range(4):
            st, raw, _ = req(fleet["n0"].port, "POST",
                             f"/docs/{doc}/ops",
                             body=_chain(6, 100, start=k * 100 + 1,
                                         prev=prev))
            prev = ts(6, (k + 1) * 100)
            assert st == 200, raw
        assert fleet["n1"].node.antientropy.sync_now() == {"n0": True}
        for fs in fleet.values():
            fs.node.engine.flush(timeout=120)
        d1 = fleet["n1"].node.engine.get(doc)
        segs = sorted(glob.glob(os.path.join(
            str(tmp_path), "n1", f"doc-{doc}", "seg-*.npz")))
        _flip_byte(segs[1])
        d1.tree._log.scrub()
        quarantined = d1.tree._log.quarantined_segments()
        assert len(quarantined) == 1
        seg = quarantined[0]
        # hand the repair WRONG rows (right length, different ts set)
        bogus = packed_mod.pack(
            [Add(ts(99, c + 1), (0,), "x") for c in range(seg.length)])
        assert not d1.tree._log.repair_segment(seg, bogus)
        assert d1.tree._log.telemetry()["quarantined"] == 1
        # the honest fetch still heals it
        d1.run_scrub()
        assert d1.tree._log.telemetry()["quarantined"] == 0
    finally:
        _stop_fleet(fleet)


def test_scrub_cadence_runs_on_maintenance_lane(tmp_path,
                                                monkeypatch):
    """GRAFT_SCRUB_INTERVAL_S arms the maintenance worker's policy
    tick: corruption is found and healed WITHOUT anyone calling
    run_scrub — the background lane owns it."""
    monkeypatch.setenv("GRAFT_SCRUB_INTERVAL_S", "0.3")
    monkeypatch.setenv("GRAFT_MATZ_TAIL_OPS", "64")
    fleet = _spawn_durable_fleet(tmp_path, ("n0", "n1"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n0", prefix="cad")
        prev = 0
        for k in range(4):
            st, raw, _ = req(fleet["n0"].port, "POST",
                             f"/docs/{doc}/ops",
                             body=_chain(7, 100, start=k * 100 + 1,
                                         prev=prev))
            prev = ts(7, (k + 1) * 100)
            assert st == 200, raw
        assert fleet["n1"].node.antientropy.sync_now() == {"n0": True}
        for fs in fleet.values():
            fs.node.engine.flush(timeout=120)
        d1 = fleet["n1"].node.engine.get(doc)
        segs = sorted(glob.glob(os.path.join(
            str(tmp_path), "n1", f"doc-{doc}", "seg-*.npz")))
        _flip_byte(segs[0])
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if d1.scrub_stats["repaired"] >= 1:
                break
            time.sleep(0.1)
        assert d1.scrub_stats["repaired"] >= 1, d1.scrub_stats
        assert d1.tree._log.telemetry()["quarantined"] == 0
        maint = fleet["n1"].node.engine.maintenance
        assert maint is not None
        assert maint.stats()["tasks_done"].get("scrub", 0) >= 1
        assert maint.stats()["scrubs_queued"] >= 1
    finally:
        _stop_fleet(fleet)
