"""Tier-1 wiring for scripts/serve_smoke.py: the serving engine's
end-to-end gate (concurrent pushes + reads across documents over real
HTTP, convergence, clean shutdown) runs fast and unmarked so every
tier-1 pass exercises the scheduler."""
import importlib.util
import os

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

_spec = importlib.util.spec_from_file_location(
    "_serve_smoke",
    os.path.join(os.path.dirname(__file__), "..", "scripts",
                 "serve_smoke.py"))
_serve_smoke = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_serve_smoke)


def test_fleet_smoke_end_to_end():
    """--fleet mode (ISSUE 7): 3 servers, one write per server, RYW
    through a DIFFERENT server after anti-entropy, fingerprint-equal
    reads everywhere, cluster scrape surface on every member."""
    summary = _serve_smoke.run_fleet(n_servers=3, n_docs=2)
    assert summary["writes"] == 6
    assert summary["cross_server_ryw"] == 6
    assert summary["forwarded"] > 0
    assert summary["fleet0"]["visible"] == 15      # 3 servers x 5 adds


def test_fleet_procs_shm_exact_ledger():
    """--fleet-procs mode (ISSUE 17): 3 REAL processes converge on one
    document over 4 generations of the host-shared body cache — per
    generation exactly one encode on the whole host (misses +1) and an
    attach from everyone else (hits +(N-1)), zero degraded attaches,
    zero leaked segments.  The assertions live in run_fleet_procs; the
    summary re-pins the ledger at the tier-1 surface."""
    summary = _serve_smoke.run_fleet_procs(n_procs=3, gens=4)
    assert summary["misses"] == 4
    assert summary["hits"] == 8
    assert summary["shared_bytes"] > 0


def test_mergetier_smoke_end_to_end():
    """--mergetier mode (disaggregated merge tier): 3 docs through a
    REAL worker server over HTTP coalesce into ONE width-3 launch,
    zero fallbacks, bit-identical to a local-only control engine, and
    both scrape surfaces (front-end + worker) strict-parse."""
    summary = _serve_smoke.run_mergetier(n_docs=3, n_ops=1200)
    assert summary["remote_docs"] == 3
    assert summary["batch_width_max"] == 3
    assert summary["launches"] == 1
    assert summary["fallbacks"] == {}


def test_serve_smoke_end_to_end():
    summary = _serve_smoke.run(n_docs=4, writers_per_doc=3, deltas=3,
                               delta_size=8)
    assert len([k for k in summary if k.startswith("smoke")]) == 4
    assert summary["scheduler"]["queue_depth_total"] == 0
    # the telemetry exposition is pinned in tier-1: the smoke scraped
    # /metrics/prom (strict parse) and /debug/flight (trace-id
    # coverage) before shutting down
    assert summary["flight"]["records_total"] >= 1
    assert summary["flight"]["trace_ids_seen"] >= 36   # 4×3×3 pushes
    # pooled keep-alive clients (ISSUE 15): connections were REUSED
    # (the smoke asserts reuses > opens internally too) and the old
    # TIME_WAIT transport flake is gone by construction — a clean run
    # fires zero genuine retries
    assert summary["connpool"]["reuses"] > summary["connpool"]["opens"]
    assert summary["transport_retries"] == 0
