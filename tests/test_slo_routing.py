"""SLO routing contract (VERDICT r5 next-7): a delta at or under
DELTA_THRESHOLD must NEVER dispatch the device kernel — the interactive
editor path is the O(delta) host mirror, whatever the service layers
above do with the batch."""
import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

import crdt_graph_tpu as crdt  # noqa: E402
from crdt_graph_tpu import engine as engine_mod  # noqa: E402
from crdt_graph_tpu.core.operation import Add, Batch  # noqa: E402
from crdt_graph_tpu.ops import merge as merge_mod  # noqa: E402

OFFSET = 2**32


def _chain(replica, counter, anchor, size):
    ops = []
    prev = anchor
    for _ in range(size):
        counter += 1
        ts = replica * OFFSET + counter
        ops.append(Add(ts, (prev,), counter))
        prev = ts
    return Batch(tuple(ops)), counter, prev


@pytest.fixture()
def no_kernel(monkeypatch):
    """Arms a tripwire: any device-kernel dispatch fails the test."""
    def _boom(*a, **k):
        raise AssertionError("device kernel dispatched for a "
                             "sub-threshold delta")
    monkeypatch.setattr(merge_mod, "materialize", _boom)
    monkeypatch.setattr(engine_mod.merge_mod, "materialize", _boom,
                        raising=False)
    yield


def test_engine_small_deltas_stay_on_host_path(no_kernel):
    t = engine_mod.init(1)
    counter, anchor = 0, 0
    # threshold-sized, single-op, and mid-sized deltas from a peer
    for size in (1, 64, engine_mod.DELTA_THRESHOLD):
        delta, counter, anchor = _chain(9, counter, anchor, size)
        t.apply(delta)
    assert len(t.visible_values()) == counter


def test_serving_engine_small_deltas_stay_on_host_path(no_kernel):
    from crdt_graph_tpu.codec import json_codec
    from crdt_graph_tpu.serve import ServingEngine

    eng = ServingEngine()
    counter, anchor = 0, 0
    try:
        for size in (1, 64, engine_mod.DELTA_THRESHOLD):
            delta, counter, anchor = _chain(9, counter, anchor, size)
            accepted, _ = eng.submit("slo", json_codec.dumps(delta))
            assert accepted
        snap = eng.get("slo").snapshot
        assert snap is not None
    finally:
        eng.close()


def test_above_threshold_crosses_to_kernel(monkeypatch):
    """The complementary direction: once packed_route says kernel, the
    kernel really is what runs (so the SLO table's two sides are the
    two real paths, not one path measured twice)."""
    calls = []
    real = merge_mod.materialize

    def _spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    monkeypatch.setattr(engine_mod.merge_mod, "materialize", _spy)
    t = engine_mod.init(1)
    n = max(4 * engine_mod.DELTA_THRESHOLD, 1100)
    delta, _, _ = _chain(9, 0, 0, n)
    t.apply(delta)
    assert calls, "large delta should have dispatched the kernel"
    assert len(t.visible_values()) == n
