"""Host-shared encoded-body cache (ISSUE 17; docs/SERVING.md
§Shared-memory body cache): N processes serve ONE copy of each
generation's encoded bodies.  Pins the sharing + accounting contract,
the segment-lifetime rules (a held memoryview survives the publish
swap, every release, and the unlink — no SIGBUS), the
``GRAFT_READCACHE=0`` dual-tier bypass, cross-process attach, and the
prom family gating.
"""
import json
import os
import subprocess
import sys
import uuid

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.serve import ServingEngine

OFF = 2**32


def chain_ops(r, n, start=1):
    out = []
    prev = r * OFF + start - 1 if start > 1 else 0
    for c in range(start, start + n):
        t = r * OFF + c
        out.append(Add(t, (prev,), f"v{r}.{c}"))
        prev = t
    return out


def _submit(eng, doc, ops):
    return eng.submit(doc, json_codec.dumps(Batch(tuple(ops))))


@pytest.fixture()
def shm_ns(monkeypatch):
    """A unique per-test shm namespace, so parallel test runs (and
    leftovers from killed ones) can never collide."""
    ns = f"t{uuid.uuid4().hex[:10]}"
    monkeypatch.setenv("GRAFT_SHMCACHE_NS", ns)
    return ns


def _engine(**kw):
    kw.setdefault("oplog_hot_ops", 8)
    kw.setdefault("shmcache", True)
    return ServingEngine(**kw)


def _shm_listing(ns):
    try:
        return sorted(f for f in os.listdir("/dev/shm")
                      if ns in f and not f.endswith(".manifest"))
    except OSError:
        return []


def test_two_engines_share_one_segment(shm_ns):
    """Converged engines (same doc state → same fingerprint) agree on
    the segment name without coordination: the first encode publishes
    (miss), the second ATTACHES (hit) and serves the same bytes."""
    e1, e2 = _engine(), _engine()
    assert e1.shmcache is not None and e2.shmcache is not None
    ops = chain_ops(1, 12)
    for eng in (e1, e2):
        ok, _ = _submit(eng, "d", ops)
        assert ok
    s1, s2 = e1.get("d").read_view(), e2.get("d").read_view()
    assert s1.state_fingerprint() == s2.state_fingerprint()
    b1 = bytes(s1.values_body())
    b2 = bytes(s2.values_body())
    assert b1 == b2
    assert bytes(s1.clock_body()) == bytes(s2.clock_body())
    st1 = e1.shmcache.stats.snapshot()
    st2 = e2.shmcache.stats.snapshot()
    assert st1["misses"] == 1 and st1["hits"] == 0, st1
    assert st2["hits"] == 1 and st2["misses"] == 0, st2
    assert s1.shm_seg_name == s2.shm_seg_name is not None
    e1.close()
    e2.close()
    assert _shm_listing(shm_ns) == [], "segments leaked past close"


def test_held_memoryview_survives_swap_release_and_unlink(shm_ns):
    """The parked-watcher / mid-write-reader lifetime contract: a
    memoryview taken from a shared segment stays valid across the
    publish swap that releases the generation's claim, across engine
    close, and across the unlink itself (POSIX keeps the mapping until
    the last map drops) — reading it can never SIGBUS."""
    eng = _engine()
    ok, _ = _submit(eng, "d", chain_ops(1, 8))
    assert ok
    snap = eng.get("d").read_view()
    mv = snap.values_body()
    assert isinstance(mv, memoryview)
    want = bytes(mv)
    seg = snap.shm_seg_name
    assert seg is not None
    # publish swap: the outgoing generation's claim is released
    ok, _ = _submit(eng, "d", chain_ops(1, 8, start=9))
    assert ok
    assert eng.flush(20)
    fresh = eng.get("d").read_view()
    assert fresh.seq > snap.seq
    assert bytes(mv) == want
    eng.close()
    # all claims dropped, name unlinked — the held view still reads
    assert _shm_listing(shm_ns) == []
    assert bytes(mv) == want
    assert json.loads(want.decode())["values"] == list(snap.values)


def test_readcache_off_bypasses_both_tiers(shm_ns, monkeypatch):
    """GRAFT_READCACHE=0 restores the per-request re-encode path: no
    shared tier is even constructed, and the wire bytes stay
    byte-identical to the dual-tier engine's."""
    cached = _engine()
    ok, _ = _submit(cached, "d", chain_ops(1, 10))
    assert ok
    want_vals = bytes(cached.get("d").read_view().values_body())
    want_clock = bytes(cached.get("d").read_view().clock_body())

    monkeypatch.setenv("GRAFT_READCACHE", "0")
    plain = _engine()
    assert plain.shmcache is None
    ok, _ = _submit(plain, "d", chain_ops(1, 10))
    assert ok
    snap = plain.get("d").read_view()
    assert bytes(snap.values_body()) == want_vals
    assert bytes(snap.clock_body()) == want_clock
    assert snap.shm_seg_name is None
    cached.close()
    plain.close()


_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.serve import ServingEngine

OFF = 2**32
ops, prev = [], 0
for c in range(1, 13):
    t = OFF + c
    ops.append(Add(t, (prev,), f"v1.{c}"))
    prev = t
eng = ServingEngine(oplog_hot_ops=8, shmcache=True)
assert eng.shmcache is not None
ok, _ = eng.submit("d", json_codec.dumps(Batch(tuple(ops))))
assert ok
snap = eng.get("d").read_view()
body = bytes(snap.values_body())
out = {"stats": eng.shmcache.stats.snapshot(),
       "seg": snap.shm_seg_name,
       "body_sha": __import__("hashlib").sha1(body).hexdigest()}
eng.close()
print(json.dumps(out))
"""


def test_cross_process_attach_single_encode(shm_ns):
    """A REAL second process converging on the same doc attaches the
    parent's segment: child stats show hits=1/misses=0 and the same
    bytes — the fleet's one-encode-per-host property."""
    import hashlib
    eng = _engine()
    ok, _ = _submit(eng, "d", chain_ops(1, 12))
    assert ok
    snap = eng.get("d").read_view()
    body = bytes(snap.values_body())
    assert snap.shm_seg_name is not None
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    got = json.loads(proc.stdout.strip().splitlines()[-1])
    assert got["seg"] == snap.shm_seg_name
    assert got["stats"]["hits"] == 1 and got["stats"]["misses"] == 0
    assert got["body_sha"] == hashlib.sha1(body).hexdigest()
    eng.close()
    assert _shm_listing(shm_ns) == []


def test_prom_shmcache_families_strict_parse(shm_ns):
    """crdt_shmcache_* renders under the strict parser when armed and
    is ABSENT on a default (shmcache-off) engine — same presence
    gating as crdt_wal_*."""
    eng = _engine()
    ok, _ = _submit(eng, "d", chain_ops(1, 8))
    assert ok
    bytes(eng.get("d").read_view().values_body())
    fams = prom_mod.parse_text(eng.render_prom())
    for fam in ("crdt_shmcache_hits_total", "crdt_shmcache_misses_total",
                "crdt_shmcache_attach_failed_total",
                "crdt_shmcache_shared_bytes_total",
                "crdt_shmcache_released_total",
                "crdt_shmcache_scavenged_total"):
        assert fam in fams, fam
        assert fams[fam]["type"] == "counter"
    sample = fams["crdt_shmcache_misses_total"]["samples"][0]
    assert sample[2] >= 1.0
    eng.close()
    off = ServingEngine(oplog_hot_ops=8)
    fams2 = prom_mod.parse_text(off.render_prom())
    assert not any(f.startswith("crdt_shmcache_") for f in fams2)
    off.close()
