"""Randomized fuzz of the native wire codec (VERDICT r3 weak-5).

``parse_pack`` is a hand-written C++ JSON parser consuming UNTRUSTED bytes
behind the public HTTP endpoint (service/http.py do_POST →
codec/packed.pack_json → native.parse_pack).  Its contract: acceptance and
output must exactly match the pure-Python path
(``json_codec.loads`` → ``packed.pack``) on EVERY input, and rejection is
always a clean ``ValueError`` — never a crash (a segfault would kill this
test process, which is the detection).  Three generators:

- structured: randomly-built valid operation payloads (wide value
  space: unicode, big ints, floats, deep-ish nesting) — must accept and
  agree column-for-column;
- mutation: valid payloads put through random byte surgery (flips,
  splices, truncations, token inserts) — accept/reject must match the
  Python path, and agreement must hold when both accept;
- byte soup: random JSON-alphabet strings — same differential contract.

The egress mirror (``encode_pack``) is fuzzed for byte-identity against
``json_codec.dumps`` on the structured corpus.

Generators are SEEDED plain ``random`` (ISSUE 3 satellite: the original
hypothesis-built strategies made the whole module a collection error on
the driver image, which ships no ``hypothesis`` — and a fuzz suite that
never runs fuzzes nothing).  Distributions mirror the old strategies:
integers cluster on the interesting boundaries (0, 2^32, the 2^62
sentinel cutoff, int64/uint64 edges), values recurse through
lists/dicts/unicode/floats.  Each test walks a fixed seed range, so CI
runs are deterministic and a failure names its seed.

A longer ASAN-instrumented loop lives in scripts/fuzz_native.py
(GRAFT_NATIVE_ASAN=1); this in-CI pass runs a bounded number of examples.
"""
import json
import math
import random

import numpy as np
import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import native
from crdt_graph_tpu.codec import json_codec, packed
from crdt_graph_tpu.core import operation as op_mod

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")

COLUMNS = ("kind", "ts", "parent_ts", "anchor_ts", "depth", "paths",
           "value_ref", "pos", "parent_pos", "anchor_pos", "target_pos")


def python_path(payload):
    try:
        return True, packed.pack(json_codec.loads(payload))
    except (ValueError, RecursionError, OverflowError):
        # DecodeError/JSONDecodeError are ValueErrors; RecursionError is
        # json.loads on pathological nesting (native: clean ValueError);
        # OverflowError is pack() on > int64 timestamps (same)
        return False, None


def native_path(payload):
    try:
        return True, native.parse_pack(payload)
    except ValueError:
        return False, None


def check_differential(payload):
    ok_n, got = native_path(payload)
    ok_p, want = python_path(payload)
    assert ok_n == ok_p, f"acceptance diverged on {payload[:300]!r}"
    if ok_n:
        assert got.num_ops == want.num_ops
        for f in COLUMNS:
            np.testing.assert_array_equal(getattr(got, f),
                                          getattr(want, f), f)
        # repr: NaN payloads break ==, bool-vs-int (True == 1) break
        # naive equality in the other direction
        assert repr(got.values) == repr(want.values)


# -- seeded generators (mirroring the old hypothesis strategies) ----------

def wire_int(rng: random.Random) -> int:
    """ts/path values clustered around the interesting boundaries (0,
    the 2^62 sentinel cutoff, int64/uint64 edges, the replica*2^32
    scheme)."""
    lo, hi = rng.choice([
        (0, 20), (2**32 - 2, 2**32 + 20), (2**62 - 2, 2**62 + 2),
        (-5, 5), (2**63 - 2, 2**63 + 2), (-2**80, 2**80)])
    return rng.randint(lo, hi)


def json_value(rng: random.Random, depth: int = 0):
    """None/bool/int/float/text leaves recursing through small lists
    and dicts (float NaN excluded, like the old strategy)."""
    kinds = ["none", "bool", "int", "float", "text"]
    if depth < 3:
        kinds += ["list", "dict"]
    k = rng.choice(kinds)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "int":
        return rng.randint(-2**70, 2**70)
    if k == "float":
        # infinities stay in (the old strategy's allow_nan=False kept
        # them too): json.dumps emits the non-standard Infinity literal
        # and the differential contract must hold on it either way
        return rng.choice([0.0, -0.0, 1e308, -1e308, 2.5, 1e-300,
                           math.inf, -math.inf,
                           rng.uniform(-1e6, 1e6)])
    if k == "text":
        alphabet = "abé☃\U0001F600\"\\\n\t {}[]:,0"
        return "".join(rng.choice(alphabet)
                       for _ in range(rng.randint(0, 12)))
    if k == "list":
        return [json_value(rng, depth + 1)
                for _ in range(rng.randint(0, 4))]
    return {"".join(rng.choice("abcdef")
                    for _ in range(rng.randint(0, 6))):
            json_value(rng, depth + 1)
            for _ in range(rng.randint(0, 4))}


def wire_op(rng: random.Random, depth: int = 0) -> dict:
    kind = rng.choice(["add", "del", "batch", "mystery"])
    if kind == "add" or (kind == "batch" and depth >= 2):
        return {"op": "add",
                "path": [wire_int(rng)
                         for _ in range(rng.randint(0, 5))],
                "ts": wire_int(rng), "val": json_value(rng)}
    if kind == "del":
        return {"op": "del",
                "path": [wire_int(rng)
                         for _ in range(rng.randint(0, 5))]}
    if kind == "batch":
        return {"op": "batch",
                "ops": [wire_op(rng, depth + 1)
                        for _ in range(rng.randint(0, 3))]}
    return {"op": "mystery", "junk": json_value(rng)}


def test_structured_payloads_agree():
    for seed in range(150):
        rng = random.Random(seed)
        check_differential(json.dumps(wire_op(rng)))


def test_mutated_payloads_agree():
    tokens = [b'{', b'}', b'[', b']', b'"', b':', b',', b'\\u0000',
              b'\\ud800', b'9' * 25, b'-', b'.', b'e99', b'null',
              b'Infinity', b'{"op":"add"', b'\xff', b'\x00', b' ']
    for seed in range(150):
        rng = random.Random(10_000 + seed)
        payload = json.dumps(wire_op(rng))
        data = bytearray(payload.encode())
        for _ in range(rng.randint(1, 8)):
            if not data:
                break
            kind = rng.randrange(5)
            i = rng.randrange(len(data))
            if kind == 0:                       # bit flip
                data[i] ^= 1 << rng.randrange(8)
            elif kind == 1:                     # delete a slice
                j = min(len(data), i + rng.randint(1, 8))
                del data[i:j]
            elif kind == 2:                     # duplicate a slice
                j = min(len(data), i + rng.randint(1, 8))
                data[i:i] = data[i:j]
            elif kind == 3:                     # insert a token
                data[i:i] = rng.choice(tokens)
            else:                               # truncate
                del data[i:]
        try:
            payload = data.decode()
        except UnicodeDecodeError:
            # non-UTF-8 bytes: the HTTP layer decodes the body before
            # the codec ever sees it, so the native contract is
            # bytes-in → it must still reject cleanly, matching Python
            # on the surrogateescape-free path
            with pytest.raises(ValueError):
                native.parse_pack(bytes(data))
            continue
        check_differential(payload)


def test_byte_soup_agrees():
    alphabet = '{}[]":,0123456789.eE+-aduloptsrbv\\ \t\n"'
    for seed in range(200):
        rng = random.Random(20_000 + seed)
        soup = "".join(rng.choice(alphabet)
                       for _ in range(rng.randint(0, 120)))
        check_differential(soup)


def test_encode_fuzz_byte_identical():
    """Egress fuzz: whatever ops pack() accepts, encode_pack must emit
    byte-identically to the Python encoder."""
    for seed in range(100):
        rng = random.Random(30_000 + seed)
        adds = [crdt.Add(rng.randint(1, 2**62 - 1),
                         tuple(rng.randint(0, 2**62 - 1)
                               for _ in range(rng.randint(0, 4))),
                         json_value(rng))
                for _ in range(rng.randint(0, 8))]
        try:
            p = packed.pack(adds)
        except ValueError:
            continue        # replica-id range rejection — nothing to encode
        assert native.encode_pack(p).decode() == \
            json_codec.dumps(op_mod.from_list(tuple(adds)))
