"""Randomized fuzz of the native wire codec (VERDICT r3 weak-5).

``parse_pack`` is a hand-written C++ JSON parser consuming UNTRUSTED bytes
behind the public HTTP endpoint (service/http.py do_POST →
codec/packed.pack_json → native.parse_pack).  Its contract: acceptance and
output must exactly match the pure-Python path
(``json_codec.loads`` → ``packed.pack``) on EVERY input, and rejection is
always a clean ``ValueError`` — never a crash (a segfault would kill this
test process, which is the detection).  Three generators:

- structured: hypothesis-built valid operation payloads (wide value
  space: unicode, big ints, floats, deep-ish nesting) — must accept and
  agree column-for-column;
- mutation: valid payloads put through random byte surgery (flips,
  splices, truncations, token inserts) — accept/reject must match the
  Python path, and agreement must hold when both accept;
- byte soup: random JSON-alphabet strings — same differential contract.

The egress mirror (``encode_pack``) is fuzzed for byte-identity against
``json_codec.dumps`` on the structured corpus.

A longer ASAN-instrumented loop lives in scripts/fuzz_native.py
(GRAFT_NATIVE_ASAN=1); this in-CI pass runs a bounded number of examples.
"""
import json
import random

import numpy as np
import pytest
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

import crdt_graph_tpu as crdt
from crdt_graph_tpu import native
from crdt_graph_tpu.codec import json_codec, packed
from crdt_graph_tpu.core import operation as op_mod

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")

COLUMNS = ("kind", "ts", "parent_ts", "anchor_ts", "depth", "paths",
           "value_ref", "pos", "parent_pos", "anchor_pos", "target_pos")


def python_path(payload):
    try:
        return True, packed.pack(json_codec.loads(payload))
    except (ValueError, RecursionError, OverflowError):
        # DecodeError/JSONDecodeError are ValueErrors; RecursionError is
        # json.loads on pathological nesting (native: clean ValueError);
        # OverflowError is pack() on > int64 timestamps (same)
        return False, None


def native_path(payload):
    try:
        return True, native.parse_pack(payload)
    except ValueError:
        return False, None


def check_differential(payload):
    ok_n, got = native_path(payload)
    ok_p, want = python_path(payload)
    assert ok_n == ok_p, f"acceptance diverged on {payload[:300]!r}"
    if ok_n:
        assert got.num_ops == want.num_ops
        for f in COLUMNS:
            np.testing.assert_array_equal(getattr(got, f),
                                          getattr(want, f), f)
        # repr: NaN payloads break ==, bool-vs-int (True == 1) break
        # naive equality in the other direction
        assert repr(got.values) == repr(want.values)


# -- strategies -----------------------------------------------------------

json_values = st.recursive(
    st.none() | st.booleans() |
    st.integers(min_value=-2**70, max_value=2**70) |
    st.floats(allow_nan=False) | st.text(max_size=40),
    lambda children: st.lists(children, max_size=4) |
    st.dictionaries(st.text(max_size=10), children, max_size=4),
    max_leaves=12)

# ts/path values: cluster around the interesting boundaries (0, the
# 2^62 sentinel cutoff, int64 edges, the replica*2^32 scheme)
wire_ints = st.one_of(
    st.integers(min_value=0, max_value=20),
    st.integers(min_value=2**32 - 2, max_value=2**32 + 20),
    st.integers(min_value=2**62 - 2, max_value=2**62 + 2),
    st.integers(min_value=-5, max_value=5),
    st.integers(min_value=2**63 - 2, max_value=2**63 + 2),
    st.integers(min_value=-2**80, max_value=2**80))


def op_dict(draw):
    kind = draw(st.sampled_from(["add", "del", "batch", "mystery"]))
    if kind == "add":
        return {"op": "add",
                "path": draw(st.lists(wire_ints, max_size=5)),
                "ts": draw(wire_ints), "val": draw(json_values)}
    if kind == "del":
        return {"op": "del", "path": draw(st.lists(wire_ints, max_size=5))}
    if kind == "batch":
        return {"op": "batch",
                "ops": [draw(st.deferred(lambda: wire_op_strategy))
                        for _ in range(draw(st.integers(0, 3)))]}
    return {"op": "mystery", "junk": draw(json_values)}


wire_op_strategy = st.composite(op_dict)()


@settings(max_examples=150, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(wire_op_strategy)
def test_structured_payloads_agree(op):
    check_differential(json.dumps(op))


@settings(max_examples=150, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(wire_op_strategy, st.integers(0, 2**32))
def test_mutated_payloads_agree(op, seed):
    payload = json.dumps(op)
    rng = random.Random(seed)
    data = bytearray(payload.encode())
    tokens = [b'{', b'}', b'[', b']', b'"', b':', b',', b'\\u0000',
              b'\\ud800', b'9' * 25, b'-', b'.', b'e99', b'null',
              b'Infinity', b'{"op":"add"', b'\xff', b'\x00', b' ']
    for _ in range(rng.randint(1, 8)):
        if not data:
            break
        kind = rng.randrange(5)
        i = rng.randrange(len(data))
        if kind == 0:                       # bit flip
            data[i] ^= 1 << rng.randrange(8)
        elif kind == 1:                     # delete a slice
            j = min(len(data), i + rng.randint(1, 8))
            del data[i:j]
        elif kind == 2:                     # duplicate a slice
            j = min(len(data), i + rng.randint(1, 8))
            data[i:i] = data[i:j]
        elif kind == 3:                     # insert a token
            data[i:i] = rng.choice(tokens)
        else:                               # truncate
            del data[i:]
    try:
        payload = data.decode()
    except UnicodeDecodeError:
        # non-UTF-8 bytes: the HTTP layer decodes the body before the
        # codec ever sees it, so the native contract is bytes-in →
        # it must still reject cleanly, matching Python on the
        # surrogateescape-free path
        with pytest.raises(ValueError):
            native.parse_pack(bytes(data))
        return
    check_differential(payload)


@settings(max_examples=200, deadline=None)
@given(st.text(alphabet='{}[]":,0123456789.eE+-aduloptsrbv\\ \t\n"',
               max_size=120))
def test_byte_soup_agrees(soup):
    check_differential(soup)


@settings(max_examples=100, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.lists(st.builds(
    lambda ts, path, val: crdt.Add(ts, tuple(path), val),
    st.integers(min_value=1, max_value=2**62 - 1),
    st.lists(st.integers(min_value=0, max_value=2**62 - 1), max_size=4),
    json_values), max_size=8))
def test_encode_fuzz_byte_identical(adds):
    """Egress fuzz: whatever ops pack() accepts, encode_pack must emit
    byte-identically to the Python encoder."""
    try:
        p = packed.pack(adds)
    except ValueError:
        return          # replica-id range rejection — nothing to encode
    assert native.encode_pack(p).decode() == \
        json_codec.dumps(op_mod.from_list(tuple(adds)))
