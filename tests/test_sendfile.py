"""Zero-copy cold egress (ISSUE 17; docs/SERVING.md §Zero-copy
egress): ``/ops`` windows that land entirely on sealed cold segments
are served by ``os.sendfile`` straight from the wire sidecars.  Pins
BYTE-identity with the buffered path (body, ETag, X-Since-* headers,
304s) across a full resumable window chain, the ``GRAFT_SENDFILE=0``
A/B baseline, sidecar cleanup on ephemeral close, and the
crdt_sendfile_* prom family gating.
"""
import os
import threading
import time
from http.client import HTTPConnection

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import oplog as oplog_mod
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.serve import ServingEngine
from crdt_graph_tpu.service.http import make_server

OFF = 2**32


def chain(rid, n, counter0=0, anchor=0):
    ops, prev = [], anchor
    for i in range(n):
        t = rid * OFF + counter0 + i + 1
        ops.append(Add(t, (prev,), (counter0 + i) & 0xFF))
        prev = t
    return ops, prev


def _fill(eng, doc="d", rounds=30):
    """Enough sealed cold segments for several all-cold windows."""
    anchor = 0
    for i in range(rounds):
        ops, anchor = _chain_round(i, anchor)
        ok, _ = eng.submit(doc, json_codec.dumps(Batch(tuple(ops))))
        assert ok, i


def _chain_round(i, anchor):
    return chain(1, 4, counter0=i * 4, anchor=anchor)


@pytest.fixture()
def served():
    eng = ServingEngine(oplog_hot_ops=8)
    assert eng.sendfile_stats is not None, "GRAFT_SENDFILE default-on"
    _fill(eng)
    srv = make_server(port=0, store=eng)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    port = srv.server_address[1]

    def get(path, headers=None):
        c = HTTPConnection("127.0.0.1", port, timeout=10)
        c.request("GET", path, headers=headers or {})
        r = c.getresponse()
        body = r.read()
        hdrs = {k.lower(): v for k, v in r.getheaders()}
        c.close()
        return r.status, body, hdrs

    yield eng, get
    srv.shutdown()
    eng.close()


def _await_sendfile(eng, get, path):
    """First pull is buffered (sidecars build on the maintenance
    lane); poll until a window actually went out via sendfile."""
    st, body, hdrs = get(path)
    assert st == 200
    deadline = time.time() + 15
    while time.time() < deadline:
        st2, body2, hdrs2 = get(path)
        assert st2 == 200
        if eng.sendfile_stats.get("windows"):
            return (body, hdrs), (body2, hdrs2)
        time.sleep(0.1)
    pytest.fail(f"sendfile never served: "
                f"{eng.sendfile_stats.snapshot()}")


def test_zero_copy_window_is_byte_identical(served):
    eng, get = served
    (b0, h0), (b1, h1) = _await_sendfile(
        eng, get, "/docs/d/ops?since=0&limit=16")
    assert b1 == b0, "zero-copy bytes != buffered bytes"
    assert h1["etag"] == h0["etag"]
    assert h1["content-length"] == str(len(b1))
    assert eng.sendfile_stats.get("file_bytes") > 0


def test_window_chain_identical_to_buffered_truth(served):
    """Walk the whole resumable chain (since -> next_since): every
    window's body, ETag, more-flag and cursor match the buffered
    snapshot path exactly — the in-memory truth the plan path must
    never diverge from."""
    eng, get = served
    _await_sendfile(eng, get, "/docs/d/ops?since=0&limit=16")
    snap = eng.get("d").snapshot_view()
    since = 0
    for _ in range(100):
        bbody, bmeta = snap.ops_since_window(since, 16)
        st, zbody, zh = get(f"/docs/d/ops?since={since}&limit=16")
        assert st == 200
        assert zbody == bbody, f"mismatch at since={since}"
        assert zh["etag"] == bmeta["etag"], since
        assert zh["x-since-more"] == ("1" if bmeta["more"] else "0")
        nxt = zh.get("x-since-next")
        assert (nxt is None) == (bmeta["next_since"] is None)
        if nxt is not None:
            assert int(nxt) == bmeta["next_since"]
        if not bmeta["more"]:
            break
        since = bmeta["next_since"]
    else:
        pytest.fail("window chain never terminated")


def test_conditional_get_304_on_zero_copy_path(served):
    eng, get = served
    _await_sendfile(eng, get, "/docs/d/ops?since=0&limit=16")
    st, _body, h = get("/docs/d/ops?since=0&limit=16")
    assert st == 200
    st304, body304, h304 = get("/docs/d/ops?since=0&limit=16",
                               {"If-None-Match": h["etag"]})
    assert st304 == 304 and body304 == b""
    assert h304["etag"] == h["etag"]


def test_sendfile_off_baseline_identical(served, monkeypatch):
    """GRAFT_SENDFILE=0 is the A/B baseline: no stats object, no
    sidecars consulted, byte-identical windows."""
    eng, get = served
    (b0, _h0), _ = _await_sendfile(
        eng, get, "/docs/d/ops?since=0&limit=16")
    monkeypatch.setenv("GRAFT_SENDFILE", "0")
    eng2 = ServingEngine(oplog_hot_ops=8)
    assert eng2.sendfile_stats is None
    _fill(eng2)
    doc2 = eng2.get("d")
    assert doc2.ops_window_plan(0, 16) is None
    b2, _m2 = doc2.ops_since_window(0, 16)
    assert b2 == b0, "baseline engine bytes differ"
    eng2.close()


def test_sidecars_removed_on_ephemeral_close():
    """Ephemeral engines scrub their scratch segments on close — the
    wire sidecars must go with them, never orphaned on disk."""
    eng = ServingEngine(oplog_hot_ops=8)
    _fill(eng, rounds=12)
    log = eng.get("d").tree._log
    segs = list(log._bases) + list(log._cold)
    assert segs, "workload sealed no cold segments"
    built = [s for s in segs if oplog_mod.ensure_wire_sidecar(s)]
    assert built, "no sidecar built"
    paths = [p for s in built for p in oplog_mod.wire_paths(s.path)]
    for p in paths:
        assert os.path.exists(p), p
    eng.close()
    for s in segs:
        assert not os.path.exists(s.path), "segment survived close"
    for p in paths:
        assert not os.path.exists(p), f"orphaned sidecar: {p}"


def test_prom_sendfile_families_strict_parse(monkeypatch):
    """crdt_sendfile_* renders under the strict parser when armed
    (default) and is ABSENT under GRAFT_SENDFILE=0."""
    eng = ServingEngine(oplog_hot_ops=8)
    _fill(eng, rounds=12)
    fams = prom_mod.parse_text(eng.render_prom())
    for fam in ("crdt_sendfile_windows_total",
                "crdt_sendfile_bytes_total",
                "crdt_sendfile_fallback_total",
                "crdt_sendfile_sidecar_builds_total",
                "crdt_sendfile_sidecar_build_failures_total"):
        assert fam in fams, fam
        assert fams[fam]["type"] == "counter"
    eng.close()
    monkeypatch.setenv("GRAFT_SENDFILE", "0")
    off = ServingEngine(oplog_hot_ops=8)
    fams2 = prom_mod.parse_text(off.render_prom())
    assert not any(f.startswith("crdt_sendfile_") for f in fams2)
    off.close()
