"""Reactor egress tier (serve/reactor.py; ISSUE 18).

The contract under test, in rough order of consequence:

- **A/B byte identity.** With a fixed ``X-Session-Id``, every watch
  delivery class — resume walk across tier seams, park→notify,
  timeout heartbeat, slow-consumer shed, SSE event frames — produces
  the SAME wire bytes whether the park is held by a handler thread
  (``reactor=False``) or by a reactor loop.  The only permitted
  difference is the ``Date`` header's timestamp.
- **Partial-write continuation.** A throttled client (small SO_SNDBUF
  on the listener, small SO_RCVBUF on the client) forces EAGAIN
  mid-delivery; the reactor re-arms EPOLLOUT and resumes at the exact
  byte, so the drained body still equals the ``/ops`` reference.
- **Buffer-lifetime pins.** A publish that swaps the generation while
  a delivery is still queued must not corrupt the in-flight bytes:
  the egress buffer pins the snapshot it was encoded from.
- **Keep-alive re-injection.** After a reactor-written response the
  socket waits in the reactor; the client's next pipelined request is
  handed back to a transient handler thread intact.
- **Reaping.** A parked client that disappears is found by the
  selector (MSG_PEEK EOF / error) — without waiting for a publish —
  and its registry slot is released.
- **Shutdown.** ``engine.close()`` drains every reactor-parked
  watcher with the same named close the threaded path writes.
- **Scale pin.** 2k watchers park on the reactor with a flat server
  thread count (loops ≤ 4) and one publish fans out from a single
  window encode (readcache misses +1 / hits +(N-1)).
"""

import contextlib
import json
import os
import re
import socket
import threading
import time
from http.client import HTTPConnection

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from crdt_graph_tpu import engine as engine_mod
from crdt_graph_tpu.cluster import FleetServer, MemoryKV, NetChaos
from crdt_graph_tpu.cluster.pool import ConnectionPool
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.oplog import EMPTY_BATCH_BYTES
from crdt_graph_tpu.serve import ServingEngine
from crdt_graph_tpu.service import make_server


def _ts(r, c):
    return r * 2**32 + c


def _chain(rid, n, start=1, prev=0, pad=0):
    ops = []
    for c in range(start, start + n):
        val = f"r{rid}:{c}" + ("x" * pad if pad else "")
        ops.append(Add(_ts(rid, c), (prev,), val))
        prev = _ts(rid, c)
    return json_codec.dumps(Batch(tuple(ops)))


@contextlib.contextmanager
def _served(**engine_kw):
    eng = ServingEngine(**engine_kw)
    srv = make_server(port=0, store=eng)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    pool = ConnectionPool()

    def req(method, path, body=None, headers=None, timeout=60):
        resp, raw = pool.request(
            threading.current_thread().name, "server", "127.0.0.1",
            srv.server_port, method, path, body=body, headers=headers,
            timeout=timeout)
        return resp.status, raw, {k: v for k, v in resp.getheaders()}

    try:
        yield srv, req, eng
    finally:
        pool.close()
        srv.shutdown()
        srv.server_close()
        eng.close()


def _read_http(sock, timeout=30.0):
    """One Content-Length framed response off a raw keep-alive
    socket: ``(head_bytes, body_bytes)``."""
    sock.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        c = sock.recv(65536)
        if not c:
            raise ConnectionError("eof before head")
        buf += c
    head, _, rest = buf.partition(b"\r\n\r\n")
    clen = int(re.search(rb"Content-Length: (\d+)", head).group(1))
    while len(rest) < clen:
        c = sock.recv(65536)
        if not c:
            raise ConnectionError("eof mid body")
        rest += c
    return head, rest[:clen]


def _send_watch(sock, doc, since, limit, timeout,
                session="sess-ab-0001", extra=""):
    sock.sendall(
        (f"GET /docs/{doc}/watch?since={since}&limit={limit}"
         f"&timeout={timeout}{extra} HTTP/1.1\r\nHost: t\r\n"
         f"X-Session-Id: {session}\r\n\r\n").encode())


def _norm_head(head):
    """The ``Date`` stamp is the single permitted A/B difference."""
    return re.sub(rb"Date: [^\r]+", b"Date: *", head)


def _wait_parked(doc, n=1, timeout=10.0):
    deadline = time.monotonic() + timeout
    while doc.watch.counts()["parked"] < n:
        assert time.monotonic() < deadline, "never parked"
        time.sleep(0.005)


# -- A/B byte identity -------------------------------------------------------


def _ab_poll_leg(reactor_on):
    """Drive one server through every long-poll delivery class over a
    single raw keep-alive socket; return the labelled wire bytes."""
    out = {}
    with _served(reactor=reactor_on, oplog_hot_ops=16) as \
            (srv, req, eng):
        prev = 0
        for k in range(4):
            st, raw, _ = req("POST", "/docs/d/ops",
                             body=_chain(4, 10, start=k * 10 + 1,
                                         prev=prev))
            prev = _ts(4, (k + 1) * 10)
            assert st == 200 and json.loads(raw)["accepted"]
        assert eng.flush(timeout=60)
        assert eng.get("d").snapshot_view().log_segments > 1

        s = socket.create_connection(("127.0.0.1", srv.server_port),
                                     timeout=30)
        try:
            # resume walk across the hot→cold seams, to the heartbeat
            since, rounds = 0, 0
            while True:
                _send_watch(s, "d", since, 7, 0.3)
                head, body = _read_http(s)
                out[f"walk{rounds}"] = (head, body)
                ev = re.search(rb"X-Watch-Event: (\w+)",
                               head).group(1)
                if ev == b"timeout":
                    assert body == EMPTY_BATCH_BYTES
                    break
                since = int(re.search(rb"X-Since-Next: (\d+)",
                                      head).group(1))
                rounds += 1
                assert rounds < 100
            # caught-up park -> notify
            _send_watch(s, "d", since, 100, 10)
            _wait_parked(eng.get("d"))
            st, raw, _ = req("POST", "/docs/d/ops",
                             body=_chain(4, 3, start=41, prev=prev))
            assert st == 200 and json.loads(raw)["accepted"]
            out["notify"] = _read_http(s)
            since = int(re.search(rb"X-Since-Next: (\d+)",
                                  out["notify"][0]).group(1))
            # park then fall far behind -> shed with the resume mark
            _send_watch(s, "d", since, 2, 10)
            _wait_parked(eng.get("d"))
            st, raw, _ = req("POST", "/docs/d/ops",
                             body=_chain(4, 12, start=44,
                                         prev=_ts(4, 43)))
            assert st == 200 and json.loads(raw)["accepted"]
            out["shed"] = _read_http(s)
        finally:
            s.close()
    return out


def test_reactor_ab_poll_byte_identity_across_seams():
    """Every long-poll delivery class — seam-crossing resume walk,
    notify, timeout heartbeat, shed — is byte-identical between the
    reactor and the threaded park path, modulo the Date stamp."""
    a = _ab_poll_leg(True)
    b = _ab_poll_leg(False)
    assert a.keys() == b.keys()
    for leg in a:
        assert _norm_head(a[leg][0]) == _norm_head(b[leg][0]), leg
        assert a[leg][1] == b[leg][1], leg
    # the classes the walk must actually have covered
    events = b"".join(h for h, _ in a.values())
    for ev in (b"X-Watch-Event: resume", b"X-Watch-Event: timeout",
               b"X-Watch-Event: notify", b"X-Watch-Event: shed"):
        assert ev in events


def _ab_sse_leg(reactor_on):
    with _served(reactor=reactor_on) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        eng.get("d").watch.heartbeat_s = 0.2
        conn = HTTPConnection("127.0.0.1", srv.server_port,
                              timeout=30)
        try:
            conn.request("GET",
                         "/docs/d/watch?since=0&limit=1000&mode=sse"
                         "&timeout=1.0",
                         headers={"X-Session-Id": "sess-ab-0001"})
            resp = conn.getresponse()
            head = {k.lower(): v for k, v in resp.getheaders()
                    if k.lower() != "date"}
            time.sleep(0.35)
            st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 2))
            assert st == 200 and json.loads(raw)["accepted"]
            raw = resp.read()
        finally:
            conn.close()
    frames = [f for f in raw.split(b"\n\n")
              if f and not f.startswith(b": hb")]
    return resp.status, head, frames


def test_reactor_ab_sse_frames_identical():
    """The SSE stream's event frames (backlog, live commit, named
    goodbye) and response head match the threaded path exactly;
    only the comment-heartbeat cadence may drift."""
    sa, ha, fa = _ab_sse_leg(True)
    sb, hb, fb = _ab_sse_leg(False)
    assert (sa, ha) == (sb, hb)
    assert fa == fb
    kinds = [re.search(rb"event: (\w+)", f).group(1) for f in fa]
    assert kinds[0] == b"ops" and kinds[-1] == b"bye"
    assert kinds.count(b"ops") == 2


# -- partial-write continuation + pin integrity ------------------------------


def _throttled_park(srv, req, eng, since, pad_posts):
    """A tiny-window client parked caught-up, then fed fat publishes:
    returns the raw socket mid-partial-write."""
    srv.socket.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16384)
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8192)
    s.connect(("127.0.0.1", srv.server_port))
    _send_watch(s, "d", since, 2000, 20)
    _wait_parked(eng.get("d"))
    for body in pad_posts:
        st, raw, _ = req("POST", "/docs/d/ops", body=body)
        assert st == 200 and json.loads(raw)["accepted"]
    return s


def test_reactor_partial_write_continuation_throttled_client():
    """A window much larger than the socket buffers is delivered in
    EAGAIN-interrupted pieces; the drained body still equals the
    ``/ops`` reference byte for byte, and the continuation counter
    proves the slow path actually ran."""
    with _served(reactor=True) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])
        fat = _chain(2, 300, pad=1024)
        s = _throttled_park(srv, req, eng, mark, [fat])
        try:
            time.sleep(0.3)          # let the reactor hit EAGAIN
            st, ref, _ = req("GET",
                             f"/docs/d/ops?since={mark}&limit=2000")
            assert st == 200
            head, body = _read_http(s, timeout=60)
            assert b"X-Watch-Event: notify" in head
            assert body == ref
        finally:
            s.close()
        snap = eng.reactor.snapshot()
        assert snap["partial_writes"] >= 1
        assert snap["buf_hw"] > 16384


def test_reactor_pin_survives_publish_swap_mid_write():
    """A second publish lands while the first delivery is still
    queued behind a throttled socket: the egress buffer's snapshot
    pin keeps the in-flight bytes valid, and the follow-up poll on
    the same keep-alive socket resumes exactly."""
    with _served(reactor=True) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, full0, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])
        fat = _chain(2, 300, pad=1024)
        s = _throttled_park(srv, req, eng, mark, [fat])
        try:
            time.sleep(0.2)
            # the reference for the IN-FLIGHT window, then swap the
            # generation underneath it (window LRU may evict, the shm
            # body cache may remap — the pin must hold regardless)
            st, ref1, _ = req("GET",
                              f"/docs/d/ops?since={mark}&limit=2000")
            assert st == 200
            st, raw, _ = req("POST", "/docs/d/ops",
                             body=_chain(3, 200, pad=512))
            assert st == 200 and json.loads(raw)["accepted"]
            head, body = _read_http(s, timeout=60)
            assert body == ref1
            nxt = int(re.search(rb"X-Since-Next: (\d+)",
                                head).group(1))
            # keep-alive re-injection: the next poll on the SAME
            # socket walks the rest of the log.  Bootstrap order
            # matters: windows redeliver their last Add as the resume
            # terminator, absorbed only once the prefix is applied.
            replica = engine_mod.init(0)
            replica.apply(json_codec.loads(full0))
            replica.apply(json_codec.loads(ref1))
            since = nxt
            for _ in range(50):
                _send_watch(s, "d", since, 2000, 0.3)
                h2, b2 = _read_http(s, timeout=60)
                if b"X-Watch-Event: timeout" in h2:
                    break
                replica.apply(json_codec.loads(b2))
                since = int(re.search(rb"X-Since-Next: (\d+)",
                                      h2).group(1))
            else:
                pytest.fail("never caught up after swap")
        finally:
            s.close()
        st, raw, _ = req("GET", "/docs/d")
        assert replica.visible_values() == json.loads(raw)["values"]
        assert eng.reactor.snapshot()["partial_writes"] >= 1


# -- keep-alive re-injection + heartbeat re-park -----------------------------


def test_reactor_heartbeat_reinjects_and_reparks():
    """timeout heartbeat → the socket waits in the reactor → the next
    request on the same connection is re-injected into a handler
    thread, parks again, and the publish notify lands on it."""
    with _served(reactor=True) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])
        s = socket.create_connection(("127.0.0.1", srv.server_port),
                                     timeout=30)
        try:
            _send_watch(s, "d", mark, 100, 0.3)
            head, body = _read_http(s)
            assert b"X-Watch-Event: timeout" in head
            assert body == EMPTY_BATCH_BYTES
            # the client can hold the response bytes a beat before the
            # reactor thread finishes its release bookkeeping (GIL
            # scheduling on a 1-core host): wait for the slot drop so
            # the re-park below is unambiguously the SECOND park
            deadline = time.monotonic() + 10
            while eng.get("d").watch.counts()["registered"] > 0:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            _send_watch(s, "d", mark, 100, 10)
            _wait_parked(eng.get("d"))
            deadline = time.monotonic() + 10
            while eng.reactor.snapshot()["reinjects"] < 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 2))
            assert st == 200 and json.loads(raw)["accepted"]
            head, body = _read_http(s)
            assert b"X-Watch-Event: notify" in head
            st, ref, _ = req("GET",
                             f"/docs/d/ops?since={mark}&limit=100")
            assert body == ref
        finally:
            s.close()
        deadline = time.monotonic() + 10
        while eng.get("d").watch.counts()["registered"] > 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)


# -- reaping -----------------------------------------------------------------


def test_reactor_reaps_closed_clients_without_a_publish():
    """The selector notices a dead parked client on its own — FIN or
    RST — and frees the slot with no publish to flush it out (the
    threaded path only discovers the corpse at write time)."""
    with _served(reactor=True) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])
        socks = []
        for _ in range(2):
            s = socket.create_connection(
                ("127.0.0.1", srv.server_port), timeout=10)
            _send_watch(s, "d", mark, 100, 30)
            socks.append(s)
        doc = eng.get("d")
        _wait_parked(doc, n=2)
        # one RST, one FIN — both must reap
        socks[0].setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            b"\x01\x00\x00\x00\x00\x00\x00\x00")
        socks[0].close()
        socks[1].close()
        deadline = time.monotonic() + 10
        while doc.watch.counts()["registered"] > 0:
            assert time.monotonic() < deadline, "slots never freed"
            time.sleep(0.01)
        assert eng.reactor.snapshot()["reaps"] == 2
        assert doc.watch.stats.snapshot()["reaped"] == 2


# -- shutdown drains with named closes ---------------------------------------


def test_reactor_shutdown_writes_named_closes():
    """``engine.close()`` drains every reactor-parked watcher — long
    polls answer the 503 ``X-Watch-Event: closed``, SSE streams get
    ``event: closed`` — before the loops join."""
    with _served(reactor=True) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])
        polls = []
        for _ in range(2):
            s = socket.create_connection(
                ("127.0.0.1", srv.server_port), timeout=10)
            _send_watch(s, "d", mark, 100, 30)
            polls.append(s)
        sse = socket.create_connection(
            ("127.0.0.1", srv.server_port), timeout=10)
        _send_watch(sse, "d", mark, 100, 30, extra="&mode=sse")
        _wait_parked(eng.get("d"), n=3)
        eng.close()
        for s in polls:
            head, body = _read_http(s)
            assert b"HTTP/1.1 503" in head
            assert b"X-Watch-Event: closed" in head
            assert json.loads(body) == {"error": "engine shutting down"}
            s.close()
        sse.settimeout(10)
        raw = b""
        while True:
            c = sse.recv(65536)
            if not c:
                break
            raw += c
        sse.close()
        assert b"event: closed\ndata: {}\n\n" in raw
        assert eng.reactor.snapshot()["closes"] == 3


# -- churn under chaos -------------------------------------------------------


def test_reactor_watch_under_netchaos_churn_exact_resume():
    """The fleet churn leg with the reactor holding the parks: chaos
    delays/duplicates/cuts the inter-node pulls while a reconnecting
    watcher on the non-primary resumes with its mark — zero acked
    writes lost, and the parks actually rode the reactor."""
    chaos = NetChaos(31, "delay=1-6@0.4;dup=0.3;cut=0.2")
    kv = MemoryKV()
    fleet = {}
    for n in ("a", "b"):
        fleet[n] = FleetServer(n, kv, ttl_s=600.0,
                               ae_interval_s=3600.0, netchaos=chaos)
    for fs in fleet.values():
        fs.node.refresh_ring()
    try:
        ring = fleet["a"].node.ring()
        doc = next(f"w{i}" for i in range(500)
                   if ring.primary(f"w{i}") == "a")

        def fleet_req(port, method, path, body=None, headers=None):
            conn = HTTPConnection("127.0.0.1", port, timeout=30)
            try:
                conn.request(method, path, body=body,
                             headers=headers or {})
                resp = conn.getresponse()
                return resp.status, resp.read(), \
                    dict(resp.getheaders())
            finally:
                conn.close()

        stop = threading.Event()
        state = {"mark": 0, "errors": []}
        replica = engine_mod.init(0)

        def watcher():
            while not stop.is_set():
                try:
                    st, raw, hdr = fleet_req(
                        fleet["b"].port, "GET",
                        f"/docs/{doc}/watch?since={state['mark']}"
                        f"&limit=8192&timeout=0.3")
                except OSError as e:
                    state["errors"].append(repr(e))
                    return
                if st in (404, 503):
                    time.sleep(0.01)
                    continue
                if st != 200:
                    state["errors"].append(f"watch -> {st}")
                    return
                if hdr["X-Watch-Event"] == "timeout":
                    continue
                replica.apply(json_codec.loads(raw))
                state["mark"] = int(hdr["X-Since-Next"])

        t = threading.Thread(target=watcher, daemon=True,
                             name="chaos-watch")
        t.start()
        prev = 0
        for k in range(4):
            st, raw, _ = fleet_req(
                fleet["a"].port, "POST", f"/docs/{doc}/ops",
                body=_chain(3, 15, start=k * 15 + 1, prev=prev))
            prev = _ts(3, (k + 1) * 15)
            assert st == 200, raw
            for _ in range(50):
                if fleet["b"].node.antientropy.sync_now() == \
                        {"a": True}:
                    break
            else:
                pytest.fail(f"sync never healed: {chaos.describe()}")
        st, raw, hdr = fleet_req(
            fleet["b"].port, "GET",
            f"/docs/{doc}/ops?since=0&limit=100000")
        final_mark = int(hdr["X-Since-Next"])
        deadline = time.monotonic() + 15
        while state["mark"] != final_mark:
            assert time.monotonic() < deadline, \
                (state, final_mark, chaos.describe())
            time.sleep(0.05)
        stop.set()
        t.join(30)
        assert state["errors"] == [], state["errors"]
        st, raw, _ = fleet_req(fleet["b"].port, "GET", f"/docs/{doc}")
        served = json.loads(raw)["values"]
        assert replica.visible_values() == served
        assert len(served) == 60          # zero acked-write loss
        # the caught-up parks between generations rode the reactor
        assert fleet["b"].node.engine.reactor.snapshot()[
            "detached"] >= 1
    finally:
        for fs in fleet.values():
            try:
                fs.stop()
            except Exception:  # noqa: BLE001 — teardown boundary
                pass


# -- observability gating ----------------------------------------------------


def test_reactor_prom_families_present_and_gated():
    """``crdt_reactor_*`` renders under the strict-parse contract
    when the reactor runs, and the families are entirely ABSENT when
    the threaded path is selected — the exposition is the A/B gate."""
    with _served(reactor=True) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])
        s = socket.create_connection(("127.0.0.1", srv.server_port),
                                     timeout=10)
        try:
            _send_watch(s, "d", mark, 100, 10)
            _wait_parked(eng.get("d"))
            text = prom_mod.render_engine(eng)
            fams = prom_mod.parse_text(text)
            assert fams["crdt_reactor_parked"]["samples"][0][2] == 1
            assert "crdt_reactor_detached_total" in fams
            assert fams["crdt_reactor_sheds_total"]["samples"][0][1] \
                == {"reason": "buffer"}
            assert fams["crdt_reactor_threads"]["samples"][0][2] >= 1
        finally:
            s.close()
    with _served(reactor=False) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        text = prom_mod.render_engine(eng)
        fams = prom_mod.parse_text(text)
        assert not any(n.startswith("crdt_reactor_") for n in fams)


# -- the scale pin -----------------------------------------------------------


N_SCALE = int(os.environ.get("GRAFT_TEST_WATCHERS", "2000"))


def test_reactor_parks_2k_watchers_flat_threads():
    """The headline mechanism at tier-1 scale: 2k watchers parked on
    ≤4 reactor loops with a flat server thread count, and one publish
    fans out to all of them from a SINGLE window encode — readcache
    misses +1, hits +(N-1), every body identical."""
    with _served(reactor=True) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=50")
        mark = int(hdr["X-Since-Next"])
        doc = eng.get("d")
        doc.watch.max_watchers = max(doc.watch.max_watchers, N_SCALE)

        socks = []
        try:
            for base in range(0, N_SCALE, 100):
                burst = []
                for i in range(base, min(base + 100, N_SCALE)):
                    s = socket.socket(socket.AF_INET,
                                      socket.SOCK_STREAM)
                    s.connect(("127.0.0.1", srv.server_port))
                    _send_watch(s, "d", mark, 100, 120,
                                session=f"w-{i:04d}")
                    burst.append(s)
                socks.extend(burst)
                # pace the herd: let this burst park before the next
                # slams the accept queue (request_queue_size=128)
                _wait_parked(doc, n=len(socks), timeout=60)

            assert doc.watch.counts()["parked"] == N_SCALE
            assert doc.watch.counts()["reactor_parked"] == N_SCALE
            rsnap = eng.reactor.snapshot()
            assert rsnap["threads"] <= 4
            assert rsnap["parked"] == N_SCALE

            # handler threads are transient: once every park has
            # detached, the server's thread population must be FLAT —
            # loops + acceptor + scheduler noise, nowhere near N
            deadline = time.monotonic() + 30
            while threading.active_count() > 24:
                assert time.monotonic() < deadline, \
                    f"threads never drained: {threading.active_count()}"
                time.sleep(0.05)

            rc0 = doc.readcache.snapshot()
            st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 4))
            assert st == 200 and json.loads(raw)["accepted"]
            bodies = set()
            for s in socks:
                head, body = _read_http(s, timeout=120)
                assert b"X-Watch-Event: notify" in head
                bodies.add(body)
            assert len(bodies) == 1      # one window, N deliveries
            rc1 = doc.readcache.snapshot()
            assert rc1["misses"] - rc0["misses"] == 1
            assert rc1["hits"] - rc0["hits"] == N_SCALE - 1
        finally:
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass
        deadline = time.monotonic() + 30
        while doc.watch.counts()["registered"] > 0:
            assert time.monotonic() < deadline, "registry never drained"
            time.sleep(0.05)
