"""The replica fleet (crdt_graph_tpu/cluster/, ISSUE 7): coordination
KV, consistent-hash ring, replica-id leases, the bounded anti-entropy
wire, and the in-process 3-server fleet — forwarding, replica-local
reads, deterministic chaos (kill the primary mid-queued-merge, operator
failover, fingerprint-equal convergence, crash-safe rejoin under a
bumped fencing epoch), plus the ``crdt_cluster_*`` exposition under the
strict prom naming contract.

The slow-marked soak at the bottom runs the same story against REAL
processes (``python -m crdt_graph_tpu.cluster`` over a shared FileKV
spool) with an actual ``SIGKILL`` — the one failure shape an in-process
crash cannot model (a merge dying mid-kernel).
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.client import HTTPConnection

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import engine
from crdt_graph_tpu.cluster import (FileKV, FleetServer, HashRing,
                                    LeaseError, LeaseLost, LeaseService,
                                    MemoryKV)
from crdt_graph_tpu.cluster import kv as kv_mod
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.codec import packed as packed_mod
from crdt_graph_tpu.core.operation import Add, Batch, Delete
from crdt_graph_tpu.obs import prom as prom_mod


def ts(r, c):
    return r * 2**32 + c


def req(port, method, path, body=None, headers=None, timeout=60):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw, dict(resp.getheaders())
    finally:
        conn.close()


# -- coordination KV ---------------------------------------------------------


def _kv_contract(kv):
    assert kv.get("a") is None
    assert kv.cas("a", "v1", 0)            # create
    assert not kv.cas("a", "v2", 0)        # create-only loses to exist
    assert kv.get("a") == ("v1", 1)
    assert kv.cas("a", "v2", 1)            # versioned update
    assert not kv.cas("a", "v3", 1)        # stale version loses
    assert kv.get("a") == ("v2", 2)
    assert kv.cas("lease/3", "x", 0)       # path-like keys
    assert kv.keys("lease/") == ["lease/3"]
    assert not kv.delete("a", 1)           # stale delete loses
    assert kv.delete("a", 2)
    assert kv.get("a") is None
    assert kv.keys() == ["lease/3"]


def test_memory_kv_contract():
    _kv_contract(MemoryKV())


def test_file_kv_contract_and_cross_instance(tmp_path):
    root = str(tmp_path / "spool")
    _kv_contract(FileKV(root))
    # a second instance over the same spool sees the same store (the
    # many-process, one-host deployment)
    a, b = FileKV(root), FileKV(root)
    assert a.cas("shared", "from-a", 0)
    assert b.get("shared") == ("from-a", 1)
    assert b.cas("shared", "from-b", 1)
    assert a.get("shared") == ("from-b", 2)


@pytest.mark.parametrize("make", [
    lambda tmp: MemoryKV(), lambda tmp: FileKV(str(tmp / "ctr"))],
    ids=["memory", "file"])
def test_kv_counter_unique_under_threads(tmp_path, make):
    kv = make(tmp_path)
    n_threads, per = 8, 12
    got = [[] for _ in range(n_threads)]

    def worker(i):
        for _ in range(per):
            got[i].append(kv_mod.next_counter(kv, "replica/doc"))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    flat = [v for g in got for v in g]
    assert sorted(flat) == list(range(1, n_threads * per + 1))


# -- consistent-hash ring ----------------------------------------------------


def test_ring_deterministic_and_balanced():
    members = {"a": "h:1", "b": "h:2", "c": "h:3"}
    docs = [f"d{i}" for i in range(300)]
    r1, r2 = HashRing(members), HashRing(dict(members))
    assert [r1.primary(d) for d in docs] == [r2.primary(d) for d in docs]
    spread = r1.spread(docs)
    assert set(spread) == set(members)
    assert all(v > 0 for v in spread.values()), spread
    for d in docs[:20]:
        pref = r1.preference(d)
        assert pref[0] == r1.primary(d)
        assert sorted(pref) == sorted(members)


def test_ring_minimal_rebalance_on_member_loss():
    """Dropping one member moves ONLY its documents; everything else
    keeps its primary — the property that makes failover cheap."""
    docs = [f"d{i}" for i in range(300)]
    r3 = HashRing({"a": "h:1", "b": "h:2", "c": "h:3"})
    r2 = HashRing({"a": "h:1", "b": "h:2"})
    moved = 0
    for d in docs:
        before = r3.primary(d)
        if before == "c":
            moved += 1
            assert r2.primary(d) in ("a", "b")
        else:
            assert r2.primary(d) == before, d
    assert 0 < moved < len(docs)
    # and the failover target was the doc's next preference all along
    for d in docs:
        if r3.primary(d) == "c":
            assert r2.primary(d) == [m for m in r3.preference(d)
                                     if m != "c"][0]


def test_ring_empty_and_single():
    assert HashRing({}).primary("x") is None
    assert HashRing({}).preference("x") == []
    assert HashRing({"only": "h:1"}).primary("x") == "only"


# -- replica-id leases -------------------------------------------------------


def test_lease_protocol_fencing_and_reclaim():
    now = [1000.0]
    svc = LeaseService(MemoryKV(), ttl_s=5.0, max_ids=4,
                       clock=lambda: now[0])
    a = svc.acquire("alice", "h:1")
    b = svc.acquire("bob", "h:2")
    assert (a.id, b.id) == (0, 1)
    assert a.token == b.token == 1
    assert set(svc.members()) == {"alice", "bob"}

    # renewal extends; release frees the slot immediately
    now[0] += 3.0
    a = svc.renew(a)
    assert a.expires == now[0] + 5.0
    assert svc.release(b)
    assert set(svc.members()) == {"alice"}

    # natural expiry: the slot becomes claimable, the claim BUMPS the
    # fencing token, and the deposed holder's renew is refused
    now[0] += 10.0
    assert svc.members() == {}
    c = svc.acquire("carol", "h:3")
    assert c.id == 0 and c.token == 2       # alice's slot, fenced
    with pytest.raises(LeaseLost):
        svc.renew(a)
    assert not svc.release(a)

    # crash-safe re-acquisition: the SAME name reclaims its own slot
    # immediately — no TTL wait — with a bumped token (the dead
    # incarnation is fenced the moment the CAS lands)
    c2 = svc.acquire("carol", "h:3b")
    assert c2.id == c.id and c2.token == c.token + 1
    with pytest.raises(LeaseLost):
        svc.renew(c)

    # operator force-expiry keeps the token; the next claimant bumps it
    assert svc.expire_now("carol")
    assert "carol" not in svc.members()
    d = svc.acquire("dave", "h:4")
    assert d.id == c2.id and d.token == c2.token + 1


def test_lease_fleet_full():
    svc = LeaseService(MemoryKV(), ttl_s=60.0, max_ids=2)
    svc.acquire("a", "h:1")
    svc.acquire("b", "h:2")
    with pytest.raises(LeaseError):
        svc.acquire("c", "h:3")


# -- the anti-entropy window (packed_since_window) ---------------------------


def _mixed_log():
    """A log with interleaved adds and deletes ENDING on deletes, so
    window trimming (a window must end on an Add) is exercised."""
    ops, prev = [], 0
    for c in range(1, 13):
        ops.append(Add(ts(1, c), (prev,), f"v{c}"))
        prev = ts(1, c)
        if c % 4 == 0:
            ops.append(Delete((ts(1, c - 1),)))
    ops.append(Delete((ts(1, 12),)))        # trailing deletes
    ops.append(Delete((ts(1, 11),)))
    return ops


def test_window_unbounded_matches_since_bytes():
    p = packed_mod.pack(_mixed_log())
    for since in (0, ts(1, 1), ts(1, 6), ts(1, 12)):
        wire, meta = engine.packed_since_window(p, since, 0)
        assert wire == engine.packed_since_bytes(p, since)
        assert meta["found"] and not meta["more"]


def test_window_boundary_at_exact_timestamp():
    p = packed_mod.pack(_mixed_log())
    # the since terminator is served INCLUSIVELY (the overlap row
    # absorbs as a duplicate at the puller) — the boundary the
    # reference's operationsSince contract pins
    wire, meta = engine.packed_since_window(p, ts(1, 6), 5)
    got = json_codec.loads(wire.decode())
    assert got.ops[0].ts == ts(1, 6)
    assert meta["found"] and meta["count"] >= 1
    # a mark equal to the LAST Add: just the terminator row (plus any
    # trailing deletes) — and never "more"
    wire, meta = engine.packed_since_window(p, ts(1, 12), 5)
    got = json_codec.loads(wire.decode())
    assert got.ops[0].ts == ts(1, 12)
    assert not meta["more"]
    # an unknown timestamp — and a DELETE's timestamp, which is never
    # a valid terminator — both report found=False so the puller
    # resets its mark instead of spinning
    for bogus in (ts(1, 99), ts(2, 1)):
        wire, meta = engine.packed_since_window(p, bogus, 5)
        assert not meta["found"] and meta["count"] == 0
        assert wire == b'{"op":"batch","ops":[]}'


def test_window_chain_resumes_and_ends_on_adds():
    ops = _mixed_log()
    p = packed_mod.pack(ops)
    add_ts = {op.ts for op in ops if isinstance(op, Add)}
    since, windows, metas = 0, [], []
    for _ in range(50):
        wire, meta = engine.packed_since_window(p, since, 3)
        assert meta["found"]
        windows.append(wire)
        metas.append(meta)
        if meta["next_since"] is not None:
            assert meta["next_since"] in add_ts    # resumable marks
            since = meta["next_since"]
        if not meta["more"]:
            break
    else:
        pytest.fail("window chain never terminated")
    assert len(windows) > 2                        # actually windowed
    assert sum(m["count"] for m in metas) >= p.num_ops  # overlap rows
    # reassembly: a fresh replica applying the chained windows equals
    # one applying the full log in one shot
    t_full, t_chain = engine.init(7), engine.init(7)
    t_full.apply(json_codec.loads(
        engine.packed_since_bytes(p, 0).decode()))
    for wire in windows:
        t_chain.apply(json_codec.loads(wire.decode()))
    assert t_chain.visible_values() == t_full.visible_values()


def test_window_exchange_idempotent_and_commutative():
    """Interleaved peer exchanges over the windowed wire: any delivery
    order, any duplication, same converged state — idempotence and
    commutativity are the CRDT's, the windows only have to preserve
    them (incl. the inclusive-terminator overlap rows)."""
    a, b = engine.init(1), engine.init(2)
    for i in range(1, 19):
        a.add(f"a{i}")
        if i % 5 == 0:
            prev = a.operations_since(0).ops[-2]
            a.delete(prev.path[:-1] + (prev.ts,))
    for i in range(1, 14):
        b.add(f"b{i}")

    def windows(tree, limit):
        p = packed_mod.pack(tuple(tree.operations_since(0).ops))
        since, out = 0, []
        while True:
            wire, meta = engine.packed_since_window(p, since, limit)
            out.append(json_codec.loads(wire.decode()))
            if meta["next_since"] is not None:
                since = meta["next_since"]
            if not meta["more"]:
                return out

    wa, wb = windows(a, 4), windows(b, 3)
    # a pulls b, b pulls a — opposite window orders are NOT possible
    # (windows chain), but interleaving ACROSS peers is free
    for w in wb:
        a.apply(w)
    for w in wa:
        b.apply(w)
    assert a.visible_values() == b.visible_values()
    # a third replica hears everything late, duplicated, interleaved
    c = engine.init(3)
    for w in (wb[0], *wa, *wb, *wa[::1], wb[-1]):
        c.apply(w)
    assert c.visible_values() == a.visible_values()
    # idempotence: replaying every window changes nothing
    before = a.visible_values()
    for w in (*wa, *wb):
        a.apply(w)
    assert a.visible_values() == before


# -- in-process fleet --------------------------------------------------------


def _spawn_fleet(kv, names, **kw):
    """Deterministic fleet: huge TTL (no renew races), dormant
    anti-entropy daemon (tests drive ``sync_now`` themselves)."""
    fleet = {}
    for n in names:
        fleet[n] = FleetServer(n, kv, ttl_s=600.0,
                               ae_interval_s=3600.0, **kw)
    for fs in fleet.values():
        fs.node.refresh_ring()
    return fleet


def _stop_fleet(fleet):
    for fs in fleet.values():
        try:
            fs.stop()
        except Exception:  # noqa: BLE001 — teardown boundary
            pass


def _doc_owned_by(ring, owner, prefix="doc"):
    for i in range(500):
        d = f"{prefix}{i}"
        if ring.primary(d) == owner:
            return d
    pytest.fail(f"no doc routed to {owner}")


def _chain(rid, n, start=1, prev=0):
    ops = []
    for c in range(start, start + n):
        ops.append(Add(ts(rid, c), (prev,), f"r{rid}:{c}"))
        prev = ts(rid, c)
    return json_codec.dumps(Batch(tuple(ops)))


def _state_fp(fleet_server, doc):
    st, raw, hdr = req(fleet_server.port, "GET", f"/docs/{doc}")
    assert st == 200, raw
    return hdr["X-State-Fingerprint"], json.loads(raw)["values"], hdr


def test_fleet_forwarding_replica_reads_and_convergence():
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1", "n2"))
    try:
        ring = fleet["n0"].node.ring()
        assert len(ring) == 3
        doc = _doc_owned_by(ring, "n1")

        # fleet-unique client replica ids, allocated via ANY server
        rids = [json.loads(req(fs.port, "POST",
                               f"/docs/{doc}/replicas")[1])["replica"]
                for fs in fleet.values() for _ in range(2)]
        assert sorted(rids) == list(range(1, 7))

        # a write entering through a NON-primary lands on the primary
        st, raw, hdr = req(fleet["n0"].port, "POST", f"/docs/{doc}/ops",
                           body=_chain(rids[0], 5),
                           headers={"X-Trace-Id": "fleet-fwd-00000001"})
        out = json.loads(raw)
        assert st == 200 and out["accepted"], out
        assert out["served_by"]["name"] == "n1"
        assert out["trace_id"] == "fleet-fwd-00000001"  # echo survives
        assert fleet["n0"].node.counters["forwarded_ok"] >= 1
        assert fleet["n1"].node.counters["forwarded_in"] >= 1

        # replica-local reads: the primary has it NOW (read-your-writes
        # through the committing node); a peer does not until it syncs
        fp1, values1, hdr1 = _state_fp(fleet["n1"], doc)
        assert values1 == [f"r{rids[0]}:{c}" for c in range(1, 6)]
        assert hdr1["X-Replica-Name"] == "n1"
        assert hdr1["X-Replica-Id"] == str(fleet["n1"].node.node_id())
        assert hdr1["X-Replica-Epoch"] == "1"
        assert "X-Commit-Seq" in hdr1 and "X-Snapshot-Fingerprint" in hdr1
        st, raw, _ = req(fleet["n2"].port, "GET", f"/docs/{doc}")
        # n2 materialized an empty local doc when it allocated replica
        # ids above; the write itself has not synced yet — honest
        assert st == 200 and json.loads(raw)["values"] == []

        # one anti-entropy round per peer and the fleet is converged,
        # with the replica-INDEPENDENT fingerprint agreeing everywhere
        # (X-Commit-Seq legitimately differs per server)
        for fs in fleet.values():
            fs.node.antientropy.sync_now()
        for fs in fleet.values():
            fp, values, _ = _state_fp(fs, doc)
            assert values == values1, fs.name
            assert fp == fp1, fs.name

        # the windowed pull surface over HTTP: bounded, resumable
        st, raw, hdr = req(fleet["n1"].port, "GET",
                           f"/docs/{doc}/ops?since=0&limit=2")
        assert st == 200 and hdr["X-Since-Found"] == "1"
        assert hdr["X-Since-More"] == "1"
        assert int(hdr["X-Since-Next"]) == ts(rids[0], 2)
        st, raw, hdr = req(fleet["n1"].port, "GET",
                           f"/docs/{doc}/ops?since=12345&limit=2")
        assert st == 200 and hdr["X-Since-Found"] == "0"

        # /cluster introspection + the crdt_cluster_* prom families
        # under the SAME strict naming contract as everything else
        st, raw, _ = req(fleet["n2"].port, "GET", "/cluster")
        view = json.loads(raw)
        assert set(view["members"]) == {"n0", "n1", "n2"}
        assert view["node"]["name"] == "n2"
        assert view["antientropy"]["rounds"] >= 1
        st, raw, _ = req(fleet["n2"].port, "GET", "/metrics/prom")
        fams = prom_mod.parse_text(raw.decode())
        for fam in ("crdt_cluster_members", "crdt_cluster_node_id",
                    "crdt_cluster_lease_epoch",
                    "crdt_cluster_forwarded_ok_total",
                    "crdt_cluster_antientropy_rounds_total",
                    "crdt_cluster_antientropy_round_ms",
                    "crdt_cluster_antientropy_sync_age_seconds",
                    "crdt_cluster_antientropy_ops_applied_total"):
            assert fam in fams, fam
        peers = {lbl["peer"] for _, lbl, _ in
                 fams["crdt_cluster_antientropy_ops_applied_total"]
                 ["samples"]}
        assert peers == {"n0", "n1"}
    finally:
        _stop_fleet(fleet)


def test_fleet_antientropy_mark_reset_on_lost_peer_log():
    """A peer that no longer knows our high-water mark (restarted with
    a fresh log) answers X-Since-Found: 0 — the puller resets to 0 and
    re-pulls from scratch instead of spinning on empty windows."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1"))
    try:
        doc = _doc_owned_by(fleet["n0"].node.ring(), "n0")
        st, raw, _ = req(fleet["n0"].port, "POST", f"/docs/{doc}/ops",
                         body=_chain(7, 6))
        assert st == 200
        ae = fleet["n1"].node.antientropy
        assert ae.sync_now() == {"n0": True}
        last = ts(7, 6)
        assert ae._peers["n0"].hw[doc] == last
        fp0, _, _ = _state_fp(fleet["n0"], doc)
        fp1, _, _ = _state_fp(fleet["n1"], doc)
        assert fp0 == fp1
        # poison the mark (models: n0 restarted with an empty log and
        # refilled differently — our mark no longer resolves there)
        ae._peers["n0"].hw[doc] = ts(9, 999)
        assert ae.sync_now() == {"n0": True}
        assert ae._peers["n0"].hw[doc] == last   # reset + re-pulled
        fp1b, _, _ = _state_fp(fleet["n1"], doc)
        assert fp1b == fp0                       # duplicates absorbed
    finally:
        _stop_fleet(fleet)


def test_fleet_chaos_kill_failover_converge_rejoin():
    """The tier-1 chaos round, fully deterministic: the victim's
    scheduler is PAUSED so a forwarded write is queued-but-unmerged
    when the crash lands (the in-flight client gets an honest 503 and
    re-pushes through a survivor), failover is operator-forced
    (``expire_now`` — no TTL sleep), survivors converge to
    fingerprint-equal snapshots, and the victim rejoins under its old
    name with a bumped fencing epoch and syncs back to equality."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("n0", "n1", "n2"))
    try:
        ring = fleet["n0"].node.ring()
        doc = _doc_owned_by(ring, "n1", prefix="chaos")
        victim = fleet["n1"]

        # seed state through every server (all forwarded to n1), sync
        for i, fs in enumerate(fleet.values()):
            st, raw, _ = req(fs.port, "POST", f"/docs/{doc}/ops",
                             body=_chain(10 + i, 4))
            assert st == 200, raw
        for fs in fleet.values():
            fs.node.antientropy.sync_now()
        fp_seed, _, _ = _state_fp(fleet["n0"], doc)

        # stage the kill: giant delta forwarded to the paused primary
        victim.node.engine.scheduler.pause()
        result = {}

        def giant():
            st, raw, _ = req(fleet["n0"].port, "POST",
                             f"/docs/{doc}/ops",
                             body=_chain(42, 300), timeout=120)
            result["status"], result["raw"] = st, raw

        th = threading.Thread(target=giant, daemon=True)
        th.start()
        deadline = time.monotonic() + 60
        while victim.node.engine.scheduler_metrics()[
                "queue_depth_total"] < 1:
            assert time.monotonic() < deadline, \
                "giant never reached the victim's queue"
            time.sleep(0.01)

        victim.crash()                      # no drain, no lease release
        th.join(120)
        assert result["status"] == 503, result  # honest failure, not a hang

        # lease-table failover, operator-forced (deterministic)
        assert fleet["n0"].node.leases.expire_now("n1")
        for n in ("n0", "n2"):
            fleet[n].node.refresh_ring()
        new_primary = fleet["n0"].node.primary_for(doc)
        assert new_primary in ("n0", "n2")

        # the client re-pushes the SAME delta through a survivor —
        # idempotent by CRDT construction
        st, raw, _ = req(fleet["n0"].port, "POST", f"/docs/{doc}/ops",
                         body=_chain(42, 300), timeout=120)
        out = json.loads(raw)
        assert st == 200 and out["accepted"], out
        assert out["served_by"]["name"] == new_primary

        # survivors converge; fingerprints equal and state moved on
        for n in ("n0", "n2"):
            fleet[n].node.antientropy.sync_now()
        fp0, values0, _ = _state_fp(fleet["n0"], doc)
        fp2, values2, _ = _state_fp(fleet["n2"], doc)
        assert fp0 == fp2 and fp0 != fp_seed
        assert values0 == values2
        assert "r42:300" in values0

        # rejoin under the old name: same slot, bumped fencing epoch,
        # anti-entropy refills the state to fingerprint equality
        reborn = FleetServer("n1", kv, ttl_s=600.0,
                             ae_interval_s=3600.0)
        fleet["n1"] = reborn
        assert reborn.node.node_id() == victim.node.node_id()
        assert reborn.node.epoch() == victim.node.epoch() + 1
        for fs in fleet.values():
            fs.node.refresh_ring()
        reborn.node.antientropy.sync_now()
        fp1, values1, hdr1 = _state_fp(reborn, doc)
        assert fp1 == fp0 and values1 == values0
        assert hdr1["X-Replica-Epoch"] == str(reborn.node.epoch())
    finally:
        _stop_fleet(fleet)


# -- the real thing: processes, SIGKILL, restart -----------------------------


def _proc_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    return env


def _spawn_node(name, spool, ttl=2.0):
    proc = subprocess.Popen(
        [sys.executable, "-m", "crdt_graph_tpu.cluster", "--cpu",
         "--name", name, "--kv-dir", spool, "--port", "0",
         "--ttl", str(ttl), "--ae-interval", "0.2"],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        env=_proc_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    line = proc.stdout.readline()
    assert line.startswith("READY "), line
    return proc, json.loads(line[len("READY "):])


@pytest.mark.slow
def test_fleet_soak_sigkill_primary_mid_merge(tmp_path):
    """3 real server processes over a shared FileKV spool; the
    primary of the giant's doc dies by SIGKILL mid-merge (the lease is
    NOT released — peers fail it over on TTL expiry), the giant
    re-pushes through a survivor, the victim restarts under its old
    name (bumped fencing epoch) and the fleet converges to
    fingerprint-equal snapshots everywhere."""
    spool = str(tmp_path / "fleet-kv")
    procs, infos = {}, {}
    try:
        for n in ("n0", "n1", "n2"):
            procs[n], infos[n] = _spawn_node(n, spool)
        ports = {n: int(i["addr"].rsplit(":", 1)[1])
                 for n, i in infos.items()}
        # wait until every node's ring sees the whole fleet
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            views = {n: json.loads(req(p, "GET", "/cluster")[1])
                     for n, p in ports.items()}
            if all(len(v["members"]) == 3 for v in views.values()):
                break
            time.sleep(0.1)
        else:
            pytest.fail("fleet membership never stabilized")

        doc = "soak0"
        # route discovery: the seed write's served_by names the primary
        st, raw, _ = req(ports["n0"], "POST", f"/docs/{doc}/ops",
                         body=_chain(5, 3))
        assert st == 200
        victim = json.loads(raw)["served_by"]["name"]
        survivors = [n for n in procs if n != victim]

        # giant push through a SURVIVOR entry (forwarded to the
        # victim), killed mid-merge
        giant_body = _chain(6, 60_000)
        result = {}

        def push_giant():
            entry = ports[survivors[0]]
            dl = time.monotonic() + 300
            while time.monotonic() < dl:
                try:
                    st, raw, _ = req(entry, "POST",
                                     f"/docs/{doc}/ops",
                                     body=giant_body, timeout=300)
                except OSError:
                    time.sleep(0.5)
                    continue
                if st == 200:
                    result["ack"] = json.loads(raw)
                    return
                time.sleep(0.5)     # 429/503: retry through failover
            result["error"] = "giant never acked"

        th = threading.Thread(target=push_giant, daemon=True)
        th.start()
        time.sleep(0.6)             # let the merge start at the victim
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(30)

        th.join(300)
        assert "ack" in result, result
        # acked by a live server (the victim may have acked first if
        # the kill lost the race — then failover still must complete)
        procs.pop(victim).stdout.close()
        p_new, info_new = _spawn_node(victim, spool)
        procs[victim] = p_new
        assert info_new["epoch"] >= 2      # fenced past the dead one
        ports[victim] = int(info_new["addr"].rsplit(":", 1)[1])

        # convergence: every node reports the SAME replica-independent
        # state fingerprint and the giant's 60k values
        deadline = time.monotonic() + 180
        fps = {}
        while time.monotonic() < deadline:
            fps = {}
            for n, p in ports.items():
                try:
                    st, raw, hdr = req(p, "GET", f"/docs/{doc}")
                except OSError:
                    break
                if st != 200:
                    break
                fps[n] = hdr["X-State-Fingerprint"]
            if len(fps) == 3 and len(set(fps.values())) == 1:
                break
            time.sleep(0.5)
        assert len(set(fps.values())) == 1, fps
        st, raw, _ = req(ports[victim], "GET", f"/docs/{doc}")
        values = json.loads(raw)["values"]
        assert len(values) == 60_000 + 3
    finally:
        for p in procs.values():
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for p in procs.values():
            try:
                p.wait(20)
            except subprocess.TimeoutExpired:
                p.kill()


# -- loadgen fleet mode (bench/loadgen.py run_fleet) -------------------------


def test_fleet_loadgen_smoke():
    """Tier-1 closed-loop fleet smoke: concurrent sessions through 3
    servers, sprayed replica-local reads, anti-entropy lag probes, and
    the oracle's cross-replica convergence check actually biting on
    more than one server — zero violations, zero session errors."""
    from crdt_graph_tpu.bench import loadgen
    cfg = loadgen.LoadgenConfig(
        n_servers=3, n_sessions=6, n_docs=2, writes_per_session=4,
        delta_size=6, giant_ops=0, kill_mid_run=False,
        lag_probe_every=2, lease_ttl_s=3.0, ae_interval_s=0.1, seed=3)
    rep = loadgen.run_fleet(cfg)
    assert rep["errors"] == [], rep["errors"]
    assert rep["violations"] == []
    assert rep["oracle"]["violations_total"] == 0
    # every doc fingerprint-converged across all three replicas, and
    # the convergence check ran per doc over the replica set
    assert len(rep["converged"]) == 2
    assert rep["oracle"]["checks"]["convergence"] >= 2
    # reads really were served by non-primary replicas, and lag was
    # actually measured ack -> visible-on-another-replica
    assert rep["reads_replica"] > 0
    assert rep["lag_probes"] > 0 and rep["lag_p99_s"] is not None
    # anti-entropy lag is first-class on the scrape surface
    assert "crdt_cluster_antientropy_sync_age_seconds" in \
        rep["prom_cluster_families"]
    assert "crdt_cluster_antientropy_round_ms" in \
        rep["prom_cluster_families"]


@pytest.mark.slow
def test_fleet_headline_full(tmp_path):
    """The committed-artifact run (BENCH_FLEET_r01_cpu.json shape):
    3 servers, concurrent sessions + giant racer, mid-merge kill with
    lease failover and rejoin, zero violations, fingerprint-equal
    convergence.  Slow-marked — tier-1 runs the smoke above."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_fleet_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_fleet_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_FLEET_test.json"))
    rep = out["report"]
    assert out["violations_total"] == 0
    assert not rep["errors"], rep["errors"]
    assert out["servers"] == 3 and out["sessions"] >= 60
    assert out["total_leaves"] >= 40_000
    assert rep["kill"] and "failover_s" in rep["kill"]
    assert rep["kill"]["rejoined_epoch"] >= 2
    assert out["converged_docs"] == 6
    assert out["antientropy_lag_p99_s"] is not None
    assert out["read_replica_p99_ms"] is not None
