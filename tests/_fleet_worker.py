"""Worker for the fleet failure-injection test (tests/test_distributed.py
::test_fleet_kill_restart_rejoin; VERDICT r4 next-8).

Three phases model the life of a 2-process compute fleet whose data
plane is the replication service:

- ``run`` (pid 0 and 1): join the jax.distributed runtime, claim a
  replica id from the service, make 40 local edits, checkpoint the full
  local state (the WAL role of ``checkpoint_packed``), push HALF the
  edits, run the 8-doc fleet merge (real collectives across both
  processes), then pid 1 dies hard (``os._exit``) with its second half
  unpushed — death mid-session, after the gang-scheduled collective.
  (A death DURING a collective hangs the gang — XLA collectives are
  all-or-nothing, same as the reference's NCCL world — so the fleet
  policy for that case is detect-and-restart of the whole gang, which
  phase ``refleet`` exercises.)
- ``rejoin`` (single replacement for the dead pid 1): warm restart from
  its WAL checkpoint, anti-entropy pull (the overlap absorbs as
  duplicates), idempotent re-push of its whole log; THEN a total-loss
  observer bootstraps from ``GET /snapshot`` under a fresh replica id
  and catches up over ``/ops?since=<last add it knows>`` (inclusive
  overlap absorbs) — both converge with the server.
- ``refleet`` (pid 0 and 1, fresh coordinator): a NEW gang re-forms
  with NO local state, each process bootstrapping purely from the
  service snapshot, and re-runs the fleet merge — the compute fleet is
  stateless modulo the replicated data plane.

Usage: python tests/_fleet_worker.py PHASE COORD_PORT HTTP_PORT PID CKPT_DIR
"""
import io
import json
import os
import sys

PHASE, COORD_PORT, HTTP_PORT, PID, CKPT_DIR = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5])

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from http.client import HTTPConnection  # noqa: E402

from crdt_graph_tpu import engine  # noqa: E402
from crdt_graph_tpu.bench import workloads  # noqa: E402
from crdt_graph_tpu.codec import json_codec  # noqa: E402
from crdt_graph_tpu.ops import merge  # noqa: E402
from crdt_graph_tpu.parallel import distributed  # noqa: E402
from crdt_graph_tpu.parallel import mesh as mesh_mod  # noqa: E402

N_PROCS = 2
DOCS_PER_PROC = 4
N_PAD = 64
EDITS = 40
DOC = "fleet"


def req(method, path, body=None, raw=False):
    conn = HTTPConnection("127.0.0.1", HTTP_PORT, timeout=60)
    conn.request(method, path, body=body)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, (data if raw else json.loads(data))


def fleet_merge(tag: str) -> None:
    """The compute-fleet half: 8 documents sharded across both
    processes' devices, merged collectively, fingerprint-checked
    against local single-device merges (mix-up detection) — the same
    recipe as _distributed_worker.py, run here to pin that fleet
    compute and the data-plane session coexist."""
    import jax.numpy as jnp

    def doc_ops(doc_id):
        return mesh_mod._pad_ops_to(
            workloads.chain_workload(2 + doc_id, 30), N_PAD)

    mesh = distributed.global_device_mesh(n_ops=1)
    my_docs = range(PID * DOCS_PER_PROC, (PID + 1) * DOCS_PER_PROC)
    local = [doc_ops(d) for d in my_docs]
    stacked = {k: np.stack([d[k] for d in local]) for k in local[0]}
    # global assembly exercised; compute runs on this process's local
    # shard and convergence is KV-verified — see _distributed_worker.py
    # (this jaxlib's CPU client cannot EXECUTE cross-process
    # computations; a TPU fleet runs the global jit here)
    global_ops = distributed.host_local_docs_to_global(stacked, mesh)
    assert all(not v.is_fully_addressable for v in global_ops.values())
    from jax.sharding import Mesh
    local_mesh = Mesh(
        np.asarray(jax.local_devices()).reshape(DOCS_PER_PROC, 1),
        (mesh_mod.DOCS_AXIS, mesh_mod.OPS_AXIS))
    table = mesh_mod.batched_materialize(stacked, local_mesh)

    def fp(t):
        return jnp.sum(jnp.where(t.visible, t.ts % jnp.int64(1000003), 0),
                       axis=-1)

    fp_l = np.asarray(jax.jit(fp)(table)).tolist()
    got = distributed.allgather_scalars(
        f"fleetfp-{tag}",
        {PID * DOCS_PER_PROC + i: int(v) for i, v in enumerate(fp_l)})
    for d in range(8):
        want = int(np.asarray(jax.device_get(jax.jit(fp)(
            merge.materialize({k: jax.device_put(v)
                               for k, v in doc_ops(d).items()})))))
        assert int(got[d]) == want, (tag, d, int(got[d]), want)
    print(f"worker {PID}: fleet merge {tag} OK", flush=True)


def run() -> None:
    distributed.initialize(f"127.0.0.1:{COORD_PORT}",
                           num_processes=N_PROCS, process_id=PID)
    assert jax.process_count() == N_PROCS
    _, r = req("POST", f"/docs/{DOC}/replicas")
    t = engine.init(r["replica"])
    for i in range(EDITS):
        t.add(f"w{PID}-e{i}")
    # WAL: full local state is durable before anything is pushed
    t.checkpoint_packed(os.path.join(CKPT_DIR, f"w{PID}.npz"))
    half = engine.Batch(t.operations_since(0).ops[:EDITS // 2])
    st, out = req("POST", f"/docs/{DOC}/ops", json_codec.dumps(half))
    assert st == 200 and out["accepted"], out

    fleet_merge("pre-crash")        # collectives run gang-scheduled

    if PID == 1:
        print("worker 1: dying mid-session", flush=True)
        os._exit(17)                # second half exists only in the WAL
    rest = engine.Batch(t.operations_since(0).ops[EDITS // 2:])
    st, out = req("POST", f"/docs/{DOC}/ops", json_codec.dumps(rest))
    assert st == 200 and out["accepted"], out
    print(f"worker {PID}: OK", flush=True)


def rejoin() -> None:
    # warm restart: the WAL checkpoint carries replica id + unpushed tail
    t = engine.TpuTree.restore_packed(os.path.join(CKPT_DIR, "w1.npz"))
    assert t.log_length == EDITS, t.log_length
    # snapshot BEFORE the re-push: the observer below must need /ops?since=
    _, snap = req("GET", f"/docs/{DOC}/snapshot", raw=True)
    # anti-entropy pull + idempotent re-push
    _, ops = req("GET", f"/docs/{DOC}/ops?since=0", raw=True)
    t.apply(json_codec.loads(ops.decode()))
    st, out = req("POST", f"/docs/{DOC}/ops",
                  json_codec.dumps(t.operations_since(0)))
    assert st == 200 and out["accepted"], out

    # total-loss observer: snapshot bootstrap under a FRESH id, then
    # catch up over /ops?since= (inclusive-add semantics: start from the
    # newest add the snapshot contains; the overlap absorbs)
    _, r = req("POST", f"/docs/{DOC}/replicas")
    obs = engine.TpuTree.restore_packed(io.BytesIO(snap),
                                        replica=r["replica"])
    last_known = max(op.ts for op in obs.operations_since(0).ops
                     if isinstance(op, engine.Add))
    _, delta = req("GET", f"/docs/{DOC}/ops?since={last_known}", raw=True)
    obs.apply(json_codec.loads(delta.decode()))

    _, doc = req("GET", f"/docs/{DOC}")
    assert sorted(doc["values"]) == sorted(t.visible_values()) \
        == sorted(obs.visible_values()), "rejoin did not converge"
    assert len(doc["values"]) == N_PROCS * EDITS
    print("rejoined: OK", flush=True)


def refleet() -> None:
    distributed.initialize(f"127.0.0.1:{COORD_PORT}",
                           num_processes=N_PROCS, process_id=PID)
    # gang re-forms with zero local state: bootstrap from the service
    _, snap = req("GET", f"/docs/{DOC}/snapshot", raw=True)
    _, r = req("POST", f"/docs/{DOC}/replicas")
    t = engine.TpuTree.restore_packed(io.BytesIO(snap),
                                      replica=r["replica"])
    assert len(t.visible_values()) == N_PROCS * EDITS
    fleet_merge("post-restart")
    print(f"worker {PID}: refleet OK", flush=True)


if __name__ == "__main__":
    {"run": run, "rejoin": rejoin, "refleet": refleet}[PHASE]()
