"""Merge-kernel parity suite: the jitted semilattice join must reproduce the
sequential oracle on every fixture and on randomized causally-valid
multi-replica logs, under arbitrary permutations of delivery order.

This is the convergence/race-detection strategy of the framework (SURVEY §5):
random op permutations and partitions must produce identical visible
sequences, with the pure oracle as the correctness reference.
"""
import random

import numpy as np
import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import Add, Batch, Delete
from crdt_graph_tpu.codec import packed
from crdt_graph_tpu.core import operation as op_mod
from crdt_graph_tpu.ops import merge, view
from crdt_graph_tpu.utils import jaxcompat

OFFSET = 2**32


def kernel_visible(ops, max_depth=16):
    p = packed.pack(ops, max_depth=max_depth)
    t = view.to_host(merge.materialize(p.arrays()))
    return view.visible_values(t, p.values), t, p


def oracle_visible(ops):
    tree = crdt.init(99)
    for op in ops:
        try:
            tree = tree.apply(op)
        except crdt.CRDTError:
            pass
    return tree.visible_values(), tree


# -- the canonical convergence fixtures (tests/NodeTest.elm:23-60) --------

@pytest.mark.parametrize("order", [(6, 5, 4), (4, 6, 5), (4, 5, 6),
                                   (5, 4, 6), (5, 6, 4), (6, 4, 5)])
def test_interleaving_converges(order):
    ops = [Add(1, (0,), 1), Add(2, (1,), 2), Add(3, (2,), 3)] + \
        [Add(t, (1,), t) for t in order]
    vis, _, _ = kernel_visible(ops)
    assert vis == [1, 6, 5, 4, 2, 3]


def test_append_order_converges():
    for ops in ([Add(1, (0,), "a"), Add(2, (0,), "b")],
                [Add(2, (0,), "b"), Add(1, (0,), "a")]):
        vis, _, _ = kernel_visible(ops)
        assert vis == ["b", "a"]


# -- reference state-machine scenarios through the kernel -----------------

def test_insert_between():
    ops = [Add(1, (0,), "a"), Add(2, (1,), "b"), Add(3, (2,), "c"),
           Add(4, (1,), "z")]
    vis, _, _ = kernel_visible(ops)
    assert vis == ["a", "z", "b", "c"]


def test_delete_kills_subtree():
    ops = [Add(1, (0,), "a"), Add(2, (1, 0), "b"), Add(3, (1,), "c"),
           Delete((1,))]
    vis, t, p = kernel_visible(ops)
    assert vis == ["c"]
    assert view.statuses(t, p.num_ops) == ["applied"] * 4


def test_add_to_deleted_branch_absorbed():
    ops = [Add(1, (0,), "a"), Delete((1,)), Add(2, (1, 0), "b")]
    vis, t, p = kernel_visible(ops)
    assert vis == []
    assert view.statuses(t, p.num_ops) == \
        ["applied", "applied", "already_applied"]


def test_add_idempotent():
    ops = [Add(1, (0,), "a")] * 4
    vis, t, p = kernel_visible(ops)
    assert vis == ["a"]
    assert view.statuses(t, p.num_ops) == \
        ["applied"] + ["already_applied"] * 3


def test_delete_idempotent():
    ops = [Add(1, (0,), "a")] + [Delete((1,))] * 5
    vis, t, p = kernel_visible(ops)
    assert vis == []
    assert view.statuses(t, p.num_ops) == \
        ["applied", "applied"] + ["already_applied"] * 4


def test_empty_path_ops_flagged():
    ops = [crdt.Add(1, (0,), "a"), Add(10, (), "y"), Delete(())]
    vis, t, p = kernel_visible(ops)
    assert vis == ["a"]
    assert view.statuses(t, p.num_ops) == \
        ["applied", "invalid_path", "invalid_path"]


def test_delete_sentinel_already_applied():
    # deleting a branch head (path ending in 0) finds the sentinel tombstone
    ops = [crdt.Add(1, (0,), "a"), Delete((0,)), Delete((1, 0))]
    vis, t, p = kernel_visible(ops)
    assert vis == ["a"]
    assert view.statuses(t, p.num_ops) == \
        ["applied", "already_applied", "already_applied"]


def test_missing_anchor_flagged():
    ops = [Add(1, (0,), "a"), Add(2, (9,), "b")]
    vis, t, p = kernel_visible(ops)
    assert vis == ["a"]
    assert view.statuses(t, p.num_ops) == ["applied", "not_found"]


def test_missing_intermediate_flagged():
    ops = [Add(1, (0,), "a"), Add(2, (7, 0), "b")]
    vis, t, p = kernel_visible(ops)
    assert view.statuses(t, p.num_ops) == ["applied", "invalid_path"]


def test_invalid_parent_cascades():
    # b's parent add is invalid, so b and everything under it is invalid too
    ops = [Add(1, (0,), "a"), Add(2, (9, 0), "b"), Add(3, (2, 0), "c")]
    vis, t, p = kernel_visible(ops)
    assert vis == ["a"]
    st = view.statuses(t, p.num_ops)
    assert st[1] == "invalid_path" and st[2] == "invalid_path"


def test_nested_branches():
    ops = [Add(1, (0,), "a"), Add(2, (1, 0), "b"), Add(3, (1, 2, 0), "c"),
           Add(4, (1, 2, 3, 0), "d"), Add(5, (1, 2, 3, 4, 0), "e"),
           Add(6, (1, 2, 3, 4, 5), "f")]
    vis, t, p = kernel_visible(ops)
    assert vis == ["a", "b", "c", "d", "e", "f"]
    assert view.get_value(t, p.values, [1, 2, 3]) == "c"
    assert view.get_value(t, p.values, [1, 2, 3, 4, 6]) == "f"
    assert view.get_value(t, p.values, [9]) is None


def test_tombstone_anchor_still_orders():
    # chain a(10) b(30)† then insert 20 after 10: must skip past the
    # tombstone (divergence note in core/node.py applies to both engines)
    ops = [Add(10, (0,), "a"), Add(30, (10,), "b"), Delete((30,)),
           Add(20, (10,), "c")]
    vis, _, _ = kernel_visible(ops)
    assert vis == ["a", "c"]


@pytest.mark.parametrize("cycle", [
    # 1-cycle: an op anchored at its own timestamp
    [Add(5, (5,), "a")],
    # 2-cycle: each op anchors at the other
    [Add(5, (7,), "a"), Add(7, (5,), "b")],
    # 3-cycle
    [Add(5, (9,), "a"), Add(7, (5,), "b"), Add(9, (7,), "c")],
])
def test_anchor_cycles_rejected_like_the_oracle(cycle):
    """An adversarial op set closing an anchor loop admits NO serial
    order — the oracle rejects every member (anchor absent on arrival),
    and the kernel's cycle check must agree instead of letting the loop
    corrupt the order forest.  Surrounding valid ops are unaffected."""
    ops = [Add(1, (0,), "x")] + cycle + [Add(2, (1,), "y")]
    want, _ = oracle_visible(ops)
    vis, t, p = kernel_visible(ops)
    assert want == ["x", "y"]
    assert vis == want
    st = view.statuses(t, p.num_ops)
    assert st[0] == "applied" and st[-1] == "applied"
    assert all(s in ("not_found", "invalid_path") for s in st[1:-1])


def test_long_ascending_chain_with_late_small_anchor():
    """Regression (round-3 soak): an ASCENDING anchor chain resolves each
    node's nearest-smaller-ancestor instantly (frozen answers), and a
    late smaller-timestamp op anchored at the chain tail must then walk
    that answer chain — longer than the chase's log-trip cap.  The
    binary-lifting fallback finishes the walk exactly; without it the
    node mis-parents and the visible order flips its last two entries."""
    R = 2 * 2**32
    chain_len = 40                       # > ceil(log2(M)) + 2 trips
    ops = [Add(R + 1, (0,), "A"), Add(R + 2, (R + 1,), "X")]
    prev = R + 1
    for k in range(3, chain_len + 3):
        ops.append(Add(R + k, (prev,), f"c{k}"))
        prev = R + k
    # replica 1: smaller ts than every chain node, anchored at the tail
    ops.append(Add(1 * 2**32 + 1, (prev,), "Z"))
    vis, _, _ = kernel_visible(ops)
    want, _ = oracle_visible(ops)
    assert vis == want
    # the reference order: Z drifts right past X (larger ts) to the end
    assert vis[-2:] == ["X", "Z"]


# -- permutation invariance on fixed fixtures -----------------------------

def test_permutation_invariance_small():
    base = [Add(1, (0,), "a"), Add(2, (1, 0), "b"), Add(3, (1, 2), "c"),
            Add(4, (1,), "d"), Delete((3,)), Add(5, (2**32 + 1,), "e")]
    # note: op 5 anchors at a missing node — stays invalid in every order
    want, _ = oracle_visible(base)
    rng = random.Random(7)
    for _ in range(12):
        perm = base[:]
        rng.shuffle(perm)
        vis, _, _ = kernel_visible(perm)
        assert vis == want


def test_out_of_range_replica_id_rejected_loudly():
    # timestamps at/above 2**62 collide with kernel sentinels; pack refuses
    with pytest.raises(ValueError):
        packed.pack([Add(2**62 + 1, (0,), "a")])
    with pytest.raises(ValueError):
        packed.pack([Delete((2**62 + 1,))])


# -- link hints: the hinted resolution path (ops/merge.py step 4) ---------

def test_hinted_and_joined_paths_agree():
    """The same batch through the hinted path (pack's link hints) and the
    join path (hint columns stripped) must produce identical tables."""
    merged, ops = _random_session(21, n_replicas=3, steps=80)
    p = packed.pack(ops)
    arrs = p.arrays()
    t_hint = view.to_host(merge.materialize(arrs))
    stripped = {k: v for k, v in arrs.items()
                if k not in ("parent_pos", "anchor_pos", "target_pos")}
    t_join = view.to_host(merge.materialize(stripped))
    assert view.visible_values(t_hint, p.values) == \
        view.visible_values(t_join, p.values)
    assert view.statuses(t_hint, p.num_ops) == \
        view.statuses(t_join, p.num_ops)
    assert np.array_equal(np.asarray(t_hint.doc_index),
                          np.asarray(t_join.doc_index))


def test_mislinked_hints_fall_back_to_join():
    """Corrupted hints (every hint pointing at op 0) must not change the
    result — the kernel verifies on device and falls back to the join."""
    merged, ops = _random_session(22, n_replicas=3, steps=60)
    want = merged.visible_values()
    p = packed.pack(ops)
    arrs = dict(p.arrays())
    for k in ("parent_pos", "anchor_pos", "target_pos"):
        bad = np.asarray(arrs[k]).copy()
        bad[bad >= 0] = 0           # mislink everything resolvable
        arrs[k] = bad
    t = view.to_host(merge.materialize(arrs))
    assert view.visible_values(t, p.values) == want


def test_hint_modes_agree():
    """auto (cond fallback), exhaustive (no join compiled), and join
    (hints ignored) must produce identical tables on pack-produced
    batches — including one with a genuinely missing anchor (unresolved
    ref: auto takes the join at runtime, exhaustive resolves to
    not-found directly; same answer)."""
    merged, ops = _random_session(33, n_replicas=3, steps=70)
    ops = ops + [Add(77 * 2**32 + 1, (12345,), "orphan")]  # absent anchor
    p = packed.pack(ops)
    arrs = p.arrays()
    tables = [view.to_host(merge.materialize(arrs, hints=h))
              for h in (None, "exhaustive", "join")]
    for t in tables[1:]:
        assert view.visible_values(t, p.values) == \
            view.visible_values(tables[0], p.values)
        assert view.statuses(t, p.num_ops) == \
            view.statuses(tables[0], p.num_ops)
        assert np.array_equal(np.asarray(t.doc_index),
                              np.asarray(tables[0].doc_index))


def test_hostile_ranks_fall_back():
    """Corrupting ts_rank in every distinct way (shuffle, collision, gap,
    missing, out-of-range) must trip the device-side rank verification
    and route the batch down the sorted+join branch — identical tables,
    wrong hints cost speed never correctness (ops/merge.py steps 1-4)."""
    merged, ops = _random_session(91, n_replicas=3, steps=60)
    p = packed.pack(ops)
    base = p.arrays()
    want_t = view.to_host(merge.materialize(base, hints="join"))
    want_vals = view.visible_values(want_t, p.values)
    want_status = view.statuses(want_t, p.num_ops)
    rng = np.random.default_rng(5)
    adds = np.nonzero(base["ts_rank"] >= 0)[0]

    def corrupt(name, mutate):
        arrs = dict(base)
        r = base["ts_rank"].copy()
        mutate(r)
        arrs["ts_rank"] = r
        t = view.to_host(merge.materialize(arrs))     # auto mode
        assert view.visible_values(t, p.values) == want_vals, name
        assert view.statuses(t, p.num_ops) == want_status, name
        assert np.array_equal(np.asarray(t.doc_index),
                              np.asarray(want_t.doc_index)), name

    corrupt("shuffled",
            lambda r: r.__setitem__(adds, rng.permutation(r[adds])))
    corrupt("collision", lambda r: r.__setitem__(adds[1], r[adds[0]]))
    corrupt("gap", lambda r: r.__setitem__(adds, r[adds] + 1))
    corrupt("missing", lambda r: r.__setitem__(adds[2], -1))
    corrupt("oob", lambda r: r.__setitem__(adds[0], 10**6))
    corrupt("all_missing", lambda r: r.fill(-1))


def test_concat_reresolves_cross_hints():
    """concat must re-resolve each side's unresolved refs against the
    other side so the union's hints stay exhaustive (b's ops anchored in
    a, and a's shuffled ops anchored in b)."""
    base = [Add(1, (0,), "a"), Add(2, (1,), "b")]
    delta = [Add(3, (2,), "c"), Delete((1,))]    # refs into base
    u = packed.concat(packed.pack(base), packed.pack(delta))
    assert int(u.anchor_pos[2]) == 1             # c's anchor = b (pos 1)
    assert int(u.target_pos[3]) == 0             # delete target = a
    t = view.to_host(merge.materialize(u.arrays()))
    assert view.visible_values(t, u.values) == ["b", "c"]
    # reverse direction: first part references ops living in the second
    back = packed.concat(packed.pack(delta), packed.pack(base))
    assert int(back.anchor_pos[0]) == 3          # c's anchor = b at pos 3
    assert int(back.target_pos[1]) == 2          # delete target = a at pos 2
    t2 = view.to_host(merge.materialize(back.arrays()))
    assert view.visible_values(t2, back.values) == ["b", "c"]


# -- randomized causal multi-replica logs vs the oracle -------------------

def _random_session(seed, n_replicas=4, steps=120):
    """Simulate replicas editing + syncing through the oracle API; return
    (fully merged oracle tree, full op list)."""
    rng = random.Random(seed)
    trees = [crdt.init(r + 1) for r in range(n_replicas)]
    for _ in range(steps):
        i = rng.randrange(n_replicas)
        t = trees[i]
        roll = rng.random()
        try:
            if roll < 0.5:
                t = t.add(rng.randrange(1000))
            elif roll < 0.65:
                t = t.add_branch(rng.randrange(1000))
            elif roll < 0.8:
                # delete a random visible node
                vis = []
                t.walk(lambda n, acc: (crdt.TAKE, acc.append(n.path) or acc),
                       vis)
                if vis:
                    t = t.delete(rng.choice(vis))
            else:
                # sync: pull everything from a random peer
                j = rng.randrange(n_replicas)
                if j != i:
                    t = t.apply(trees[j].operations_since(0))
        except crdt.CRDTError:
            pass
        trees[i] = t
    # full mesh sync to convergence
    for i in range(n_replicas):
        for j in range(n_replicas):
            if i != j:
                trees[i] = trees[i].apply(trees[j].operations_since(0))
    merged = trees[0]
    ops = op_mod.to_list(merged.operations_since(0))
    return merged, ops


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_session_parity(seed):
    merged, ops = _random_session(seed)
    want = merged.visible_values()
    vis, _, _ = kernel_visible(ops)
    assert vis == want
    # convergence under random permutation of the op log
    rng = random.Random(seed + 100)
    perm = ops[:]
    rng.shuffle(perm)
    vis_p, _, _ = kernel_visible(perm)
    assert vis_p == want


@pytest.mark.parametrize("seed", [5, 6])
def test_random_session_partition_merge(seed):
    """Splitting a log in two and concatenating the packed halves (the
    semilattice union) must equal materialising the whole."""
    merged, ops = _random_session(seed, n_replicas=3, steps=80)
    want = merged.visible_values()
    k = len(ops) // 3
    a, b = packed.pack(ops[:k]), packed.pack(ops[k:])
    u = packed.concat(a, b)
    t = view.to_host(merge.materialize(u.arrays()))
    assert view.visible_values(t, u.values) == want


def test_status_parity_random_sequential():
    """Statuses match what the oracle reports op-by-op on a causal log."""
    merged, ops = _random_session(11, n_replicas=3, steps=60)
    # oracle: apply sequentially, record per-op outcome
    tree = crdt.init(50)
    want = []
    for op in ops:
        before = len(tree.operations)
        tree = tree.apply(op)
        if len(tree.operations) > before:
            want.append("applied")
        else:
            want.append("already_applied")
    vis, t, p = kernel_visible(ops)
    assert view.statuses(t, p.num_ops) == want


def test_forged_prefix_rejected_exactly():
    """Path validation is EXACT row comparison, not a hash: an op whose
    claimed prefix names the right parent timestamp in the wrong positions
    (or any adversarially-crafted near-miss) must be invalid_path.  Guards
    the removal of the old fixed-base polynomial hash, which a malicious
    peer could collide (ADVICE r1)."""
    ops = [crdt.Add(1, (0,), "a"),           # node at path (1,)
           crdt.Add(2, (1, 0), "b"),         # nested: path (1, 2)
           # forged: claims parent prefix (2,) but node 2's path is (1, 2)
           Add(7, (2, 2), "x"),
           # forged: right length, wrong element
           Add(8, (9, 2, 0), "y")]
    vis, t, p = kernel_visible(ops)
    assert vis == ["a", "b"]
    assert view.statuses(t, p.num_ops)[2:] == \
        ["invalid_path", "invalid_path"]


def test_no_deletes_trace_parity():
    """The static no-deletes fast path must be bit-identical to the
    default trace on an all-adds batch, and materialize must keep it OFF
    the moment a delete is present (merge.host_no_deletes is the single
    definition both call sites share)."""
    merged, ops = _random_session(17, n_replicas=3, steps=50)
    ops = [op for op in ops if not isinstance(op, Delete)]  # all-adds
    p = packed.pack(ops)
    arrs = p.arrays()
    assert merge.host_no_deletes(arrs["kind"])
    import jax
    with jaxcompat.enable_x64(True):
        lean = view.to_host(merge._materialize(arrs, None, None, True))
        full = view.to_host(merge._materialize(arrs, None, None, False))
    for f in ("ts", "parent", "depth", "value_ref", "exists", "tombstone",
              "dead", "visible", "doc_index", "order", "visible_order",
              "status"):
        assert np.array_equal(np.asarray(getattr(lean, f)),
                              np.asarray(getattr(full, f))), f
    # a single delete flips the host check off
    with_del = ops + [Delete(ops[0].path[:0] + (ops[0].ts,))]
    p2 = packed.pack(with_del)
    assert not merge.host_no_deletes(p2.arrays()["kind"])


@pytest.mark.slow
def test_probe_cuts_run_every_stage():
    """The kernel's profiling cut points (merge._materialize probe=k,
    scripts/probe_stages.py) must keep returning a scalar at every
    stage, with and without deletes — so the on-chip stage profile the
    r4 verdict asked for can never bit-rot.  Slow-marked (ISSUE 12
    tier-1 budget): 14 compiles for a profiling-script tripwire, not a
    production-path invariant."""
    import jax
    _, ops = _random_session(23, n_replicas=3, steps=40)
    for op_set in (ops, [op for op in ops if not isinstance(op, Delete)]):
        arrs = packed.pack(op_set).arrays()
        nd = merge.host_no_deletes(arrs["kind"])
        with jaxcompat.enable_x64(True):
            for k in range(1, 8):
                out = merge._materialize(arrs, None, "exhaustive", nd, k)
                assert np.asarray(out).shape == (), k
            t = merge._materialize(arrs, None, "exhaustive", nd, None)
        assert hasattr(t, "status")


def test_hostile_pos_duplicate_winner_agrees():
    """ADVICE r3: a raw-array producer violating the pos == array-index
    contract must not let the ranked path and the join fallback pick
    different canonical copies of a duplicated timestamp.  Both paths
    share one winner rule — the first ARRAY ROW — so the surfaced
    payload/value_ref/status cannot depend on which construction ran."""
    ops = [Add(1, (0,), "first"), Add(1, (0,), "second"),
           Add(2, (1,), "tail")]
    p = packed.pack(ops)
    arrs = dict(p.arrays())
    hostile = np.asarray(arrs["pos"]).copy()
    hostile[0], hostile[1] = 1, 0       # pos claims row 1 arrived first
    arrs["pos"] = hostile
    t_rank = view.to_host(merge.materialize(arrs))           # ranked path
    t_join = view.to_host(merge.materialize(arrs, hints="join"))
    assert view.visible_values(t_rank, p.values) == \
        view.visible_values(t_join, p.values) == ["first", "tail"]
    assert view.statuses(t_rank, p.num_ops) == \
        view.statuses(t_join, p.num_ops)


def test_verify_hints_audits_rank_and_links():
    """packed.verify_hints (the restore-time host audit, ADVICE r3)
    accepts a pack-produced batch and rejects each corruption class:
    stale ranks, mislinked hints, and a dropped hint whose reference is
    resolvable in-batch."""
    ops = [Add(1, (0,), "a"), Add(2, (1,), "b"), Add(3, (2,), "c"),
           Add(4, (1, 0), "d"), Delete((2,))]
    p = packed.pack(ops)
    assert packed.verify_hints(p)

    import dataclasses as dc

    def mutated(**cols):
        q = dc.replace(p, **{k: np.asarray(v).copy()
                             for k, v in cols.items()})
        return packed.verify_hints(q)

    r = p.ts_rank.copy(); r[0], r[1] = r[1], r[0]
    assert not mutated(ts_rank=r)
    a = p.anchor_pos.copy(); a[a >= 0] = 0
    assert not mutated(anchor_pos=a)
    t = p.target_pos.copy(); t[t >= 0] = -1     # drop a resolvable hint
    assert not mutated(target_pos=t)
    pp = p.parent_pos.copy(); pp[3] = 2         # wrong row for d's parent
    assert not mutated(parent_pos=pp)


def test_verify_hints_rejects_stray_out_of_batch_hint():
    """Property (c): an UNRESOLVABLE reference must carry -1.  The
    exhaustive kernel resolves ``hint >= 0`` without the per-hint ts
    check gather (merge._res_hint_impl check_ts=False), so a stray
    hint on an out-of-batch reference would silently resolve to an
    unrelated node instead of landing NOT_FOUND — verify_hints (run on
    every restore/foreign ingest) must therefore reject it, and auto
    mode must still converge to the oracle via the cond fallback."""
    import dataclasses as dc
    ops = [Add(1, (0,), "a"), Add(2, (1,), "b"),
           Add(6, (5, 0), "x")]          # parent ts 5 not in batch
    p = packed.pack(ops)
    assert packed.verify_hints(p)
    assert p.parent_pos[2] == -1
    sp = p.parent_pos.copy()
    sp[2] = 0                            # stray: points at the ts-1 row
    q = dc.replace(p, parent_pos=sp)
    assert not packed.verify_hints(q)
    # auto mode re-verifies on device: the stray hint fails the link
    # check and the whole batch routes through sort+join — same tree
    # as the untampered batch
    t_ok = view.to_host(merge.materialize(p.arrays(), hints="auto"))
    t_bad = view.to_host(merge.materialize(q.arrays(), hints="auto"))
    assert view.visible_values(t_ok, p.values) == \
        view.visible_values(t_bad, q.values)
    assert view.statuses(t_ok, p.num_ops) == \
        view.statuses(t_bad, q.num_ops)


# -- int32 bit-half discipline (round 5): every i64 scatter runs as two
# i32 half scatters (v5e-emulated i64 scatters measured ~25x an i32
# scatter, SWEEP_TPU_r05_prefix).  These pin the wrap/bias edges: low
# halves >= 2^31 (negative as raw int32), and values adjacent to the
# BIG sentinel's bit pattern.

def test_split_pack_roundtrip_edges():
    import jax
    import jax.numpy as jnp
    vals = np.array([0, 1, 2**31 - 1, 2**31, 2**32 - 1, 2**32,
                     2**32 + 2**31, 5 * 2**32 + (2**32 - 1),
                     merge.BIG - 1, merge.BIG], dtype=np.int64)
    with jaxcompat.enable_x64(True):     # bare asarray would truncate to i32
        v = jnp.asarray(vals)
        h, l = merge._split_u(v)
        assert np.array_equal(np.asarray(merge._pack_u(h, l)), vals)
        hb, lb = merge._split_ts(v)
        assert np.array_equal(
            np.asarray(merge._pack_biased(hb, lb)), vals)
    # biased halves preserve order as a 2-key comparison
    order = np.lexsort((np.asarray(lb), np.asarray(hb)))
    assert np.array_equal(vals[order], np.sort(vals))


def test_high_low_half_timestamps_converge():
    """Counters >= 2^31 put the ts LOW half in negative int32 territory
    for both the biased (sort keys) and raw (fp planes) splits; the
    kernel (any delivery order — SET semantics) must agree with the
    oracle's causal-order fold exactly there."""
    hi = 2**31  # counter crossing the int32 sign boundary
    ops = [Add(1 * OFFSET + hi, (0,), "a"),
           Add(1 * OFFSET + hi + 1, (1 * OFFSET + hi,), "b"),
           Add(2 * OFFSET + 5, (0,), "c"),
           Add(2 * OFFSET + hi + 7, (2 * OFFSET + 5,), "d"),
           Add(1 * OFFSET + 3, (0,), "e")]
    exp, _ = oracle_visible(ops)       # causal order for the oracle
    for seed in range(6):
        rng = random.Random(seed)
        shuffled = ops[:]
        rng.shuffle(shuffled)
        vis, _, _ = kernel_visible(shuffled)
        assert vis == exp, f"seed {seed}: {vis} != {exp}"


def test_pack_gather_layout_bit_identity(monkeypatch):
    """GRAFT_PACK_GATHER routes the shared-index gathers of stages 1-2
    through multi-column plane row-gathers (merge._pack_gather_on); the
    two layouts are exact integer re-packings, so every NodeTable field
    must be bit-identical across them in all three hint modes.  The flag
    is read at trace time, so the caches are cleared between settings."""
    import jax

    rng = random.Random(77)
    o = crdt.init(5)
    for i in range(300):
        r = rng.random()
        if r < 0.55:
            o = o.add(f"v{i}")
        elif r < 0.7 and len(o.cursor) < 10:
            o = o.add_branch(f"b{i}")
        elif o.visible_values():
            try:
                o = o.delete(list(o.cursor))
            except (crdt.OperationFailedError, crdt.InvalidPathError):
                pass
    arrs = packed.pack(o.operations_since(0)).arrays()
    fields = ["ts", "parent", "depth", "value_ref", "paths", "exists",
              "tombstone", "dead", "visible", "doc_index", "order",
              "visible_order", "num_nodes", "num_visible", "status"]

    def tables():
        out = {h: view.to_host(merge.materialize(arrs, hints=h))
               for h in ("exhaustive", "auto", "join")}
        # the explicit shard schedule shares _node_cols_from_row/_finish:
        # the flag must preserve its bit-identity contract there too
        from crdt_graph_tpu import parallel
        out["shard"] = parallel.shard_materialize(
            arrs, parallel.make_mesh(n_ops=8))
        return out

    # default is ON (round 6): pin the two legs explicitly either way
    monkeypatch.setenv("GRAFT_PACK_GATHER", "0")
    jax.clear_caches()
    base = tables()
    monkeypatch.setenv("GRAFT_PACK_GATHER", "1")
    jax.clear_caches()
    packed_t = tables()
    monkeypatch.undo()
    jax.clear_caches()
    for h in base:
        for f in fields:
            assert np.array_equal(np.asarray(getattr(base[h], f)),
                                  np.asarray(getattr(packed_t[h], f))), \
                (h, f)
