"""TPU engine (array-backed replica) conformance: the same scenarios the
oracle suite pins, driven through ``TpuTree``, plus engine-vs-oracle
equivalence on randomized sessions and checkpoint/restore."""
import random

import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import Add, Batch, Delete, engine
from crdt_graph_tpu.core import operation as op_mod

OFFSET = 2**32


def test_local_editing_parity_with_oracle():
    t = engine.init(0)
    t.add("a").add("b").add_after([1], "z")
    o = crdt.init(0).add("a").add("b").add_after([1], "z")
    assert t.visible_values() == o.visible_values() == ["a", "z", "b"]
    assert t.timestamp == o.timestamp
    assert t.cursor == o.cursor
    assert op_mod.to_list(t.operations_since(0)) == \
        op_mod.to_list(o.operations_since(0))


def test_add_branch_and_cursor():
    t = engine.init(0).add_branch("a").add_branch("b")
    assert t.cursor == (1, 2, 0)
    t.add("c")
    assert t.cursor == (1, 2, 3)
    assert t.get_value([1, 2, 3]) == "c"
    t.move_cursor_up()
    assert t.cursor == (1, 2)


def test_remote_apply_keeps_cursor_and_clock():
    t = engine.init(2)
    t.add("x")
    cur, ts = t.cursor, t.timestamp
    t.apply(Add(5 * OFFSET + 1, (0,), "r"))
    assert t.cursor == cur and t.timestamp == ts
    assert t.last_replica_timestamp(5) == 5 * OFFSET + 1


def test_idempotent_redelivery():
    t = engine.init(1)
    t.add("a").add("b")
    delta = t.operations_since(0)
    peer = engine.init(2)
    peer.apply(delta).apply(delta).apply(delta)
    assert peer.visible_values() == ["a", "b"]
    assert len(op_mod.to_list(peer.operations_since(0))) == 2


def test_batch_atomicity_rolls_back():
    t = engine.init(0)
    t.add("a")
    with pytest.raises(crdt.OperationFailedError):
        t.apply(Batch((Add(7, (1,), "ok"), Add(8, (99,), "bad"))))
    assert t.visible_values() == ["a"]
    assert len(op_mod.to_list(t.operations_since(0))) == 1


def test_delete_cursor_to_predecessor():
    t = engine.init(0).add("a").add("b").add("c")
    o = crdt.init(0).add("a").add("b").add("c")
    t.delete([2])
    o = o.delete([2])
    assert t.cursor == o.cursor == (1,)
    assert t.visible_values() == ["a", "c"]
    # with b tombstoned, c's predecessor is the nearest VISIBLE node "a"
    # (the reference probe skips tombstone runs, CRDTree.elm:199-216)
    t.delete([3])
    o = o.delete([3])
    assert t.cursor == o.cursor == (1,)


def test_double_delete_cursor_matches_oracle():
    t = engine.init(0).add("a").add("b").add("c")
    o = crdt.init(0).add("a").add("b").add("c")
    t.delete([2])
    o = o.delete([2])
    t.delete([2])   # absorbed: target already a tombstone
    o = o.delete([2])
    assert t.cursor == o.cursor
    assert t.visible_values() == o.visible_values()


def test_delete_under_dead_branch_cursor_matches_oracle():
    ops = Batch((Add(1, (0,), "a"), Add(2, (1, 0), "b"), Add(3, (1,), "c"),
                 Delete((1,))))
    t = engine.init(0)
    t.apply(ops)
    o = crdt.init(0).apply(ops)
    t.delete([1, 2])   # child of deleted branch: absorbed
    o = o.delete([1, 2])
    assert t.cursor == o.cursor
    assert t.visible_values() == o.visible_values()


def test_batch_rollback_restores_last_operation():
    t = engine.init(0).add("a")
    before = t.last_operation

    def boom(tree):
        raise RuntimeError("nope")

    with pytest.raises(RuntimeError):
        t.batch([lambda x: x.add("b"), boom])
    assert t.last_operation == before
    assert t.visible_values() == ["a"]


def test_first_failing_op_decides_batch_error():
    # invalid-path op precedes a not-found op: the first one wins, like the
    # oracle's sequential stop
    t = engine.init(0).add("a")
    with pytest.raises(crdt.InvalidPathError):
        t.apply(Batch((Add(7, (5, 6), "x"), Add(8, (99,), "y"))))
    o = crdt.init(0).add("a")
    with pytest.raises(crdt.InvalidPathError):
        o.apply(Batch((Add(7, (5, 6), "x"), Add(8, (99,), "y"))))


def test_operations_since_parity():
    t = engine.init(0)
    t.apply(Batch((Add(1, (0,), "a"), Add(2, (1,), "b"), Add(3, (2,), "c"),
                   Delete((2,)))))
    assert op_mod.to_list(t.operations_since(2)) == \
        [Add(2, (1,), "b"), Add(3, (2,), "c"), Delete((2,))]
    assert op_mod.to_list(t.operations_since(99)) == []


def test_absorbed_ops_stay_out_of_log():
    batch = Batch((Add(1, (0,), "a"), Delete((1,)), Add(2, (1, 0), "b")))
    t = engine.init(0)
    t.apply(batch)
    o = crdt.init(0).apply(batch)
    assert op_mod.to_list(t.operations_since(0)) == \
        [Add(1, (0,), "a"), Delete((1,))]
    assert t.visible_values() == o.visible_values() == []
    # quirk preserved: the clock advanced for BOTH own-replica adds, the
    # absorbed one included (reference Ok-no-op path)
    assert t.timestamp == o.timestamp == 2
    # the view after absorption must still resolve values correctly
    t.add("c")
    o2 = o.add("c")
    assert t.visible_values() == o2.visible_values() == ["c"]


def test_random_session_engine_equals_oracle():
    rng = random.Random(42)
    eng, orc = engine.init(3), crdt.init(3)
    for step in range(60):
        roll = rng.random()
        if roll < 0.55:
            v = rng.randrange(1000)
            eng.add(v)
            orc = orc.add(v)
        elif roll < 0.7:
            v = rng.randrange(1000)
            eng.add_branch(v)
            orc = orc.add_branch(v)
        elif roll < 0.85 and len(orc.visible_values()) > 0:
            paths = []
            orc.walk(lambda n, acc: (crdt.TAKE, acc.append(n.path) or acc),
                     paths)
            p = rng.choice(paths)
            eng.delete(p)
            orc = orc.delete(p)
        else:
            # remote traffic interleaved
            ts = 9 * OFFSET + step + 1
            op = Add(ts, (0,), f"r{step}")
            eng.apply(op)
            orc = orc.apply(op)
        assert eng.cursor == orc.cursor
    assert eng.visible_values() == orc.visible_values()
    assert eng.timestamp == orc.timestamp
    assert op_mod.to_list(eng.operations_since(0)) == \
        op_mod.to_list(orc.operations_since(0))


def test_to_oracle_round_trip():
    t = engine.init(1).add("a").add_branch("b")
    t.add("c")
    o = t.to_oracle()
    assert o.visible_values() == t.visible_values()
    assert o.cursor == t.cursor
    assert o.timestamp == t.timestamp


def test_checkpoint_restore(tmp_path):
    t = engine.init(7)
    t.add("a").add("b").delete([7 * OFFSET + 1])
    f = str(tmp_path / "ckpt.json")
    t.checkpoint(f)
    back = engine.restore(f)
    assert back.visible_values() == t.visible_values() == ["b"]
    assert back.timestamp == t.timestamp
    assert back.cursor == t.cursor
    assert back.last_replica_timestamp(7) == t.last_replica_timestamp(7)
    # restored replica keeps editing exactly like the oracle would: the
    # cursor sits on the deleted node's tombstone, so the new node lands
    # before "b" (higher ts closer to that anchor)
    back.add("c")
    o = crdt.init(7).add("a").add("b").delete([7 * OFFSET + 1]).add("c")
    assert back.visible_values() == o.visible_values() == ["c", "b"]


def test_batch_with_non_editing_func_matches_oracle():
    # a cursor-only func must not leak the pre-batch last_operation into
    # the accumulated batch (oracle resets the accumulator first)
    t = engine.init(0)
    t.add("a")
    t.batch([lambda x: x.move_cursor_up()])
    o = crdt.init(0).add("a").batch([lambda x: x.move_cursor_up()])
    assert t.last_operation == o.last_operation == Batch(())


def test_set_cursor_rejects_dead_nodes_like_oracle():
    ops = Batch((Add(1, (0,), "a"), Add(2, (1, 0), "b"), Delete((1,))))
    t = engine.init(9)
    t.apply(ops)
    o = crdt.init(9).apply(ops)
    # tombstoned node itself remains addressable (reference get finds it)
    t.set_cursor((1,))
    o.set_cursor((1,))
    # its discarded descendant is not
    with pytest.raises(crdt.NotFound):
        t.set_cursor((1, 2))
    with pytest.raises(crdt.NotFound):
        o.set_cursor((1, 2))


def test_hint_provenance_gates_exhaustive_mode():
    """pack/concat/parse_pack vouch for link-hint completeness (the
    engine may then use the cond-free exhaustive kernel mode); a
    PackedOps whose hint columns were DEFAULTED — an old checkpoint —
    must stay on the verified auto path, where the join resolves what
    the missing hints cannot (engine._mode)."""
    from crdt_graph_tpu.codec import packed as packed_mod
    from crdt_graph_tpu.engine import _mode

    ops = [Add(1, (0,), "a"), Add(2, (1,), "b"), Delete((1,))]
    p = packed_mod.pack(ops)
    assert p.hints_vouched and _mode(p) == "exhaustive"
    u = packed_mod.concat(p, packed_mod.pack([Add(3, (2,), "c")]))
    assert u.hints_vouched and _mode(u) == "exhaustive"
    # strip provenance the way an old npz restore does: defaulted columns
    bare = packed_mod.PackedOps(
        kind=p.kind, ts=p.ts, parent_ts=p.parent_ts, anchor_ts=p.anchor_ts,
        depth=p.depth, paths=p.paths, value_ref=p.value_ref, pos=p.pos,
        values=list(p.values), num_ops=p.num_ops)
    assert not bare.hints_vouched and _mode(bare) is None
    # and the auto path still merges it correctly via the join
    from crdt_graph_tpu.ops import merge as merge_mod
    from crdt_graph_tpu.ops import view as view_mod
    t = view_mod.to_host(merge_mod.materialize(bare.arrays()))
    assert view_mod.visible_values(t, bare.values) == ["b"]


def test_checkpoint_roundtrip_preserves_hint_provenance(tmp_path):
    t = engine.init(4)
    t.add("a")
    t.add("b")
    path = str(tmp_path / "ck.npz")
    t.checkpoint_packed(path)
    r = engine.TpuTree.restore_packed(path)
    assert r._packed.hints_vouched
    assert r.visible_values() == t.visible_values()


def test_dumps_since_matches_python_encode():
    """The native egress fast path must emit byte-identical wire JSON to
    json_codec.dumps(operations_since(ts)) for ts=0 (bootstrap), a
    mid-log Add timestamp (inclusive suffix), and a timestamp matching
    nothing (empty batch)."""
    from crdt_graph_tpu.codec import json_codec
    t = engine.init(3)
    for i in range(20):
        t.add(f"v{i}")
    t.delete((t.last_replica_timestamp(3),))
    mid = 3 * 2**32 + 7
    for ts in (0, mid, 999):
        want = json_codec.dumps(t.operations_since(ts))
        assert t.dumps_since(ts) == want, ts


def test_apply_packed_matches_apply_on_random_sessions(monkeypatch):
    """apply_packed (the column ingest path) must leave the replica in a
    state indistinguishable from apply() on the same ops — across random
    multi-replica sessions with deletes, duplicate redelivery, and
    nesting.  The bulk-kernel crossover is forced to 0 so the column
    path actually runs at test sizes."""
    from test_merge_kernel import _random_session
    from crdt_graph_tpu.codec import json_codec, packed

    for seed in (11, 12, 13):
        _, ops = _random_session(seed, n_replicas=4, steps=250)
        ops = ops + ops[:40]          # duplicate redelivery
        batch = crdt.Batch(tuple(ops))

        a = engine.init(9)
        a.apply(batch)

        b = engine.init(9)
        p = packed.pack(ops)
        monkeypatch.setattr(engine, "DELTA_THRESHOLD", 0)
        b.apply_packed(p)
        monkeypatch.undo()

        assert a.visible_values() == b.visible_values(), seed
        assert a.log_length == b.log_length, seed
        assert a.timestamp == b.timestamp, seed
        assert a._replicas == b._replicas, seed
        assert a.last_operation == b.last_operation, seed
        # and the wire entry point composes the same way
        c = engine.init(9)
        monkeypatch.setattr(engine, "DELTA_THRESHOLD", 0)
        c.apply_wire(json_codec.dumps(batch))
        monkeypatch.undo()
        assert c.visible_values() == a.visible_values(), seed
        assert c.log_length == a.log_length, seed


def test_sentinel_delete_is_noop_and_cursor_stays():
    """Deleting at a branch-head sentinel path (cursor inside an empty
    branch) absorbs as AlreadyApplied — children dicts are seeded with
    ``0 -> Tombstone`` (Internal/Node.elm:48), deleteHelp answers
    AlreadyApplied for tombstones (Internal/Node.elm:112-122) and
    updateTree maps that to a no-op with ``lastOperation = Batch []``
    (CRDTree.elm:318-319); no chain member's next-sibling is the chain
    head, so pathPrevious defaults to the target path and the cursor
    stays inside the branch (CRDTree.elm:199-216).  Regression: the
    engine routed the sentinel through the missing-target fallback and
    parked the cursor at the last visible ROOT sibling, sending every
    subsequent local edit to the wrong subtree."""
    t = engine.init(9).add("v").add_branch("b")
    o = crdt.init(9).add("v").add_branch("b")
    assert t.cursor == o.cursor and t.cursor[-1] == 0
    sentinel = list(t.cursor)
    t.delete(sentinel)
    o = o.delete(sentinel)
    assert t.cursor == o.cursor == tuple(sentinel)
    assert t.last_operation == o.last_operation == Batch(())
    assert t.visible_values() == o.visible_values()
    # edits continue INSIDE the branch on both sides
    t.add("inside")
    o = o.add("inside")
    assert t.cursor == o.cursor
    assert t.visible_values() == o.visible_values()


def test_sentinel_delete_missing_branch_fails():
    """Sentinel path under a branch that does not exist: the DESCENT
    fails at the missing intermediate, so the reference answers
    InvalidPath (Internal/Node.elm:156-159, CRDTree.elm:321-322); tree
    and cursor unchanged."""
    t = engine.init(9).add("v")
    o = crdt.init(9).add("v")
    cur = t.cursor
    with pytest.raises(crdt.InvalidPathError):
        t.delete([99 * 2 ** 32 + 1, 0])
    with pytest.raises(crdt.InvalidPathError):
        o.delete([99 * 2 ** 32 + 1, 0])
    assert t.cursor == cur == o.cursor
    assert t.visible_values() == o.visible_values() == ["v"]


def test_random_session_engine_oracle_lockstep():
    """300-step random local session (adds, branches, deletes at the
    cursor) driven through BOTH the oracle and the engine: visible
    values, cursor, and delete outcomes must stay in lockstep — the
    probe that exposed the sentinel-delete cursor bug."""
    rng = random.Random(4242)
    o = crdt.init(9)
    t = engine.init(9)
    for i in range(300):
        r = rng.random()
        if r < 0.6:
            o = o.add(f"v{i}")
            t.add(f"v{i}")
        elif r < 0.75 and len(o.cursor) < 12:
            # stay inside the engine's static max_depth=16 path planes
            o = o.add_branch(f"b{i}")
            t.add_branch(f"b{i}")
        elif o.visible_values():
            p = list(o.cursor)
            o_ok = e_ok = "ok"
            try:
                o = o.delete(p)
            except (crdt.OperationFailedError, crdt.InvalidPathError) as ex:
                o_ok = type(ex).__name__
            try:
                t.delete(p)
            except (crdt.OperationFailedError, crdt.InvalidPathError) as ex:
                e_ok = type(ex).__name__
            assert o_ok == e_ok, (i, p)
        assert tuple(o.cursor) == tuple(t.cursor), i
        assert o.visible_values() == t.visible_values(), i


def test_sentinel_get_and_set_cursor_match_oracle():
    """Every children dict is seeded with the branch-head sentinel
    (``0 -> Tombstone``, Internal/Node.elm:46-48), ``get`` resolves it
    (descendant/child, Internal/Node.elm:284-299) and ``setCursor``
    validates with ``get`` (CRDTree.elm:551-558) — so trailing-0 paths
    under live nodes are real, addressable targets: value None, deleted,
    timestamp 0, the SHARED empty path, parent = root, no siblings.
    Under a tombstoned/dead/missing prefix the sentinel left the tree
    with its branch.  Regression: the engine answered None/NotFound for
    every sentinel path."""
    OFF = 9 * 2 ** 32
    o = crdt.init(9).add("a").add("b")
    t = engine.init(9).add("a").add("b")

    so, st = o.get([OFF + 1, 0]), t.get([OFF + 1, 0])
    assert so is not None and st is not None
    assert (so.get_value(), so.is_deleted(), so.timestamp, tuple(so.path)) \
        == (st.value, st.is_deleted, st.timestamp, tuple(st.path)) \
        == (None, True, 0, ())
    assert o.parent(so) is o.root and t.parent(st).is_root
    assert o.next(so) is None is t.next(st)
    assert o.prev(so) is None is t.prev(st)
    assert st.children() == []

    # root's own sentinel
    assert o.get([0]) is not None and t.get([0]) is not None
    o = o.set_cursor([0])
    t.set_cursor([0])
    assert tuple(o.cursor) == tuple(t.cursor) == (0,)

    # valid target under a live node
    o = o.set_cursor([OFF + 1, 0])
    t.set_cursor([OFF + 1, 0])
    assert tuple(o.cursor) == tuple(t.cursor) == (OFF + 1, 0)

    # gone with its branch: tombstoned prefix, missing prefix, sentinel
    # prefix
    o2 = o.delete([OFF + 1])
    t.delete([OFF + 1])
    for bad in ([OFF + 1, 0], [999, 0], [0, 0]):
        assert o2.get(bad) is None and t.get(bad) is None
        with pytest.raises(crdt.NotFound):
            o2.set_cursor(bad)
        with pytest.raises(crdt.NotFound):
            t.set_cursor(bad)


@pytest.mark.parametrize("seed", [0, 7, 13])
def test_wide_op_mix_lockstep(seed):
    """Randomized lockstep over the FULL local-edit surface — add,
    add_branch, add_after at historical paths, move_cursor_up,
    set_cursor at historical paths (sentinels included), delete,
    interleaved remote applies — asserting cursor, visible values,
    clock, and outcome (success vs error TYPE) at every step, and log
    equality at the end.  The narrower mixes in this file each found a
    real divergence (sentinel delete, sentinel set_cursor); this pins
    the widened surface.  20 seeds were clean at authoring; three run
    in CI for time."""
    rng = random.Random(seed)
    o = crdt.init(7)
    e = engine.init(7)
    paths = [[0]]
    rts = 0

    def outcome(f):
        try:
            return "ok", f()
        except (crdt.OperationFailedError, crdt.InvalidPathError,
                crdt.NotFound) as ex:
            return type(ex).__name__, None

    for i in range(250):
        r = rng.random()
        if r < 0.4:
            o = o.add(f"v{i}")
            e.add(f"v{i}")
            paths.append(list(o.cursor))
        elif r < 0.5 and len(o.cursor) < 11:
            o = o.add_branch(f"b{i}")
            e.add_branch(f"b{i}")
            paths.append(list(o.cursor))
        elif r < 0.6:
            p = rng.choice(paths)
            ro, o2 = outcome(lambda: o.add_after(p, f"aa{i}"))
            re2, _ = outcome(lambda: e.add_after(p, f"aa{i}"))
            assert ro == re2, (seed, i, p, ro, re2)
            if o2 is not None:
                o = o2
                paths.append(list(o.cursor))
        elif r < 0.68:
            o = o.move_cursor_up()
            e.move_cursor_up()
        elif r < 0.78:
            p = rng.choice(paths)
            ro, o2 = outcome(lambda: o.set_cursor(p))
            re2, _ = outcome(lambda: e.set_cursor(p))
            assert ro == re2, (seed, i, p, ro, re2)
            if o2 is not None:
                o = o2
        elif r < 0.88:
            # remote replica 99 appends a chain at the root: first op
            # anchors at the head sentinel, later ops after the previous
            # remote node — every apply SUCCEEDS, pinning cursor
            # stability and clock bookkeeping under interleaved remote
            # traffic (path's last element is the ANCHOR timestamp)
            rts += 1
            anchor = 0 if rts == 1 else 99 * 2 ** 32 + rts - 1
            op = Add(99 * 2 ** 32 + rts, (anchor,), f"r{rts}")
            ro, o2 = outcome(lambda: o.apply(op))
            re2, _ = outcome(lambda: e.apply(op))
            assert ro == re2 == "ok", (seed, i, ro, re2)
            o = o2
        elif o.visible_values():
            p = rng.choice(paths)
            ro, o2 = outcome(lambda: o.delete(p))
            re2, _ = outcome(lambda: e.delete(p))
            assert ro == re2, (seed, i, p, ro, re2)
            if o2 is not None:
                o = o2
        assert tuple(o.cursor) == tuple(e.cursor), (seed, i)
        assert o.visible_values() == e.visible_values(), (seed, i)
        assert o.timestamp == e.timestamp, (seed, i)
    assert op_mod.to_list(o.operations_since(0)) == \
        op_mod.to_list(e.operations_since(0)), seed


def test_corrupt_checkpoint_detected_or_harmless():
    """Snapshot-bootstrap robustness: any truncation, bit flip, or
    garbage splice of a packed checkpoint either raises the one typed
    ``CheckpointError`` (no zipfile/zlib internals leak to callers) or —
    when the flip lands in zip padding the per-member CRCs don't cover —
    decodes to a tree EQUAL to the original.  Valid snapshots restore;
    a missing file stays FileNotFoundError (caller mistake, not
    corruption)."""
    import io
    import random

    t = engine.init(3)
    for i in range(40):
        t.add(f"v{i}")
    buf = io.BytesIO()
    t.checkpoint_packed(buf)
    data = buf.getvalue()
    expected = t.visible_values()

    rng = random.Random(7)
    detected = harmless = 0
    for trial in range(120):
        b = bytearray(data)
        mode = trial % 3
        if mode == 0:
            b = b[:rng.randrange(1, len(b))]
        elif mode == 1:
            j = rng.randrange(len(b))
            b[j] ^= 1 << rng.randrange(8)
        else:
            j = rng.randrange(len(b))
            b[j:j + 8] = bytes(rng.randrange(256) for _ in range(
                min(8, len(b) - j)))
        try:
            t2 = engine.TpuTree.restore_packed(io.BytesIO(bytes(b)),
                                               replica=9)
            assert t2.visible_values() == expected, trial
            harmless += 1
        except crdt.CheckpointError:
            detected += 1
    assert detected + harmless == 120 and detected > 0

    ok = engine.TpuTree.restore_packed(io.BytesIO(data), replica=9)
    assert ok.visible_values() == expected
    with pytest.raises(FileNotFoundError):
        engine.TpuTree.restore_packed("/nonexistent/ckpt.npz")

    # CRC-valid but hand-edited: meta fields holding the wrong JSON types
    # must also resolve to CheckpointError, not leak TypeError
    import json as json_mod
    import zipfile

    import numpy as np
    src = zipfile.ZipFile(io.BytesIO(data))
    meta = json_mod.loads(bytes(np.load(io.BytesIO(src.read("meta.npy")))
                                .tobytes()).decode())
    meta["cursor"] = 5
    out = io.BytesIO()
    with zipfile.ZipFile(out, "w") as zf:
        for name in src.namelist():
            if name == "meta.npy":
                b = io.BytesIO()
                np.save(b, np.frombuffer(
                    json_mod.dumps(meta).encode(), dtype=np.uint8))
                zf.writestr(name, b.getvalue())
            else:
                zf.writestr(name, src.read(name))
    with pytest.raises(crdt.CheckpointError):
        engine.TpuTree.restore_packed(io.BytesIO(out.getvalue()),
                                      replica=9)
