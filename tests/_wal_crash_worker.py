"""Crash-point worker for tests/test_wal.py (ISSUE 9).

One run = one kill site: serve a durable document over real HTTP,
record every acked write to an append-only ack log (line-buffered — a
killed process's written bytes survive in the page cache exactly like
the WAL's), then arm ``GRAFT_CRASH_POINT=<site>`` + ``GRAFT_CRASH_EXIT``
and keep writing until the process dies hard (``os._exit(137)``) at the
armed durability boundary.  The parent asserts the 137, recovers a
fresh engine from the same durable dir, and checks ZERO acked-write
loss plus window byte-identity — the oracle contract of the crash
matrix.

Traffic is shaped so every site is reachable within one armed commit:
tiny hot budget (spills every couple of commits), fold-every-spill GC,
and a wide post-arm batch that forces spill + fold + manifest in the
same commit the WAL barrier fsyncs.

Usage: python tests/_wal_crash_worker.py SITE DURABLE_DIR ACK_LOG \
           [shared]
"""
import json
import os
import sys
import threading

SITE, DDIR, ACK_LOG = sys.argv[1], sys.argv[2], sys.argv[3]
SHARED = len(sys.argv) > 4 and sys.argv[4] == "shared"

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "true"
os.environ["GRAFT_OPLOG_HOT_OPS"] = "8"
os.environ["GRAFT_OPLOG_GC_SEGS"] = "1"
# tiny materialization cadence: the armed wide commit must cross the
# matz refresh too, so the mid-matz-write site fires within one commit
os.environ["GRAFT_MATZ_TAIL_OPS"] = "8"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from http.client import HTTPConnection  # noqa: E402

from crdt_graph_tpu.codec import json_codec  # noqa: E402
from crdt_graph_tpu.core.operation import Add, Batch  # noqa: E402
from crdt_graph_tpu.obs import flight as flight_mod  # noqa: E402
from crdt_graph_tpu.serve import ServingEngine  # noqa: E402
from crdt_graph_tpu.service import make_server  # noqa: E402

OFF = 2**32
PRELUDE_ACKS = 4          # committed-and-durable history before arming


def main() -> None:
    engine = ServingEngine(durable_dir=DDIR, wal_sync="batch",
                           wal_shared=SHARED,
                           flight=flight_mod.FlightRecorder(),
                           submit_timeout_s=10.0)
    srv = make_server(port=0, store=engine)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_port

    ack_f = open(ACK_LOG, "a")
    counter = 0
    prev = 0

    def chain(n):
        nonlocal counter, prev
        ops = []
        for _ in range(n):
            counter += 1
            ts = 1 * OFF + counter
            ops.append(Add(ts, (prev,), f"v{counter}"))
            prev = ts
        return ops

    conn = HTTPConnection("127.0.0.1", port, timeout=15)
    acked = 0
    for i in range(60):
        width = 20 if acked >= PRELUDE_ACKS else 5
        ops = chain(width)
        try:
            conn.request("POST", "/docs/crash/ops",
                         body=json_codec.dumps(Batch(tuple(ops))))
            resp = conn.getresponse()
            out = json.loads(resp.read())
        except Exception:
            # the armed site killed the server mid-request in some
            # OTHER thread's timing — only reachable if os._exit lost
            # a race to this read; nothing was acked
            break
        if resp.status != 200 or not out.get("accepted"):
            break
        for op in ops:
            ack_f.write(op.value + "\n")
        ack_f.flush()
        acked += 1
        if acked == PRELUDE_ACKS:
            # everything above is acked AND fsynced; the next wide
            # commit must die at the armed site
            os.environ["GRAFT_CRASH_POINT"] = SITE
            os.environ["GRAFT_CRASH_EXIT"] = "1"
    print("NOCRASH", flush=True)   # the site never fired: test fails
    os._exit(7)


if __name__ == "__main__":
    main()
