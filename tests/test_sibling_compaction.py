"""The crowded-compaction sibling sort (ops/merge.py step 9) only
engages above S_CAP = 65536 slots — the regular suites run far below it,
so these cases cross the threshold on each cond branch:

- chain workload: 64 crowded rows among 70k (small-sort branch);
- tombstone-heavy at 76k ops: 40k crowded root children + deletes
  (small-sort branch with a contested parent and dead masking);
- descending rounds: every op is a root child (full-sort fallback).

Each pins the full visible sequence against its closed form / mirror.
"""
import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.bench import workloads
from crdt_graph_tpu.core.operation import Add
from crdt_graph_tpu.host_tree import HostTree
from crdt_graph_tpu.ops import merge, view

N = 70_000


def _visible_ts(arrs):
    t = view.to_host(merge.materialize(arrs))
    nv = int(t.num_visible)
    return np.asarray(t.ts)[np.asarray(t.visible_order)[:nv]]


def test_chain_small_branch_above_cap():
    got = _visible_ts(workloads.chain_workload(64, N))
    want = workloads.chain_expected_ts(64, N)
    assert got.shape == want.shape and np.array_equal(got, want)


def test_descending_full_branch_above_cap():
    got = _visible_ts(workloads.descending_chains(64, N))
    want = workloads.descending_expected_ts(64, N)
    assert got.shape == want.shape and np.array_equal(got, want)


def test_tombstone_heavy_crowded_small_branch():
    ops = workloads.tombstone_heavy(n_adds=40_000)   # + 36k deletes = 76k
    from crdt_graph_tpu.codec import packed
    p = packed.pack(ops)
    assert p.capacity > 1 << 16                      # crosses S_CAP
    got = _visible_ts(p.arrays())
    m = HostTree(16)
    for op in ops:
        if isinstance(op, Add):
            m.apply_add(op.ts, tuple(op.path), op.value)
        else:
            m.apply_delete(tuple(op.path))
    want = np.array([int(m.ts[s]) for s in m.iter_visible()], dtype=np.int64)
    assert got.shape == want.shape and np.array_equal(got, want)
