"""The crowded-compaction sibling sort (ops/merge.py step 9) only
engages above S_CAP = 65536 slots — the regular suites run far below it,
so these cases cross the threshold on each cond branch:

- chain workload: 64 crowded rows among 70k (small-sort branch);
- tombstone-heavy at 76k ops: 40k crowded root children + deletes
  (small-sort branch with a contested parent and dead masking);
- descending rounds: every op is a root child (full-sort fallback).

Each pins the full visible sequence against its closed form / mirror.
"""
import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.bench import workloads
from crdt_graph_tpu.core.operation import Add
from crdt_graph_tpu.host_tree import HostTree
from crdt_graph_tpu.ops import merge, view

N = 70_000


def _visible_ts(arrs):
    t = view.to_host(merge.materialize(arrs))
    nv = int(t.num_visible)
    return np.asarray(t.ts)[np.asarray(t.visible_order)[:nv]]


def test_chain_small_branch_above_cap():
    got = _visible_ts(workloads.chain_workload(64, N))
    want = workloads.chain_expected_ts(64, N)
    assert got.shape == want.shape and np.array_equal(got, want)


def test_descending_full_branch_above_cap():
    got = _visible_ts(workloads.descending_chains(64, N))
    want = workloads.descending_expected_ts(64, N)
    assert got.shape == want.shape and np.array_equal(got, want)


def test_tombstone_heavy_crowded_small_branch():
    ops = workloads.tombstone_heavy(n_adds=40_000)   # + 36k deletes = 76k
    from crdt_graph_tpu.codec import packed
    p = packed.pack(ops)
    assert p.capacity > 1 << 16                      # crosses S_CAP
    got = _visible_ts(p.arrays())
    m = HostTree(16)
    for op in ops:
        if isinstance(op, Add):
            m.apply_add(op.ts, tuple(op.path), op.value)
        else:
            m.apply_delete(tuple(op.path))
    want = np.array([int(m.ts[s]) for s in m.iter_visible()], dtype=np.int64)
    assert got.shape == want.shape and np.array_equal(got, want)


def test_single_group_shortcut_matches_sort(monkeypatch):
    """The sort-free single-group branch (merge.py ``br_single``: all
    crowded rows share one (parent, group) key, so sorted order is
    analytically slot-descending) must be bit-identical to the full
    sort it replaces — compared by forcing ``GRAFT_S_CAP >= M`` (the
    branch-free ``_sib_links`` path) on the same batches.  Covers the
    taking cases (flat concurrent sibling storm; sibling storm with
    deletes) and refusing near-misses (two crowded parents, in one case
    split across branch-children and root-level siblings), all pinned
    against the host mirror."""
    from crdt_graph_tpu.codec import packed as packed_mod
    from crdt_graph_tpu.core.operation import Batch, Delete

    def mirror_ts(raw):
        m = HostTree(16)
        for op in raw:
            if isinstance(op, Add):
                m.apply_add(op.ts, tuple(op.path), op.value)
            else:
                m.apply_delete(tuple(op.path))
        return np.array([int(m.ts[s]) for s in m.iter_visible()],
                        dtype=np.int64)

    R = 2 ** 32
    cases = {}
    # 1: flat sibling storm — every op a root child, interleaved replicas
    storm = [Add((r + 1) * R + k, (0,), f"v{r}.{k}")
             for k in range(300) for r in range(4)]
    cases["storm"] = storm
    # 2: storm with deletes sprinkled in
    dels = [Delete((2 * R + k,)) for k in range(0, 300, 7)]
    cases["storm+deletes"] = storm + dels
    # 3: near-miss — two crowded parents
    two = [Add(1 * R + 1, (0,), "p1"), Add(1 * R + 2, (0,), "p2")]
    two += [Add(2 * R + k, (1 * R + 1, 0), f"a{k}") for k in range(3, 40)]
    two += [Add(3 * R + k, (1 * R + 2, 0), f"b{k}") for k in range(3, 40)]
    cases["two-parents"] = two
    # 4: near-miss — crowding split across the host's branch children
    # and root-level siblings anchored at the host
    mixed = [Add(1 * R + 1, (0,), "host")]
    mixed += [Add(2 * R + k, (1 * R + 1, 0), f"c{k}") for k in range(2, 30)]
    mixed += [Add(3 * R + k, (1 * R + 1,), f"s{k}") for k in range(2, 30)]
    cases["mixed-groups"] = mixed

    for name, raw in cases.items():
        arrs = packed_mod.pack(Batch(tuple(raw))).arrays()
        want = mirror_ts(raw)
        # the batches pack to M << the default S_CAP, where the Python-
        # level ``if S_CAP >= M`` short-circuits to the plain sort and
        # the cond machinery never traces — force the compaction branch
        # (S_CAP below M) so br_single/one_group actually execute
        monkeypatch.setenv("GRAFT_S_CAP", "4")
        jax.clear_caches()
        got = _visible_ts(arrs)
        assert np.array_equal(got, want), name
        # force the sort-only construction and compare bit-for-bit
        monkeypatch.setenv("GRAFT_S_CAP", str(10 ** 9))
        jax.clear_caches()
        got_sort = _visible_ts(arrs)
        monkeypatch.delenv("GRAFT_S_CAP")
        jax.clear_caches()
        assert np.array_equal(got, got_sort), name
