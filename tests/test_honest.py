"""The honest-timing harness (bench/honest.py) is what makes every perf
number in this repo trustworthy — pin its pieces."""
import numpy as np

import jax
import jax.numpy as jnp

from crdt_graph_tpu.bench import honest


def test_fingerprint_depends_on_every_leaf():
    a = jnp.arange(100, dtype=jnp.int32)
    b = jnp.ones(7, dtype=jnp.int32)
    base = int(np.asarray(honest.fingerprint((a, b))))
    assert int(np.asarray(honest.fingerprint((a, b)))) == base
    bumped = int(np.asarray(honest.fingerprint((a.at[3].add(1), b))))
    assert bumped != base
    bumped2 = int(np.asarray(honest.fingerprint((a, b.at[0].add(1)))))
    assert bumped2 != base


def test_fingerprint_handles_bool_and_float():
    t = (jnp.array([True, False]), jnp.array([1.5, 2.5]),
         jnp.arange(3, dtype=jnp.int64))
    v = int(np.asarray(honest.fingerprint(t)))
    assert isinstance(v, int)


def test_force_returns_host_values():
    out = honest.force({"x": jnp.arange(4), "y": (jnp.ones(2),)})
    assert isinstance(out["x"], np.ndarray)
    assert isinstance(out["y"][0], np.ndarray)


def test_time_with_readback_reports_and_returns_result():
    fn = jax.jit(lambda x: jnp.sum(x) * 2)
    x = jnp.arange(10, dtype=jnp.int32)
    stats = honest.time_with_readback(fn, x, repeats=3)
    assert len(stats["times_s"]) == 3
    assert stats["p50_ms"] >= 0
    assert int(stats["last_result"]) == 90


def test_audit_passes_for_honest_backend():
    fn = jax.jit(lambda x: jnp.sum(x * x))
    x = jnp.arange(1000, dtype=jnp.int32)
    audit = honest.audit_async_gap(fn, x, expected_s=0.01)
    assert audit["ok"] is True
    assert audit["readback_after_sleep_ms"] < 250


def test_overhead_floor_small_on_cpu():
    floor = honest.overhead_floor_ms()
    assert 0 <= floor < 250
