"""The pallas bounded-span monotone gather must equal the lax reference
(ops/mono_gather.py).  Runs the Mosaic kernel in interpreter mode on CPU;
the real-TPU path is exercised by the bench."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crdt_graph_tpu.ops import mono_gather


def _case(rng, t, r, v=5):
    # nondecreasing rid with increments in {0, 1}, like a run-id cumsum
    inc = rng.integers(0, 2, t).astype(np.int32)
    inc[0] = 0
    rid = np.cumsum(inc).astype(np.int32)
    r_eff = max(r, int(rid[-1]) + 1)
    values = rng.integers(0, min(2**23, 10 * r_eff), (v, r_eff),
                          dtype=np.int32)
    return jnp.asarray(values), jnp.asarray(rid)


@pytest.mark.parametrize("t", [7, 512, 513, 2048, 5000])
def test_interpret_matches_lax(t):
    rng = np.random.default_rng(t)
    values, rid = _case(rng, t, 64)
    want = np.asarray(mono_gather._lax_gather(values, rid))
    got = np.asarray(mono_gather.monotone_gather(values, rid,
                                                 interpret=True))
    np.testing.assert_array_equal(got, want)


def test_constant_rid():
    values = jnp.arange(40, dtype=jnp.int32).reshape(5, 8)
    rid = jnp.zeros(700, jnp.int32)
    got = np.asarray(mono_gather.monotone_gather(values, rid,
                                                 interpret=True))
    want = np.asarray(values[:, rid])
    np.testing.assert_array_equal(got, want)


def test_full_merge_with_pallas_rank_interpret(monkeypatch):
    """The whole merge kernel with the pallas rank path (interpreted)
    must match the default lax path on a real log."""
    monkeypatch.setenv("GRAFT_PALLAS_INTERPRET", "1")
    from crdt_graph_tpu.codec import packed
    from crdt_graph_tpu.ops import merge, view
    from test_merge_kernel import _random_session

    _, ops = _random_session(77, n_replicas=3, steps=70)
    p = packed.pack(ops)
    t_lax = view.to_host(merge.materialize(p.arrays()))
    t_pal = view.to_host(merge.materialize(p.arrays(), use_pallas=True))
    np.testing.assert_array_equal(np.asarray(t_pal.doc_index),
                                  np.asarray(t_lax.doc_index))
    np.testing.assert_array_equal(np.asarray(t_pal.visible_order),
                                  np.asarray(t_lax.visible_order))
    assert view.visible_values(t_pal, p.values) == \
        view.visible_values(t_lax, p.values)


def test_auto_falls_back_on_cpu():
    """On a CPU backend the auto mode must pick the lax path (and agree)."""
    rng = np.random.default_rng(0)
    values, rid = _case(rng, 300, 16)
    got = np.asarray(mono_gather.monotone_gather(values, rid))
    np.testing.assert_array_equal(got, np.asarray(values[:, rid]))
