"""Failure injection over the replication service (SURVEY §5 failure
semantics; VERDICT r3's distributed row lacked any failure test).

A real worker PROCESS joins the service, edits, pushes half its log,
checkpoints its full local state, and dies hard (os._exit mid-session).
The parent detects the failure (exit code), observes the partial server
state, then restarts the worker from its checkpoint — recovery is pure
CRDT anti-entropy: pull absorbs the overlap as duplicates, the re-push
is idempotent, and both sides converge on the full edit history.  No
coordination, fencing, or replay log beyond the checkpoint is needed —
that is the failure model the semilattice join buys."""
import os
import subprocess
import sys
import threading

from crdt_graph_tpu.service import make_server

_WORKER = os.path.join(os.path.dirname(__file__), "_crash_worker.py")


def _run_worker(phase, port, ckpt):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, _WORKER, phase, str(port), ckpt],
        env=env, capture_output=True, text=True, timeout=300)


def test_worker_crash_checkpoint_resync(tmp_path):
    srv = make_server(port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    ckpt = str(tmp_path / "wal.npz")
    try:
        # phase 1: worker dies mid-session with half its log unpushed
        crashed = _run_worker("crash", srv.server_port, ckpt)
        assert crashed.returncode == 3, crashed.stdout + crashed.stderr
        doc = srv.store.get("wal", create=False)
        assert doc is not None
        assert len(doc.tree.visible_values()) == 5   # the pushed half
        assert os.path.exists(ckpt)                  # the local WAL

        # phase 2: restart from the checkpoint; anti-entropy converges
        rec = _run_worker("recover", srv.server_port, ckpt)
        assert rec.returncode == 0, rec.stdout + rec.stderr
        assert "recovered: OK" in rec.stdout
        assert doc.tree.visible_values() == \
            [f"edit-{i}" for i in range(10)]
        # the overlap was absorbed, not re-applied
        assert doc.metrics()["dup_absorbed"] >= 5
    finally:
        srv.shutdown()
        srv.server_close()
