"""Online session-guarantee oracle (ISSUE 6): per-guarantee unit
checks, seeded fault injection through the real engine, the read-path
correlation headers, the flush barrier, and the tier-1 closed-loop
smoke.

Acceptance pins:

- each guarantee check flags an injected violation and stays silent on
  a clean history;
- every ``GRAFT_ORACLE_FAULT`` kind (stale-snapshot, dropped-ack,
  fingerprint-regression) is caught by the oracle AND trips the
  ``oracle`` flight-dump trigger exactly once;
- a small closed-loop run against the real HTTP server reports zero
  violations and exercises ≥1 genuinely coalesced multi-writer commit;
- ``/metrics/prom`` strict-parses with the ``crdt_oracle_*`` families
  when an oracle is attached.
"""
import json
import os
import threading
import time

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.codec import json_codec                   # noqa: E402
from crdt_graph_tpu.core.operation import Add, Batch          # noqa: E402
from crdt_graph_tpu.obs import flight as flight_mod           # noqa: E402
from crdt_graph_tpu.obs import oracle as oracle_mod           # noqa: E402
from crdt_graph_tpu.obs import prom as prom_mod               # noqa: E402
from crdt_graph_tpu.obs.trace import (COMMIT_SEQ_HEADER,      # noqa: E402
                                      SESSION_HEADER, SNAP_FP_HEADER,
                                      TRACE_HEADER)
from crdt_graph_tpu.serve import ServingEngine                # noqa: E402

OFFSET = 2**32


def chain_ops(rid, n, counter0=0, anchor=0):
    ops, prev = [], anchor
    for i in range(n):
        ts = rid * OFFSET + counter0 + i + 1
        ops.append(Add(ts, (prev,), (counter0 + i) & 0xFF))
        prev = ts
    return ops


def chain_body(rid, n, counter0=0, anchor=0):
    return json_codec.dumps(Batch(tuple(chain_ops(rid, n, counter0,
                                                  anchor))))


def mk_recorder(tmp_path, **kw):
    kw.setdefault("capacity", 256)
    kw.setdefault("slo_ms", 60_000.0)
    kw.setdefault("audit_every", 0)
    kw.setdefault("dump_dir", str(tmp_path))
    kw.setdefault("min_dump_interval_s", 0.0)
    return flight_mod.FlightRecorder(**kw)


def commit_rec(doc_id="d", trace_ids=("trace-000001",), seq=1,
               fp="fp1", outcome="committed", width=1):
    return {"doc_id": doc_id, "trace_ids": list(trace_ids),
            "outcome": outcome, "snapshot_seq": seq, "fingerprint": fp,
            "coalesce_width": width}


# -- per-guarantee unit checks (pure oracle, no engine) --------------------


def test_clean_history_is_silent():
    o = oracle_mod.SessionOracle()
    o.observe_write_ack("sess-0001", "d", "trace-000001")
    o.ingest_commit_record(commit_rec(seq=1, fp="fp1"))
    o.observe_read("sess-0001", "d", 1, "fp1")
    o.observe_final_read("sess-0001", "d", 1, "fp1")
    assert o.finalize() == []
    st = o.stats()
    assert st["violations_total"] == 0
    assert st["pending_writes"] == 0
    # every check family actually evaluated something
    assert all(st["checks"][k] >= 1 for k in oracle_mod.CHECKS)


def test_read_your_writes_violation_after_resolution():
    """The commit record resolved the write to seq 2 BEFORE the read:
    a read at seq 1 is flagged immediately."""
    o = oracle_mod.SessionOracle()
    o.observe_write_ack("sess-0001", "d", "trace-000001")
    o.ingest_commit_record(commit_rec(seq=2, fp="fp2"))
    o.observe_read("sess-0001", "d", 1, "fp1")
    (v,) = o.violations
    assert v["check"] == "read_your_writes"
    assert v["seq"] == 1 and v["required_seq"] == 2


def test_read_your_writes_parked_read_resolves_late():
    """The read lands before the commit record (the async-record
    reality): it is parked and condemned on resolution."""
    o = oracle_mod.SessionOracle()
    o.observe_write_ack("sess-0001", "d", "trace-000001")
    o.observe_read("sess-0001", "d", 1, "fp1")       # parked, no verdict
    assert o.stats()["violations_total"] == 0
    assert o.stats()["pending_writes"] == 1
    o.ingest_commit_record(commit_rec(seq=2, fp="fp2"))
    (v,) = o.violations
    assert v["check"] == "read_your_writes"
    assert v["trace_id"] == "trace-000001"
    assert o.stats()["pending_writes"] == 0


def test_monotonic_read_regression_and_fork():
    o = oracle_mod.SessionOracle()
    o.observe_read("sess-0001", "d", 2, "fp2")
    o.observe_read("sess-0001", "d", 1, "fp1")       # seq regressed
    o.observe_read("sess-0002", "d", 3, "fpA")
    o.observe_read("sess-0002", "d", 3, "fpB")       # forked at same seq
    kinds = [v["check"] for v in o.violations]
    assert kinds == ["monotonic_read", "monotonic_read"]
    # per-session isolation: a third session at seq 1 is fine
    o.observe_read("sess-0003", "d", 1, "fp1")
    assert o.stats()["violations"]["monotonic_read"] == 2


def test_fingerprint_cross_check_against_flight_stream():
    o = oracle_mod.SessionOracle()
    o.ingest_commit_record(commit_rec(seq=5, fp="flightfp"))
    o.observe_read("sess-0001", "d", 5, "readfp")
    (v,) = o.violations
    assert v["check"] == "fingerprint_match"
    assert v["flight_fingerprint"] == "flightfp"


def test_fingerprintless_read_does_not_poison_next_seq():
    """A fingerprint-less read at a NEW seq must not carry the prior
    seq's fingerprint forward — the next fingerprinted read at the new
    seq is not a fork.  Same-seq retention still catches real forks."""
    o = oracle_mod.SessionOracle()
    o.observe_read("sess-0001", "d", 5, "fpA")
    o.observe_read("sess-0001", "d", 6, None)        # headerless read
    o.observe_read("sess-0001", "d", 6, "fpB")       # NOT a fork
    assert o.stats()["violations_total"] == 0
    o.observe_read("sess-0001", "d", 6, None)        # same seq: fpB kept
    o.observe_read("sess-0001", "d", 6, "fpC")       # genuine fork
    assert [v["check"] for v in o.violations] == ["monotonic_read"]


def test_noop_record_resolves_empty_acked_write():
    """An acked EMPTY delta lands on a "noop" record that publishes no
    new snapshot: the ack resolves with NO read floor — not a
    dropped_ack at finalize.  Both arrival orders are legal."""
    o = oracle_mod.SessionOracle()
    o.observe_write_ack("sess-0001", "d", "trace-000001")
    o.ingest_commit_record(commit_rec(outcome="noop"))
    assert o.stats()["pending_writes"] == 0
    o.observe_read("sess-0001", "d", 0, None)        # no floor imposed
    assert o.finalize() == []
    o2 = oracle_mod.SessionOracle()                  # record beats ack
    o2.ingest_commit_record(commit_rec(outcome="noop"))
    o2.observe_write_ack("sess-0001", "d", "trace-000001")
    assert o2.stats()["pending_writes"] == 0 and o2.finalize() == []


def test_colliding_client_trace_ids_resolve_all_owners():
    """The HTTP layer adopts any well-formed client trace id, so
    sessions may collide on one: every owner must resolve when its
    doc's record lands — no silent shadowing, no false dropped_ack."""
    o = oracle_mod.SessionOracle()
    o.observe_write_ack("sess-0001", "d", "shared-trace-01")
    o.observe_write_ack("sess-0002", "d", "shared-trace-01")
    o.observe_write_ack("sess-0003", "e", "shared-trace-01")  # other doc
    o.ingest_commit_record(commit_rec(
        doc_id="d", trace_ids=("shared-trace-01",), seq=3, fp="fp3"))
    assert o.stats()["pending_writes"] == 1   # only the doc-e ack left
    o.ingest_commit_record(commit_rec(
        doc_id="e", trace_ids=("shared-trace-01",), seq=1, fp="fpE"))
    assert o.stats()["pending_writes"] == 0
    o.observe_read("sess-0001", "d", 3, "fp3")
    o.observe_read("sess-0002", "d", 3, "fp3")
    assert o.finalize() == []


def test_resolved_history_is_bounded():
    """An oracle on a long-running engine must not grow with total
    commits: resolved traces and fingerprint history evict FIFO."""
    o = oracle_mod.SessionOracle(max_resolved_traces=10,
                                 max_fp_entries=10)
    for i in range(50):
        o.ingest_commit_record(commit_rec(
            trace_ids=(f"trace-{i:06d}",), seq=i + 1, fp=f"fp{i}"))
    assert len(o._trace_commits) <= 10
    assert len(o._fp_by_seq) <= 10
    assert o.stats()["violations_total"] == 0
    # session churn is bounded too, while the distinct-session counter
    # stays monotonic (it feeds crdt_oracle_sessions_total)
    o2 = oracle_mod.SessionOracle(max_session_states=8)
    for i in range(40):
        o2.observe_read(f"sess-{i:04d}", "d", 1, "fp1")
    assert len(o2._sessions) <= 8 and len(o2._session_ids) <= 8
    assert o2.stats()["sessions"] == 40
    assert o2.stats()["violations_total"] == 0


def test_dropped_ack_flagged_at_finalize_only():
    o = oracle_mod.SessionOracle()
    o.observe_write_ack("sess-0001", "d", "trace-000001")
    assert o.stats()["violations_total"] == 0        # online: not yet
    vs = o.finalize()
    assert [v["check"] for v in vs] == ["dropped_ack"]
    assert vs[0]["trace_id"] == "trace-000001"


def test_convergence_mismatch_across_sessions():
    o = oracle_mod.SessionOracle()
    o.observe_final_read("sess-0001", "d", 4, "fp4")
    o.observe_final_read("sess-0002", "d", 4, "fp4x")
    vs = o.finalize()
    assert [v["check"] for v in vs] == ["convergence"]


def test_violation_fires_oracle_dump_with_rate_limit(tmp_path):
    rec = mk_recorder(tmp_path, min_dump_interval_s=60.0)
    rec.record({  # something in the ring so the dump carries context
        "doc_id": "d", "trace_ids": ("t" * 16,), "outcome": "committed",
        "num_ops": 1, "applied_ops": 1, "dup_ops": 0,
        "coalesce_width": 1, "chunk_count": 1,
        "queue_depth_admission": 0, "stages_ms": {}, "total_ms": 0.1,
        "staleness_s": None, "snapshot_seq": 1, "fingerprint": "f",
        "audit": None, "error": None})
    o = oracle_mod.SessionOracle(flight=rec)
    o.observe_read("sess-0001", "d", 2, "fp2")
    o.observe_read("sess-0001", "d", 1, "fp1")       # violation → dump
    o.observe_read("sess-0001", "d", 0, "fp0")       # rate-limited
    st = rec.stats()
    assert st["dumps"] == {"oracle": 1, "suppressed": 1}
    path = st["last_dump_path"]
    assert path.endswith("_oracle.jsonl")
    lines = [json.loads(ln) for ln in open(path).read().splitlines()]
    assert lines[0]["reason"] == "oracle" and len(lines) == 2


# -- fault injection through the real engine -------------------------------


def test_fault_injector_env_parse_and_one_shot(monkeypatch):
    monkeypatch.delenv("GRAFT_ORACLE_FAULT", raising=False)
    assert oracle_mod.FaultInjector.from_env() is None
    monkeypatch.setenv("GRAFT_ORACLE_FAULT", "stale, drop,bogus")
    inj = oracle_mod.FaultInjector.from_env()
    assert inj.armed("stale") and inj.armed("drop")
    assert not inj.armed("bogus") and not inj.armed("regress")
    assert inj.pop("stale") and not inj.pop("stale")   # one-shot
    # regress burns one skip before firing (the eligible read that
    # must still see the CURRENT snapshot)
    inj2 = oracle_mod.FaultInjector(("regress",))
    assert not inj2.pop("regress") and inj2.pop("regress")
    assert not inj2.pop("regress")


def oracle_engine(tmp_path, fault_kinds):
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(
        flight=rec, fault=oracle_mod.FaultInjector(fault_kinds))
    o = oracle_mod.SessionOracle()
    o.attach_engine(engine)
    return engine, o, rec


def test_fault_stale_snapshot_caught(tmp_path):
    """An injected stale read (the previous published snapshot) is a
    read-your-writes violation — caught via trace_id → CommitRecord →
    seq correlation, and it trips the oracle dump exactly once."""
    engine, o, rec = oracle_engine(tmp_path, ("stale",))
    try:
        for w in range(2):
            tid = f"stale-w{w:04d}"
            acc, _ = engine.submit("d", chain_body(
                1, 6, counter0=6 * w,
                anchor=(OFFSET + 6 * w) if w else 0), trace_id=tid)
            assert acc
            o.observe_write_ack("sess-0001", "d", tid)
        assert engine.flush(timeout=30)
        snap = engine.get("d").read_view()         # fault fires: prev
        assert snap.seq == 1
        o.observe_read("sess-0001", "d", snap.seq, snap.fingerprint())
        (v,) = o.violations
        assert v["check"] == "read_your_writes"
        assert v["seq"] == 1 and v["required_seq"] == 2
        assert engine.fault.fired == {"stale": 1}
        assert rec.stats()["dumps"].get("oracle") == 1
        # the fault is one-shot: the next read serves the real snapshot
        assert engine.get("d").read_view().seq == 2
    finally:
        o.detach_engine(engine)
        engine.close()


def test_fault_fingerprint_regression_caught(tmp_path):
    """An injected regression (current snapshot observed, then the
    previous one served) is a monotonic-read violation."""
    engine, o, rec = oracle_engine(tmp_path, ("regress",))
    try:
        for w in range(2):
            acc, _ = engine.submit("d", chain_body(
                1, 6, counter0=6 * w,
                anchor=(OFFSET + 6 * w) if w else 0))
            assert acc
        assert engine.flush(timeout=30)
        doc = engine.get("d")
        s1 = doc.read_view()                       # skip burn: current
        assert s1.seq == 2
        o.observe_read("sess-0001", "d", s1.seq, s1.fingerprint())
        s2 = doc.read_view()                       # fault fires: prev
        assert s2.seq == 1
        o.observe_read("sess-0001", "d", s2.seq, s2.fingerprint())
        (v,) = o.violations
        assert v["check"] == "monotonic_read"
        assert v["seq"] == 1 and v["prev_seq"] == 2
        assert rec.stats()["dumps"].get("oracle") == 1
    finally:
        o.detach_engine(engine)
        engine.close()


def test_fault_dropped_ack_caught(tmp_path):
    """An injected dropped ack (ticket acked, publish + record
    skipped) is invisible online and condemned at finalize."""
    engine, o, rec = oracle_engine(tmp_path, ("drop",))
    try:
        acc, _ = engine.submit("d", chain_body(1, 6),
                               trace_id="drop-w0000")
        assert acc                                  # acked regardless
        o.observe_write_ack("sess-0001", "d", "drop-w0000")
        assert engine.flush(timeout=30)
        assert rec.stats()["records_total"] == 0    # record suppressed
        assert engine.get("d").snapshot_view().seq == 0   # no publish
        assert engine.counters.get("fault_dropped_commits") == 1
        vs = o.finalize()
        assert [v["check"] for v in vs] == ["dropped_ack"]
        assert vs[0]["trace_id"] == "drop-w0000"
        assert rec.stats()["dumps"].get("oracle") == 1
    finally:
        o.detach_engine(engine)
        engine.close()


def test_fault_stale_over_http_via_read_headers(tmp_path, monkeypatch):
    """End-to-end fault proof: GRAFT_ORACLE_FAULT=stale in the env, a
    real server, and the oracle fed ONLY from wire-observable evidence
    (ack echoes, read headers, /debug/flight)."""
    import http.client
    from crdt_graph_tpu.service import make_server
    monkeypatch.setenv("GRAFT_ORACLE_FAULT", "stale")
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(flight=rec)        # fault read from env
    o = oracle_mod.SessionOracle()
    srv = make_server(port=0, store=engine)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.server_port,
                                          timeout=30)
        for w in range(2):
            tid = f"http-w{w:04d}"
            conn.request("POST", "/docs/h/ops", body=chain_body(
                1, 5, counter0=5 * w,
                anchor=(OFFSET + 5 * w) if w else 0),
                headers={TRACE_HEADER: tid,
                         SESSION_HEADER: "sess-http1"})
            resp = conn.getresponse()
            out = json.loads(resp.read())
            assert resp.status == 200 and out["accepted"]
            assert resp.getheader(SESSION_HEADER) == "sess-http1"
            o.observe_write_ack("sess-http1", "h", tid)
        assert engine.flush(timeout=30)
        # feed the oracle from the wire-side flight scrape (the
        # polling-free path: flush already guaranteed the records)
        conn.request("GET", "/debug/flight")
        for r in json.loads(conn.getresponse().read())["records"]:
            o.ingest_commit_record(r)
        conn.request("GET", "/docs/h",
                     headers={SESSION_HEADER: "sess-http1"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        seq = int(resp.getheader(COMMIT_SEQ_HEADER))
        fp = resp.getheader(SNAP_FP_HEADER)
        assert seq == 1 and len(body["values"]) == 5   # the stale view
        o.observe_read("sess-http1", "h", seq, fp)
        conn.close()
        (v,) = o.violations
        assert v["check"] == "read_your_writes"
        assert v["required_seq"] == 2
    finally:
        srv.shutdown()
        srv.server_close()
        engine.close()


# -- read-path headers on a clean server -----------------------------------


def test_read_headers_echo_snapshot_identity(server):
    """GET /docs/{id} and /snapshot carry the served snapshot's
    fingerprint + seq and adopt (or mint) X-Session-Id; the
    fingerprint matches the commit's flight record."""
    import http.client
    engine = server.store
    conn = http.client.HTTPConnection("127.0.0.1", server.server_port,
                                      timeout=30)
    conn.request("POST", "/docs/hdr/ops", body=chain_body(1, 7))
    resp = conn.getresponse()
    resp.read()
    assert resp.status == 200
    assert engine.flush(timeout=30)
    conn.request("GET", "/docs/hdr",
                 headers={SESSION_HEADER: "sess-hdr-1"})
    resp = conn.getresponse()
    resp.read()
    seq = int(resp.getheader(COMMIT_SEQ_HEADER))
    fp = resp.getheader(SNAP_FP_HEADER)
    assert seq == 1 and fp
    assert resp.getheader(SESSION_HEADER) == "sess-hdr-1"
    (rec,) = engine.flight.records()
    assert rec.snapshot_seq == seq and rec.fingerprint == fp
    # /snapshot serves the same identity; malformed session re-minted
    conn.request("GET", "/docs/hdr/snapshot",
                 headers={SESSION_HEADER: "bad id!"})
    resp = conn.getresponse()
    resp.read()
    assert resp.getheader(SNAP_FP_HEADER) == fp
    assert int(resp.getheader(COMMIT_SEQ_HEADER)) == seq
    minted = resp.getheader(SESSION_HEADER)
    assert minted and minted != "bad id!"
    conn.close()


# -- the flush barrier -----------------------------------------------------


def test_flush_barrier_replaces_record_polling(tmp_path):
    """flush() returns only after every prior ticket's flight record
    has landed — and leaves the engine serving (unlike close())."""
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(flight=rec)
    try:
        for w in range(3):
            engine.submit("f", chain_body(1, 4, counter0=4 * w,
                                          anchor=(OFFSET + 4 * w)
                                          if w else 0))
            assert engine.flush(timeout=30)
            assert rec.stats()["records_total"] == w + 1   # no polling
        # engine still alive after the barrier
        acc, _ = engine.submit("f", chain_body(
            1, 4, counter0=12, anchor=OFFSET + 12))
        assert acc
        # a paused scheduler with pending work times out (False)
        engine.scheduler.pause()
        try:
            t = threading.Thread(
                target=lambda: engine.submit(
                    "f", chain_body(1, 4, counter0=16,
                                    anchor=OFFSET + 16)),
                daemon=True)
            t.start()
            deadline = 100
            while not len(engine.get("f").queue) and deadline:
                deadline -= 1
                threading.Event().wait(0.01)
            assert engine.flush(timeout=0.3) is False
        finally:
            engine.scheduler.resume()
            t.join(30)
    finally:
        engine.close()


def test_flush_refuses_on_stopping_scheduler(tmp_path):
    """A stopping scheduler fails its pending tickets WITHOUT flight
    records — flush() must refuse (False) immediately, not report the
    barrier held and not burn its whole timeout."""
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(flight=rec)
    try:
        engine.scheduler.pause()
        t = threading.Thread(
            target=lambda: engine.submit("f", chain_body(1, 4)),
            daemon=True)
        t.start()
        deadline = 100
        while not len(engine.get("f").queue) and deadline:
            deadline -= 1
            time.sleep(0.01)
        with engine.scheduler.cond:
            engine.scheduler._stop_requested = True
        t0 = time.monotonic()
        assert engine.flush(timeout=5) is False
        assert time.monotonic() - t0 < 2.0   # refused, not timed out
        with engine.scheduler.cond:
            engine.scheduler._stop_requested = False
        engine.scheduler.resume()
        t.join(30)
    finally:
        engine.close()


def test_prev_snapshot_not_retained_without_fault(tmp_path):
    """Production engines (no fault injector) must not hold the
    outgoing snapshot generation after publish — only fault injection
    ever serves it (read_view)."""
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(flight=rec)
    try:
        engine.submit("m", chain_body(1, 3))
        assert engine.flush(timeout=30)
        doc = engine.get("m")
        assert doc.snapshot_view().seq == 1
        assert doc._prev_snap is None
    finally:
        engine.close()


# -- prom exposition round-trip with the oracle families -------------------


def test_prom_round_trip_includes_oracle_families(tmp_path):
    rec = mk_recorder(tmp_path)
    engine = ServingEngine(flight=rec)
    o = oracle_mod.SessionOracle()
    o.attach_engine(engine)
    try:
        engine.submit("p", chain_body(1, 5), trace_id="prom-w0000")
        o.observe_write_ack("sess-prom1", "p", "prom-w0000")
        assert engine.flush(timeout=30)
        snap = engine.get("p").snapshot_view()
        o.observe_read("sess-prom1", "p", snap.seq, snap.fingerprint())
        o.observe_read("sess-prom1", "p", snap.seq - 1, None)  # inject
        fams = prom_mod.parse_text(engine.render_prom())
        for fam in ("crdt_oracle_sessions_total",
                    "crdt_oracle_checks_total",
                    "crdt_oracle_violations_total",
                    "crdt_oracle_commits_ingested_total",
                    "crdt_oracle_pending_writes"):
            assert fam in fams, fam
        viol = {lbl["check"]: v for _, lbl, v in
                fams["crdt_oracle_violations_total"]["samples"]}
        assert set(viol) == set(oracle_mod.CHECKS)   # full label set
        # the injected regressed read trips BOTH session guarantees:
        # it reads below the session's resolved write AND regresses
        assert viol["read_your_writes"] == 1.0
        assert viol["monotonic_read"] == 1.0
        assert viol["dropped_ack"] == 0
        checks = {lbl["check"]: v for _, lbl, v in
                  fams["crdt_oracle_checks_total"]["samples"]}
        assert checks["read_your_writes"] >= 2
    finally:
        o.detach_engine(engine)
        engine.close()


# -- the tier-1 closed-loop smoke ------------------------------------------


def test_loadgen_smoke_zero_violations(tmp_path):
    """Small closed-loop run against the real HTTP server: zero
    violations, ≥1 genuinely coalesced multi-writer commit, shedding
    and the giant racer exercised, prom families present."""
    from crdt_graph_tpu.bench import loadgen
    rec = mk_recorder(tmp_path, capacity=4096)
    engine = ServingEngine(flight=rec, max_queue_requests=4)
    cfg = loadgen.LoadgenConfig(
        n_sessions=10, n_docs=2, writes_per_session=5, delta_size=8,
        max_queue_requests=4, giant_ops=2000, stage_first_round=True,
        seed=2)
    try:
        res = loadgen.run(cfg, engine=engine)
    finally:
        engine.close()
    assert not res["errors"], res["errors"]
    assert res["oracle"]["violations_total"] == 0
    assert res["violations"] == []
    # a genuinely coalesced multi-writer commit happened (the staged
    # first round guarantees it deterministically)
    assert res["staged_first_round"]
    assert res["oracle"]["max_coalesce_width"] >= 2
    assert res["writes_acked"] == 10 * 5 + 1         # + the giant
    assert res["ops_merged"] == res["leaves_acked"]  # nothing lost
    assert res["reads"] >= 10 and res["read_p99_ms"] is not None
    assert res["flushed"]
    assert res["oracle"]["pending_writes"] == 0      # every ack resolved
    assert "crdt_oracle_violations_total" in res["prom_oracle_families"]
    # the flight stream fed the oracle without any records_total polling
    assert res["oracle"]["commits_ingested"] >= 2


@pytest.mark.slow
def test_serve_headline_full(tmp_path):
    """The committed-artifact run (BENCH_SERVE_r01_cpu.json shape):
    ≥200 sessions, ≥50k leaves, zero violations.  Slow-marked — the
    tier-1 gate runs the small smoke above instead."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_bench_serve_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_serve_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_SERVE_test.json"))
    assert out["violations_total"] == 0
    assert out["sessions"] >= 200 and out["total_leaves"] >= 50_000
    assert not out["report"]["errors"]
    assert out["report"]["oracle"]["max_coalesce_width"] >= 2
