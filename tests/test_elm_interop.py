"""Elm-client interop, end to end (VERDICT r3 missing-3).

The reference deploys as an Elm client (`CRDTree.Backend` port) shipping
operation batches over the wire (reference README.md:20-22).  These tests
replay the reference's OWN fixtures — hand-written here as the exact byte
strings Elm's ``CRDTree.Operation.encoder`` + ``Encode.encode 0`` emit
(field order op/path/ts/val pinned by CRDTree/Operation.elm:106-128) —
through the HTTP service, and assert

- the service accepts them and the visible document matches the oracle,
- node lookups match the reference's per-fixture ``expectNode`` claims
  (tests/CRDTreeTest.elm), and
- the re-encoded log pulled back from ``GET /ops?since=0`` is
  BYTE-IDENTICAL to what the Elm encoder would produce for the same ops —
  so an Elm peer replaying our response sees exactly its own wire format.

None of the wire strings below are produced by this package's codec; they
fail if either the codec or the RGA semantics drift from
CRDTree/Operation.elm:109-159 / Internal/Node.elm.

Provenance note (VERDICT r4 next-6): generating these fixtures by
RUNNING the reference toolchain is not possible in this environment —
no ``elm``/``elm-test`` binary nor any JS runtime (node/deno/bun) is in
the image, and the build has zero network egress to fetch one (checked
2026-07-30; ``which elm elm-test node…`` all empty).  The corpus is
therefore hand-derived from reading the encoder/decoder source, and
extended below to the cases the r4 verdict called out: deep addBranch
nesting with sibling branches, batch-in-batch (the wire format nests;
the reference log flattens — applyLocal maps apply over Batch ops and
appends each leaf, CRDTree.elm:294-311), and unknown-tag forward
compatibility (decoder falls through to ``Batch []``,
CRDTree/Operation.elm:158-159).
"""
import json

import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu.codec import json_codec

# ``server`` and ``req`` fixtures come from tests/conftest.py (shared
# with test_service.py)


def canonical(payload) -> str:
    """Compact re-serialization — Elm's ``Encode.encode 0`` shape.  Key
    ORDER survives json.loads→dumps, so equality here is byte equality
    of the service's wire output vs the Elm encoder's."""
    return json.dumps(payload, separators=(",", ":"))


def elm_add(ts, path, val) -> str:
    """Exactly what Elm's encoder emits for ``Add ts path val``
    (CRDTree/Operation.elm:109-116: op, path, ts, val)."""
    p = json.dumps(list(path), separators=(",", ":"))
    return f'{{"op":"add","path":{p},"ts":{ts},"val":{json.dumps(val)}}}'


def elm_del(path) -> str:
    return ('{"op":"del","path":'
            + json.dumps(list(path), separators=(",", ":")) + "}")


def elm_batch(*ops: str) -> str:
    return '{"op":"batch","ops":[' + ",".join(ops) + "]}"


def oracle_replay(wire: str):
    """The pure oracle applying the decoded wire batch (reference
    CRDTree.apply semantics)."""
    tree = crdt.init(99)
    return tree.apply(json_codec.loads(wire))


def push_and_compare(req, server, doc, wire, expect_accept=True):
    st, out = req(server, "POST", f"/docs/{doc}/ops", wire)
    if expect_accept:
        assert st == 200 and out["accepted"], out
    else:
        assert st == 409 and not out["accepted"], out
    _, snap = req(server, "GET", f"/docs/{doc}")
    return snap["values"]


# -- tests/CRDTreeTest.elm:324-358 — applies several remote operations ----

def test_apply_batch_fixture(server, req):
    wire = elm_batch(elm_add(1, [0], "a"), elm_add(2, [1], "b"))
    values = push_and_compare(req, server, "batch", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == ["a", "b"]
    # expectNode [1] "a", [2] "b" (the reference's per-path claims)
    assert oracle.get_value((1,)) == "a"
    assert oracle.get_value((2,)) == "b"
    # byte-identical log echo: an Elm peer pulling since=0 receives its
    # own encoder's bytes back
    _, log = req(server, "GET", "/docs/batch/ops?since=0")
    assert canonical(log) == wire


# -- tests/CRDTreeTest.elm:203-258 — addBranch five levels deep -----------

def test_add_branch_fixture(server, req):
    ops = [elm_add(1, [0], "a"), elm_add(2, [1, 0], "b"),
           elm_add(3, [1, 2, 0], "c"), elm_add(4, [1, 2, 3, 0], "d"),
           elm_add(5, [1, 2, 3, 4, 0], "e"), elm_add(6, [1, 2, 3, 4, 5], "f")]
    wire = elm_batch(*ops)
    values = push_and_compare(req, server, "branch", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == \
        ["a", "b", "c", "d", "e", "f"]
    for path, want in [((1,), "a"), ((1, 2), "b"), ((1, 2, 3), "c"),
                       ((1, 2, 3, 4), "d"), ((1, 2, 3, 4, 5), "e"),
                       ((1, 2, 3, 4, 6), "f")]:
        assert oracle.get_value(path) == want, path
    _, log = req(server, "GET", "/docs/branch/ops?since=0")
    assert canonical(log) == wire


# -- tests/CRDTreeTest.elm:401-440 — apply Add inserts between nodes ------

def test_insertion_between_nodes_fixture(server, req):
    wire = elm_batch(elm_add(1, [0], "a"), elm_add(2, [1], "c"),
                     elm_add(3, [1], "b"))
    values = push_and_compare(req, server, "insert", wire)
    oracle = oracle_replay(wire)
    # same anchor [1]: higher timestamp rests closer to the anchor
    assert values == oracle.visible_values() == ["a", "b", "c"]
    assert oracle.get_value((1,)) == "a"
    assert oracle.get_value((2,)) == "c"
    assert oracle.get_value((3,)) == "b"
    _, log = req(server, "GET", "/docs/insert/ops?since=0")
    assert canonical(log) == wire


# -- tests/CRDTreeTest.elm:443-477 — nested-branch leaves -----------------

def test_add_leaf_fixture(server, req):
    wire = elm_batch(elm_add(1, [0], "a"), elm_add(2, [1, 0], "b"),
                     elm_add(3, [1, 2], "c"))
    values = push_and_compare(req, server, "leaf", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == ["a", "b", "c"]
    assert oracle.get_value((1, 2)) == "b"
    assert oracle.get_value((1, 3)) == "c"
    _, log = req(server, "GET", "/docs/leaf/ops?since=0")
    assert canonical(log) == wire


# -- tests/CRDTreeTest.elm:263-321 — delete marks tombstone ---------------

def test_delete_fixture(server, req):
    wire = elm_batch(elm_add(1, [0], "a"), elm_del([1]))
    values = push_and_compare(req, server, "dele", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == []
    assert oracle.get_value((1,)) is None  # tombstoned, no visible value
    _, log = req(server, "GET", "/docs/dele/ops?since=0")
    assert canonical(log) == wire


# -- tests/CRDTreeTest.elm:480-496 — batch atomicity ----------------------

def test_batch_atomicity_fixture(server, req):
    # second op anchors at an absent node [9]: the reference rejects the
    # WHOLE batch (Expect.err); service answers 409, document unchanged
    wire = elm_batch(elm_add(1, [0], "a"), elm_add(2, [9], "b"))
    values = push_and_compare(req, server, "atomic", wire, expect_accept=False)
    assert values == []
    with pytest.raises(crdt.CRDTError):
        oracle_replay(wire)
    _, log = req(server, "GET", "/docs/atomic/ops?since=0")
    assert canonical(log) == '{"op":"batch","ops":[]}'


# -- tests/CRDTreeTest.elm:358-399 / 498-560 — idempotence ----------------

def test_add_idempotent_fixture(server, req):
    wire = elm_batch(*([elm_add(1, [0], "a")] * 4))
    values = push_and_compare(req, server, "idem", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == ["a"]


def test_delete_idempotent_fixture(server, req):
    wire = elm_batch(elm_add(1, [0], "a"), *([elm_del([1])] * 5))
    values = push_and_compare(req, server, "idemdel", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == []


# -- batch-in-batch: wire nests, log flattens (CRDTree.elm:294-311) -------

def test_batch_in_batch_fixture(server, req):
    inner = elm_batch(elm_add(2, [1], "b"), elm_add(3, [2], "c"))
    wire = elm_batch(elm_add(1, [0], "a"), inner, elm_del([3]))
    # the nested structure survives DECODING losslessly…
    op = json_codec.loads(wire)
    assert op == crdt.Batch((
        crdt.Add(1, (0,), "a"),
        crdt.Batch((crdt.Add(2, (1,), "b"), crdt.Add(3, (2,), "c"))),
        crdt.Delete((3,))))
    # …and our encoder emits the nested bytes back unchanged
    assert canonical(json_codec.encode(op)) == wire
    # applied, it equals the flat sequence (applyLocal maps apply over
    # Batch ops); the LOG stores leaves, so the echo is the FLAT batch
    values = push_and_compare(req, server, "nested", wire)
    flat = elm_batch(elm_add(1, [0], "a"), elm_add(2, [1], "b"),
                     elm_add(3, [2], "c"), elm_del([3]))
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == \
        oracle_replay(flat).visible_values() == ["a", "b"]
    _, log = req(server, "GET", "/docs/nested/ops?since=0")
    assert canonical(log) == flat


# -- deep addBranch nesting WITH sibling branches -------------------------

def test_nested_sibling_branches_fixture(server, req):
    """Two branches under the same parent, each with children, plus a
    mid-branch delete — the addBranch shape the r4 verdict asked the
    corpus to cover beyond the straight 5-deep chain: branch [1]
    ("a") holds children b,c; sibling branch [4] ("d") holds e; then
    the WHOLE first branch is deleted, discarding its subtree
    (Internal/Node.elm delete semantics)."""
    ops = [elm_add(1, [0], "a"),        # addBranch "a"
           elm_add(2, [1, 0], "b"),     # child of a
           elm_add(3, [1, 2], "c"),     # sibling after b, inside a
           elm_add(4, [1], "d"),        # sibling branch after a
           elm_add(5, [4, 0], "e")]     # child of d
    wire = elm_batch(*ops)
    values = push_and_compare(req, server, "sibs", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == ["a", "b", "c", "d", "e"]
    for path, want in [((1,), "a"), ((1, 2), "b"), ((1, 3), "c"),
                       ((4,), "d"), ((4, 5), "e")]:
        assert oracle.get_value(path) == want, path
    _, log = req(server, "GET", "/docs/sibs/ops?since=0")
    assert canonical(log) == wire
    # deleting branch [1] discards its subtree but leaves [4]'s intact
    values = push_and_compare(req, server, "sibs", elm_batch(elm_del([1])))
    assert values == ["d", "e"]
    assert oracle_replay(
        elm_batch(*ops, elm_del([1]))).visible_values() == ["d", "e"]


# -- unknown-tag forward compatibility (Operation.elm:158-159) ------------

def test_unknown_tag_fixture(server, req):
    """A future/unknown op tag decodes to ``Batch []`` — a no-op — both
    bare and inside a batch; the surrounding ops still apply and the
    echoed log contains only them."""
    assert json_codec.loads('{"op":"move","path":[1],"to":[2]}') == \
        crdt.Batch(())
    wire = ('{"op":"batch","ops":[' + elm_add(1, [0], "a") +
            ',{"op":"move","path":[1],"to":[2]},' +
            elm_add(2, [1], "b") + "]}")
    values = push_and_compare(req, server, "future", wire)
    oracle = oracle_replay(wire)
    assert values == oracle.visible_values() == ["a", "b"]
    _, log = req(server, "GET", "/docs/future/ops?since=0")
    assert canonical(log) == elm_batch(elm_add(1, [0], "a"),
                                       elm_add(2, [1], "b"))


# -- tests/JsonTest.elm:16-64 — codec round trips, byte level -------------

@pytest.mark.parametrize("wire,op", [
    (elm_add(3, [1, 2], "a"), crdt.Add(3, (1, 2), "a")),
    (elm_del([1, 2]), crdt.Delete((1, 2))),
    (elm_batch(elm_add(3, [1, 2], "a"), elm_add(4, [1, 3], "b"),
               elm_del([1, 2])),
     crdt.Batch((crdt.Add(3, (1, 2), "a"), crdt.Add(4, (1, 3), "b"),
                 crdt.Delete((1, 2))))),
])
def test_json_fixture_bytes(wire, op):
    # Elm bytes decode to the expected operation…
    assert json_codec.loads(wire) == op
    # …and our encoder emits Elm's bytes back, byte for byte
    assert canonical(json_codec.encode(op)) == wire


# -- multi-replica: two Elm clients through the coordinator ---------------

def test_two_elm_clients_converge_through_service(server, req):
    """Two simulated Elm clients (hand-encoded wire, reference timestamp
    scheme replica*2^32+counter, CRDTree/Timestamp.elm) interleave edits
    through the service; the pulled logs replayed into the oracle match
    the service snapshot at every step."""
    _, r1 = req(server, "POST", "/docs/doc/replicas")
    _, r2 = req(server, "POST", "/docs/doc/replicas")
    a, b = r1["replica"], r2["replica"]
    assert a != b
    ts = lambda rid, c: rid * 2 ** 32 + c

    # client A appends "x" at root
    wire_a = elm_batch(elm_add(ts(a, 1), [0], "x"))
    push_and_compare(req, server, "doc", wire_a)
    # client B (having pulled) anchors "y" after A's node
    wire_b = elm_batch(elm_add(ts(b, 1), [ts(a, 1)], "y"))
    values = push_and_compare(req, server, "doc", wire_b)
    assert values == ["x", "y"]

    # a third, concurrent edit racing on the same anchor
    wire_a2 = elm_batch(elm_add(ts(a, 2), [ts(a, 1)], "z"))
    values = push_and_compare(req, server, "doc", wire_a2)
    oracle = crdt.init(77)
    _, log = req(server, "GET", "/docs/doc/ops?since=0")
    oracle = oracle.apply(json_codec.decode(log))
    assert oracle.visible_values() == values
