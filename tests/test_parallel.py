"""Sharded-merge parity: the multi-chip paths must produce bit-identical
tables to the single-device kernel, on the simulated 8-device CPU mesh
(conftest.py sets XLA_FLAGS=--xla_force_host_platform_device_count=8)."""
import random

import numpy as np
import pytest

import jax

import crdt_graph_tpu as crdt
from crdt_graph_tpu.codec import packed
from crdt_graph_tpu.ops import merge, view
from crdt_graph_tpu.parallel import mesh as mesh_mod

from test_merge_kernel import _random_session


@pytest.fixture(scope="module")
def session_ops():
    merged, ops = _random_session(21, n_replicas=4, steps=150)
    return merged.visible_values(), ops


def test_eight_way_ops_sharding(session_ops):
    want, ops = session_ops
    p = packed.pack(ops)
    m = mesh_mod.make_mesh(n_docs=1, n_ops=8)
    t = view.to_host(mesh_mod.sharded_materialize(p.arrays(), m))
    ref = view.to_host(merge.materialize(p.arrays()))
    assert view.visible_values(t, p.values) == want
    for field in ("ts", "parent", "doc_index", "order", "visible_order",
                  "status"):
        np.testing.assert_array_equal(getattr(t, field), getattr(ref, field))


def test_batched_docs_sharding(session_ops):
    _, ops = session_ops
    rng = random.Random(3)
    docs = []
    wants = []
    for d in range(8):
        perm = ops[:]
        rng.shuffle(perm)
        sub = perm[: 40 + 10 * d]
        docs.append(packed.pack(sub, capacity=256))
        t = view.to_host(merge.materialize(docs[-1].arrays()))
        wants.append(view.visible_values(t, docs[-1].values))
    stacked = mesh_mod.stack_packed(docs)
    m = mesh_mod.make_mesh(n_docs=8, n_ops=1)
    tb = view.to_host(mesh_mod.batched_materialize(stacked, m))
    for d in range(8):
        row = jax.tree.map(lambda a: a[d], tb)
        assert view.visible_values(row, docs[d].values) == wants[d]


def test_batched_exhaustive_hints_parity(session_ops):
    """The opt-in cond-free hinted batched kernel must match the safe
    (join) batched kernel bit for bit on pack-produced batches."""
    _, ops = session_ops
    docs = [packed.pack(ops[: 60 + 20 * d], capacity=256) for d in range(8)]
    stacked = mesh_mod.stack_packed(docs)
    m = mesh_mod.make_mesh(n_docs=8, n_ops=1)
    safe = view.to_host(mesh_mod.batched_materialize(stacked, m))
    fast = view.to_host(
        mesh_mod.batched_materialize(stacked, m, exhaustive_hints=True))
    for field in ("ts", "doc_index", "visible_order", "status"):
        np.testing.assert_array_equal(getattr(fast, field),
                                      getattr(safe, field), field)


def test_2d_mesh_docs_by_ops(session_ops):
    want, ops = session_ops
    p = packed.pack(ops)
    docs = [p, p, p, p]
    stacked = mesh_mod.stack_packed(docs)
    m = mesh_mod.make_mesh(n_docs=4, n_ops=2)
    tb = view.to_host(
        mesh_mod.batched_materialize(stacked, m, shard_ops_axis=True))
    for d in range(4):
        row = jax.tree.map(lambda a: a[d], tb)
        assert view.visible_values(row, p.values) == want


def test_uneven_doc_axis_rejected():
    p = packed.pack([crdt.Add(1, (0,), "a")])
    stacked = mesh_mod.stack_packed([p, p, p])
    m = mesh_mod.make_mesh(n_docs=8, n_ops=1)
    with pytest.raises(ValueError):
        mesh_mod.batched_materialize(stacked, m)
