"""Replica state-machine conformance suite.

Port of the reference's tests/CRDTreeTest.elm (684 LoC, 15 named cases).
Each case checks the invariant triple the reference checks
(tests/CRDTreeTest.elm:661-684): tree content at a path, the full
chronological log, and the last broadcast operation.
"""
import pytest

from crdt_graph_tpu import (Add, Batch, CRDTree, Delete, InvalidPathError,
                            OperationFailedError, init)
from crdt_graph_tpu.core import operation as op_mod

OFFSET = 2**32


def ops_since_zero(tree):
    return op_mod.to_list(tree.operations_since(0))


def expect_operations(tree, expected):
    assert ops_since_zero(tree) == expected


# -- adds node (CRDTreeTest.elm:56-82) ------------------------------------

def test_add_node():
    tree = init(0).add("a")
    assert tree.get_value([1]) == "a"
    expect_operations(tree, [Add(1, (0,), "a")])
    assert tree.last_operation == Add(1, (0,), "a")


# -- adds after node (CRDTreeTest.elm:85-122) -----------------------------

def test_add_after():
    tree = init(0).add("a").add("b").add_after([1], "c")
    assert tree.get_value([1]) == "a"
    assert tree.get_value([2]) == "b"
    assert tree.get_value([3]) == "c"
    expect_operations(tree, [Add(1, (0,), "a"), Add(2, (1,), "b"),
                             Add(3, (1,), "c")])
    assert tree.last_operation == Add(3, (1,), "c")


# -- adds between nodes (CRDTreeTest.elm:125-160) -------------------------

def test_add_between_nodes():
    tree = init(0).add("a").add("b").add("c").add_after([1], "z")
    assert tree.visible_values() == ["a", "z", "b", "c"]
    expect_operations(tree, [Add(1, (0,), "a"), Add(2, (1,), "b"),
                             Add(3, (2,), "c"), Add(4, (1,), "z")])
    assert tree.last_operation == Add(4, (1,), "z")


# -- batch (CRDTreeTest.elm:163-199) --------------------------------------

def test_batch():
    tree = init(0).batch([lambda t: t.add("a"), lambda t: t.add("b")])
    assert tree.get_value([1]) == "a"
    assert tree.get_value([2]) == "b"
    expect_operations(tree, [Add(1, (0,), "a"), Add(2, (1,), "b")])
    assert tree.last_operation == Batch((Add(1, (0,), "a"),
                                         Add(2, (1,), "b")))


# -- adds branch (CRDTreeTest.elm:202-258) --------------------------------

def test_add_branch():
    tree = init(0).batch([
        lambda t: t.add_branch("a"),
        lambda t: t.add_branch("b"),
        lambda t: t.add_branch("c"),
        lambda t: t.add_branch("d"),
        lambda t: t.add("e"),
        lambda t: t.add("f"),
    ])
    operations = [
        Add(1, (0,), "a"),
        Add(2, (1, 0), "b"),
        Add(3, (1, 2, 0), "c"),
        Add(4, (1, 2, 3, 0), "d"),
        Add(5, (1, 2, 3, 4, 0), "e"),
        Add(6, (1, 2, 3, 4, 5), "f"),
    ]
    assert tree.get_value([1]) == "a"
    assert tree.get_value([1, 2]) == "b"
    assert tree.get_value([1, 2, 3]) == "c"
    assert tree.get_value([1, 2, 3, 4]) == "d"
    assert tree.get_value([1, 2, 3, 4, 5]) == "e"
    assert tree.get_value([1, 2, 3, 4, 6]) == "f"
    expect_operations(tree, operations)
    assert tree.last_operation == Batch(tuple(operations))


# -- delete marks node as tombstone (CRDTreeTest.elm:261-278) -------------

def test_delete():
    tree = init(0).add("a").delete([1])
    assert tree.get_value([1]) is None
    assert tree.last_operation == Delete((1,))


# -- add to deleted branch is absorbed (CRDTreeTest.elm:281-321) ----------

def test_add_to_deleted_branch():
    batch = Batch((Add(1, (0,), "a"), Delete((1,)), Add(2, (1, 0), "b")))
    tree = init(0).apply(batch)
    assert tree.get_value([1]) is None
    expect_operations(tree, [Add(1, (0,), "a"), Delete((1,))])
    assert tree.last_operation == Batch((Add(1, (0,), "a"), Delete((1,))))


# -- applies several remote operations (CRDTreeTest.elm:324-358) ----------

def test_apply_batch():
    batch = Batch((Add(1, (0,), "a"), Add(2, (1,), "b")))
    tree = init(0).apply(batch)
    assert tree.get_value([1]) == "a"
    assert tree.get_value([2]) == "b"
    expect_operations(tree, [Add(1, (0,), "a"), Add(2, (1,), "b")])
    assert tree.last_operation == batch


# -- batch atomicity (CRDTreeTest.elm:482-498) ----------------------------

def test_batch_atomicity():
    batch = Batch((Add(1, (0,), "a"), Add(2, (9,), "b")))
    with pytest.raises(OperationFailedError):
        init(0).apply(batch)


# -- Add is idempotent (CRDTreeTest.elm:361-398) --------------------------

def test_add_is_idempotent():
    batch = Batch(tuple(Add(1, (0,), "a") for _ in range(4)))
    tree = init(0).apply(batch)
    assert tree.get_value([1]) == "a"
    expect_operations(tree, [Add(1, (0,), "a")])
    assert tree.last_operation == Batch((Add(1, (0,), "a"),))


# -- insert at any position (CRDTreeTest.elm:401-440) ---------------------

def test_insertion_between_nodes():
    batch = Batch((Add(1, (0,), "a"), Add(2, (1,), "c"), Add(3, (1,), "b")))
    tree = init(0).apply(batch)
    assert tree.get_value([1]) == "a"
    assert tree.get_value([2]) == "c"
    assert tree.get_value([3]) == "b"
    # higher timestamp lands closer to the anchor
    assert tree.visible_values() == ["a", "b", "c"]
    expect_operations(tree, [Add(1, (0,), "a"), Add(2, (1,), "c"),
                             Add(3, (1,), "b")])
    assert tree.last_operation == batch


# -- inserts node as child of nested branch (CRDTreeTest.elm:443-479) -----

def test_add_leaf():
    batch = Batch((Add(1, (0,), "a"), Add(2, (1, 0), "b"),
                   Add(3, (1, 2), "c")))
    tree = init(0).apply(batch)
    assert tree.get_value([1, 2]) == "b"
    assert tree.get_value([1, 3]) == "c"
    expect_operations(tree, [Add(1, (0,), "a"), Add(2, (1, 0), "b"),
                             Add(3, (1, 2), "c")])
    assert tree.last_operation == batch


# -- Delete is idempotent (CRDTreeTest.elm:501-544) -----------------------

def test_delete_is_idempotent():
    batch = Batch((Add(1, (0,), "a"),) + tuple(Delete((1,))
                                               for _ in range(5)))
    tree = init(0).apply(batch)
    assert tree.get_value([1]) is None
    expect_operations(tree, [Add(1, (0,), "a"), Delete((1,))])
    assert tree.last_operation == Batch((Add(1, (0,), "a"), Delete((1,))))


# -- timestamps carry the replica offset (CRDTreeTest.elm:547-589) --------

def test_timestamps_replica_0():
    tree = init(0).batch([lambda t: t.add("a"), lambda t: t.add("b"),
                          lambda t: t.add("c")])
    expect_operations(tree, [Add(1, (0,), "a"), Add(2, (1,), "b"),
                             Add(3, (2,), "c")])


def test_timestamps_replica_1():
    tree = init(1).batch([lambda t: t.add("a"), lambda t: t.add("b"),
                          lambda t: t.add("c")])
    expect_operations(tree, [
        Add(OFFSET + 1, (0,), "a"),
        Add(OFFSET + 2, (OFFSET + 1,), "b"),
        Add(OFFSET + 3, (OFFSET + 2,), "c"),
    ])


# -- operationsSince (CRDTreeTest.elm:592-658) ----------------------------

@pytest.fixture
def since_tree():
    batch = Batch((
        Add(1, (0,), "a"), Add(2, (1,), "b"), Add(3, (2,), "c"),
        Add(4, (3,), "d"), Delete((3,)), Batch(()),
        Add(5, (4,), "e"), Add(6, (5,), "f"),
    ))
    return init(0).apply(batch)


def test_operations_since_beginning(since_tree):
    assert ops_since_zero(since_tree) == [
        Add(1, (0,), "a"), Add(2, (1,), "b"), Add(3, (2,), "c"),
        Add(4, (3,), "d"), Delete((3,)), Add(5, (4,), "e"),
        Add(6, (5,), "f"),
    ]


def test_operations_since_2(since_tree):
    assert op_mod.to_list(since_tree.operations_since(2)) == [
        Add(2, (1,), "b"), Add(3, (2,), "c"), Add(4, (3,), "d"),
        Delete((3,)), Add(5, (4,), "e"), Add(6, (5,), "f"),
    ]


def test_operations_since_last(since_tree):
    assert op_mod.to_list(since_tree.operations_since(6)) == [
        Add(6, (5,), "f")]


def test_operations_since_unknown_returns_empty(since_tree):
    assert op_mod.to_list(since_tree.operations_since(10)) == []


# -- beyond the reference suite: replica/vector-clock accessors -----------

def test_replica_bookkeeping():
    a = init(1).add("a").add("b")
    b = init(2).apply(a.operations_since(0))
    assert b.last_replica_timestamp(1) == OFFSET + 2
    assert b.last_replica_timestamp(2) == 0  # b originated nothing
    assert b.visible_values() == a.visible_values() == ["a", "b"]
    # remote application must not advance the local clock
    assert b.timestamp == 2 * OFFSET


def test_cursor_semantics():
    tree = init(0).add_branch("a").add_branch("b")
    assert tree.cursor == (1, 2, 0)
    tree = tree.add("c")
    assert tree.cursor == (1, 2, 3)
    assert tree.move_cursor_up().cursor == (1, 2)
    # remote apply restores the local cursor
    remote = Add(5 * OFFSET + 1, (0,), "x")
    assert tree.apply(remote).cursor == tree.cursor


def test_delete_moves_cursor_to_predecessor():
    tree = init(0).add("a").add("b").add("c")
    tree = tree.delete([2])
    assert tree.cursor == (1,)
    assert tree.visible_values() == ["a", "c"]


def test_delete_cursor_lands_on_tombstone_predecessor():
    # the predecessor search walks raw next pointers, tombstones included
    # (Internal/Node.elm:166-183 via CRDTree.elm:199-216): after deleting
    # "a" then "b", the cursor points at a's tombstone path, not at "b".
    tree = init(0).add("a").add("b").delete([1]).delete([2])
    assert tree.cursor == (1,)


def test_set_cursor_missing_raises_not_found():
    from crdt_graph_tpu import NotFound
    with pytest.raises(NotFound):
        init(0).add("a").set_cursor([9])


def test_invalid_path_errors():
    with pytest.raises(InvalidPathError):
        init(0).apply(Add(1, (), "a"))
    with pytest.raises(InvalidPathError):
        init(0).add("a").apply(Add(7, (9, 0), "b"))
    with pytest.raises(OperationFailedError):
        init(0).delete([1])


def test_replica_id_bounded_at_constructive_source():
    """Replica ids are bounded to [0, 2^30) where timestamps are MINTED
    (core/timestamp.make): a larger id would stamp timestamps outside
    the wire's [0, 2^62) integer domain and every peer would reject the
    replica's edits at decode — the failure must surface at init, not
    as remote "malformed add" errors."""
    import crdt_graph_tpu as crdt
    from crdt_graph_tpu import engine as engine_mod
    with pytest.raises(ValueError):
        crdt.init(2 ** 30)
    with pytest.raises(ValueError):
        crdt.init(-1)
    with pytest.raises(ValueError):
        engine_mod.init(2 ** 31)
    t = crdt.init(2 ** 30 - 1)           # the largest legal id works
    t = t.add("x")
    assert t.timestamp == (2 ** 30 - 1) * 2 ** 32 + 1
