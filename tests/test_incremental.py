"""The incremental-apply guarantee: per-op latency independent of document
size (VERDICT r1 item 4; reference bar O(depth·log b + siblings) per op,
Internal/Node.elm:51-104)."""
from crdt_graph_tpu.bench import incremental


def test_per_op_latency_flat_in_doc_size():
    """A 16× bigger document must not make the editor replay per-op p50
    more than ~4× slower (generous margin for CI noise; the measured ratio
    on a quiet box is <2× across a 100× size range)."""
    sizes = (500, 8_000)
    rows = incremental.run(doc_sizes=sizes, n_ops=300)
    p50s = {r["doc_size"]: r["p50_us"] for r in rows}
    assert p50s[8_000] < 4 * max(p50s[500], 10.0), rows


def test_editor_replay_converges_with_oracle():
    """The replay driven through the host path matches an oracle replica
    that merges the same deltas."""
    from crdt_graph_tpu.models.text import TextBuffer

    a = TextBuffer(70, engine="tpu")
    incremental.seed_document(a, 300)
    b = TextBuffer(71, engine="oracle")
    b.sync_from(a)
    incremental.editor_replay(a, 120)
    b.sync_from(a)
    assert a.text() == b.text()
