"""Pipelined commit path (ISSUE 12): the two-stage scheduler/WAL-sync
pipeline and the background tier-maintenance worker.

The contract under test: pipelining is a PURE latency-overlap
optimization — byte-identical serving state and windows vs the
serialized path, the same fsync-before-ack durability point, rollback
of every covered commit (across rounds) on a failed fsync, a flush()
barrier that covers the pipeline's deferred work too, and spill
policies (deferral, hard-cap inline fallback, age, engine-wide
resident bytes) that keep memory bounded without ever touching rows a
failed fsync could still roll back.
"""
import threading
import time

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import engine
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.obs import flight as flight_mod
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.serve import ServingEngine, WalUnavailable

OFF = 2**32


def ts(r, c):
    return r * OFF + c


def chain_ops(r, n, start=1):
    out = []
    prev = ts(r, start - 1) if start > 1 else 0
    for c in range(start, start + n):
        out.append(Add(ts(r, c), (prev,), f"v{r}.{c}"))
        prev = ts(r, c)
    return out


def _submit(eng, doc, ops):
    return eng.submit(doc, json_codec.dumps(Batch(tuple(ops))))


def _engine(ddir, pipeline, **kw):
    kw.setdefault("oplog_hot_ops", 8)
    kw.setdefault("flight", flight_mod.FlightRecorder())
    return ServingEngine(durable_dir=str(ddir), wal_sync="batch",
                         pipeline=pipeline, **kw)


def test_pipeline_ab_bit_identical_fingerprints_and_windows(tmp_path):
    """Interleaved A/B: the same write sequence through the pipelined
    and the serialized engine publishes bit-identical fingerprints
    (seq included — same commit count) and byte-identical sync windows
    at every tier seam, even though the physical spill timing differs
    (background vs inline)."""
    engines = {
        True: _engine(tmp_path / "p1", True),
        False: _engine(tmp_path / "p0", False),
    }
    assert engines[True].sync_worker is not None
    assert engines[False].sync_worker is None
    ops = chain_ops(1, 60)
    for i in range(0, 60, 6):
        for pipe in (True, False):      # interleaved, not sequential
            ok, _ = _submit(engines[pipe], "ab", ops[i:i + 6])
            assert ok
    for eng in engines.values():
        assert eng.flush(30)
    docs = {p: e.get("ab") for p, e in engines.items()}
    s1, s0 = docs[True].snapshot_view(), docs[False].snapshot_view()
    assert s1.fingerprint() == s0.fingerprint()
    assert s1.state_fingerprint() == s0.state_fingerprint()
    assert s1.seq == s0.seq and s1.log_length == s0.log_length
    # windows byte-identical at hot/cold/base seams, pinned both ways
    for since in (0, ts(1, 1), ts(1, 9), ts(1, 31), ts(1, 55)):
        for limit in (0, 7):
            if limit:
                b1, m1 = s1.ops_since_window(since, limit)
                b0, m0 = s0.ops_since_window(since, limit)
                assert b1 == b0 and m1 == m0, (since, limit)
            else:
                assert s1.ops_since_bytes(since) == \
                    s0.ops_since_bytes(since), since
    # the pipelined leg really pipelined (rounds rode the worker) and
    # really deferred maintenance (spills ran on the worker)
    assert engines[True].sync_worker.stats()["jobs_done"] >= 1
    assert engines[True].maintenance.stats()["tasks_done"].get(
        "spill", 0) >= 1
    for e in engines.values():
        e.close()


def test_flush_true_means_pipeline_lanes_drained(tmp_path):
    """ISSUE 12 satellite: flush() == True must mean every queued
    fsync resolved AND the maintenance queue drained — not just that
    the tickets resolved (the old barrier only joined the scheduler
    round)."""
    eng = _engine(tmp_path / "dur", True)
    for i in range(0, 48, 6):
        ok, _ = _submit(eng, "fdoc", chain_ops(1, 48)[i:i + 6])
        assert ok
    assert eng.flush(30)
    # by construction: both lanes idle the moment flush returns True
    assert eng.sync_worker.idle()
    assert eng.maintenance.idle()
    doc = eng.get("fdoc")
    # the deferred spills actually landed (hot tail back under budget)
    assert doc.tree._log.hot_len <= 8 + 8 // 4
    assert doc.safe_extent() == doc.tree.log_length
    # pipelined stage split present on committed records
    rec = [r for r in eng.flight.records()
           if r.outcome == "committed"][-1]
    assert "wal_fsync" in rec.stages_ms
    assert "wal_fsync_queued" in rec.stages_ms
    # telemetry surfaces strict-parse clean
    fams = prom_mod.parse_text(eng.render_prom())
    for fam in ("crdt_sched_pipeline_enabled",
                "crdt_sched_pipeline_rounds_total",
                "crdt_sched_pipeline_commits_synced_total",
                "crdt_sched_pipeline_inflight",
                "crdt_maint_queue_depth", "crdt_maint_tasks_total",
                "crdt_maint_inline_spill_fallbacks_total"):
        assert fam in fams, fam
    sm = eng.scheduler_metrics()
    assert sm["pipeline"]["enabled"] and sm["maintenance"] is not None
    # a paused scheduler with pending work still refuses the barrier
    eng.scheduler.pause()
    t = threading.Thread(
        target=lambda: _submit(eng, "fdoc", chain_ops(9, 1)),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and not len(eng.get("fdoc").queue):
        time.sleep(0.01)
    assert not eng.flush(1.0)
    eng.scheduler.resume()
    t.join(20)
    assert eng.flush(30)
    eng.close()


def test_failed_pipelined_fsync_rolls_back_both_rounds(tmp_path):
    """A failed fsync sheds EVERY commit it dooms — including the
    NEXT round's commit on the same document, which the scheduler
    already merged while the fsync was in flight (it causally sits on
    top of the doomed ops).  Both clients get the honest 503, the
    tree rolls back to the earliest doomed commit's pre-state, and
    the server keeps serving."""
    eng = _engine(tmp_path / "dur", True, submit_timeout_s=30.0)
    ok, _ = _submit(eng, "doc", chain_ops(1, 4))
    assert ok
    assert eng.flush(30)
    doc = eng.get("doc")
    vals = doc.snapshot()

    real_sync = doc.wal.sync
    real_sync_begin = doc.wal.sync_begin
    release = threading.Event()

    def blocked_sync(*_a, **_k):
        # hold round N's fsync open until round N+1 has computed,
        # then fail it — the deterministic cross-round overlap
        release.wait(20)
        raise OSError(28, "No space left on device")

    # completion-driven lanes enter the WAL at sync_begin(); the single
    # and threaded lanes call sync() — block both seams so the injected
    # failure fires regardless of GRAFT_WAL_SYNC_BACKEND
    doc.wal.sync = blocked_sync
    doc.wal.sync_begin = blocked_sync
    results = {}

    def writer(name, ops):
        try:
            results[name] = _submit(eng, "doc", ops)
        except WalUnavailable:
            results[name] = "shed"

    ta = threading.Thread(target=writer,
                          args=("a", chain_ops(1, 4, start=5)),
                          daemon=True)
    ta.start()
    # wait until round N's job is in flight (worker blocked in sync)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and eng.sync_worker.stats()["inflight"] == 0:
        time.sleep(0.01)
    assert eng.sync_worker.stats()["inflight"] == 1
    tb = threading.Thread(target=writer,
                          args=("b", chain_ops(1, 4, start=9)),
                          daemon=True)
    tb.start()
    # round N+1 must have merged b's ops on top of the doomed round
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and doc.tree.log_length < 12:
        time.sleep(0.01)
    assert doc.tree.log_length == 12
    doc.wal.sync = real_sync
    doc.wal.sync_begin = real_sync_begin
    release.set()
    ta.join(30)
    tb.join(30)
    assert results == {"a": "shed", "b": "shed"}, results
    # rolled back to the EARLIEST doomed commit's pre-state
    assert doc.tree.log_length == 4
    assert doc.snapshot() == vals
    assert eng.scheduler.is_alive()
    assert eng.counters.snapshot().get("pipeline_shed_commits", 0) >= 2
    # disk back: the whole chain re-applies for real
    ok, _ = _submit(eng, "doc", chain_ops(1, 8, start=5))
    assert ok
    assert doc.tree.log_length == 12
    assert eng.flush(30)
    eng.close()


def test_age_spill_policy_drains_idle_hot_tails(tmp_path, monkeypatch):
    """GRAFT_OPLOG_HOT_AGE_S: an idle document's hot tail is swept to
    cold by the maintenance worker's policy tick even though it never
    crossed the size budget."""
    monkeypatch.setenv("GRAFT_OPLOG_HOT_AGE_S", "0.2")
    eng = _engine(tmp_path / "dur", True, oplog_hot_ops=4096)
    ok, _ = _submit(eng, "aged", chain_ops(1, 12))
    assert ok
    doc = eng.get("aged")
    assert doc.tree._log.tiered_extent == 0    # under the size budget
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and doc.tree._log.tiered_extent < 12:
        time.sleep(0.05)
    assert doc.tree._log.tiered_extent == 12, \
        doc.tree._log.telemetry()
    assert doc.tree._log.hot_len == 0
    assert eng.maintenance.stats()["policy_age_spills"] >= 1
    # serving state untouched by the sweep
    assert len(doc.snapshot()) == 12
    eng.close()


def test_resident_budget_policy_spills_largest_first(tmp_path,
                                                     monkeypatch):
    """GRAFT_OPLOG_RESIDENT_MB: when the engine-wide hot-resident
    total exceeds the budget, the policy drains the LARGEST hot tails
    first."""
    monkeypatch.setenv("GRAFT_OPLOG_RESIDENT_MB", "1")
    eng = _engine(tmp_path / "dur", True, oplog_hot_ops=1 << 20)
    big = [Add(ts(1, c), (ts(1, c - 1) if c > 1 else 0,), "x" * 200)
           for c in range(1, 8001)]
    for i in range(0, 8000, 1000):
        ok, _ = _submit(eng, "big", big[i:i + 1000])
        assert ok
    ok, _ = _submit(eng, "small", chain_ops(2, 10))
    assert ok
    bigdoc, smalldoc = eng.get("big"), eng.get("small")
    # (no pre-assert on hot_bytes: the policy tick may already have
    # begun draining it — exactly the behavior under test)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and bigdoc.tree._log.tiered_extent == 0:
        time.sleep(0.05)
    assert bigdoc.tree._log.tiered_extent > 0, \
        bigdoc.tree._log.telemetry()
    assert eng.maintenance.stats()["policy_resident_spills"] >= 1
    # the small doc was not the victim
    assert smalldoc.tree._log.tiered_extent == 0
    eng.close()


def test_hard_cap_inline_spill_fallback_bounds_memory(tmp_path,
                                                      monkeypatch):
    """When the maintenance worker lags (here: its queue refuses), a
    hot tail past the hard cap spills INLINE on the scheduler —
    resident memory stays bounded no matter what, and the fallback is
    counted."""
    monkeypatch.setenv("GRAFT_OPLOG_HOT_HARD_MULT", "2")
    eng = _engine(tmp_path / "dur", True)       # hot_ops=8, cap=16
    maint = eng.maintenance
    monkeypatch.setattr(maint, "enqueue",
                        lambda *a, **k: False)  # worker "full"
    for i in range(0, 60, 6):
        ok, _ = _submit(eng, "cap", chain_ops(1, 60)[i:i + 6])
        assert ok
    doc = eng.get("cap")
    assert maint.stats()["inline_spill_fallbacks"] >= 1
    # bounded: the tail never grew far past the cap
    assert doc.tree._log.hot_len <= 16 + 6
    assert doc.tree._log.tiered_extent > 0
    eng.close()


def test_pipeline_recovery_matches_serialized(tmp_path):
    """A pipelined engine's durable dir restores to the same serving
    state a serialized engine's does — recovery is mode-blind."""
    dirs = {p: tmp_path / f"r{int(p)}" for p in (True, False)}
    vals = {}
    for pipe, d in dirs.items():
        eng = _engine(d, pipe)
        for i in range(0, 30, 5):
            ok, _ = _submit(eng, "rdoc", chain_ops(1, 30)[i:i + 5])
            assert ok
        assert eng.flush(30)
        vals[pipe] = eng.get("rdoc").snapshot()
        eng.close()
    assert vals[True] == vals[False]
    restored = {}
    for pipe, d in dirs.items():
        # recover each dir under the OPPOSITE mode: on-disk state is
        # mode-portable (same WAL format, same tiers)
        eng = _engine(d, not pipe)
        doc = eng.get("rdoc", create=False)
        assert doc is not None and doc.recovered
        restored[pipe] = doc.snapshot()
        sv = doc.snapshot_view()
        restored[f"fp{pipe}"] = sv.state_fingerprint()
        eng.close()
    assert restored[True] == restored[False] == vals[True]
    assert restored["fpTrue"] == restored["fpFalse"]


@pytest.mark.slow
def test_bench_pipeline_headline_full(tmp_path):
    """The committed-artifact run (BENCH_PIPELINE_r01_cpu.json shape,
    reduced): the pipelined leg beats the serialized baseline on
    acked throughput with zero oracle violations both legs.  The
    committed artifact holds the honest ≥1.5× number; the reduced
    gate is looser against 1-core scheduling noise."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "_bench_pipeline_headline",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_pipeline_headline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(out_path=str(tmp_path / "BENCH_PIPELINE_test.json"),
                  n_sessions=32, n_docs=32, writes_per_session=4,
                  rounds=1)
    best = out["best"]
    for leg in ("pipelined", "serialized"):
        assert best[leg]["violations"] == 0
        assert best[leg]["writes_acked"] >= 32 * 4
        assert best[leg]["wal"]["fsyncs"] >= 1, leg
    # correctness is the hard gate here; the throughput bound is a
    # broken-pipeline tripwire only (the committed artifact holds the
    # honest ≥1.5× A/B — a contended CI box can squeeze the reduced
    # shape's overlap to near-parity, which must not read as red)
    assert out["pipelined_vs_serialized_speedup"] >= 0.8
    # and the pipeline really ran
    assert best["pipelined"]["pipeline"]["commits_synced"] > 0


def test_engine_without_durability_still_gets_maintenance(tmp_path):
    """Non-durable serving engines (ephemeral tiering) have no WAL to
    pipeline but still move spills off the scheduler thread."""
    eng = ServingEngine(oplog_hot_ops=8,
                        flight=flight_mod.FlightRecorder())
    assert eng.sync_worker is None and eng.maintenance is not None
    for i in range(0, 40, 5):
        ok, _ = _submit(eng, "edoc", chain_ops(1, 40)[i:i + 5])
        assert ok
    assert eng.flush(30)
    doc = eng.get("edoc")
    assert doc.tree._log.tiered_extent > 0
    assert eng.maintenance.stats()["tasks_done"].get("spill", 0) >= 1
    assert len(doc.snapshot()) == 40
    eng.close()
