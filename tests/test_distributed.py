"""Multi-PROCESS distribution smoke test (SURVEY §5 A8; VERDICT r2 task 5).

Two real OS processes, each with 4 virtual CPU devices, joined by
``jax.distributed.initialize`` into one 8-device runtime; the docs mesh
axis spans both processes and each feeds only its local documents through
``parallel.distributed.host_local_docs_to_global``.  This exercises the
actual multi-controller code path (process_count == 2), not the
single-process no-op fallbacks.
"""
import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_fleet_merge():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # kill BOTH workers, then drain their pipes so the hung worker's
        # output (distributed-init barrier logs) makes it into the failure
        for p in procs:
            p.kill()
        drained = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                out = "<unreadable>"
            drained.append(f"--- worker rc={p.returncode} ---\n{out}")
        pytest.fail("distributed workers timed out:\n" + "\n".join(drained))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
    assert "worker 0: OK" in outs[0]
    assert "worker 1: OK" in outs[1]
