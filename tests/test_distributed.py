"""Multi-PROCESS distribution smoke test (SURVEY §5 A8; VERDICT r2 task 5).

Two real OS processes, each with 4 virtual CPU devices, joined by
``jax.distributed.initialize`` into one 8-device runtime; the docs mesh
axis spans both processes and each feeds only its local documents through
``parallel.distributed.host_local_docs_to_global``.  This exercises the
actual multi-controller code path (process_count == 2), not the
single-process no-op fallbacks.
"""
import os
import socket
import subprocess
import sys
import threading

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_distributed_worker.py")
_FLEET = os.path.join(os.path.dirname(__file__), "_fleet_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_fleet_merge():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # kill BOTH workers, then drain their pipes so the hung worker's
        # output (distributed-init barrier logs) makes it into the failure
        for p in procs:
            p.kill()
        drained = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                out = "<unreadable>"
            drained.append(f"--- worker rc={p.returncode} ---\n{out}")
        pytest.fail("distributed workers timed out:\n" + "\n".join(drained))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
    assert "worker 0: OK" in outs[0]
    assert "worker 1: OK" in outs[1]


def _spawn_fleet(phase, coord_port, http_port, pid, ckpt_dir):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # fast failure detection: this harness WANTS the injected death
    # observed promptly — the default detector (10 s × 10 misses)
    # would stall the surviving gang member ~100 s per phase, which
    # was most of this test's wall time (tier-1 budget satellite)
    env["GRAFT_DIST_HEARTBEAT_S"] = "1"
    env["GRAFT_DIST_MAX_MISSING"] = "4"
    return subprocess.Popen(
        [sys.executable, _FLEET, phase, str(coord_port), str(http_port),
         str(pid), ckpt_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _join(procs, timeout=420):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        drained = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                out = "<unreadable>"
            drained.append(f"--- rc={p.returncode} ---\n{out}")
        pytest.fail("fleet workers timed out:\n" + "\n".join(drained))
    return outs


def test_fleet_kill_restart_rejoin(tmp_path):
    """VERDICT r4 next-8: kill one process of a running compute fleet
    mid-session, detect the death (exit code — the controller's failure
    detector), restart, and rejoin via snapshot + /ops?since= to full
    convergence; then a fresh gang re-forms from the replicated state
    alone.  The data plane is the replication service (the fleet's
    durable truth, reference recovery semantics CRDTree.elm:408-418);
    the compute plane is jax.distributed whose collectives are
    gang-scheduled — mid-collective death means gang restart, which the
    refleet phase models."""
    from crdt_graph_tpu.service import make_server

    srv = make_server(port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        # phase 1: both fleet workers run; worker 1 dies mid-session
        coord = _free_port()
        procs = [_spawn_fleet("run", coord, srv.server_port, pid,
                              str(tmp_path)) for pid in (0, 1)]
        outs = _join(procs)
        assert procs[1].returncode == 17, outs[1]     # died as injected
        # the survivor finishes its WORK (merge verified, edits pushed)
        # but cannot cleanly outlive the gang: jax's coordination
        # service detects the dead peer by heartbeat timeout and fails
        # the shutdown barrier — the runtime's own failure detector,
        # observable to the controller alongside the exit codes
        assert "fleet merge pre-crash OK" in outs[0]
        assert "worker 0: OK" in outs[0]
        assert procs[0].returncode == 0 \
            or "heartbeat timeout" in outs[0] \
            or "Shutdown barrier" in outs[0], outs[0]
        assert "worker 1: dying mid-session" in outs[1]
        doc = srv.store.get("fleet", create=False)
        # server holds worker 0's 40 edits + worker 1's pushed half only
        assert len(doc.tree.visible_values()) == 60
        assert os.path.exists(str(tmp_path / "w1.npz"))

        # phase 2: controller detected rc=17; replacement rejoins
        rec = _spawn_fleet("rejoin", 0, srv.server_port, 1,
                           str(tmp_path))
        out = _join([rec])[0]
        assert rec.returncode == 0, out
        assert "rejoined: OK" in out
        assert len(doc.tree.visible_values()) == 80
        assert doc.metrics()["dup_absorbed"] >= 60    # idempotent re-push

        # phase 3: a brand-new gang re-forms purely from the service
        coord2 = _free_port()
        procs = [_spawn_fleet("refleet", coord2, srv.server_port, pid,
                              str(tmp_path)) for pid in (0, 1)]
        outs = _join(procs)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out
        assert "fleet merge post-restart OK" in outs[0]
    finally:
        srv.shutdown()
        srv.server_close()
