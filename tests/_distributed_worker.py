"""Worker process for the multi-process distribution test (A8).

Launched twice by tests/test_distributed.py: each process owns 4 virtual
CPU devices and 4 of the 8 documents; ``jax.distributed.initialize`` wires
the two processes into one runtime, the docs mesh axis spans the fleet,
and each process feeds only its local documents through
``host_local_docs_to_global`` — the exact multi-host recipe
parallel/distributed.py documents, exercised for real (num_processes=2).

Backend honesty note (ISSUE 3 triage): this image's jaxlib CPU client
refuses to EXECUTE any cross-process computation ("Multiprocess
computations aren't implemented on the CPU backend"), so the pieces a
CPU fleet can really run are what this worker pins: the 2-process
runtime (process_count/device enumeration), the global mesh + global
array ASSEMBLY (make_array_from_process_local_data), the per-shard
merge compute on each process's addressable devices, and cross-process
CONVERGENCE via the coordination-service KV exchange (every process
verifies every document's fingerprint, its own and its peer's, against
a local single-device merge).  On a TPU pod the same code path runs the
global jit for real; the compute split here is the documented CPU
degradation, not a weaker check of convergence.

Usage: python tests/_distributed_worker.py PORT PROCESS_ID
"""
import os
import sys

PORT = sys.argv[1]
PID = int(sys.argv[2])

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import jax  # noqa: E402

# the axon sitecustomize registers its TPU plugin before this script body
# runs; env alone is not enough (see utils/hostenv.py) — pin the platform
# at the config level before any backend initialises
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu.bench import workloads  # noqa: E402
from crdt_graph_tpu.ops import merge  # noqa: E402
from crdt_graph_tpu.parallel import distributed, mesh as mesh_mod  # noqa: E402

N_PROCS = 2
DOCS_PER_PROC = 4
N_PAD = 64


def doc_ops(doc_id: int):
    """Deterministic per-document workload whose CONTENT differs for every
    doc id (different replica counts → different timestamp sets), so a
    shard permutation or doc mix-up is detectable."""
    ops = workloads.chain_workload(2 + doc_id, 60)
    return mesh_mod._pad_ops_to(ops, N_PAD)


def _fingerprints(table):
    """Per-doc content scalar: sum of visible timestamps (mod a prime)."""
    import jax.numpy as jnp
    vis = table.visible
    ts = jnp.where(vis, table.ts % jnp.int64(1000003), 0)
    return jnp.sum(ts, axis=-1), table.num_visible


def main() -> None:
    distributed.initialize(f"127.0.0.1:{PORT}", num_processes=N_PROCS,
                           process_id=PID)
    assert jax.process_count() == N_PROCS, jax.process_count()
    assert len(jax.devices()) == N_PROCS * DOCS_PER_PROC
    assert len(jax.local_devices()) == DOCS_PER_PROC

    mesh = distributed.global_device_mesh(n_ops=1)
    assert mesh.shape[mesh_mod.DOCS_AXIS] == N_PROCS * DOCS_PER_PROC

    # this process's local document shard
    my_docs = range(PID * DOCS_PER_PROC, (PID + 1) * DOCS_PER_PROC)
    local = [doc_ops(d) for d in my_docs]
    stacked = {k: np.stack([d[k] for d in local]) for k in local[0]}
    # global-array assembly is exercised for real (the fleet wiring the
    # TPU path depends on)...
    global_ops = distributed.host_local_docs_to_global(stacked, mesh)
    for v in global_ops.values():
        assert v.shape[0] == N_PROCS * DOCS_PER_PROC
        assert not v.is_fully_addressable     # really spans the fleet

    # ...while the merge compute runs on this process's addressable
    # devices (see module docstring: this jaxlib's CPU client cannot
    # execute cross-process computations; a TPU fleet runs
    # batched_materialize(global_ops, mesh) here instead)
    from jax.sharding import Mesh
    local_mesh = Mesh(
        np.asarray(jax.local_devices()).reshape(DOCS_PER_PROC, 1),
        (mesh_mod.DOCS_AXIS, mesh_mod.OPS_AXIS))
    table = mesh_mod.batched_materialize(stacked, local_mesh)

    # gather the per-doc scalars over the coordination service's KV
    # store (the control plane every initialized runtime carries;
    # multihost_utils.process_allgather would need the data plane)
    fp_l, nv_l = jax.jit(_fingerprints)(table)
    base = PID * DOCS_PER_PROC
    fp = distributed.allgather_scalars(
        "fpv1", {base + i: int(v)
                 for i, v in enumerate(np.asarray(fp_l).tolist())})
    num_visible = distributed.allgather_scalars(
        "nvv1", {base + i: int(v)
                 for i, v in enumerate(np.asarray(nv_l).tolist())})

    # every process verifies every document against a local single-device
    # merge (documents are tiny; the oracle-parity of the kernel itself is
    # pinned elsewhere — here we check the fleet assembly didn't mix,
    # permute, or duplicate docs: the timestamp-sum fingerprint differs
    # per doc by construction)
    wants = []
    for d in range(N_PROCS * DOCS_PER_PROC):
        expected = merge.materialize(
            {k: jax.device_put(v) for k, v in doc_ops(d).items()})
        efp, env_ = jax.jit(_fingerprints)(expected)
        want_fp = int(np.asarray(jax.device_get(efp)))
        want_nv = int(np.asarray(jax.device_get(env_)))
        wants.append(want_fp)
        assert int(num_visible[d]) == want_nv, (d, num_visible[d], want_nv)
        assert int(fp[d]) == want_fp, (d, int(fp[d]), want_fp)
    assert len(set(wants)) == N_PROCS * DOCS_PER_PROC, \
        "per-doc fingerprints must be distinct for the mix-up check"

    print(f"worker {PID}: OK ({sum(num_visible.values())} visible nodes "
          f"across {N_PROCS * DOCS_PER_PROC} docs)", flush=True)


if __name__ == "__main__":
    main()
