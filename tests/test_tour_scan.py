"""The pallas fused prefix-sum sweep must equal the lax cumsums
(ops/tour_scan.py) — interpret-mode Mosaic on CPU, every lane and
padding shape, including the segment-carry resets between the token
stream and the weight lanes (ISSUE 3 satellite: bit-identity for every
new pallas kernel)."""
import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from crdt_graph_tpu.ops import tour_scan  # noqa: E402


def _check(boundary, weights):
    want_b, want_w = tour_scan._lax_prefix(jnp.asarray(boundary),
                                           jnp.asarray(weights))
    got_b, got_w = tour_scan.prefix_sums(jnp.asarray(boundary),
                                         jnp.asarray(weights),
                                         interpret=True)
    np.testing.assert_array_equal(np.asarray(got_b), np.asarray(want_b))
    np.testing.assert_array_equal(np.asarray(got_w), np.asarray(want_w))


@pytest.mark.parametrize("m,kw", [(2048, 1), (2048, 2), (5000, 1),
                                  (5000, 2), (16384, 2), (33000, 1)])
def test_interpret_matches_lax(m, kw):
    """Random 0/1 lanes at T = 2M, tile-aligned and ragged sizes."""
    rng = np.random.default_rng(m * 7 + kw)
    boundary = rng.integers(0, 2, 2 * m).astype(np.int32)
    weights = rng.integers(0, 2, (kw, m)).astype(np.int32)
    _check(boundary, weights)


def test_all_ones_and_all_zeros():
    """Degenerate lanes: the carry chain must stay exact across every
    tile (prefix reaches T > the in-tile matmul bound — exactness rides
    the int32 carry, not the f32 contraction)."""
    m = 9000
    _check(np.ones(2 * m, np.int32), np.zeros((2, m), np.int32))
    _check(np.zeros(2 * m, np.int32), np.ones((1, m), np.int32))


def test_segment_isolation():
    """A boundary lane ending mid-tile must not leak its carry into the
    first weight lane (static segment resets)."""
    m = 3000           # T = 6000: last boundary tile is half-padding
    boundary = np.ones(2 * m, np.int32)
    weights = np.zeros((2, m), np.int32)
    weights[0, 0] = 1
    got_b, got_w = tour_scan.prefix_sums(jnp.asarray(boundary),
                                         jnp.asarray(weights),
                                         interpret=True)
    assert int(got_w[0, 0]) == 1 and int(got_w[0, -1]) == 1
    assert int(got_w[1, -1]) == 0
    assert int(got_b[-1]) == 2 * m


def test_small_input_takes_lax_path():
    """Below one tile the wrapper returns the lax scans outright."""
    b = np.ones(64, np.int32)
    w = np.ones((1, 32), np.int32)
    got_b, got_w = tour_scan.prefix_sums(jnp.asarray(b), jnp.asarray(w),
                                         interpret=True)
    np.testing.assert_array_equal(np.asarray(got_b),
                                  np.cumsum(b).astype(np.int32))
    np.testing.assert_array_equal(np.asarray(got_w)[0],
                                  np.cumsum(w[0]).astype(np.int32))
