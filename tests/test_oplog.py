"""Columnar op log (crdt_graph_tpu/oplog.py) — VERDICT r4 next-5.

The log is the replica state, so its columnar form must be
indistinguishable from the object list it replaced: same iteration
order, same ``operations_since`` suffixes, same rollback, same
checkpoint round trips — while the bulk ingest path builds zero per-op
Python objects (pinned here by counting materializations).
"""
import io
import json

import numpy as np
import pytest

from crdt_graph_tpu import engine
from crdt_graph_tpu.codec import packed as packed_mod
from crdt_graph_tpu.core.operation import Add, Batch, Delete
from crdt_graph_tpu.core import operation as op_mod
from crdt_graph_tpu.oplog import OpLog, PackedBatch


def ts(r, c):
    return r * 2**32 + c


def chain_ops(r, n, start=1):
    """n adds by replica r, each anchored on the previous; a start > 1
    continues the chain (anchoring on ts(r, start-1)), so split chains
    carry cross-batch references."""
    out = []
    prev = ts(r, start - 1) if start > 1 else 0
    for c in range(start, start + n):
        out.append(Add(ts(r, c), (prev,), f"v{r}.{c}"))
        prev = ts(r, c)
    return out


def test_mixed_segments_iterate_in_order():
    objs1 = chain_ops(1, 5)
    packed_seg = packed_mod.pack(chain_ops(2, 7), max_depth=4)
    objs2 = [Delete((ts(1, 5),))]
    log = OpLog(objs1)
    log.extend_packed(packed_seg)
    log.extend(objs2)
    expect = objs1 + packed_mod.unpack(packed_seg) + objs2
    assert len(log) == len(expect)
    assert list(log) == expect
    assert log[5] == expect[5]
    assert log[-1] == expect[-1]
    assert log[3:9] == expect[3:9]


def test_truncate_inside_packed_segment():
    log = OpLog(chain_ops(1, 3))
    p = packed_mod.pack(chain_ops(2, 6), max_depth=4)
    log.extend_packed(p)
    full = list(log)
    log.truncate(5)
    assert len(log) == 5
    assert list(log) == full[:5]
    # the packed tail beyond the cut never reappears
    log.extend([Delete((ts(1, 1),))])
    assert list(log) == full[:5] + [Delete((ts(1, 1),))]


def test_index_of_add_spans_segments():
    objs = chain_ops(1, 4)
    p = packed_mod.pack(chain_ops(2, 4), max_depth=4)
    log = OpLog(objs)
    log.extend_packed(p)
    assert log.index_of_add(ts(1, 3)) == 2
    assert log.index_of_add(ts(2, 2)) == 5
    assert log.index_of_add(ts(9, 9)) is None
    # deletes never terminate the scan (only Adds index)
    log.extend([Delete((ts(2, 4),))])
    assert log.index_of_add(ts(2, 4)) == 7


def test_to_packed_matches_full_pack():
    objs = chain_ops(1, 5)
    tail = chain_ops(2, 5)
    log = OpLog(objs)
    log.extend_packed(packed_mod.pack(tail, max_depth=4))
    a = log.to_packed(max_depth=4)
    b = packed_mod.pack(objs + tail, max_depth=4)
    assert a.num_ops == b.num_ops
    for name in ("kind", "ts", "parent_ts", "anchor_ts", "depth"):
        np.testing.assert_array_equal(
            getattr(a, name)[:a.num_ops], getattr(b, name)[:b.num_ops])
    assert packed_mod.unpack(a) == packed_mod.unpack(b)
    assert a.hints_vouched
    assert packed_mod.verify_hints(a)


def test_slice_step_rejected():
    log = OpLog(chain_ops(1, 6))
    with pytest.raises(ValueError):
        log[::2]
    with pytest.raises(ValueError):
        log[::-1]


def test_concat_many_matches_single_pack():
    """N-way union (which concat() is now the 2-part case of) must be
    indistinguishable from packing the flat op list: same rows, hints
    that VERIFY (cross-part refs resolved — replica 1's chain continues
    in part 3, referencing part 1), vouch preserved."""
    flat = chain_ops(1, 5) + chain_ops(2, 3) + chain_ops(1, 4, start=6)
    parts = [packed_mod.pack(chain_ops(1, 5), max_depth=4),
             packed_mod.pack(chain_ops(2, 3), max_depth=4),
             packed_mod.pack(chain_ops(1, 4, start=6), max_depth=4)]
    many = packed_mod.concat_many(parts)
    one = packed_mod.pack(flat, max_depth=4)
    assert many.num_ops == one.num_ops == 12
    assert packed_mod.unpack(many) == flat
    # part 3's first op anchors on ts(1,5) — a CROSS-PART ref that must
    # carry a verified hint for the union to stay exhaustive
    assert many.anchor_pos[8] == 4
    assert many.hints_vouched
    assert packed_mod.verify_hints(many)
    np.testing.assert_array_equal(many.ts_rank[:12], one.ts_rank[:12])


def test_packed_batch_is_lazy_and_counts():
    p = packed_mod.pack(chain_ops(3, 8), max_depth=4)
    pb = PackedBatch(p)
    assert op_mod.count(pb) == 8
    assert pb._ops is None, "count must not materialize"
    assert isinstance(pb, Batch)
    # equality across the class boundary, both directions
    plain = Batch(tuple(packed_mod.unpack(p)))
    assert pb == plain and plain == pb
    assert pb.ops == plain.ops


def test_bulk_ingest_stays_columnar():
    """A bootstrap-size apply_packed extends the log by a COLUMN
    segment and wraps the result lazily — no object materialization."""
    ops = chain_ops(1, 2000)
    pnew = packed_mod.pack(ops, max_depth=4)
    t = engine.init(0)
    t.apply_packed(pnew)
    assert isinstance(t.last_operation, PackedBatch)
    assert t.last_operation._ops is None
    assert op_mod.count(t.last_operation) == 2000
    seg = t._log._segs[-1]
    assert not isinstance(seg, list), "log tail must be a column segment"
    # suffix pull materializes only the asked-for rows
    suffix = t.operations_since(ts(1, 1999))
    assert [op.ts for op in suffix.ops] == [ts(1, 1999), ts(1, 2000)]
    assert t.operations_since(ts(7, 1)) == Batch(())


def test_bulk_ingest_partial_absorb_columnar():
    """Redelivered rows absorb; only the applied subset enters the log
    (as columns), and the document matches the object-path result."""
    ops = chain_ops(1, 1500)
    t = engine.init(0)
    t.apply_packed(packed_mod.pack(ops, max_depth=4))
    # redeliver the tail 1100 plus 1100 genuinely new ops
    new = chain_ops(1, 1100, start=1501)
    t.apply_packed(packed_mod.pack(ops[-1100:] + new, max_depth=4))
    assert t.log_length == 2600
    assert op_mod.count(t.last_operation) == 1100
    oracle = engine.init(0)
    oracle.apply(op_mod.from_list(ops + new))
    assert t.visible_values() == oracle.visible_values()
    # clocks agree with the object path
    assert t._replicas == oracle._replicas


def test_bulk_reject_reports_first_failing_op():
    t = engine.init(0)
    t.apply_packed(packed_mod.pack(chain_ops(1, 1200), max_depth=4))
    bad = chain_ops(2, 1100) + [Add(ts(3, 1), (ts(9, 9),), "orphan")]
    with pytest.raises(engine.OperationFailedError):
        t.apply_packed(packed_mod.pack(bad, max_depth=4))
    assert t.log_length == 1200, "rejected batch must not mutate state"


def test_checkpoint_span_roundtrip_columnar():
    """Binary checkpoint after a columnar commit takes the O(1)
    last_op_span path and restores to an equal tree."""
    t = engine.init(0)
    t.apply_packed(packed_mod.pack(chain_ops(1, 1500), max_depth=4))
    buf = io.BytesIO()
    t.checkpoint_packed(buf, compress=False)
    buf.seek(0)
    z = np.load(buf)
    meta = json.loads(bytes(z["meta"]).decode())
    assert meta["last_op_span"] == [0, 1500]
    assert "last_operation" not in meta
    buf.seek(0)
    r = engine.TpuTree.restore_packed(buf)
    assert r.log_length == 1500
    assert isinstance(r.last_operation, PackedBatch)
    assert r.visible_values() == t.visible_values()
    assert list(r._log) == list(t._log)


def test_corrupt_hint_checkpoint_never_reaches_cond_free(monkeypatch):
    """VERDICT r4 next-7: a checkpoint whose persisted hint columns are
    corrupt (but still vouched) must be repaired at restore BEFORE any
    merge — with the GRAFT_DEBUG_VOUCH tripwire UNSET, so the guarantee
    holds in production mode, not just under the test harness."""
    monkeypatch.delenv("GRAFT_DEBUG_VOUCH", raising=False)
    t = engine.init(0)
    t.apply_packed(packed_mod.pack(chain_ops(1, 1500), max_depth=4))
    buf = io.BytesIO()
    t.checkpoint_packed(buf, compress=False)
    # tamper: point every parent/anchor hint at row 0 and shuffle ranks,
    # keeping the vouch flag — a hand-edited / bit-rotted snapshot
    buf.seek(0)
    z = np.load(buf)
    cols = {k: z[k].copy() for k in z.files}
    n = len(cols["kind"])
    cols["anchor_pos"][:] = 0
    cols["parent_pos"][:] = 0
    cols["ts_rank"][:n // 2] = np.arange(n // 2, dtype=np.int32)[::-1]
    evil = io.BytesIO()
    np.savez(evil, **cols)
    evil.seek(0)
    r = engine.TpuTree.restore_packed(evil)
    # the restore audit rebuilt the hints: the packed state verifies,
    # stays vouched (cond-free mode is SAFE again), and a follow-up
    # merge converges with the object path
    assert r._packed.hints_vouched
    assert packed_mod.verify_hints(r._packed)
    more = chain_ops(2, 1100)
    r.apply_packed(packed_mod.pack(more, max_depth=4))
    oracle = engine.init(0)
    oracle.apply(op_mod.from_list(chain_ops(1, 1500) + more))
    assert r.visible_values() == oracle.visible_values()


def test_wire_ingest_audit_repairs_bad_parser_hints(monkeypatch):
    """VERDICT r4 next-7, wire face: if the native parser ever emitted
    wrong hint columns, the default-on ingest audit rebuilds them before
    the batch can reach the cond-free kernel mode."""
    from crdt_graph_tpu import native
    if not native.available():
        pytest.skip("native codec unavailable")
    monkeypatch.delenv("GRAFT_DEBUG_VOUCH", raising=False)
    real = native.load().parse_pack

    def corrupting(payload, max_depth):
        cols = dict(real(payload, max_depth))
        bad = np.frombuffer(cols["anchor_pos"], np.int32).copy()
        bad[:] = 0          # simulated parser bug
        cols["anchor_pos"] = bad.tobytes()
        return cols

    import types
    monkeypatch.setattr(native, "_mod",
                        types.SimpleNamespace(parse_pack=corrupting))
    from crdt_graph_tpu.codec import json_codec
    ops = chain_ops(1, 600)
    p = native.parse_pack(json_codec.dumps(op_mod.from_list(ops)))
    assert p.hints_vouched
    assert packed_mod.verify_hints(p), "ingest audit must repair hints"
    assert packed_mod.unpack(p) == ops


def test_windowed_suffix_matches_operations_since_at_every_boundary():
    """The anti-entropy window (``engine.packed_since_window``,
    cluster/antientropy.py's wire) must agree with the reference
    ``operations_since`` suffix at EVERY Add boundary — including the
    exactly-equal-timestamp case, where the terminator row itself is
    served inclusively (the puller's overlap absorbs as a duplicate).
    Chained bounded windows must reassemble the identical suffix."""
    from crdt_graph_tpu.codec import json_codec

    ops = []
    for r in (1, 2):
        ops.extend(chain_ops(r, 9))
    # interleave: replica order in the LOG is application order
    t = engine.init(0)
    mixed = [op for pair in zip(ops[:9], ops[9:]) for op in pair]
    for op in mixed:
        t.apply(op)
    t.apply(Delete((ts(1, 9),)))            # trailing delete tail
    full = Batch(tuple(t.operations_since(0).ops))
    p = packed_mod.pack(full.ops, max_depth=4)

    for boundary in [0] + [op.ts for op in full.ops
                           if isinstance(op, Add)]:
        want = t.operations_since(boundary)
        wire, meta = engine.packed_since_window(p, boundary, 0)
        assert meta["found"] and not meta["more"]
        got = json_codec.loads(wire.decode())
        assert tuple(got.ops) == tuple(want.ops), boundary
        # bounded windows chain back into the same suffix
        since, chained = boundary, []
        for _ in range(40):
            wire, meta = engine.packed_since_window(p, since, 4)
            chained.extend(json_codec.loads(wire.decode()).ops)
            if meta["next_since"] is not None:
                since = meta["next_since"]
            if not meta["more"]:
                break
        # drop inclusive-terminator overlap rows, keeping first sight
        seen, dedup = set(), []
        for op in chained:
            key = (op.ts if isinstance(op, Add) else ("d", op.path))
            if key not in seen:
                seen.add(key)
                dedup.append(op)
        assert tuple(dedup) == tuple(want.ops), boundary
    # a timestamp the log never contained is reported, not silently
    # treated as "from 0" (the puller resets its own mark)
    _, meta = engine.packed_since_window(p, ts(5, 5), 4)
    assert not meta["found"]
