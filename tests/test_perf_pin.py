"""Same-host relative perf pin (ISSUE 3 satellite).

An absolute wall-clock assertion would flap with machine variance, so
the pin is a RATIO: the full production merge's p50 against a fixed
reference primitive — a [N, 6] int64 plane row-gather, the kernel's own
dominant memory shape — measured back-to-back on the same host in the
same process.  A ~2x kernel-side CPU regression (a re-added serialized
scatter, a de-fused pass) roughly doubles the ratio and fails tier-1;
a slow machine slows both sides and cancels.

The tier-1 pin runs at 256k ops (compile + repeats in ~30 s on the
2-core driver box); the 1M headline-scale variant is slow-marked.
Measured round-7 ratio on the driver box: 1.3-2.0 at 256k (CPU
backend, best-of-5 both sides).  The bound is 2x the observed max, so
same-host regressions of the 2-3x class trip it while machine variance
(which moves numerator and denominator together) does not.
"""
import time

import numpy as np
import pytest

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from crdt_graph_tpu.bench import workloads  # noqa: E402
from crdt_graph_tpu.ops import merge  # noqa: E402


def _p50(fn, *args, repeats=5):
    """Best-of-N: the minimum is the stablest same-host statistic under
    CI noise (a contended repeat inflates mean/median, never the min),
    and a structural regression shifts the minimum too."""
    jax.block_until_ready(fn(*args))          # compile + warm
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def _ratio(n_ops: int) -> float:
    arrs = workloads.chain_workload(64, n_ops)
    dev = jax.device_put(arrs)

    @jax.jit
    def kernel(o):
        # reductions over result fields so no stage can be DCE'd
        t = merge._materialize(o, False, "exhaustive", True)
        return jnp.sum(t.doc_index) + jnp.sum(t.status.astype(jnp.int32))

    n = int(arrs["kind"].shape[0])
    rng = np.random.default_rng(0)
    plane = jax.device_put(
        rng.integers(0, 2**60, (n, 6), dtype=np.int64))
    idx = jax.device_put(rng.integers(0, n, n, dtype=np.int32))

    @jax.jit
    def reference(p, i):
        # four DEPENDENT full-plane row gathers (each index derives from
        # the previous gather's data, so XLA can neither elide nor
        # overlap them): big enough that the ratio's denominator is not
        # noise-dominated on a busy CI box
        acc = jnp.int64(0)
        idx = i
        for _ in range(4):
            g = p[idx]
            acc = acc + jnp.sum(g)
            idx = (idx + g[:, 0].astype(jnp.int32)) & (n - 1)
        return acc

    kernel_p50 = _p50(kernel, dev)
    ref_p50 = max(_p50(reference, plane, idx), 1e-5)
    return kernel_p50 / ref_p50


def test_kernel_vs_reference_ratio_256k():
    r = _ratio(262_144)
    assert r < 4.0, f"merge/reference p50 ratio {r:.2f} (round-7 " \
        "measured 1.3-2.0 on the driver box): a kernel-side CPU " \
        "regression, not machine variance — both sides ran on this host"


@pytest.mark.slow
def test_kernel_vs_reference_ratio_1m():
    r = _ratio(1_000_000)
    assert r < 4.0, f"merge/reference p50 ratio {r:.2f} at 1M ops"
