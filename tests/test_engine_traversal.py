"""Engine node-view/traversal API (TableNode) vs the oracle: get, parent,
next, prev, walk, children must agree on randomized sessions — a reference
user switching engines finds the same surface with the same answers."""
import random

import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import engine

from test_merge_kernel import _random_session


@pytest.fixture(params=[0, 1, 2])
def pair(request):
    merged, ops = _random_session(request.param + 60, n_replicas=3,
                                  steps=70)
    e = engine.init(42)
    e.apply(crdt.Batch(tuple(ops)))
    o = crdt.init(42).apply(crdt.Batch(tuple(ops)))
    return e, o


def all_paths(o):
    acc = []
    o.walk(lambda n, a: ("take", a.append(n.path) or a), acc)
    return acc


def test_walk_matches_oracle(pair):
    e, o = pair
    assert [n.path for n in walk_nodes(e)] == all_paths(o)


def walk_nodes(e, start=None):
    acc = []
    e.walk(lambda n, a: ("take", a.append(n) or a), acc, start=start)
    return acc


def test_get_value_timestamp_children(pair):
    e, o = pair
    for path in all_paths(o):
        en, on = e.get(path), o.get(path)
        assert en is not None and on is not None
        assert en.value == on.get_value()
        assert en.timestamp == on.timestamp
        assert en.path == on.path
        assert [c.path for c in en.children()] == \
            [c.path for c in __import__(
                'crdt_graph_tpu.core.node', fromlist=['x']
            ).iter_visible(on)]
    assert e.get((424242,)) is None and o.get((424242,)) is None


def test_next_prev_parent_match_oracle(pair):
    e, o = pair
    for path in all_paths(o):
        en, on = e.get(path), o.get(path)
        for name in ("next", "prev"):
            ge = getattr(e, name)(en)
            go = getattr(o, name)(on)
            assert (ge is None) == (go is None), (name, path)
            if ge is not None:
                assert ge.path == go.path, (name, path)
        pe, po = e.parent(en), o.parent(on)
        if po is None or po.kind == "root":
            assert pe is not None and pe.is_root
        else:
            assert pe.path == po.path


def test_resumable_walk_matches_oracle(pair):
    e, o = pair
    paths = all_paths(o)
    rng = random.Random(5)
    for path in rng.sample(paths, min(8, len(paths))):
        got = [n.path for n in walk_nodes(e, start=e.get(path))]
        want = []
        o.walk(lambda n, a: ("take", a.append(n.path) or a), want,
               start=o.get(path))
        assert got == want, path


def test_walk_early_exit(pair):
    e, o = pair
    stops = []
    out = e.walk(lambda n, a: ("done", a + 1) if a >= 2 else ("take", a + 1),
                 0)
    assert out == 3 or out <= 2  # stopped at the third visible node


def test_root_and_id(pair):
    e, o = pair
    assert e.root().is_root and e.root().value is None
    assert e.id == o.id == 42
    kids = e.root().children()
    assert [k.path for k in kids] == [p for p in all_paths(o)
                                      if len(p) == 1]


def test_tombstone_node_view():
    e = engine.init(1)
    e.add("a").add("b")
    first = e.visible_paths()[0]
    e.delete(first)
    n = e.get(first)
    assert n is not None and n.is_deleted and n.value is None


def test_views_survive_host_edits():
    """Mirror slots are append-only: outstanding TableNodes stay valid —
    and stay CORRECT — across small (host-path) edits."""
    e = engine.init(1)
    e.add("a").add("b").add("c")
    n = e.get(e.visible_paths()[1])
    e.add("d")  # host path: no slot reassignment
    assert n.value == "b"
    assert [c.path for c in n.children()] == []
    assert e.next(n) is not None and e.prev(n) is not None
    # a delete flips visibility in place; the view reflects it live
    e.delete(n.path)
    assert n.is_deleted and n.value is None


def _big_batch(n0, count=engine.DELTA_THRESHOLD + 1):
    """A >threshold remote batch that forces the KERNEL path: delivered
    fully reversed, so the host-first bulk attempt (round-3 cliff fix,
    engine._apply_bulk) rejects the non-causal order and falls back to
    the set-join — which reassigns slots and stales outstanding views.
    (A causal bulk batch now applies host-side and keeps views valid.)"""
    rid = 9
    ops = []
    prev = 0
    for i in range(1, count + 1):
        ts = rid * 2**32 + n0 + i
        ops.append(crdt.Add(ts, (prev,), f"r{i}"))
        prev = ts
    return crdt.Batch(tuple(reversed(ops)))


def test_stale_views_fail_loudly_after_kernel_merge():
    """A kernel merge compacts/reassigns slots: every access path —
    accessors, children, and the tree-side traversal methods that take a
    node — must raise StaleNodeView rather than silently resolve the old
    slot against the rebuilt mirror."""
    e = engine.init(1)
    e.add("a").add("b").add("c")
    n = e.get(e.visible_paths()[1])
    e.apply(_big_batch(0))
    for access in (lambda: n.value, lambda: n.path, lambda: n.is_deleted,
                   lambda: n.children(), lambda: e.parent(n),
                   lambda: e.next(n), lambda: e.prev(n),
                   lambda: e.walk(lambda x, a: ("take", a), None, start=n)):
        with pytest.raises(engine.StaleNodeView):
            access()
    # re-fetching yields a live view
    assert e.get(e.visible_paths()[0]).value is not None


def test_kernel_merge_rebuilds_mirror_for_nested_docs():
    """Regression: the kernel table's path plane is depth-bucketed
    (narrower than the mirror's max_depth plane); rebuilding the mirror
    from a nested document's table must widen it, and flat documents must
    not smear their single path column across the padding."""
    rid = 9 * 2**32
    ops = []
    prev_branch = (0,)
    for i in range(1, engine.DELTA_THRESHOLD + 8):
        ts = rid + i
        if i % 3 == 1 and len(prev_branch) < 4:
            ops.append(crdt.Add(ts, prev_branch, f"b{i}"))
            prev_branch = prev_branch[:-1] + (ts, 0)
        else:
            ops.append(crdt.Add(ts, prev_branch[:-1] + (0,), f"n{i}"))
    e = engine.init(1)
    e.apply(crdt.Batch(tuple(reversed(ops))))   # kernel path (non-causal)
    # mirror reads (paths, traversal) must agree with the oracle's causal
    # replay of the same op set
    o = crdt.init(2).apply(crdt.Batch(tuple(ops)))
    assert e.visible_values() == o.visible_values()
    for p in e.visible_paths()[:10]:
        assert e.get_value(p) == o.get_value(p)


def test_bulk_causal_apply_keeps_views_valid():
    """Round-3 cliff fix: a CAUSALLY ordered bulk batch (what anti-entropy
    delivers) applies through the host mirror in O(delta) — slots are
    append-only there, so outstanding views survive, and the result
    matches the kernel set-join bit for bit."""
    e = engine.init(1)
    e.add("a").add("b").add("c")
    n = e.get(e.visible_paths()[1])
    causal = crdt.Batch(tuple(reversed(_big_batch(0).ops)))
    e.apply(causal)
    assert n.value == "b"          # view still live
    # same converged document as a from-scratch kernel materialisation
    e2 = engine.init(2)
    e2.apply(e.operations_since(0))
    assert e2.visible_values() == e.visible_values()
    assert e.log_length == 3 + engine.DELTA_THRESHOLD + 1


def test_stale_view_identity_and_repr():
    """A stale view never masquerades as a live one: unequal, distinct as a
    dict key, and its repr reports staleness instead of raising."""
    e = engine.init(1)
    e.add("a").add("b")
    n = e.get(e.visible_paths()[0])
    live_repr = repr(n)
    assert "stale" not in live_repr
    e.apply(_big_batch(100))  # kernel merge: slots reassigned
    m = e.get(e.visible_paths()[0])  # may reuse n's slot number
    assert n != m
    assert len({n, m}) == 2
    assert "stale" in repr(n)


# -- node-combinator facade vs oracle (CRDTree/Node.elm:96-181) -----------

def _node_pairs(pair):
    """(engine node, oracle node) for the root and every visible path."""
    e, o = pair
    out = [(e.root(), o.root)]
    for path in all_paths(o):
        out.append((e.get(path), o.get(path)))
    return out


def test_combinators_fold_map_head_last(pair):
    e, o = pair
    from crdt_graph_tpu.core import node as onode
    for en, on in _node_pairs(pair):
        assert en.map(lambda n: n.path) == \
            onode.node_map(lambda n: n.path, on)
        assert en.foldl(lambda n, a: a + [n.path], []) == \
            onode.foldl(lambda n, a: a + [n.path], [], on)
        assert en.foldr(lambda n, a: a + [n.path], []) == \
            onode.foldr(lambda n, a: a + [n.path], [], on)
        assert en.filter_map(
            lambda n: n.path if n.timestamp % 2 else None) == \
            onode.filter_map(
                lambda n: n.path if n.timestamp % 2 else None, on)
        eh, oh = en.head(), onode.head(on)
        assert (eh is None) == (oh is None)
        if eh is not None:
            assert eh.path == oh.path
        el, ol = en.last(), onode.last(on)
        assert (el is None) == (ol is None)
        if el is not None:
            assert el.path == ol.path


def test_combinators_loop_and_find(pair):
    e, o = pair
    from crdt_graph_tpu.core import node as onode
    for en, on in _node_pairs(pair):
        got = en.loop(
            lambda n, a: ("done", a) if len(a) >= 2 else
            ("take", a + [n.path]), [])
        want = onode.loop(
            lambda n, a: ("done", a) if len(a) >= 2 else
            ("take", a + [n.path]), [], on)
        assert got == want
        # find: tombstones ARE candidates (raw chain scan)
        ef = en.find(lambda n: n.timestamp % 3 == 0)
        of = onode.find(lambda n: n.timestamp % 3 == 0, on)
        assert (ef is None) == (of is None)
        if ef is not None:
            assert ef.path == of.path
        ef = en.find(lambda n: n.is_deleted)
        of = onode.find(lambda n: n.is_deleted(), on)
        assert (ef is None) == (of is None)
        if ef is not None:
            assert ef.path == of.path


def test_combinator_descendant(pair):
    e, o = pair
    from crdt_graph_tpu.core import node as onode
    er = e.root()
    for path in all_paths(o):
        got = er.descendant(path)
        want = onode.descendant(o.root, path)
        assert (got is None) == (want is None), path
        if got is not None:
            assert got.path == want.path
        # relative descent from each node's parent
        if len(path) > 1:
            en = e.get(path[:-1])
            got2 = en.descendant(path[-1:])
            assert got2 is not None and got2.path == path
    assert er.descendant(()) is None
    assert er.descendant((987654,)) is None
