"""Randomized suite around tombstone-ADJACENT inserts (VERDICT r2 weak-7).

The one documented divergence from the reference: ``findInsertion``
(Internal/Node.elm:93-104) pairs the immediate next *timestamp* with the
tombstone-*skipping* next node, which in the tombstone-between-siblings
state would overwrite a tombstone's mapping slot and orphan a sibling key
— a state no reference test reaches (core/node.py:19-28).  This framework
instead treats tombstones as ordinary chain members during the skip-scan.

These tests make the claim durable: hundreds of randomized logs whose
inserts deliberately anchor AT tombstones, next to tombstones, and into
tombstone runs, checked for (a) oracle/kernel agreement, (b) structural
self-consistency (every visible node reachable exactly once, chain order
= document order), and (c) convergence under delivery-order permutation.
"""
import random

import pytest

import crdt_graph_tpu as crdt
from crdt_graph_tpu import engine
from crdt_graph_tpu.codec import packed
from crdt_graph_tpu.ops import merge, view

OFFSET = 2**32


def kernel_visible(ops):
    p = packed.pack(ops)
    t = view.to_host(merge.materialize(p.arrays()))
    return view.visible_values(t, p.values)


def oracle_apply_all(ops):
    tree = crdt.init(99)
    for op in ops:
        try:
            tree = tree.apply(op)
        except crdt.CRDTError:
            pass
    return tree


def _tombstone_adjacent_log(seed: int, steps: int = 60):
    """A flat-branch log biased to create and then insert around
    tombstones: ~half the deletes target the most recent insert's left or
    right neighbour, and ~half the adds anchor AT a tombstoned node."""
    rng = random.Random(seed)
    ops = []
    counters = {}
    alive = []          # (ts, deleted) in chain order, tombstones kept
    for _ in range(steps):
        roll = rng.random()
        live = [i for i, (_, d) in enumerate(alive) if not d]
        dead = [i for i, (_, d) in enumerate(alive) if d]
        if alive and roll < 0.35 and live:
            # delete a visible node, biased toward neighbours of tombstones
            cands = live
            next_to_dead = [i for i in live
                            if (i > 0 and alive[i - 1][1])
                            or (i + 1 < len(alive) and alive[i + 1][1])]
            if next_to_dead and rng.random() < 0.7:
                cands = next_to_dead
            k = rng.choice(cands)
            ops.append(crdt.Delete((alive[k][0],)))
            alive[k] = (alive[k][0], True)
        else:
            rid = rng.randrange(1, 5)
            counters[rid] = counters.get(rid, 0) + 1
            ts = rid * OFFSET + counters[rid]
            # anchor: sentinel, a live node, or (biased) a TOMBSTONE
            if dead and rng.random() < 0.5:
                k = rng.choice(dead)
                anchor = alive[k][0]
                insert_at = k + 1
            elif alive and rng.random() < 0.8:
                k = rng.randrange(len(alive))
                anchor = alive[k][0]
                insert_at = k + 1
            else:
                anchor = 0
                insert_at = 0
            ops.append(crdt.Add(ts, (anchor,), ts))
            # position per the RGA rule: skip right past larger timestamps
            # (tombstones included — the documented rule under test)
            while insert_at < len(alive) and alive[insert_at][0] > ts:
                insert_at += 1
            alive.insert(insert_at, (ts, False))
    expected = [ts for ts, d in alive if not d]
    return ops, expected


@pytest.mark.parametrize("seed", range(12))
def test_tombstone_adjacent_inserts_match_oracle_and_model(seed):
    """Kernel == oracle == the independent list-model expectation, on logs
    dense with tombstone-adjacent inserts."""
    ops, expected = _tombstone_adjacent_log(seed)
    tree = oracle_apply_all(ops)
    assert tree.visible_values() == expected, "oracle deviates from model"
    assert kernel_visible(ops) == expected, "kernel deviates from model"


@pytest.mark.parametrize("seed", range(6))
def test_tombstone_adjacent_structure_consistent(seed):
    """No orphaned keys / detached chain members: walking the oracle
    visits every non-deleted insert exactly once, and every delete's
    target stays addressable (tombstones keep their list position)."""
    ops, _ = _tombstone_adjacent_log(seed, steps=80)
    tree = oracle_apply_all(ops)
    added = {op.ts for op in ops if isinstance(op, crdt.Add)}
    deleted = {op.path[-1] for op in ops if isinstance(op, crdt.Delete)}
    seen = []
    tree.walk(lambda n, acc: ("take", acc.append(n.timestamp) or acc), seen)
    assert len(seen) == len(set(seen)), "node visited twice (orphaned key)"
    assert set(seen) == added - deleted, "visible set wrong"
    # every tombstone still addressable at its path (kept list position)
    for ts in deleted:
        node = tree.get((ts,))
        assert node is not None and node.is_deleted()


@pytest.mark.parametrize("seed", range(6))
def test_tombstone_adjacent_permutation_convergence(seed):
    """Same logs, shuffled delivery through the kernel: identical visible
    sequence (deletes may precede their add in the shuffle — the kernel
    set-join absorbs that; the converged tree must not care)."""
    ops, expected = _tombstone_adjacent_log(seed)
    rng = random.Random(seed + 500)
    for _ in range(3):
        perm = ops[:]
        rng.shuffle(perm)
        assert kernel_visible(perm) == expected


def test_engine_host_path_agrees_on_tombstone_adjacent_log():
    """The mutable host mirror (engine small-delta path) replays the same
    logs to the same document as oracle and kernel."""
    for seed in range(6):
        ops, expected = _tombstone_adjacent_log(seed)
        e = engine.init(99)
        for op in ops:
            try:
                e.apply(op)
            except crdt.CRDTError:
                pass
        assert e.visible_values() == expected, seed
