"""Read-path egress overhaul (ISSUE 15): the per-snapshot encoded-body
cache, the conditional-GET (ETag / If-None-Match / 304) contract, the
window-bytes LRU's seam identity, the pooled-connection layer, the new
prom families, and the tier-1 cached-vs-re-encode perf ratio pin.
"""
import hashlib
import json
import threading
import time

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import engine as engine_mod
from crdt_graph_tpu.cluster.pool import ConnectionPool
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.obs import prom as prom_mod
from crdt_graph_tpu.serve import ServingEngine
from crdt_graph_tpu.serve import snapshot as snapshot_mod
from crdt_graph_tpu.service import make_server
from crdt_graph_tpu.service.http import etag_matches


def _ts(r, c):
    return r * 2**32 + c


def _chain(rid, n, start=1, prev=0):
    ops = []
    for c in range(start, start + n):
        ops.append(Add(_ts(rid, c), (prev,), f"r{rid}:{c}"))
        prev = _ts(rid, c)
    return json_codec.dumps(Batch(tuple(ops)))


@pytest.fixture()
def served():
    """A running server over a fresh ServingEngine + one pooled client
    request helper."""
    srv = make_server(port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    pool = ConnectionPool()

    def req(method, path, body=None, headers=None):
        resp, raw = pool.request("t", "server", "127.0.0.1",
                                 srv.server_port, method, path,
                                 body=body, headers=headers, timeout=30)
        return resp.status, raw, {k: v for k, v in resp.getheaders()}

    yield srv, req
    pool.close()
    srv.shutdown()
    srv.server_close()


# -- ETag / If-None-Match / 304 ----------------------------------------------


def test_etag_304_contract(served):
    srv, req = served
    st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 4))
    assert st == 200 and json.loads(raw)["accepted"]

    st, body1, hdr = req("GET", "/docs/d")
    assert st == 200
    etag = hdr["ETag"]
    # the ETag is the QUOTED replica-independent state fingerprint
    snap = srv.store.get("d").read_view()
    assert etag == f'"{snap.state_fingerprint()}"'

    # a matching If-None-Match answers 304 with NO body but the full
    # correlation header set intact
    st, raw, hdr2 = req("GET", "/docs/d",
                        headers={"If-None-Match": etag})
    assert st == 304 and raw == b""
    assert hdr2["ETag"] == etag
    assert hdr2["X-Commit-Seq"] == hdr["X-Commit-Seq"]
    assert hdr2["X-Snapshot-Fingerprint"] == hdr["X-Snapshot-Fingerprint"]
    # list form + weak validators + * all match
    st, _, _ = req("GET", "/docs/d",
                   headers={"If-None-Match": f'"zzz", W/{etag}'})
    assert st == 304
    st, raw, _ = req("GET", "/docs/d", headers={"If-None-Match": "*"})
    assert st == 304
    # malformed If-None-Match is IGNORED: an unconditional 200
    st, raw, _ = req("GET", "/docs/d",
                     headers={"If-None-Match": "not quoted garbage"})
    assert st == 200 and raw == body1

    # a new write publishes a new generation -> new ETag, and the OLD
    # validator stops matching (a poller never sleeps through a write)
    st, raw, _ = req("POST", "/docs/d/ops",
                     body=_chain(1, 2, start=5, prev=_ts(1, 4)))
    assert st == 200 and json.loads(raw)["accepted"]
    st, body2, hdr3 = req("GET", "/docs/d",
                          headers={"If-None-Match": etag})
    assert st == 200 and hdr3["ETag"] != etag
    assert body2 != body1
    assert json.loads(body2)["values"] == [f"r1:{c}"
                                           for c in range(1, 7)]

    # /snapshot carries the same validator and honors it too
    st, _, shdr = req("GET", "/docs/d/snapshot")
    assert st == 200 and shdr["ETag"] == hdr3["ETag"]
    st, raw, shdr2 = req("GET", "/docs/d/snapshot",
                         headers={"If-None-Match": shdr["ETag"]})
    assert st == 304 and raw == b"" and "X-Commit-Seq" in shdr2


def test_etag_matches_unit():
    assert etag_matches('"abc"', '"abc"')
    assert etag_matches('W/"abc"', '"abc"')
    assert etag_matches('"x", "y" , "abc"', '"abc"')
    assert etag_matches("*", '"abc"')
    assert not etag_matches(None, '"abc"')
    assert not etag_matches("", '"abc"')
    assert not etag_matches('"abcd"', '"abc"')
    assert not etag_matches("garbage tokens ,,, ", '"abc"')


# -- encoded-body cache ------------------------------------------------------


def test_cached_body_identity_and_invalidation(served):
    """Every reader of one generation gets the SAME bytes object; a
    publish swaps the whole cache with the snapshot (never a stale
    generation's body), and cached bytes equal a fresh encode."""
    srv, req = served
    st, _, _ = req("POST", "/docs/d/ops", body=_chain(2, 8))
    assert st == 200
    doc = srv.store.get("d")
    snap = doc.read_view()
    b1 = snap.values_body()
    b2 = snap.values_body()
    assert b1 is b2                       # one encode per generation
    assert json.loads(b1) == {"values": snap.visible_values()}
    assert doc.readcache.snapshot()["hits"] >= 1

    st, wire, _ = req("GET", "/docs/d")
    assert wire == b1

    # publish invalidates by POINTER SWAP: the new generation encodes
    # fresh, the old snapshot keeps serving its own (still-correct) body
    st, _, _ = req("POST", "/docs/d/ops",
                   body=_chain(2, 1, start=9, prev=_ts(2, 8)))
    assert st == 200
    snap2 = doc.read_view()
    assert snap2 is not snap
    assert snap2.values_body() is not b1
    assert json.loads(snap2.values_body())["values"] == \
        snap.visible_values() + ["r2:9"]
    assert snap.values_body() is b1       # pinned generation unchanged

    # clock wire body is cached and identical to the dict encoding
    assert json.loads(snap2.clock_body()) == \
        {"replicas": snap2.clock_wire()}


def test_cache_on_off_bodies_byte_identical():
    """GRAFT_READCACHE=0 (the A/B baseline leg) must serve EXACTLY the
    bytes the cached path serves — the cache is an egress optimization,
    never a wire change."""
    bodies = {}
    for enabled in (True, False):
        eng = ServingEngine(readcache=enabled)
        try:
            doc = eng.get("d")
            doc.apply_body(_chain(3, 6))
            snap = doc.read_view()
            bodies[enabled] = (snap.values_body(), snap.clock_body(),
                               snap.ops_since_window(0, 3),
                               snap.ops_since_bytes(0), snap.etag())
            if not enabled:
                # disabled: every call re-encodes (misses only)
                snap.values_body()
                assert doc.readcache.snapshot()["hits"] == 0
        finally:
            eng.close()
    assert bodies[True][0] == bodies[False][0]
    assert bodies[True][1] == bodies[False][1]
    assert bodies[True][2][0] == bodies[False][2][0]
    assert bodies[True][2][1] == bodies[False][2][1]
    assert bodies[True][3] == bodies[False][3]
    assert bodies[True][4] == bodies[False][4]


def test_window_lru_seam_identity_and_eviction():
    """Cached window bytes are byte-identical to the uncached
    ``engine.packed_since_window`` over the untiered full packing —
    across tier seams — and the bounded LRU evicts (counted) without
    ever serving wrong bytes for an evicted-then-refetched key."""
    eng = ServingEngine(oplog_hot_ops=16, readcache_windows=2)
    try:
        doc = eng.get("d")
        prev = 0
        for k in range(6):                # several commits -> spills
            doc.apply_body(_chain(4, 10, start=k * 10 + 1, prev=prev))
            prev = _ts(4, (k + 1) * 10)
        snap = doc.read_view()
        assert snap.log_segments > 1      # the cascade actually tiered
        full = snap.packed                # untiered reference columns

        since, limit = 0, 7
        seen = 0
        while True:
            body, meta = snap.ops_since_window(since, limit)
            ref_body, ref_meta = engine_mod.packed_since_window(
                full, since, limit)
            assert body == ref_body       # seam-identical wire bytes
            # the cached window's meta additionally carries the wire
            # validator (ISSUE 16): the quoted sha1 of the body
            assert meta["etag"] == \
                f'"{hashlib.sha1(body).hexdigest()}"'
            assert {k: v for k, v in meta.items()
                    if k != "etag"} == ref_meta
            # a repeat of the same key is a cache HIT on the same obj
            body2, meta2 = snap.ops_since_window(since, limit)
            assert body2 is body
            seen += meta["count"]
            if not meta["more"]:
                break
            since = meta["next_since"]
        assert seen >= snap.log_length
        # the chain walked > window_cap distinct keys through a
        # 2-entry LRU: evictions counted, and an evicted key re-serves
        # byte-identically
        rc = doc.readcache.snapshot()
        assert rc["window_evictions"] > 0
        body0, meta0 = snap.ops_since_window(0, limit)
        assert body0 == engine_mod.packed_since_window(full, 0, limit)[0]
    finally:
        eng.close()


# -- pooled connections ------------------------------------------------------


def test_pool_reuse_release_and_poison(served):
    srv, req = served
    pool = ConnectionPool(max_idle_per_link=2)
    try:
        for _ in range(5):
            resp, raw = pool.request("c", "server", "127.0.0.1",
                                     srv.server_port, "GET", "/docs",
                                     timeout=10)
            assert resp.status == 200
        st = pool.stats()
        assert st["opens"] == 1 and st["reuses"] == 4
        assert st["idle"] == 1

        # a poisoned release closes the connection and the next lease
        # opens fresh
        conn = pool.lease("c", "server", "127.0.0.1",
                          srv.server_port, 10)
        assert conn._pool_reused
        pool.release(conn, ok=False)
        assert pool.stats()["poisoned"] == 1
        conn = pool.lease("c", "server", "127.0.0.1",
                          srv.server_port, 10)
        assert not conn._pool_reused
        pool.release(conn, ok=True)

        # idle overflow evicts the oldest
        c1 = pool.lease("c", "server", "127.0.0.1", srv.server_port, 10)
        c2 = pool.lease("c", "server", "127.0.0.1", srv.server_port, 10)
        c3 = pool.lease("c", "server", "127.0.0.1", srv.server_port, 10)
        for c in (c1, c2, c3):
            pool.release(c, ok=True)
        st = pool.stats()
        assert st["idle"] == 2 and st["evictions"] >= 1
    finally:
        pool.close()


def test_pool_stale_reuse_retries_once(served):
    """A reused keep-alive connection the server closed retries once
    on a fresh one (counted, not an error); pooling never turns server
    restarts into client failures."""
    srv, req = served
    pool = ConnectionPool(max_age_s=3600)
    try:
        resp, _ = pool.request("c", "server", "127.0.0.1",
                               srv.server_port, "GET", "/docs",
                               timeout=10)
        assert resp.status == 200
        # sever the idle pooled connection behind the pool's back —
        # the next lease reuses a conn whose next send raises
        # BrokenPipeError (ESHUTDOWN), the stale class
        import socket as socket_mod
        with pool._mu:
            entries = next(iter(pool._idle.values()))
            conn, _t = entries[0]
        conn.sock.shutdown(socket_mod.SHUT_WR)
        resp, _ = pool.request("c", "server", "127.0.0.1",
                               srv.server_port, "GET", "/docs",
                               timeout=10)
        assert resp.status == 200
        st = pool.stats()
        assert st["stale_retries"] == 1 and st["poisoned"] == 1

        # SEVERAL stale idles at once (a peer restart stales the whole
        # link): the retry must lease a GUARANTEED-fresh connection,
        # never the next stale idle candidate
        conns = [pool.lease("c", "server", "127.0.0.1",
                            srv.server_port, 10) for _ in range(3)]
        for c in conns:                   # actually connect each one
            c.request("GET", "/docs")
            c.getresponse().read()
        for c in conns:
            pool.release(c, ok=True)
        for c in conns:
            c.sock.shutdown(socket_mod.SHUT_WR)
        resp, _ = pool.request("c", "server", "127.0.0.1",
                               srv.server_port, "GET", "/docs",
                               timeout=10)
        assert resp.status == 200
        assert pool.stats()["stale_retries"] == 2
    finally:
        pool.close()


def test_server_close_severs_keepalive_connections():
    """crash() semantics under pooling: server_close force-closes
    ESTABLISHED keep-alive connections, so a 'crashed' fleet member
    cannot keep serving pooled clients through leftover handler
    threads."""
    srv = make_server(port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    pool = ConnectionPool()
    resp, _ = pool.request("c", "server", "127.0.0.1",
                           srv.server_port, "GET", "/docs", timeout=10)
    assert resp.status == 200
    srv.shutdown()
    srv.server_close()
    with pytest.raises(OSError):
        # the reused conn is severed; the fresh retry is refused too
        pool.request("c", "server", "127.0.0.1", srv.server_port,
                     "GET", "/docs", timeout=5)
    pool.close()


# -- prom families (strict round-trip) ---------------------------------------


def test_prom_readcache_and_connpool_families_strict(served):
    srv, req = served
    st, _, _ = req("POST", "/docs/d/ops", body=_chain(5, 4))
    assert st == 200
    for _ in range(3):
        st, _, _ = req("GET", "/docs/d")
        assert st == 200
    st, raw, _ = req("GET", "/metrics/prom")
    assert st == 200
    fams = prom_mod.parse_text(raw.decode())
    for fam in ("crdt_readcache_hits_total",
                "crdt_readcache_misses_total",
                "crdt_readcache_encoded_bytes_total",
                "crdt_readcache_window_evictions_total",
                "crdt_readcache_not_modified_total",
                "crdt_readcache_enabled"):
        assert fam in fams, f"missing {fam}"
    hits = {lbl["doc"]: v for _, lbl, v in
            fams["crdt_readcache_hits_total"]["samples"]}
    assert hits.get("d", 0) >= 2          # repeat reads actually hit

    # cluster side: the connpool families render on a fleet node and
    # survive the strict parser
    from crdt_graph_tpu.cluster import FleetServer, MemoryKV
    kv = MemoryKV()
    a = FleetServer("pa", kv, ttl_s=600.0, ae_interval_s=3600.0)
    b = FleetServer("pb", kv, ttl_s=600.0, ae_interval_s=3600.0)
    try:
        for fs in (a, b):
            fs.node.refresh_ring()
        # one driven round creates pooled anti-entropy traffic
        a.node.antientropy.sync_now()
        text = a.node.render_prom()
        fams = prom_mod.parse_text(text)
        for fam in ("crdt_connpool_opens_total",
                    "crdt_connpool_reuses_total",
                    "crdt_connpool_evictions_total",
                    "crdt_connpool_poisoned_total",
                    "crdt_connpool_stale_retries_total",
                    "crdt_connpool_idle_connections"):
            assert fam in fams, f"missing {fam}"
        opens = fams["crdt_connpool_opens_total"]["samples"][0][2]
        assert opens >= 1
        a.node.antientropy.sync_now()
        st2 = a.node.pool.stats()
        assert st2["reuses"] >= 1         # round 2 reused round 1's conn
    finally:
        a.stop()
        b.stop()


# -- WAL-stream scrub (satellite) --------------------------------------------


def test_scrub_walks_wal_stream(tmp_path):
    from crdt_graph_tpu import wal as wal_mod
    eng = ServingEngine(durable_dir=str(tmp_path / "dur"),
                        wal_sync="batch", oplog_hot_ops=8)
    try:
        doc = eng.get("d")
        doc.apply_body(_chain(6, 12))
        eng.flush(timeout=30)
        rep = doc.run_scrub()
        st = dict(doc.scrub_stats)
        assert st["runs"] == 1
        assert st["wal_mid_log"] == 0
        # the sweep actually walked the stream's records (the shared/
        # per-doc split both expose verify())
        assert doc.wal.verify()["mid_log"] == 0
    finally:
        eng.close()

    # mid-log damage: flip bytes INSIDE an early record of the per-doc
    # WAL, then verify() classifies it as the typed-WalError class and
    # a scrub pass surfaces it via counters + a flight dump
    wal_path = tmp_path / "dur" / "doc-d" / "wal.log"
    data = bytearray(wal_path.read_bytes())
    if len(data) > 64:
        data[40] ^= 0xFF
        # append a second valid-looking garbage record boundary is not
        # needed: scan() reports mid-log only when valid bytes follow
        # the bad record — corrupt an early offset of a multi-record
        # file, or fall back to asserting torn-tail classification
        wal_path.write_bytes(bytes(data))
        v = wal_mod._verify(str(wal_path), wal_mod.MAGIC)
        assert v["mid_log"] == 1 or v["torn_tail"] == 1


def test_shared_wal_scrub_sweeps_stream_once_per_cadence(tmp_path):
    """GRAFT_WAL_SHARED: many docs share ONE stream — the scrub
    cadence must walk it once engine-wide, not once per document
    (N-fold re-scans, and one corruption reported N times)."""
    eng = ServingEngine(durable_dir=str(tmp_path / "dur"),
                        wal_sync="batch", wal_shared=True,
                        oplog_hot_ops=1 << 16)
    try:
        eng.scrub_interval_s = 60.0       # the dedupe window
        for i in range(3):
            eng.get(f"d{i}").apply_body(_chain(8 + i, 4))
        eng.flush(timeout=30)
        swept = 0
        for i in range(3):
            doc = eng.get(f"d{i}")
            doc.run_scrub()
            if doc.scrub_stats["wal_records"] > 0:
                swept += 1
        assert swept == 1                 # one sweep covered the stream
        total = sum(eng.get(f"d{i}").scrub_stats["wal_records"]
                    for i in range(3))
        assert total == eng.shared_wal.verify()["records"]
    finally:
        eng.close()


def test_scrub_mid_log_wal_damage_counts_and_dumps(tmp_path):
    """Construct a WAL with guaranteed MID-log corruption (a bad crc
    with valid records after it) and prove the scrub cadence surfaces
    it: wal_mid_log counter + scheduler counter + a flight dump — not
    first discovered at recovery."""
    import struct
    import zlib

    from crdt_graph_tpu import wal as wal_mod
    eng = ServingEngine(durable_dir=str(tmp_path / "dur"),
                        wal_sync="batch", oplog_hot_ops=1 << 16)
    try:
        doc = eng.get("d")
        prev = 0
        for k in range(3):                # three records in the WAL
            doc.apply_body(_chain(7, 4, start=k * 4 + 1, prev=prev))
            prev = _ts(7, (k + 1) * 4)
        eng.flush(timeout=30)
        wal_path = tmp_path / "dur" / "doc-d" / "wal.log"
        data = bytearray(wal_path.read_bytes())
        records, torn, _ = wal_mod.scan(str(wal_path))
        assert len(records) >= 2 and torn == 0
        # corrupt the FIRST record's payload: valid bytes continue
        # past it -> mid-log, the class recovery refuses on
        first_off = records[0][0]
        data[first_off + 8 + 2] ^= 0xFF
        wal_path.write_bytes(bytes(data))
        doc.run_scrub()
        st = dict(doc.scrub_stats)
        assert st["wal_mid_log"] == 1, st
        assert eng.counters.snapshot().get("wal_scrub_mid_log") == 1
        assert eng.flight.stats()["dumps"].get("wal-corruption", 0) >= 0
    finally:
        eng.close()


# -- tier-1 perf ratio pin (satellite) ---------------------------------------


def _encode_ratio(n_values: int) -> float:
    """Cached repeat read vs forced re-encode on the SAME snapshot
    shape, same host, best-of-N both sides (the test_perf_pin.py
    recipe: machine variance moves both sides together)."""
    tree = engine_mod.init(0)
    values = tuple(f"v{i:09d}" for i in range(n_values))
    cached = snapshot_mod.DocSnapshot(
        "d", 1, tree.log_view(), values, {1: n_values}, 0, n_values,
        (0,), 16, stats=snapshot_mod.ReadCacheStats(enabled=True))
    uncached = snapshot_mod.DocSnapshot(
        "d", 1, tree.log_view(), values, {1: n_values}, 0, n_values,
        (0,), 16, stats=snapshot_mod.ReadCacheStats(enabled=False))
    assert cached.values_body() == uncached.values_body()

    def best_of(fn, n=5):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    cached.values_body()                  # warm the cache
    t_hot = max(best_of(cached.values_body), 1e-7)
    t_encode = best_of(uncached.values_body)
    return t_encode / t_hot


def test_cached_read_ratio_256k():
    r = _encode_ratio(262_144)
    assert r >= 5.0, \
        f"cached repeat read only {r:.1f}x faster than a forced " \
        f"re-encode at 256k values — the encoded-body cache is not " \
        f"doing its job (same host, best-of-5 both sides)"


@pytest.mark.slow
def test_cached_read_ratio_1m():
    r = _encode_ratio(1_000_000)
    assert r >= 5.0, f"cached/re-encode ratio {r:.1f}x at 1M values"
