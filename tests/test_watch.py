"""Delta-push fan-out (ISSUE 16): the watch/subscription tier.

``GET /docs/{id}/watch?since=`` parks readers on the publish pointer
(serve/watch.py) and answers with the PR-15 cached ops window — one
encode per generation shared by the whole watcher population.  Pinned
here:

- park/notify/resume exactness across tier seams: every delivered
  window is byte-identical to the ``/ops`` window at the same mark,
  and the reassembled chain equals the served document;
- bounded admission (429 past ``watch_max``), registry drain, dead-
  connection reaping, close-while-parked 503;
- slow-consumer shed: the window ships WITH an honest resumable mark
  (``X-Watch-Resume-Since``) — handoff to polling loses nothing;
- timeout heartbeats: an empty wire batch stamped with the caught-up
  window's ``ETag`` so the re-poll parks instead of re-downloading;
- SSE mode: one stream, one ``ops`` event per generation, comment
  heartbeats, every close named;
- the conditional-GET window contract (``/ops`` 304s) and the anti-
  entropy client's bodyless dup-window skip riding it;
- fleet semantics: watch on a non-primary serves local generations
  under the lag stamp and the bounded-staleness 503;
- netchaos churn: a watcher reconnecting with its mark across chaos
  rounds misses nothing, and the loadgen watcher mode holds the
  session-guarantee oracle at zero violations.
"""
import contextlib
import json
import socket
import threading
import time
from http.client import HTTPConnection

import pytest

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from crdt_graph_tpu import engine as engine_mod
from crdt_graph_tpu.cluster import FleetServer, MemoryKV, NetChaos
from crdt_graph_tpu.cluster.pool import ConnectionPool
from crdt_graph_tpu.codec import json_codec
from crdt_graph_tpu.core.operation import Add, Batch
from crdt_graph_tpu.oplog import EMPTY_BATCH_BYTES
from crdt_graph_tpu.serve import ServingEngine
from crdt_graph_tpu.service import make_server


def _ts(r, c):
    return r * 2**32 + c


def _chain(rid, n, start=1, prev=0):
    ops = []
    for c in range(start, start + n):
        ops.append(Add(_ts(rid, c), (prev,), f"r{rid}:{c}"))
        prev = _ts(rid, c)
    return json_codec.dumps(Batch(tuple(ops)))


@contextlib.contextmanager
def _served(**engine_kw):
    """A server over a fresh engine with chosen knobs + a pooled
    request helper (link per calling thread, so concurrent watchers
    get their own connections)."""
    eng = ServingEngine(**engine_kw)
    srv = make_server(port=0, store=eng)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    pool = ConnectionPool()

    def req(method, path, body=None, headers=None, timeout=60):
        resp, raw = pool.request(
            threading.current_thread().name, "server", "127.0.0.1",
            srv.server_port, method, path, body=body, headers=headers,
            timeout=timeout)
        return resp.status, raw, {k: v for k, v in resp.getheaders()}

    try:
        yield srv, req, eng
    finally:
        pool.close()
        srv.shutdown()
        srv.server_close()
        eng.close()


def _watch_walk(req, doc, since=0, limit=7, max_rounds=300):
    """Drive ``/watch`` until caught up (first timeout heartbeat),
    applying every delivered window into a fresh replica.  Returns
    ``(replica, final_mark, events)`` — the walk IS the resume-
    exactness check when the replica equals the served document."""
    replica = engine_mod.init(0)
    events = []
    for _ in range(max_rounds):
        st, raw, hdr = req(
            "GET", f"/docs/{doc}/watch?since={since}"
                   f"&limit={limit}&timeout=0.2")
        assert st == 200, raw
        ev = hdr["X-Watch-Event"]
        events.append(ev)
        if ev == "timeout":
            assert raw == EMPTY_BATCH_BYTES
            return replica, since, events
        replica.apply(json_codec.loads(raw))
        since = int(hdr["X-Since-Next"])
    pytest.fail("watch never caught up")


# -- resume exactness --------------------------------------------------------


def test_watch_resume_walk_byte_identity_across_seams():
    """A watcher chasing a tiered log through ``/watch`` sees, window
    for window, the exact ``/ops`` bytes — across hot→cold spills —
    and its reassembled replica equals the served document."""
    with _served(oplog_hot_ops=16) as (srv, req, eng):
        prev = 0
        for k in range(6):
            st, raw, _ = req("POST", "/docs/d/ops",
                             body=_chain(4, 10, start=k * 10 + 1,
                                         prev=prev))
            prev = _ts(4, (k + 1) * 10)
            assert st == 200 and json.loads(raw)["accepted"]
        assert eng.flush(timeout=60)
        assert eng.get("d").snapshot_view().log_segments > 1

        # walk the chain, checking each delivery against /ops at the
        # same (since, limit) — byte-identical or the tier seams leak
        replica = engine_mod.init(0)
        since, limit = 0, 7
        saw_shed = False
        for _ in range(100):
            st, raw, hdr = req(
                "GET", f"/docs/d/watch?since={since}"
                       f"&limit={limit}&timeout=0.2")
            assert st == 200
            if hdr["X-Watch-Event"] == "timeout":
                break
            st2, ref, _ = req(
                "GET", f"/docs/d/ops?since={since}&limit={limit}")
            assert st2 == 200 and raw == ref
            if hdr["X-Watch-Event"] == "shed":
                saw_shed = True
                assert hdr["X-Watch-Resume-Since"] == \
                    hdr["X-Since-Next"]
            replica.apply(json_codec.loads(raw))
            since = int(hdr["X-Since-Next"])
        else:
            pytest.fail("watch never caught up")
        assert saw_shed                  # limit 7 over 60 ops: behind
        st, raw, _ = req("GET", "/docs/d")
        assert replica.visible_values() == json.loads(raw)["values"]


def test_watch_park_notify_exact_delivery():
    """A caught-up watcher parks; the next commit wakes it with
    exactly the window it is missing (the ``/ops`` bytes at its mark),
    measured by the notify histogram, and the registry drains."""
    with _served() as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 5))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])

        out = {}

        def watcher():
            out["r"] = req(
                "GET", f"/docs/d/watch?since={mark}"
                       f"&limit=100&timeout=20")

        t = threading.Thread(target=watcher, daemon=True,
                             name="watch-notify")
        t.start()
        doc = eng.get("d")
        deadline = time.monotonic() + 10
        while doc.watch.counts()["parked"] < 1:
            assert time.monotonic() < deadline, "never parked"
            time.sleep(0.005)
        st, raw, _ = req("POST", "/docs/d/ops",
                         body=_chain(2, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        t.join(30)
        st, body, hdr = out["r"]
        assert st == 200
        assert hdr["X-Watch-Event"] == "notify"
        new_mark = int(hdr["X-Since-Next"])
        assert new_mark != mark
        # the delivery IS the /ops window at the parked mark
        st, ref, rhdr = req(
            "GET", f"/docs/d/ops?since={mark}&limit=100")
        assert body == ref and hdr["ETag"] == rhdr["ETag"]
        ws = doc.watch.stats.snapshot()
        assert ws["notifies"] == 1
        assert ws["notify_ms"]["count"] == 1
        assert doc.watch.counts()["registered"] == 0


def test_watch_timeout_heartbeat_etag_parks_next_poll():
    """A caught-up watcher times out with an EMPTY batch + the
    caught-up window's validator; carrying it back as If-None-Match
    parks again, while a stale validator delivers immediately (the
    delete-tail escape hatch)."""
    with _served() as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 4))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])

        st, body, hdr = req(
            "GET", f"/docs/d/watch?since={mark}&limit=100&timeout=0.2")
        assert st == 200
        assert hdr["X-Watch-Event"] == "timeout"
        assert body == EMPTY_BATCH_BYTES
        etag = hdr["ETag"]
        assert int(hdr["X-Since-Next"]) == mark

        # validator matches -> park again (no re-download)
        st, body, hdr = req(
            "GET", f"/docs/d/watch?since={mark}&limit=100&timeout=0.2",
            headers={"If-None-Match": etag})
        assert hdr["X-Watch-Event"] == "timeout"
        assert body == EMPTY_BATCH_BYTES

        # stale validator -> immediate delivery of the current window
        st, body, hdr = req(
            "GET", f"/docs/d/watch?since={mark}&limit=100&timeout=5",
            headers={"If-None-Match": '"deadbeef"'})
        assert hdr["X-Watch-Event"] == "resume"
        assert body != EMPTY_BATCH_BYTES
        assert eng.get("d").watch.stats.snapshot()["heartbeats"] == 2


# -- registry bounds, reaping, shutdown --------------------------------------


def test_watch_admission_bounded_429_then_drains():
    with _served(watch_max=2) as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])

        results = {}

        def watcher(k):
            results[k] = req(
                "GET", f"/docs/d/watch?since={mark}"
                       f"&limit=100&timeout=10")

        threads = [threading.Thread(target=watcher, args=(k,),
                                    daemon=True, name=f"watch-adm-{k}")
                   for k in range(2)]
        for t in threads:
            t.start()
        doc = eng.get("d")
        deadline = time.monotonic() + 10
        while doc.watch.counts()["parked"] < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # the registry is full: the third watcher sheds at the door
        st, raw, hdr = req(
            "GET", f"/docs/d/watch?since={mark}&limit=100&timeout=5")
        assert st == 429
        assert "Retry-After" in hdr
        assert doc.watch.stats.snapshot()["rejected"] == 1
        # a commit releases both parked watchers; slots free up
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 2))
        assert st == 200 and json.loads(raw)["accepted"]
        for t in threads:
            t.join(30)
        assert all(results[k][0] == 200 for k in results)
        assert doc.watch.counts()["registered"] == 0
        st, _, hdr = req(
            "GET", f"/docs/d/watch?since={mark}&limit=100&timeout=0.1")
        assert st == 200                 # admitted again


def test_watch_reaps_dead_connection_and_frees_slot():
    """A watcher that dies while parked is found at delivery time:
    the write fails, the reap is counted, and the slot is released —
    dead connections cannot pin the registry past one generation."""
    with _served() as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])

        sock = socket.create_connection(
            ("127.0.0.1", srv.server_port), timeout=10)
        sock.sendall(
            f"GET /docs/d/watch?since={mark}&limit=100&timeout=30 "
            f"HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        doc = eng.get("d")
        deadline = time.monotonic() + 10
        while doc.watch.counts()["parked"] < 1:
            assert time.monotonic() < deadline, "never parked"
            time.sleep(0.005)
        # RST on close (not FIN) so the server's delivery write fails
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        b"\x01\x00\x00\x00\x00\x00\x00\x00")
        sock.close()
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 2))
        assert st == 200 and json.loads(raw)["accepted"]
        deadline = time.monotonic() + 10
        while doc.watch.counts()["registered"] > 0:
            assert time.monotonic() < deadline, "slot never freed"
            time.sleep(0.01)
        assert doc.watch.stats.snapshot()["reaped"] == 1


def test_watch_close_while_parked_answers_503():
    with _served() as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        st, _, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        mark = int(hdr["X-Since-Next"])
        out = {}

        def watcher():
            out["r"] = req(
                "GET", f"/docs/d/watch?since={mark}"
                       f"&limit=100&timeout=30")

        t = threading.Thread(target=watcher, daemon=True,
                             name="watch-close")
        t.start()
        doc = eng.get("d")
        deadline = time.monotonic() + 10
        while doc.watch.counts()["parked"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        doc.watch.close()
        t.join(30)
        st, raw, hdr = out["r"]
        assert st == 503
        assert hdr["X-Watch-Event"] == "closed"
        # and a NEW watch after close sheds at the door, not a dangle
        st, raw, _ = req(
            "GET", f"/docs/d/watch?since={mark}&limit=100&timeout=5")
        assert st == 503


# -- slow-consumer shed ------------------------------------------------------


def test_watch_slow_consumer_shed_honest_handoff():
    """A watcher woken more than one window behind gets the window
    PLUS the exact resumable mark; polling ``/ops`` from that mark
    reassembles everything — shed is a handoff, never a loss."""
    with _served() as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 6))
        assert st == 200 and json.loads(raw)["accepted"]
        st, full0, hdr = req("GET", "/docs/d/ops?since=0&limit=1000")
        mark = int(hdr["X-Since-Next"])
        out = {}

        def watcher():
            out["r"] = req(
                "GET", f"/docs/d/watch?since={mark}"
                       f"&limit=4&timeout=20")

        t = threading.Thread(target=watcher, daemon=True,
                             name="watch-shed")
        t.start()
        doc = eng.get("d")
        deadline = time.monotonic() + 10
        while doc.watch.counts()["parked"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 20))
        assert st == 200 and json.loads(raw)["accepted"]
        t.join(30)
        st, body, hdr = out["r"]
        assert st == 200
        assert hdr["X-Watch-Event"] == "shed"
        resume = int(hdr["X-Watch-Resume-Since"])
        assert resume == int(hdr["X-Since-Next"])
        assert doc.watch.stats.snapshot()["shed_slow"] == 1
        # shed body == the /ops window at the parked mark
        st, ref, _ = req("GET", f"/docs/d/ops?since={mark}&limit=4")
        assert body == ref
        # the handoff: poll /ops from the resume mark until caught up
        replica = engine_mod.init(0)
        replica.apply(json_codec.loads(full0))
        replica.apply(json_codec.loads(body))
        since = resume
        for _ in range(50):
            st, raw, hdr = req(
                "GET", f"/docs/d/ops?since={since}&limit=4")
            assert st == 200
            replica.apply(json_codec.loads(raw))
            since = int(hdr["X-Since-Next"])
            if hdr.get("X-Since-More") != "1":
                break
        st, raw, _ = req("GET", "/docs/d")
        assert replica.visible_values() == json.loads(raw)["values"]


# -- SSE mode ----------------------------------------------------------------


def test_watch_sse_stream_generations_and_goodbye():
    """One SSE stream: the backlog as the first ``ops`` event, a live
    commit as the second, comment heartbeats between, and a named
    ``bye`` carrying the resume mark at the stream budget."""
    with _served() as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 3))
        assert st == 200 and json.loads(raw)["accepted"]
        eng.get("d").watch.heartbeat_s = 0.15

        got = {}

        def stream():
            conn = HTTPConnection("127.0.0.1", srv.server_port,
                                  timeout=30)
            try:
                conn.request(
                    "GET", "/docs/d/watch?since=0&limit=1000"
                           "&mode=sse&timeout=1.2")
                resp = conn.getresponse()
                got["status"] = resp.status
                got["ctype"] = resp.getheader("Content-Type")
                got["raw"] = resp.read()
            finally:
                conn.close()

        t = threading.Thread(target=stream, daemon=True,
                             name="watch-sse")
        t.start()
        time.sleep(0.5)
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 2))
        assert st == 200 and json.loads(raw)["accepted"]
        t.join(30)
        assert got["status"] == 200
        assert got["ctype"].startswith("text/event-stream")
        frames = [f for f in got["raw"].split(b"\n\n") if f]
        kinds = []
        replica = engine_mod.init(0)
        for f in frames:
            if f.startswith(b": hb"):
                kinds.append("hb")
                continue
            fields = dict()
            datas = []
            for line in f.split(b"\n"):
                k, _, v = line.partition(b": ")
                if k == b"data":
                    datas.append(v)
                else:
                    fields[k] = v
            kinds.append(fields.get(b"event", b"?").decode())
            if fields.get(b"event") == b"ops":
                replica.apply(json_codec.loads(b"\n".join(datas)))
        assert kinds[0] == "ops"             # the backlog
        assert kinds.count("ops") == 2       # + the live commit
        assert "hb" in kinds                 # idle keepalives
        assert kinds[-1] == "bye"            # named close
        st, raw, _ = req("GET", "/docs/d")
        assert replica.visible_values() == json.loads(raw)["values"]
        assert eng.get("d").watch.counts()["registered"] == 0


# -- conditional-GET windows + anti-entropy 304s -----------------------------


def test_ops_window_if_none_match_304():
    """The ``/ops`` windowed read serves the window's ETag; an
    unchanged re-pull with If-None-Match is a bodyless 304 still
    carrying the resume headers (the anti-entropy steady state)."""
    with _served() as (srv, req, eng):
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(1, 5))
        assert st == 200 and json.loads(raw)["accepted"]
        st, body, hdr = req("GET", "/docs/d/ops?since=0&limit=100")
        assert st == 200
        etag = hdr["ETag"]
        st, body2, hdr2 = req("GET", "/docs/d/ops?since=0&limit=100",
                              headers={"If-None-Match": etag})
        assert st == 304 and body2 == b""
        assert hdr2["X-Since-Next"] == hdr["X-Since-Next"]
        assert eng.get("d").readcache.snapshot()["not_modified"] == 1
        # new data invalidates: the same validator downloads again
        st, raw, _ = req("POST", "/docs/d/ops", body=_chain(2, 2))
        assert st == 200 and json.loads(raw)["accepted"]
        st, body3, _ = req("GET", "/docs/d/ops?since=0&limit=100",
                           headers={"If-None-Match": etag})
        assert st == 200 and body3 != b""


def _spawn_fleet(kv, names, **kw):
    fleet = {}
    for n in names:
        fleet[n] = FleetServer(n, kv, ttl_s=600.0,
                               ae_interval_s=3600.0, **kw)
    for fs in fleet.values():
        fs.node.refresh_ring()
    return fleet


def _stop_fleet(fleet):
    for fs in fleet.values():
        try:
            fs.stop()
        except Exception:  # noqa: BLE001 — teardown boundary
            pass


def _doc_owned_by(ring, owner, prefix="w"):
    for i in range(500):
        d = f"{prefix}{i}"
        if ring.primary(d) == owner:
            return d
    pytest.fail(f"no doc routed to {owner}")


def _req(port, method, path, body=None, headers=None, timeout=60):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def test_antientropy_dup_windows_skip_as_304():
    """The anti-entropy client sends the stored window validator as
    If-None-Match once its mark is steady: unchanged windows stop
    shipping bytes at all (the fleet's idle chatter goes bodyless)."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("a", "b"))
    try:
        doc = _doc_owned_by(fleet["a"].node.ring(), "a")
        st, raw, _ = _req(fleet["a"].port, "POST",
                          f"/docs/{doc}/ops", body=_chain(1, 5))
        assert st == 200, raw
        ae = fleet["b"].node.antientropy
        for _ in range(5):
            assert ae.sync_now() == {"a": True}
        peers = ae.stats()["peers"]["a"]
        # round 1 applies, round 2 re-lands the terminator window and
        # stores the (mark, etag) pair, rounds 3+ are bodyless 304s
        assert peers["dup_window_304s"] >= 2
        assert peers["dup_windows_skipped"] >= peers["dup_window_304s"]
        fa = _req(fleet["a"].port, "GET", f"/docs/{doc}")[2]
        fb = _req(fleet["b"].port, "GET", f"/docs/{doc}")[2]
        assert fa["X-State-Fingerprint"] == fb["X-State-Fingerprint"]
        # new data invalidates the validator: the next round applies
        st, raw, _ = _req(fleet["a"].port, "POST",
                          f"/docs/{doc}/ops", body=_chain(2, 3))
        assert st == 200, raw
        assert ae.sync_now() == {"a": True}
        fa = _req(fleet["a"].port, "GET", f"/docs/{doc}")[2]
        fb = _req(fleet["b"].port, "GET", f"/docs/{doc}")[2]
        assert fa["X-State-Fingerprint"] == fb["X-State-Fingerprint"]
    finally:
        _stop_fleet(fleet)


# -- fleet watch semantics ---------------------------------------------------


def test_watch_on_non_primary_lag_stamp_and_staleness_gate():
    """A watch on a non-primary serves LOCAL generations under the
    honest lag stamp; the bounded-staleness 503 outranks parking."""
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("a", "b"))
    try:
        doc = _doc_owned_by(fleet["a"].node.ring(), "a")
        st, raw, _ = _req(fleet["a"].port, "POST",
                          f"/docs/{doc}/ops", body=_chain(1, 5))
        assert st == 200, raw
        assert fleet["b"].node.antientropy.sync_now() == {"a": True}
        # resume delivery off b's LOCAL state, lag stamped
        st, body, hdr = _req(
            fleet["b"].port, "GET",
            f"/docs/{doc}/watch?since=0&limit=1000&timeout=0.2")
        assert st == 200
        assert hdr["X-Watch-Event"] in ("resume", "shed")
        assert hdr["X-Replica-Name"] == "b"
        assert float(hdr["X-Ae-Lag-Seconds"]) >= 0.0
        st, ref, _ = _req(fleet["b"].port, "GET",
                          f"/docs/{doc}/ops?since=0&limit=1000")
        assert body == ref
        # let the lag grow past a tight bound: the watch 503s at the
        # door instead of parking a reader whose bound is already blown
        time.sleep(0.15)
        st, raw, hdr = _req(
            fleet["b"].port, "GET",
            f"/docs/{doc}/watch?since=0&limit=1000&timeout=5",
            headers={"X-Max-Staleness": "0.05"})
        assert st == 503, raw
        assert "Retry-After" in hdr
        # and the registry took no slot for the refused watch
        d1 = fleet["b"].node.engine.get(doc)
        assert d1.watch.counts()["registered"] == 0
    finally:
        _stop_fleet(fleet)


def test_watch_under_netchaos_churn_exact_resume_zero_loss():
    """Chaos on the inter-node links — delays, duplicated windows,
    and connection CUTS (the per-request partition fault) — while a
    watcher on the NON-primary reconnects with its mark every round
    trip: parked generations stall at the cut, resume exactly at the
    heal, and the reassembled chain equals the converged document —
    no acked write lost, no window skipped, duplicates absorbed."""
    chaos = NetChaos(29, "delay=1-8@0.4;dup=0.3;cut=0.25")
    kv = MemoryKV()
    fleet = _spawn_fleet(kv, ("a", "b"), netchaos=chaos)
    try:
        print("REPLAY:", chaos.describe())
        doc = _doc_owned_by(fleet["a"].node.ring(), "a")
        stop = threading.Event()
        state = {"mark": 0, "deliveries": 0, "errors": []}
        replica = engine_mod.init(0)

        def watcher():
            # a FRESH connection per request: the reconnect-with-mark
            # path is exercised on every single round trip
            while not stop.is_set():
                try:
                    st, raw, hdr = _req(
                        fleet["b"].port, "GET",
                        f"/docs/{doc}/watch?since={state['mark']}"
                        f"&limit=8192&timeout=0.3", timeout=30)
                except OSError as e:
                    state["errors"].append(repr(e))
                    return
                if st in (404, 503):
                    time.sleep(0.01)      # not yet synced into b /
                    continue              # legal Retry-After shed
                if st != 200:
                    state["errors"].append(f"watch -> {st}")
                    return
                if hdr["X-Watch-Event"] == "timeout":
                    continue
                replica.apply(json_codec.loads(raw))
                state["mark"] = int(hdr["X-Since-Next"])
                state["deliveries"] += 1

        t = threading.Thread(target=watcher, daemon=True,
                             name="chaos-watch")
        t.start()
        prev = 0
        for k in range(6):
            st, raw, _ = _req(fleet["a"].port, "POST",
                              f"/docs/{doc}/ops",
                              body=_chain(3, 20, start=k * 20 + 1,
                                          prev=prev))
            prev = _ts(3, (k + 1) * 20)
            assert st == 200, raw
            # chaos-delayed/duplicated/cut pull into b: a cut round
            # legally fails whole — the watcher just stays parked —
            # and the retry IS the partition heal
            for _ in range(50):
                if fleet["b"].node.antientropy.sync_now() == \
                        {"a": True}:
                    break
            else:
                pytest.fail(f"sync never healed: {chaos.describe()}")
        # drain: give the watcher one more park cycle to collect the
        # final generation, then stop it
        deadline = time.monotonic() + 15
        st, raw, hdr = _req(fleet["b"].port, "GET",
                            f"/docs/{doc}/ops?since=0&limit=100000")
        final_mark = int(hdr["X-Since-Next"])
        while state["mark"] != final_mark:
            assert time.monotonic() < deadline, \
                (state, final_mark, chaos.describe())
            time.sleep(0.05)
        stop.set()
        t.join(30)
        assert state["errors"] == [], (state["errors"],
                                       chaos.describe())
        # generations may coalesce into one window between polls —
        # only the floor is deterministic
        assert state["deliveries"] >= 1
        st, raw, _ = _req(fleet["b"].port, "GET", f"/docs/{doc}")
        served = json.loads(raw)["values"]
        assert replica.visible_values() == served
        assert len(served) == 120         # zero acked-write loss
        assert chaos.stats()["counters"]["requests"] > 0
    finally:
        _stop_fleet(fleet)


# -- the loadgen watcher mode under the oracle -------------------------------


def test_loadgen_watcher_mode_oracle_clean():
    """The closed-loop harness with a watcher population: push reads
    flow into the session-guarantee oracle and hold at zero
    violations, the registries drain, and the report stamps the
    delivery classes + merged notify percentiles."""
    from crdt_graph_tpu.bench import loadgen
    cfg = loadgen.LoadgenConfig(
        n_sessions=6, n_docs=2, writes_per_session=4, delta_size=6,
        n_watchers=6, watch_timeout_s=1.0, seed=31)
    rep = loadgen.run(cfg)
    assert rep["errors"] == [], rep["errors"]
    assert rep["violations"] == [], rep["violations"]
    w = rep["watch"]
    assert w["watchers"] == 6
    assert w["deliveries"] > 0
    srv_stats = w["server"]
    assert srv_stats["notifies"] + srv_stats["resumes"] > 0
    assert srv_stats["registered"] == 0      # drained at teardown
    assert srv_stats["notify_ms"]["count"] == srv_stats["notifies"]
