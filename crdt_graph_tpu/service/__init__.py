"""Wire service: the server side of the reference's deployment model."""
from .http import make_server, serve
from .store import Document, DocumentStore

__all__ = ["Document", "DocumentStore", "make_server", "serve"]
