"""Wire service: the server side of the reference's deployment model."""
from ..serve import ServingEngine
from .http import make_server, serve
from .store import Document, DocumentStore

__all__ = ["Document", "DocumentStore", "ServingEngine", "make_server",
           "serve"]
