"""Server-side document store: replicated trees keyed by document id.

The reference's deployment model (README.md:5-9, 20-22) needs two server
roles it leaves to the application: a coordinator that hands out unique
numeric replica ids, and a relay/merger that moves operation batches
between replicas.  ``DocumentStore`` is both, backed by the TPU engine:
each document is one server replica that merges every client's deltas
(one batched kernel call per apply) and serves pull-based anti-entropy
(``operations_since``) to any client.

Observability counters (SURVEY §5 metrics row: ops merged, dedup hits,
rejected batches) are served alongside.

This is the LEGACY inline-merge store: one lock per document, held
across the kernel merge — reads of a document queue behind its merges.
The wire service now defaults to :class:`crdt_graph_tpu.serve.
ServingEngine` (same duck-typed surface: ``get``/``ids``/``encode_ops``/
``decode_ops``, documents exposing the read/write methods below), which
serves reads from published immutable snapshots and coalesces writes
through a merge scheduler (docs/SERVING.md).  ``Document`` remains the
simple embeddable single-threaded/locked container, and its
``apply``/``apply_body`` semantics are the reference behavior the
scheduler's sequential fallback preserves per request.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from .. import engine as engine_mod
from ..codec import json_codec
from ..core import operation as op_mod
from ..core.errors import CRDTError
from ..core.operation import Operation
# canonical definitions live with the serving engine (serve/engine.py);
# both write paths MUST agree — the replica-id scheme and the ingest
# crossover are wire-visible behavior, not per-store tuning
from ..serve.engine import SERVER_REPLICA, WIRE_FAST_BYTES

__all__ = ["Document", "DocumentStore", "SERVER_REPLICA"]


class Document:
    """One replicated document plus its merge counters."""

    def __init__(self, doc_id: str, max_depth: int = 16):
        self.doc_id = doc_id
        self.tree = engine_mod.init(SERVER_REPLICA, max_depth=max_depth)
        self.next_replica = 1
        self.ops_merged = 0
        self.dup_absorbed = 0
        self.batches_rejected = 0
        self.lock = threading.Lock()

    def assign_replica(self) -> int:
        with self.lock:
            rid = self.next_replica
            self.next_replica += 1
            return rid

    def apply(self, operation: Operation) -> Tuple[bool, Operation]:
        """Merge a client delta.  Returns (accepted, applied_ops).

        A rejected batch (causality gap / invalid path) leaves the document
        untouched — the client should sync and retry, the reference's
        recovery contract (CRDTree.elm:104-107)."""
        leaves = list(op_mod.iter_leaves(operation))
        with self.lock:
            return self._merge(lambda: self.tree.apply(operation),
                               len(leaves))

    # wire bodies at/above this take the column ingest path (native
    # parse, no per-op Python objects before the kernel) — shared with
    # the serving engine's parse crossover (class attr so tests can
    # monkeypatch the routing)
    WIRE_FAST_BYTES = WIRE_FAST_BYTES

    def apply_body(self, body,
                   trace_id: Optional[str] = None
                   ) -> Tuple[bool, Operation]:
        """Merge a raw wire body (``bytes`` as read off the socket, or
        ``str``; the threshold is in BYTES, so handlers should pass the
        undecoded body — ADVICE r4).  ``trace_id`` is accepted for
        write-path signature parity with ``ServedDoc.apply_body`` (the
        handler always passes one); the legacy inline store has no
        flight recorder, so it is ignored here.  Small deltas decode to op objects
        (sequence semantics, byte-for-byte the old path); bootstrap-size
        bodies stream through the native column ingest
        (engine.apply_wire) — the wire→objects→columns round trip
        dominated POST /ops at headline scale
        (scripts/bench_service_e2e.py)."""
        from .. import native
        if isinstance(body, str):
            body = body.encode()
        if len(body) < self.WIRE_FAST_BYTES or not native.available():
            return self.apply(DocumentStore.decode_ops(body))
        pnew = native.parse_pack(body, max_depth=self.tree._max_depth)
        with self.lock:
            return self._merge(lambda: self.tree.apply_packed(pnew),
                               pnew.num_ops)

    def _merge(self, run, n_leaves: int) -> Tuple[bool, Operation]:
        try:
            run()
        except CRDTError:
            self.batches_rejected += 1
            return False, op_mod.from_list([])
        applied = self.tree.last_operation
        n_applied = op_mod.count(applied)
        self.ops_merged += n_applied
        self.dup_absorbed += n_leaves - n_applied
        return True, applied

    def operations_since(self, ts: int) -> Operation:
        with self.lock:
            return self.tree.operations_since(ts)

    def dumps_since_bytes(self, ts: int) -> bytes:
        """Pre-encoded wire JSON for ``GET /ops`` — the bootstrap fast
        path (native column encoder, engine.TpuTree.dumps_since_bytes),
        written to the socket with no str round trip."""
        with self.lock:
            return self.tree.dumps_since_bytes(ts)

    def snapshot_packed(self) -> bytes:
        """Binary snapshot wire format: the packed-checkpoint npz bytes
        (engine.checkpoint_packed).  A client bootstraps a 1M-op doc
        from this in one transfer + ``restore_packed`` instead of
        replaying the JSON log, then catches up via ``/ops?since=``."""
        import io
        with self.lock:
            buf = io.BytesIO()
            # uncompressed: the lock is held while encoding, and zlib at
            # 1M ops costs seconds (scripts/bench_egress.py)
            self.tree.checkpoint_packed(buf, compress=False)
            return buf.getvalue()

    def snapshot(self) -> List[Any]:
        with self.lock:
            return self.tree.visible_values()

    def clock(self) -> Dict[str, int]:
        """The server's vector clock (replica id → last applied ts).

        Lets a client ask for exactly its missing suffix
        (``/ops?since=clock[my_replica]``) instead of replaying from 0 —
        the server-side face of the reference's ``lastReplicaTimestamp``
        (CRDTree.elm:637-639)."""
        with self.lock:
            return {str(r): ts for r, ts in self.tree._replicas.items()}

    def metrics(self) -> Dict[str, int]:
        with self.lock:
            return {
                "ops_merged": self.ops_merged,
                "dup_absorbed": self.dup_absorbed,
                "batches_rejected": self.batches_rejected,
                "num_visible": len(self.tree),
                "log_length": self.tree.log_length,
                "replicas_assigned": self.next_replica - 1,
            }


class DocumentStore:
    """All documents hosted by this server."""

    def __init__(self, max_depth: int = 16):
        self._docs: Dict[str, Document] = {}
        self._lock = threading.Lock()
        self._max_depth = max_depth

    def get(self, doc_id: str, create: bool = True) -> Optional[Document]:
        with self._lock:
            doc = self._docs.get(doc_id)
            if doc is None and create:
                doc = self._docs[doc_id] = Document(
                    doc_id, max_depth=self._max_depth)
            return doc

    def ids(self) -> List[str]:
        with self._lock:
            return sorted(self._docs)

    # -- wire-format helpers ---------------------------------------------

    @staticmethod
    def encode_ops(op: Operation) -> str:
        return json_codec.dumps(op)

    @staticmethod
    def decode_ops(payload) -> Operation:
        """Wire JSON (str or bytes) → Operation."""
        return json_codec.loads(payload)
