"""HTTP wire service speaking the reference JSON codec.

Endpoints (all JSON; the operation payloads are byte-compatible with the
reference codec, CRDTree/Operation.elm:109-159, so Elm clients — e.g. the
companion text editor — interoperate unmodified):

- ``POST /docs/{id}/replicas``         → ``{"replica": n}``  (coordinator
  role: unique numeric replica ids, README.md:20-22)
- ``POST /docs/{id}/ops``   body = op  → ``{"accepted": bool,
  "applied_count": n, "applied": op}`` (merge a delta; rejection =
  causality gap, client syncs and retries; ``applied`` is echoed only
  for deltas ≤ 4096 leaves — bootstrap-size pushes get the count)
- ``GET  /docs/{id}/ops?since=ts``     → op batch (pull anti-entropy,
  CRDTree.elm:390-418; served pre-encoded by the native column encoder)
- ``GET  /docs/{id}/snapshot``         → binary packed checkpoint (npz)
  — one-transfer bootstrap for big docs; claim an id via
  ``POST /replicas``, restore with
  ``TpuTree.restore_packed(io.BytesIO(body), replica=id)`` (the raw
  snapshot carries the SERVER's id), then catch up with ``/ops?since=``
- ``GET  /docs/{id}/clock``            → ``{"replicas": {rid: ts}}`` —
  the server's vector clock; pull ``/ops?since=clock[you]`` for exactly
  the missing suffix (server face of ``lastReplicaTimestamp``,
  CRDTree.elm:637-639)
- ``GET  /docs/{id}``                  → ``{"values": [...]}`` (visible doc)
- ``GET  /docs/{id}/metrics`` and ``GET /metrics`` → counters
- ``GET  /metrics/scheduler``          → serving-engine counters + spans
- ``GET  /metrics/prom``               → unified Prometheus-style text
  exposition (doc counters, scheduler histograms WITH bucket bounds,
  span registry, flight-recorder gauges — docs/OBSERVABILITY.md)
- ``GET  /debug/flight``               → flight-recorder ring as JSON
  (per-commit records: trace_ids, stage breakdown, fingerprints)

Write tracing: ``POST /docs/{id}/ops`` mints a ``trace_id`` at
admission (or adopts a well-formed ``X-Trace-Id`` request header),
threads it through the coalescing scheduler into the commit's flight
record, and echoes it in every response (body + ``X-Trace-Id``).

Read tracing (ISSUE 6): ``GET /docs/{id}`` and ``GET /docs/{id}/
snapshot`` resolve body AND headers against ONE snapshot view and echo
``X-Snapshot-Fingerprint`` + ``X-Commit-Seq`` (the served snapshot's
identity) plus an adopted-or-minted ``X-Session-Id`` — so reads are as
attributable as writes and a session-guarantee checker
(obs/oracle.py) can join every read to the commit stream.  Writes
echo a well-formed client ``X-Session-Id`` too.

Read-path egress (ISSUE 15; docs/SERVING.md §Read path & egress):
document and snapshot reads carry an ``ETag`` (the quoted
replica-independent state fingerprint) and honor ``If-None-Match`` —
an unchanged document answers ``304`` with the full correlation
header set (``X-Commit-Seq``/``X-Replica-*``/``X-Ae-Lag-Seconds``)
but no body; the bounded-staleness 503 gate runs FIRST, so a 304
never outranks the staleness contract.  200 bodies come from the
snapshot's per-generation encoded-body cache (serve/snapshot.py) and
ship as memoryviews — no per-request ``json.dumps`` or list copy.

Run: ``python -m crdt_graph_tpu.service [port]`` or embed via
``serve(port)`` / ``make_server(port)``.

Concurrency design (serve/, docs/SERVING.md): reads and merges are
decoupled by the serving engine.  Every read endpoint (doc values,
``/ops?since=``, ``/clock``, ``/snapshot``, metrics) resolves against
the document's PUBLISHED IMMUTABLE SNAPSHOT — swapped in atomically on
each merge commit — so reads never take a merge lock and never stall
behind a large catch-up merge.  ``POST /ops`` parses the body in the
handler thread, enqueues the delta on the document's bounded merge
queue, and blocks until the scheduler thread has fused it (with every
other delta pending on that document, and with other documents' merges
in one batched launch when they coincide) and published the commit's
snapshot — so a client always reads its own writes.  Backpressure is
explicit: a full queue answers ``429`` with a ``Retry-After`` estimate
from the document's recent commit latency, without touching the tree;
giant pushes merge as bounded chunks so they cannot monopolize the
scheduler.  ``POST /ops`` bodies are capped (``max_body``, default
128 MB ≈ a 2M-op JSON batch) and oversized requests get 413 without
reading the body.  Passing an explicit ``DocumentStore`` to
``make_server`` keeps the legacy lock-per-document inline-merge path
(same wire contract).
"""
from __future__ import annotations

import json
import re
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..codec.json_codec import DecodeError
from ..core.errors import CheckpointError
from ..obs import prom as prom_mod
from ..obs.trace import (AE_LAG_HEADER, AE_PEER_HEADER,
                         CATCHUP_REMAINING_HEADER,
                         COMMIT_SEQ_HEADER,
                         FORWARDED_HEADER, MAX_STALENESS_HEADER,
                         SESSION_HEADER,
                         SINCE_FOUND_HEADER, SINCE_MORE_HEADER,
                         SINCE_NEXT_HEADER, SNAP_FP_HEADER,
                         TRACE_HEADER, ensure_session_id,
                         ensure_trace_id, is_valid_id)
from ..cluster.gateway import ForwardError
from ..serve import (ECHO_LIMIT, QueueFull, SchedulerError,
                     SchedulerStopped, ServingEngine)
from .store import DocumentStore

_DOC = re.compile(r"^/docs/([A-Za-z0-9_.-]+)(/.*)?$")


def etag_matches(header: Optional[str], etag: str) -> bool:
    """Whether an ``If-None-Match`` header matches ``etag`` (the
    snapshot's quoted state fingerprint).  RFC 7232 weak-comparison
    shape: ``*`` matches anything, the list splits on commas, a ``W/``
    prefix is ignored.  Malformed members simply fail to match — a
    garbage header degrades to an unconditional GET, never an error
    (ISSUE 15 satellite)."""
    if not header:
        return False
    if header.strip() == "*":
        return True
    for tok in header.split(","):
        tok = tok.strip()
        if tok.startswith("W/"):
            tok = tok[2:]
        if tok == etag:
            return True
    return False


DEFAULT_MAX_BODY = 128 << 20
# ECHO_LIMIT (serve/engine.py): applied-ops echo cap in leaves; above it
# the response carries the count only.  Imported, not redefined — the
# scheduler stops materializing echo objects at the same bound.


def make_handler(store: DocumentStore, max_body: int = DEFAULT_MAX_BODY):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):   # quiet by default
            pass

        def _send(self, code: int, payload, headers=None) -> None:
            self._send_raw(code, json.dumps(payload).encode(),
                           headers=headers)

        def _send_raw(self, code: int, body,
                      ctype: str = "application/json",
                      headers=None) -> None:
            """Ship one response.  ``body`` may be any buffer — cached
            snapshot bodies go out as a memoryview so a shared
            generation-wide ``bytes`` object is never copied per
            request.  A 304 carries its headers (the conditional-GET
            contract: seq/replica/lag stamps intact) but no body, and
            when the handler decided to close the connection the
            client is TOLD so (keep-alive pools must not discover it
            by a failed reuse)."""
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length",
                             "0" if code == 304 else str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if self.close_connection:
                self.send_header("Connection", "close")
            self.end_headers()
            if code != 304 and len(body):
                self.wfile.write(body if isinstance(body, memoryview)
                                 else memoryview(body))

        def _route(self) -> Tuple[Optional[str], str, dict]:
            url = urlparse(self.path)
            m = _DOC.match(url.path)
            if not m:
                return None, url.path, parse_qs(url.query)
            return m.group(1), (m.group(2) or ""), parse_qs(url.query)

        def _content_length(self) -> Optional[int]:
            """Content-Length as an int, or None (with a 400 already
            sent) when the header is malformed — int() raising inside
            the handler would abort the connection instead of
            answering (ADVICE r4)."""
            raw = self.headers.get("Content-Length", 0)
            try:
                return int(raw)
            except ValueError:
                self.close_connection = True
                self._send(400, {"error": "malformed Content-Length"})
                return None

        def _body(self, n: int) -> bytes:
            return self.rfile.read(n)

        def _read_trace_headers(self, snap, ae_lag_hdr=None) -> dict:
            """Read-path correlation headers (obs/trace.py): the served
            snapshot's identity plus the session id (adopted from a
            well-formed ``X-Session-Id``, minted otherwise).  A fleet
            store (cluster/gateway.py) additionally stamps the replica
            identity + replica-independent state fingerprint, so a
            replica-local read's staleness is wire-observable
            (``ae_lag_hdr`` carries the staleness gate's own lag
            sample so it is computed once per request)."""
            out = {
                SNAP_FP_HEADER: snap.fingerprint(),
                COMMIT_SEQ_HEADER: str(snap.seq),
                SESSION_HEADER: ensure_session_id(
                    self.headers.get(SESSION_HEADER)),
            }
            if hasattr(store, "extra_read_headers"):
                out.update(store.extra_read_headers(
                    snap, ae_lag_hdr=ae_lag_hdr))
            return out

        def do_GET(self):
            doc_id, sub, query = self._route()
            if doc_id is None:
                if sub == "/metrics":
                    self._send(200, {d: store.get(d).metrics()
                                     for d in store.ids()})
                elif sub == "/metrics/scheduler" and \
                        hasattr(store, "scheduler_metrics"):
                    self._send(200, store.scheduler_metrics())
                elif sub == "/metrics/prom" and \
                        hasattr(store, "render_prom"):
                    # the unified Prometheus-style scrape: doc counters,
                    # scheduler histograms with bucket bounds, the span
                    # registry, flight gauges (obs/prom.py)
                    self._send_raw(200, store.render_prom().encode(),
                                   ctype=prom_mod.CONTENT_TYPE)
                elif sub == "/debug/flight" and \
                        hasattr(store, "debug_flight"):
                    # the flight recorder's ring + counters, enriched
                    # for post-mortem without waiting for a dump file
                    self._send(200, store.debug_flight())
                elif sub == "/docs":
                    self._send(200, {"docs": store.ids()})
                elif sub == "/cluster" and \
                        hasattr(store, "cluster_view"):
                    # fleet introspection: membership, lease, ring
                    # spread, anti-entropy state (docs/CLUSTER.md)
                    self._send(200, store.cluster_view())
                else:
                    self._send(404, {"error": "not found"})
                return
            doc = store.get(doc_id, create=False)
            if doc is None:
                # rejoining-node catch-up (cluster/gateway.py): when a
                # fleet peer HAS this document, this node is merely
                # behind (restart / fresh ring ownership) — answer an
                # honest 503 + Retry-After with a catch-up hint and
                # trigger a priority anti-entropy pull, instead of the
                # long 404 window the background sync left before
                cs = store.catchup_status(doc_id) \
                    if hasattr(store, "catchup_status") else None
                if cs is not None:
                    self._send(
                        503, {"error": f"document {doc_id} is being "
                                       "caught up from the fleet",
                              "retry_after_s": cs["retry_after_s"]},
                        headers={"Retry-After": str(cs["retry_after_s"]),
                                 CATCHUP_REMAINING_HEADER:
                                     str(cs["remaining"])})
                    return
                self._send(404, {"error": f"no document {doc_id}"})
                return
            ae_lag_hdr = None
            if sub in ("", "/snapshot") and \
                    hasattr(store, "check_staleness"):
                # bounded-staleness read contract (docs/CLUSTER.md
                # §Partitions & staleness): a read bounded by
                # X-Max-Staleness (or the server's
                # GRAFT_MAX_STALENESS_S default) on a replica whose
                # anti-entropy lag exceeds the bound gets an honest
                # 503 + Retry-After instead of silently stale data —
                # a partitioned replica degrades, it does not lie.
                # The gate's lag sample also feeds the served read's
                # X-Ae-Lag-Seconds stamp (one sample per request —
                # gate and stamp can never disagree)
                stale, ae_lag_hdr = store.check_staleness(
                    self.headers.get(MAX_STALENESS_HEADER))
                if stale is not None:
                    # lag_s is None when unbounded (a replica that has
                    # never fully synced) — Infinity is not valid JSON
                    lag_txt = "unbounded" if stale["lag_s"] is None \
                        else f"{stale['lag_s']}s"
                    self._send(
                        503,
                        {"error": f"replica staleness {lag_txt} "
                                  f"exceeds the {stale['bound_s']}s "
                                  "bound",
                         "ae_lag_s": stale["lag_s"],
                         "retry_after_s": stale["retry_after_s"]},
                        headers={
                            "Retry-After": str(stale["retry_after_s"]),
                            AE_LAG_HEADER: ae_lag_hdr})
                    return
            if sub == "":
                if hasattr(doc, "read_view"):
                    # body and headers come from the SAME snapshot: a
                    # checker correlating the fingerprint header to the
                    # values body must never straddle a publish.  The
                    # body is the generation's CACHED encoding
                    # (serve/snapshot.py) and the read is conditional:
                    # If-None-Match against the state-fingerprint ETag
                    # answers 304 with the full header set but no body
                    # — polling readers of an idle doc stop paying
                    # O(doc) egress.  The staleness gate above already
                    # overrode this path with its 503 when the bound
                    # was exceeded: a 304 never vouches for freshness
                    # beyond what the lag stamp admits.
                    snap = doc.read_view()
                    hdrs = self._read_trace_headers(
                        snap, ae_lag_hdr=ae_lag_hdr)
                    hdrs["ETag"] = snap.etag()
                    if etag_matches(self.headers.get("If-None-Match"),
                                    snap.etag()):
                        snap.cache_stats.served_304()
                        self._send_raw(304, b"", headers=hdrs)
                    else:
                        self._send_raw(200, snap.values_body(),
                                       headers=hdrs)
                else:       # legacy DocumentStore: no snapshot identity
                    self._send(200, {"values": doc.snapshot()})
            elif sub == "/ops":
                try:
                    since = int(query.get("since", ["0"])[0])
                    limit = int(query.get("limit", ["0"])[0])
                except ValueError:
                    self._send(400, {"error": "since and limit must "
                                              "be integers"})
                    return
                # a pull that names its fleet node (X-Ae-Peer) feeds
                # the causal-stability watermark: the peer provably
                # consumed our log through `since`, which is what
                # gates the cascade op-log's checkpoint advancement
                # and segment GC (cluster/gateway.py, docs/OPLOG.md)
                peer = self.headers.get(AE_PEER_HEADER)
                if peer and hasattr(store, "note_peer_mark"):
                    store.note_peer_mark(doc_id, peer, since)
                # pre-encoded fast path: the bootstrap contract serves
                # the full log, so avoid a json.loads/dumps round trip.
                # With ?limit= (anti-entropy pulls) the window is
                # bounded + resumable and its state rides the
                # X-Since-* headers — the body stays a plain wire
                # batch either way (engine.packed_since_window)
                try:
                    if limit > 0 and hasattr(doc, "ops_since_window"):
                        body, meta = doc.ops_since_window(since, limit)
                        self._send_raw(200, body, headers={
                            SINCE_FOUND_HEADER:
                                "1" if meta["found"] else "0",
                            SINCE_MORE_HEADER:
                                "1" if meta["more"] else "0",
                            **({SINCE_NEXT_HEADER:
                                str(meta["next_since"])}
                               if meta["next_since"] is not None
                               else {}),
                        })
                    else:
                        self._send_raw(200,
                                       doc.dumps_since_bytes(since))
                except CheckpointError as e:
                    # a window that touches a quarantined (bit-rotted)
                    # tier file: typed refusal + Retry-After — the
                    # scrub repair path is healing the range; corrupt
                    # bytes are NEVER served (docs/DURABILITY.md
                    # §Scrub & repair)
                    self._send(503, {"error": str(e),
                                     "retry_after_s": 5},
                               headers={"Retry-After": "5"})
            elif sub == "/snapshot":
                try:
                    if hasattr(doc, "read_view"):
                        snap = doc.read_view()
                        hdrs = self._read_trace_headers(
                            snap, ae_lag_hdr=ae_lag_hdr)
                        hdrs["ETag"] = snap.etag()
                        if etag_matches(
                                self.headers.get("If-None-Match"),
                                snap.etag()):
                            # the 304 fires BEFORE checkpoint_bytes:
                            # an unchanged bootstrap poll skips the
                            # whole O(doc) npz assembly, not just the
                            # egress
                            snap.cache_stats.served_304()
                            self._send_raw(
                                304, b"",
                                ctype="application/octet-stream",
                                headers=hdrs)
                            return
                        self._send_raw(
                            200, snap.checkpoint_bytes(),
                            ctype="application/octet-stream",
                            headers=hdrs)
                    else:
                        self._send_raw(200, doc.snapshot_packed(),
                                       ctype="application/octet-stream")
                except CheckpointError as e:
                    # same quarantine rule as /ops: a checkpoint
                    # reassembly that needs a quarantined file refuses
                    # honestly instead of serving corrupt bytes
                    self._send(503, {"error": str(e),
                                     "retry_after_s": 5},
                               headers={"Retry-After": "5"})
            elif sub == "/clock":
                if hasattr(doc, "snapshot_view"):
                    # the clock wire body is cached per generation too.
                    # Deliberately snapshot_view, NOT read_view: the
                    # one-shot GRAFT_ORACLE_FAULT stale/regress faults
                    # must fire on a VALUE/snapshot read (where the
                    # oracle can catch them), never be consumed by a
                    # clock poll — doc.clock() always read the
                    # published snapshot directly
                    self._send_raw(200,
                                   doc.snapshot_view().clock_body())
                else:
                    self._send(200, {"replicas": doc.clock()})
            elif sub == "/metrics":
                self._send(200, doc.metrics())
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            # reject oversized bodies before reading them (the connection
            # closes: unread body bytes would otherwise be parsed as the
            # next request line on keep-alive)
            n = self._content_length()
            if n is None:
                return
            if n > max_body:
                self.close_connection = True
                self._send(413, {"error": f"body exceeds {max_body} "
                                          "bytes; chunk the batch"})
                return
            # always drain the request body first (keep-alive connections
            # would otherwise read leftover body bytes as the next request
            # line), and validate the route BEFORE store.get(create=True)
            # so invalid requests never materialize documents
            body = self._body(n)
            doc_id, sub, _ = self._route()
            if doc_id is None or sub not in ("/replicas", "/ops"):
                self._send(404, {"error": "not found"})
                return
            if sub == "/replicas":
                # a fleet store allocates from the shared KV counter so
                # ids stay unique across servers AND across primary
                # failover; the single-server path keeps the local
                # per-document counter
                if hasattr(store, "assign_replica"):
                    store.get(doc_id)      # materialize the local doc
                    rid = store.assign_replica(doc_id)
                else:
                    rid = store.get(doc_id).assign_replica()
                self._send(200, {"replica": rid})
                return
            # fleet write routing (cluster/gateway.py): a non-primary
            # node relays the request to the document's primary and
            # answers with the PRIMARY's response verbatim (status,
            # trace echo, Retry-After backpressure included); a request
            # already forwarded once always applies locally — one hop,
            # no loops
            if hasattr(store, "write_route") \
                    and self.headers.get(FORWARDED_HEADER) is None:
                try:
                    fwd = store.forward_write(
                        doc_id, body,
                        {TRACE_HEADER: self.headers.get(TRACE_HEADER),
                         SESSION_HEADER:
                             self.headers.get(SESSION_HEADER)})
                except ForwardError as e:
                    self._send(503, {"error": str(e)},
                               headers={"Retry-After":
                                        str(e.retry_after_s)})
                    return
                if fwd is not None:
                    status, out_body, out_headers = fwd
                    ctype = out_headers.pop("Content-Type",
                                            "application/json")
                    self._send_raw(status, out_body, ctype=ctype,
                                   headers=out_headers)
                    return
            elif self.headers.get(FORWARDED_HEADER) is not None \
                    and hasattr(store, "note_forwarded_in"):
                store.note_forwarded_in()
            # trace admission point (obs/trace.py): mint — or adopt a
            # well-formed client-supplied X-Trace-Id — BEFORE parsing,
            # so even a malformed or shed request is attributable; the
            # id rides the write ticket into the commit's flight record
            # and is echoed in the response (body + header) so a client
            # report joins against the server-side record
            trace_id = ensure_trace_id(self.headers.get(TRACE_HEADER))
            trace_hdr = {TRACE_HEADER: trace_id}
            # echo a client-supplied session id on writes too, so one
            # session's whole request stream correlates on both paths
            sess = self.headers.get(SESSION_HEADER)
            if is_valid_id(sess):
                trace_hdr[SESSION_HEADER] = sess
            try:
                accepted, applied = store.get(doc_id).apply_body(
                    body, trace_id=trace_id)
            except QueueFull as e:
                # admission control: the merge queue is at capacity —
                # shed the write at the door with the server's own
                # drain-time estimate (serve/queue.py)
                self._send(429, {"error": str(e),
                                 "retry_after_s": e.retry_after_s,
                                 "trace_id": trace_id},
                           headers={"Retry-After": str(e.retry_after_s),
                                    **trace_hdr})
                return
            except SchedulerStopped as e:
                self._send(503, {"error": str(e), "trace_id": trace_id},
                           headers=trace_hdr)
                return
            except SchedulerError as e:
                # server-side merge failure: MUST answer 500, never a
                # client-error class — this request was well-formed and
                # retrying it later is legitimate
                self._send(500, {"error": str(e), "trace_id": trace_id},
                           headers=trace_hdr)
                return
            except (DecodeError, json.JSONDecodeError, ValueError) as e:
                # ValueError: the native parser's rejections (same
                # malformed-input class as DecodeError)
                self._send(400, {"error": str(e), "trace_id": trace_id},
                           headers=trace_hdr)
                return
            from ..core import operation as op_mod
            n_applied = op_mod.count(applied)
            payload = {"accepted": accepted, "applied_count": n_applied,
                       "trace_id": trace_id}
            if hasattr(store, "served_by"):
                # fleet attribution: the node that committed this
                # write (the oracle keys read-your-writes on it)
                payload["served_by"] = store.served_by()
            # echo the applied ops only for interactive-scale deltas —
            # for a bootstrap-size push, re-encoding the whole batch
            # into the response costs multiples of the merge itself
            # (scripts/bench_service_e2e.py) and the client already has
            # the ops it sent
            if n_applied <= ECHO_LIMIT:
                payload["applied"] = json.loads(store.encode_ops(applied))
            self._send(200 if accepted else 409, payload,
                       headers=trace_hdr)

    return Handler


class ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that shuts an OWNED serving engine down with
    the server — the scheduler thread stops and any in-flight write
    tickets resolve (503) before ``server_close`` returns.

    Connections are HTTP/1.1 keep-alive (every client path pools them
    through :class:`~crdt_graph_tpu.cluster.pool.ConnectionPool`), so
    ``server_close`` also force-closes every ESTABLISHED connection:
    stopping the accept loop alone would leave handler threads serving
    pooled keep-alive connections of a "crashed" fleet member — a
    zombie the per-request-connection era never had (the chaos tests'
    kill semantics depend on a crash actually severing the wire)."""

    owned_engine: Optional[ServingEngine] = None

    def __init__(self, *args, **kw):
        self._conn_lock = threading.Lock()
        self._live_conns: set = set()
        super().__init__(*args, **kw)

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._live_conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._live_conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conn_lock:
            live = list(self._live_conns)
            self._live_conns.clear()
        for sock in live:
            # a hard RST-like severance: handler threads blocked on
            # the next keep-alive request line wake with EOF and exit
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self.owned_engine is not None:
            self.owned_engine.close()

    def handle_error(self, request, client_address):
        """A client that hung up mid-response is routine operation —
        long-poll writers time out, fleet peers crash (chaos tests
        kill them on purpose) — not a stack trace on stderr.  Anything
        that is NOT a connection death still gets the default dump."""
        import sys as _sys
        exc = _sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            ConnectionAbortedError, TimeoutError)):
            return
        super().handle_error(request, client_address)


def make_server(port: int = 0, store=None,
                max_body: int = DEFAULT_MAX_BODY) -> ThreadingHTTPServer:
    """Build the wire server.  ``store`` defaults to a fresh
    :class:`~crdt_graph_tpu.serve.ServingEngine` (snapshot reads +
    merge scheduler; closed with the server); pass a ``DocumentStore``
    for the legacy inline-merge path or a pre-configured engine the
    caller owns."""
    owned = store is None
    store = store if store is not None else ServingEngine()
    server = ServingHTTPServer(("127.0.0.1", port),
                               make_handler(store, max_body=max_body))
    server.store = store
    if owned:
        server.owned_engine = store
    return server


def serve(port: int = 8900) -> None:
    server = make_server(port)
    print(f"crdt_graph_tpu service on 127.0.0.1:{server.server_port}")
    server.serve_forever()
