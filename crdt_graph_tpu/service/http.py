"""HTTP wire service speaking the reference JSON codec.

Endpoints (all JSON; the operation payloads are byte-compatible with the
reference codec, CRDTree/Operation.elm:109-159, so Elm clients — e.g. the
companion text editor — interoperate unmodified):

- ``POST /docs/{id}/replicas``         → ``{"replica": n}``  (coordinator
  role: unique numeric replica ids, README.md:20-22)
- ``POST /docs/{id}/ops``   body = op  → ``{"accepted": bool,
  "applied_count": n, "applied": op}`` (merge a delta; rejection =
  causality gap, client syncs and retries; ``applied`` is echoed only
  for deltas ≤ 4096 leaves — bootstrap-size pushes get the count)
- ``GET  /docs/{id}/ops?since=ts``     → op batch (pull anti-entropy,
  CRDTree.elm:390-418; served pre-encoded by the native column encoder)
- ``GET  /docs/{id}/snapshot``         → binary packed checkpoint (npz)
  — one-transfer bootstrap for big docs; claim an id via
  ``POST /replicas``, restore with
  ``TpuTree.restore_packed(io.BytesIO(body), replica=id)`` (the raw
  snapshot carries the SERVER's id), then catch up with ``/ops?since=``
- ``GET  /docs/{id}/clock``            → ``{"replicas": {rid: ts}}`` —
  the server's vector clock; pull ``/ops?since=clock[you]`` for exactly
  the missing suffix (server face of ``lastReplicaTimestamp``,
  CRDTree.elm:637-639)
- ``GET  /docs/{id}``                  → ``{"values": [...]}`` (visible doc)
- ``GET  /docs/{id}/watch?since=ts``   → delta-push fan-out
  (serve/watch.py; docs/SERVING.md §Watch & fan-out): long-poll
  (default; one ops window per response, parks until the next publish)
  or SSE (``mode=sse``; one ``ops`` event per generation on a single
  stream).  Bounded admission (429 past ``GRAFT_WATCH_MAX``),
  slow-consumer shed with ``X-Watch-Resume-Since``, heartbeats, and
  the bounded-staleness 503 gate ahead of parking.
- ``GET  /docs/{id}/metrics`` and ``GET /metrics`` → counters
- ``GET  /metrics/scheduler``          → serving-engine counters + spans
- ``GET  /metrics/prom``               → unified Prometheus-style text
  exposition (doc counters, scheduler histograms WITH bucket bounds,
  span registry, flight-recorder gauges — docs/OBSERVABILITY.md)
- ``GET  /debug/flight``               → flight-recorder ring as JSON
  (per-commit records: trace_ids, stage breakdown, fingerprints)

Write tracing: ``POST /docs/{id}/ops`` mints a ``trace_id`` at
admission (or adopts a well-formed ``X-Trace-Id`` request header),
threads it through the coalescing scheduler into the commit's flight
record, and echoes it in every response (body + ``X-Trace-Id``).

Read tracing (ISSUE 6): ``GET /docs/{id}`` and ``GET /docs/{id}/
snapshot`` resolve body AND headers against ONE snapshot view and echo
``X-Snapshot-Fingerprint`` + ``X-Commit-Seq`` (the served snapshot's
identity) plus an adopted-or-minted ``X-Session-Id`` — so reads are as
attributable as writes and a session-guarantee checker
(obs/oracle.py) can join every read to the commit stream.  Writes
echo a well-formed client ``X-Session-Id`` too.

Read-path egress (ISSUE 15; docs/SERVING.md §Read path & egress):
document and snapshot reads carry an ``ETag`` (the quoted
replica-independent state fingerprint) and honor ``If-None-Match`` —
an unchanged document answers ``304`` with the full correlation
header set (``X-Commit-Seq``/``X-Replica-*``/``X-Ae-Lag-Seconds``)
but no body; the bounded-staleness 503 gate runs FIRST, so a 304
never outranks the staleness contract.  200 bodies come from the
snapshot's per-generation encoded-body cache (serve/snapshot.py) and
ship as memoryviews — no per-request ``json.dumps`` or list copy.

Run: ``python -m crdt_graph_tpu.service [port]`` or embed via
``serve(port)`` / ``make_server(port)``.

Concurrency design (serve/, docs/SERVING.md): reads and merges are
decoupled by the serving engine.  Every read endpoint (doc values,
``/ops?since=``, ``/clock``, ``/snapshot``, metrics) resolves against
the document's PUBLISHED IMMUTABLE SNAPSHOT — swapped in atomically on
each merge commit — so reads never take a merge lock and never stall
behind a large catch-up merge.  ``POST /ops`` parses the body in the
handler thread, enqueues the delta on the document's bounded merge
queue, and blocks until the scheduler thread has fused it (with every
other delta pending on that document, and with other documents' merges
in one batched launch when they coincide) and published the commit's
snapshot — so a client always reads its own writes.  Backpressure is
explicit: a full queue answers ``429`` with a ``Retry-After`` estimate
from the document's recent commit latency, without touching the tree;
giant pushes merge as bounded chunks so they cannot monopolize the
scheduler.  ``POST /ops`` bodies are capped (``max_body``, default
128 MB ≈ a 2M-op JSON batch) and oversized requests get 413 without
reading the body.  Passing an explicit ``DocumentStore`` to
``make_server`` keeps the legacy lock-per-document inline-merge path
(same wire contract).
"""
from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..codec.json_codec import DecodeError
from ..core.errors import CheckpointError
from ..obs import prom as prom_mod
from ..obs.trace import (AE_LAG_HEADER, AE_PEER_HEADER,
                         CATCHUP_REMAINING_HEADER,
                         COMMIT_SEQ_HEADER,
                         FORWARDED_HEADER, MAX_STALENESS_HEADER,
                         SESSION_HEADER,
                         SINCE_FOUND_HEADER, SINCE_MORE_HEADER,
                         SINCE_NEXT_HEADER, SNAP_FP_HEADER,
                         SPAN_CTX_HEADER, TRACE_FRONTIER_HEADER,
                         TRACE_HEADER, WATCH_EVENT_HEADER,
                         WATCH_RESUME_HEADER, ensure_session_id,
                         ensure_trace_id, is_valid_id)
from ..cluster.gateway import ForwardError
from ..oplog import EMPTY_BATCH_BYTES
from ..serve import (ECHO_LIMIT, QueueFull, SchedulerError,
                     SchedulerStopped, ServingEngine)
from ..serve import watch as watch_mod
from ..serve.watch import WatchClosed, WatchFull
from .store import DocumentStore

# default and ceiling for one watch request's ops window (leaves): a
# caught-up watcher population all asks for the same (since, limit), so
# ONE value here is what makes the generation's encode shared
DEFAULT_WATCH_LIMIT = 8192

_DOC = re.compile(r"^/docs/([A-Za-z0-9_.-]+)(/.*)?$")


def etag_matches(header: Optional[str], etag: str) -> bool:
    """Whether an ``If-None-Match`` header matches ``etag`` (the
    snapshot's quoted state fingerprint).  RFC 7232 weak-comparison
    shape: ``*`` matches anything, the list splits on commas, a ``W/``
    prefix is ignored.  Malformed members simply fail to match — a
    garbage header degrades to an unconditional GET, never an error
    (ISSUE 15 satellite)."""
    if not header:
        return False
    if header.strip() == "*":
        return True
    for tok in header.split(","):
        tok = tok.strip()
        if tok.startswith("W/"):
            tok = tok[2:]
        if tok == etag:
            return True
    return False


DEFAULT_MAX_BODY = 128 << 20
# ECHO_LIMIT (serve/engine.py): applied-ops echo cap in leaves; above it
# the response carries the count only.  Imported, not redefined — the
# scheduler stops materializing echo objects at the same bound.


def make_handler(store: DocumentStore, max_body: int = DEFAULT_MAX_BODY):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):   # quiet by default
            pass

        def _send(self, code: int, payload, headers=None) -> None:
            self._send_raw(code, json.dumps(payload).encode(),
                           headers=headers)

        def _send_raw(self, code: int, body,
                      ctype: str = "application/json",
                      headers=None) -> None:
            """Ship one response.  ``body`` may be any buffer — cached
            snapshot bodies go out as a memoryview so a shared
            generation-wide ``bytes`` object is never copied per
            request.  A 304 carries its headers (the conditional-GET
            contract: seq/replica/lag stamps intact) but no body, and
            when the handler decided to close the connection the
            client is TOLD so (keep-alive pools must not discover it
            by a failed reuse)."""
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length",
                             "0" if code == 304 else str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if self.close_connection:
                self.send_header("Connection", "close")
            self.end_headers()
            if code != 304 and len(body):
                self.wfile.write(body if isinstance(body, memoryview)
                                 else memoryview(body))

        def _serve_ops_plan(self, doc, plan) -> bool:
            """Ship a zero-copy ``/ops`` window (docs/SERVING.md
            §Zero-copy egress): the plan's literal pieces go out with
            ``sendall`` and its sidecar file ranges with
            ``os.sendfile`` straight from page cache to socket — the
            window body is never materialized in this process.  The
            bytes on the wire, the ``ETag``, the ``X-Since-*`` resume
            headers, and the 304 behavior are IDENTICAL to the
            buffered path.  Returns True when the response was handled
            (200, 304, or a died-mid-stream connection), False when
            the caller should fall back to buffered (a planned sidecar
            vanished before any byte was sent)."""
            chunks, total, meta, snap = plan
            hdrs = {
                SINCE_FOUND_HEADER: "1" if meta["found"] else "0",
                SINCE_MORE_HEADER: "1" if meta["more"] else "0",
            }
            if meta["next_since"] is not None:
                hdrs[SINCE_NEXT_HEADER] = str(meta["next_since"])
            hdrs["ETag"] = meta["etag"]
            # same frontier stamp as the buffered branch — the two
            # /ops paths must carry identical headers (ISSUE 20)
            if hasattr(store, "trace_frontier_header"):
                tf = store.trace_frontier_header(
                    getattr(doc, "doc_id", None))
                if tf:
                    hdrs[TRACE_FRONTIER_HEADER] = tf
            if etag_matches(self.headers.get("If-None-Match"),
                            meta["etag"]):
                if hasattr(doc, "readcache"):
                    doc.readcache.served_304()
                self._send_raw(304, b"", headers=hdrs)
                return True
            sf = getattr(doc, "sendfile_stats", None)
            # open every planned file BEFORE the status line goes out:
            # an open failure here still has the buffered fallback
            fds: dict = {}
            try:
                for c in chunks:
                    if c[0] == "f" and c[1] not in fds:
                        fds[c[1]] = os.open(c[1], os.O_RDONLY)
            except OSError:
                for fd in fds.values():
                    os.close(fd)
                if sf is not None:
                    sf.add("fallback")
                return False
            try:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(total))
                for k, v in hdrs.items():
                    self.send_header(k, v)
                if self.close_connection:
                    self.send_header("Connection", "close")
                self.end_headers()
                # drain the handler's buffered writer before touching
                # the raw socket — header bytes must precede body bytes
                self.wfile.flush()
                out = self.connection.fileno()
                file_bytes = 0
                for c in chunks:
                    if c[0] == "b":
                        self.connection.sendall(c[1])
                        continue
                    _, path, off, remaining = c
                    fd = fds[path]
                    while remaining:
                        sent = os.sendfile(out, fd, off, remaining)
                        if sent == 0:
                            raise BrokenPipeError(
                                "client closed during sendfile")
                        off += sent
                        remaining -= sent
                        file_bytes += sent
                if sf is not None:
                    sf.add("windows")
                    sf.add("file_bytes", file_bytes)
            except (BrokenPipeError, ConnectionResetError,
                    ConnectionAbortedError, OSError):
                # headers already went out: the response cannot be
                # retried on this connection — kill it
                self.close_connection = True
            finally:
                for fd in fds.values():
                    os.close(fd)
                del snap   # held until here: pins the planned files
            return True

        def _route(self) -> Tuple[Optional[str], str, dict]:
            url = urlparse(self.path)
            m = _DOC.match(url.path)
            if not m:
                return None, url.path, parse_qs(url.query)
            return m.group(1), (m.group(2) or ""), parse_qs(url.query)

        def _content_length(self) -> Optional[int]:
            """Content-Length as an int, or None (with a 400 already
            sent) when the header is malformed — int() raising inside
            the handler would abort the connection instead of
            answering (ADVICE r4)."""
            raw = self.headers.get("Content-Length", 0)
            try:
                return int(raw)
            except ValueError:
                self.close_connection = True
                self._send(400, {"error": "malformed Content-Length"})
                return None

        def _body(self, n: int) -> bytes:
            return self.rfile.read(n)

        def _read_trace_headers(self, snap, ae_lag_hdr=None) -> dict:
            """Read-path correlation headers (obs/trace.py): the served
            snapshot's identity plus the session id (adopted from a
            well-formed ``X-Session-Id``, minted otherwise).  A fleet
            store (cluster/gateway.py) additionally stamps the replica
            identity + replica-independent state fingerprint, so a
            replica-local read's staleness is wire-observable
            (``ae_lag_hdr`` carries the staleness gate's own lag
            sample so it is computed once per request)."""
            out = {
                SNAP_FP_HEADER: snap.fingerprint(),
                COMMIT_SEQ_HEADER: str(snap.seq),
                SESSION_HEADER: ensure_session_id(
                    self.headers.get(SESSION_HEADER)),
            }
            if hasattr(store, "extra_read_headers"):
                out.update(store.extra_read_headers(
                    snap, ae_lag_hdr=ae_lag_hdr))
            return out

        @staticmethod
        def _since_headers(hdrs: dict, meta: dict) -> None:
            hdrs[SINCE_FOUND_HEADER] = "1" if meta["found"] else "0"
            hdrs[SINCE_MORE_HEADER] = "1" if meta["more"] else "0"
            if meta["next_since"] is not None:
                hdrs[SINCE_NEXT_HEADER] = str(meta["next_since"])

        @staticmethod
        def _watch_fresh(meta, since) -> bool:
            """Shared freshness predicate (serve/watch.py) — ONE
            implementation for the threaded park path and the reactor,
            so the wire cannot drift between them."""
            return watch_mod.watch_fresh(meta, since)

        def _watch_detach(self, reactor, doc, reg, mode, since, limit,
                          deadline, parked_seq,
                          hb_deadline=None) -> bool:
            """The detach seam (ISSUE 18; serve/reactor.py): this
            caught-up watch connection's socket leaves the handler
            thread and parks on the reactor.  Everything
            request-shaped already happened here — parsing, admission,
            the staleness gate, the resume walk; the reactor only ever
            delivers forward from ``since``.  Steps: drain the
            buffered writer (header/stream bytes must precede reactor
            bytes), mark the socket detached so the server-side
            teardown skips its shutdown/close, flag the registry slot
            as reactor-owned (the ``finally`` below must not release
            it), and hand the socket over.  Returns False when
            detaching is not possible (no reactor-capable server, or
            the reactor is stopped) — the caller falls back to the
            threaded park, same wire either way."""
            if not hasattr(self.server, "note_detached"):
                return False
            if not reactor.ensure_started():
                return False
            self.wfile.flush()
            sess = ensure_session_id(self.headers.get(SESSION_HEADER))
            keep_alive = not self.close_connection
            self.server.note_detached(self.connection)
            self._watch_detached = True
            # exit the keep-alive handler loop WITHOUT closing: the
            # skip in shutdown_request keeps the fd alive; rfile/wfile
            # close in finish() but the socket object stays open
            self.close_connection = True
            if reactor.park(self.connection, self.client_address,
                            store, doc, reg, mode, since, limit,
                            deadline, parked_seq, sess, keep_alive,
                            hb_deadline=hb_deadline):
                return True
            # stopped between ensure_started and park (shutdown race):
            # the socket is already detached — close it here
            self._watch_detached = False
            try:
                self.connection.close()
            except OSError:
                pass
            return True

        def _watch_poll(self, doc, reg, since, limit, timeout):
            """One long-poll watch round trip (serve/watch.py): answer
            immediately when the window already has new ops (*resume*),
            else park on the registry until the next publish
            (*notify* — latency measured from the pointer swap) or the
            park budget (*timeout* — an empty-batch heartbeat bounding
            how long a dead connection pins its slot, stamped with the
            caught-up window's ``ETag`` so the re-poll can validate).
            A woken watcher delivers whenever the published seq moved
            past the one it parked on — even if ``next_since`` did not
            (a delete-only tail grows the re-served window without
            moving the terminator; duplicates absorb).  A first poll
            carrying ``If-None-Match`` that does NOT match the window
            etag also delivers — the exactness escape hatch for a
            client whose delete tail predates its watch call.  A
            delivery more than one window behind is a *shed*: the
            window ships, plus the exact resumable mark
            (``X-Watch-Resume-Since``) — the client polls ``/ops``
            until caught up, losing nothing.  The correlation headers
            resolve against the SAME snapshot as the body, and the lag
            stamp is re-sampled at delivery time (a park can outlive
            the admission-time sample)."""
            deadline = time.monotonic() + timeout
            parked, woke_at = False, 0.0
            last_seq = None
            inm = self.headers.get("If-None-Match")
            reactor = getattr(store, "reactor", None)
            while True:
                snap = doc.snapshot_view()
                body, meta = snap.ops_since_window(since, limit)
                fresh = self._watch_fresh(meta, since)
                if not fresh and last_seq is not None \
                        and snap.seq > last_seq:
                    # a commit landed while parked: the re-served
                    # window carries its tail even when the terminator
                    # (and so next_since) did not move
                    fresh = True
                if not fresh and last_seq is None and inm is not None \
                        and not etag_matches(inm, meta["etag"]):
                    fresh = True
                if fresh:
                    # ONE header builder for both delivery tiers
                    # (serve/watch.py): the reactor's notify bytes and
                    # this thread's are identical by construction
                    hdrs = watch_mod.delivery_headers(
                        store, snap, meta, since, ensure_session_id(
                            self.headers.get(SESSION_HEADER)))
                    if parked:
                        reg.stats.observe_notify(
                            (time.perf_counter() - woke_at) * 1e3)
                        hdrs[WATCH_EVENT_HEADER] = "notify"
                    else:
                        reg.stats.add("resumes")
                        hdrs[WATCH_EVENT_HEADER] = "resume"
                    if meta["more"]:
                        reg.stats.add("shed_slow")
                        hdrs[WATCH_EVENT_HEADER] = "shed"
                        hdrs[WATCH_RESUME_HEADER] = str(
                            meta["next_since"])
                    self._send_raw(200, body, headers=hdrs)
                    return
                last_seq = snap.seq
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    st, pub_at = "timeout", None
                elif reactor is not None and not parked \
                        and self._watch_detach(reactor, doc, reg,
                                               "poll", since, limit,
                                               deadline, snap.seq):
                    # detach seam: the caught-up connection now parks
                    # on the reactor — this thread returns to the pool
                    return
                else:
                    st, pub_at = reg.wait_beyond(snap.seq, remaining)
                if st == "new":
                    parked, woke_at = True, pub_at
                    continue
                if st == "closed":
                    self._send(503, {"error": "engine shutting down"},
                               headers={WATCH_EVENT_HEADER: "closed"})
                    return
                # timeout heartbeat: an EMPTY wire batch (nothing to
                # re-send), resume mark unchanged, ETag = the caught-up
                # window's validator for the next poll's If-None-Match
                hdrs = watch_mod.delivery_headers(
                    store, snap, meta, since, ensure_session_id(
                        self.headers.get(SESSION_HEADER)))
                hdrs[WATCH_EVENT_HEADER] = "timeout"
                reg.stats.add("heartbeats")
                self._send_raw(200, EMPTY_BATCH_BYTES, headers=hdrs)
                return

        def _watch_sse(self, doc, reg, since, limit, timeout):
            """Streamed watch (``mode=sse``): one response, one
            ``ops`` event per generation (``id:`` = the resume mark),
            comment heartbeats while idle.  The stream closes itself
            on slow-consumer shed (``event: shed`` with the resumable
            mark), on an unknown mark (``event: reset`` — resync via
            snapshot), on the stream budget (``event: bye``), and on
            engine shutdown (``event: closed``) — every close names
            its reason; reconnect-with-mark is always exact.  What SSE
            does NOT re-check per event: the bounded-staleness gate
            ran once, at admission — a long-lived stream on a
            partitioned replica keeps serving local generations;
            clients needing a re-armed bound must reconnect."""
            snap = doc.snapshot_view()
            self.close_connection = True    # streams are not reusable
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            for k, v in self._read_trace_headers(snap).items():
                self.send_header(k, v)
            self.end_headers()
            deadline = time.monotonic() + timeout
            hb = max(0.05, reg.heartbeat_s)
            parked, woke_at = False, 0.0
            last_seq = None
            reactor = getattr(store, "reactor", None)
            while True:
                snap = doc.snapshot_view()
                body, meta = snap.ops_since_window(since, limit)
                fresh = self._watch_fresh(meta, since) or (
                    last_seq is not None and snap.seq > last_seq)
                last_seq = snap.seq
                if fresh:
                    if parked:
                        reg.stats.observe_notify(
                            (time.perf_counter() - woke_at) * 1e3)
                    else:
                        reg.stats.add("resumes")
                    parked = False
                    ev = bytearray(b"event: ops\n")
                    if meta["next_since"] is not None:
                        ev += b"id: %d\n" % meta["next_since"]
                    for line in bytes(body).split(b"\n"):
                        ev += b"data: " + line + b"\n"
                    ev += b"\n"
                    self.wfile.write(ev)
                    self.wfile.flush()
                    if not meta["found"]:
                        # unknown mark (we restarted with a fresh
                        # log): the client must resync via /snapshot
                        self.wfile.write(b"event: reset\ndata: {}\n\n")
                        return
                    if meta["next_since"] is not None:
                        since = meta["next_since"]
                    if meta["more"]:
                        reg.stats.add("shed_slow")
                        self.wfile.write(
                            b"event: shed\ndata: "
                            b'{"resume_since": %d}\n\n' % since)
                        return
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.wfile.write(b"event: bye\ndata: "
                                     b'{"resume_since": %d}\n\n'
                                     % since)
                    return
                if reactor is not None and self._watch_detach(
                        reactor, doc, reg, "sse", since, limit,
                        deadline, snap.seq,
                        hb_deadline=time.monotonic() + hb):
                    # caught-up stream: the reactor owns it from here
                    # (per-generation events, : hb keepalives, named
                    # closes) — this thread returns to the pool
                    return
                st, pub_at = reg.wait_beyond(
                    snap.seq, min(hb, remaining))
                if st == "closed":
                    self.wfile.write(b"event: closed\ndata: {}\n\n")
                    return
                if st == "timeout":
                    # keepalive comment: detects a dead consumer at
                    # the next write instead of never
                    reg.stats.add("heartbeats")
                    self.wfile.write(b": hb\n\n")
                    self.wfile.flush()
                    continue
                parked, woke_at = True, pub_at

        def do_GET(self):
            doc_id, sub, query = self._route()
            if doc_id is None:
                if sub == "/metrics":
                    self._send(200, {d: store.get(d).metrics()
                                     for d in store.ids()})
                elif sub == "/metrics/scheduler" and \
                        hasattr(store, "scheduler_metrics"):
                    self._send(200, store.scheduler_metrics())
                elif sub == "/metrics/prom" and \
                        hasattr(store, "render_prom"):
                    # the unified Prometheus-style scrape: doc counters,
                    # scheduler histograms with bucket bounds, the span
                    # registry, flight gauges (obs/prom.py)
                    self._send_raw(200, store.render_prom().encode(),
                                   ctype=prom_mod.CONTENT_TYPE)
                elif sub == "/debug/flight" and \
                        hasattr(store, "debug_flight"):
                    # the flight recorder's ring + counters, enriched
                    # for post-mortem without waiting for a dump file
                    self._send(200, store.debug_flight())
                elif sub.startswith("/debug/trace/") and \
                        hasattr(store, "debug_trace"):
                    # fleet trace assembly (docs/OBSERVABILITY.md
                    # §Fleet tracing): this node's spans for the id
                    # plus — unless ?federate=0, which is what the
                    # federated fetch itself sends so assembly is one
                    # bounded hop, never recursive — every peer's
                    tid = sub[len("/debug/trace/"):]
                    fed = query.get("federate", ["1"])[0] != "0"
                    self._send(200, store.debug_trace(
                        tid, federate=fed))
                elif sub.startswith("/debug/visibility/") and \
                        hasattr(store, "debug_visibility"):
                    # the visibility ledger's per-doc tail: when each
                    # recent commit became durable / published /
                    # watch-delivered here, plus frontier applies
                    # pulled from peers (bounds, not truths)
                    self._send(200, store.debug_visibility(
                        sub[len("/debug/visibility/"):]))
                elif sub == "/docs":
                    self._send(200, {"docs": store.ids()})
                elif sub == "/cluster" and \
                        hasattr(store, "cluster_view"):
                    # fleet introspection: membership, lease, ring
                    # spread, anti-entropy state (docs/CLUSTER.md)
                    self._send(200, store.cluster_view())
                else:
                    self._send(404, {"error": "not found"})
                return
            doc = store.get(doc_id, create=False)
            if doc is None:
                # rejoining-node catch-up (cluster/gateway.py): when a
                # fleet peer HAS this document, this node is merely
                # behind (restart / fresh ring ownership) — answer an
                # honest 503 + Retry-After with a catch-up hint and
                # trigger a priority anti-entropy pull, instead of the
                # long 404 window the background sync left before
                cs = store.catchup_status(doc_id) \
                    if hasattr(store, "catchup_status") else None
                if cs is not None:
                    self._send(
                        503, {"error": f"document {doc_id} is being "
                                       "caught up from the fleet",
                              "retry_after_s": cs["retry_after_s"]},
                        headers={"Retry-After": str(cs["retry_after_s"]),
                                 CATCHUP_REMAINING_HEADER:
                                     str(cs["remaining"])})
                    return
                self._send(404, {"error": f"no document {doc_id}"})
                return
            ae_lag_hdr = None
            if sub in ("", "/snapshot", "/watch") and \
                    hasattr(store, "check_staleness"):
                # bounded-staleness read contract (docs/CLUSTER.md
                # §Partitions & staleness): a read bounded by
                # X-Max-Staleness (or the server's
                # GRAFT_MAX_STALENESS_S default) on a replica whose
                # anti-entropy lag exceeds the bound gets an honest
                # 503 + Retry-After instead of silently stale data —
                # a partitioned replica degrades, it does not lie.
                # The gate's lag sample also feeds the served read's
                # X-Ae-Lag-Seconds stamp (one sample per request —
                # gate and stamp can never disagree)
                stale, ae_lag_hdr = store.check_staleness(
                    self.headers.get(MAX_STALENESS_HEADER))
                if stale is not None:
                    # lag_s is None when unbounded (a replica that has
                    # never fully synced) — Infinity is not valid JSON
                    lag_txt = "unbounded" if stale["lag_s"] is None \
                        else f"{stale['lag_s']}s"
                    self._send(
                        503,
                        {"error": f"replica staleness {lag_txt} "
                                  f"exceeds the {stale['bound_s']}s "
                                  "bound",
                         "ae_lag_s": stale["lag_s"],
                         "retry_after_s": stale["retry_after_s"]},
                        headers={
                            "Retry-After": str(stale["retry_after_s"]),
                            AE_LAG_HEADER: ae_lag_hdr})
                    return
            if sub == "":
                if hasattr(doc, "read_view"):
                    # body and headers come from the SAME snapshot: a
                    # checker correlating the fingerprint header to the
                    # values body must never straddle a publish.  The
                    # body is the generation's CACHED encoding
                    # (serve/snapshot.py) and the read is conditional:
                    # If-None-Match against the state-fingerprint ETag
                    # answers 304 with the full header set but no body
                    # — polling readers of an idle doc stop paying
                    # O(doc) egress.  The staleness gate above already
                    # overrode this path with its 503 when the bound
                    # was exceeded: a 304 never vouches for freshness
                    # beyond what the lag stamp admits.
                    snap = doc.read_view()
                    hdrs = self._read_trace_headers(
                        snap, ae_lag_hdr=ae_lag_hdr)
                    hdrs["ETag"] = snap.etag()
                    if etag_matches(self.headers.get("If-None-Match"),
                                    snap.etag()):
                        snap.cache_stats.served_304()
                        self._send_raw(304, b"", headers=hdrs)
                    else:
                        self._send_raw(200, snap.values_body(),
                                       headers=hdrs)
                else:       # legacy DocumentStore: no snapshot identity
                    self._send(200, {"values": doc.snapshot()})
            elif sub == "/ops":
                try:
                    since = int(query.get("since", ["0"])[0])
                    limit = int(query.get("limit", ["0"])[0])
                except ValueError:
                    self._send(400, {"error": "since and limit must "
                                              "be integers"})
                    return
                # a pull that names its fleet node (X-Ae-Peer) feeds
                # the causal-stability watermark: the peer provably
                # consumed our log through `since`, which is what
                # gates the cascade op-log's checkpoint advancement
                # and segment GC (cluster/gateway.py, docs/OPLOG.md)
                peer = self.headers.get(AE_PEER_HEADER)
                if peer and hasattr(store, "note_peer_mark"):
                    store.note_peer_mark(doc_id, peer, since)
                # pre-encoded fast path: the bootstrap contract serves
                # the full log, so avoid a json.loads/dumps round trip.
                # With ?limit= (anti-entropy pulls) the window is
                # bounded + resumable and its state rides the
                # X-Since-* headers — the body stays a plain wire
                # batch either way (engine.packed_since_window)
                try:
                    # zero-copy fast path (ISSUE 17): a catch-up
                    # window landing entirely on cold segments with
                    # ready wire sidecars ships as os.sendfile ranges
                    # — byte-, header-, and ETag-identical to the
                    # buffered branch below, which remains the answer
                    # for hot/mixed windows (and the A/B baseline
                    # under GRAFT_SENDFILE=0)
                    if limit > 0 and hasattr(doc, "ops_window_plan"):
                        plan = doc.ops_window_plan(since, limit)
                        if plan is not None \
                                and self._serve_ops_plan(doc, plan):
                            return
                    if limit > 0 and hasattr(doc, "ops_since_window"):
                        body, meta = doc.ops_since_window(since, limit)
                        hdrs = {
                            SINCE_FOUND_HEADER:
                                "1" if meta["found"] else "0",
                            SINCE_MORE_HEADER:
                                "1" if meta["more"] else "0",
                            **({SINCE_NEXT_HEADER:
                                str(meta["next_since"])}
                               if meta["next_since"] is not None
                               else {}),
                        }
                        # fleet tracing (ISSUE 20): the window rides
                        # out with our send timestamp + the doc's
                        # recent commit trace ids so the PULLING node
                        # can stamp ae_apply spans and a visibility
                        # BOUND; absent under GRAFT_FLEETTRACE=0
                        if hasattr(store, "trace_frontier_header"):
                            tf = store.trace_frontier_header(doc_id)
                            if tf:
                                hdrs[TRACE_FRONTIER_HEADER] = tf
                        # conditional window pull (ISSUE 16 satellite):
                        # the window's content fingerprint is its ETag,
                        # so a steady-state anti-entropy re-pull of an
                        # unchanged window (every peer re-asking the
                        # same (since, limit) of an idle doc every
                        # round) becomes a bodyless 304 ON THE WIRE —
                        # the X-Since-* resume state still rides the
                        # headers, so the puller's mark advances
                        # exactly as a 200 would have advanced it
                        wetag = meta.get("etag")
                        if wetag:
                            hdrs["ETag"] = wetag
                            if etag_matches(
                                    self.headers.get("If-None-Match"),
                                    wetag):
                                if hasattr(doc, "readcache"):
                                    doc.readcache.served_304()
                                self._send_raw(304, b"", headers=hdrs)
                                return
                        self._send_raw(200, body, headers=hdrs)
                    else:
                        self._send_raw(200,
                                       doc.dumps_since_bytes(since))
                except CheckpointError as e:
                    # a window that touches a quarantined (bit-rotted)
                    # tier file: typed refusal + Retry-After — the
                    # scrub repair path is healing the range; corrupt
                    # bytes are NEVER served (docs/DURABILITY.md
                    # §Scrub & repair)
                    self._send(503, {"error": str(e),
                                     "retry_after_s": 5},
                               headers={"Retry-After": "5"})
            elif sub == "/watch":
                # delta-push fan-out (serve/watch.py; docs/SERVING.md
                # §Watch & fan-out): park on the publish pointer, wake
                # on the next generation, deliver the PR-15 cached
                # window — one encode per generation shared by every
                # watcher.  The staleness gate above already ran: a
                # bounded-staleness 503 outranks parking.
                if not hasattr(doc, "watch"):
                    self._send(404, {"error": "watch requires the "
                                              "serving engine"})
                    return
                reg = doc.watch
                try:
                    since = int(query.get("since", ["0"])[0])
                    limit = int(query.get("limit", ["0"])[0]) \
                        or DEFAULT_WATCH_LIMIT
                    timeout = float(
                        query.get("timeout", [""])[0] or reg.park_s)
                except ValueError:
                    self._send(400, {"error": "since, limit, timeout "
                                              "must be numeric"})
                    return
                if limit < 0 or timeout < 0:
                    self._send(400, {"error": "limit and timeout "
                                              "must be >= 0"})
                    return
                mode = query.get("mode", ["poll"])[0]
                # long-poll park is capped by the registry budget; an
                # SSE stream legitimately spans many generations so it
                # gets 10× (heartbeats bound dead-connection detection
                # either way)
                timeout = min(timeout, reg.park_s *
                              (10.0 if mode == "sse" else 1.0))
                try:
                    # bounded admission: past GRAFT_WATCH_MAX the
                    # watch is shed at the door, same semantic as the
                    # write queue's 429
                    reg.register()
                except WatchFull as e:
                    self._send(429, {"error": str(e),
                                     "retry_after_s": e.retry_after_s},
                               headers={"Retry-After":
                                        str(e.retry_after_s)})
                    return
                except WatchClosed as e:
                    self._send(503, {"error": str(e)})
                    return
                self._watch_detached = False
                try:
                    if mode == "sse":
                        self._watch_sse(doc, reg, since, limit,
                                        timeout)
                    else:
                        self._watch_poll(doc, reg, since, limit,
                                         timeout)
                except CheckpointError as e:
                    # quarantined tier range mid-watch: same typed
                    # refusal as /ops — never corrupt bytes
                    self._send(503, {"error": str(e),
                                     "retry_after_s": 5},
                               headers={"Retry-After": "5"})
                except (BrokenPipeError, ConnectionResetError,
                        ConnectionAbortedError, OSError):
                    # the watcher hung up while parked or mid-write:
                    # count the reap, release the slot (finally), and
                    # let the connection die quietly
                    reg.stats.add("reaped")
                    self.close_connection = True
                finally:
                    if not self._watch_detached:
                        reg.unregister()
                    # a detached slot is the reactor's to release:
                    # its delivery/heartbeat/reap/close unregisters
                    # with the same lifetime the threaded path had
            elif sub == "/snapshot":
                try:
                    if hasattr(doc, "read_view"):
                        snap = doc.read_view()
                        hdrs = self._read_trace_headers(
                            snap, ae_lag_hdr=ae_lag_hdr)
                        hdrs["ETag"] = snap.etag()
                        if etag_matches(
                                self.headers.get("If-None-Match"),
                                snap.etag()):
                            # the 304 fires BEFORE checkpoint_bytes:
                            # an unchanged bootstrap poll skips the
                            # whole O(doc) npz assembly, not just the
                            # egress
                            snap.cache_stats.served_304()
                            self._send_raw(
                                304, b"",
                                ctype="application/octet-stream",
                                headers=hdrs)
                            return
                        self._send_raw(
                            200, snap.checkpoint_bytes(),
                            ctype="application/octet-stream",
                            headers=hdrs)
                    else:
                        self._send_raw(200, doc.snapshot_packed(),
                                       ctype="application/octet-stream")
                except CheckpointError as e:
                    # same quarantine rule as /ops: a checkpoint
                    # reassembly that needs a quarantined file refuses
                    # honestly instead of serving corrupt bytes
                    self._send(503, {"error": str(e),
                                     "retry_after_s": 5},
                               headers={"Retry-After": "5"})
            elif sub == "/clock":
                if hasattr(doc, "snapshot_view"):
                    # the clock wire body is cached per generation too.
                    # Deliberately snapshot_view, NOT read_view: the
                    # one-shot GRAFT_ORACLE_FAULT stale/regress faults
                    # must fire on a VALUE/snapshot read (where the
                    # oracle can catch them), never be consumed by a
                    # clock poll — doc.clock() always read the
                    # published snapshot directly
                    self._send_raw(200,
                                   doc.snapshot_view().clock_body())
                else:
                    self._send(200, {"replicas": doc.clock()})
            elif sub == "/metrics":
                self._send(200, doc.metrics())
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            # reject oversized bodies before reading them (the connection
            # closes: unread body bytes would otherwise be parsed as the
            # next request line on keep-alive)
            n = self._content_length()
            if n is None:
                return
            if n > max_body:
                self.close_connection = True
                self._send(413, {"error": f"body exceeds {max_body} "
                                          "bytes; chunk the batch"})
                return
            # always drain the request body first (keep-alive connections
            # would otherwise read leftover body bytes as the next request
            # line), and validate the route BEFORE store.get(create=True)
            # so invalid requests never materialize documents
            body = self._body(n)
            doc_id, sub, _ = self._route()
            # merge-tier wire surface (docs/MERGETIER.md): a merge
            # worker — any store exposing ``handle_merge`` — answers
            # ``POST /merge`` with the packed-npz codec's bytes; the
            # handler shape mirrors the fleet forward path so both
            # transports serve identical responses
            if doc_id is None and sub == "/merge" \
                    and hasattr(store, "handle_merge"):
                status, out_body, out_headers = store.handle_merge(body)
                ctype = out_headers.pop("Content-Type",
                                        "application/octet-stream")
                self._send_raw(status, out_body, ctype=ctype,
                               headers=out_headers)
                return
            if doc_id is None or sub not in ("/replicas", "/ops"):
                self._send(404, {"error": "not found"})
                return
            if sub == "/replicas":
                # a fleet store allocates from the shared KV counter so
                # ids stay unique across servers AND across primary
                # failover; the single-server path keeps the local
                # per-document counter
                if hasattr(store, "assign_replica"):
                    store.get(doc_id)      # materialize the local doc
                    rid = store.assign_replica(doc_id)
                else:
                    rid = store.get(doc_id).assign_replica()
                self._send(200, {"replica": rid})
                return
            # fleet write routing (cluster/gateway.py): a non-primary
            # node relays the request to the document's primary and
            # answers with the PRIMARY's response verbatim (status,
            # trace echo, Retry-After backpressure included); a request
            # already forwarded once always applies locally — one hop,
            # no loops
            if hasattr(store, "write_route") \
                    and self.headers.get(FORWARDED_HEADER) is None:
                try:
                    fwd = store.forward_write(
                        doc_id, body,
                        {TRACE_HEADER: self.headers.get(TRACE_HEADER),
                         SESSION_HEADER:
                             self.headers.get(SESSION_HEADER)})
                except ForwardError as e:
                    self._send(503, {"error": str(e)},
                               headers={"Retry-After":
                                        str(e.retry_after_s)})
                    return
                if fwd is not None:
                    status, out_body, out_headers = fwd
                    ctype = out_headers.pop("Content-Type",
                                            "application/json")
                    self._send_raw(status, out_body, ctype=ctype,
                                   headers=out_headers)
                    return
            elif self.headers.get(FORWARDED_HEADER) is not None \
                    and hasattr(store, "note_forwarded_in"):
                store.note_forwarded_in()
            # trace admission point (obs/trace.py): mint — or adopt a
            # well-formed client-supplied X-Trace-Id — BEFORE parsing,
            # so even a malformed or shed request is attributable; the
            # id rides the write ticket into the commit's flight record
            # and is echoed in the response (body + header) so a client
            # report joins against the server-side record
            trace_id = ensure_trace_id(self.headers.get(TRACE_HEADER))
            # fleet tracing (ISSUE 20): a forwarded write carries the
            # sender's X-Span-Ctx — splice its hop into OUR span ring
            # under the shared trace id before the commit's own spans
            span_ctx = self.headers.get(SPAN_CTX_HEADER)
            if span_ctx and hasattr(store, "note_span_ctx"):
                store.note_span_ctx(trace_id, span_ctx)
            trace_hdr = {TRACE_HEADER: trace_id}
            # echo a client-supplied session id on writes too, so one
            # session's whole request stream correlates on both paths
            sess = self.headers.get(SESSION_HEADER)
            if is_valid_id(sess):
                trace_hdr[SESSION_HEADER] = sess
            try:
                accepted, applied = store.get(doc_id).apply_body(
                    body, trace_id=trace_id)
            except QueueFull as e:
                # admission control: the merge queue is at capacity —
                # shed the write at the door with the server's own
                # drain-time estimate (serve/queue.py)
                self._send(429, {"error": str(e),
                                 "retry_after_s": e.retry_after_s,
                                 "trace_id": trace_id},
                           headers={"Retry-After": str(e.retry_after_s),
                                    **trace_hdr})
                return
            except SchedulerStopped as e:
                self._send(503, {"error": str(e), "trace_id": trace_id},
                           headers=trace_hdr)
                return
            except SchedulerError as e:
                # server-side merge failure: MUST answer 500, never a
                # client-error class — this request was well-formed and
                # retrying it later is legitimate
                self._send(500, {"error": str(e), "trace_id": trace_id},
                           headers=trace_hdr)
                return
            except (DecodeError, json.JSONDecodeError, ValueError) as e:
                # ValueError: the native parser's rejections (same
                # malformed-input class as DecodeError)
                self._send(400, {"error": str(e), "trace_id": trace_id},
                           headers=trace_hdr)
                return
            from ..core import operation as op_mod
            n_applied = op_mod.count(applied)
            payload = {"accepted": accepted, "applied_count": n_applied,
                       "trace_id": trace_id}
            if hasattr(store, "served_by"):
                # fleet attribution: the node that committed this
                # write (the oracle keys read-your-writes on it)
                payload["served_by"] = store.served_by()
            # echo the applied ops only for interactive-scale deltas —
            # for a bootstrap-size push, re-encoding the whole batch
            # into the response costs multiples of the merge itself
            # (scripts/bench_service_e2e.py) and the client already has
            # the ops it sent
            if n_applied <= ECHO_LIMIT:
                payload["applied"] = json.loads(store.encode_ops(applied))
            self._send(200 if accepted else 409, payload,
                       headers=trace_hdr)

    return Handler


class ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that shuts an OWNED serving engine down with
    the server — the scheduler thread stops and any in-flight write
    tickets resolve (503) before ``server_close`` returns.

    Connections are HTTP/1.1 keep-alive (every client path pools them
    through :class:`~crdt_graph_tpu.cluster.pool.ConnectionPool`), so
    ``server_close`` also force-closes every ESTABLISHED connection:
    stopping the accept loop alone would leave handler threads serving
    pooled keep-alive connections of a "crashed" fleet member — a
    zombie the per-request-connection era never had (the chaos tests'
    kill semantics depend on a crash actually severing the wire)."""

    owned_engine: Optional[ServingEngine] = None

    # reactor-scale ramp (ISSUE 18): thousands of watcher connects can
    # arrive in one burst; socketserver's default backlog of 5 would
    # RST the tail of the herd at the kernel accept queue
    request_queue_size = 128

    def __init__(self, *args, **kw):
        self._conn_lock = threading.Lock()
        self._live_conns: set = set()
        self._detached: set = set()
        super().__init__(*args, **kw)

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._live_conns.add(request)
        super().process_request(request, client_address)

    def note_detached(self, request) -> None:
        """The detach seam (serve/reactor.py): a watch handler hands
        this connection's socket to the reactor and exits.  From here
        the reactor owns the socket's lifetime — the handler-thread
        teardown (``shutdown_request``) must skip the shutdown/close
        it would otherwise do, and ``server_close`` must not sever it
        (the engine's ``close()`` drains the reactor with named
        closes instead)."""
        with self._conn_lock:
            self._live_conns.discard(request)
            self._detached.add(request)

    def shutdown_request(self, request):
        with self._conn_lock:
            if request in self._detached:
                self._detached.discard(request)
                return          # the reactor owns this socket now
            self._live_conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conn_lock:
            live = list(self._live_conns)
            self._live_conns.clear()
        for sock in live:
            # a hard RST-like severance: handler threads blocked on
            # the next keep-alive request line wake with EOF and exit
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self.owned_engine is not None:
            self.owned_engine.close()

    def handle_error(self, request, client_address):
        """A client that hung up mid-response is routine operation —
        long-poll writers time out, fleet peers crash (chaos tests
        kill them on purpose) — not a stack trace on stderr.  Anything
        that is NOT a connection death still gets the default dump."""
        import sys as _sys
        exc = _sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            ConnectionAbortedError, TimeoutError)):
            return
        super().handle_error(request, client_address)


def make_server(port: int = 0, store=None,
                max_body: int = DEFAULT_MAX_BODY) -> ThreadingHTTPServer:
    """Build the wire server.  ``store`` defaults to a fresh
    :class:`~crdt_graph_tpu.serve.ServingEngine` (snapshot reads +
    merge scheduler; closed with the server); pass a ``DocumentStore``
    for the legacy inline-merge path or a pre-configured engine the
    caller owns."""
    owned = store is None
    store = store if store is not None else ServingEngine()
    server = ServingHTTPServer(("127.0.0.1", port),
                               make_handler(store, max_body=max_body))
    server.store = store
    if owned:
        server.owned_engine = store
    # reactor egress (serve/reactor.py): the reactor re-injects a
    # keep-alive connection's NEXT request through the server's
    # process_request, so it needs the server reference
    reactor = getattr(store, "reactor", None)
    if reactor is not None:
        reactor.server = server
    return server


def serve(port: int = 8900) -> None:
    server = make_server(port)
    print(f"crdt_graph_tpu service on 127.0.0.1:{server.server_port}")
    server.serve_forever()
