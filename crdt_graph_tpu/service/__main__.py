import sys

from ..utils import compcache
from .http import serve

compcache.enable()
serve(int(sys.argv[1]) if len(sys.argv) > 1 else 8900)
