import sys

from .http import serve

serve(int(sys.argv[1]) if len(sys.argv) > 1 else 8900)
