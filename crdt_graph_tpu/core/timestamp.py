"""Timestamp scheme: ``ts = replica_id * 2**32 + counter``.

A replica's logical clock is a single integer whose high bits carry the
replica id and whose low 32 bits carry a per-replica operation counter
(reference: CRDTree/Timestamp.elm:16-18, CRDTree.elm:33-35,137).  The clock
advances only for operations originated by the local replica
(CRDTree.elm:337-343), so timestamps are per-replica sequence numbers — a
vector clock entry — not a Lamport clock.

Because every operation's timestamp embeds its origin, timestamps are
globally unique, which makes them usable as node identities and as the final
tie-break of every deterministic sort in the TPU kernels.
"""

REPLICA_SHIFT = 2**32


def replica_id(timestamp: int) -> int:
    """Extract the replica id from a timestamp (CRDTree/Timestamp.elm:16-18)."""
    return timestamp // REPLICA_SHIFT


def counter(timestamp: int) -> int:
    """The per-replica sequence number in the low 32 bits."""
    return timestamp % REPLICA_SHIFT


MAX_REPLICA = 1 << 30


def make(replica: int, count: int) -> int:
    """Compose a timestamp from a replica id and a counter.

    Replica ids are bounded to [0, 2^30): the wire's integer domain is
    [0, 2^62) (json_codec._int_field / fastcodec int64_field — the merge
    kernel's int32 bit-half sort keys need ts < 2^62), so a larger id
    would mint timestamps every peer rejects at decode — the bound is
    enforced HERE, at the constructive source, so the failure surfaces
    at init instead of as remote decode errors."""
    if not (0 <= replica < MAX_REPLICA):
        raise ValueError(
            f"replica id {replica!r} outside [0, 2**30): timestamps "
            f"would leave the wire's [0, 2**62) integer domain")
    return replica * REPLICA_SHIFT + count
