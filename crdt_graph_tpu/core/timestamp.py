"""Timestamp scheme: ``ts = replica_id * 2**32 + counter``.

A replica's logical clock is a single integer whose high bits carry the
replica id and whose low 32 bits carry a per-replica operation counter
(reference: CRDTree/Timestamp.elm:16-18, CRDTree.elm:33-35,137).  The clock
advances only for operations originated by the local replica
(CRDTree.elm:337-343), so timestamps are per-replica sequence numbers — a
vector clock entry — not a Lamport clock.

Because every operation's timestamp embeds its origin, timestamps are
globally unique, which makes them usable as node identities and as the final
tie-break of every deterministic sort in the TPU kernels.
"""

REPLICA_SHIFT = 2**32


def replica_id(timestamp: int) -> int:
    """Extract the replica id from a timestamp (CRDTree/Timestamp.elm:16-18)."""
    return timestamp // REPLICA_SHIFT


def counter(timestamp: int) -> int:
    """The per-replica sequence number in the low 32 bits."""
    return timestamp % REPLICA_SHIFT


def make(replica: int, count: int) -> int:
    """Compose a timestamp from a replica id and a counter."""
    return replica * REPLICA_SHIFT + count
