"""Sequential oracle engine: reference-exact semantics on persistent values."""
from . import node, operation, timestamp
from .errors import (AlreadyApplied, CRDTError, InvalidPath, InvalidPathError,
                     NotFound, OperationFailedError)
from .operation import Add, Batch, Delete, Operation
from .tree import CRDTree, DONE, TAKE, init
