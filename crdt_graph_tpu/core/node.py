"""The oracle tree kernel: a persistent replicated tree with RGA branches.

This is the sequential correctness oracle for the TPU engine.  Semantics
follow the reference node kernel (Internal/Node.elm): every branch keeps its
children in a mapping keyed by timestamp, ordered as a singly linked list
threaded through ``nxt`` pointers, with a sentinel tombstone at key ``0``
acting as the list head (Internal/Node.elm:25-48).  Inserting after an anchor
skips right past existing siblings with a larger timestamp — among concurrent
inserts at the same anchor, the higher timestamp sits closer to the anchor
(Internal/Node.elm:93-104).  Deleting replaces a node with a tombstone that
keeps its path and list position but loses value and children
(Internal/Node.elm:112-122, 237-238).

Persistence is by path copying: an update rebuilds only the spine from the
edited branch to the root, sharing everything else — failed operations
therefore never observably mutate the tree, which is what makes local batch
atomicity free (CRDTree.elm:224-232).

Known divergence from the reference, by design: the reference's
``findInsertion`` (Internal/Node.elm:93-104) pairs the *immediate* next
timestamp with the *tombstone-skipping* next node; when a tombstone sits
between siblings those two disagree and an insert then overwrites the
tombstone's mapping slot with a copy of a later sibling, orphaning that
sibling's own key and detaching subsequent deletes from the visible list.
No reference test reaches that state.  We instead treat tombstones as
ordinary members of the sibling chain during the skip-scan — the standard
RGA rule — which reproduces every reference test fixture and keeps the
structure self-consistent under tombstone-heavy workloads (BASELINE config 4).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from .errors import AlreadyApplied, InvalidPath, NotFound

ROOT = 0
NODE = 1
TOMBSTONE = 2


class Node:
    """One tree node.  ``kind`` is ROOT, NODE, or TOMBSTONE.

    - ROOT: only ``children`` is meaningful; path is ``()``.
    - NODE: ``value``, ``children``, ``path`` (full path, last element is the
      node's own timestamp) and ``nxt`` (next sibling timestamp or None).
    - TOMBSTONE: ``path`` and ``nxt`` only; children read as empty
      (Internal/Node.elm:237-238) — a deleted node's descendants are
      discarded.
    """

    __slots__ = ("kind", "value", "_children", "path", "nxt")

    def __init__(self, kind: int, value: Any = None,
                 children: Optional[Dict[int, "Node"]] = None,
                 path: Tuple[int, ...] = (), nxt: Optional[int] = None):
        self.kind = kind
        self.value = value
        self._children = children if children is not None else {}
        self.path = path
        self.nxt = nxt

    # -- construction -----------------------------------------------------

    @staticmethod
    def sentinel(path: Tuple[int, ...] = (), nxt: Optional[int] = None) -> "Node":
        return Node(TOMBSTONE, path=path, nxt=nxt)

    @staticmethod
    def root() -> "Node":
        """Fresh root with the sentinel list head at key 0
        (Internal/Node.elm:41-48)."""
        return Node(ROOT, children={0: Node.sentinel()})

    def _fresh_branch_children(self) -> Dict[int, "Node"]:
        return {0: Node.sentinel()}

    # -- accessors --------------------------------------------------------

    @property
    def children(self) -> Dict[int, "Node"]:
        if self.kind == TOMBSTONE:
            return {}
        return self._children

    def child(self, ts: int) -> Optional["Node"]:
        return self.children.get(ts)

    @property
    def timestamp(self) -> int:
        """Last path element, 0 for the root (Internal/Node.elm:302-308)."""
        return self.path[-1] if self.path else 0

    def get_value(self) -> Any:
        """Value unless deleted or root (Internal/Node.elm:329-339)."""
        return self.value if self.kind == NODE else None

    def is_deleted(self) -> bool:
        return self.kind == TOMBSTONE

    # -- persistent update helpers ---------------------------------------

    def with_children(self, children: Dict[int, "Node"]) -> "Node":
        return Node(self.kind, self.value, children, self.path, self.nxt)

    def with_next(self, nxt: Optional[int]) -> "Node":
        if self.kind == ROOT:
            return self
        return Node(self.kind, self.value, self._children, self.path, nxt)

    def put_child(self, ts: int, node: "Node") -> "Node":
        """Copy of self with ``children[ts] = node``; no-op on tombstones
        (Internal/Node.elm:125-135)."""
        if self.kind == TOMBSTONE:
            return self
        new_children = dict(self._children)
        new_children[ts] = node
        return self.with_children(new_children)


# -- the two mutations ----------------------------------------------------

def add_after(root: Node, path: Sequence[int], ts: int, value: Any) -> Node:
    """Insert ``(ts, value)`` after the node addressed by ``path``.

    ``path[-1]`` is the anchor timestamp within the target branch (0 = branch
    head sentinel); the new node is stamped ``path[:-1] + (ts,)``
    (Internal/Node.elm:51-90).

    Raises AlreadyApplied if ``ts`` already exists in the branch, NotFound if
    the anchor is missing, InvalidPath for empty/broken paths.
    """
    path = tuple(path)

    def edit(anchor_ts: int, parent: Node) -> Node:
        if parent.child(ts) is not None:
            raise AlreadyApplied  # idempotence (Internal/Node.elm:63-65)
        anchor = parent.child(anchor_ts)
        if anchor is None:
            raise NotFound
        # RGA skip-scan: walk right past siblings with larger timestamps;
        # tombstones participate like any other sibling (see module note).
        left_ts, left = anchor_ts, anchor
        while left.nxt is not None and ts < left.nxt:
            left_ts = left.nxt
            left = parent.children[left_ts]
        node = Node(NODE, value, {0: Node.sentinel()},
                    path[:-1] + (ts,), left.nxt)
        return parent.put_child(left_ts, left.with_next(ts)).put_child(ts, node)

    return _update(edit, path, root)


def delete(root: Node, path: Sequence[int]) -> Node:
    """Tombstone the node at ``path``, keeping its list position and path but
    discarding value and children (Internal/Node.elm:107-122).

    Raises NotFound if absent, AlreadyApplied if already a tombstone.
    """
    def edit(target_ts: int, parent: Node) -> Node:
        target = parent.child(target_ts)
        if target is None:
            raise NotFound
        if target.kind != NODE:
            raise AlreadyApplied
        return parent.put_child(target_ts, Node(TOMBSTONE, path=target.path,
                                                nxt=target.nxt))

    return _update(edit, tuple(path), root)


def _update(edit: Callable[[int, Node], Node], path: Tuple[int, ...],
            parent: Node) -> Node:
    """Persistent descent-by-path, rebuilding the spine on the way back up
    (Internal/Node.elm:138-163).

    A tombstone anywhere along the descent raises AlreadyApplied — edits
    under a deleted branch are absorbed as no-ops.
    """
    if parent.kind == TOMBSTONE:
        raise AlreadyApplied
    if not path:
        raise InvalidPath
    if len(path) == 1:
        return edit(path[0], parent)
    head, rest = path[0], path[1:]
    found = parent.child(head)
    if found is None:
        raise InvalidPath
    return parent.put_child(head, _update(edit, rest, found))


# -- traversal ------------------------------------------------------------

def iter_chain(parent: Node) -> Iterator[Node]:
    """All siblings of a branch in list order, tombstones included, sentinel
    excluded."""
    children = parent.children
    cur = children.get(0)
    while cur is not None and cur.nxt is not None:
        cur = children.get(cur.nxt)
        if cur is None:
            return
        yield cur


def iter_visible(parent: Node) -> Iterator[Node]:
    """Visible (non-tombstone) siblings in list order
    (Internal/Node.elm:206-228, 257-268)."""
    for node in iter_chain(parent):
        if node.kind == NODE:
            yield node


def next_node(node: Node, parent: Node) -> Optional[Node]:
    """Next visible sibling after ``node`` (Internal/Node.elm:257-268)."""
    children = parent.children
    cur: Optional[Node] = node
    while cur is not None and cur.nxt is not None:
        cur = children.get(cur.nxt)
        if cur is not None and cur.kind == NODE:
            return cur
    return None


def foldl(func: Callable[[Node, Any], Any], acc: Any, parent: Node) -> Any:
    for node in iter_visible(parent):
        acc = func(node, acc)
    return acc


def foldr(func: Callable[[Node, Any], Any], acc: Any, parent: Node) -> Any:
    for node in reversed(list(iter_visible(parent))):
        acc = func(node, acc)
    return acc


def node_map(func: Callable[[Node], Any], parent: Node) -> List[Any]:
    return [func(n) for n in iter_visible(parent)]


# reference-named alias (CRDTree/Node.elm `map`); node_map stays the
# idiomatic name since `map` shadows the builtin at module scope
map = node_map  # noqa: A001


def filter_map(func: Callable[[Node], Any], parent: Node) -> List[Any]:
    out = []
    for n in iter_visible(parent):
        v = func(n)
        if v is not None:
            out.append(v)
    return out


def find(pred: Callable[[Node], bool], parent: Node) -> Optional[Node]:
    """First chain member matching ``pred`` — tombstones are candidates too:
    the reference's ``findHelp`` follows raw ``next`` pointers without
    skipping (Internal/Node.elm:166-183), which is load-bearing for the
    delete-cursor predecessor search (CRDTree.elm:199-216)."""
    for n in iter_chain(parent):
        if pred(n):
            return n
    return None


def loop(func: Callable[[Node, Any], Any], acc: Any, parent: Node) -> Any:
    """Fold over visible children from the left while the step is "take"
    (CRDTree/Node.elm:136-160).  ``func(node, acc)`` returns ``(step, acc)``
    with step ``"take"`` to continue or ``"done"`` to stop early."""
    for node in iter_visible(parent):
        step, acc = func(node, acc)
        if step == "done":
            return acc
    return acc


def children(parent: Node) -> List[Node]:
    """Visible children in list order (CRDTree/Node.elm:94-98)."""
    return list(iter_visible(parent))


def head(parent: Node) -> Optional[Node]:
    for n in iter_visible(parent):
        return n
    return None


def last(parent: Node) -> Optional[Node]:
    out = None
    for n in iter_visible(parent):
        out = n
    return out


def descendant(node: Node, path: Sequence[int]) -> Optional[Node]:
    """Node at ``path`` below ``node`` (Internal/Node.elm:289-299)."""
    cur: Optional[Node] = node
    if not path:
        return None
    for ts in path:
        if cur is None:
            return None
        cur = cur.child(ts)
    return cur
