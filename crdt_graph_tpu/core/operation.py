"""Operation algebra: the op model every engine in this framework speaks.

``Add(ts, path, value)`` inserts a node with identity ``ts`` *after* the node
addressed by ``path``; the last element of ``path`` is the **anchor** (the
left neighbour's timestamp, ``0`` for the head sentinel of a branch), not the
new node's position.  The new node's own path is ``path[:-1] + (ts,)``.
``Delete(path)`` tombstones the node at ``path``; the operation's timestamp is
the last path element.  ``Batch`` groups operations
(reference: Internal/Operation.elm:17-20, 94-104).

Operations are immutable values.  A replica's full state is reconstructible
from its operation list alone, which is why the TPU engine treats *the op set
itself* as the CRDT state: merge = set union, materialisation = one batched
kernel call.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple, Union

from . import timestamp as ts_mod


@dataclasses.dataclass(frozen=True)
class Add:
    """Insert a node with identity ``ts`` after the node at ``path``."""

    ts: int
    path: Tuple[int, ...]
    value: Any

    def __post_init__(self) -> None:
        object.__setattr__(self, "path", tuple(self.path))


@dataclasses.dataclass(frozen=True)
class Delete:
    """Tombstone the node at ``path``."""

    path: Tuple[int, ...]

    def __post_init__(self) -> None:
        object.__setattr__(self, "path", tuple(self.path))


@dataclasses.dataclass(frozen=True)
class Batch:
    """An ordered group of operations applied atomically when local."""

    ops: Tuple[Operation, ...]

    def __post_init__(self) -> None:
        object.__setattr__(self, "ops", tuple(self.ops))


Operation = Union[Add, Delete, Batch]


def op_timestamp(op: Operation) -> Optional[int]:
    """Timestamp of an operation (Internal/Operation.elm:94-104).

    A ``Delete``'s timestamp is its target's timestamp (the last path
    element); a ``Batch`` has none.
    """
    if isinstance(op, Add):
        return op.ts
    if isinstance(op, Delete):
        return op.path[-1] if op.path else None
    return None


def op_path(op: Operation) -> Optional[Tuple[int, ...]]:
    """Path of an operation (Internal/Operation.elm:109-119)."""
    if isinstance(op, (Add, Delete)):
        return op.path
    return None


def op_replica_id(op: Operation) -> Optional[int]:
    """Id of the replica that originated the operation."""
    ts = op_timestamp(op)
    return None if ts is None else ts_mod.replica_id(ts)


def to_list(op: Operation) -> list:
    """Flatten one level: a Batch's ops, or the op itself in a singleton list
    (Internal/Operation.elm:58-68)."""
    if isinstance(op, Batch):
        return list(op.ops)
    return [op]


def from_list(ops: Iterable[Operation]) -> Batch:
    """Wrap a list of operations in a Batch (Internal/Operation.elm:73-75)."""
    return Batch(tuple(ops))


def merge(a: Operation, b: Operation) -> Batch:
    """Concatenate two operations into one Batch (Internal/Operation.elm:80-82)."""
    return Batch(tuple(to_list(a) + to_list(b)))


def since(ts: int, operations: list) -> list:
    """Operations at-or-after ``ts`` from a reverse-chronological log.

    Scans the (newest-first) log accumulating ops until it finds the ``Add``
    whose timestamp equals ``ts`` exactly — that Add is *included* in the
    result.  Batch entries are skipped; Deletes never terminate the scan.  If
    no Add matches, returns ``[]`` (Internal/Operation.elm:25-53).  The
    inclusive overlap is deliberate: receivers rely on idempotent re-apply.
    """
    acc: list = []
    for op in operations:
        if isinstance(op, Batch):
            continue
        acc.append(op)
        if isinstance(op, Add) and op.ts == ts:
            acc.reverse()
            return acc
    return []


def count(op: Operation) -> int:
    """Leaf count of an operation, without materializing lazy batches
    (oplog.PackedBatch exposes ``num_leaves``; a plain Batch recurses)."""
    n = getattr(op, "num_leaves", None)
    if n is not None:
        return n
    if isinstance(op, Batch):
        return sum(count(child) for child in op.ops)
    return 1


def iter_leaves(op: Operation) -> Iterator[Operation]:
    """Depth-first iteration over the non-Batch leaves of an operation."""
    if isinstance(op, Batch):
        for child in op.ops:
            yield from iter_leaves(child)
    else:
        yield op
