"""The replica state machine: a persistent CRDTree value.

Semantics mirror the reference replica layer (CRDTree.elm).  A ``CRDTree``
holds the tree root, the replica clock, a local cursor, the reverse-
chronological operation log, a vector clock of per-replica last-seen
timestamps, and the last successfully applied operation (for broadcasting)
(CRDTree.elm:112-139).

All methods are pure: they return a new ``CRDTree`` and never mutate the
receiver; failures raise and leave every previously obtained value intact.
Local batches are atomic — the first failing step aborts the whole batch
(CRDTree.elm:224-232, tests/CRDTreeTest.elm:482-498) — which falls out of
persistence for free.

Idempotence contract: an operation that already took effect (duplicate add,
repeated delete, edit under a deleted branch) is absorbed as a success-no-op
with ``last_operation`` reset to an empty batch (CRDTree.elm:318-319).
Duplicate delivery is normal in this protocol; receivers must absorb the
inclusive overlap of ``operations_since`` (CRDTree.elm:390-418).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

from . import node as node_mod
from . import operation as op_mod
from . import timestamp as ts_mod
from .errors import (AlreadyApplied, CRDTError, InvalidPath, InvalidPathError,
                     NotFound, OperationFailedError)
from .node import Node
from .operation import Add, Batch, Delete, Operation

# Steps for the resumable `walk` fold (CRDTree/Node.elm:80-85).
DONE = "done"
TAKE = "take"


class CRDTree:
    """A replicated tree value.  Construct with :func:`init`."""

    __slots__ = ("root", "timestamp", "cursor", "operations", "replicas",
                 "last_operation")

    def __init__(self, root: Node, timestamp: int, cursor: Tuple[int, ...],
                 operations: Tuple[Operation, ...], replicas: dict,
                 last_operation: Operation):
        self.root = root
        self.timestamp = timestamp
        self.cursor = cursor
        self.operations = operations  # newest first
        self.replicas = replicas
        self.last_operation = last_operation

    # -- construction -----------------------------------------------------

    @staticmethod
    def init(replica: int) -> "CRDTree":
        """Fresh replica with clock ``replica * 2**32`` (CRDTree.elm:130-139)."""
        return CRDTree(root=Node.root(),
                       timestamp=ts_mod.make(replica, 0),
                       cursor=(0,),
                       operations=(),
                       replicas={},
                       last_operation=Batch(()))

    def _replace(self, **kw) -> "CRDTree":
        fields = {s: getattr(self, s) for s in CRDTree.__slots__}
        fields.update(kw)
        return CRDTree(**fields)

    # -- identity ---------------------------------------------------------

    @property
    def replica_id(self) -> int:
        return ts_mod.replica_id(self.timestamp)

    @property
    def id(self) -> int:
        """Reference-named alias of :attr:`replica_id` (CRDTree.elm `id`)."""
        return self.replica_id

    def next_timestamp(self) -> int:
        return self.timestamp + 1

    def last_replica_timestamp(self, replica: int) -> int:
        """Last seen timestamp for a replica, 0 if never seen
        (CRDTree.elm:637-639)."""
        return self.replicas.get(replica, 0)

    # -- local edits ------------------------------------------------------

    def add(self, value: Any) -> "CRDTree":
        """Add a node after the cursor (CRDTree.elm:151-153)."""
        return self.add_after(self.cursor, value)

    def add_after(self, path: Sequence[int], value: Any) -> "CRDTree":
        """Add a node after the node at ``path``, stamped with the next local
        timestamp (CRDTree.elm:166-168)."""
        return self._apply_local(Add(self.next_timestamp(), tuple(path), value))

    def add_branch(self, value: Any) -> "CRDTree":
        """Add a node and descend the cursor into it so subsequent adds nest
        (CRDTree.elm:180-186)."""
        tree = self.add(value)
        return tree._replace(cursor=tree.cursor + (0,))

    def delete(self, path: Sequence[int]) -> "CRDTree":
        """Tombstone the node at ``path`` and move the cursor to its
        predecessor (CRDTree.elm:199-216)."""
        path = tuple(path)
        target = self.get(path)
        parent = self._parent_or_root(target) if target is not None else self.root
        prev = node_mod.find(
            lambda n: self.next(n) is target, parent) if parent else None
        path_previous = prev.path if prev is not None else path
        tree = self._apply_local(Delete(path))
        return tree.set_cursor(path_previous)

    def batch(self, funcs: Iterable[Callable[["CRDTree"], "CRDTree"]]
              ) -> "CRDTree":
        """Apply a sequence of edit functions atomically, accumulating their
        last-operations into one Batch (CRDTree.elm:224-232)."""
        tree = self._replace(last_operation=Batch(()))
        for func in funcs:
            prev_last = tree.last_operation
            tree = func(tree)
            tree = tree._replace(
                last_operation=op_mod.merge(prev_last, tree.last_operation))
        return tree

    # -- remote application ----------------------------------------------

    def apply(self, operation: Operation) -> "CRDTree":
        """Apply a remote operation; the local cursor does not move
        (CRDTree.elm:265-269)."""
        saved = self.cursor
        tree = self._apply_local(operation)
        return tree._replace(cursor=saved)

    def _apply_local(self, operation: Operation) -> "CRDTree":
        """Dispatch one operation into the node kernel and commit
        (CRDTree.elm:275-295)."""
        if isinstance(operation, Add):
            result = self._edit(
                lambda: node_mod.add_after(self.root, operation.path,
                                           operation.ts, operation.value),
                operation, operation.path, operation.ts)
            return result._increment_timestamp(operation.ts)
        if isinstance(operation, Delete):
            ts = op_mod.op_timestamp(operation) or 0
            return self._edit(
                lambda: node_mod.delete(self.root, operation.path),
                operation, operation.path, ts)
        # Batch: each member applied with cursor-restoring `apply`
        # (CRDTree.elm:294-295).
        return self.batch([(lambda op: lambda t: t.apply(op))(op)
                           for op in operation.ops])

    def _edit(self, thunk: Callable[[], Node], operation: Operation,
              path: Tuple[int, ...], ts: int) -> "CRDTree":
        """Run a node edit and commit the result (CRDTree.elm:298-325)."""
        try:
            new_root = thunk()
        except AlreadyApplied:
            # Success-no-op; nothing logged, nothing broadcast.
            return self._replace(last_operation=Batch(()))
        except InvalidPath:
            raise InvalidPathError(f"invalid path {path!r}")
        except NotFound:
            raise OperationFailedError(operation)
        new_replicas = dict(self.replicas)
        new_replicas[ts_mod.replica_id(ts)] = ts
        return self._replace(
            root=new_root,
            cursor=tuple(path[:-1]) + (ts,),
            operations=(operation,) + self.operations,
            last_operation=operation,
            replicas=new_replicas)

    def _increment_timestamp(self, ts: int) -> "CRDTree":
        """Advance the clock only for operations this replica originated
        (CRDTree.elm:337-343)."""
        if ts_mod.replica_id(ts) == self.replica_id:
            return self._replace(timestamp=self.next_timestamp())
        return self

    # -- anti-entropy -----------------------------------------------------

    def operations_since(self, initial_timestamp: int) -> Operation:
        """Batch of operations at-or-after a timestamp; 0 replays the full
        log chronologically (CRDTree.elm:408-418).  The match is inclusive —
        receivers absorb the overlap idempotently."""
        if initial_timestamp == 0:
            return op_mod.from_list(tuple(reversed(self.operations)))
        return op_mod.from_list(
            op_mod.since(initial_timestamp, list(self.operations)))

    # -- queries ----------------------------------------------------------

    def get(self, path: Sequence[int]) -> Optional[Node]:
        """Node at ``path`` (tombstones included) or None (CRDTree.elm:464-466)."""
        return node_mod.descendant(self.root, tuple(path))

    def get_value(self, path: Sequence[int]) -> Any:
        """Value at ``path``; None for missing/deleted nodes
        (CRDTree.elm:486-488)."""
        found = self.get(path)
        return found.get_value() if found is not None else None

    def parent(self, node: Node) -> Optional[Node]:
        """Parent of a node; the root for depth-1 nodes (CRDTree.elm:430-444)."""
        parent_path = node.path[:-1]
        if not parent_path:
            return self.root
        return self.get(parent_path)

    def _parent_or_root(self, node: Optional[Node]) -> Optional[Node]:
        if node is None:
            return self.root
        parent = self.parent(node)
        return parent if parent is not None else self.root

    def next(self, node: Node) -> Optional[Node]:
        """Next visible sibling (CRDTree.elm:563-568)."""
        parent = self.parent(node)
        if parent is None:
            return None
        return node_mod.next_node(node, parent)

    def prev(self, node: Node) -> Optional[Node]:
        """Previous visible sibling (CRDTree.elm:573-577)."""
        parent = self.parent(node)
        if parent is None:
            return None
        return node_mod.find(lambda n: self.next(n) is node, parent)

    def walk(self, func: Callable[[Node, Any], Tuple[str, Any]], acc: Any,
             start: Optional[Node] = None) -> Any:
        """Resumable depth-first fold over visible nodes in document order
        (CRDTree.elm:583-625).

        ``func(node, acc)`` returns ``(TAKE, acc)`` to continue (descending
        into the node's children) or ``(DONE, acc)`` to stop.  ``start`` is
        exclusive: the walk resumes *after* it, covering the remainder of its
        sibling list (with full descents); ``start=None`` walks the whole
        tree.  The reference's ``walk`` is untested (CRDTree.elm:585 "TODO:
        no tests") and as written skips the first node of every visited
        branch; we implement the self-consistent resumable reading instead.
        """
        if start is None:
            _, acc = self._walk_children(func, acc, self.root)
            return acc
        parent = self.parent(start)
        if parent is None:
            return acc
        node = node_mod.next_node(start, parent)
        while node is not None:
            step, acc = func(node, acc)
            if step == DONE:
                return acc
            done, acc = self._walk_children(func, acc, node)
            if done:
                return acc
            node = node_mod.next_node(node, parent)
        return acc

    def _walk_children(self, func, acc, branch: Node):
        for child in node_mod.iter_visible(branch):
            step, acc = func(child, acc)
            if step == DONE:
                return True, acc
            done, acc = self._walk_children(func, acc, child)
            if done:
                return True, acc
        return False, acc

    # -- cursor -----------------------------------------------------------

    def move_cursor_up(self) -> "CRDTree":
        """Truncate the cursor one level (CRDTree.elm:537-543)."""
        if len(self.cursor) == 1:
            return self
        return self._replace(cursor=self.cursor[:-1])

    def set_cursor(self, path: Sequence[int]) -> "CRDTree":
        """Point the cursor at an existing node (CRDTree.elm:551-558)."""
        path = tuple(path)
        if self.get(path) is None:
            raise NotFound(f"no node at {path!r}")
        return self._replace(cursor=path)

    # -- convenience ------------------------------------------------------

    def visible_values(self) -> List[Any]:
        """Values of all visible nodes in document order — the render path."""
        out: List[Any] = []
        self.walk(lambda n, acc: (TAKE, acc.append(n.get_value()) or acc), out)
        return out

    def __repr__(self) -> str:
        return (f"CRDTree(replica={self.replica_id}, "
                f"ops={len(self.operations)}, ts={self.timestamp})")


def init(replica: int) -> CRDTree:
    """Build a CRDTree for a replica id (CRDTree.elm:130-139)."""
    return CRDTree.init(replica)
