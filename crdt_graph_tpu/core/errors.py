"""Error taxonomy shared by every engine.

The node kernel signals three conditions (reference Internal/Node.elm:35-38):

- ``AlreadyApplied`` — the operation is a duplicate or targets a tombstone;
  the replica layer absorbs it as a success-no-op (CRDTree.elm:318-319).
  This is the idempotence contract: duplicate delivery is normal.
- ``NotFound`` — the anchor/target is missing; surfaces as
  ``OperationFailedError`` at the replica layer (CRDTree.elm:324-325),
  typically a causality gap the application retries after a wider sync.
- ``InvalidPath`` — the path is empty or an intermediate node is missing;
  surfaces as ``InvalidPathError`` (CRDTree.elm:321-322).
"""
from __future__ import annotations


class CRDTError(Exception):
    """Base class for all errors raised by this framework."""


class NodeError(CRDTError):
    """Base class for node-kernel level errors."""


class AlreadyApplied(NodeError):
    """Operation already took effect (duplicate add, delete of tombstone,
    or edit under a deleted branch)."""


class NotFound(NodeError):
    """Anchor or delete target missing from its branch."""


class InvalidPath(NodeError):
    """Empty path or missing intermediate node along the path."""


class InvalidPathError(CRDTError):
    """Replica-level: an operation carried an invalid path."""


class OperationFailedError(CRDTError):
    """Replica-level: an operation's target was not found."""

    def __init__(self, operation) -> None:
        super().__init__(f"operation failed: {operation!r}")
        self.operation = operation


class CheckpointError(CRDTError):
    """A checkpoint/snapshot byte stream could not be parsed.

    ``restore_packed`` translates the zoo of low-level failures a corrupt
    or truncated npz produces (BadZipFile, zlib.error, KeyError on a
    missing member, struct/ValueError on malformed metadata, …) into this
    one typed error so servers and bootstrap clients can answer "bad
    snapshot" without matching on zipfile internals.  Payload corruption
    inside intact zip members is caught by the per-member CRC; flipped
    bits in zip padding that change nothing decode to the original tree.
    """
