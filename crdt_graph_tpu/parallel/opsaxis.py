"""Ops-axis sharded merge: the single-document kernel at M/k width per
device with ring-collective boundary exchange (ISSUE 13 tentpole).

The docs axis already shards (8 independent documents via
``mesh.batched_materialize``, docs/SHARD_TAIL.md §6), and
``parallel/shard.py`` partitions the RESOLUTION stages — but the tail
(tour scan, plane sweeps, rank expansion, order scatters) still ran
replicated, capping a single giant merge at ~1.6× on 8 chips (§2b).
This module shards the tail's billed memory ops too, following the §4
design the round-5 doc committed:

- **One shard_map, one code path.**  The whole kernel runs inside ONE
  ``shard_map`` over a 1-D ``ops`` mesh; the body all-gathers the op
  columns once (the same boundary exchange parallel/shard.py performs)
  and then calls the STOCK kernel — ``merge._materialize`` — with a
  :class:`OpsAxisPart` partition context threaded through it.  Every
  stage the context does not intercept runs replicated and is therefore
  bit-identical by construction; the intercepted stages are proven-
  equal rewrites (associative scan splits, disjoint-index scatter
  joins, windowed gathers), pinned across all 8 sweep shapes by
  tests/test_opsaxis.py.
- **Tour-scan prefix = local scan + ring carries + local fixup**
  (ops/tour_scan.sharded_prefix_sums): each device cumsums its
  contiguous ceil(M/k)-wide chunks; per-chunk run-id offsets and
  suffix-weight totals ride one fused ``lax.ppermute`` ring; a local
  elementwise fixup finishes.  Exact — integer addition is associative.
- **Bounded-span plane sweeps get halo rows**: the node-frame plane
  gather and the parent-plane gather read, per device, only a
  ``[W + 2·HALO]``-row window around its own slot range (HALO is
  STATIC — fused_resolve's span bound, SPAN2 = SPAN + 2·HOP_J, already
  bounds how far a vouched batch's source rows stray from the
  diagonal).  ROOT/NULL rows are overlaid elementwise (their frames
  are constants of the construction).  A batch whose indices straddle
  more than the halo fails the replicated window check and the WHOLE
  gather falls back to the single-device path via ``lax.cond`` —
  exactly the existing lax-fallback pattern; fallback speed, never
  correctness.
- **Frame scatters join like semilattices**: order/visible-order/
  first-child scatters write globally-unique targets, so each device
  scatters only its ceil(M/k) local pairs into a default frame and one
  ``lax.pmin``/``pmax`` joins the frames (the parallel/shard.py winner-
  frame pattern); scatter-adds join by ``psum``.

On the 8-device host-platform CPU mesh every collective executes for
real (lax.ppermute/psum/all_gather — tier-1 runs this path); the pallas
``make_async_remote_copy`` ring variant of the carry exchange is
validated in interpret mode where supported and staged for the TPU
grant (ops/tour_scan.ring_exclusive_pallas,
scripts/tpu_next_grant.sh).

What stays replicated per device, disclosed: all ELEMENTWISE M-wide
arithmetic (the cost model bills memory ops, not elementwise lanes —
docs/TPU_PROFILE.md §3), the compact sub-threshold stages (S_CAP/R_CAP
sibling sort and Wyllie — §4 items 4+6, the Amdahl core), and the
0-trip fixpoint loop bodies.  utils/chainaudit.py v3 audits the traced
shard body and CI pins: no billed fast-path op inside it wider than
ceil(M/k) + HALO at 1M config 5, and the collective bytes within the
documented bound (tests/test_chain_audit.py).

Serving routes big merges here behind the ``GRAFT_OPSAXIS``
kill-switch: candidate sets ≥ ``GRAFT_OPSAXIS_MIN_OPS`` (default 256k)
on hosts with ≥ 2 devices whose padded capacity the device count
divides (engine.py) — the NodeTable shapes are then identical to the
single-device path, so chunked-apply rollback and
``last_applied_mask`` attribution ride through unchanged; fingerprints
and sync windows are pinned byte-identical flag on/off.
"""
from __future__ import annotations

import functools
import math
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..codec import packed as packed_mod
from ..ops import fused_resolve, merge as merge_mod
from ..ops import tour_scan
from ..ops.merge import NodeTable
from ..utils import hostenv, jaxcompat

AXIS = "ops"

# Static halo rows per shard edge for the windowed plane sweeps: the
# 2-hop span bound the pallas sweeps already enforce (fused_resolve
# SPAN2 = SPAN + 2·HOP_J) — a vouched near-diagonal batch's source rows
# stay within it, and anything that strays takes the single-device
# fallback exactly like the pallas span check does.
HALO = fused_resolve.SPAN2

# documented collective-byte bound for the 1M config-5 trace (CI gate,
# tests/test_chain_audit.py): the input-column exchange (~56 B/op) +
# the replicated-output reassembly all-gathers (plane rows, prefix
# lanes, rank frames ≈ 190 B/op) with ~25% headroom.  Billed as summed
# collective OUTPUT bytes per device (chainaudit v3 counting rule).
COLLECTIVE_BYTES_CAP_1M = 320 * 1024 * 1024


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class OpsAxisPart:
    """Partition context threaded through ``merge._finish``: the
    sharded implementations of the kernel's billed memory ops (module
    docstring).  Lives only inside the shard_map body; every method
    takes replicated operands and returns replicated results."""

    def __init__(self, k: int):
        self.k = k
        self.halo = HALO

    # -- local slicing helpers -------------------------------------------

    def _w(self, n: int) -> int:
        return _ceil_div(n, self.k)

    def _local(self, arr: jax.Array, w: int) -> jax.Array:
        """Device's own contiguous chunk of a replicated 1-D array,
        padded so every device slices a full ``w`` rows."""
        i = lax.axis_index(AXIS)
        pad = self.k * w - arr.shape[0]
        if pad:
            arr = jnp.pad(arr, [(0, pad)] + [(0, 0)] * (arr.ndim - 1))
        start = (i * w,) + (jnp.zeros((), i.dtype),) * (arr.ndim - 1)
        return lax.dynamic_slice(arr, start,
                                 (w,) + arr.shape[1:])

    def _ag(self, local: jax.Array, n: int) -> jax.Array:
        """Tiled all-gather of per-device chunks back to the replicated
        [n]-row array."""
        return lax.all_gather(local, AXIS, tiled=True)[:n]

    # -- halo-windowed plane row gather ----------------------------------

    def plane_rows(self, plane: jax.Array, idx: jax.Array) -> jax.Array:
        """``plane[idx]`` with each device gathering only its own
        ceil(M/k) output rows from a static [W + 2·HALO]-row halo
        window around its slot range.  ROOT (row 0) and NULL (last
        row) reads are overlaid elementwise — both rows are constants
        of the frame construction — so the common cross-shard
        references (root parents, parked slots) never widen the halo.
        A batch whose remaining indices straddle the window falls back
        wholesale to the single-device gather via ``lax.cond`` (the
        replicated predicate keeps every device on the same branch)."""
        r, c = plane.shape
        mi = idx.shape[0]
        w = self._w(mi)
        wwin = w + 2 * self.halo
        # replicated window check (fused_resolve.halo_window_ok — the
        # ops-axis twin of the pallas sweeps' per-tile span checks)
        ok = fused_resolve.halo_window_ok(idx, w, self.halo, r)

        def _windowed(_):
            i = lax.axis_index(AXIS)
            lo = i * w
            plane_p = plane
            if r < wwin:
                plane_p = jnp.pad(plane, ((0, wwin - r), (0, 0)))
            rp = plane_p.shape[0]
            start = jnp.clip(lo - self.halo, 0, rp - wwin)
            win = lax.dynamic_slice(
                plane_p, (start, jnp.zeros((), start.dtype)), (wwin, c))
            idx_l = self._local(idx, w)
            off = jnp.clip(idx_l - start, 0, wwin - 1)
            g = win[off]
            g = jnp.where((idx_l <= 0)[:, None], plane[0][None, :], g)
            g = jnp.where((idx_l >= r - 1)[:, None],
                          plane[r - 1][None, :], g)
            return self._ag(g, mi)

        return lax.cond(ok, _windowed, lambda _: plane[idx], None)

    # -- per-row gathers from replicated frames --------------------------

    def gather_rows(self, table: jax.Array, idx: jax.Array) -> jax.Array:
        """``table[idx]`` with the INDEX axis sharded: each device
        gathers its own ceil(len/k) rows, one tiled all-gather
        reassembles.  ``table`` may be 1-D or a [rows, C] plane."""
        n = idx.shape[0]
        w = self._w(n)
        idx_l = self._local(idx, w)
        return self._ag(table[idx_l], n)

    # -- frame scatters joined by all-reduce -----------------------------

    def frame_set(self, size: int, default, tgt: jax.Array,
                  val: jax.Array, combine: str,
                  dtype=jnp.int32) -> jax.Array:
        """``full(size, default).at[tgt].set(val, mode="drop")`` with
        the scatter's INDEX axis sharded and the per-device frames
        joined by ``pmin``/``pmax`` — exact when targets are globally
        unique and every scattered value wins ``default`` under the
        combine (the shard.py winner-frame pattern)."""
        n = tgt.shape[0]
        w = self._w(n)
        # pad targets with ``size`` (dropped) so pad rows scatter nowhere
        i = lax.axis_index(AXIS)
        pad = self.k * w - n
        tgt_p = jnp.pad(tgt, (0, pad), constant_values=size) if pad \
            else tgt
        val_p = jnp.pad(val, (0, pad)) if pad else val
        tgt_l = lax.dynamic_slice(tgt_p, (i * w,), (w,))
        val_l = lax.dynamic_slice(val_p, (i * w,), (w,))
        frame = jnp.full(size, default, dtype).at[tgt_l].set(
            val_l.astype(dtype), mode="drop", unique_indices=True)
        red = lax.pmin if combine == "min" else lax.pmax
        return red(frame, AXIS)

    def frame_reduce(self, size: int, default, tgt: jax.Array,
                     val: jax.Array, op: str) -> jax.Array:
        """``full(size, default).at[tgt].min/max(val, mode="drop")``
        with DUPLICATE targets allowed (winner election, delete
        tombstones): per-device partial reduce frames joined by
        ``pmin``/``pmax`` — exact because min/max are associative,
        commutative, and absorb the default identity."""
        n = tgt.shape[0]
        w = self._w(n)
        i = lax.axis_index(AXIS)
        pad = self.k * w - n
        tgt_p = jnp.pad(tgt, (0, pad), constant_values=size) if pad \
            else tgt
        val_p = jnp.pad(val, (0, pad)) if pad else val
        tgt_l = lax.dynamic_slice(tgt_p, (i * w,), (w,))
        val_l = lax.dynamic_slice(val_p, (i * w,), (w,))
        frame = jnp.full(size, default, val.dtype)
        if op == "min":
            frame = frame.at[tgt_l].min(val_l, mode="drop")
            return lax.pmin(frame, AXIS)
        frame = frame.at[tgt_l].max(val_l, mode="drop")
        return lax.pmax(frame, AXIS)

    def frame_add(self, size: int, tgt: jax.Array,
                  val=1) -> jax.Array:
        """``zeros(size).at[tgt].add(val, mode="drop")`` sharded along
        the index axis, per-device partial counts joined by ``psum``
        (exact: integer addition)."""
        n = tgt.shape[0]
        w = self._w(n)
        tgt_l = self._local(jnp.where(tgt >= size, size, tgt), w)
        # pad rows beyond n must not count: _local pads with 0, which
        # WOULD land in the frame — re-mask by global row position
        i = lax.axis_index(AXIS)
        rows = i * w + jnp.arange(w, dtype=jnp.int32)
        tgt_l = jnp.where(rows < n, tgt_l, size)
        frame = jnp.zeros(size, jnp.int32).at[tgt_l].add(
            val, mode="drop")
        return lax.psum(frame, AXIS)

    # -- chunked scans with ring carries ---------------------------------

    def prefix_sums(self, boundary: jax.Array, weights: jax.Array):
        """The tour-scan prefix (run-id cumsum over T tokens + weight
        lanes over M slots): local chunk scans + one fused ppermute
        ring of the carries + local fixup
        (ops/tour_scan.sharded_prefix_sums)."""
        return tour_scan.sharded_prefix_sums(boundary, weights,
                                             axis=AXIS, k=self.k)

    def cumsum(self, x: jax.Array) -> jax.Array:
        """1-D inclusive integer cumsum, chunked with ring carries."""
        n = x.shape[0]
        w = self._w(n)
        loc = lax.cumsum(self._local(x.astype(jnp.int32), w))
        carry = tour_scan.ring_exclusive(loc[-1:], AXIS, self.k)
        return self._ag(loc + carry[0], n)

    def cummax(self, x: jax.Array) -> jax.Array:
        """1-D inclusive integer cummax, chunked with ring MAX carries.
        Values are biased non-negative so ppermute's zero-fill acts as
        the identity (tour_scan.ring_exclusive op="max" contract)."""
        n = x.shape[0]
        w = self._w(n)
        lo = jnp.min(x)
        bias = jnp.maximum(jnp.int32(1) - lo, 0)
        loc = lax.cummax(self._local(x, w) + bias)
        # pad rows (value 0 + bias) could inflate the carry of the LAST
        # chunk only — re-mask pad rows to the identity
        i = lax.axis_index(AXIS)
        rows = i * w + jnp.arange(w, dtype=jnp.int32)
        loc = jnp.where(rows < n, loc, 0)
        carry = tour_scan.ring_exclusive(loc[-1:], AXIS, self.k,
                                         op="max")
        fixed = jnp.maximum(loc, carry[0]) - bias
        return self._ag(fixed, n)

    def mono_expand(self, per_run: jax.Array,
                    rid_m: jax.Array) -> jax.Array:
        """``per_run[:, rid_m]`` (the rank-expansion monotone gather)
        with the token axis sharded."""
        n = rid_m.shape[0]
        w = self._w(n)
        rid_l = self._local(rid_m, w)
        g = per_run[:, rid_l]                       # [rows, W] gather
        return jnp.swapaxes(self._ag(jnp.swapaxes(g, 0, 1), n), 0, 1)


# ---- the shard_map entry ------------------------------------------------

# every op column crosses sharded; order fixed for the jit signature
def _cols_of(ops: Dict[str, np.ndarray]):
    return tuple(sorted(ops.keys()))


@functools.partial(jax.jit,
                   static_argnames=("mesh", "cols", "hints",
                                    "no_deletes"))
def _opsaxis_jit(device_ops, mesh: Mesh, cols, hints,
                 no_deletes: bool) -> NodeTable:
    k = mesh.shape[AXIS]

    def body(*vals):
        # boundary exchange: the op columns all-gather ONCE (each
        # device owns a contiguous ops shard on entry — the same
        # exchange parallel/shard.py's resolve performs), then the
        # STOCK kernel runs with the partition context intercepting
        # its billed memory ops.  use_pallas pinned False: Mosaic
        # calls must not trace inside shard_map (mesh.py precedent).
        gathered = {c: lax.all_gather(v, AXIS, tiled=True)
                    for c, v in zip(cols, vals)}
        part = OpsAxisPart(k)
        return merge_mod._materialize.__wrapped__(
            gathered, False, hints, no_deletes, part=part)

    spec = tuple(P(AXIS) if device_ops[c].ndim == 1 else P(AXIS, None)
                 for c in cols)
    fn = jaxcompat.shard_map(body, mesh=mesh, in_specs=spec,
                             out_specs=P(), check_vma=False)
    return fn(*[device_ops[c] for c in cols])


_MESHES: Dict[int, Mesh] = {}
_STATS = {"merges": 0, "devices": 0, "routed_ops": 0}
_STATS_LOCK = threading.Lock()


def _mesh(k: int) -> Mesh:
    m = _MESHES.get(k)
    if m is None:
        m = _MESHES[k] = Mesh(np.asarray(jax.devices()[:k]), (AXIS,))
    return m


def mesh_devices() -> int:
    """The ops-axis width this host would shard at: the largest power
    of two ≤ the local device count."""
    n = len(jax.devices())
    return 1 << (n.bit_length() - 1) if n else 1


def materialize(ops: Dict[str, np.ndarray], k: Optional[int] = None,
                hints: str = "auto") -> NodeTable:
    """One ops-axis sharded merge: bit-identical to
    ``merge.materialize`` on the same (padded) arrays.  ``k`` defaults
    to :func:`mesh_devices`; a non-divisible op count pads to the next
    multiple (the padded tail rides the LAST shard), which widens the
    returned table exactly like padding before the stock kernel would.
    """
    if k is None:
        k = mesh_devices()
    n = ops["kind"].shape[0]
    n_pad = _ceil_div(n, k) * k
    padded = packed_mod.pad_arrays(ops, n_pad) if n_pad != n else ops
    no_deletes = merge_mod.host_no_deletes(np.asarray(padded["kind"]))
    cols = _cols_of(padded)
    mesh = _mesh(k)

    def run():
        device_ops = {
            c: jax.device_put(
                padded[c],
                NamedSharding(mesh, P(AXIS) if padded[c].ndim == 1
                              else P(AXIS, None)))
            for c in cols}
        return _opsaxis_jit(device_ops, mesh, cols, hints, no_deletes)

    with _STATS_LOCK:
        _STATS["merges"] += 1
        _STATS["devices"] = k
        _STATS["routed_ops"] += int(n)
        # shape signature of the last routed merge, kept so runtime
        # reporters (bench/loadgen) can re-derive the shard audit
        # without holding the arrays (shape-only tracing)
        _STATS["last"] = {
            "k": k, "hints": hints, "no_deletes": no_deletes,
            "shapes": {c: (tuple(np.asarray(padded[c]).shape),
                           str(np.asarray(padded[c]).dtype))
                       for c in cols},
            "leg": "hinted" if merge_mod.crowding_hinted(
                padded, hints, no_deletes) else "counted",
        }
    if jax.config.jax_enable_x64:
        return run()
    with jaxcompat.enable_x64(True):
        return run()


# ---- serving route (engine.py) ------------------------------------------

MIN_OPS_DEFAULT = 1 << 18


def route_min_ops() -> int:
    return hostenv.env_int("GRAFT_OPSAXIS_MIN_OPS", MIN_OPS_DEFAULT)


def enabled_for(n_ops: int) -> bool:
    """True when a candidate set of ``n_ops`` rows should take the
    sharded path: GRAFT_OPSAXIS on (kill-switch, default on), ≥ 2
    devices (so <2-device hosts default off), the batch at or past the
    route threshold, and the capacity divisible by the mesh width (the
    engine's power-of-two buckets always are — divisibility keeps the
    NodeTable shapes identical to the single-device path, which the
    serving rollback/attribution contract relies on)."""
    if not hostenv.flag_on("GRAFT_OPSAXIS"):
        return False
    k = mesh_devices()
    return k >= 2 and n_ops >= route_min_ops() and n_ops % k == 0


def routed_materialize(arrays: Dict[str, np.ndarray],
                       hints) -> NodeTable:
    """The serving dispatch: ``merge.materialize`` or the sharded path
    per :func:`enabled_for` — same arrays, same hints mode, identical
    table either way (pinned by tests/test_opsaxis.py through the
    serving path)."""
    n = int(arrays["kind"].shape[0])
    if enabled_for(n):
        return materialize(arrays, hints=hints)
    return merge_mod.materialize(arrays, hints=hints)


def stats() -> dict:
    """Routing counters for the prom scrape + scheduler metrics."""
    with _STATS_LOCK:
        out = {k: v for k, v in _STATS.items() if k != "last"}
    out["enabled"] = hostenv.flag_on("GRAFT_OPSAXIS")
    out["min_ops"] = route_min_ops()
    out["halo_rows"] = HALO
    return out


# ---- audit (chainaudit v3 wiring) ---------------------------------------

def _audit_traced(shapes: Dict[str, jax.ShapeDtypeStruct], k: int,
                  hints, no_deletes: bool, leg: str) -> dict:
    """The shared core: trace the shard_map program shape-only, bill
    per-shard widths + collective bytes (utils/chainaudit.py v3), and
    shape the bench-facing ``opsaxis`` record."""
    from ..utils import chainaudit
    cols = tuple(sorted(shapes))
    mesh = _mesh(k)

    def fn(device_ops):
        return _opsaxis_jit.__wrapped__(device_ops, mesh, cols, hints,
                                        no_deletes)

    with jaxcompat.enable_x64(True):
        audit = chainaudit.count_mwide(fn, shapes)
    m = shapes["kind"].shape[0] + 2
    budget = _ceil_div(m, k) + HALO
    return {
        "devices": k,
        "shard_width": audit.shard_width,
        "shard_budget": budget,
        "halo_rows": HALO,
        "collective_bytes": audit.collective_bytes,
        "collective_count": audit.collective_count,
        "leg": leg,
        "ok": bool(audit.shard_width <= budget),
    }


def audit_opsaxis(ops: Dict[str, np.ndarray], k: Optional[int] = None,
                  hints: str = "exhaustive") -> dict:
    """Shape-only audit of the sharded trace for an op-column dict:
    the ``opsaxis`` stats record {devices, shard_width, shard_budget,
    halo_rows, collective_bytes, leg, ok} every bench row carries
    (bench/runner.py)."""
    if k is None:
        k = mesh_devices()
    n = ops["kind"].shape[0]
    n_pad = _ceil_div(n, k) * k
    padded = packed_mod.pad_arrays(ops, n_pad) if n_pad != n else ops
    no_deletes = merge_mod.host_no_deletes(np.asarray(padded["kind"]))
    leg = "hinted" if merge_mod.crowding_hinted(padded, hints,
                                                no_deletes) \
        else "counted"
    shapes = {c: jax.ShapeDtypeStruct(np.asarray(padded[c]).shape,
                                      np.asarray(padded[c]).dtype)
              for c in _cols_of(padded)}
    return _audit_traced(shapes, k, hints, no_deletes, leg)


def audit_last() -> Optional[dict]:
    """The shard audit of the LAST routed merge's shape signature
    (recorded by :func:`materialize`) — what the loadgen report
    attaches without ever holding the arrays.  None when nothing
    routed this process."""
    with _STATS_LOCK:
        last = _STATS.get("last")
    if not last:
        return None
    shapes = {c: jax.ShapeDtypeStruct(tuple(shape), np.dtype(dt))
              for c, (shape, dt) in last["shapes"].items()}
    return _audit_traced(shapes, last["k"], last["hints"],
                         last["no_deletes"], last["leg"])
