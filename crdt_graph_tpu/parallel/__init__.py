"""Multi-chip scale-out: device meshes and sharded merge entry points."""
from .mesh import (DOCS_AXIS, OPS_AXIS, batched_materialize, make_mesh,
                   sharded_materialize, stack_packed)
from .shard import collective_stats, measure_collectives, shard_materialize

__all__ = [
    "DOCS_AXIS", "OPS_AXIS", "batched_materialize", "make_mesh",
    "sharded_materialize", "stack_packed",
    "shard_materialize", "collective_stats", "measure_collectives",
]
from . import distributed  # noqa: E402  (multi-host helpers)

__all__.append("distributed")
