"""Explicitly partitioned merge: per-shard local work + named collectives.

SURVEY §7 step 4 asks for genuinely partitioned joins with explicit
boundary exchange — the ICI answer to the reference's application network
(ops ship between replicas as JSON; here shards of one op batch exchange
summaries over the mesh).  ``parallel/mesh.py`` delegates partitioning to
XLA (whole-array kernel + input shardings); this module instead expresses
the resolution stages (slot assignment, duplicate election, timestamp→slot
reference resolution, hint verification) as ``jax.shard_map`` with the
communication written out:

- **local slot scatter + min all-reduce**: each shard scatters its ops'
  global row indices into an M-wide winner frame (slot = ingest
  rank + 1), and ONE ``lax.pmin`` joins the frames — the semilattice
  join of partial node tables, 4·M bytes/device ring traffic.  Every
  other node column then derives by gathering the winning row's fields
  from the gathered summaries (the stock ranked path's one-scatter
  construction; scatters carry a large fixed per-element cost on v5e).
- **shard-summary all-gather**: link hints are GLOBAL row positions, so
  resolving a cross-shard reference needs the referenced row's
  (ts, is_add, slot) — exactly the "boundary exchange of shard
  summaries": one tiled ``lax.all_gather`` of the 13-byte/op summary
  columns, after which every resolution gather is local.
- **replicated tail**: the downstream stages (validity cascade, tombstone
  propagation, Euler tour, run-contracted ranking — merge._finish) run
  replicated on every device from the reduced node frame.  This is a
  MEASURED trade, not a guess (VERDICT r4 next-3; updated with the r5
  on-chip shares and the post-rewrite CPU-proxy split, tail ≈ 76% ⇒
  single-merge ceiling ~1.3–1.6× on 8 chips): the fully sharded tail
  is designed (segmented-scan rid, searchsorted compaction, replicated
  ≤32k-wide Wyllie core) with a ~4× Amdahl ceiling, and the docs axis
  delivers 8× today — data, model, design and the committed conclusion
  live in docs/SHARD_TAIL.md (§2b for round 5), instruments in
  scripts/probe_stages.py (kernel ``probe=`` cuts) and
  scripts/probe_shard_stages.py.
  The full op columns are all-gathered once inside the shard_map (the
  tail needs them for the path-plane scatter; doing it explicitly keeps
  the collective schedule visible and measurable).

The whole-array kernel remains the reference path; this path is pinned
bit-identical to it (tests/test_shard_map.py) and its collective volume
is measured against XLA's auto-partitioning of the same merge
(``collective_bytes``; artifact in the round sweep file).

Fallback semantics match the stock kernel: in auto mode the rank/link
verification runs distributed (violation counts psum-reduced), and a
failed verification routes the GATHERED batch through the shared
``merge._resolve_sorted`` under a replicated ``lax.cond`` — wrong hints
cost speed, never correctness.

Pallas note: the rank-expansion gather (ops/mono_gather.py) runs inside
the replicated tail, where every operand is fully replicated — the SPMD
partitioner does not need to slice through the Mosaic call, so
``use_pallas`` may be left on auto here (unlike mesh.py's input-sharded
whole-array path, where the pallas call would sit astride a partitioned
axis and is pinned off).  CPU-mesh tests exercise the lax path; the
Mosaic path under a real multi-chip mesh is untested until multi-chip
hardware exists (single-chip TPU runs never shard).
"""
from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..codec.packed import KIND_ADD, KIND_DELETE
from ..ops import merge as merge_mod
from ..ops.merge import BIG, IPOS, NodeTable
from ..utils import jaxcompat
from .mesh import OPS_AXIS, _pad_ops_to, round_up

# op columns crossing the shard_map boundary, in positional order
_COLS = ("kind", "ts", "parent_ts", "anchor_ts", "depth", "paths",
         "value_ref", "pos", "parent_pos", "anchor_pos", "target_pos",
         "ts_rank")


def _resolve_local(N: int, M: int, vouched: bool, *cols):
    """Per-shard body: local resolution + explicit collectives.

    Runs under shard_map with every input sliced along the op axis
    (length N/k rows here); every output is REPLICATED (identical on
    all devices) — node frames by min all-reduce, per-op columns by
    tiled all-gather.  ``N``/``M`` are the GLOBAL widths; ``vouched``
    mirrors the stock kernel's exhaustive mode (skip the per-hint ts
    check gathers, merge._res_hint_impl)."""
    (kind, ts, parent_ts, anchor_ts, depth, paths, value_ref, pos,
     parent_pos, anchor_pos, target_pos, ts_rank) = cols
    ROOT, NULL = 0, M - 1
    n_loc = kind.shape[0]
    ts = ts.astype(jnp.int64)
    rank = ts_rank.astype(jnp.int32)
    is_add = kind == KIND_ADD
    is_del = kind == KIND_DELETE
    row = (lax.axis_index(OPS_AXIS) * n_loc +
           jnp.arange(n_loc, dtype=jnp.int32))   # global array row

    # ---- slot assignment: local elementwise (rank hints are global)
    is_real_add = is_add & (ts > 0) & (ts < BIG)
    has_rank = is_real_add & (rank >= 0) & (rank < N)
    op_slot = jnp.where(has_rank, rank + 1, NULL).astype(jnp.int32)

    # ---- duplicate election: local M-frame scatter-min of the global
    # row index, joined by one ring min-reduce (the first five tuple
    # entries of the kernel's resolution interface come from these
    # frames).  Winner rule = min array row, identical to the stock
    # ranked path and the stable sort.
    tgt = jnp.where(has_rank, op_slot, M)
    win = jnp.full(M, IPOS, jnp.int32).at[tgt].min(row, mode="drop")
    win = lax.pmin(win, OPS_AXIS)
    is_canon = has_rank & (row == win[op_slot])
    op_is_dup = has_rank & ~is_canon

    # ---- boundary exchange: the shard summary every other shard needs
    # to answer timestamp references into this shard (hint columns hold
    # GLOBAL rows).  12 bytes/op, one tiled all-gather (is_add and
    # op_slot travel pre-fused, merge._pack_slot_or_neg); all resolution
    # gathers below are then local.
    ts_g = lax.all_gather(ts, OPS_AXIS, tiled=True)
    son_g = lax.all_gather(
        merge_mod._pack_slot_or_neg(is_add, op_slot), OPS_AXIS,
        tiled=True)

    # node frame: the joined win row IS the whole frame — every other
    # node column derives by gathering the canonical row's fields from
    # the gathered summary (merge._node_cols_from_row, the stock ranked
    # path's one-scatter construction).  A slot is used iff some row won
    # it; no op scatters to ROOT/NULL (slot = rank+1 ∈ [1, N]).
    pos_g = lax.all_gather(pos.astype(jnp.int32), OPS_AXIS, tiled=True)
    is_node_slot, node_ts, node_pos = merge_mod._node_cols_from_row(
        win, ts_g, pos_g, M, ROOT, N)

    res = functools.partial(merge_mod._res_hint_impl, slot_or_neg=son_g,
                            ts=ts_g, N=N, ROOT=ROOT, NULL=NULL,
                            check_ts=not vouched)
    pp_slot, pp_found, pp_miss = res(
        parent_pos.astype(jnp.int32), parent_ts.astype(jnp.int64))
    # fused anchor-or-target resolution (merge._join_ops_impl): anchor
    # for Add rows, delete target for Delete rows — consumed at disjoint
    # row sets by the tail, so one resolution (and one all-gather pair
    # below) serves both
    at_slot, at_found, at_miss = res(
        jnp.where(is_add, anchor_pos.astype(jnp.int32),
                  target_pos.astype(jnp.int32)),
        merge_mod._at_ts(is_add, anchor_ts.astype(jnp.int64), ts))

    # ---- distributed rank/link verification (the stock kernel's auto
    # mode, violation counts joined by psum): node-frame properties are
    # replicated after the reduces, per-op properties verify locally.
    used = is_node_slot
    dense_ok = jnp.all(~used[2:M - 1] | used[1:M - 2])
    incr_ok = jnp.all(jnp.where(used[1:M - 1] & used[2:M],
                                node_ts[1:M - 1] < node_ts[2:M], True))
    ts_match_l = jnp.all(
        jnp.where(has_rank, node_ts[jnp.clip(op_slot, 0, M - 1)] == ts,
                  True))
    all_ranked_l = jnp.all(~is_real_add | has_rank)
    link_miss_l = jnp.any(pp_miss) | \
        jnp.any(at_miss & (is_add | is_del))
    viol = (~ts_match_l).astype(jnp.int32) + \
        (~all_ranked_l).astype(jnp.int32) + link_miss_l.astype(jnp.int32)
    hints_ok = dense_ok & incr_ok & (lax.psum(viol, OPS_AXIS) == 0)

    # ---- assemble replicated outputs: per-op resolution columns and
    # the full op columns the replicated tail consumes (one explicit
    # all-gather each — this is where auto-partitioning would have
    # inserted its own gathers around the tail's scatters)
    gath = lambda x: lax.all_gather(x, OPS_AXIS, tiled=True)  # noqa: E731
    # global op_slot column, recovered elementwise from the fused
    # slot-or-neg summary (non-Add rows carried op_slot == NULL locally)
    op_slot_g = jnp.where(son_g >= 0, son_g, NULL).astype(jnp.int32)
    sel = (op_slot_g, gath(op_is_dup), node_ts, node_pos,
           is_node_slot, win, gath(pp_slot), gath(at_slot),
           gath(pp_found), gath(at_found))
    gathered = {
        "kind": gath(kind), "ts": ts_g,
        "parent_ts": gath(parent_ts), "anchor_ts": gath(anchor_ts),
        "depth": gath(depth), "paths": gath(paths),
        "value_ref": gath(value_ref), "pos": pos_g,
    }
    return gathered, sel, hints_ok


@functools.partial(jax.jit,
                   static_argnames=("mesh", "hints", "use_pallas",
                                    "no_deletes"))
def _shard_materialize_jit(device_ops, mesh: Mesh, hints: str,
                           use_pallas, no_deletes: bool) -> NodeTable:
    N = device_ops["kind"].shape[0]
    M = N + 2
    body = functools.partial(_resolve_local, N, M,
                             hints == "exhaustive")
    spec = [P(OPS_AXIS) if device_ops[c].ndim == 1 else P(OPS_AXIS, None)
            for c in _COLS]
    resolve = jaxcompat.shard_map(body, mesh=mesh, in_specs=tuple(spec),
                            out_specs=P(), check_vma=False)
    gathered, sel, hints_ok = resolve(*[device_ops[c] for c in _COLS])
    if hints == "exhaustive":
        pass          # caller vouched: the cond (and the sort) never trace
    else:
        sel = lax.cond(hints_ok, lambda _: sel,
                       lambda _: merge_mod._resolve_sorted(gathered), None)
    return merge_mod._finish(gathered, sel, use_pallas, no_deletes)


def shard_materialize(ops: Dict[str, np.ndarray], mesh: Mesh,
                      hints: str = "auto",
                      use_pallas=None) -> NodeTable:
    """One merge with the resolution stages explicitly partitioned over
    the mesh's ``ops`` axis (module docstring).  Requires the hint
    columns (any PackedOps has them); result is replicated and
    bit-identical to ``merge.materialize`` on the same ops."""
    if hints not in ("auto", "exhaustive"):
        raise ValueError(f"hints must be 'auto' or 'exhaustive', "
                         f"got {hints!r}")
    missing = [c for c in _COLS if c not in ops]
    if missing:
        raise ValueError(f"shard_materialize needs hint columns; "
                         f"missing {missing} (use packed.pack)")
    k = mesh.shape[OPS_AXIS]
    n = round_up(ops["kind"].shape[0], k)
    padded = _pad_ops_to(ops, n)
    no_deletes = merge_mod.host_no_deletes(np.asarray(ops["kind"]))

    def run():
        device_ops = {
            c: jax.device_put(
                padded[c],
                NamedSharding(mesh, P(OPS_AXIS) if padded[c].ndim == 1
                              else P(OPS_AXIS, None)))
            for c in _COLS}
        return _shard_materialize_jit(device_ops, mesh, hints,
                                      use_pallas, no_deletes)

    if jax.config.jax_enable_x64:
        return run()
    with jaxcompat.enable_x64(True):
        return run()


# ---- collective-volume accounting --------------------------------------

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8}

_COLLECTIVES = ("all-gather", "all-reduce", "all-to-all",
                "collective-permute", "reduce-scatter")


def _shape_bytes(shape: str) -> int:
    """Bytes of one HLO shape string like ``s32[8,131072]{1,0}``."""
    dt = shape.split("[", 1)[0]
    if dt not in _DTYPE_BYTES:
        return 0
    dims = shape.split("[", 1)[1].split("]", 1)[0]
    total = _DTYPE_BYTES[dt]
    for d in dims.split(","):
        d = d.strip()
        if d:
            total *= int(d)
    return total


def collective_stats(hlo_text: str) -> Dict[str, int]:
    """Per-collective output bytes summed from compiled HLO text — the
    measurable 'bytes moved' comparison between this module's explicit
    schedule and XLA's auto-partitioning (VERDICT r3 missing-2)."""
    import re
    out = {name: 0 for name in _COLLECTIVES}
    out["count"] = 0
    pat = re.compile(
        r"=\s*(\(?[a-z0-9]+\[[^=]*?)\s+(" + "|".join(_COLLECTIVES) +
        r")(-start|-done)?\(")
    for m in pat.finditer(hlo_text):
        shapes, name, phase = m.group(1), m.group(2), m.group(3)
        if phase == "-done":
            continue           # the -start already counted this transfer
        found = re.findall(r"[a-z0-9]+\[[0-9, ]*\]", shapes)
        if phase == "-start" and len(found) > 1:
            # async tuple is (operand alias, result, ...): count only
            # the transferred result, not the aliased operand
            found = found[1:]
        out[name] += sum(_shape_bytes(s) for s in found)
        out["count"] += 1
    out["total_bytes"] = sum(out[n] for n in _COLLECTIVES)
    return out


def measure_collectives(fn, *args) -> Dict[str, int]:
    """Compile ``fn(*args)`` and account its collective traffic."""
    return collective_stats(jax.jit(fn).lower(*args).compile().as_text())
