"""Multi-chip scale-out for the merge kernel: meshes, shardings, collectives.

The reference distributes by shipping JSON op batches between replicas over
an application-provided network (CRDTree/Operation.elm:109-159, README.md:
20-22).  Here "the network" is the TPU interconnect: op arrays live sharded
over a ``jax.sharding.Mesh`` and the collectives XLA inserts for the merge
kernel's sorts and gathers ride ICI/DCN.

Two orthogonal mesh axes, composable into a 2-D mesh:

- ``docs`` — data parallelism over independent documents (trees).  A server
  merging many documents batches them on a leading axis and shards that axis;
  merges never communicate across documents, so scaling is linear.  This is
  the realistic serving axis (each collaborative document is independent).
- ``ops`` — parallelism *within* one merge: the packed op axis is sharded, so
  each chip holds a slice of the operation set (e.g. the logs of a subset of
  replicas, concatenated: the semilattice join is insensitive to how ops are
  distributed).  The kernel is expressed as whole-array ``lax`` ops
  (sort/scatter/gather); partitioning is delegated to XLA's SPMD partitioner
  via input shardings — the idiomatic JAX recipe (mesh → shardings → let XLA
  insert all-to-alls/all-gathers) rather than hand-written per-chip message
  passing.

Entry points:

- :func:`make_mesh` — build a 1-D or 2-D device mesh.
- :func:`sharded_materialize` — one merge, op axis sharded over ``ops``.
- :func:`batched_materialize` — B independent merges, vmapped on a leading
  doc axis, sharded over ``docs`` (and optionally ``ops``).

This module is the auto-partitioned path (whole-array kernel + input
shardings; XLA chooses the collectives).  The EXPLICIT schedule — per-
shard local resolution with hand-placed pmin/all_gather boundary
exchange, which moves ~2x fewer bytes in ~3x fewer collectives (measured:
SWEEP_CPU_r04.jsonl) — lives in :mod:`crdt_graph_tpu.parallel.shard`;
both are pinned bit-identical to the single-device kernel.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..codec.packed import KIND_PAD, PackedOps
from ..codec.packed import pad_arrays as packed_pad_arrays
from ..ops import merge as merge_mod
from ..utils import jaxcompat
from ..ops.merge import NodeTable

DOCS_AXIS = "docs"
OPS_AXIS = "ops"


def make_mesh(n_docs: int = 1, n_ops: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A ``(docs, ops)`` mesh over ``n_docs * n_ops`` devices."""
    if devices is None:
        devices = jax.devices()
    need = n_docs * n_ops
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_docs, n_ops)
    return Mesh(grid, (DOCS_AXIS, OPS_AXIS))


# canonical implementation lives with the column format (codec.packed);
# kept under the old name for the existing call sites
_pad_ops_to = packed_pad_arrays


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def sharded_materialize(ops: Dict[str, np.ndarray], mesh: Mesh) -> NodeTable:
    """One merge with the op axis sharded over the mesh's ``ops`` axis.

    The op arrays are padded to a multiple of the axis size, placed with
    ``NamedSharding(mesh, P(OPS_AXIS))``, and the stock kernel is jitted with
    those input shardings; XLA partitions the sorts and scatter/gathers and
    inserts the ICI collectives.  The resulting table is replicated (every
    chip holds the converged tree — which is what a serving replica wants).
    """
    n_ops = mesh.shape[OPS_AXIS]
    n = round_up(ops["kind"].shape[0], n_ops)
    padded = _pad_ops_to(ops, n)

    def run():
        # device_put must sit inside the x64 scope: outside it JAX silently
        # downcasts int64 host arrays to int32, truncating timestamps.
        # The pallas rank gather is not partition-aware, so explicitly
        # sharded merges pin the lax path; hints keep the default auto
        # mode (the cond's scalar predicate partitions fine under SPMD,
        # and the join fallback stays available for hint-less or
        # mislinked inputs — e.g. restored old checkpoints).
        device_ops = {k: jax.device_put(v, NamedSharding(mesh, P(OPS_AXIS)))
                      for k, v in padded.items()}
        return merge_mod.materialize(device_ops, use_pallas=False)

    if jax.config.jax_enable_x64:
        return run()
    with jaxcompat.enable_x64(True):
        return run()


def _materialize_batched_safe(ops):
    # default batched body: the hinted path's lax.cond would execute
    # BOTH branches under vmap, so hints are dropped and the join runs;
    # pallas stays off (must not trace under vmap)
    ops = {k: v for k, v in ops.items()
           if k not in ("parent_pos", "anchor_pos", "target_pos")}
    return merge_mod._materialize.__wrapped__(ops, False, "join")


def _materialize_batched_exhaustive(ops):
    # opt-in fast body (batched_materialize(exhaustive_hints=True)):
    # cond-free hinted resolution, valid ONLY for batches whose hint
    # coverage the caller vouches for (pack/stack_packed provenance) —
    # a violated promise silently mis-resolves references
    return merge_mod._materialize.__wrapped__(ops, False, "exhaustive")


_batched_kernel = jax.jit(jax.vmap(_materialize_batched_safe))
_batched_kernel_hinted = jax.jit(jax.vmap(_materialize_batched_exhaustive))


def batched_materialize(ops: Dict[str, np.ndarray], mesh: Mesh,
                        shard_ops_axis: bool = False,
                        exhaustive_hints: bool = False) -> NodeTable:
    """B independent merges: arrays carry a leading document axis ``[B, N]``.

    The doc axis is sharded over ``docs`` — embarrassingly parallel, linear
    scaling (the serving path: many documents, one merge each).  With
    ``shard_ops_axis`` the op axis is additionally sharded over ``ops`` for
    2-D parallelism on large per-document batches.

    ``exhaustive_hints=True`` opts into the cond-free hinted timestamp
    resolution — ONLY for batches whose link-hint coverage the caller
    vouches for (pack/stack_packed provenance); the default drops hints
    and joins, which is correct for any input.
    """
    n_docs = mesh.shape[DOCS_AXIS]
    b = ops["kind"].shape[0]
    if b % n_docs != 0:
        raise ValueError(f"doc axis {b} not divisible by mesh docs axis "
                         f"{n_docs}; pad the document batch")
    op_spec = (OPS_AXIS,) if shard_ops_axis else (None,)

    def spec_for(v: np.ndarray) -> P:
        return P(DOCS_AXIS, *op_spec[:max(0, v.ndim - 1)])

    kernel = _batched_kernel_hinted if exhaustive_hints else _batched_kernel

    def _placed(v, sharding):
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            # cross-process global array (host_local_docs_to_global
            # already placed it): a device_put here would RESHARD
            # through the data plane, which the CPU multi-controller
            # backend cannot do — the computation follows the array's
            # existing docs-axis sharding instead
            return v
        return jax.device_put(v, sharding)

    def run():
        device_ops = {k: _placed(v, NamedSharding(mesh, spec_for(v)))
                      for k, v in ops.items()}
        return kernel(device_ops)

    if jax.config.jax_enable_x64:
        return run()
    with jaxcompat.enable_x64(True):
        return run()


def stack_aligned(batches: Sequence[PackedOps]
                  ) -> "tuple[Dict[str, np.ndarray], list]":
    """:func:`stack_packed` plus per-document capacity alignment: every
    batch is first re-padded to the SHARED capacity (codec.packed
    ``with_capacity``) and the aligned PackedOps are returned alongside
    the stacked arrays.  The serving scheduler commits each document
    against its slice of the batched table, so the per-document columns
    it parks must have the same row capacity the table was materialized
    at — stacking alone would leave them inconsistent."""
    from ..codec.packed import with_capacity
    shared = max(p.capacity for p in batches)
    aligned = [with_capacity(p, shared) for p in batches]
    return stack_packed(aligned), aligned


class LingerBatcher:
    """Cross-caller batch accumulation window for the vmapped launch
    (the merge tier's coalescing heart — mergetier/worker.py).

    Many threads each hold ONE item (a document's prepared candidate
    set) and want it materialized; a wide ``batched_materialize``
    amortizes launch overhead across all of them, but only if their
    arrivals meet in the same launch.  :meth:`submit` parks the caller
    while a shared window fills: the FIRST arrival becomes the epoch's
    leader and waits up to ``linger_s`` (``GRAFT_MERGETIER_BATCH_MS``)
    for co-travellers, launching early the moment ``max_width`` items
    are aboard; everyone else rides, and every submitter gets exactly
    its own item's result back.  A failed launch fails the WHOLE
    epoch's submitters (each caller falls back on its own — for the
    merge tier that is the front-end's bit-identical local merge).

    ``launch`` receives the epoch's item list and must return one
    result per item, in order.  It runs on the leader's thread; the
    batcher itself never touches JAX, so the one-thread-owns-JAX
    serving invariant is the launch callable's business, not ours.
    """

    def __init__(self, launch: Callable[[List[Any]], List[Any]],
                 linger_s: float = 0.002, max_width: int = 16):
        self._launch = launch
        self.linger_s = max(0.0, float(linger_s))
        self.max_width = max(1, int(max_width))
        self._cv = threading.Condition()
        self._epoch = 0
        self._items: List[Any] = []          # current epoch's cargo
        self._done: Dict[int, tuple] = {}    # epoch -> (results, error)
        self._riders: Dict[int, int] = {}    # epoch -> riders not yet woken
        self._closed = False
        # telemetry (read under the cv by stats())
        self.launches = 0
        self.items_in = 0
        self.full_launches = 0               # width cap hit (no linger)
        self.linger_waits = 0                # epochs that waited the window

    def submit(self, item: Any) -> Any:
        """Park until this item's epoch launches; returns its result.
        Raises whatever the epoch's launch raised (every rider sees the
        same error) or ``RuntimeError`` after :meth:`close`."""
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher closed")
            epoch = self._epoch
            self._items.append(item)
            self.items_in += 1
            index = len(self._items) - 1
            leader = index == 0
            if not leader and len(self._items) >= self.max_width:
                # cap reached: wake the lingering leader early
                self._cv.notify_all()
            if leader:
                deadline = time.monotonic() + self.linger_s
                waited = False
                while (len(self._items) < self.max_width
                       and not self._closed):
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        break
                    waited = True
                    self._cv.wait(remain)
                cargo, self._items = self._items, []
                self._epoch += 1
                if waited:
                    self.linger_waits += 1
                if len(cargo) >= self.max_width:
                    self.full_launches += 1
            else:
                while epoch not in self._done and not self._closed:
                    self._cv.wait(1.0)
                if epoch not in self._done:
                    raise RuntimeError("batcher closed mid-epoch")
                results, error = self._done[epoch]
                # last rider out sweeps the epoch's parking spot
                self._rider_done(epoch)
                if error is not None:
                    raise error
                return results[index]
        # leader, outside the lock: run the launch for the whole epoch
        results: Optional[List[Any]] = None
        error: Optional[BaseException] = None
        try:
            results = self._launch(cargo)
            if results is None or len(results) != len(cargo):
                raise RuntimeError(
                    f"launch returned {0 if results is None else len(results)}"
                    f" results for {len(cargo)} items")
        except BaseException as e:   # noqa: BLE001 — every rider must
            # wake with THIS error, whatever class it is
            error = e
        with self._cv:
            self.launches += 1
            self._done[epoch] = (results, error)
            self._riders[epoch] = len(cargo) - 1
            if self._riders[epoch] == 0:
                del self._done[epoch], self._riders[epoch]
            self._cv.notify_all()
        if error is not None:
            raise error
        return results[0]

    def _rider_done(self, epoch: int) -> None:
        # requires self._cv
        self._riders[epoch] -= 1
        if self._riders[epoch] == 0:
            del self._done[epoch], self._riders[epoch]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            return {"launches": self.launches,
                    "items_in": self.items_in,
                    "full_launches": self.full_launches,
                    "linger_waits": self.linger_waits,
                    "linger_ms": round(self.linger_s * 1e3, 3),
                    "max_width": self.max_width,
                    "pending": len(self._items)}


def stack_packed(batches: Sequence[PackedOps]) -> Dict[str, np.ndarray]:
    """Stack per-document packed ops into ``[B, N]`` arrays (N = max,
    pad-extended; path planes widened to the widest depth bucket) for
    :func:`batched_materialize`."""
    n = max(p.capacity for p in batches)
    width = max(p.max_depth for p in batches)
    per = []
    for p in batches:
        arrs = dict(p.arrays())
        if arrs["paths"].shape[1] < width:
            wide = np.zeros((arrs["paths"].shape[0], width),
                            dtype=arrs["paths"].dtype)
            wide[:, :arrs["paths"].shape[1]] = arrs["paths"]
            arrs["paths"] = wide
        per.append(_pad_ops_to(arrs, n))
    # derived slot-hint columns ride along only when EVERY document has
    # them (arrays() omits them for unvouched batches; a mixed stack
    # takes the gather-based resolution rather than trusting half)
    keys = set(per[0])
    for d in per[1:]:
        keys &= set(d)
    return {k: np.stack([d[k] for d in per]) for k in keys}
