"""Multi-host distribution: the DCN story above the single-host mesh.

The reference's "distributed backend" is a protocol contract carried by an
application network (SURVEY §2b); within one TPU pod slice, ICI collectives
replace it (parallel/mesh.py).  Across hosts, this module provides the
standard JAX multi-controller setup: every host runs the same program,
``jax.distributed.initialize`` wires them into one runtime, and arrays are
assembled from per-host shards so each host feeds only its local documents
(the docs axis spans the fleet; XLA routes any cross-host collectives over
DCN).

On a single host everything degrades to the local mesh — ``initialize`` is
skipped and ``global_device_mesh`` is exactly ``make_mesh``.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DOCS_AXIS, OPS_AXIS


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime; no-op for single-process runs.

    Call once at startup on every host, before any device computation:
    ``initialize("host0:1234", num_processes=N, process_id=k)``.  With no
    arguments, auto-detects cluster env (TPU pod metadata) and falls back
    to single-process when there is none.

    ``GRAFT_DIST_HEARTBEAT_S`` / ``GRAFT_DIST_MAX_MISSING`` tune the
    coordination service's failure detector (default 10 s × 10 misses
    ≈ 100 s to declare a dead peer — the right paranoia for a TPU pod,
    but a localhost chaos harness that WANTS the death observed fast
    can drop detection to seconds instead of stalling the surviving
    gang member for the full default window)."""
    if num_processes is not None and num_processes <= 1:
        return
    import os as _os
    kw = {}
    hb = _os.environ.get("GRAFT_DIST_HEARTBEAT_S")
    mm = _os.environ.get("GRAFT_DIST_MAX_MISSING")
    if hb or mm:
        try:
            if hb:
                kw["service_heartbeat_interval_seconds"] = \
                    kw["client_heartbeat_interval_seconds"] = \
                    max(1, int(hb))
            if mm:
                kw["service_max_missing_heartbeats"] = \
                    kw["client_max_missing_heartbeats"] = \
                    max(2, int(mm))
        except ValueError:
            kw = {}
    try:
        if kw:
            # the public wrapper doesn't expose the heartbeat knobs;
            # the state object's initialize (which it delegates to)
            # does — fall back to the public call if the private
            # surface moves under a future jax
            try:
                from jax._src.distributed import global_state
                global_state.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id, **kw)
                return
            except (ImportError, AttributeError, TypeError):
                # the private surface moved (or dropped the knobs):
                # fall through to the public call — slower failure
                # detection beats a node that cannot start
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except (ValueError, RuntimeError):
        if coordinator_address is not None or num_processes is not None:
            # the caller explicitly asked for a cluster — a silent
            # single-process fallback would shard the fleet wrongly
            raise
        # bare auto-detect on a non-cluster machine: nothing to do


def global_device_mesh(n_ops: int = 1) -> Mesh:
    """A ``(docs, ops)`` mesh over EVERY device in the fleet (all hosts).

    The docs axis spans hosts (document merges never communicate, so DCN
    carries no merge traffic); the ops axis should stay within a host's
    devices so op-axis collectives ride ICI — keep ``n_ops`` ≤ local
    device count.
    """
    devices = jax.devices()
    if len(devices) % n_ops != 0:
        raise ValueError(f"{len(devices)} devices not divisible by "
                         f"n_ops={n_ops}")
    grid = np.asarray(devices).reshape(len(devices) // n_ops, n_ops)
    return Mesh(grid, (DOCS_AXIS, OPS_AXIS))


def allgather_scalars(tag: str, local: Dict[int, int],
                      timeout_ms: int = 120_000) -> Dict[int, int]:
    """All-gather a small ``{index: value}`` host dict across the fleet
    via the coordination service's key-value store.

    The CONTROL plane, not the data plane: this jaxlib's CPU backend
    cannot reshard device arrays across processes
    (``multihost_utils.process_allgather`` dies with "Multiprocess
    computations aren't implemented on the CPU backend"), but the
    coordination client every ``jax.distributed.initialize`` runtime
    already carries moves host scalars fine — which is all the fleet
    verification sweeps exchange (per-doc fingerprints).  Keys are
    namespaced by ``tag``; call with a fresh tag per exchange (the KV
    store has no delete).  Raises on timeout — a dead peer must fail
    the gather loudly, not hang it."""
    import json as _json

    from jax._src import distributed as _dist

    client = _dist.global_state.client
    if client is None:
        return dict(local)
    pid = jax.process_index()
    client.key_value_set(f"{tag}/{pid}",
                         _json.dumps(sorted(local.items())))
    out: Dict[int, int] = {}
    for p in range(jax.process_count()):
        got = client.blocking_key_value_get(f"{tag}/{p}", timeout_ms)
        out.update({int(k): int(v) for k, v in _json.loads(got)})
    return out


def host_local_docs_to_global(ops: Dict[str, np.ndarray],
                              mesh: Mesh) -> Dict[str, jax.Array]:
    """Assemble a fleet-wide batch from per-host document shards.

    Each host passes the packed ``[B_local, N]`` arrays of its own
    documents; the result is one global ``[B_global, N]`` array sharded
    over the mesh's docs axis, ready for ``batched_materialize``'s kernel
    (every host computes only its shard).
    """
    spec = P(DOCS_AXIS)
    return {
        k: jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), v)
        for k, v in ops.items()
    }
