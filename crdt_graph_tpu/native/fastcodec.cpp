// Native wire codec: reference-format JSON -> packed operation columns.
//
// The TPU merge kernel consumes struct-of-array operation batches
// (codec/packed.py).  The pure-Python path (json.loads -> Operation objects
// -> pack()) builds millions of Python objects per large batch and caps
// ingest around half a million ops/s — far below the device's merge rate.
// This extension parses the wire format (CRDTree/Operation.elm:109-159 —
// {"op":"add","path":[..],"ts":n,"val":..}, {"op":"del","path":[..]},
// {"op":"batch","ops":[..]}, unknown tags = empty batch) straight into
// int64/int32/int8 columns in a single pass, building Python objects only
// for the opaque "val" payloads.
//
// Exposed as crdt_graph_tpu.native._fastcodec.parse_pack(payload, max_depth)
// -> dict of bytes columns + values list + count; the Python wrapper wraps
// them in numpy without copying (np.frombuffer) and pads to capacity.
// Semantics (flatten order, strict ints, range checks) mirror
// codec/json_codec.py + codec/packed.py and are pinned by
// tests/test_native_codec.py.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <limits>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t MAX_TS = int64_t(1) << 62;  // kernel sentinel space

struct Columns {
  std::vector<int8_t> kind;
  std::vector<int64_t> ts, parent, anchor;
  std::vector<int32_t> depth, value_ref;
  std::vector<int64_t> paths;  // row-major [n, max_depth]
  PyObject* values;            // list of parsed "val" payloads
  int max_depth;
};

struct Parser {
  const char* begin;
  const char* p;
  const char* end;
  std::string err;
  int value_depth = 0;  // recursion guard for value_py/skip_value

  // Untrusted wire input must not be able to overflow the C stack with
  // deep nesting (Python's json raises RecursionError; we fail the parse).
  // 512 matches the operation() batch-nesting cap.
  static constexpr int kMaxValueDepth = 512;

  struct DepthGuard {
    int& d;
    bool ok;
    explicit DepthGuard(int& depth)
        : d(depth), ok(++depth <= kMaxValueDepth) {}
    ~DepthGuard() { --d; }
  };

  explicit Parser(const char* data, Py_ssize_t n)
      : begin(data), p(data), end(data + n) {}

  bool fail(const std::string& m) {
    if (err.empty()) {
      err = m + " at offset " + std::to_string(size_t(p - begin));
    }
    return false;
  }

  void ws() {
    while (p < end &&
           (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) {
      ++p;
    }
  }

  bool lit(const char* s) {
    size_t n = std::strlen(s);
    if (size_t(end - p) < n || std::memcmp(p, s, n) != 0) {
      return fail(std::string("expected '") + s + "'");
    }
    p += n;
    return true;
  }

  // ---- numbers ----
  // Integral JSON number -> int64 (strict: JSON int grammar -?(0|[1-9]\d*),
  // no fraction/exponent, in-range).
  bool int64_field(int64_t* out) {
    ws();
    bool neg = false;
    if (p < end && *p == '-') { neg = true; ++p; }
    if (p >= end || *p < '0' || *p > '9') return fail("expected integer");
    if (*p == '0' && p + 1 < end && p[1] >= '0' && p[1] <= '9') {
      return fail("leading zero in integer");
    }
    uint64_t v = 0;
    while (p < end && *p >= '0' && *p <= '9') {
      uint64_t d = uint64_t(*p - '0');
      if (v > (UINT64_MAX - d) / 10) return fail("integer overflow");
      v = v * 10 + d;
      ++p;
    }
    if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) {
      return fail("expected integer, got float");
    }
    // The wire's integer domain is [0, MAX_TS): the merge kernel's int32
    // bit-half sort keys assume ts < 2^62 (merge.py _split_ts), so a
    // well-formed wire op past the bound would silently corrupt bulk
    // merges while the host path absorbed it — both ingest paths reject
    // at decode (json_codec._int_field matches).  Single source of
    // truth for the domain; emit() no longer re-checks.
    // JSON "-0" parses to 0 on the Python path (json.loads), so it must
    // here too; any other negative is out of the wire domain
    if ((neg && v != 0) || v >= uint64_t(MAX_TS)) {
      return fail("integer out of range");
    }
    *out = int64_t(v);
    return true;
  }

  // Full JSON number grammar: int frac? exp?  (used for value payloads).
  bool scan_number(bool* is_float) {
    *is_float = false;
    if (p < end && *p == '-') ++p;
    if (p >= end || *p < '0' || *p > '9') return fail("bad number");
    if (*p == '0') {
      ++p;
    } else {
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p < end && *p == '.') {
      *is_float = true;
      ++p;
      if (p >= end || *p < '0' || *p > '9') return fail("bad number");
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      *is_float = true;
      ++p;
      if (p < end && (*p == '+' || *p == '-')) ++p;
      if (p >= end || *p < '0' || *p > '9') return fail("bad number");
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    return true;
  }

  // ---- strings ----
  bool hex4(unsigned* out) {
    if (end - p < 4) return fail("bad \\u escape");
    unsigned v = 0;
    for (int i = 0; i < 4; i++) {
      char c = p[i];
      unsigned d;
      if (c >= '0' && c <= '9') d = c - '0';
      else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
      else return fail("bad \\u escape");
      v = (v << 4) | d;
    }
    p += 4;
    *out = v;
    return true;
  }

  static void append_utf8(std::string& s, unsigned cp) {
    if (cp < 0x80) {
      s += char(cp);
    } else if (cp < 0x800) {
      s += char(0xC0 | (cp >> 6));
      s += char(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      s += char(0xE0 | (cp >> 12));
      s += char(0x80 | ((cp >> 6) & 0x3F));
      s += char(0x80 | (cp & 0x3F));
    } else {
      s += char(0xF0 | (cp >> 18));
      s += char(0x80 | ((cp >> 12) & 0x3F));
      s += char(0x80 | ((cp >> 6) & 0x3F));
      s += char(0x80 | (cp & 0x3F));
    }
  }

  bool string_raw(std::string* out) {
    ws();
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    out->clear();
    while (p < end) {
      unsigned char c = *p;
      if (c == '"') { ++p; return true; }
      if (c == '\\') {
        ++p;
        if (p >= end) return fail("unterminated escape");
        char e = *p++;
        switch (e) {
          case '"': *out += '"'; break;
          case '\\': *out += '\\'; break;
          case '/': *out += '/'; break;
          case 'b': *out += '\b'; break;
          case 'f': *out += '\f'; break;
          case 'n': *out += '\n'; break;
          case 'r': *out += '\r'; break;
          case 't': *out += '\t'; break;
          case 'u': {
            unsigned cp;
            if (!hex4(&cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              if (end - p >= 2 && p[0] == '\\' && p[1] == 'u') {
                const char* save = p;
                p += 2;
                unsigned lo;
                if (!hex4(&lo)) return false;
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                } else {
                  p = save;  // lone high surrogate kept, like json.loads
                }
              }
              // lone surrogates encode as WTF-8; decoded with
              // "surrogatepass" below, matching Python's json.loads
            }
            append_utf8(*out, cp);
            break;
          }
          default:
            return fail("bad escape");
        }
      } else if (c < 0x20) {
        return fail("control char in string");
      } else if (c < 0x80) {
        *out += char(c);
        ++p;
      } else {
        // Literal multi-byte sequence: validate STRICT UTF-8, exactly
        // like json.loads on bytes input (which utf-8-decodes the whole
        // document before parsing — invalid sequences, overlongs,
        // surrogate encodings and > U+10FFFF are all rejections there).
        // Escape-produced lone surrogates take the \u path above and
        // stay admitted (WTF-8), matching Python.
        unsigned cp;
        int extra;
        if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; extra = 1; }
        else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; extra = 2; }
        else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; extra = 3; }
        else return fail("invalid utf-8");
        if (end - p <= extra) return fail("invalid utf-8");
        for (int i = 1; i <= extra; ++i) {
          if ((static_cast<unsigned char>(p[i]) & 0xC0) != 0x80) {
            return fail("invalid utf-8");
          }
          cp = (cp << 6) | (static_cast<unsigned char>(p[i]) & 0x3F);
        }
        static const unsigned kMin[4] = {0, 0x80, 0x800, 0x10000};
        if (cp < kMin[extra] || cp > 0x10FFFF ||
            (cp >= 0xD800 && cp <= 0xDFFF)) {
          return fail("invalid utf-8");
        }
        out->append(reinterpret_cast<const char*>(p), size_t(extra) + 1);
        p += extra + 1;
      }
    }
    return fail("unterminated string");
  }

  // ---- generic values (for "val" payloads) -> Python objects ----
  PyObject* value_py() {
    DepthGuard guard(value_depth);
    if (!guard.ok) { fail("value nesting too deep"); return nullptr; }
    ws();
    if (p >= end) { fail("unexpected end"); return nullptr; }
    switch (*p) {
      case '{': {
        ++p;
        PyObject* d = PyDict_New();
        if (!d) return nullptr;
        ws();
        if (p < end && *p == '}') { ++p; return d; }
        while (true) {
          std::string key;
          if (!string_raw(&key)) { Py_DECREF(d); return nullptr; }
          ws();
          if (p >= end || *p != ':') {
            fail("expected ':'");
            Py_DECREF(d);
            return nullptr;
          }
          ++p;
          PyObject* v = value_py();
          if (!v) { Py_DECREF(d); return nullptr; }
          PyObject* k = PyUnicode_DecodeUTF8(
              key.data(), Py_ssize_t(key.size()), "surrogatepass");
          if (!k || PyDict_SetItem(d, k, v) < 0) {
            Py_XDECREF(k); Py_DECREF(v); Py_DECREF(d);
            return nullptr;
          }
          Py_DECREF(k);
          Py_DECREF(v);
          ws();
          if (p < end && *p == ',') { ++p; continue; }
          if (p < end && *p == '}') { ++p; return d; }
          fail("expected ',' or '}'");
          Py_DECREF(d);
          return nullptr;
        }
      }
      case '[': {
        ++p;
        PyObject* l = PyList_New(0);
        if (!l) return nullptr;
        ws();
        if (p < end && *p == ']') { ++p; return l; }
        while (true) {
          PyObject* v = value_py();
          if (!v) { Py_DECREF(l); return nullptr; }
          if (PyList_Append(l, v) < 0) {
            Py_DECREF(v); Py_DECREF(l);
            return nullptr;
          }
          Py_DECREF(v);
          ws();
          if (p < end && *p == ',') { ++p; continue; }
          if (p < end && *p == ']') { ++p; return l; }
          fail("expected ',' or ']'");
          Py_DECREF(l);
          return nullptr;
        }
      }
      case '"': {
        std::string s;
        if (!string_raw(&s)) return nullptr;
        return PyUnicode_DecodeUTF8(s.data(), Py_ssize_t(s.size()),
                                    "surrogatepass");
      }
      case 't':
        if (!lit("true")) return nullptr;
        Py_RETURN_TRUE;
      case 'f':
        if (!lit("false")) return nullptr;
        Py_RETURN_FALSE;
      case 'n':
        if (!lit("null")) return nullptr;
        Py_RETURN_NONE;
      case 'N':
        if (!lit("NaN")) return nullptr;
        return PyFloat_FromDouble(std::numeric_limits<double>::quiet_NaN());
      case 'I':
        if (!lit("Infinity")) return nullptr;
        return PyFloat_FromDouble(std::numeric_limits<double>::infinity());
      default: {
        // number: validate the JSON grammar, decide int vs float like
        // Python's json (which also accepts -Infinity)
        if (*p == '-' && p + 1 < end && p[1] == 'I') {
          if (!lit("-Infinity")) return nullptr;
          return PyFloat_FromDouble(
              -std::numeric_limits<double>::infinity());
        }
        const char* start = p;
        bool is_float;
        if (!scan_number(&is_float)) return nullptr;
        std::string tok(start, size_t(p - start));
        if (is_float) {
          return PyFloat_FromDouble(strtod(tok.c_str(), nullptr));
        }
        return PyLong_FromString(tok.c_str(), nullptr, 10);
      }
    }
  }

  // Validate-and-skip a JSON value textually (no Python objects built).
  bool skip_value() {
    DepthGuard guard(value_depth);
    if (!guard.ok) return fail("value nesting too deep");
    ws();
    if (p >= end) return fail("unexpected end");
    switch (*p) {
      case '{': {
        ++p;
        ws();
        if (p < end && *p == '}') { ++p; return true; }
        while (true) {
          std::string key;
          if (!string_raw(&key)) return false;
          ws();
          if (p >= end || *p != ':') return fail("expected ':'");
          ++p;
          if (!skip_value()) return false;
          ws();
          if (p < end && *p == ',') { ++p; ws(); continue; }
          if (p < end && *p == '}') { ++p; return true; }
          return fail("expected ',' or '}'");
        }
      }
      case '[': {
        ++p;
        ws();
        if (p < end && *p == ']') { ++p; return true; }
        while (true) {
          if (!skip_value()) return false;
          ws();
          if (p < end && *p == ',') { ++p; continue; }
          if (p < end && *p == ']') { ++p; return true; }
          return fail("expected ',' or ']'");
        }
      }
      case '"': {
        std::string s;
        return string_raw(&s);
      }
      case 't':
        return lit("true");
      case 'f':
        return lit("false");
      case 'n':
        return lit("null");
      case 'N':
        return lit("NaN");
      case 'I':
        return lit("Infinity");
      default: {
        if (*p == '-' && p + 1 < end && p[1] == 'I') {
          return lit("-Infinity");
        }
        bool is_float;
        return scan_number(&is_float);
      }
    }
  }

  // ---- operations ----
  bool path_field(std::vector<int64_t>* out) {
    ws();
    if (p >= end || *p != '[') return fail("expected path list");
    ++p;
    out->clear();
    ws();
    if (p < end && *p == ']') { ++p; return true; }
    while (true) {
      int64_t v;
      if (!int64_field(&v)) return false;
      out->push_back(v);
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; return true; }
      return fail("expected ',' or ']' in path");
    }
  }

  bool emit(Columns* c, int8_t kind, int64_t ts,
            const std::vector<int64_t>& path, PyObject* val) {
    int D = c->max_depth;
    if (int(path.size()) > D) {
      return fail("path depth " + std::to_string(path.size()) +
                  " exceeds max_depth " + std::to_string(D));
    }
    // ts and path elements were domain-checked at parse (int64_field:
    // [0, MAX_TS)), so no per-element re-check here
    c->kind.push_back(kind);
    c->depth.push_back(int32_t(path.size()));
    int64_t last = path.empty() ? 0 : path.back();
    int64_t par = path.size() >= 2 ? path[path.size() - 2] : 0;
    c->parent.push_back(par);
    if (kind == 0) {  // add
      c->ts.push_back(ts);
      c->anchor.push_back(last);
      c->value_ref.push_back(int32_t(PyList_GET_SIZE(c->values)));
      if (PyList_Append(c->values, val) < 0) return false;
    } else {  // delete
      c->ts.push_back(last);
      c->anchor.push_back(last);
      c->value_ref.push_back(-1);
    }
    size_t row = c->paths.size();
    c->paths.resize(row + size_t(D), 0);
    std::memcpy(c->paths.data() + row, path.data(),
                path.size() * sizeof(int64_t));
    return true;
  }

  // One operation object; flattens batches depth-first.  Duplicate keys
  // follow JSON object semantics (last occurrence wins, matching Python's
  // json.loads): fields are collected with overwrite, the raw "ops" span
  // is remembered rather than parsed inline, and leaves are emitted only
  // after the object closes — so the final tag governs and only the final
  // "ops" list contributes.
  bool operation(Columns* c, int depth_guard) {
    if (depth_guard > 512) return fail("batch nesting too deep");
    ws();
    if (p >= end || *p != '{') return fail("expected operation object");
    ++p;
    // Every field is grammar-validated as generic JSON during the object
    // walk (matching json.loads, which parses the whole document before
    // any semantic check); "ts"/"path"/"ops" are remembered as raw spans
    // and re-parsed with the tag's SEMANTIC rules only after the object
    // closes and the final tag is known.  Unknown tags therefore tolerate
    // arbitrary field contents, exactly like the Python decoder.
    bool has_op = false, has_val = false;
    bool tag_is_string = false;
    std::string tag;
    PyObject* val = nullptr;
    const char* ts_span = nullptr, *ts_span_end = nullptr;
    const char* path_span = nullptr, *path_span_end = nullptr;
    const char* ops_span = nullptr, *ops_span_end = nullptr;
    bool ok = true;
    bool done = false;
    ws();
    if (p < end && *p == '}') { ++p; ok = fail("missing 'op' tag"); done = true; }
    while (ok && !done) {
      std::string key;
      if (!(ok = string_raw(&key))) break;
      ws();
      if (p >= end || *p != ':') { ok = fail("expected ':'"); break; }
      ++p;
      if (key == "op") {
        // a non-string tag is not an error: Python's decoder compares
        // obj["op"] against the known tags and falls through to the
        // forward-compatible empty batch, so any JSON value is admitted
        // (last occurrence wins, like every duplicate key)
        ws();
        if (p < end && *p == '"') {
          if (!(ok = string_raw(&tag))) break;
          tag_is_string = true;
        } else {
          if (!(ok = skip_value())) break;
          tag_is_string = false;
        }
        has_op = true;
      } else if (key == "ts") {
        ws();
        ts_span = p;
        if (!(ok = skip_value())) break;
        ts_span_end = p;
      } else if (key == "path") {
        ws();
        path_span = p;
        if (!(ok = skip_value())) break;
        path_span_end = p;
      } else if (key == "val") {
        Py_XDECREF(val);
        val = value_py();
        if (!val) { ok = false; break; }
        has_val = true;
      } else if (key == "ops") {
        ws();
        ops_span = p;
        if (!(ok = skip_value())) break;
        ops_span_end = p;
      } else {
        if (!(ok = skip_value())) break;
      }
      ws();
      if (p < end && *p == ',') { ++p; ws(); continue; }
      if (p < end && *p == '}') { ++p; done = true; break; }
      ok = fail("expected ',' or '}'");
      break;
    }
    if (ok) {
      if (!has_op) {
        ok = fail("missing 'op' tag");
      } else if (!tag_is_string) {
        // unknown (non-string) tag: forward-compatible no-op
      } else if (tag == "add") {
        int64_t ts = 0;
        std::vector<int64_t> path;
        if (ts_span == nullptr || path_span == nullptr || !has_val) {
          ok = fail("malformed add (need ts, path, val)");
        } else {
          ok = reparse(ts_span, ts_span_end,
                       [&] { return int64_field(&ts); }) &&
               reparse(path_span, path_span_end,
                       [&] { return path_field(&path); }) &&
               emit(c, 0, ts, path, val);
        }
      } else if (tag == "del") {
        std::vector<int64_t> path;
        if (path_span == nullptr) {
          ok = fail("malformed del (need path)");
        } else {
          ok = reparse(path_span, path_span_end,
                       [&] { return path_field(&path); }) &&
               emit(c, 1, 0, path, nullptr);
        }
      } else if (tag == "batch") {
        if (ops_span == nullptr) {
          // {"op":"batch"} without ops is malformed in the reference
          ok = fail("malformed batch (need ops)");
        } else {
          ok = reparse(ops_span, ops_span_end,
                       [&] { return ops_list(c, depth_guard); });
        }
      }
      // unknown tag: forward-compatible no-op, nothing emitted
    }
    Py_XDECREF(val);
    return ok;
  }

  // Run ``body`` against a remembered [s, e) span, restoring the cursor.
  template <typename F>
  bool reparse(const char* s, const char* e, F body) {
    const char* save_p = p;
    const char* save_end = end;
    p = s;
    end = e;
    bool ok = body();
    if (ok) {
      ws();
      if (p != end) ok = fail("trailing data in field");
    }
    p = save_p;
    end = save_end;
    return ok;
  }

  bool ops_list(Columns* c, int depth_guard) {
    ws();
    if (p >= end || *p != '[') return fail("expected ops list");
    ++p;
    ws();
    if (p < end && *p == ']') { ++p; return true; }
    while (true) {
      if (!operation(c, depth_guard + 1)) return false;
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; return true; }
      return fail("expected ',' or ']' in ops");
    }
  }
};

PyObject* bytes_from(const void* data, size_t nbytes) {
  return PyBytes_FromStringAndSize(static_cast<const char*>(data),
                                   Py_ssize_t(nbytes));
}

PyObject* parse_pack(PyObject*, PyObject* args) {
  Py_buffer buf;
  int max_depth;
  if (!PyArg_ParseTuple(args, "y*i", &buf, &max_depth)) return nullptr;
  if (max_depth <= 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "max_depth must be positive");
    return nullptr;
  }
  Columns cols;
  cols.max_depth = max_depth;
  cols.values = PyList_New(0);
  if (!cols.values) { PyBuffer_Release(&buf); return nullptr; }

  Parser parser(static_cast<const char*>(buf.buf), buf.len);
  bool ok = parser.operation(&cols, 0);
  if (ok) {
    parser.ws();
    if (parser.p != parser.end) ok = parser.fail("trailing data");
  }
  PyBuffer_Release(&buf);
  if (!ok) {
    Py_DECREF(cols.values);
    if (!PyErr_Occurred()) {
      PyErr_SetString(PyExc_ValueError, parser.err.c_str());
    }
    return nullptr;
  }

  size_t n = cols.kind.size();
  // Link hints (codec/packed.py module docstring): resolve each op's
  // timestamp references to batch positions with one hash map, so the
  // device kernel can use verified gathers instead of a sort-join.
  // First add with a given ts wins, matching the kernel's dedup.
  std::vector<int32_t> parent_pos(n, -1), anchor_pos(n, -1),
      target_pos(n, -1);
  {
    std::unordered_map<int64_t, int32_t> first;
    first.reserve(n * 2);
    for (size_t i = 0; i < n; ++i) {
      if (cols.kind[i] == 0) first.emplace(cols.ts[i], int32_t(i));
    }
    auto look = [&](int64_t t) -> int32_t {
      if (!t) return -1;
      auto it = first.find(t);
      return it == first.end() ? -1 : it->second;
    };
    for (size_t i = 0; i < n; ++i) {
      if (cols.parent[i]) parent_pos[i] = look(cols.parent[i]);
      if (cols.kind[i] == 0) {
        anchor_pos[i] = look(cols.anchor[i]);
      } else {
        target_pos[i] = look(cols.ts[i]);
      }
    }
  }
  PyObject* out = Py_BuildValue(
      "{s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:n}",
      "kind", bytes_from(cols.kind.data(), n),
      "ts", bytes_from(cols.ts.data(), n * 8),
      "parent_ts", bytes_from(cols.parent.data(), n * 8),
      "anchor_ts", bytes_from(cols.anchor.data(), n * 8),
      "depth", bytes_from(cols.depth.data(), n * 4),
      "value_ref", bytes_from(cols.value_ref.data(), n * 4),
      "paths", bytes_from(cols.paths.data(), n * size_t(max_depth) * 8),
      "parent_pos", bytes_from(parent_pos.data(), n * 4),
      "anchor_pos", bytes_from(anchor_pos.data(), n * 4),
      "target_pos", bytes_from(target_pos.data(), n * 4),
      "n", Py_ssize_t(n));
  if (!out) { Py_DECREF(cols.values); return nullptr; }
  if (PyDict_SetItemString(out, "values", cols.values) < 0) {
    Py_DECREF(cols.values);
    Py_DECREF(out);
    return nullptr;
  }
  Py_DECREF(cols.values);
  return out;
}

// ===== Egress: packed columns -> wire JSON ================================
// Mirror of the ingest direction (VERDICT r3 missing-4): the reference's
// full-state bootstrap contract is ``operationsSince 0`` serving the whole
// log (CRDTree.elm:408-418), and per-op recursive Python encode is seconds
// at headline scale.  One pass over the columns emits wire bytes that are
// byte-compatible with ``json.dumps(..., separators=(",", ":"))`` of the
// Python codec's output (ensure_ascii escapes, repr floats, insertion-order
// dicts), pinned by the differential suite in tests/test_native_codec.py.

struct Writer {
  std::string out;
  bool ok = true;
  std::string err;

  bool fail(const char* m) {
    if (ok) { err = m; ok = false; }
    return false;
  }

  void raw(const char* s) { out += s; }
  void ch(char c) { out += c; }

  void num_i64(int64_t v) {
    char buf[24];
    auto r = std::to_chars(buf, buf + sizeof buf, v);
    out.append(buf, size_t(r.ptr - buf));
  }

  void esc_unit(unsigned v) {  // \uXXXX, lowercase hex like json.dumps
    static const char* hexdig = "0123456789abcdef";
    out += "\\u";
    out += hexdig[(v >> 12) & 0xF];
    out += hexdig[(v >> 8) & 0xF];
    out += hexdig[(v >> 4) & 0xF];
    out += hexdig[v & 0xF];
  }

  // Python str -> quoted JSON, ensure_ascii=True escapes.  Encoded via
  // surrogatepass so lone surrogates admitted by the parser round-trip
  // (they re-emit as their \uD8xx escapes, exactly like json.dumps).
  bool str_py(PyObject* s) {
    if (PyUnicode_IS_ASCII(s)) {
      // common case: no bytes-object round trip, one escape-scan pass
      const char* q = reinterpret_cast<const char*>(PyUnicode_1BYTE_DATA(s));
      Py_ssize_t len = PyUnicode_GET_LENGTH(s);
      ch('"');
      Py_ssize_t run = 0;
      for (Py_ssize_t i = 0; i < len; ++i) {
        unsigned char c = (unsigned char)q[i];
        // ensure_ascii escapes DEL (0x7f) too, not just controls
        if (c >= 0x20 && c < 0x7f && c != '"' && c != '\\') {
          ++run;
          continue;
        }
        if (run) out.append(q + i - run, size_t(run));
        run = 0;
        switch (c) {
          case '"': raw("\\\""); break;
          case '\\': raw("\\\\"); break;
          case '\b': raw("\\b"); break;
          case '\f': raw("\\f"); break;
          case '\n': raw("\\n"); break;
          case '\r': raw("\\r"); break;
          case '\t': raw("\\t"); break;
          default: esc_unit(c);
        }
      }
      if (run) out.append(q + len - run, size_t(run));
      ch('"');
      return true;
    }
    PyObject* b = PyUnicode_AsEncodedString(s, "utf-8", "surrogatepass");
    if (!b) { PyErr_Clear(); return fail("unencodable string"); }
    const unsigned char* q =
        reinterpret_cast<const unsigned char*>(PyBytes_AS_STRING(b));
    const unsigned char* qe = q + PyBytes_GET_SIZE(b);
    ch('"');
    while (q < qe) {
      unsigned char c = *q;
      if (c < 0x80) {
        switch (c) {
          case '"': raw("\\\""); break;
          case '\\': raw("\\\\"); break;
          case '\b': raw("\\b"); break;
          case '\f': raw("\\f"); break;
          case '\n': raw("\\n"); break;
          case '\r': raw("\\r"); break;
          case '\t': raw("\\t"); break;
          default:
            if (c < 0x20 || c == 0x7f) esc_unit(c);
            else ch(char(c));
        }
        ++q;
      } else {
        unsigned cp;
        int extra;
        if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; extra = 1; }
        else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; extra = 2; }
        else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; extra = 3; }
        else { Py_DECREF(b); return fail("bad utf-8 from str"); }
        if (qe - q <= extra) { Py_DECREF(b); return fail("bad utf-8"); }
        ++q;
        for (int i = 0; i < extra; ++i, ++q) cp = (cp << 6) | (*q & 0x3F);
        if (cp < 0x10000) {
          esc_unit(cp);  // BMP incl. WTF-8 lone surrogates
        } else {
          cp -= 0x10000;
          esc_unit(0xD800 + (cp >> 10));
          esc_unit(0xDC00 + (cp & 0x3FF));
        }
      }
    }
    ch('"');
    Py_DECREF(b);
    return true;
  }

  bool value_py(PyObject* v, int depth) {
    if (depth > Parser::kMaxValueDepth) return fail("value nesting too deep");
    if (v == Py_None) { raw("null"); return true; }
    if (PyBool_Check(v)) {  // before PyLong: bool subclasses int
      raw(v == Py_True ? "true" : "false");
      return true;
    }
    if (PyLong_Check(v)) {
      int overflow = 0;
      long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
      if (overflow == 0 && !(x == -1 && PyErr_Occurred())) {
        num_i64(x);
        return true;
      }
      PyErr_Clear();
      PyObject* s = PyObject_Str(v);  // arbitrary-precision fallback
      if (!s) { PyErr_Clear(); return fail("int str failed"); }
      const char* u = PyUnicode_AsUTF8(s);
      if (!u) { Py_DECREF(s); PyErr_Clear(); return fail("int str failed"); }
      out += u;
      Py_DECREF(s);
      return true;
    }
    if (PyFloat_Check(v)) {
      double d = PyFloat_AS_DOUBLE(v);
      // json.dumps default allow_nan=True spellings
      if (std::isnan(d)) { raw("NaN"); return true; }
      if (std::isinf(d)) { raw(d > 0 ? "Infinity" : "-Infinity"); return true; }
      // float.__repr__'s exact spelling (shortest repr + trailing .0)
      char* s = PyOS_double_to_string(d, 'r', 0, Py_DTSF_ADD_DOT_0,
                                      nullptr);
      if (!s) { PyErr_Clear(); return fail("float repr failed"); }
      out += s;
      PyMem_Free(s);
      return true;
    }
    if (PyUnicode_Check(v)) return str_py(v);
    if (PyList_Check(v) || PyTuple_Check(v)) {
      ch('[');
      Py_ssize_t len = PySequence_Fast_GET_SIZE(v);
      PyObject** items = PySequence_Fast_ITEMS(v);
      for (Py_ssize_t i = 0; i < len; ++i) {
        if (i) ch(',');
        if (!value_py(items[i], depth + 1)) return false;
      }
      ch(']');
      return true;
    }
    if (PyDict_Check(v)) {
      ch('{');
      PyObject* k;
      PyObject* val;
      Py_ssize_t pos = 0;
      bool first = true;
      while (PyDict_Next(v, &pos, &k, &val)) {
        if (!first) ch(',');
        first = false;
        if (PyUnicode_Check(k)) {
          if (!str_py(k)) return false;
        } else if (PyBool_Check(k)) {  // json.dumps key coercions
          raw(k == Py_True ? "\"true\"" : "\"false\"");
        } else if (k == Py_None) {
          raw("\"null\"");
        } else if (PyLong_Check(k) || PyFloat_Check(k)) {
          ch('"');
          if (!value_py(k, depth + 1)) return false;
          ch('"');
        } else {
          return fail("unsupported dict key type");
        }
        ch(':');
        if (!value_py(val, depth + 1)) return false;
      }
      ch('}');
      return true;
    }
    return fail("unsupported value type");
  }
};

PyObject* encode_pack(PyObject*, PyObject* args) {
  Py_buffer kind, ts, depth, paths, value_ref;
  PyObject* values;
  Py_ssize_t start, n, width;
  if (!PyArg_ParseTuple(args, "y*y*y*y*y*O!nnn", &kind, &ts, &depth,
                        &paths, &value_ref, &PyList_Type, &values,
                        &start, &n, &width)) {
    return nullptr;
  }
  auto release = [&]() {
    PyBuffer_Release(&kind); PyBuffer_Release(&ts);
    PyBuffer_Release(&depth); PyBuffer_Release(&paths);
    PyBuffer_Release(&value_ref);
  };
  if (n < 0 || start < 0 || start > n || width <= 0 ||
      kind.len < n || ts.len < n * 8 || depth.len < n * 4 ||
      value_ref.len < n * 4 || paths.len < n * width * 8) {
    release();
    PyErr_SetString(PyExc_ValueError, "encode_pack: column size mismatch");
    return nullptr;
  }
  const int8_t* K = static_cast<const int8_t*>(kind.buf);
  const int64_t* T = static_cast<const int64_t*>(ts.buf);
  const int32_t* DP = static_cast<const int32_t*>(depth.buf);
  const int64_t* P = static_cast<const int64_t*>(paths.buf);
  const int32_t* VR = static_cast<const int32_t*>(value_ref.buf);
  Py_ssize_t n_values = PyList_GET_SIZE(values);

  Writer w;
  w.out.reserve(size_t(n - start) * 48 + 32);
  w.raw("{\"op\":\"batch\",\"ops\":[");
  bool first = true;
  for (Py_ssize_t i = start; i < n && w.ok; ++i) {
    if (K[i] == 2) continue;  // padding row
    if (!first) w.ch(',');
    first = false;
    w.raw(K[i] == 0 ? "{\"op\":\"add\",\"path\":["
                    : "{\"op\":\"del\",\"path\":[");
    int32_t d = DP[i];
    if (d > width) d = int32_t(width);
    const int64_t* row = P + size_t(i) * size_t(width);
    for (int32_t j = 0; j < d; ++j) {
      if (j) w.ch(',');
      w.num_i64(row[j]);
    }
    if (K[i] == 0) {
      w.raw("],\"ts\":");
      w.num_i64(T[i]);
      w.raw(",\"val\":");
      int32_t r = VR[i];
      PyObject* v = (r >= 0 && r < n_values)
                        ? PyList_GET_ITEM(values, r) : Py_None;
      if (!w.value_py(v, 0)) break;
      w.ch('}');
    } else {
      w.raw("]}");
    }
  }
  w.raw("]}");
  release();
  if (!w.ok) {
    PyErr_SetString(PyExc_ValueError, w.err.c_str());
    return nullptr;
  }
  return PyBytes_FromStringAndSize(w.out.data(), Py_ssize_t(w.out.size()));
}

PyMethodDef methods[] = {
    {"parse_pack", parse_pack, METH_VARARGS,
     "parse_pack(payload: bytes, max_depth: int) -> dict of packed columns"},
    {"encode_pack", encode_pack, METH_VARARGS,
     "encode_pack(kind, ts, depth, paths, value_ref, values, start, n, "
     "width) -> wire JSON bytes for ops [start, n)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastcodec",
    "Native JSON wire codec for crdt_graph_tpu", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__fastcodec(void) {
  return PyModule_Create(&moduledef);
}
