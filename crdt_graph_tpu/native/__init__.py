"""Native runtime components.

``_fastcodec`` (fastcodec.cpp) parses reference-format JSON operation
batches straight into packed numpy columns, bypassing per-op Python object
construction — the host-side ingest path for large merges.  Built on first
use with the system compiler (g++, CPython C API only — no third-party
build deps); everything falls back to the pure-Python codec when a compiler
is unavailable, so the native layer is an accelerator, never a requirement.
"""
from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
from typing import Optional

import numpy as np

from ..codec.packed import (DEFAULT_MAX_DEPTH, PackedOps, _bucket,
                            _depth_bucket)

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "fastcodec.cpp")
_SO = os.path.join(_HERE, "_fastcodec.so")

_mod = None
_build_error: Optional[str] = None


def _try_import():
    """Load the extension by file path — no sys.path mutation."""
    global _mod
    spec = importlib.util.spec_from_file_location(
        "crdt_graph_tpu.native._fastcodec", _SO)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _mod = mod
    return _mod


def _build() -> None:
    include = sysconfig.get_path("include")
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           f"-I{include}", _SRC, "-o", _SO]
    if os.environ.get("GRAFT_NATIVE_ASAN"):
        # memory-safety fuzz build (scripts/fuzz_native.py re-execs with
        # libasan LD_PRELOADed so the sanitized .so loads into CPython)
        cmd[1:1] = ["-fsanitize=address", "-fno-omit-frame-pointer", "-g"]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def load(rebuild: bool = False):
    """The native module, building it if needed; None if unavailable."""
    global _mod, _build_error
    if _mod is not None and not rebuild:
        return _mod
    if _build_error is not None and not rebuild:
        return None
    try:
        if rebuild or not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            _build()
        return _try_import()
    except Exception as e:   # missing compiler, sandboxed fs, …
        _build_error = str(e)
        return None


def available() -> bool:
    return load() is not None


def parse_pack(payload, max_depth: int = DEFAULT_MAX_DEPTH,
               capacity: Optional[int] = None) -> PackedOps:
    """Wire JSON (str/bytes) → :class:`PackedOps` via the native parser.

    Raises ``RuntimeError`` when the native module is unavailable — callers
    wanting transparent fallback should use
    :func:`crdt_graph_tpu.codec.packed.pack_json`.
    """
    mod = load()
    if mod is None:
        raise RuntimeError(f"native codec unavailable: {_build_error}")
    if isinstance(payload, str):
        payload = payload.encode()
    cols = mod.parse_pack(payload, max_depth)
    n = cols["n"]
    cap = capacity if capacity is not None else _bucket(n)
    if cap < n:
        raise ValueError(f"capacity {cap} < op count {n}")

    def col(name, dtype, shape=None):
        arr = np.frombuffer(cols[name], dtype=dtype)
        if shape is not None:
            arr = arr.reshape(shape)
        return arr

    kind = np.full(cap, 2, dtype=np.int8)           # KIND_PAD
    kind[:n] = col("kind", np.int8)
    # shrink the path plane to the batch's depth bucket, matching
    # packed.pack (the kernel specialises per width; flat logs get [N,1])
    depth_col = col("depth", np.int32)
    width = _depth_bucket(int(depth_col.max(initial=1)), max_depth)
    out = PackedOps(
        kind=kind,
        ts=_padded(col("ts", np.int64), cap),
        parent_ts=_padded(col("parent_ts", np.int64), cap),
        anchor_ts=_padded(col("anchor_ts", np.int64), cap),
        depth=_padded(depth_col, cap),
        paths=_padded2(
            col("paths", np.int64, (n, max_depth))[:, :width].copy(), cap),
        value_ref=_padded(col("value_ref", np.int32), cap, fill=-1),
        pos=np.arange(cap, dtype=np.int32),
        values=cols["values"],
        num_ops=n,
        parent_pos=_padded(col("parent_pos", np.int32), cap, fill=-1),
        anchor_pos=_padded(col("anchor_pos", np.int32), cap, fill=-1),
        target_pos=_padded(col("target_pos", np.int32), cap, fill=-1),
        hints_vouched=True)   # the C++ parser resolves every in-batch ref
    # Foreign-provenance audit (VERDICT r4 next-7), DEFAULT-ON: wire
    # bytes come from outside this process, so the vouch above is only
    # as good as the C++ hint resolution — re-verify on host before the
    # batch can reach the kernel's cond-free exhaustive mode, and
    # REBUILD (not demote) on failure so a parser bug costs speed and a
    # loud repair, never a silent mis-resolution.  One vectorized pass
    # (~1.5% of the 1M-op ingest merge).  Same-process pack/concat
    # products keep the zero-cost vouch; GRAFT_DEBUG_VOUCH remains the
    # suite-wide tripwire for those.
    from ..codec.packed import rebuild_hints, verify_hints
    # check_rank=False: ts_rank was computed in-process by __post_init__
    # from these very columns; only the C++ link-hint columns are foreign
    if not verify_hints(out, check_rank=False):
        import logging
        logging.getLogger(__name__).warning(
            "native parse_pack produced hint columns that failed the "
            "host audit; rebuilt (parser bug — please report)")
        rebuild_hints(out)
    return out


def encode_pack(p: PackedOps, start: int = 0) -> bytes:
    """:class:`PackedOps` columns → wire JSON bytes via the native
    encoder — the egress mirror of :func:`parse_pack` (one C++ pass, no
    per-op Python objects).  Emits ``{"op":"batch","ops":[...]}`` for
    rows ``[start, num_ops)``, byte-compatible with
    ``json_codec.dumps`` of the same ops.

    Raises ``RuntimeError`` when the native module is unavailable —
    callers wanting transparent fallback use
    :meth:`engine.TpuTree.dumps_since`.
    """
    mod = load()
    if mod is None:
        raise RuntimeError(f"native codec unavailable: {_build_error}")
    n = p.num_ops
    # slice to the requested suffix so a small delta pull costs O(delta),
    # not O(document) (suffix slices of contiguous columns stay views —
    # no copies); values is passed whole (value_ref indexes it) and
    # borrowed, never copied
    return mod.encode_pack(
        np.ascontiguousarray(p.kind[start:n], dtype=np.int8),
        np.ascontiguousarray(p.ts[start:n], dtype=np.int64),
        np.ascontiguousarray(p.depth[start:n], dtype=np.int32),
        np.ascontiguousarray(p.paths[start:n], dtype=np.int64),
        np.ascontiguousarray(p.value_ref[start:n], dtype=np.int32),
        p.values, 0, n - start, p.paths.shape[1])


def _padded(a: np.ndarray, cap: int, fill=0) -> np.ndarray:
    out = np.full(cap, fill, dtype=a.dtype)
    out[:a.shape[0]] = a
    return out


def _padded2(a: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros((cap, a.shape[1]), dtype=a.dtype)
    out[:a.shape[0]] = a
    return out
