"""The fleet's coordination key-value store: a tiny versioned-CAS
surface that leases, membership, and replica-id counters are built on.

The contract is deliberately minimal — ``get`` returns ``(value,
version)``, ``cas`` writes iff the caller's expected version still
holds (0 = create-only), ``delete`` is CAS-guarded too, ``keys`` lists
a prefix — because that is exactly the subset every real coordination
service offers (etcd/zookeeper/consul transactions; the jax
coordination-service KV that ``parallel.distributed.allgather_scalars``
already rides covers the publish-only half).  Two implementations ship:

- :class:`MemoryKV` — one process, many threads (the in-process fleet
  tests and the tier-1 chaos variant share one instance);
- :class:`FileKV` — many processes, one host: one file per key under a
  spool directory, writes atomic via tmp+rename, CAS linearized by an
  ``flock`` on a single lock file (released by the kernel when a
  process dies, so a crashed node can never wedge the store — the
  crash-safety the chaos soak leans on).

A pod deployment swaps in an etcd-backed implementation of the same
five methods; nothing above this module knows the difference
(docs/CLUSTER.md §Membership).
"""
from __future__ import annotations

import fcntl
import os
import threading
from typing import Dict, List, Optional, Tuple


class KVError(Exception):
    """The store itself failed (I/O, lock acquisition) — distinct from
    a CAS miss, which is an ordinary ``False`` return."""


class MemoryKV:
    """In-process store: a dict guarded by one lock, versions counted
    per key from 1."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, Tuple[str, int]] = {}

    def get(self, key: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._data.get(key)

    def cas(self, key: str, value: str, expected_version: int) -> bool:
        """Write ``value`` iff the key's current version is
        ``expected_version`` (0 = the key must not exist).  Returns
        whether the write happened."""
        with self._lock:
            cur = self._data.get(key)
            if (cur[1] if cur else 0) != expected_version:
                return False
            self._data[key] = (value, expected_version + 1)
            return True

    def delete(self, key: str, expected_version: int) -> bool:
        with self._lock:
            cur = self._data.get(key)
            if cur is None or cur[1] != expected_version:
                return False
            del self._data[key]
            return True

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))


class FileKV:
    """One-host multi-process store over a spool directory.

    Layout: key → ``<dir>/<quoted-key>`` holding ``"<version>\\n<value>"``.
    Reads are lock-free (rename is atomic, so a read sees one complete
    generation or the previous one); all writes serialize on an
    ``flock``-ed ``.lock`` file so read-modify-write CAS is atomic
    across processes AND threads (each operation opens its own fd —
    flock exclusion is per-open-file, not per-process)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock_path = os.path.join(root, ".lock")

    def _path(self, key: str) -> str:
        # keys are path-like ("lease/3"); flatten to one spool level so
        # listing stays a single readdir
        quoted = key.replace("%", "%25").replace("/", "%2F")
        return os.path.join(self.root, quoted)

    @staticmethod
    def _unquote(name: str) -> str:
        return name.replace("%2F", "/").replace("%25", "%")

    def _read(self, path: str) -> Optional[Tuple[str, int]]:
        try:
            with open(path, "r") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        head, _, value = raw.partition("\n")
        try:
            return value, int(head)
        except ValueError:
            return None   # torn legacy write; treated as absent

    def _locked(self):
        f = open(self._lock_path, "a+")
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        except OSError as e:
            f.close()
            raise KVError(f"flock({self._lock_path}): {e}") from e
        return f

    def get(self, key: str) -> Optional[Tuple[str, int]]:
        return self._read(self._path(key))

    def cas(self, key: str, value: str, expected_version: int) -> bool:
        path = self._path(key)
        lock = self._locked()
        try:
            cur = self._read(path)
            if (cur[1] if cur else 0) != expected_version:
                return False
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{expected_version + 1}\n{value}")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return True
        finally:
            fcntl.flock(lock.fileno(), fcntl.LOCK_UN)
            lock.close()

    def delete(self, key: str, expected_version: int) -> bool:
        path = self._path(key)
        lock = self._locked()
        try:
            cur = self._read(path)
            if cur is None or cur[1] != expected_version:
                return False
            os.unlink(path)
            return True
        finally:
            fcntl.flock(lock.fileno(), fcntl.LOCK_UN)
            lock.close()

    def keys(self, prefix: str = "") -> List[str]:
        out = []
        for name in os.listdir(self.root):
            if name == ".lock" or name.endswith(".tmp"):
                continue
            key = self._unquote(name)
            if key.startswith(prefix):
                out.append(key)
        return sorted(out)


def next_counter(kv, key: str, retries: int = 64) -> int:
    """Atomically increment a KV-backed counter and return its new
    value (fleet-unique CLIENT replica ids: ``POST /docs/{id}/replicas``
    on any server allocates from ``replica/{doc}``, so ids survive
    primary failover without collisions — a local per-document counter
    would restart at 1 on the new primary and hand out timestamps that
    collide with the old primary's grants)."""
    for _ in range(retries):
        cur = kv.get(key)
        value, version = (int(cur[0]), cur[1]) if cur else (0, 0)
        if kv.cas(key, str(value + 1), version):
            return value + 1
    raise KVError(f"counter {key!r}: CAS contention past {retries} "
                  "retries")
