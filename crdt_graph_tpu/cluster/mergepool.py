"""Merge-worker registration over the coordination KV
(docs/MERGETIER.md §Topology).

Merge workers are a POOL, not ring members: they hold no documents, so
they register under the ring-independent ``mergeworker/`` prefix —
the consistent-hash ring (cluster/ring.py) is derived from ``lease/``
slots only and never sees them.  The record shape mirrors a lease
(name + advertised addr + wall-clock expiry, TTL-renewed), so the
same expiry rule applies: a worker that stops renewing is out of
every front-end's pool within one TTL with no extra protocol.  No
fencing token — workers are stateless per request, so two
incarnations under one name can only duplicate work, never corrupt a
commit (the front-end's input-digest check binds each response to its
request regardless of which incarnation answered).

Front-ends list the pool with :func:`list_workers` and hand the
addresses to :class:`~crdt_graph_tpu.mergetier.client.MergeTierClient`
(which layers breakers on top: registration says "intended alive",
the breaker says "actually answering").
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

PREFIX = "mergeworker/"
DEFAULT_TTL_S = 5.0


def register(kv, name: str, addr: str, ttl_s: float = DEFAULT_TTL_S,
             clock: Callable[[], float] = time.time,
             retries: int = 16) -> None:
    """Claim (or refresh) ``mergeworker/<name>``.  CAS-retried: the
    only contender for a name's key is its own previous incarnation,
    so a handful of attempts always lands."""
    from .kv import KVError
    key = f"{PREFIX}{name}"
    for _ in range(retries):
        got = kv.get(key)
        version = got[1] if got is not None else 0
        record = json.dumps({"name": name, "addr": addr,
                             "expires": clock() + ttl_s},
                            sort_keys=True)
        if kv.cas(key, record, version):
            return
    raise KVError(f"could not register merge worker {name!r}")


def deregister(kv, name: str) -> None:
    """Best-effort removal (clean shutdown); a crashed worker just
    ages out at its TTL."""
    key = f"{PREFIX}{name}"
    got = kv.get(key)
    if got is not None:
        kv.delete(key, got[1])


def list_workers(kv, clock: Callable[[], float] = time.time
                 ) -> List[Dict]:
    """Unexpired worker records, name-sorted (deterministic pool
    order across front-ends)."""
    out = []
    for key in kv.keys(PREFIX):
        got = kv.get(key)
        if got is None:
            continue
        try:
            rec = json.loads(got[0])
        except ValueError:
            continue
        if rec.get("expires", 0) > clock():
            out.append(rec)
    return sorted(out, key=lambda r: r.get("name", ""))


class MergePoolKeeper:
    """TTL renewal loop for one worker's registration — the
    ``LeaseKeeper`` shape (renew every ``ttl/3``), minus fencing."""

    def __init__(self, kv, name: str, addr: str,
                 ttl_s: float = DEFAULT_TTL_S):
        self.kv = kv
        self.name = name
        self.addr = addr
        self.ttl_s = float(ttl_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        register(kv, name, addr, ttl_s)

    def start(self) -> "MergePoolKeeper":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        from .kv import KVError
        while not self._stop.wait(self.ttl_s / 3.0):
            try:
                register(self.kv, self.name, self.addr, self.ttl_s)
            except KVError:
                # transient KV contention: the record survives until
                # its TTL, so the next beat retries with time to spare
                continue

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10)
        deregister(self.kv, self.name)
