"""Deterministic network fault injection for every inter-node HTTP
client path (docs/CLUSTER.md §Partitions & staleness).

The fleet's distribution contract is pure anti-entropy — it only
converges if it survives the network actually misbehaving.  This module
makes the network misbehave ON PURPOSE, reproducibly: a
:class:`NetChaos` instance wraps every outbound fleet connection
(anti-entropy pulls, write forwarding, scrub peer-repair fetches, and
the loadgen fleet clients when armed) in a :class:`ChaosHTTPConnection`
that consults a seeded per-link decision stream before and after each
request.  Same seed + same spec + same per-link request sequence ⇒ the
exact same faults, so a partition test is a replayable artifact, not a
flake — chaos tests print ``describe()`` so any red run replays
verbatim.

Faults (all optional, combined freely):

- **drop** — the request never reaches the peer (``ConnectionRefused``
  before any bytes move: the same shape a dead/unroutable peer has);
- **delay** — seeded latency before the request is sent;
- **throttle** — response bandwidth cap (the body "arrives" at N
  bytes/s: a sleep proportional to its size before ``read`` returns);
- **cut** — the peer dies mid-response: ``read()`` delivers a prefix
  and raises :class:`http.client.IncompleteRead` (an HTTPException,
  NOT an OSError — exactly the class the fleet paths must already
  catch, docs/CLUSTER.md §Failure matrix);
- **dup** — duplicate/reordered delivery: the link re-serves the
  PREVIOUS response for the same endpoint instead of the fresh one
  (an anti-entropy puller then applies an older window again and its
  mark regresses — the CRDT absorbs both, which is the point);
- **partitions** — full, asymmetric, and flapping link cuts, driven
  either by spec clauses over the per-link request index (replayable
  schedules) or programmatically (:meth:`NetChaos.block` /
  :meth:`heal` — the deterministic tier-1 matrix drives these).

Spec grammar (``GRAFT_NETCHAOS="<seed>:<clause>[;<clause>...]"``)::

    drop=P                 drop a request with probability P
    delay=LO-HI@P          sleep LO..HI ms with probability P
    throttle=BPS           response bandwidth cap, bytes/second
    cut=P                  cut a response mid-body with probability P
    dup=P                  re-deliver the link's previous response
    part=A|B@LO-HI         symmetric partition between groups A and B
                           for link request indexes [LO, HI)
    oneway=A>B@LO-HI       asymmetric: only A→B requests blocked
    flap=A|B@PERIOD/DUTY   flapping partition: blocked while
                           (link request index % PERIOD) < DUTY

Groups are ``+``-joined node names or ``*`` (any).  Example: a fleet
where ``n2`` is cut off for its first 40 cross-link requests, over a
generally lossy slow network::

    GRAFT_NETCHAOS="7:drop=0.05;delay=5-40@0.5;part=n2|*@0-40"

Schedules are indexed by the per-link REQUEST COUNTER, not wall time,
so replays do not depend on thread timing.  Every decision draws from
a per-link ``random.Random(f"{seed}|{src}>{dst}")`` stream.

Pooled connections (cluster/pool.py; ISSUE 15): fleet clients now
keep-alive and POOL their connections, created through :func:`connect`
— the decision stream is consulted per WIRE REQUEST (``request()``
calls ``decide``), so a long-lived pooled connection draws exactly the
same per-link fault sequence per-request connections did, and the pool
poisons (evicts) exactly the connection a cut/drop fired on.
``getresponse`` fully buffers the real response before faulting at
``read()``, so an injected cut never leaves stranded bytes that would
corrupt the NEXT request on a reused connection.  Replay caveat: the
counter indexes wire requests, so anything that RE-SENDS — a client
429/503 retry loop, or the pool's one stale-reuse retry after a peer
closed an idle connection — consumes an additional decision, exactly
as it did pre-pooling; deterministic tier-1 matrices drive
programmatic ``block``/``heal`` (counter-independent) or in-process
fleets whose servers never idle-close, so their schedules replay
verbatim.
"""
from __future__ import annotations

import os
import random
import re
import threading
import time
from http.client import HTTPConnection, IncompleteRead
from typing import Dict, FrozenSet, List, Optional, Tuple

# bodies above this are not cached for dup re-delivery (the cache holds
# at most one response per (link, endpoint) — this bounds it further)
_DUP_CACHE_MAX_BODY = 1 << 20
# throttle sleeps are capped so a tiny configured bandwidth cannot
# wedge a test harness past its own timeouts
_THROTTLE_SLEEP_CAP_S = 5.0

_CLAUSE_RE = re.compile(r"^(\w+)=(.*)$")
_PART_RE = re.compile(r"^([^|>@]+)([|>])([^@]+)@(\d+)-(\d+)$")
_FLAP_RE = re.compile(r"^([^|@]+)\|([^@]+)@(\d+)/(\d+)$")


class NetChaosSpecError(ValueError):
    """The ``GRAFT_NETCHAOS`` spec string failed to parse (the error
    message carries the offending clause; the grammar lives in the
    module docstring and docs/CLUSTER.md)."""


def _group(text: str) -> FrozenSet[str]:
    names = frozenset(n for n in text.split("+") if n)
    if not names:
        raise NetChaosSpecError(f"empty node group in {text!r}")
    return names


def _in_group(name: str, group: FrozenSet[str]) -> bool:
    return "*" in group or name in group


class _Partition:
    """One scheduled link cut: symmetric or one-way, active for link
    request indexes [lo, hi) — or flapping with (period, duty)."""

    __slots__ = ("a", "b", "oneway", "lo", "hi", "period", "duty")

    def __init__(self, a, b, oneway=False, lo=0, hi=1 << 62,
                 period=0, duty=0):
        self.a, self.b, self.oneway = a, b, oneway
        self.lo, self.hi = lo, hi
        self.period, self.duty = period, duty

    def crosses(self, src: str, dst: str) -> bool:
        if _in_group(src, self.a) and _in_group(dst, self.b):
            return True
        if not self.oneway and _in_group(src, self.b) \
                and _in_group(dst, self.a):
            return True
        return False

    def active(self, idx: int) -> bool:
        if self.period:
            return idx % self.period < self.duty
        return self.lo <= idx < self.hi


class _LinkState:
    __slots__ = ("rng", "n", "last_resp")

    def __init__(self, seed: int, src: str, dst: str):
        self.rng = random.Random(f"{seed}|{src}>{dst}")
        self.n = 0                       # request index on this link
        # (endpoint) -> (status, reason, headers, body) — the dup
        # fault's re-delivery source; at most one entry per endpoint
        self.last_resp: Dict[str, tuple] = {}


class _Plan:
    """Per-request fault decisions, drawn at request() time."""

    __slots__ = ("delay_s", "throttle_bps", "cut", "dup")

    def __init__(self):
        self.delay_s = 0.0
        self.throttle_bps = 0
        self.cut = False
        self.dup = False


class NetChaos:
    """One fleet's fault plan: parsed spec clauses + programmatic
    partitions + per-link seeded decision streams + fired counters
    (the ``crdt_netchaos_*`` prom families)."""

    def __init__(self, seed: int = 0, spec: str = ""):
        self.seed = int(seed)
        self.spec = spec or ""
        self.drop_p = 0.0
        self.delay: Optional[Tuple[float, float, float]] = None
        self.throttle_bps = 0
        self.cut_p = 0.0
        self.dup_p = 0.0
        self.partitions: List[_Partition] = []
        self._mu = threading.Lock()
        self._links: Dict[Tuple[str, str], _LinkState] = {}
        # programmatic partitions (the deterministic tier-1 matrix):
        # (src, dst) pairs blocked RIGHT NOW, direction-sensitive
        self._blocked: set = set()
        self.counters: Dict[str, int] = {
            "requests": 0, "drops": 0, "delays": 0, "throttles": 0,
            "cuts": 0, "dups": 0, "partition_blocks": 0,
        }
        for clause in filter(None,
                             (c.strip() for c in self.spec.split(";"))):
            self._parse_clause(clause)

    # -- construction ------------------------------------------------------

    @classmethod
    def parse(cls, raw: str) -> "NetChaos":
        """``"<seed>:<spec>"`` (or just ``"<seed>"``) → an instance.
        Raises :class:`NetChaosSpecError` with the grammar hint on any
        malformed value — the one parser behind ``GRAFT_NETCHAOS``
        and the ``--netchaos`` flag."""
        seed, _, spec = raw.strip().partition(":")
        try:
            return cls(int(seed), spec)
        except ValueError as e:
            raise NetChaosSpecError(
                f"{raw!r}: {e} (grammar: "
                f"'<seed>:drop=P;delay=LO-HI@P;throttle=BPS;cut=P;"
                f"dup=P;part=A|B@LO-HI;oneway=A>B@LO-HI;"
                f"flap=A|B@PERIOD/DUTY')") from e

    @classmethod
    def from_env(cls, var: str = "GRAFT_NETCHAOS"
                 ) -> Optional["NetChaos"]:
        """The env entry: an instance from ``GRAFT_NETCHAOS``, or None
        when unset — the multi-process soak's way of arming one
        identical plan in every node process."""
        raw = os.environ.get(var, "").strip()
        if not raw:
            return None
        return cls.parse(raw)

    def _parse_clause(self, clause: str) -> None:
        m = _CLAUSE_RE.match(clause)
        if not m:
            raise NetChaosSpecError(f"unparseable clause {clause!r}")
        key, val = m.group(1), m.group(2)
        try:
            if key == "drop":
                self.drop_p = float(val)
            elif key == "delay":
                rng_part, _, p = val.partition("@")
                rng_part = rng_part.removesuffix("ms")
                lo, _, hi = rng_part.partition("-")
                lo_ms = float(lo)
                hi_ms = float(hi) if hi else lo_ms
                self.delay = (lo_ms / 1e3, hi_ms / 1e3,
                              float(p) if p else 1.0)
            elif key == "throttle":
                self.throttle_bps = int(float(val))
            elif key == "cut":
                self.cut_p = float(val)
            elif key == "dup":
                self.dup_p = float(val)
            elif key in ("part", "oneway"):
                pm = _PART_RE.match(val)
                if not pm:
                    raise ValueError(f"want A|B@LO-HI, got {val!r}")
                a, sep, b, lo, hi = pm.groups()
                oneway = key == "oneway" or sep == ">"
                self.partitions.append(_Partition(
                    _group(a), _group(b), oneway=oneway,
                    lo=int(lo), hi=int(hi)))
            elif key == "flap":
                fm = _FLAP_RE.match(val)
                if not fm:
                    raise ValueError(f"want A|B@PERIOD/DUTY, got {val!r}")
                a, b, period, duty = fm.groups()
                if int(period) <= 0 or not 0 < int(duty) <= int(period):
                    raise ValueError(
                        f"flap needs 0 < DUTY <= PERIOD, got {val!r}")
                self.partitions.append(_Partition(
                    _group(a), _group(b),
                    period=int(period), duty=int(duty)))
            else:
                raise ValueError(f"unknown fault kind {key!r}")
        except (ValueError, TypeError) as e:
            if isinstance(e, NetChaosSpecError):
                raise
            raise NetChaosSpecError(
                f"clause {clause!r}: {e}") from e

    def describe(self) -> str:
        """The replay line chaos tests print on failure: rebuilding a
        ``NetChaos(seed, spec)`` from it reproduces every decision."""
        return f"GRAFT_NETCHAOS={self.seed}:{self.spec}"

    # -- programmatic partitions (deterministic tier-1 matrices) -----------

    def block(self, src: str, dst: str, oneway: bool = False) -> None:
        """Cut the link ``src → dst`` now (and ``dst → src`` unless
        ``oneway``) until :meth:`unblock`/:meth:`heal`."""
        with self._mu:
            self._blocked.add((src, dst))
            if not oneway:
                self._blocked.add((dst, src))

    def block_groups(self, a, b, oneway: bool = False) -> None:
        """Cut every link between node groups ``a`` and ``b``."""
        for s in a:
            for d in b:
                self.block(s, d, oneway=oneway)

    def unblock(self, src: str, dst: str) -> None:
        with self._mu:
            self._blocked.discard((src, dst))
            self._blocked.discard((dst, src))

    def heal(self) -> None:
        """Lift every programmatic partition (spec-scheduled clauses
        keep their own [lo, hi) windows)."""
        with self._mu:
            self._blocked.clear()

    # -- the per-request decision ------------------------------------------

    def _link(self, src: str, dst: str) -> _LinkState:
        key = (src, dst)
        st = self._links.get(key)
        if st is None:
            st = self._links[key] = _LinkState(self.seed, src, dst)
        return st

    def decide(self, src: str, dst: str) -> _Plan:
        """Draw this request's fate.  Raises ``ConnectionRefusedError``
        for drops and partition blocks (the caller's existing
        peer-failure handling must treat chaos exactly like a dead
        peer — that is the test).  Sleeps the delay before returning
        so the caller's ``request()`` sees it as network latency."""
        with self._mu:
            link = self._link(src, dst)
            idx = link.n
            link.n += 1
            self.counters["requests"] += 1
            blocked = (src, dst) in self._blocked or any(
                p.crosses(src, dst) and p.active(idx)
                for p in self.partitions)
            plan = _Plan()
            delay_s = 0.0
            if blocked:
                self.counters["partition_blocks"] += 1
            else:
                rng = link.rng
                if self.drop_p and rng.random() < self.drop_p:
                    self.counters["drops"] += 1
                    blocked = True
                else:
                    if self.delay is not None:
                        lo, hi, p = self.delay
                        if rng.random() < p:
                            delay_s = rng.uniform(lo, hi)
                            self.counters["delays"] += 1
                    if self.cut_p and rng.random() < self.cut_p:
                        plan.cut = True
                        self.counters["cuts"] += 1
                    if self.dup_p and rng.random() < self.dup_p:
                        plan.dup = True
                    plan.throttle_bps = self.throttle_bps
                    if self.throttle_bps:
                        self.counters["throttles"] += 1
        if blocked:
            raise ConnectionRefusedError(
                f"netchaos: link {src}->{dst} blocked "
                f"(request #{idx}; {self.describe()})")
        if delay_s > 0.0:
            time.sleep(delay_s)
        plan.delay_s = delay_s
        return plan

    # -- dup cache ---------------------------------------------------------

    def stale_response(self, src: str, dst: str, endpoint: str,
                       fresh: tuple) -> tuple:
        """Dup fault: remember ``fresh`` and return the link's PREVIOUS
        response for the same endpoint (or ``fresh`` itself when none
        is cached yet).  The fresh response is always what the NEXT
        delivery sees — a genuine reordering, never a fabrication."""
        with self._mu:
            link = self._link(src, dst)
            prev = link.last_resp.get(endpoint)
            if len(fresh[3]) <= _DUP_CACHE_MAX_BODY:
                link.last_resp[endpoint] = fresh
            if prev is None:
                return fresh
            self.counters["dups"] += 1
            return prev

    def remember_response(self, src: str, dst: str, endpoint: str,
                          resp: tuple) -> None:
        if self.dup_p <= 0.0 or len(resp[3]) > _DUP_CACHE_MAX_BODY:
            return
        with self._mu:
            self._link(src, dst).last_resp[endpoint] = resp

    # -- exposition --------------------------------------------------------

    def stats(self) -> Dict:
        with self._mu:
            return {
                "seed": self.seed,
                "spec": self.spec,
                "links": len(self._links),
                "blocked_links": len(self._blocked),
                "counters": dict(self.counters),
            }


class _ChaosResponse:
    """A fully buffered response standing in for ``HTTPResponse``:
    ``status``/``reason``/``read``/``getheader``/``getheaders`` — the
    surface every fleet client path consumes.  Throttle and cut faults
    fire at ``read()`` time (the body is where the bytes are)."""

    def __init__(self, status: int, reason: str,
                 headers: List[Tuple[str, str]], body: bytes,
                 plan: _Plan):
        self.status = status
        self.reason = reason
        self._headers = headers
        self._body = body
        self._plan = plan
        self._consumed = False

    def read(self, amt: Optional[int] = None) -> bytes:
        if self._consumed:
            return b""
        self._consumed = True
        plan = self._plan
        if plan.throttle_bps > 0 and self._body:
            time.sleep(min(_THROTTLE_SLEEP_CAP_S,
                           len(self._body) / plan.throttle_bps))
        if plan.cut:
            # the peer died mid-body: deliver a prefix, then the same
            # exception a real half-closed socket raises
            raise IncompleteRead(self._body[:len(self._body) // 2])
        return self._body

    def getheader(self, name: str, default=None):
        low = name.lower()
        for k, v in self._headers:
            if k.lower() == low:
                return v
        return default

    def getheaders(self) -> List[Tuple[str, str]]:
        return list(self._headers)


class ChaosHTTPConnection(HTTPConnection):
    """An ``HTTPConnection`` whose requests pass through a
    :class:`NetChaos` decision stream.  Drop-in: ``request`` may raise
    ``ConnectionRefusedError`` (drop/partition), ``getresponse`` returns
    a :class:`_ChaosResponse` whose ``read`` may raise
    ``IncompleteRead`` (cut) — both failure classes the fleet client
    paths already handle for REAL network failures."""

    def __init__(self, chaos: NetChaos, src: str, dst: str,
                 host: str, port: int, timeout: float):
        super().__init__(host, port, timeout=timeout)
        self._chaos = chaos
        self._src = src
        self._dst = dst
        self._plan: Optional[_Plan] = None
        self._endpoint = ""

    def request(self, method, url, body=None, headers=None, **kw):
        # the decision (and any injected latency/refusal) happens
        # BEFORE bytes move, like the network it models
        self._plan = self._chaos.decide(self._src, self._dst)
        self._endpoint = f"{method} {url.partition('?')[0]}"
        super().request(method, url, body=body,
                        headers=headers or {}, **kw)

    def getresponse(self):
        plan = self._plan or _Plan()
        self._plan = None
        real = super().getresponse()
        fresh = (real.status, real.reason, real.getheaders(),
                 real.read())
        if plan.dup:
            status, reason, headers, data = self._chaos.stale_response(
                self._src, self._dst, self._endpoint, fresh)
        else:
            self._chaos.remember_response(self._src, self._dst,
                                          self._endpoint, fresh)
            status, reason, headers, data = fresh
        return _ChaosResponse(status, reason, headers, data, plan)


# -- module-level env instance (multi-process soaks) -----------------------

_env_chaos: Optional[NetChaos] = None
_env_read = False
_env_mu = threading.Lock()


def env_chaos() -> Optional[NetChaos]:
    """The process-wide ``GRAFT_NETCHAOS`` instance (parsed once,
    lazily) — what :func:`connect` falls back to when the caller has
    no explicitly armed plan."""
    global _env_chaos, _env_read
    with _env_mu:
        if not _env_read:
            _env_chaos = NetChaos.from_env()
            _env_read = True
        return _env_chaos


def reset_env_chaos() -> None:
    """Forget the cached env instance (tests that mutate
    ``GRAFT_NETCHAOS`` between cases)."""
    global _env_chaos, _env_read
    with _env_mu:
        _env_chaos = None
        _env_read = False


def connect(chaos: Optional[NetChaos], src: str, dst: str,
            host: str, port: int, timeout: float) -> HTTPConnection:
    """The fleet's one connection factory: a plain ``HTTPConnection``
    when no chaos plan is armed (explicitly or via the env), a
    :class:`ChaosHTTPConnection` otherwise.  ``src``/``dst`` are the
    logical link endpoints (node names; loadgen clients use their
    session/client names) the spec's partition groups match on."""
    if chaos is None:
        chaos = env_chaos()
    if chaos is None:
        return HTTPConnection(host, int(port), timeout=timeout)
    return ChaosHTTPConnection(chaos, src, dst, host, int(port),
                               timeout=timeout)
