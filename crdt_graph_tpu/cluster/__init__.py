"""Replica fleet: consistent-hash routing, replica-id leases, and
background anti-entropy between serving processes (docs/CLUSTER.md).

The reference delegates distribution to "a coordinating server that
assigns replica ids" plus an ``operationsSince`` anti-entropy contract
(PAPER.md survey §1); this package is that coordinator made real, as a
fleet of peers instead of a privileged process:

- :mod:`~crdt_graph_tpu.cluster.kv` — the small coordination
  key-value store everything else is built on (in-process for tests,
  file-backed for localhost fleets, adapter-ready for etcd/the jax
  coordination service in a pod);
- :mod:`~crdt_graph_tpu.cluster.ring` — consistent-hash doc→server
  routing over the live membership, with deterministic rebalancing;
- :mod:`~crdt_graph_tpu.cluster.lease` — TTL replica-id leases with
  fencing tokens and crash-safe re-acquisition (membership IS the
  lease table);
- :mod:`~crdt_graph_tpu.cluster.antientropy` — the background sync
  daemon: peers exchange packed ``operationsSince`` windows with
  per-peer high-water marks, delta caps, and backoff + jitter;
- :mod:`~crdt_graph_tpu.cluster.gateway` — the store the HTTP layer
  serves: any server accepts any request, writes forward to the doc's
  primary, reads serve the LOCAL replica snapshot with honest
  ``X-Replica-*`` / ``X-State-Fingerprint`` / ``X-Ae-Lag-Seconds``
  headers;
- :mod:`~crdt_graph_tpu.cluster.netchaos` — deterministic network
  fault injection for every inter-node client path: seeded drop /
  delay / throttle / cut / dup faults and scheduled partition
  matrices (``GRAFT_NETCHAOS``), so a partition test is a replayable
  artifact;
- :mod:`~crdt_graph_tpu.cluster.pool` — persistent keep-alive
  connection pooling for every one of those client paths, threaded
  through the ``netchaos.connect`` factory so chaos bites pooled
  traffic exactly as it bit per-request connections (a cut poisons
  exactly the pooled connection it hit).

Run one node: ``python -m crdt_graph_tpu.cluster --name n0
--kv-dir /tmp/fleet --port 8931``.
"""
from .antientropy import AntiEntropy
from .gateway import ClusterNode, FleetServer, ForwardError
from .kv import FileKV, MemoryKV
from .lease import Lease, LeaseError, LeaseLost, LeaseService
from .netchaos import ChaosHTTPConnection, NetChaos, NetChaosSpecError
from .pool import ConnectionPool
from .ring import HashRing

__all__ = ["AntiEntropy", "ChaosHTTPConnection", "ClusterNode",
           "ConnectionPool", "FileKV", "FleetServer",
           "ForwardError", "HashRing", "Lease", "LeaseError",
           "LeaseLost", "LeaseService", "MemoryKV", "NetChaos",
           "NetChaosSpecError"]
